"""Batched optimal-ate pairing on device — the verification hot path.

Replaces the per-round sequential pairing calls of the reference
(chain/beacon/node.go:112 VerifyPartial, chain/beacon.go:87 VerifyBeacon,
client/verify.go:146-163 catch-up loop) with one batched computation:
``pairing_check2`` verifies a whole tensor of (signature, message) pairs in
a single jitted graph — the TPU analogue of the reference's hot loop.

Design:
- Lines are denominator-eliminated (scaled by Fp2 factors, which the final
  exponentiation kills), so the Miller loop is inversion-free: T is tracked
  in Jacobian coordinates on the twist.
- The Miller loop over |x| is SEGMENTED: runs of doubling bits are
  `lax.scan`s, the 5 addition bits are unrolled — no wasted conditional
  add-work per iteration, compact trace.
- Sparse line multiplication: the line has w-coefficients only at slots
  {0, 1, 3} (D-twist untwist: lambda*w, x-terms at w^3), one stacked
  Fp2-multiply per application.
- Final exponentiation = easy part + Hayashida chain (cube of the canonical
  pairing; equality checks are cube-invariant). `canonical=True` corrects by
  3^-1 mod r for GT interop (timelock IBE).

Host golden reference: drand_tpu.crypto.pairing.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..crypto.fields import P, R, X_BLS
from ..crypto.curves import PointG1, PointG2
from . import limb, tower
from .tower import (
    f2_add, f2_sub, f2_neg, f2_mul, f2_sqr, f2_mul_fp, f2_mul_small,
    f2_mul_by_xi, f12_mul, f12_sqr, f12_conj, f12_inv, f12_frobenius,
    f12_cyclotomic_sqr, f12_cyc_pow_const, f12_one, f12_is_one,
    f12_to_w, f12_from_w,
)

# ---------------------------------------------------------------------------
# Host-side input preparation
# ---------------------------------------------------------------------------

def g1_affine_to_device(p: PointG1) -> jnp.ndarray:
    """(2, 32) mont limbs (x, y). Point must not be at infinity."""
    x, y = p.to_affine()
    return jnp.stack([limb.fp_to_device(x.v), limb.fp_to_device(y.v)])


def g2_affine_to_device(q: PointG2) -> jnp.ndarray:
    """(2, 2, 32) mont limbs (x, y) as Fp2 coordinates."""
    x, y = q.to_affine()
    return jnp.stack([tower.fp2_to_device(x), tower.fp2_to_device(y)])


# ---------------------------------------------------------------------------
# Miller loop steps. State: f (Fp12), T = (X, Y, Z) Jacobian on the twist,
# with a trailing pair axis: T* have shape (..., npairs, 2, 32); p_aff =
# (xp, yp) each (..., npairs, 32); q_aff = (..., npairs, 2, 2, 32).
# ---------------------------------------------------------------------------

def _sparse_mul_013(f, c0, c1, c3, npairs: int):
    """f * L for lines L = c0 + c1*w + c3*w^3 (per pair), folding the pair
    axis: multiplies all npairs lines into f sequentially."""
    for j in range(npairs):
        fw = f12_to_w(f)  # (..., 6, 2, 32)
        cj = jnp.stack([c0[..., j, :, :], c1[..., j, :, :], c3[..., j, :, :]],
                       axis=-3)
        # products p[m, i] = fw_i * c_m : (..., 3, 6, 2, 32)
        prod = f2_mul(fw[..., None, :, :, :], cj[..., :, None, :, :])
        p0, p1, p3 = prod[..., 0, :, :, :], prod[..., 1, :, :, :], prod[..., 2, :, :, :]
        out = []
        for k in range(6):
            term = p0[..., k, :, :]
            i1 = (k - 1) % 6
            t1 = p1[..., i1, :, :]
            if k - 1 < 0:
                t1 = f2_mul_by_xi(t1)
            i3 = (k - 3) % 6
            t3 = p3[..., i3, :, :]
            if k - 3 < 0:
                t3 = f2_mul_by_xi(t3)
            out.append(limb.reduce_limbs(term + t1 + t3))
        f = f12_from_w(jnp.stack(out, axis=-3))
    return f


def _dbl_step(T, p_aff):
    """Doubling step: new T = 2T and line coefficients (c0, c1, c3).

    Line (scaled by 2YZ^3, an Fp2 factor the final exp kills):
        c0 = 2YZ^3 * yp,  c1 = -3X^2Z^2 * xp,  c3 = 3X^3 - 2Y^2
    T-update (Jacobian, a=0): standard doubling.
    """
    X, Y, Z = T
    xp, yp = p_aff
    X2 = f2_sqr(X)
    Y2 = f2_sqr(Y)
    Z2 = f2_sqr(Z)
    Z3 = f2_mul(Z2, Z)
    YZ3 = f2_mul(Y, Z3)
    lam_s = f2_mul_small(f2_mul(X2, Z2), 3)      # 3 X^2 Z^2
    c0 = f2_mul_fp(f2_mul_small(YZ3, 2), yp)
    c1 = f2_neg(f2_mul_fp(lam_s, xp))
    X3cu = f2_mul(X2, X)
    c3 = f2_sub(f2_mul_small(X3cu, 3), f2_mul_small(Y2, 2))
    # point doubling
    C = f2_sqr(Y2)
    D = f2_mul_small(f2_sub(f2_sqr(f2_add(X, Y2)), f2_add(X2, C)), 2)
    E = f2_mul_small(X2, 3)
    F = f2_sqr(E)
    Xn = f2_sub(F, f2_mul_small(D, 2))
    Yn = f2_sub(f2_mul(E, f2_sub(D, Xn)), f2_mul_small(C, 8))
    Zn = f2_mul_small(f2_mul(Y, Z), 2)
    return (Xn, Yn, Zn), (c0, c1, c3)


def _add_step(T, q_aff, p_aff):
    """Mixed addition step T <- T + Q and line coefficients.

    H = xq Z^2 - X, M = yq Z^3 - Y (scaled slope numerator). Line scaled by
    H*Z: c0 = HZ*yp, c1 = -M*xp, c3 = M*xq - HZ*yq.
    """
    X, Y, Z = T
    xq, yq = q_aff[..., 0, :, :], q_aff[..., 1, :, :]
    xp, yp = p_aff
    Z2 = f2_sqr(Z)
    Z3 = f2_mul(Z2, Z)
    U2 = f2_mul(xq, Z2)
    S2 = f2_mul(yq, Z3)
    H = f2_sub(U2, X)
    M = f2_sub(S2, Y)
    HZ = f2_mul(H, Z)
    c0 = f2_mul_fp(HZ, yp)
    c1 = f2_neg(f2_mul_fp(M, xp))
    c3 = f2_sub(f2_mul(M, xq), f2_mul(HZ, yq))
    # point update
    HH = f2_sqr(H)
    HHH = f2_mul(HH, H)
    V = f2_mul(X, HH)
    M2 = f2_sqr(M)
    Xn = f2_sub(M2, f2_add(HHH, f2_mul_small(V, 2)))
    Yn = f2_sub(f2_mul(M, f2_sub(V, Xn)), f2_mul(Y, HHH))
    Zn = f2_mul(Z, H)
    return (Xn, Yn, Zn), (c0, c1, c3)


# Bit schedule of |x| (MSB implicit): segments of doubling-only runs split by
# the addition bits.
_X_ABS = abs(X_BLS)
_BITS_MSB = bin(_X_ABS)[3:]  # after the implicit leading 1
# parse: each char is one iteration (sqr+dbl); '1' additionally does an add.
_runs: list[tuple[int, bool]] = []
_count = 0
for _ch in _BITS_MSB:
    _count += 1
    if _ch == "1":
        _runs.append((_count, True))
        _count = 0
if _count:
    _runs.append((_count, False))


def miller_loop(p_affs, q_affs):
    """Batched shared-squaring Miller loop.

    p_affs: tuple (xp, yp) arrays shaped (..., npairs, 32), mont domain.
    q_affs: (..., npairs, 2, 2, 32) affine twist points, mont domain.
    Returns f (..., 2, 3, 2, 32); the |x|<0 conjugation is applied.
    No point may be at infinity (callers filter; drand inputs never are).
    """
    npairs = q_affs.shape[-4]
    xq, yq = q_affs[..., 0, :, :], q_affs[..., 1, :, :]
    T = (xq, yq, tower.f2_one(xq.shape[:-2]))
    batch_shape = q_affs.shape[:-4]
    f = jnp.broadcast_to(f12_one(), batch_shape + (2, 3, 2, limb.NLIMBS))

    def dbl_body(state, _):
        f, T = state
        f = f12_sqr(f)
        T, (c0, c1, c3) = _dbl_step(T, p_affs)
        f = _sparse_mul_013(f, c0, c1, c3, npairs)
        return (f, T), None

    state = (f, T)
    for run_len, has_add in _runs:
        state, _ = jax.lax.scan(dbl_body, state, None, length=run_len)
        if has_add:
            f, T = state
            T, (c0, c1, c3) = _add_step(T, q_affs, p_affs)
            f = _sparse_mul_013(f, c0, c1, c3, npairs)
            state = (f, T)
    f, T = state
    return f12_conj(f)  # x < 0


# ---------------------------------------------------------------------------
# Final exponentiation (mirrors crypto/pairing.py final_exponentiation)
# ---------------------------------------------------------------------------

_INV3_MOD_R = pow(3, -1, R)


def final_exponentiation(f, canonical: bool = False):
    f1 = f12_mul(f12_conj(f), f12_inv(f))
    m = f12_mul(f12_frobenius(f1, 2), f1)
    a = f12_cyc_pow_const(m, X_BLS - 1)
    a = f12_cyc_pow_const(a, X_BLS - 1)
    a = f12_mul(f12_cyc_pow_const(a, X_BLS), f12_frobenius(a, 1))
    a = f12_mul(
        f12_cyc_pow_const(f12_cyc_pow_const(a, X_BLS), X_BLS),
        f12_mul(f12_frobenius(a, 2), f12_conj(a)),
    )
    cubed = f12_mul(a, f12_mul(m, f12_cyclotomic_sqr(m)))
    if canonical:
        return f12_cyc_pow_const(cubed, _INV3_MOD_R)
    return cubed


def multi_pairing(p_affs, q_affs, canonical: bool = False):
    """prod_j e(P_j, Q_j) over the trailing pair axis, batched over leading
    axes. All inputs affine mont-domain device arrays."""
    return final_exponentiation(miller_loop(p_affs, q_affs), canonical)


def pairing_check(p_affs, q_affs):
    """Batched check prod_j e(P_j, Q_j) == 1 -> bool array over batch."""
    return f12_is_one(multi_pairing(p_affs, q_affs))


# ---------------------------------------------------------------------------
# BLS verification: e(-g1, sig) * e(pub, H(msg)) == 1
# ---------------------------------------------------------------------------

_NEG_G1_AFF = None


def _neg_g1():
    global _NEG_G1_AFF
    if _NEG_G1_AFF is None:
        _NEG_G1_AFF = np.asarray(g1_affine_to_device(-PointG1.generator()))
    return jnp.asarray(_NEG_G1_AFF)


def verify_prepared(pub_aff, sig_aff, msg_aff):
    """Batched BLS verify on prepared device inputs.

    pub_aff: (..., 2, 32) or (2, 32) G1 public key(s), affine mont.
    sig_aff: (..., 2, 2, 32) G2 signatures, affine mont.
    msg_aff: (..., 2, 2, 32) G2 hashed messages, affine mont.
    Returns bool (...,).
    """
    neg_g1 = _neg_g1()
    batch = sig_aff.shape[:-3]
    pub_aff = jnp.broadcast_to(pub_aff, batch + (2, limb.NLIMBS))
    ng1 = jnp.broadcast_to(neg_g1, batch + (2, limb.NLIMBS))
    xp = jnp.stack([ng1[..., 0, :], pub_aff[..., 0, :]], axis=-2)
    yp = jnp.stack([ng1[..., 1, :], pub_aff[..., 1, :]], axis=-2)
    q = jnp.stack([sig_aff, msg_aff], axis=-4)
    return pairing_check((xp, yp), q)
