"""Batched optimal-ate pairing on device — the verification hot path.

Replaces the per-round sequential pairing calls of the reference
(chain/beacon/node.go:112 VerifyPartial, chain/beacon.go:87 VerifyBeacon,
client/verify.go:146-163 catch-up loop) with one batched computation:
``pairing_check2`` verifies a whole tensor of (signature, message) pairs in
a single jitted graph — the TPU analogue of the reference's hot loop.

Design:
- Lines are denominator-eliminated (scaled by Fp2 factors, which the final
  exponentiation kills), so the Miller loop is inversion-free: T is tracked
  in Jacobian coordinates on the twist.
- The Miller loop over |x| is ONE `lax.scan` (compile-time critical: a
  single traced body); the rare addition steps run under `lax.cond`, so
  only the ~6 set bits of |x| pay for the mixed addition.
- Sparse line multiplication: the line has w-coefficients only at slots
  {0, 3, 5} (M-twist untwist (x,y) -> (xi^-1 x w^4, xi^-1 y w^3)), one
  stacked Fp2-multiply per application.
- Final exponentiation = easy part + Hayashida chain (cube of the canonical
  pairing; equality checks are cube-invariant), with the five pow-by-x
  stages fused into a single scan over a (bit, boundary, segment) schedule.
  `canonical=True` corrects by 3^-1 mod r for GT interop (timelock IBE).

Host golden reference: drand_tpu.crypto.pairing.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..crypto.fields import P, R, X_BLS
from ..crypto.curves import PointG1, PointG2
from . import limb, tower
from .tower import (
    f2_add, f2_sub, f2_neg, f2_mul, f2_sqr, f2_mul_fp, f2_mul_small,
    f2_mul_by_xi, f12_mul, f12_sqr, f12_conj, f12_inv, f12_frobenius,
    f12_cyclotomic_sqr, f12_cyc_pow_const, f12_one, f12_is_one,
    f12_to_w, f12_from_w,
)

# ---------------------------------------------------------------------------
# Host-side input preparation
# ---------------------------------------------------------------------------

def g1_affine_to_device(p: PointG1) -> jnp.ndarray:
    """(2, 32) mont limbs (x, y). Point must not be at infinity."""
    x, y = p.to_affine()
    return jnp.stack([limb.fp_to_device(x.v), limb.fp_to_device(y.v)])


def g2_affine_to_device(q: PointG2) -> jnp.ndarray:
    """(2, 2, 32) mont limbs (x, y) as Fp2 coordinates."""
    x, y = q.to_affine()
    return jnp.stack([tower.fp2_to_device(x), tower.fp2_to_device(y)])


# ---------------------------------------------------------------------------
# Miller loop steps. State: f (Fp12), T = (X, Y, Z) Jacobian on the twist,
# with a trailing pair axis: T* have shape (..., npairs, 2, 32); p_aff =
# (xp, yp) each (..., npairs, 32); q_aff = (..., npairs, 2, 2, 32).
# ---------------------------------------------------------------------------

def _sparse_mul_035(f, c0, c3, c5, npairs: int):
    """f * L for lines L = c0 + c3*w^3 + c5*w^5 (per pair), folding the pair
    axis: multiplies all npairs lines into f sequentially.

    Slots {0, 3, 5} come from the M-twist untwist (x, y) -> (xi^-1 x w^4,
    xi^-1 y w^3): the y_p term sits at w^0, the x_p (slope) term at w^5, and
    the twist-coordinate constant at w^3 (overall line scaled by xi * H*Z or
    xi * 2YZ^3, an Fp2 factor the final exponentiation kills)."""
    for j in range(npairs):
        fw = f12_to_w(f)  # (..., 6, 2, 32)
        cj = jnp.stack([c0[..., j, :, :], c3[..., j, :, :], c5[..., j, :, :]],
                       axis=-3)
        # products p[m, i] = fw_i * c_m : (..., 3, 6, 2, 32)
        prod = f2_mul(fw[..., None, :, :, :], cj[..., :, None, :, :])
        p0, p3, p5 = prod[..., 0, :, :, :], prod[..., 1, :, :, :], prod[..., 2, :, :, :]
        out = []
        for k in range(6):
            term = p0[..., k, :, :]
            t3 = p3[..., (k - 3) % 6, :, :]
            if k - 3 < 0:
                t3 = f2_mul_by_xi(t3)
            t5 = p5[..., (k - 5) % 6, :, :]
            if k - 5 < 0:
                t5 = f2_mul_by_xi(t5)
            out.append(limb.reduce_light(term + t3 + t5))
        f = f12_from_w(jnp.stack(out, axis=-3))
    return f


def _dbl_step(T, p_aff):
    """Doubling step: new T = 2T and line coefficients (c0, c3, c5).

    Line (scaled by xi * 2YZ^3, an Fp2 factor the final exp kills):
        c0 = xi * 2YZ^3 * yp,  c3 = 3X^3 - 2Y^2,  c5 = -3X^2Z^2 * xp
    T-update (Jacobian, a=0): standard doubling.
    """
    X, Y, Z = T
    xp, yp = p_aff
    X2 = f2_sqr(X)
    Y2 = f2_sqr(Y)
    Z2 = f2_sqr(Z)
    Z3 = f2_mul(Z2, Z)
    YZ3 = f2_mul(Y, Z3)
    lam_s = f2_mul_small(f2_mul(X2, Z2), 3)      # 3 X^2 Z^2
    c0 = f2_mul_by_xi(f2_mul_fp(f2_mul_small(YZ3, 2), yp))
    c5 = f2_neg(f2_mul_fp(lam_s, xp))
    X3cu = f2_mul(X2, X)
    c3 = f2_sub(f2_mul_small(X3cu, 3), f2_mul_small(Y2, 2))
    # point doubling
    C = f2_sqr(Y2)
    D = f2_mul_small(f2_sub(f2_sqr(f2_add(X, Y2)), f2_add(X2, C)), 2)
    E = f2_mul_small(X2, 3)
    F = f2_sqr(E)
    Xn = f2_sub(F, f2_mul_small(D, 2))
    Yn = f2_sub(f2_mul(E, f2_sub(D, Xn)), f2_mul_small(C, 8))
    Zn = f2_mul_small(f2_mul(Y, Z), 2)
    return (Xn, Yn, Zn), (c0, c3, c5)


def _add_step(T, q_aff, p_aff):
    """Mixed addition step T <- T + Q and line coefficients.

    H = xq Z^2 - X, M = yq Z^3 - Y (scaled slope numerator). Line scaled by
    xi * H*Z: c0 = xi*HZ*yp, c3 = M*xq - HZ*yq, c5 = -M*xp.
    """
    X, Y, Z = T
    xq, yq = q_aff[..., 0, :, :], q_aff[..., 1, :, :]
    xp, yp = p_aff
    Z2 = f2_sqr(Z)
    Z3 = f2_mul(Z2, Z)
    U2 = f2_mul(xq, Z2)
    S2 = f2_mul(yq, Z3)
    H = f2_sub(U2, X)
    M = f2_sub(S2, Y)
    HZ = f2_mul(H, Z)
    c0 = f2_mul_by_xi(f2_mul_fp(HZ, yp))
    c5 = f2_neg(f2_mul_fp(M, xp))
    c3 = f2_sub(f2_mul(M, xq), f2_mul(HZ, yq))
    # point update
    HH = f2_sqr(H)
    HHH = f2_mul(HH, H)
    V = f2_mul(X, HH)
    M2 = f2_sqr(M)
    Xn = f2_sub(M2, f2_add(HHH, f2_mul_small(V, 2)))
    Yn = f2_sub(f2_mul(M, f2_sub(V, Xn)), f2_mul(Y, HHH))
    Zn = f2_mul(Z, H)
    return (Xn, Yn, Zn), (c0, c3, c5)


# Bit schedule of |x| (MSB implicit): one scan iteration per bit; a '1' bit
# additionally performs the mixed-addition step (under lax.cond — the
# predicate is a scalar per step, so only ~6 of 63 iterations pay for it).
_X_ABS = abs(X_BLS)
_BITS_MSB = bin(_X_ABS)[3:]  # after the implicit leading 1
_MILLER_BITS = np.array([int(_ch) for _ch in _BITS_MSB], dtype=np.int32)


def miller_loop(p_affs, q_affs):
    """Batched shared-squaring Miller loop — a single lax.scan over the bits
    of |x| (compile-time critical: one traced body, 63 iterations).

    p_affs: tuple (xp, yp) arrays shaped (..., npairs, 32), mont domain.
    q_affs: (..., npairs, 2, 2, 32) affine twist points, mont domain.
    Returns f (..., 2, 3, 2, 32); the |x|<0 conjugation is applied.
    No point may be at infinity (callers filter; drand inputs never are).
    """
    npairs = q_affs.shape[-4]
    xq, yq = q_affs[..., 0, :, :], q_affs[..., 1, :, :]
    T = (xq, yq, tower.f2_one(xq.shape[:-2]))
    batch_shape = q_affs.shape[:-4]
    f = jnp.broadcast_to(f12_one(), batch_shape + (2, 3, 2, limb.NLIMBS))

    def add_part(state):
        f, T = state
        T, (c0, c3, c5) = _add_step(T, q_affs, p_affs)
        f = _sparse_mul_035(f, c0, c3, c5, npairs)
        return (f, T)

    def body(state, bit):
        f, T = state
        f = f12_sqr(f)
        T, (c0, c3, c5) = _dbl_step(T, p_affs)
        f = _sparse_mul_035(f, c0, c3, c5, npairs)
        state = jax.lax.cond(bit.astype(bool), add_part, lambda s: s, (f, T))
        return state, None

    (f, T), _ = jax.lax.scan(body, (f, T), jnp.asarray(_MILLER_BITS))
    return f12_conj(f)  # x < 0


# ---------------------------------------------------------------------------
# Final exponentiation (mirrors crypto/pairing.py final_exponentiation).
#
# The Hayashida hard part is FIVE pow-by-(~x) chains; tracing five separate
# scans quintuples compile time, so the whole chain runs as ONE lax.scan over
# a (bit, boundary, segment) schedule. Each step is a MSB-first pow step
# (acc <- acc^2; acc <- acc*base if bit); at the 5 segment boundaries a
# lax.switch applies the inter-pow glue (frobenius multiplies, base/acc
# reload). Registers: acc, base, keep (holds a2 then a3).
#
#   seg0: a1 = m^(x-1)            = pow(conj(m), |x-1|)          [x < 0]
#   seg1: a2 = a1^(x-1)
#   seg2: a3 = a2^x * frob1(a2)
#   seg3: t  = a3^x
#   seg4: a4 = t^x * frob2(a3) * conj(a3)
#   out: cubed = a4 * m^3  (host: a * m * cyclotomic_square(m))
# ---------------------------------------------------------------------------

_INV3_MOD_R = pow(3, -1, R)

_SEG_LEN = 64  # covers |x-1| and |x| (both 64-bit)


def _msb_bits(e: int, width: int) -> np.ndarray:
    return np.array([(e >> (width - 1 - i)) & 1 for i in range(width)],
                    dtype=np.int32)


_HARD_EXPS = [abs(X_BLS - 1), abs(X_BLS - 1), abs(X_BLS), abs(X_BLS), abs(X_BLS)]
_HARD_BITS = np.concatenate([_msb_bits(e, _SEG_LEN) for e in _HARD_EXPS])
_HARD_BOUNDARY = np.zeros(5 * _SEG_LEN, dtype=np.int32)
_HARD_BOUNDARY[_SEG_LEN - 1 :: _SEG_LEN] = 1
_HARD_SEG = np.repeat(np.arange(5, dtype=np.int32), _SEG_LEN)


def _hard_part(m):
    """m^(hard exponent) for cyclotomic m — single-scan Hayashida chain."""
    one = jnp.broadcast_to(f12_one(), m.shape)

    def glue0(r, keep):  # also seg3
        return one, f12_conj(r), keep
    def glue1(r, keep):
        return one, f12_conj(r), r
    def glue2(r, keep):
        rr = f12_mul(r, f12_frobenius(keep, 1))
        return one, f12_conj(rr), rr
    def glue4(r, keep):
        out = f12_mul(f12_mul(r, f12_frobenius(keep, 2)), f12_conj(keep))
        return out, f12_conj(r), keep

    def body(state, x):
        bit, boundary, seg = x
        acc, base, keep = state
        acc = f12_cyclotomic_sqr(acc)
        acc = tower.f12_select(
            jnp.broadcast_to(bit.astype(bool), acc.shape[:-4]),
            f12_mul(acc, base), acc)

        def at_boundary(s):
            acc, base, keep = s
            return jax.lax.switch(
                seg, [glue0, glue1, glue2, glue0, glue4], acc, keep)

        state = jax.lax.cond(boundary.astype(bool), at_boundary, lambda s: s,
                             (acc, base, keep))
        return state, None

    xs = (jnp.asarray(_HARD_BITS), jnp.asarray(_HARD_BOUNDARY),
          jnp.asarray(_HARD_SEG))
    (acc, _, _), _ = jax.lax.scan(body, (one, f12_conj(m), m), xs)
    return acc


def final_exponentiation(f, canonical: bool = False):
    f1 = f12_mul(f12_conj(f), f12_inv(f))
    m = f12_mul(f12_frobenius(f1, 2), f1)
    a4 = _hard_part(m)
    cubed = f12_mul(a4, f12_mul(m, f12_cyclotomic_sqr(m)))
    if canonical:
        return f12_cyc_pow_const(cubed, _INV3_MOD_R)
    return cubed


def multi_pairing(p_affs, q_affs, canonical: bool = False):
    """prod_j e(P_j, Q_j) over the trailing pair axis, batched over leading
    axes. All inputs affine mont-domain device arrays."""
    return final_exponentiation(miller_loop(p_affs, q_affs), canonical)


def pairing_check(p_affs, q_affs):
    """Batched check prod_j e(P_j, Q_j) == 1 -> bool array over batch."""
    return f12_is_one(multi_pairing(p_affs, q_affs))


# ---------------------------------------------------------------------------
# BLS verification: e(-g1, sig) * e(pub, H(msg)) == 1
# ---------------------------------------------------------------------------

_NEG_G1_AFF = None


def _neg_g1():
    # Host-side numpy (no jax ops): safe to call lazily even under jit trace.
    global _NEG_G1_AFF
    if _NEG_G1_AFF is None:
        x, y = (-PointG1.generator()).to_affine()
        _NEG_G1_AFF = np.stack([
            limb.int_to_limbs(x.v * limb.R_MONT % P),
            limb.int_to_limbs(y.v * limb.R_MONT % P),
        ])
    return jnp.asarray(_NEG_G1_AFF)


def verify_prepared(pub_aff, sig_aff, msg_aff):
    """Batched BLS verify on prepared device inputs.

    pub_aff: (..., 2, 32) or (2, 32) G1 public key(s), affine mont.
    sig_aff: (..., 2, 2, 32) G2 signatures, affine mont.
    msg_aff: (..., 2, 2, 32) G2 hashed messages, affine mont.
    Returns bool (...,).
    """
    neg_g1 = _neg_g1()
    batch = sig_aff.shape[:-3]
    pub_aff = jnp.broadcast_to(pub_aff, batch + (2, limb.NLIMBS))
    ng1 = jnp.broadcast_to(neg_g1, batch + (2, limb.NLIMBS))
    xp = jnp.stack([ng1[..., 0, :], pub_aff[..., 0, :]], axis=-2)
    yp = jnp.stack([ng1[..., 1, :], pub_aff[..., 1, :]], axis=-2)
    q = jnp.stack([sig_aff, msg_aff], axis=-4)
    return pairing_check((xp, yp), q)
