"""Batched optimal-ate pairing on device — the verification hot path.

Replaces the per-round sequential pairing calls of the reference
(chain/beacon/node.go:112 VerifyPartial, chain/beacon.go:87 VerifyBeacon,
client/verify.go:146-163 catch-up loop) with one batched computation:
``pairing_check2`` verifies a whole tensor of (signature, message) pairs in
a single jitted graph — the TPU analogue of the reference's hot loop.

Design:
- Lines are denominator-eliminated (scaled by Fp2 factors, which the final
  exponentiation kills), so the Miller loop is inversion-free: T is tracked
  in Jacobian coordinates on the twist.
- The Miller loop over |x| runs as pure-doubling `lax.scan` segments with
  the ~5 mixed-addition steps unrolled at the set bits of |x|. There is
  deliberately NO `lax.cond`/`lax.switch` inside any `lax.scan`: that
  construct miscompiles on the axon TPU backend for batches >= ~64 (plain
  scans are fine at every size). Do not re-fuse the loop into a single
  scan with conditional add steps without re-running the batch-64
  regression (tests/test_batch_engine.py::test_batch64_regression).
- Sparse line multiplication: the line has w-coefficients only at slots
  {0, 3, 5} (M-twist untwist (x,y) -> (xi^-1 x w^4, xi^-1 y w^3)), one
  stacked Fp2-multiply per application.
- Final exponentiation = easy part + Hayashida chain (cube of the
  canonical pairing; equality checks are cube-invariant) as five separate
  plain pow scans with explicit glue (same no-cond-in-scan rule).
  `canonical=True` corrects by 3^-1 mod r for GT interop (timelock IBE).

Host golden reference: drand_tpu.crypto.pairing.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..crypto.fields import P, R, X_BLS
from ..crypto.curves import PointG1, PointG2
from . import limb, tower
from .tower import (
    f2_add, f2_sub, f2_neg, f2_mul, f2_sqr, f2_mul_fp, f2_mul_small,
    f2_mul_by_xi, f12_mul, f12_sqr, f12_conj, f12_inv, f12_frobenius,
    f12_cyclotomic_sqr, f12_cyc_pow_const, f12_one, f12_is_one,
    f12_to_w, f12_from_w,
)

# ---------------------------------------------------------------------------
# Host-side input preparation
# ---------------------------------------------------------------------------

def g1_affine_to_device(p: PointG1) -> jnp.ndarray:
    """(2, 32) mont limbs (x, y). Point must not be at infinity."""
    x, y = p.to_affine()
    return jnp.stack([limb.fp_to_device(x.v), limb.fp_to_device(y.v)])


def g2_affine_to_device(q: PointG2) -> jnp.ndarray:
    """(2, 2, 32) mont limbs (x, y) as Fp2 coordinates."""
    x, y = q.to_affine()
    return jnp.stack([tower.fp2_to_device(x), tower.fp2_to_device(y)])


# ---------------------------------------------------------------------------
# Miller loop steps. State: f (Fp12), T = (X, Y, Z) Jacobian on the twist,
# with a trailing pair axis: T* have shape (..., npairs, 2, 32); p_aff =
# (xp, yp) each (..., npairs, 32); q_aff = (..., npairs, 2, 2, 32).
# ---------------------------------------------------------------------------

def _sparse_mul_035(f, c0, c3, c5, npairs: int):
    """f * L for lines L = c0 + c3*w^3 + c5*w^5 (per pair), folding the pair
    axis: multiplies all npairs lines into f sequentially.

    Slots {0, 3, 5} come from the M-twist untwist (x, y) -> (xi^-1 x w^4,
    xi^-1 y w^3): the y_p term sits at w^0, the x_p (slope) term at w^5, and
    the twist-coordinate constant at w^3 (overall line scaled by xi * H*Z or
    xi * 2YZ^3, an Fp2 factor the final exponentiation kills)."""
    for j in range(npairs):
        fw = f12_to_w(f)  # (..., 6, 2, 32)
        cj = jnp.stack([c0[..., j, :, :], c3[..., j, :, :], c5[..., j, :, :]],
                       axis=-3)
        # products p[m, i] = fw_i * c_m : (..., 3, 6, 2, 32)
        prod = f2_mul(fw[..., None, :, :, :], cj[..., :, None, :, :])
        p0, p3, p5 = prod[..., 0, :, :, :], prod[..., 1, :, :, :], prod[..., 2, :, :, :]
        out = []
        for k in range(6):
            term = p0[..., k, :, :]
            t3 = p3[..., (k - 3) % 6, :, :]
            if k - 3 < 0:
                t3 = f2_mul_by_xi(t3)
            t5 = p5[..., (k - 5) % 6, :, :]
            if k - 5 < 0:
                t5 = f2_mul_by_xi(t5)
            out.append(limb.reduce_light(term + t3 + t5))
        f = f12_from_w(jnp.stack(out, axis=-3))
    return f


def _dbl_step(T, p_aff):
    """Doubling step: new T = 2T and line coefficients (c0, c3, c5).

    Line (scaled by xi * 2YZ^3, an Fp2 factor the final exp kills):
        c0 = xi * 2YZ^3 * yp,  c3 = 3X^3 - 2Y^2,  c5 = -3X^2Z^2 * xp
    T-update (Jacobian, a=0): standard doubling.
    """
    X, Y, Z = T
    xp, yp = p_aff
    X2 = f2_sqr(X)
    Y2 = f2_sqr(Y)
    Z2 = f2_sqr(Z)
    Z3 = f2_mul(Z2, Z)
    YZ3 = f2_mul(Y, Z3)
    lam_s = f2_mul_small(f2_mul(X2, Z2), 3)      # 3 X^2 Z^2
    c0 = f2_mul_by_xi(f2_mul_fp(f2_mul_small(YZ3, 2), yp))
    c5 = f2_neg(f2_mul_fp(lam_s, xp))
    X3cu = f2_mul(X2, X)
    c3 = f2_sub(f2_mul_small(X3cu, 3), f2_mul_small(Y2, 2))
    # point doubling
    C = f2_sqr(Y2)
    D = f2_mul_small(f2_sub(f2_sqr(f2_add(X, Y2)), f2_add(X2, C)), 2)
    E = f2_mul_small(X2, 3)
    F = f2_sqr(E)
    Xn = f2_sub(F, f2_mul_small(D, 2))
    Yn = f2_sub(f2_mul(E, f2_sub(D, Xn)), f2_mul_small(C, 8))
    Zn = f2_mul_small(f2_mul(Y, Z), 2)
    return (Xn, Yn, Zn), (c0, c3, c5)


def _add_step(T, q_aff, p_aff):
    """Mixed addition step T <- T + Q and line coefficients.

    H = xq Z^2 - X, M = yq Z^3 - Y (scaled slope numerator). Line scaled by
    xi * H*Z: c0 = xi*HZ*yp, c3 = M*xq - HZ*yq, c5 = -M*xp.
    """
    X, Y, Z = T
    xq, yq = q_aff[..., 0, :, :], q_aff[..., 1, :, :]
    xp, yp = p_aff
    Z2 = f2_sqr(Z)
    Z3 = f2_mul(Z2, Z)
    U2 = f2_mul(xq, Z2)
    S2 = f2_mul(yq, Z3)
    H = f2_sub(U2, X)
    M = f2_sub(S2, Y)
    HZ = f2_mul(H, Z)
    c0 = f2_mul_by_xi(f2_mul_fp(HZ, yp))
    c5 = f2_neg(f2_mul_fp(M, xp))
    c3 = f2_sub(f2_mul(M, xq), f2_mul(HZ, yq))
    # point update
    HH = f2_sqr(H)
    HHH = f2_mul(HH, H)
    V = f2_mul(X, HH)
    M2 = f2_sqr(M)
    Xn = f2_sub(M2, f2_add(HHH, f2_mul_small(V, 2)))
    Yn = f2_sub(f2_mul(M, f2_sub(V, Xn)), f2_mul(Y, HHH))
    Zn = f2_mul(Z, H)
    return (Xn, Yn, Zn), (c0, c3, c5)


# Bit schedule of |x| (MSB implicit), segmented at the set bits: |x| has
# hamming weight 6, so the loop is a handful of pure-doubling lax.scan
# segments with the ~5 mixed additions unrolled at the segment boundaries.
# NO lax.cond inside lax.scan: that construct miscompiles on the axon TPU
# backend for batch >= ~64 (observed jax 0.9.0: correct at B=16, all-wrong
# at B=64; plain scans are fine at every size — see tests/test_ops_golden
# batch-64 regression).
_X_ABS = abs(X_BLS)
_BITS_MSB = bin(_X_ABS)[3:]  # after the implicit leading 1
# run-lengths of doubling steps between additions: for each '1' bit at
# position i (0-based after MSB), an add follows (i+1 - prev) doublings
_MILLER_SEGMENTS: list[int] = []  # doubling-run lengths
_MILLER_ADDS: list[bool] = []     # whether an add follows the run
_run = 0
for _ch in _BITS_MSB:
    _run += 1
    if _ch == "1":
        _MILLER_SEGMENTS.append(_run)
        _MILLER_ADDS.append(True)
        _run = 0
if _run:
    _MILLER_SEGMENTS.append(_run)
    _MILLER_ADDS.append(False)


def miller_loop(p_affs, q_affs):
    """Batched shared-squaring Miller loop — pure-doubling scans segmented
    at the set bits of |x|, additions unrolled (cond-free; see above).

    p_affs: tuple (xp, yp) arrays shaped (..., npairs, 32), mont domain.
    q_affs: (..., npairs, 2, 2, 32) affine twist points, mont domain.
    Returns f (..., 2, 3, 2, 32); the |x|<0 conjugation is applied.
    No point may be at infinity (callers filter; drand inputs never are).
    """
    npairs = q_affs.shape[-4]
    xq, yq = q_affs[..., 0, :, :], q_affs[..., 1, :, :]
    T = (xq, yq, tower.f2_one(xq.shape[:-2]) + xq * 0)
    # f's initial value is derived from the inputs (not a broadcast
    # constant) so the scan carry keeps the inputs' varying-manual-axes
    # type under shard_map
    tag = q_affs[..., 0, 0, 0, 0][..., None, None, None, None] * 0
    f = f12_one() + tag

    def dbl_body(state, _):
        f, T = state
        f = f12_sqr(f)
        T, (c0, c3, c5) = _dbl_step(T, p_affs)
        f = _sparse_mul_035(f, c0, c3, c5, npairs)
        return (f, T), None

    for seg_len, has_add in zip(_MILLER_SEGMENTS, _MILLER_ADDS):
        (f, T), _ = jax.lax.scan(dbl_body, (f, T), None, length=seg_len)
        if has_add:
            T, (c0, c3, c5) = _add_step(T, q_affs, p_affs)
            f = _sparse_mul_035(f, c0, c3, c5, npairs)
    return f12_conj(f)  # x < 0


def miller_loop_shared_q(p_affs, q_aff):
    """Batched Miller loop against ONE shared G2 point — the timelock
    round-open structure (crypto/timelock.py: every ciphertext of a round
    pairs its own U in G1 with the round's V2 signature).

    The G2-side line/T trajectory carries NO batch axis: the doubling and
    addition steps run once per Miller step, exactly like a single-pair
    loop, and only the line evaluations (the xp/yp scalings of the c0/c5
    coefficients) and the per-item Fp12 accumulation ride the batch axis.
    Same cond-free scan segmentation as :func:`miller_loop`.

    p_affs: tuple (xp, yp) arrays shaped (b, 1, 32), mont domain.
    q_aff: (1, 1, 2, 2, 32) affine twist point, mont domain — must not be
    at infinity (callers filter).
    Returns f (b, 2, 3, 2, 32); the |x|<0 conjugation is applied.
    """
    xp, yp = p_affs
    xq, yq = q_aff[..., 0, :, :], q_aff[..., 1, :, :]
    T = (xq, yq, tower.f2_one(xq.shape[:-2]) + xq * 0)
    # f's tag comes from the BATCHED side so the scan carry holds the
    # (b, ...) accumulator from step one (the shared-T coefficients
    # broadcast into it)
    tag = xp[..., 0, 0][..., None, None, None, None] * 0
    f = f12_one() + tag

    def dbl_body(state, _):
        f, T = state
        f = f12_sqr(f)
        T, (c0, c3, c5) = _dbl_step(T, p_affs)
        c3 = jnp.broadcast_to(c3, c0.shape)
        f = _sparse_mul_035(f, c0, c3, c5, 1)
        return (f, T), None

    for seg_len, has_add in zip(_MILLER_SEGMENTS, _MILLER_ADDS):
        (f, T), _ = jax.lax.scan(dbl_body, (f, T), None, length=seg_len)
        if has_add:
            T, (c0, c3, c5) = _add_step(T, q_aff, p_affs)
            c3 = jnp.broadcast_to(c3, c0.shape)
            f = _sparse_mul_035(f, c0, c3, c5, 1)
    return f12_conj(f)  # x < 0


# ---------------------------------------------------------------------------
# Final exponentiation (mirrors crypto/pairing.py final_exponentiation).
#
# The Hayashida hard part runs as FIVE pow-by-(~x) cyclotomic chains with
# explicit glue between them. Each pow is a plain lax.scan (MSB-first
# square-and-multiply with the multiply under a masked select) — NO
# lax.cond/lax.switch inside lax.scan, which miscompiles on the axon TPU
# backend at batch >= ~64 (see miller_loop's note). The extra scans cost
# compile time once; the persistent compilation cache absorbs it.
#
#   a1 = m^(x-1)            = pow(conj(m), |x-1|)          [x < 0]
#   a2 = a1^(x-1)
#   a3 = a2^x * frob1(a2)
#   t  = a3^x
#   a4 = t^x * frob2(a3) * conj(a3)
#   out: cubed = a4 * m^3  (host: a * m * cyclotomic_square(m))
# ---------------------------------------------------------------------------

_INV3_MOD_R = pow(3, -1, R)


def _msb_bits(e: int) -> np.ndarray:
    return np.array([int(c) for c in bin(e)[2:]], dtype=np.int32)


_BITS_X_M1 = _msb_bits(abs(X_BLS - 1))
_BITS_X = _msb_bits(abs(X_BLS))


def _cyc_pow_neg(m, bits: np.ndarray):
    """m^(-|e|) for cyclotomic m, MSB-first plain scan (x < 0: the caller's
    exponents are x or x-1, both negative, so the base is conjugated)."""
    base = f12_conj(m)
    one = f12_one() + m * 0

    def body(acc, bit):
        acc = f12_cyclotomic_sqr(acc)
        acc = tower.f12_select(
            jnp.broadcast_to(bit.astype(bool), acc.shape[:-4]),
            f12_mul(acc, base), acc)
        return acc, None

    acc, _ = jax.lax.scan(body, one, jnp.asarray(bits))
    return acc


def _hard_part(m):
    """m^(hard exponent) for cyclotomic m — Hayashida chain."""
    a1 = _cyc_pow_neg(m, _BITS_X_M1)
    a2 = _cyc_pow_neg(a1, _BITS_X_M1)
    a3 = f12_mul(_cyc_pow_neg(a2, _BITS_X), f12_frobenius(a2, 1))
    t = _cyc_pow_neg(a3, _BITS_X)
    a4 = f12_mul(f12_mul(_cyc_pow_neg(t, _BITS_X), f12_frobenius(a3, 2)),
                 f12_conj(a3))
    return a4


def final_exponentiation(f, canonical: bool = False):
    f1 = f12_mul(f12_conj(f), f12_inv(f))
    m = f12_mul(f12_frobenius(f1, 2), f1)
    a4 = _hard_part(m)
    cubed = f12_mul(a4, f12_mul(m, f12_cyclotomic_sqr(m)))
    if canonical:
        return f12_cyc_pow_const(cubed, _INV3_MOD_R)
    return cubed


def multi_pairing(p_affs, q_affs, canonical: bool = False):
    """prod_j e(P_j, Q_j) over the trailing pair axis, batched over leading
    axes. All inputs affine mont-domain device arrays."""
    return final_exponentiation(miller_loop(p_affs, q_affs), canonical)


def pairing_check(p_affs, q_affs):
    """Batched check prod_j e(P_j, Q_j) == 1 -> bool array over batch."""
    return f12_is_one(multi_pairing(p_affs, q_affs))


# ---------------------------------------------------------------------------
# BLS verification: e(-g1, sig) * e(pub, H(msg)) == 1
# ---------------------------------------------------------------------------

_NEG_G1_AFF = None


def _neg_g1():
    # Host-side numpy (no jax ops): safe to call lazily even under jit trace.
    global _NEG_G1_AFF
    if _NEG_G1_AFF is None:
        x, y = (-PointG1.generator()).to_affine()
        _NEG_G1_AFF = np.stack([limb.int_to_mont_limbs(x.v),
                                limb.int_to_mont_limbs(y.v)])
    return jnp.asarray(_NEG_G1_AFF)


def verify_prepared(pub_aff, sig_aff, msg_aff):
    """Batched BLS verify on prepared device inputs.

    pub_aff: (..., 2, 32) or (2, 32) G1 public key(s), affine mont.
    sig_aff: (..., 2, 2, 32) G2 signatures, affine mont.
    msg_aff: (..., 2, 2, 32) G2 hashed messages, affine mont.
    Returns bool (...,).
    """
    neg_g1 = _neg_g1()
    batch = sig_aff.shape[:-3]
    pub_aff = jnp.broadcast_to(pub_aff, batch + (2, limb.NLIMBS))
    ng1 = jnp.broadcast_to(neg_g1, batch + (2, limb.NLIMBS))
    xp = jnp.stack([ng1[..., 0, :], pub_aff[..., 0, :]], axis=-2)
    yp = jnp.stack([ng1[..., 1, :], pub_aff[..., 1, :]], axis=-2)
    q = jnp.stack([sig_aff, msg_aff], axis=-4)
    return pairing_check((xp, yp), q)
