"""Transport abstraction between nodes.

The protocol plane is transport-agnostic: the beacon engine and DKG talk to
a ``ProtocolClient`` and expose a ``ProtocolService``; implementations are
the in-memory ``LocalNetwork`` (tests — the DrandTest2 analogue,
core/util_test.go:32) and the gRPC transport (drand_tpu.net.grpc).

Reference: net/client.go:30 (ProtocolClient), net/gateway.go:44 (Service).
"""

from __future__ import annotations

import asyncio
import random
from typing import AsyncIterator, Protocol

from .packets import PartialBeaconPacket, SyncRequest
from ..chain.beacon import Beacon


class Peer(Protocol):
    def address(self) -> str: ...


class TransportError(Exception):
    pass


class PeerRejectedError(TransportError):
    """The peer ANSWERED and rejected the request (stale/future window,
    failed verification, its own policy) — reachability-wise the
    opposite of a TransportError: the link is fine. Callers feeding
    reachability SLIs (handler._send_partial) must not count these as
    unreachability; conflating them turns every lagging-but-alive peer
    into a phantom partition suspect."""


class ProtocolClient:
    """Outbound node->node calls (reference net/client.go:30-49)."""

    async def partial_beacon(self, peer, packet: PartialBeaconPacket) -> None:
        raise NotImplementedError

    async def sync_chain(self, peer, req: SyncRequest) -> AsyncIterator[Beacon]:
        raise NotImplementedError

    async def broadcast_dkg(self, peer, packet) -> None:
        raise NotImplementedError

    async def signal_dkg_participant(self, peer, packet) -> None:
        raise NotImplementedError

    async def push_dkg_info(self, peer, packet) -> None:
        raise NotImplementedError

    async def chain_info(self, peer) -> "Info":
        raise NotImplementedError

    async def get_identity(self, peer) -> dict:
        raise NotImplementedError

    async def private_rand(self, peer, request: bytes) -> bytes:
        raise NotImplementedError

    async def peer_metrics(self, peer) -> bytes:
        raise NotImplementedError

    async def public_rand(self, peer, round_no: int) -> "Beacon":
        raise NotImplementedError

    def public_rand_stream(self, peer) -> "AsyncIterator[Beacon]":
        raise NotImplementedError


class ProtocolService:
    """Inbound service surface a node registers on its transport
    (reference protobuf/drand/protocol.proto:16-33)."""

    async def process_partial_beacon(self, from_addr: str, packet: PartialBeaconPacket) -> None:
        raise NotImplementedError

    def sync_chain(self, from_addr: str, req: SyncRequest) -> AsyncIterator[Beacon]:
        raise NotImplementedError

    async def broadcast_dkg(self, from_addr: str, packet) -> None:
        raise NotImplementedError

    async def signal_dkg_participant(self, from_addr: str, packet) -> None:
        raise NotImplementedError

    async def push_dkg_info(self, from_addr: str, packet) -> None:
        raise NotImplementedError

    async def chain_info(self, from_addr: str):
        raise NotImplementedError

    async def get_identity(self, from_addr: str) -> dict:
        raise NotImplementedError

    async def private_rand(self, from_addr: str, request: bytes) -> bytes:
        raise NotImplementedError

    async def peer_metrics(self, from_addr: str) -> bytes:
        raise NotImplementedError

    async def public_rand(self, from_addr: str, round_no: int) -> "Beacon":
        raise NotImplementedError

    def public_rand_stream(self, from_addr: str) -> "AsyncIterator[Beacon]":
        raise NotImplementedError


class LocalNetwork:
    """In-process network: address -> service registry, with fault
    injection (deny lists, drop rates) mirroring the reference's DenyClient
    (core/util_test.go:450-478)."""

    def __init__(self, seed: int = 0):
        self._services: dict[str, ProtocolService] = {}
        self._deny: set[tuple[str, str]] = set()  # (src, dst) pairs
        self._down: set[str] = set()
        self._rng = random.Random(seed)

    def register(self, address: str, service: ProtocolService) -> None:
        self._services[address] = service

    def unregister(self, address: str) -> None:
        self._services.pop(address, None)

    # -- fault injection ----------------------------------------------------
    def deny(self, src: str, dst: str) -> None:
        self._deny.add((src, dst))

    def allow(self, src: str, dst: str) -> None:
        self._deny.discard((src, dst))

    def set_down(self, address: str, down: bool = True) -> None:
        (self._down.add if down else self._down.discard)(address)

    def _target(self, src: str, peer) -> ProtocolService:
        dst = peer.address() if hasattr(peer, "address") else str(peer)
        if (src, dst) in self._deny:
            raise TransportError(f"{src} -> {dst}: denied (fault injection)")
        if dst in self._down or dst not in self._services:
            raise TransportError(f"{dst}: unreachable")
        if src in self._down:
            raise TransportError(f"{src}: sender down")
        return self._services[dst]

    def client_for(self, address: str) -> "LocalClient":
        return LocalClient(self, address)


class LocalClient(ProtocolClient):
    def __init__(self, network: LocalNetwork, address: str):
        self._net = network
        self._addr = address

    async def partial_beacon(self, peer, packet: PartialBeaconPacket) -> None:
        svc = self._net._target(self._addr, peer)
        try:
            await svc.process_partial_beacon(self._addr, packet)
        except PeerRejectedError:
            raise
        except TransportError as e:
            # _target already raised for unreachability; an error from
            # the service itself is the PEER's verdict — it answered
            raise PeerRejectedError(str(e)) from e

    async def sync_chain(self, peer, req: SyncRequest) -> AsyncIterator[Beacon]:
        svc = self._net._target(self._addr, peer)
        async for b in svc.sync_chain(self._addr, req):
            yield b

    async def broadcast_dkg(self, peer, packet) -> None:
        svc = self._net._target(self._addr, peer)
        await svc.broadcast_dkg(self._addr, packet)

    async def signal_dkg_participant(self, peer, packet) -> None:
        svc = self._net._target(self._addr, peer)
        await svc.signal_dkg_participant(self._addr, packet)

    async def push_dkg_info(self, peer, packet) -> None:
        svc = self._net._target(self._addr, peer)
        await svc.push_dkg_info(self._addr, packet)

    async def chain_info(self, peer):
        svc = self._net._target(self._addr, peer)
        return await svc.chain_info(self._addr)

    async def get_identity(self, peer) -> dict:
        svc = self._net._target(self._addr, peer)
        return await svc.get_identity(self._addr)

    async def private_rand(self, peer, request: bytes) -> bytes:
        svc = self._net._target(self._addr, peer)
        return await svc.private_rand(self._addr, request)

    async def peer_metrics(self, peer) -> bytes:
        svc = self._net._target(self._addr, peer)
        return await svc.peer_metrics(self._addr)

    async def public_rand(self, peer, round_no: int):
        svc = self._net._target(self._addr, peer)
        return await svc.public_rand(self._addr, round_no)

    async def public_rand_stream(self, peer):
        svc = self._net._target(self._addr, peer)
        async for b in svc.public_rand_stream(self._addr):
            yield b
