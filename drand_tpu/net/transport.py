"""Transport abstraction between nodes.

The protocol plane is transport-agnostic: the beacon engine and DKG talk to
a ``ProtocolClient`` and expose a ``ProtocolService``; implementations are
the in-memory ``LocalNetwork`` (tests — the DrandTest2 analogue,
core/util_test.go:32) and the gRPC transport (drand_tpu.net.grpc).

Reference: net/client.go:30 (ProtocolClient), net/gateway.go:44 (Service).
"""

from __future__ import annotations

import asyncio
import os
import random
from typing import AsyncIterator, Protocol

from .packets import PartialBeaconPacket, PartialRequest, SyncRequest
from ..chain.beacon import Beacon


class Peer(Protocol):
    def address(self) -> str: ...


class TransportError(Exception):
    pass


class PeerRejectedError(TransportError):
    """The peer ANSWERED and rejected the request (stale/future window,
    failed verification, its own policy) — reachability-wise the
    opposite of a TransportError: the link is fine. Callers feeding
    reachability SLIs (handler._send_partial) must not count these as
    unreachability; conflating them turns every lagging-but-alive peer
    into a phantom partition suspect."""


class BreakerOpenError(Exception):
    """An outbound call was SKIPPED because the peer's circuit breaker
    is open. Deliberately NOT a TransportError: the retry policy must
    never classify it as a transport outcome (no send happened), and a
    retry loop whose breaker opens mid-flight aborts immediately
    instead of burning its remaining attempts."""


# breaker tuning (ISSUE 12): trip after this many CONSECUTIVE transport
# failures; the half-open probe rate is set per-handler (one probe per
# round period by default)
BREAKER_THRESHOLD = int(os.environ.get("DRAND_TPU_BREAKER_THRESHOLD", "3"))

BREAKER_CLOSED = 0
BREAKER_HALF_OPEN = 1
BREAKER_OPEN = 2


class PeerBreaker:
    """Per-peer circuit breaker for the outbound beacon plane.

    State machine: CLOSED counts consecutive transport failures and
    trips OPEN at ``threshold``; OPEN denies all sends until
    ``cooldown_s`` elapses, then admits exactly ONE probe (HALF_OPEN —
    concurrent callers keep being denied, so probes are rate-capped by
    construction even when a round fans out many sends at once); a
    successful probe closes the breaker, a failed one re-opens it for
    another cooldown.

    Classification contract (the PeerRejectedError rule): only
    TRANSPORT failures trip the breaker — a peer that answered with a
    rejection is reachable and records ``ok=True``. Feeding rejects in
    would open breakers against every lagging-but-alive peer and
    partition the group from the inside.

    Single-threaded by design: driven from the event loop by the
    handler's send path (the same path that feeds
    ``beacon_peer_reachable``); no lock needed. State transitions are
    exported via ``on_state`` (the ``beacon_peer_breaker_state{index}``
    gauge)."""

    def __init__(self, index: int, threshold: int = BREAKER_THRESHOLD,
                 cooldown_s: float = 10.0, on_state=None):
        self.index = index
        self.threshold = max(1, threshold)
        self.cooldown_s = cooldown_s
        self.state = BREAKER_CLOSED
        self._fails = 0
        self._next_probe = 0.0
        self._on_state = on_state
        if on_state is not None:
            on_state(index, BREAKER_CLOSED)

    def _set(self, state: int) -> None:
        if state != self.state:
            self.state = state
            if self._on_state is not None:
                self._on_state(self.index, state)

    def allow(self, now: float) -> bool:
        """May a send go out right now? OPEN past the cooldown admits
        one probe and moves to HALF_OPEN; the next probe slot is
        reserved immediately, so even a probe whose outcome never lands
        (wedged transport) cannot exceed the capped rate."""
        if self.state == BREAKER_CLOSED:
            return True
        if now >= self._next_probe:
            # OPEN past the cooldown — or HALF_OPEN whose reserved slot
            # EXPIRED: a probe whose outcome never landed (caller died
            # between allow() and record(), wedged transport) must not
            # blacklist the peer forever, so the slot becomes grantable
            # again after a full cooldown
            self._set(BREAKER_HALF_OPEN)
            self._next_probe = now + self.cooldown_s
            return True
        return False

    def record(self, ok: bool, now: float) -> None:
        """One send outcome. ``ok`` covers success AND answered-with-
        reject (see the classification contract)."""
        if ok:
            self._fails = 0
            self._set(BREAKER_CLOSED)
            return
        self._fails += 1
        if self.state == BREAKER_HALF_OPEN:
            # the next probe slot was already reserved when allow()
            # granted this one — a probe whose FAILURE lands late (slow
            # link, retry backoff) must not push the slot past the next
            # round's sends, or probes drift into the mid-round dead
            # zone and a healed partition takes an extra round to notice
            self._set(BREAKER_OPEN)
        elif self.state == BREAKER_CLOSED \
                and self._fails >= self.threshold:
            self._set(BREAKER_OPEN)
            self._next_probe = now + self.cooldown_s
        # failures reported while already OPEN (in-flight sends that
        # passed allow() before a sibling tripped the breaker) never
        # move the reserved probe slot


class ProtocolClient:
    """Outbound node->node calls (reference net/client.go:30-49)."""

    async def partial_beacon(self, peer, packet: PartialBeaconPacket) -> None:
        raise NotImplementedError

    async def request_partials(self, peer, req: PartialRequest
                               ) -> list[PartialBeaconPacket]:
        """Quorum repair PULL (ISSUE 12): the peer's collected partials
        for one round, minus the indices the caller already holds."""
        raise NotImplementedError

    async def sync_chain(self, peer, req: SyncRequest) -> AsyncIterator[Beacon]:
        raise NotImplementedError

    async def broadcast_dkg(self, peer, packet) -> None:
        raise NotImplementedError

    async def signal_dkg_participant(self, peer, packet) -> None:
        raise NotImplementedError

    async def push_dkg_info(self, peer, packet) -> None:
        raise NotImplementedError

    async def chain_info(self, peer) -> "Info":
        raise NotImplementedError

    async def get_identity(self, peer) -> dict:
        raise NotImplementedError

    async def private_rand(self, peer, request: bytes) -> bytes:
        raise NotImplementedError

    async def peer_metrics(self, peer) -> bytes:
        raise NotImplementedError

    async def public_rand(self, peer, round_no: int) -> "Beacon":
        raise NotImplementedError

    def public_rand_stream(self, peer) -> "AsyncIterator[Beacon]":
        raise NotImplementedError


class ProtocolService:
    """Inbound service surface a node registers on its transport
    (reference protobuf/drand/protocol.proto:16-33)."""

    async def process_partial_beacon(self, from_addr: str, packet: PartialBeaconPacket) -> None:
        raise NotImplementedError

    async def request_partials(self, from_addr: str, req: PartialRequest
                               ) -> list[PartialBeaconPacket]:
        raise NotImplementedError

    def sync_chain(self, from_addr: str, req: SyncRequest) -> AsyncIterator[Beacon]:
        raise NotImplementedError

    async def broadcast_dkg(self, from_addr: str, packet) -> None:
        raise NotImplementedError

    async def signal_dkg_participant(self, from_addr: str, packet) -> None:
        raise NotImplementedError

    async def push_dkg_info(self, from_addr: str, packet) -> None:
        raise NotImplementedError

    async def chain_info(self, from_addr: str):
        raise NotImplementedError

    async def get_identity(self, from_addr: str) -> dict:
        raise NotImplementedError

    async def private_rand(self, from_addr: str, request: bytes) -> bytes:
        raise NotImplementedError

    async def peer_metrics(self, from_addr: str) -> bytes:
        raise NotImplementedError

    async def public_rand(self, from_addr: str, round_no: int) -> "Beacon":
        raise NotImplementedError

    def public_rand_stream(self, from_addr: str) -> "AsyncIterator[Beacon]":
        raise NotImplementedError


class LocalNetwork:
    """In-process network: address -> service registry, with fault
    injection (deny lists, drop rates) mirroring the reference's DenyClient
    (core/util_test.go:450-478)."""

    def __init__(self, seed: int = 0):
        self._services: dict[str, ProtocolService] = {}
        self._deny: set[tuple[str, str]] = set()  # (src, dst) pairs
        self._down: set[str] = set()
        self._rng = random.Random(seed)

    def register(self, address: str, service: ProtocolService) -> None:
        self._services[address] = service

    def unregister(self, address: str) -> None:
        self._services.pop(address, None)

    # -- fault injection ----------------------------------------------------
    def deny(self, src: str, dst: str) -> None:
        self._deny.add((src, dst))

    def allow(self, src: str, dst: str) -> None:
        self._deny.discard((src, dst))

    def allow_all(self) -> None:
        self._deny.clear()

    def set_down(self, address: str, down: bool = True) -> None:
        (self._down.add if down else self._down.discard)(address)

    def _target(self, src: str, peer) -> ProtocolService:
        dst = peer.address() if hasattr(peer, "address") else str(peer)
        if (src, dst) in self._deny:
            raise TransportError(f"{src} -> {dst}: denied (fault injection)")
        if dst in self._down or dst not in self._services:
            raise TransportError(f"{dst}: unreachable")
        if src in self._down:
            raise TransportError(f"{src}: sender down")
        return self._services[dst]

    def client_for(self, address: str) -> "LocalClient":
        return LocalClient(self, address)


class LocalClient(ProtocolClient):
    def __init__(self, network: LocalNetwork, address: str):
        self._net = network
        self._addr = address

    async def partial_beacon(self, peer, packet: PartialBeaconPacket) -> None:
        svc = self._net._target(self._addr, peer)
        try:
            await svc.process_partial_beacon(self._addr, packet)
        except PeerRejectedError:
            raise
        except TransportError as e:
            # _target already raised for unreachability; an error from
            # the service itself is the PEER's verdict — it answered
            raise PeerRejectedError(str(e)) from e

    async def request_partials(self, peer, req: PartialRequest
                               ) -> list[PartialBeaconPacket]:
        svc = self._net._target(self._addr, peer)
        try:
            return await svc.request_partials(self._addr, req)
        except PeerRejectedError:
            raise
        except TransportError as e:
            # _target already raised for unreachability; an error from
            # the service itself is the PEER's verdict — it answered
            # (the gRPC transport maps FAILED_PRECONDITION the same way)
            raise PeerRejectedError(str(e)) from e

    async def sync_chain(self, peer, req: SyncRequest) -> AsyncIterator[Beacon]:
        svc = self._net._target(self._addr, peer)
        async for b in svc.sync_chain(self._addr, req):
            yield b

    async def broadcast_dkg(self, peer, packet) -> None:
        svc = self._net._target(self._addr, peer)
        await svc.broadcast_dkg(self._addr, packet)

    async def signal_dkg_participant(self, peer, packet) -> None:
        svc = self._net._target(self._addr, peer)
        await svc.signal_dkg_participant(self._addr, packet)

    async def push_dkg_info(self, peer, packet) -> None:
        svc = self._net._target(self._addr, peer)
        await svc.push_dkg_info(self._addr, packet)

    async def chain_info(self, peer):
        svc = self._net._target(self._addr, peer)
        return await svc.chain_info(self._addr)

    async def get_identity(self, peer) -> dict:
        svc = self._net._target(self._addr, peer)
        return await svc.get_identity(self._addr)

    async def private_rand(self, peer, request: bytes) -> bytes:
        svc = self._net._target(self._addr, peer)
        return await svc.private_rand(self._addr, request)

    async def peer_metrics(self, peer) -> bytes:
        svc = self._net._target(self._addr, peer)
        return await svc.peer_metrics(self._addr)

    async def public_rand(self, peer, round_no: int):
        svc = self._net._target(self._addr, peer)
        return await svc.public_rand(self._addr, round_no)

    async def public_rand_stream(self, peer):
        svc = self._net._target(self._addr, peer)
        async for b in svc.public_rand_stream(self._addr):
            yield b
