"""gRPC transport: the across-hosts node<->node plane.

Reference: net/gateway.go (PrivateGateway :17), net/listener.go
(NewGRPCListenerForPrivate :27), net/client_grpc.go (grpcClient :27, pooled
conns :271, per-call timeouts, streaming SyncChain :219).

grpc.aio with generic method handlers (no codegen in this image); payloads
are wire.py envelopes. Service surface mirrors protobuf/drand/
protocol.proto:16-33: GetIdentity, SignalDKGParticipant, PushDKGInfo,
BroadcastDKG, PartialBeacon (unary) and SyncChain (server-streaming).
"""

from __future__ import annotations

import asyncio
from typing import AsyncIterator

import grpc
import grpc.aio

from ..chain.beacon import Beacon
from ..obs import trace as obs_trace
from ..utils.logging import KVLogger, default_logger
from . import protowire as pw
from . import wire
from .packets import PartialBeaconPacket, SyncRequest
from .transport import (PeerRejectedError, ProtocolClient,
                        ProtocolService, TransportError)

# gRPC codes the GATEWAY maps application-level rejections onto
# (INVALID_ARGUMENT for wire errors, FAILED_PRECONDITION for protocol
# rejects, PERMISSION_DENIED for policy) — the peer answered, so these
# raise PeerRejectedError; every other code (UNAVAILABLE,
# DEADLINE_EXCEEDED, ...) is connectivity and stays TransportError.
_REJECT_CODES = (grpc.StatusCode.INVALID_ARGUMENT,
                 grpc.StatusCode.FAILED_PRECONDITION,
                 grpc.StatusCode.PERMISSION_DENIED,
                 grpc.StatusCode.NOT_FOUND,
                 grpc.StatusCode.UNIMPLEMENTED)

SERVICE = "drand.Protocol"
PUBLIC_SERVICE = "drand.Public"  # protobuf interop surface (api.proto)
_UNARY = ("GetIdentity", "SignalDKGParticipant", "PushDKGInfo",
          "BroadcastDKG", "PartialBeacon", "RequestPartials", "ChainInfo",
          "PrivateRand", "Metrics", "PublicRand")

DEFAULT_TIMEOUT = 5.0
SYNC_TIMEOUT = 600.0


class GrpcGateway:
    """Server side: exposes a ProtocolService on a TCP port; with
    ``tls=(cert_path, key_path)`` the listener speaks TLS
    (net/listener.go:108)."""

    def __init__(self, service: ProtocolService, listen: str,
                 logger: KVLogger | None = None,
                 tls: tuple[str, str] | None = None,
                 timelock_service=None):
        self._svc = service
        self._listen = listen
        self._l = logger or default_logger("grpc")
        self._tls = tls
        # optional timelock vault front (drand_tpu/timelock, ISSUE 11
        # carry-over from PR 9): mirrors the HTTP tier's POST /timelock
        # + GET /timelock/{id} as TimelockSubmit/TimelockStatus on the
        # public service, reusing TimelockService's canonicalization
        # and validation verbatim. Attachable late (set_timelock) — the
        # daemon builds the service only once the beacon exists.
        self._timelock = timelock_service
        self._server: grpc.aio.Server | None = None
        self.port: int | None = None

    def set_timelock(self, svc) -> None:
        """Attach (or detach with None) the timelock service the
        TimelockSubmit/TimelockStatus methods front."""
        self._timelock = svc

    async def start(self) -> None:
        server = grpc.aio.server()
        handlers = {}
        for name in _UNARY:
            handlers[name] = grpc.unary_unary_rpc_method_handler(
                self._unary(name))
        handlers["SyncChain"] = grpc.unary_stream_rpc_method_handler(
            self._sync_chain)
        handlers["PublicRandStream"] = grpc.unary_stream_rpc_method_handler(
            self._public_rand_stream)
        server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SERVICE, handlers),))
        # drand.Public: the reference's protobuf wire (net/protowire.py) —
        # ecosystem drand clients fetch/stream from this service untouched
        pub = {
            "PublicRand": grpc.unary_unary_rpc_method_handler(
                self._pb_public_rand),
            "PublicRandStream": grpc.unary_stream_rpc_method_handler(
                self._pb_public_rand_stream),
            "PrivateRand": grpc.unary_unary_rpc_method_handler(
                self._pb_private_rand),
            "ChainInfo": grpc.unary_unary_rpc_method_handler(
                self._pb_chain_info),
            "Home": grpc.unary_unary_rpc_method_handler(self._pb_home),
            # timelock vault mirror of the HTTP tier (JSON bodies both
            # ways — the same envelope a client POSTs to /timelock)
            "TimelockSubmit": grpc.unary_unary_rpc_method_handler(
                self._timelock_submit),
            "TimelockStatus": grpc.unary_unary_rpc_method_handler(
                self._timelock_status),
        }
        server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(PUBLIC_SERVICE, pub),))
        if self._tls is not None:
            from . import tls as tls_mod

            creds = tls_mod.server_credentials(*self._tls)
            self.port = server.add_secure_port(self._listen, creds)
        else:
            self.port = server.add_insecure_port(self._listen)
        if self.port == 0:
            raise TransportError(f"cannot bind {self._listen}")
        await server.start()
        self._server = server
        self._l.info("grpc", "listening", addr=self._listen, port=self.port,
                     tls=self._tls is not None)

    async def stop(self, grace: float = 0.5) -> None:
        if self._server is not None:
            await self._server.stop(grace)

    # ------------------------------------------------------------ handlers
    def _unary(self, name: str):
        method = {
            "GetIdentity": self._get_identity,
            "SignalDKGParticipant": self._signal,
            "PushDKGInfo": self._push_group,
            "BroadcastDKG": self._broadcast,
            "PartialBeacon": self._partial,
            "RequestPartials": self._request_partials,
            "ChainInfo": self._chain_info,
            "PrivateRand": self._private_rand,
            "Metrics": self._peer_metrics,
            "PublicRand": self._public_rand,
        }[name]

        async def handler(request: bytes, context) -> bytes:
            from .. import metrics

            metrics.API_CALLS.labels(method=name).inc()
            # adopt the caller's round-correlation id (W3C traceparent
            # layout) so the callee's spans/logs stitch into the same
            # cross-node timeline; malformed/absent metadata is a no-op
            with obs_trace.TRACER.activate_traceparent(
                    obs_trace.traceparent_from_context(context)):
                try:
                    try:
                        msg, from_addr = wire.decode(request)
                    except wire.WireError:
                        # dual-codec: a reference node speaks protobuf on
                        # the same Protocol method names
                        # (protocol.proto:16-33) — decode, convert to the
                        # native packet, reply protobuf
                        return await self._pb_protocol(name, request,
                                                       context)
                    return await method(msg, from_addr)
                except (wire.WireError, pw.WireError) as e:
                    await context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                                        str(e))
                except (TransportError, PermissionError, ValueError) as e:
                    await context.abort(grpc.StatusCode.FAILED_PRECONDITION,
                                        str(e))
        return handler

    async def _pb_protocol(self, name: str, request: bytes, context):
        """Protobuf branch of the Protocol plane: the wire layouts a
        reference PEER sends (PartialBeacon, GetIdentity,
        SignalDKGParticipant, PushDKGInfo, BroadcastDKG —
        protocol.proto:16-33, dkg.proto:14-93). Responses are protobuf
        (drand.Empty = b'' / drand.Identity). Ambiguity guard: proto3
        parses near-arbitrary bytes into all-default messages, so each
        decode requires its semantically-mandatory fields to be present
        before the packet is accepted."""
        peer = context.peer()
        if name == "PartialBeacon":
            req = pw.decode(pw.PARTIAL_BEACON_PACKET, request)
            if not req["round"] or not req["partial_sig"]:
                raise pw.WireError(
                    "PartialBeacon decodes to default round/partial_sig")
            await self._svc.process_partial_beacon(peer, PartialBeaconPacket(
                round=req["round"], previous_sig=req["previous_sig"],
                partial_sig=req["partial_sig"],
                partial_sig_v2=req["partial_sig_v2"],
                partial_ckpt=req["partial_ckpt"]))
            return b""  # drand.Empty
        if name == "GetIdentity":
            if request:
                raise pw.WireError("IdentityRequest carries no fields")
            ident = await self._svc.get_identity(peer)
            return pw.encode(pw.IDENTITY, {
                "address": ident.addr, "key": ident.key.to_bytes(),
                "tls": ident.tls, "signature": ident.signature})
        if name == "SignalDKGParticipant":
            req = pw.decode(pw.SIGNAL_DKG_PACKET, request)
            if req["node"] is None or not req["secret_proof"]:
                raise pw.WireError(
                    "SignalDKGPacket without node/secret_proof")
            from ..crypto.curves import PointG1
            from ..key.keys import Identity
            from .packets import SignalDKGPacket

            nd = req["node"]
            ident = Identity(key=PointG1.from_bytes(nd["key"]),
                             addr=nd["address"], tls=nd["tls"],
                             signature=nd["signature"])
            await self._svc.signal_dkg_participant(peer, SignalDKGPacket(
                identity=ident, secret=req["secret_proof"],
                previous_group_hash=req["previous_group_hash"]))
            return b""
        if name == "PushDKGInfo":
            req = pw.decode(pw.DKG_INFO_PACKET, request)
            if req["new_group"] is None or not req["secret_proof"]:
                raise pw.WireError(
                    "DKGInfoPacket without new_group/secret_proof")
            from .packets import GroupPacket as NativeGroupPacket

            g = req["new_group"]
            group_dict = {
                "threshold": g["threshold"], "period": g["period"],
                "catchup_period": g["catchup_period"],
                "genesis_time": g["genesis_time"],
                "transition_time": g["transition_time"],
                "genesis_seed": g["genesis_seed"].hex(),
                "nodes": [{
                    "index": n["index"],
                    "address": (n["public"] or {}).get("address", ""),
                    "tls": (n["public"] or {}).get("tls", False),
                    "key": (n["public"] or {}).get("key", b"").hex(),
                    "signature":
                        (n["public"] or {}).get("signature", b"").hex(),
                } for n in g["nodes"]],
            }
            if g["dist_key"]:
                group_dict["public_key"] = [c.hex() for c in g["dist_key"]]
            await self._svc.push_dkg_info(peer, NativeGroupPacket(
                group=group_dict, signature=req["signature"],
                secret=req["secret_proof"],
                dkg_timeout=float(req["dkg_timeout"] or 10.0)))
            return b""
        if name == "BroadcastDKG":
            req = pw.decode(pw.DKG_PACKET, request)
            if req["dkg"] is None:
                raise pw.WireError("DKGPacket without dkg bundle")
            arm, b = pw.oneof_of(req["dkg"], pw.DKG_BUNDLE_ARMS)
            if arm is None:
                raise pw.WireError("dkg.Packet with no bundle arm set")
            from ..dkg import packets as dp

            if arm == "deal":
                bundle = dp.DealBundle(
                    dealer_index=b["dealer_index"],
                    commits=tuple(b["commits"]),
                    deals=tuple(dp.Deal(share_index=d["share_index"],
                                        encrypted_share=d["encrypted_share"])
                                for d in b["deals"]),
                    session_id=b["session_id"], signature=b["signature"])
            elif arm == "response":
                bundle = dp.ResponseBundle(
                    share_index=b["share_index"],
                    responses=tuple(dp.Response(
                        dealer_index=r["dealer_index"],
                        status=(dp.STATUS_APPROVAL if r["status"]
                                else dp.STATUS_COMPLAINT))
                        for r in b["responses"]),
                    session_id=b["session_id"], signature=b["signature"])
            else:
                bundle = dp.JustificationBundle(
                    dealer_index=b["dealer_index"],
                    justifications=tuple(dp.Justification(
                        share_index=j["share_index"], share=j["share"])
                        for j in b["justifications"]),
                    session_id=b["session_id"], signature=b["signature"])
            await self._svc.broadcast_dkg(peer, bundle)
            return b""
        # no protobuf layout for this method: re-raise as a wire error
        raise pw.WireError(f"method {name} has no protobuf request layout")

    async def _get_identity(self, msg, from_addr) -> bytes:
        ident = await self._svc.get_identity(from_addr)
        return wire.encode(ident)

    async def _signal(self, msg, from_addr) -> bytes:
        await self._svc.signal_dkg_participant(from_addr, msg)
        return b"{}"

    async def _push_group(self, msg, from_addr) -> bytes:
        await self._svc.push_dkg_info(from_addr, msg)
        return b"{}"

    async def _broadcast(self, msg, from_addr) -> bytes:
        await self._svc.broadcast_dkg(from_addr, msg)
        return b"{}"

    async def _partial(self, msg, from_addr) -> bytes:
        await self._svc.process_partial_beacon(from_addr, msg)
        return b"{}"

    async def _request_partials(self, msg, from_addr) -> bytes:
        from .packets import PartialBatch

        served = await self._svc.request_partials(from_addr, msg)
        return wire.encode(PartialBatch(packets=tuple(served)))

    async def _chain_info(self, msg, from_addr) -> bytes:
        info = await self._svc.chain_info(from_addr)
        return wire.encode(info)

    async def _private_rand(self, msg, from_addr) -> bytes:
        out = await self._svc.private_rand(from_addr, bytes(msg))
        return wire.encode(wire.Blob(out))

    async def _peer_metrics(self, msg, from_addr) -> bytes:
        return wire.encode(wire.Blob(await self._svc.peer_metrics(from_addr)))

    async def _public_rand(self, msg, from_addr) -> bytes:
        # request reuses SyncRequest: from_round = wanted round (0 = latest)
        b = await self._svc.public_rand(from_addr, msg.from_round)
        return wire.encode(b)

    async def _public_rand_stream(self, request: bytes, context):
        try:
            _, from_addr = wire.decode(request)
        except wire.WireError as e:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
            return
        try:
            async for b in self._svc.public_rand_stream(from_addr):
                yield wire.encode(b)
        except TransportError as e:
            await context.abort(grpc.StatusCode.FAILED_PRECONDITION, str(e))

    async def _sync_chain(self, request: bytes, context):
        """Dual-codec: the native JSON envelope OR the reference protobuf
        SyncRequest (protocol.proto:84-92) — an ecosystem drand node can
        sync from us on the standard /drand.Protocol/SyncChain method.
        The response codec follows the request codec."""
        proto = False
        try:
            msg, from_addr = wire.decode(request)
        except wire.WireError:
            try:
                req = pw.decode(pw.SYNC_REQUEST, request)
            except pw.WireError as e:
                await context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
                return
            proto = True
            # from_round=0 (which proto3 encodes as the EMPTY message) is
            # a full-chain sync request in the reference
            # (chain/beacon/sync.go:134-150); serve it from round 1 —
            # round 0 is the locally-derivable genesis beacon.
            # Documented deviation: we cannot distinguish an
            # intentionally-empty request from a zero-valued one, both
            # get the full chain (ADVICE r4 reversing the r3 rejection).
            msg = SyncRequest(from_round=req.get("from_round") or 1)
            from_addr = context.peer()
        try:
            async for b in self._svc.sync_chain(from_addr, msg):
                if proto:
                    yield pw.encode(pw.BEACON_PACKET, {
                        "previous_sig": b.previous_sig, "round": b.round,
                        "signature": b.signature})
                else:
                    yield wire.encode(b)
        except TransportError as e:
            await context.abort(grpc.StatusCode.FAILED_PRECONDITION, str(e))

    # --------------------------------------------- drand.Public (protobuf)
    def _pb_beacon(self, b: Beacon) -> bytes:
        return pw.encode(pw.PUBLIC_RAND_RESPONSE, {
            "round": b.round, "signature": b.signature,
            "previous_signature": b.previous_sig,
            "randomness": b.randomness(),
            "signature_v2": b.signature_v2})

    async def _pb_public_rand(self, request: bytes, context) -> bytes:
        try:
            req = pw.decode(pw.PUBLIC_RAND_REQUEST, request)
            b = await self._svc.public_rand(context.peer(), req["round"])
            return self._pb_beacon(b)
        except pw.WireError as e:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        except (TransportError, ValueError) as e:
            await context.abort(grpc.StatusCode.FAILED_PRECONDITION, str(e))

    async def _pb_public_rand_stream(self, request: bytes, context):
        try:
            async for b in self._svc.public_rand_stream(context.peer()):
                yield self._pb_beacon(b)
        except TransportError as e:
            await context.abort(grpc.StatusCode.FAILED_PRECONDITION, str(e))

    async def _pb_private_rand(self, request: bytes, context) -> bytes:
        try:
            req = pw.decode(pw.PRIVATE_RAND_REQUEST, request)
            out = await self._svc.private_rand(context.peer(),
                                               req["request"])
            return pw.encode(pw.PRIVATE_RAND_RESPONSE, {"response": out})
        except pw.WireError as e:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        except (TransportError, ValueError) as e:
            await context.abort(grpc.StatusCode.FAILED_PRECONDITION, str(e))

    async def _pb_chain_info(self, request: bytes, context) -> bytes:
        try:
            info = await self._svc.chain_info(context.peer())
            return pw.encode(pw.CHAIN_INFO_PACKET, {
                "public_key": info.public_key.to_bytes(),
                "period": info.period,
                "genesis_time": info.genesis_time,
                "hash": info.hash(),
                "group_hash": info.group_hash})
        except (TransportError, ValueError) as e:
            await context.abort(grpc.StatusCode.FAILED_PRECONDITION, str(e))

    async def _pb_home(self, request: bytes, context) -> bytes:
        return pw.encode(pw.HOME_RESPONSE,
                         {"status": "drand-tpu up and running"})

    # ------------------------------------------- timelock (JSON bodies)
    async def _timelock_submit(self, request: bytes, context) -> bytes:
        """drand.Public/TimelockSubmit: request = the envelope JSON a
        client would POST to /timelock; response = the status record
        JSON. Validation, canonicalization and the idempotent token are
        TimelockService.submit — the HTTP tier's path, verbatim."""
        import json

        from ..timelock.service import TimelockError

        if self._timelock is None:
            await context.abort(grpc.StatusCode.UNIMPLEMENTED,
                                "timelock vault not enabled on this node")
        try:
            envelope = json.loads(request.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                                "body is not JSON")
        if not isinstance(envelope, dict):
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                                "envelope is not a JSON object")
        try:
            rec = await self._timelock.submit(envelope)
        except TimelockError as e:
            msg = str(e)
            code = (grpc.StatusCode.UNAVAILABLE
                    if "chain info unavailable" in msg
                    else grpc.StatusCode.INVALID_ARGUMENT)
            await context.abort(code, msg)
        return json.dumps(rec).encode()

    async def _timelock_status(self, request: bytes, context) -> bytes:
        """drand.Public/TimelockStatus: request = the ciphertext id
        (utf-8 token); response = the status record JSON (the GET
        /timelock/{id} body)."""
        import json

        if self._timelock is None:
            await context.abort(grpc.StatusCode.UNIMPLEMENTED,
                                "timelock vault not enabled on this node")
        try:
            token = request.decode("utf-8").strip()
        except UnicodeDecodeError:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                                "token is not utf-8")
        rec = await self._timelock.status(token)
        if rec is None:
            await context.abort(grpc.StatusCode.NOT_FOUND,
                                "unknown ciphertext id")
        return json.dumps(rec).encode()


class GrpcClient(ProtocolClient):
    """Outbound calls with a per-peer channel pool (client_grpc.go:271)."""

    def __init__(self, own_addr: str, timeout: float = DEFAULT_TIMEOUT,
                 logger: KVLogger | None = None, certs=None):
        self._addr = own_addr
        self._timeout = timeout
        self._l = logger or default_logger("grpc.client")
        # certs: a tls.CertManager. A peer is dialed over TLS when the pool
        # is non-empty AND the peer's Identity.tls flag allows it (plain
        # addresses default to TLS when a pool exists) — mixed groups keep
        # plaintext members reachable (net/certs.go + client_grpc.go)
        self._certs = certs
        self._channels: dict[str, grpc.aio.Channel] = {}

    def _channel(self, peer) -> tuple[grpc.aio.Channel, str]:
        target = peer.address() if hasattr(peer, "address") else str(peer)
        have_pool = self._certs is not None and \
            self._certs.pool_pem() is not None
        use_tls = have_pool and getattr(peer, "tls", True)
        key = ("tls" if use_tls else "plain", target)
        ch = self._channels.get(key)
        if ch is None:
            if use_tls:
                from . import tls as tls_mod

                ch = grpc.aio.secure_channel(
                    target, tls_mod.channel_credentials(self._certs))
            else:
                ch = grpc.aio.insecure_channel(target)
            self._channels[key] = ch
            from .. import metrics

            # inc/dec (not set): several clients can live in one process
            metrics.GROUP_CONNECTIONS.inc()
        return ch, target

    async def close(self) -> None:
        if self._channels:
            from .. import metrics

            metrics.GROUP_CONNECTIONS.dec(len(self._channels))
        for ch in self._channels.values():
            await ch.close()
        self._channels.clear()

    async def _call(self, peer, method: str, msg) -> bytes:
        ch, target = self._channel(peer)
        fn = ch.unary_unary(f"/{SERVICE}/{method}")
        try:
            return await fn(wire.encode(msg, from_addr=self._addr),
                            timeout=self._timeout,
                            metadata=obs_trace.outbound_metadata())
        except grpc.aio.AioRpcError as e:
            from .. import metrics

            metrics.DIAL_FAILURES.labels(peer=target).inc()
            cls = (PeerRejectedError if e.code() in _REJECT_CODES
                   else TransportError)
            raise cls(
                f"{target} {method}: {e.code().name} {e.details()}") from e

    # ------------------------------------------------------ ProtocolClient
    async def partial_beacon(self, peer, packet: PartialBeaconPacket) -> None:
        await self._call(peer, "PartialBeacon", packet)

    async def request_partials(self, peer, req) -> list[PartialBeaconPacket]:
        raw = await self._call(peer, "RequestPartials", req)
        msg, _ = wire.decode(raw)
        return list(msg.packets)

    async def sync_chain(self, peer, req: SyncRequest) -> AsyncIterator[Beacon]:
        ch, target = self._channel(peer)
        fn = ch.unary_stream(f"/{SERVICE}/SyncChain")
        call = fn(wire.encode(req, from_addr=self._addr),
                  timeout=SYNC_TIMEOUT)
        try:
            async for raw in call:
                msg, _ = wire.decode(raw)
                yield msg
        except grpc.aio.AioRpcError as e:
            raise TransportError(
                f"{target} SyncChain: {e.code().name} {e.details()}") from e

    async def broadcast_dkg(self, peer, packet) -> None:
        await self._call(peer, "BroadcastDKG", packet)

    async def signal_dkg_participant(self, peer, packet) -> None:
        await self._call(peer, "SignalDKGParticipant", packet)

    async def push_dkg_info(self, peer, packet) -> None:
        await self._call(peer, "PushDKGInfo", packet)

    async def chain_info(self, peer):
        raw = await self._call(peer, "ChainInfo", b_empty())
        msg, _ = wire.decode(raw)
        return msg

    async def get_identity(self, peer):
        raw = await self._call(peer, "GetIdentity", b_empty())
        msg, _ = wire.decode(raw)
        return msg

    async def private_rand(self, peer, request: bytes) -> bytes:
        raw = await self._call(peer, "PrivateRand", wire.Blob(request))
        msg, _ = wire.decode(raw)
        return bytes(msg)

    async def peer_metrics(self, peer) -> bytes:
        raw = await self._call(peer, "Metrics", b_empty())
        msg, _ = wire.decode(raw)
        return bytes(msg)

    async def public_rand(self, peer, round_no: int):
        raw = await self._call(peer, "PublicRand",
                               SyncRequest(from_round=round_no))
        msg, _ = wire.decode(raw)
        return msg

    # --------------------------------------------------- timelock mirror
    async def timelock_submit(self, peer, envelope: dict) -> dict:
        """Submit a timelock envelope over drand.Public (the gRPC
        mirror of POST /timelock). Returns the status record."""
        import json

        ch, target = self._channel(peer)
        fn = ch.unary_unary(f"/{PUBLIC_SERVICE}/TimelockSubmit")
        try:
            raw = await fn(json.dumps(envelope).encode(),
                           timeout=self._timeout)
        except grpc.aio.AioRpcError as e:
            raise TransportError(
                f"{target} TimelockSubmit: {e.code().name} "
                f"{e.details()}") from e
        return json.loads(raw.decode())

    async def timelock_status(self, peer, token: str) -> dict | None:
        """The ciphertext's status record (GET /timelock/{id} mirror);
        None for an unknown id."""
        import json

        ch, target = self._channel(peer)
        fn = ch.unary_unary(f"/{PUBLIC_SERVICE}/TimelockStatus")
        try:
            raw = await fn(token.encode(), timeout=self._timeout)
        except grpc.aio.AioRpcError as e:
            if e.code() == grpc.StatusCode.NOT_FOUND:
                return None
            raise TransportError(
                f"{target} TimelockStatus: {e.code().name} "
                f"{e.details()}") from e
        return json.loads(raw.decode())

    async def public_rand_stream(self, peer):
        ch, target = self._channel(peer)
        fn = ch.unary_stream(f"/{SERVICE}/PublicRandStream")
        # no deadline: a watch stream is indefinite (client/grpc Watch)
        call = fn(wire.encode(b_empty(), from_addr=self._addr), timeout=None)
        try:
            async for raw in call:
                msg, _ = wire.decode(raw)
                yield msg
        except grpc.aio.AioRpcError as e:
            raise TransportError(
                f"{target} PublicRandStream: {e.code().name} "
                f"{e.details()}") from e


def b_empty():
    """Placeholder request for argument-less RPCs."""
    return SyncRequest(from_round=0)
