"""gRPC transport: the across-hosts node<->node plane.

Reference: net/gateway.go (PrivateGateway :17), net/listener.go
(NewGRPCListenerForPrivate :27), net/client_grpc.go (grpcClient :27, pooled
conns :271, per-call timeouts, streaming SyncChain :219).

grpc.aio with generic method handlers (no codegen in this image); payloads
are wire.py envelopes. Service surface mirrors protobuf/drand/
protocol.proto:16-33: GetIdentity, SignalDKGParticipant, PushDKGInfo,
BroadcastDKG, PartialBeacon (unary) and SyncChain (server-streaming).
"""

from __future__ import annotations

import asyncio
from typing import AsyncIterator

import grpc
import grpc.aio

from ..chain.beacon import Beacon
from ..utils.logging import KVLogger, default_logger
from . import wire
from .packets import PartialBeaconPacket, SyncRequest
from .transport import ProtocolClient, ProtocolService, TransportError

SERVICE = "drand.Protocol"
_UNARY = ("GetIdentity", "SignalDKGParticipant", "PushDKGInfo",
          "BroadcastDKG", "PartialBeacon", "ChainInfo", "PrivateRand",
          "Metrics")

DEFAULT_TIMEOUT = 5.0
SYNC_TIMEOUT = 600.0


class GrpcGateway:
    """Server side: exposes a ProtocolService on a TCP port."""

    def __init__(self, service: ProtocolService, listen: str,
                 logger: KVLogger | None = None):
        self._svc = service
        self._listen = listen
        self._l = logger or default_logger("grpc")
        self._server: grpc.aio.Server | None = None
        self.port: int | None = None

    async def start(self) -> None:
        server = grpc.aio.server()
        handlers = {}
        for name in _UNARY:
            handlers[name] = grpc.unary_unary_rpc_method_handler(
                self._unary(name))
        handlers["SyncChain"] = grpc.unary_stream_rpc_method_handler(
            self._sync_chain)
        server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SERVICE, handlers),))
        self.port = server.add_insecure_port(self._listen)
        if self.port == 0:
            raise TransportError(f"cannot bind {self._listen}")
        await server.start()
        self._server = server
        self._l.info("grpc", "listening", addr=self._listen, port=self.port)

    async def stop(self, grace: float = 0.5) -> None:
        if self._server is not None:
            await self._server.stop(grace)

    # ------------------------------------------------------------ handlers
    def _unary(self, name: str):
        method = {
            "GetIdentity": self._get_identity,
            "SignalDKGParticipant": self._signal,
            "PushDKGInfo": self._push_group,
            "BroadcastDKG": self._broadcast,
            "PartialBeacon": self._partial,
            "ChainInfo": self._chain_info,
            "PrivateRand": self._private_rand,
            "Metrics": self._peer_metrics,
        }[name]

        async def handler(request: bytes, context) -> bytes:
            try:
                msg, from_addr = wire.decode(request)
                return await method(msg, from_addr)
            except wire.WireError as e:
                await context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
            except (TransportError, PermissionError, ValueError) as e:
                await context.abort(grpc.StatusCode.FAILED_PRECONDITION,
                                    str(e))
        return handler

    async def _get_identity(self, msg, from_addr) -> bytes:
        ident = await self._svc.get_identity(from_addr)
        return wire.encode(ident)

    async def _signal(self, msg, from_addr) -> bytes:
        await self._svc.signal_dkg_participant(from_addr, msg)
        return b"{}"

    async def _push_group(self, msg, from_addr) -> bytes:
        await self._svc.push_dkg_info(from_addr, msg)
        return b"{}"

    async def _broadcast(self, msg, from_addr) -> bytes:
        await self._svc.broadcast_dkg(from_addr, msg)
        return b"{}"

    async def _partial(self, msg, from_addr) -> bytes:
        await self._svc.process_partial_beacon(from_addr, msg)
        return b"{}"

    async def _chain_info(self, msg, from_addr) -> bytes:
        info = await self._svc.chain_info(from_addr)
        return wire.encode(info)

    async def _private_rand(self, msg, from_addr) -> bytes:
        out = await self._svc.private_rand(from_addr, bytes(msg))
        return wire.encode(wire.Blob(out))

    async def _peer_metrics(self, msg, from_addr) -> bytes:
        return wire.encode(wire.Blob(await self._svc.peer_metrics(from_addr)))

    async def _sync_chain(self, request: bytes, context):
        try:
            msg, from_addr = wire.decode(request)
        except wire.WireError as e:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
            return
        try:
            async for b in self._svc.sync_chain(from_addr, msg):
                yield wire.encode(b)
        except TransportError as e:
            await context.abort(grpc.StatusCode.FAILED_PRECONDITION, str(e))


class GrpcClient(ProtocolClient):
    """Outbound calls with a per-peer channel pool (client_grpc.go:271)."""

    def __init__(self, own_addr: str, timeout: float = DEFAULT_TIMEOUT,
                 logger: KVLogger | None = None):
        self._addr = own_addr
        self._timeout = timeout
        self._l = logger or default_logger("grpc.client")
        self._channels: dict[str, grpc.aio.Channel] = {}

    def _channel(self, peer) -> tuple[grpc.aio.Channel, str]:
        target = peer.address() if hasattr(peer, "address") else str(peer)
        ch = self._channels.get(target)
        if ch is None:
            ch = grpc.aio.insecure_channel(target)
            self._channels[target] = ch
        return ch, target

    async def close(self) -> None:
        for ch in self._channels.values():
            await ch.close()
        self._channels.clear()

    async def _call(self, peer, method: str, msg) -> bytes:
        ch, target = self._channel(peer)
        fn = ch.unary_unary(f"/{SERVICE}/{method}")
        try:
            return await fn(wire.encode(msg, from_addr=self._addr),
                            timeout=self._timeout)
        except grpc.aio.AioRpcError as e:
            from .. import metrics

            metrics.DIAL_FAILURES.labels(peer=target).inc()
            raise TransportError(
                f"{target} {method}: {e.code().name} {e.details()}") from e

    # ------------------------------------------------------ ProtocolClient
    async def partial_beacon(self, peer, packet: PartialBeaconPacket) -> None:
        await self._call(peer, "PartialBeacon", packet)

    async def sync_chain(self, peer, req: SyncRequest) -> AsyncIterator[Beacon]:
        ch, target = self._channel(peer)
        fn = ch.unary_stream(f"/{SERVICE}/SyncChain")
        call = fn(wire.encode(req, from_addr=self._addr),
                  timeout=SYNC_TIMEOUT)
        try:
            async for raw in call:
                msg, _ = wire.decode(raw)
                yield msg
        except grpc.aio.AioRpcError as e:
            raise TransportError(
                f"{target} SyncChain: {e.code().name} {e.details()}") from e

    async def broadcast_dkg(self, peer, packet) -> None:
        await self._call(peer, "BroadcastDKG", packet)

    async def signal_dkg_participant(self, peer, packet) -> None:
        await self._call(peer, "SignalDKGParticipant", packet)

    async def push_dkg_info(self, peer, packet) -> None:
        await self._call(peer, "PushDKGInfo", packet)

    async def chain_info(self, peer):
        raw = await self._call(peer, "ChainInfo", b_empty())
        msg, _ = wire.decode(raw)
        return msg

    async def get_identity(self, peer):
        raw = await self._call(peer, "GetIdentity", b_empty())
        msg, _ = wire.decode(raw)
        return msg

    async def private_rand(self, peer, request: bytes) -> bytes:
        raw = await self._call(peer, "PrivateRand", wire.Blob(request))
        msg, _ = wire.decode(raw)
        return bytes(msg)

    async def peer_metrics(self, peer) -> bytes:
        raw = await self._call(peer, "Metrics", b_empty())
        msg, _ = wire.decode(raw)
        return bytes(msg)


def b_empty():
    """Placeholder request for argument-less RPCs."""
    return SyncRequest(from_round=0)
