"""Byte-level wire codec for node<->node and control messages.

The reference serializes with protobuf (protobuf/drand/*.proto); this
framework owns both endpoints, so it uses a deterministic JSON envelope
with hex-encoded byte fields — the public REST API (http_server/) remains
the cross-ecosystem interop surface. Every message is
``{"t": <type>, "from": <sender listen addr>, "m": {...}}``; unknown types
or malformed fields raise WireError (ingress is untrusted).
"""

from __future__ import annotations

import json

from ..chain.beacon import Beacon
from ..chain.info import Info
from ..crypto.curves import PointG1
from ..dkg.packets import (
    Deal,
    DealBundle,
    Justification,
    JustificationBundle,
    Response,
    ResponseBundle,
)
from ..key.keys import Identity
from .packets import (GroupPacket, PartialBatch, PartialBeaconPacket,
                      PartialRequest, SignalDKGPacket, SyncRequest)


class WireError(Exception):
    pass


class Blob(bytes):
    """Raw-bytes payload (ECIES ciphertexts for PrivateRand)."""


def _hex(b: bytes) -> str:
    return b.hex()


def _unhex(s: str) -> bytes:
    return bytes.fromhex(s)


# --------------------------------------------------------------- encoders

def _enc_identity(i: Identity) -> dict:
    return {"key": _hex(i.key.to_bytes()), "addr": i.addr, "tls": i.tls,
            "sig": _hex(i.signature)}


def _dec_identity(d: dict) -> Identity:
    return Identity(key=PointG1.from_bytes(_unhex(d["key"])),
                    addr=d["addr"], tls=bool(d.get("tls", False)),
                    signature=_unhex(d.get("sig", "")))


_ENC = {}
_DEC = {}


def _codec(name):
    def wrap(cls_enc_dec):
        enc, dec = cls_enc_dec
        _ENC[name] = enc
        _DEC[name] = dec
        return cls_enc_dec
    return wrap


_codec("partial_beacon")((
    lambda p: {"round": p.round, "prev": _hex(p.previous_sig),
               "sig": _hex(p.partial_sig), "sig_v2": _hex(p.partial_sig_v2),
               "sig_ckpt": _hex(p.partial_ckpt)},
    lambda d: PartialBeaconPacket(
        round=int(d["round"]), previous_sig=_unhex(d["prev"]),
        partial_sig=_unhex(d["sig"]), partial_sig_v2=_unhex(d["sig_v2"]),
        partial_ckpt=_unhex(d.get("sig_ckpt", "")))))

_codec("sync_request")((
    lambda r: {"from_round": r.from_round},
    lambda d: SyncRequest(from_round=int(d["from_round"]))))

_codec("partial_request")((
    lambda r: {"round": r.round, "prev": _hex(r.previous_sig),
               "have": list(r.have)},
    lambda d: PartialRequest(round=int(d["round"]),
                             previous_sig=_unhex(d["prev"]),
                             have=tuple(int(i) for i in d.get("have", [])))))

_codec("partial_batch")((
    lambda b: {"packets": [_ENC["partial_beacon"](p) for p in b.packets]},
    lambda d: PartialBatch(packets=tuple(
        _DEC["partial_beacon"](p) for p in d.get("packets", [])))))

_codec("blob")((
    lambda b: {"data": _hex(bytes(b))},
    lambda d: Blob(_unhex(d["data"]))))

_codec("beacon")((
    lambda b: {"round": b.round, "prev": _hex(b.previous_sig),
               "sig": _hex(b.signature), "sig_v2": _hex(b.signature_v2)},
    lambda d: Beacon(round=int(d["round"]), previous_sig=_unhex(d["prev"]),
                     signature=_unhex(d["sig"]),
                     signature_v2=_unhex(d.get("sig_v2", "")))))

_codec("info")((
    lambda i: {"public_key": _hex(i.public_key.to_bytes()),
               "period": i.period, "genesis_time": i.genesis_time,
               "genesis_seed": _hex(i.genesis_seed),
               "group_hash": _hex(i.group_hash)},
    lambda d: Info(public_key=PointG1.from_bytes(_unhex(d["public_key"])),
                   period=int(d["period"]),
                   genesis_time=int(d["genesis_time"]),
                   genesis_seed=_unhex(d["genesis_seed"]),
                   group_hash=_unhex(d.get("group_hash", "")))))

_codec("identity")((_enc_identity, _dec_identity))

_codec("signal_dkg")((
    lambda p: {"identity": _enc_identity(p.identity),
               "secret": _hex(p.secret),
               "prev_group": _hex(p.previous_group_hash)},
    lambda d: SignalDKGPacket(identity=_dec_identity(d["identity"]),
                              secret=_unhex(d["secret"]),
                              previous_group_hash=_unhex(
                                  d.get("prev_group", "")))))

_codec("group_packet")((
    lambda p: {"group": p.group, "sig": _hex(p.signature),
               "secret": _hex(p.secret), "dkg_timeout": p.dkg_timeout},
    lambda d: GroupPacket(group=d["group"], signature=_unhex(d["sig"]),
                          secret=_unhex(d["secret"]),
                          dkg_timeout=float(d.get("dkg_timeout", 10.0)))))

_codec("deal_bundle")((
    lambda b: {"dealer": b.dealer_index,
               "commits": [_hex(c) for c in b.commits],
               "deals": [{"i": dl.share_index,
                          "enc": _hex(dl.encrypted_share)}
                         for dl in b.deals],
               "session": _hex(b.session_id), "sig": _hex(b.signature)},
    lambda d: DealBundle(
        dealer_index=int(d["dealer"]),
        commits=tuple(_unhex(c) for c in d["commits"]),
        deals=tuple(Deal(share_index=int(x["i"]),
                         encrypted_share=_unhex(x["enc"]))
                    for x in d["deals"]),
        session_id=_unhex(d["session"]), signature=_unhex(d["sig"]))))

_codec("response_bundle")((
    lambda b: {"share": b.share_index,
               "responses": [{"d": r.dealer_index, "s": r.status}
                             for r in b.responses],
               "session": _hex(b.session_id), "sig": _hex(b.signature)},
    lambda d: ResponseBundle(
        share_index=int(d["share"]),
        responses=tuple(Response(dealer_index=int(x["d"]),
                                 status=int(x["s"]))
                        for x in d["responses"]),
        session_id=_unhex(d["session"]), signature=_unhex(d["sig"]))))

_codec("justification_bundle")((
    lambda b: {"dealer": b.dealer_index,
               "justs": [{"i": j.share_index, "v": hex(j.share)}
                         for j in b.justifications],
               "session": _hex(b.session_id), "sig": _hex(b.signature)},
    lambda d: JustificationBundle(
        dealer_index=int(d["dealer"]),
        justifications=tuple(Justification(share_index=int(x["i"]),
                                           share=int(x["v"], 16))
                             for x in d["justs"]),
        session_id=_unhex(d["session"]), signature=_unhex(d["sig"]))))

_TYPE_OF = {
    Blob: "blob",
    PartialBeaconPacket: "partial_beacon",
    SyncRequest: "sync_request",
    PartialRequest: "partial_request",
    PartialBatch: "partial_batch",
    Beacon: "beacon",
    Info: "info",
    Identity: "identity",
    SignalDKGPacket: "signal_dkg",
    GroupPacket: "group_packet",
    DealBundle: "deal_bundle",
    ResponseBundle: "response_bundle",
    JustificationBundle: "justification_bundle",
}


def encode(obj, from_addr: str = "") -> bytes:
    t = _TYPE_OF.get(type(obj))
    if t is None:
        raise WireError(f"unencodable type {type(obj).__name__}")
    return json.dumps({"t": t, "from": from_addr, "m": _ENC[t](obj)},
                      separators=(",", ":")).encode()


def decode(data: bytes) -> tuple[object, str]:
    """-> (message, sender listen address). Raises WireError on garbage."""
    try:
        env = json.loads(data)
        t = env["t"]
        dec = _DEC.get(t)
        if dec is None:
            raise WireError(f"unknown message type {t!r}")
        return dec(env["m"]), str(env.get("from", ""))
    except WireError:
        raise
    except Exception as e:  # noqa: BLE001 — malformed input
        raise WireError(f"malformed message: {e!r}") from e
