"""Operator control plane: localhost gRPC port driving the daemon.

Reference: net/control.go (ControlListener :17, ControlClient :48) and
protobuf/drand/control.proto:14-37 (PingPong, InitDKG, InitReshare,
Share, PublicKey, PrivateKey, ChainInfo, GroupFile, Shutdown,
StartFollowChain). The CLI (`python -m drand_tpu.cli`) talks to a
running daemon exclusively through this port, like `drand` does.

DUAL CODEC (localhost operator plane): the native CLI speaks JSON
envelopes; every reference method ALSO accepts/returns control.proto
protobuf framing on the standard /drand.Control/* names, so reference
operator tooling (`drand share/stop/show` pointed at our control port)
interoperates. Codec detection: the native client always sends a JSON
object (at least ``{}``), so an empty or non-JSON request selects the
protobuf codec; the response follows the request codec.
"""

from __future__ import annotations

import asyncio
import json

from ..utils.toml_compat import tomllib

import grpc
import grpc.aio

from . import protowire as pw
from ..crypto.fields import R as _FR_R
from ..utils.logging import KVLogger, default_logger

SERVICE = "drand.Control"
_METHODS = ("Ping", "InitDKG", "InitReshare", "PublicKey", "GroupFile",
            "ChainInfo", "Status", "Shutdown", "Follow",
            # reference-only method names (protobuf codec)
            "PingPong", "Share", "PrivateKey")

# control.proto request/response specs per reference method name
_PROTO_SPECS = {
    "PingPong": (pw.EMPTY, pw.EMPTY),
    "InitDKG": (pw.INIT_DKG_PACKET, pw.GROUP_PACKET),
    "InitReshare": (pw.INIT_RESHARE_PACKET, pw.GROUP_PACKET),
    "Share": (pw.SHARE_REQUEST, pw.SHARE_RESPONSE),
    "PublicKey": (pw.PUBLIC_KEY_REQUEST, pw.PUBLIC_KEY_RESPONSE),
    "PrivateKey": (pw.PRIVATE_KEY_REQUEST, pw.PRIVATE_KEY_RESPONSE),
    "ChainInfo": (pw.CHAIN_INFO_REQUEST, pw.CHAIN_INFO_PACKET),
    "GroupFile": (pw.GROUP_REQUEST, pw.GROUP_PACKET),
    "Shutdown": (pw.SHUTDOWN_REQUEST, pw.SHUTDOWN_RESPONSE),
}


class ControlServer:
    def __init__(self, daemon, port: int, logger: KVLogger | None = None):
        self._d = daemon
        self._port = port
        self._l = logger or default_logger("control")
        self._server: grpc.aio.Server | None = None
        self.port: int | None = None
        self._shutdown_event = asyncio.Event()

    async def start(self) -> None:
        server = grpc.aio.server()
        handlers = {
            name: grpc.unary_unary_rpc_method_handler(self._dispatch(name))
            for name in _METHODS
        }
        # control.proto:37 — server-streaming follow with progress frames
        handlers["StartFollowChain"] = grpc.unary_stream_rpc_method_handler(
            self._start_follow_chain)
        server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SERVICE, handlers),))
        self.port = server.add_insecure_port(f"127.0.0.1:{self._port}")
        if self.port == 0:
            raise RuntimeError(f"cannot bind control port {self._port}")
        await server.start()
        self._server = server

    async def stop(self) -> None:
        if self._server is not None:
            await self._server.stop(0.2)

    async def wait_shutdown(self) -> None:
        await self._shutdown_event.wait()

    def _dispatch(self, name: str):
        native_method = getattr(self, f"_{name.lower()}", None)
        specs = _PROTO_SPECS.get(name)

        async def handler(request: bytes, context) -> bytes:
            req = None
            if request and native_method is not None:
                try:
                    req = json.loads(request)
                except (ValueError, UnicodeDecodeError):
                    req = None
            if req is not None:
                try:
                    return json.dumps(await native_method(req)).encode()
                except Exception as e:  # noqa: BLE001 — operator plane
                    await context.abort(grpc.StatusCode.FAILED_PRECONDITION,
                                        f"{type(e).__name__}: {e}")
                    return b""
            # protobuf codec (reference tooling)
            if specs is None:
                await context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                                    f"{name}: JSON payload expected")
                return b""
            req_spec, resp_spec = specs
            try:
                preq = pw.decode(req_spec, request)
            except pw.WireError as e:
                await context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
                return b""
            try:
                resp = await self._proto_call(name, preq)
                return pw.encode(resp_spec, resp)
            except Exception as e:  # noqa: BLE001 — operator plane
                await context.abort(grpc.StatusCode.FAILED_PRECONDITION,
                                    f"{type(e).__name__}: {e}")
                return b""
        return handler

    async def _proto_call(self, name: str, req: dict) -> dict:
        """control.proto semantics on the daemon (core/drand_control.go)."""
        d = self._d
        if name == "PingPong":
            return {}
        if name == "InitDKG":
            info = req.get("info") or {}
            timeout = float(info.get("timeout") or 60)
            if info.get("leader"):
                group = await d.init_dkg_leader(
                    expected_n=int(info.get("nodes") or 0),
                    threshold=int(info.get("threshold") or 0),
                    period=int(req.get("beacon_period") or 30),
                    secret=info.get("secret") or b"",
                    timeout=timeout,
                    catchup_period=int(req.get("catchup_period") or 0),
                    force=bool(info.get("force")))
            else:
                group = await d.init_dkg_follower(
                    leader=info.get("leader_address") or "",
                    secret=info.get("secret") or b"", timeout=timeout)
            return group.to_proto_dict()
        if name == "InitReshare":
            info = req.get("info") or {}
            timeout = float(info.get("timeout") or 60)
            if info.get("leader"):
                group = await d.init_reshare_leader(
                    expected_n=int(info.get("nodes") or 0),
                    threshold=int(info.get("threshold") or 0),
                    secret=info.get("secret") or b"", timeout=timeout,
                    force=bool(info.get("force")))
            else:
                old_group = None
                loc = req.get("old") or {}
                if loc.get("path"):
                    from ..key.group import Group

                    with open(loc["path"], "rb") as f:
                        old_group = Group.from_dict(tomllib.load(f))
                group = await d.init_reshare_follower(
                    leader=info.get("leader_address") or "",
                    secret=info.get("secret") or b"",
                    old_group=old_group, timeout=timeout)
            return group.to_proto_dict()
        if name == "Share":
            if d.share is None:
                raise RuntimeError("no share loaded")
            ps = d.share.pri_share
            return {"index": ps.index,
                    "share": (ps.value % _FR_R).to_bytes(32, "big")}
        if name == "PublicKey":
            return {"pub_key": d.priv.public.key.to_bytes()}
        if name == "PrivateKey":
            return {"pri_key": (d.priv.key % _FR_R).to_bytes(32, "big")}
        if name == "ChainInfo":
            info = await d.chain_info("control")
            return {"public_key": info.public_key.to_bytes(),
                    "period": info.period,
                    "genesis_time": info.genesis_time,
                    "hash": info.hash(),
                    "group_hash": info.group_hash}
        if name == "GroupFile":
            if d.group is None:
                raise RuntimeError("no group loaded")
            return d.group.to_proto_dict()
        if name == "Shutdown":
            self._d.stop()
            self._shutdown_event.set()
            return {}
        raise RuntimeError(f"unhandled proto method {name}")

    async def _start_follow_chain(self, request: bytes, context):
        """control.proto:37 StartFollowChain — protobuf server-streaming
        follow with FollowProgress frames (core/drand_control.go:783)."""
        try:
            req = pw.decode(pw.START_FOLLOW_REQUEST, request)
        except pw.WireError as e:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
            return
        peers = list(req.get("nodes") or [])
        up_to = int(req.get("up_to") or 0)
        # the operator-supplied chain hash is the follow's sole trust
        # anchor (core/drand_control.go:822-829): decode it up front and
        # make follow_chain validate every peer's chain info against it
        try:
            info_hash = bytes.fromhex(req.get("info_hash") or "")
        except ValueError:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                                "info_hash: not valid hex")
            return

        def last_round() -> int:
            # progress of the FOLLOW sync itself (daemon._follow_store),
            # not the daemon's own beacon — the endpoint's use case is a
            # non-member node with no beacon at all
            store = getattr(self._d, "_follow_store", None)
            if store is None:
                return 0
            try:
                return store.last().round
            except Exception:  # noqa: BLE001 — store may still be empty
                return 0

        self._d._follow_store = None  # don't report a previous follow
        task = asyncio.ensure_future(
            self._d.follow_chain(peers, up_to, info_hash=info_hash or None))
        try:
            while not task.done():
                yield pw.encode(pw.FOLLOW_PROGRESS,
                                {"current": last_round(), "target": up_to})
                await asyncio.wait({task}, timeout=1.0)
            try:
                ok = task.result()
            except Exception as e:  # noqa: BLE001 — surface as status
                await context.abort(grpc.StatusCode.FAILED_PRECONDITION,
                                    f"{type(e).__name__}: {e}")
                return
            if not ok:
                await context.abort(grpc.StatusCode.FAILED_PRECONDITION,
                                    "follow failed on all peers")
                return
            yield pw.encode(pw.FOLLOW_PROGRESS,
                            {"current": last_round(), "target": up_to})
        finally:
            if not task.done():
                task.cancel()

    # ------------------------------------------------------------ methods
    async def _ping(self, req: dict) -> dict:
        return {"pong": True}

    async def _initdkg(self, req: dict) -> dict:
        if req.get("leader"):
            group = await self._d.init_dkg_leader(
                expected_n=int(req["nodes"]), threshold=int(req["threshold"]),
                period=int(req["period"]),
                secret=bytes.fromhex(req["secret"]),
                timeout=float(req.get("timeout", 60.0)),
                catchup_period=int(req.get("catchup_period", 0)),
                force=bool(req.get("force", False)))
        else:
            group = await self._d.init_dkg_follower(
                leader=req["connect"], secret=bytes.fromhex(req["secret"]),
                timeout=float(req.get("timeout", 60.0)))
        return {"group": group.to_dict()}

    async def _initreshare(self, req: dict) -> dict:
        if req.get("leader"):
            group = await self._d.init_reshare_leader(
                expected_n=int(req["nodes"]), threshold=int(req["threshold"]),
                secret=bytes.fromhex(req["secret"]),
                timeout=float(req.get("timeout", 60.0)),
                force=bool(req.get("force", False)))
        else:
            old_group = None
            if req.get("old_group"):
                from ..key.group import Group

                old_group = Group.from_dict(req["old_group"])
            group = await self._d.init_reshare_follower(
                leader=req["connect"], secret=bytes.fromhex(req["secret"]),
                old_group=old_group, leaving=bool(req.get("leaving", False)),
                timeout=float(req.get("timeout", 60.0)))
        return {"group": group.to_dict()}

    async def _publickey(self, req: dict) -> dict:
        return {"public_key": self._d.priv.public.key.to_bytes().hex()}

    async def _groupfile(self, req: dict) -> dict:
        if self._d.group is None:
            raise RuntimeError("no group loaded")
        return {"group": self._d.group.to_dict()}

    async def _chaininfo(self, req: dict) -> dict:
        info = await self._d.chain_info("control")
        return json.loads(info.to_json())

    async def _status(self, req: dict) -> dict:
        last = 0
        if self._d.beacon is not None:
            try:
                last = self._d.beacon.chain.last().round
            except Exception:  # noqa: BLE001
                last = 0
        return {
            "address": self._d.priv.public.addr,
            "has_group": self._d.group is not None,
            "has_share": self._d.share is not None,
            "beacon_running": self._d.beacon is not None,
            "last_round": last,
        }

    async def _shutdown(self, req: dict) -> dict:
        self._d.stop()
        self._shutdown_event.set()
        return {"ok": True}

    async def _follow(self, req: dict) -> dict:
        """StartFollowChain analogue (core/drand_control.go:783): sync the
        chain from peers without participating. ``info_hash`` (hex) pins
        the chain — peers serving mismatching chain info are rejected."""
        up_to = int(req.get("up_to", 0))
        peers = req.get("peers", [])
        try:
            info_hash = bytes.fromhex(req.get("info_hash") or "")
        except ValueError as e:
            # same contract as the protobuf StartFollowChain endpoint
            raise ValueError("info_hash: not valid hex") from e
        ok = await self._d.follow_chain(peers, up_to,
                                        info_hash=info_hash or None)
        return {"ok": ok, "last": (await self._status({}))["last_round"]}


class ControlClient:
    """CLI side (net/control.go:48)."""

    def __init__(self, port: int, host: str = "127.0.0.1"):
        self._target = f"{host}:{port}"
        self._channel: grpc.aio.Channel | None = None

    async def _call(self, method: str, req: dict, timeout: float = 120.0) -> dict:
        from ..utils.retry import RetryPolicy, retry

        if self._channel is None:
            self._channel = grpc.aio.insecure_channel(self._target)
        fn = self._channel.unary_unary(f"/{SERVICE}/{method}")
        try:
            # control dials retry UNAVAILABLE only (ISSUE 12): the CLI
            # racing a daemon that is still binding its control port is
            # the classic flake; an ANSWERED error must surface verbatim
            raw = await retry(
                lambda: fn(json.dumps(req).encode(), timeout=timeout),
                op="control",
                policy=RetryPolicy(attempts=3, base_s=0.2, cap_s=1.0),
                retry_on=(grpc.aio.AioRpcError,),
                giveup=lambda e: e.code() != grpc.StatusCode.UNAVAILABLE)
        except grpc.aio.AioRpcError as e:
            raise RuntimeError(
                f"control {method}: {e.code().name} {e.details()}") from e
        return json.loads(raw)

    async def close(self) -> None:
        if self._channel is not None:
            await self._channel.close()

    async def ping(self) -> bool:
        return (await self._call("Ping", {}, timeout=5.0)).get("pong", False)

    async def init_dkg_leader(self, nodes: int, threshold: int, period: int,
                              secret: bytes, timeout: float = 60.0,
                              catchup_period: int = 0) -> dict:
        return await self._call("InitDKG", {
            "leader": True, "nodes": nodes, "threshold": threshold,
            "period": period, "secret": secret.hex(), "timeout": timeout,
            "catchup_period": catchup_period}, timeout=timeout + 120)

    async def init_dkg_follower(self, connect: str, secret: bytes,
                                timeout: float = 60.0) -> dict:
        return await self._call("InitDKG", {
            "leader": False, "connect": connect, "secret": secret.hex(),
            "timeout": timeout}, timeout=timeout + 120)

    async def init_reshare_leader(self, nodes: int, threshold: int,
                                  secret: bytes, timeout: float = 60.0) -> dict:
        return await self._call("InitReshare", {
            "leader": True, "nodes": nodes, "threshold": threshold,
            "secret": secret.hex(), "timeout": timeout}, timeout=timeout + 120)

    async def init_reshare_follower(self, connect: str, secret: bytes,
                                    old_group: dict | None = None,
                                    leaving: bool = False,
                                    timeout: float = 60.0) -> dict:
        return await self._call("InitReshare", {
            "leader": False, "connect": connect, "secret": secret.hex(),
            "old_group": old_group, "leaving": leaving,
            "timeout": timeout}, timeout=timeout + 120)

    async def public_key(self) -> str:
        return (await self._call("PublicKey", {}))["public_key"]

    async def group_file(self) -> dict:
        return (await self._call("GroupFile", {}))["group"]

    async def chain_info(self) -> dict:
        return await self._call("ChainInfo", {})

    async def status(self) -> dict:
        return await self._call("Status", {})

    async def shutdown(self) -> dict:
        return await self._call("Shutdown", {})

    async def follow(self, peers: list[str], up_to: int = 0,
                     info_hash: str = "") -> dict:
        """``info_hash``: hex chain hash pinning the followed chain —
        the daemon rejects peers whose chain info hashes differently."""
        return await self._call("Follow", {"peers": peers, "up_to": up_to,
                                           "info_hash": info_hash},
                                timeout=3600)
