"""TLS for the node<->node plane.

Reference: net/listener.go:108-146 (hardened TLS listener) and net/certs.go
(CertManager :14 — a pool of manually-trusted certificates for self-signed
deployments, the common drand setup). Certificates are generated with the
`cryptography` package; the gRPC layer consumes PEM bytes.
"""

from __future__ import annotations

import datetime
import ipaddress
import os

from ..utils import fs


def generate_self_signed(address: str, folder: str,
                         days: int = 3650) -> tuple[str, str]:
    """Create key.pem + cert.pem for `address` (host:port) under `folder`
    with the right SAN (IP or DNS). Returns (cert_path, key_path)."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import NameOID

    host = address.rsplit(":", 1)[0]
    key = ec.generate_private_key(ec.SECP256R1())
    # CN = the FULL address, not just the host: hostname validation uses
    # the SAN, but the root store looks roots up BY SUBJECT — multiple
    # self-signed certs sharing a subject (every node on 127.0.0.1) make
    # BoringSSL try the wrong root and fail the handshake in pools of 3+
    # (found via the multi-node TLS integration run)
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, address)])
    try:
        san = x509.SubjectAlternativeName(
            [x509.IPAddress(ipaddress.ip_address(host))])
    except ValueError:
        san = x509.SubjectAlternativeName([x509.DNSName(host)])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=days))
        .add_extension(san, critical=False)
        .sign(key, hashes.SHA256())
    )
    fs.create_secure_folder(folder)
    key_path = os.path.join(folder, "key.pem")
    cert_path = os.path.join(folder, "cert.pem")
    fs.write_secure_file(key_path, key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption()))
    with open(cert_path, "wb") as f:
        f.write(cert.public_bytes(serialization.Encoding.PEM))
    return cert_path, key_path


class CertManager:
    """Pool of manually-trusted peer certificates (net/certs.go:14):
    self-signed deployments exchange cert files out of band and `add` them
    here; the pool becomes the client-side root set."""

    def __init__(self):
        self._pems: list[bytes] = []

    def add(self, cert_path: str) -> None:
        with open(cert_path, "rb") as f:
            self._pems.append(f.read())

    def add_pem(self, pem: bytes) -> None:
        self._pems.append(pem)

    def pool_pem(self) -> bytes | None:
        if not self._pems:
            return None
        return b"".join(self._pems)


def server_credentials(cert_path: str, key_path: str):
    import grpc

    with open(key_path, "rb") as f:
        key = f.read()
    with open(cert_path, "rb") as f:
        cert = f.read()
    return grpc.ssl_server_credentials([(key, cert)])


def channel_credentials(certs: CertManager | None):
    """Client side: trust the managed pool (None = system roots)."""
    import grpc

    return grpc.ssl_channel_credentials(
        root_certificates=certs.pool_pem() if certs else None)
