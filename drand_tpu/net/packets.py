"""Wire packet dataclasses — the protobuf message analogues.

Reference: protobuf/drand/protocol.proto (PartialBeaconPacket :63-75,
SyncRequest/BeaconPacket :37-61). The gRPC transport serializes these;
the in-memory test transport passes them directly.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..chain.beacon import Beacon


@dataclass(frozen=True)
class PartialBeaconPacket:
    round: int
    previous_sig: bytes
    partial_sig: bytes      # 2B index || 96B G2 sig over Message(round, prev)
    partial_sig_v2: bytes   # 2B index || 96B G2 sig over MessageV2(round)


@dataclass(frozen=True)
class SyncRequest:
    from_round: int


def beacon_to_packet(b: Beacon) -> dict:
    return {
        "round": b.round,
        "previous_sig": b.previous_sig,
        "signature": b.signature,
        "signature_v2": b.signature_v2,
    }


def packet_to_beacon(d: dict) -> Beacon:
    return Beacon(
        round=d["round"],
        previous_sig=d["previous_sig"],
        signature=d["signature"],
        signature_v2=d.get("signature_v2", b""),
    )
