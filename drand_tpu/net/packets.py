"""Wire packet dataclasses — the protobuf message analogues.

Reference: protobuf/drand/protocol.proto (PartialBeaconPacket :63-75,
SyncRequest/BeaconPacket :37-61). The gRPC transport serializes these;
the in-memory test transport passes them directly.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import TYPE_CHECKING

from ..chain.beacon import Beacon

if TYPE_CHECKING:
    from ..key.keys import Identity


@dataclass(frozen=True)
class PartialBeaconPacket:
    round: int
    previous_sig: bytes
    partial_sig: bytes      # 2B index || 96B G2 sig over Message(round, prev)
    partial_sig_v2: bytes   # 2B index || 96B G2 sig over MessageV2(round)
    # checkpoint piggyback (client/checkpoint.py): when round-1 is a
    # checkpoint-interval round, a partial over
    # CheckpointMessage(chain_hash, round-1, previous_sig) — previous_sig
    # IS round-1's recovered signature, so the broadcast that announces
    # round R also threshold-attests the head it chains from. Empty
    # otherwise (wire-compatible with pre-checkpoint peers).
    partial_ckpt: bytes = b""


@dataclass(frozen=True)
class SyncRequest:
    from_round: int


@dataclass(frozen=True)
class PartialRequest:
    """Quorum-repair PULL request (ISSUE 12): give me the partials you
    collected for ``round`` that I do not already hold. ``have`` is the
    requester's share-index set — the server subtracts it so a repair
    round-trip never re-ships what the requester has."""

    round: int
    previous_sig: bytes
    have: tuple[int, ...] = ()


@dataclass(frozen=True)
class PartialBatch:
    """Quorum-repair PULL response: the served partial packets."""

    packets: tuple[PartialBeaconPacket, ...] = ()


@dataclass(frozen=True)
class SignalDKGPacket:
    """SignalDKGParticipant payload (protocol.proto PeerIdentity + secret):
    a participant announces itself to the setup leader."""

    identity: "Identity"
    secret: bytes
    previous_group_hash: bytes = b""  # reshare: pins the old group epoch


@dataclass(frozen=True)
class GroupPacket:
    """PushDKGInfo payload (common.proto GroupPacket + leader signature):
    the leader-signed group file plus the session secret."""

    group: dict           # Group.to_dict()
    signature: bytes      # leader schnorr over the group hash
    secret: bytes
    dkg_timeout: float = 10.0


def beacon_to_packet(b: Beacon) -> dict:
    return {
        "round": b.round,
        "previous_sig": b.previous_sig,
        "signature": b.signature,
        "signature_v2": b.signature_v2,
    }


def packet_to_beacon(d: dict) -> Beacon:
    return Beacon(
        round=d["round"],
        previous_sig=d["previous_sig"],
        signature=d["signature"],
        signature_v2=d.get("signature_v2", b""),
    )
