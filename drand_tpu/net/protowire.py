"""proto3 wire codecs for the reference drand gRPC surface.

The rest of the transport speaks this framework's own deterministic JSON
envelope (net/wire.py); THIS module implements the reference's protobuf
byte layouts so ecosystem drand clients can fetch, stream and sync from
a drand-tpu node — and reference NODES can talk to us as a peer — over
the standard service/method names. Field numbers and types are
transcribed from the reference wire spec (the protocol contract, not
code):

- PublicRandRequest/Response, PrivateRand*, ChainInfoPacket, Home*:
  /root/reference/protobuf/drand/api.proto:36-80,
  /root/reference/protobuf/drand/common.proto:44-60
- SyncRequest / BeaconPacket / PartialBeaconPacket / SignalDKGPacket /
  DKGInfoPacket / DKGPacket:
  /root/reference/protobuf/drand/protocol.proto:16-92
- Identity / Node / GroupPacket / Empty:
  /root/reference/protobuf/drand/common.proto:10-43
- DealBundle / ResponseBundle / JustificationBundle (+ inner Deal,
  Response, Justification; oneof wrapper Packet):
  /root/reference/protobuf/crypto/dkg/dkg.proto:14-93

Hand-rolled minimal proto3: no generated code, no protobuf runtime
dependency. Field kinds: "u64"/"i64"/"u32" (plain varint), "bool",
"bytes", "str", nested messages ``("msg", SPEC)`` and repeated fields
``("rep", inner_kind)``. proto3 semantics honored: default-valued
scalar fields are omitted on encode, unknown fields are skipped on
decode, last value wins for non-repeated occurrences, repeated fields
accumulate in order. oneof groups (dkg.Packet) are plain optional
message fields — at most one is expected set; ``oneof_of`` returns the
populated arm.
"""

from __future__ import annotations

__all__ = [
    "encode", "decode", "WireError", "oneof_of",
    "PUBLIC_RAND_REQUEST", "PUBLIC_RAND_RESPONSE",
    "PRIVATE_RAND_REQUEST", "PRIVATE_RAND_RESPONSE",
    "CHAIN_INFO_REQUEST", "CHAIN_INFO_PACKET",
    "SYNC_REQUEST", "BEACON_PACKET", "HOME_REQUEST", "HOME_RESPONSE",
    "EMPTY", "IDENTITY", "IDENTITY_REQUEST", "NODE", "GROUP_PACKET",
    "PARTIAL_BEACON_PACKET", "SIGNAL_DKG_PACKET", "DKG_INFO_PACKET",
    "DKG_PACKET", "DKG_BUNDLE", "DEAL", "DEAL_BUNDLE", "RESPONSE",
    "RESPONSE_BUNDLE", "JUSTIFICATION", "JUSTIFICATION_BUNDLE",
    "SETUP_INFO_PACKET", "ENTROPY_INFO", "INIT_DKG_PACKET", "GROUP_INFO",
    "INIT_RESHARE_PACKET", "SHARE_REQUEST", "SHARE_RESPONSE",
    "PUBLIC_KEY_REQUEST", "PUBLIC_KEY_RESPONSE", "PRIVATE_KEY_REQUEST",
    "PRIVATE_KEY_RESPONSE", "GROUP_REQUEST", "SHUTDOWN_REQUEST",
    "SHUTDOWN_RESPONSE", "START_FOLLOW_REQUEST", "FOLLOW_PROGRESS",
]


class WireError(ValueError):
    pass


# ---------------------------------------------------------------------------
# varint + tag primitives
# ---------------------------------------------------------------------------

def _put_varint(out: bytearray, v: int) -> None:
    if v < 0:  # proto3 int64: negative values use 10-byte two's complement
        v += 1 << 64
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _get_varint(data: bytes, i: int) -> tuple[int, int]:
    shift = 0
    val = 0
    while True:
        if i >= len(data):
            raise WireError("truncated varint")
        b = data[i]
        i += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            # standard protobuf masks varints to 64 bits (a 10-byte
            # encoding can carry up to ~2^70 otherwise)
            return val & ((1 << 64) - 1), i
        shift += 7
        if shift > 63:
            raise WireError("varint overflow")


_VARINT, _LEN = 0, 2


# ---------------------------------------------------------------------------
# generic message codec: spec = {field_number: (name, kind)}
# kinds: "u64" | "i64" (both plain varint on the wire), "bytes", "str"
# ---------------------------------------------------------------------------

_INT_KINDS = ("u64", "i64", "u32")


def _encode_one(out: bytearray, num: int, kind, v,
                keep_default: bool = False) -> None:
    """``keep_default``: emit the field even when default-valued —
    required inside repeated fields, where omitting an element would
    silently shift every later element's position."""
    if kind in _INT_KINDS:
        v = int(v or 0)
        if v == 0 and not keep_default:
            return
        _put_varint(out, (num << 3) | _VARINT)
        _put_varint(out, v)
    elif kind == "bool":
        if not v and not keep_default:
            return
        _put_varint(out, (num << 3) | _VARINT)
        _put_varint(out, 1 if v else 0)
    elif kind in ("bytes", "str"):
        if kind == "str":
            v = (v or "").encode()
        v = bytes(v or b"")
        if not v and not keep_default:
            return
        _put_varint(out, (num << 3) | _LEN)
        _put_varint(out, len(v))
        out += v
    elif isinstance(kind, tuple) and kind[0] == "msg":
        if v is None:
            if keep_default:
                # a None element inside a repeated field would silently
                # shift every later element's position
                raise WireError(
                    "None element in repeated message field")
            return
        body = encode(kind[1], v)
        _put_varint(out, (num << 3) | _LEN)
        _put_varint(out, len(body))
        out += body
    elif isinstance(kind, tuple) and kind[0] == "rep":
        for item in (v or ()):
            _encode_one(out, num, kind[1], item, keep_default=True)
    else:  # pragma: no cover — spec authoring error
        raise WireError(f"unknown field kind {kind!r}")


def encode(spec: dict, values: dict) -> bytes:
    out = bytearray()
    for num in sorted(spec):
        name, kind = spec[num]
        _encode_one(out, num, kind, values.get(name))
    return bytes(out)


def _default_for(kind):
    if kind in _INT_KINDS:
        return 0
    if kind == "bool":
        return False
    if kind == "str":
        return ""
    if kind == "bytes":
        return b""
    if isinstance(kind, tuple) and kind[0] == "msg":
        return None
    return []  # repeated


def decode(spec: dict, data: bytes) -> dict:
    out = {name: _default_for(kind) for name, kind in spec.values()}
    i = 0
    while i < len(data):
        tag, i = _get_varint(data, i)
        num, wt = tag >> 3, tag & 7
        if wt == _VARINT:
            v, i = _get_varint(data, i)
        elif wt == _LEN:
            ln, i = _get_varint(data, i)
            if i + ln > len(data):
                raise WireError("truncated length-delimited field")
            v = data[i:i + ln]
            i += ln
        elif wt == 1:  # 64-bit
            if i + 8 > len(data):
                raise WireError("truncated fixed64 field")
            v, i = data[i:i + 8], i + 8
        elif wt == 5:  # 32-bit
            if i + 4 > len(data):
                raise WireError("truncated fixed32 field")
            v, i = data[i:i + 4], i + 4
        else:
            raise WireError(f"unsupported wire type {wt}")
        field = spec.get(num)
        if field is None:
            continue  # unknown field: skip (proto3 forward compat)
        name, kind = field
        repeated = isinstance(kind, tuple) and kind[0] == "rep"
        inner = kind[1] if repeated else kind
        if inner in _INT_KINDS or inner == "bool":
            if repeated and wt == _LEN:
                # packed repeated scalars (proto3's default encoding for
                # repeated varints): consecutive varints in one payload
                j, vals = 0, []
                while j < len(v):
                    pv, j = _get_varint(bytes(v), j)
                    if inner == "i64" and pv >= 1 << 63:
                        pv -= 1 << 64
                    vals.append(bool(pv) if inner == "bool" else pv)
                out[name].extend(vals)
                continue
            if wt != _VARINT:
                raise WireError(f"field {name}: wrong wire type {wt}")
            if inner == "i64" and v >= 1 << 63:
                v -= 1 << 64
            val = bool(v) if inner == "bool" else v
        else:
            # everything length-delimited: a fixed64/fixed32 body must
            # not silently become the field value (ADVICE r3)
            if wt != _LEN:
                raise WireError(f"field {name}: wrong wire type {wt}")
            if inner == "str":
                try:
                    val = v.decode()
                except UnicodeDecodeError as e:
                    raise WireError(f"field {name}: invalid UTF-8") from e
            elif inner == "bytes":
                val = bytes(v)
            elif isinstance(inner, tuple) and inner[0] == "msg":
                val = decode(inner[1], bytes(v))
            else:  # pragma: no cover — spec authoring error
                raise WireError(f"unknown field kind {inner!r}")
        if repeated:
            out[name].append(val)
        else:
            # re-insert so dict order reflects LAST wire occurrence of
            # each scalar field (oneof_of's last-wins relies on this;
            # the dict is pre-populated with defaults in spec order)
            out.pop(name, None)
            out[name] = val
    return out


def oneof_of(decoded: dict, arms: tuple[str, ...]):
    """(arm_name, value) for the populated oneof arm, or (None, None).

    proto3 oneof semantics is last-value-wins, so when several arms are
    populated (a non-canonical but spec-legal encoding) the arm set
    LATEST in wire order wins. ``decode`` re-inserts a scalar field's
    dict key on every wire occurrence, so insertion order among
    populated arms IS last-wire-occurrence order (ADVICE r4, replacing
    the strict rejection)."""
    hit = [(a, decoded[a]) for a in decoded
           if a in arms and decoded.get(a) is not None]
    return hit[-1] if hit else (None, None)


# ---------------------------------------------------------------------------
# message specs (field numbers from the reference .proto files)
# ---------------------------------------------------------------------------

PUBLIC_RAND_REQUEST = {1: ("round", "u64")}
PUBLIC_RAND_RESPONSE = {
    1: ("round", "u64"),
    2: ("signature", "bytes"),
    3: ("previous_signature", "bytes"),
    4: ("randomness", "bytes"),
    5: ("signature_v2", "bytes"),
}
PRIVATE_RAND_REQUEST = {1: ("request", "bytes")}
PRIVATE_RAND_RESPONSE = {1: ("response", "bytes")}
CHAIN_INFO_REQUEST: dict = {}
CHAIN_INFO_PACKET = {
    1: ("public_key", "bytes"),
    2: ("period", "u64"),        # uint32 on the wire: same varint encoding
    3: ("genesis_time", "i64"),
    4: ("hash", "bytes"),
    5: ("group_hash", "bytes"),  # `groupHash` in the .proto
}
SYNC_REQUEST = {1: ("from_round", "u64")}
BEACON_PACKET = {
    1: ("previous_sig", "bytes"),
    2: ("round", "u64"),
    3: ("signature", "bytes"),
}
HOME_REQUEST: dict = {}
HOME_RESPONSE = {1: ("status", "str")}

# --- protocol plane (protocol.proto:16-92, common.proto:10-43) -------------

EMPTY: dict = {}
IDENTITY_REQUEST: dict = {}
IDENTITY = {
    1: ("address", "str"),
    2: ("key", "bytes"),
    3: ("tls", "bool"),
    4: ("signature", "bytes"),
}
NODE = {
    1: ("public", ("msg", IDENTITY)),
    2: ("index", "u32"),
}
GROUP_PACKET = {
    1: ("nodes", ("rep", ("msg", NODE))),
    2: ("threshold", "u32"),
    3: ("period", "u32"),            # seconds
    4: ("genesis_time", "u64"),
    5: ("transition_time", "u64"),
    6: ("genesis_seed", "bytes"),
    7: ("dist_key", ("rep", "bytes")),
    8: ("catchup_period", "u32"),    # seconds
}
PARTIAL_BEACON_PACKET = {
    1: ("round", "u64"),
    2: ("previous_sig", "bytes"),
    3: ("partial_sig", "bytes"),
    4: ("partial_sig_v2", "bytes"),
    # checkpoint piggyback partial (net/packets.py partial_ckpt) —
    # proto3-optional: absent on pre-checkpoint peers, decodes to b""
    5: ("partial_ckpt", "bytes"),
}
SIGNAL_DKG_PACKET = {
    1: ("node", ("msg", IDENTITY)),
    2: ("secret_proof", "bytes"),
    3: ("previous_group_hash", "bytes"),
}
DKG_INFO_PACKET = {
    1: ("new_group", ("msg", GROUP_PACKET)),
    2: ("secret_proof", "bytes"),
    3: ("dkg_timeout", "u32"),
    4: ("signature", "bytes"),
}

# --- DKG broadcast bundles (dkg.proto:14-93) -------------------------------

DEAL = {
    1: ("share_index", "u32"),
    2: ("encrypted_share", "bytes"),
}
DEAL_BUNDLE = {
    1: ("dealer_index", "u32"),
    2: ("commits", ("rep", "bytes")),
    3: ("deals", ("rep", ("msg", DEAL))),
    4: ("session_id", "bytes"),
    5: ("signature", "bytes"),
}
RESPONSE = {
    1: ("dealer_index", "u32"),
    2: ("status", "bool"),
}
RESPONSE_BUNDLE = {
    1: ("share_index", "u32"),
    2: ("responses", ("rep", ("msg", RESPONSE))),
    3: ("session_id", "bytes"),
    4: ("signature", "bytes"),
}
JUSTIFICATION = {
    1: ("share_index", "u32"),
    2: ("share", "bytes"),
}
JUSTIFICATION_BUNDLE = {
    1: ("dealer_index", "u32"),
    2: ("justifications", ("rep", ("msg", JUSTIFICATION))),
    3: ("session_id", "bytes"),
    4: ("signature", "bytes"),
}
# dkg.Packet: oneof {deal, response, justification} — three optional
# message fields; oneof_of() recovers the populated arm
DKG_BUNDLE = {
    1: ("deal", ("msg", DEAL_BUNDLE)),
    2: ("response", ("msg", RESPONSE_BUNDLE)),
    3: ("justification", ("msg", JUSTIFICATION_BUNDLE)),
}
DKG_BUNDLE_ARMS = ("deal", "response", "justification")
# protocol.proto DKGPacket { dkg.Packet dkg = 1; }
DKG_PACKET = {1: ("dkg", ("msg", DKG_BUNDLE))}

# --- control plane (control.proto:14-199) ----------------------------------

SETUP_INFO_PACKET = {
    1: ("leader", "bool"),
    2: ("leader_address", "str"),
    3: ("leader_tls", "bool"),
    4: ("nodes", "u32"),
    5: ("threshold", "u32"),
    6: ("timeout", "u32"),          # seconds per DKG phase
    7: ("beacon_offset", "u32"),
    8: ("dkg_offset", "u32"),
    9: ("secret", "bytes"),
    10: ("force", "bool"),
}
ENTROPY_INFO = {1: ("script", "str"), 10: ("user_only", "bool")}
INIT_DKG_PACKET = {
    1: ("info", ("msg", SETUP_INFO_PACKET)),
    2: ("entropy", ("msg", ENTROPY_INFO)),
    3: ("beacon_period", "u32"),
    4: ("catchup_period", "u32"),
}
GROUP_INFO = {1: ("path", "str"), 2: ("url", "str")}  # oneof location
INIT_RESHARE_PACKET = {
    1: ("old", ("msg", GROUP_INFO)),
    2: ("info", ("msg", SETUP_INFO_PACKET)),
    3: ("catchup_period_changed", "bool"),
    4: ("catchup_period", "u32"),
}
SHARE_REQUEST: dict = {}
SHARE_RESPONSE = {2: ("index", "u32"), 3: ("share", "bytes")}
PUBLIC_KEY_REQUEST: dict = {}
PUBLIC_KEY_RESPONSE = {2: ("pub_key", "bytes")}
PRIVATE_KEY_REQUEST: dict = {}
PRIVATE_KEY_RESPONSE = {2: ("pri_key", "bytes")}
GROUP_REQUEST: dict = {}
SHUTDOWN_REQUEST: dict = {}
SHUTDOWN_RESPONSE: dict = {}
START_FOLLOW_REQUEST = {
    1: ("info_hash", "str"),        # hex
    2: ("nodes", ("rep", "str")),
    3: ("is_tls", "bool"),
    4: ("up_to", "u64"),
}
FOLLOW_PROGRESS = {1: ("current", "u64"), 2: ("target", "u64")}
