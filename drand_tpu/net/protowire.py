"""proto3 wire codecs for the reference drand gRPC surface.

The rest of the transport speaks this framework's own deterministic JSON
envelope (net/wire.py); THIS module implements the reference's protobuf
byte layouts so ecosystem drand clients can fetch, stream and sync from
a drand-tpu node over the standard service/method names. Field numbers
and types are transcribed from the reference wire spec (the protocol
contract, not code):

- PublicRandRequest/Response, PrivateRand*, ChainInfoPacket, Home*:
  /root/reference/protobuf/drand/api.proto:36-80,
  /root/reference/protobuf/drand/common.proto:44-60
- SyncRequest / BeaconPacket:
  /root/reference/protobuf/drand/protocol.proto:84-92

Hand-rolled minimal proto3 (varint + length-delimited only — every field
in this surface is one of the two): no generated code, no protobuf
runtime dependency. proto3 semantics honored: default-valued fields are
omitted on encode, unknown fields are skipped on decode, last value wins
for repeated scalar occurrences.
"""

from __future__ import annotations

__all__ = [
    "encode", "decode", "WireError",
    "PUBLIC_RAND_REQUEST", "PUBLIC_RAND_RESPONSE",
    "PRIVATE_RAND_REQUEST", "PRIVATE_RAND_RESPONSE",
    "CHAIN_INFO_REQUEST", "CHAIN_INFO_PACKET",
    "SYNC_REQUEST", "BEACON_PACKET", "HOME_REQUEST", "HOME_RESPONSE",
]


class WireError(ValueError):
    pass


# ---------------------------------------------------------------------------
# varint + tag primitives
# ---------------------------------------------------------------------------

def _put_varint(out: bytearray, v: int) -> None:
    if v < 0:  # proto3 int64: negative values use 10-byte two's complement
        v += 1 << 64
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _get_varint(data: bytes, i: int) -> tuple[int, int]:
    shift = 0
    val = 0
    while True:
        if i >= len(data):
            raise WireError("truncated varint")
        b = data[i]
        i += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            # standard protobuf masks varints to 64 bits (a 10-byte
            # encoding can carry up to ~2^70 otherwise)
            return val & ((1 << 64) - 1), i
        shift += 7
        if shift > 63:
            raise WireError("varint overflow")


_VARINT, _LEN = 0, 2


# ---------------------------------------------------------------------------
# generic message codec: spec = {field_number: (name, kind)}
# kinds: "u64" | "i64" (both plain varint on the wire), "bytes", "str"
# ---------------------------------------------------------------------------

def encode(spec: dict, values: dict) -> bytes:
    out = bytearray()
    for num in sorted(spec):
        name, kind = spec[num]
        v = values.get(name)
        if kind in ("u64", "i64"):
            v = int(v or 0)
            if v == 0:
                continue
            _put_varint(out, (num << 3) | _VARINT)
            _put_varint(out, v)
        else:
            if kind == "str":
                v = (v or "").encode()
            v = bytes(v or b"")
            if not v:
                continue
            _put_varint(out, (num << 3) | _LEN)
            _put_varint(out, len(v))
            out += v
    return bytes(out)


def decode(spec: dict, data: bytes) -> dict:
    out = {name: ("" if kind == "str" else (0 if kind in ("u64", "i64")
                                            else b""))
           for name, kind in spec.values()}
    i = 0
    while i < len(data):
        tag, i = _get_varint(data, i)
        num, wt = tag >> 3, tag & 7
        if wt == _VARINT:
            v, i = _get_varint(data, i)
        elif wt == _LEN:
            ln, i = _get_varint(data, i)
            if i + ln > len(data):
                raise WireError("truncated length-delimited field")
            v = data[i:i + ln]
            i += ln
        elif wt == 1:  # 64-bit
            if i + 8 > len(data):
                raise WireError("truncated fixed64 field")
            v, i = data[i:i + 8], i + 8
        elif wt == 5:  # 32-bit
            if i + 4 > len(data):
                raise WireError("truncated fixed32 field")
            v, i = data[i:i + 4], i + 4
        else:
            raise WireError(f"unsupported wire type {wt}")
        field = spec.get(num)
        if field is None:
            continue  # unknown field: skip (proto3 forward compat)
        name, kind = field
        if kind in ("u64", "i64"):
            if not isinstance(v, int):
                raise WireError(f"field {name}: wrong wire type")
            if kind == "i64" and v >= 1 << 63:
                v -= 1 << 64
            out[name] = v
        else:
            if isinstance(v, int):
                raise WireError(f"field {name}: wrong wire type")
            out[name] = v.decode() if kind == "str" else bytes(v)
    return out


# ---------------------------------------------------------------------------
# message specs (field numbers from the reference .proto files)
# ---------------------------------------------------------------------------

PUBLIC_RAND_REQUEST = {1: ("round", "u64")}
PUBLIC_RAND_RESPONSE = {
    1: ("round", "u64"),
    2: ("signature", "bytes"),
    3: ("previous_signature", "bytes"),
    4: ("randomness", "bytes"),
    5: ("signature_v2", "bytes"),
}
PRIVATE_RAND_REQUEST = {1: ("request", "bytes")}
PRIVATE_RAND_RESPONSE = {1: ("response", "bytes")}
CHAIN_INFO_REQUEST: dict = {}
CHAIN_INFO_PACKET = {
    1: ("public_key", "bytes"),
    2: ("period", "u64"),        # uint32 on the wire: same varint encoding
    3: ("genesis_time", "i64"),
    4: ("hash", "bytes"),
    5: ("group_hash", "bytes"),  # `groupHash` in the .proto
}
SYNC_REQUEST = {1: ("from_round", "u64")}
BEACON_PACKET = {
    1: ("previous_sig", "bytes"),
    2: ("round", "u64"),
    3: ("signature", "bytes"),
}
HOME_REQUEST: dict = {}
HOME_RESPONSE = {1: ("status", "str")}
