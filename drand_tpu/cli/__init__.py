"""drand-tpu operator CLI (see __main__.py; reference cmd/drand-cli/)."""

from .__main__ import main  # noqa: F401
