"""drand-tpu CLI.

Reference: cmd/drand-cli/cli.go:251-430 — generate-keypair, start, stop,
share (DKG/reshare), follow, get, show, util. A running daemon is driven
through its localhost control port (cmd/drand-cli/control.go), exactly like
`drand`.

    python -m drand_tpu.cli generate-keypair --folder F addr:port
    python -m drand_tpu.cli start --folder F [--control PORT] [--public-listen addr:port]
    python -m drand_tpu.cli share --control PORT --leader --nodes N --threshold T --period S --secret-file F
    python -m drand_tpu.cli share --control PORT --connect LEADER --secret-file F [--reshare [--leaving]]
    python -m drand_tpu.cli follow --control PORT --sync-nodes a:p,b:p [--up-to R]
    python -m drand_tpu.cli get public --url http://host:port [--round R]
    python -m drand_tpu.cli get chain-info --url http://host:port
    python -m drand_tpu.cli show {share|group|chain-info|public|status} --control PORT
    python -m drand_tpu.cli util {check|ping|trace|engine} ...
    python -m drand_tpu.cli util trace --url http://host:port [--n K]
    python -m drand_tpu.cli util trace --merge http://a:port http://b:port
    python -m drand_tpu.cli util engine --url http://host:port
    python -m drand_tpu.cli util flight --url http://host:port [--dkg]
    python -m drand_tpu.cli util incidents --url http://host:port [--show ID] [--bundle ID -o FILE]
    python -m drand_tpu.cli util support-bundle --url http://host:port -o FILE
    python -m drand_tpu.cli util remediate --url http://host:port [--n K]
    python -m drand_tpu.cli stop --control PORT
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys


def _folder(args) -> str:
    return args.folder or os.path.join(os.path.expanduser("~"), ".drand-tpu")


def _read_secret(args) -> bytes:
    if args.secret_file:
        with open(args.secret_file, "rb") as f:
            secret = f.read().strip()
    else:
        secret = os.environ.get("DRAND_SHARE_SECRET", "").encode()
    if len(secret) < 16:
        raise SystemExit("setup secret must be at least 16 bytes "
                         "(--secret-file or DRAND_SHARE_SECRET)")
    return secret


# ---------------------------------------------------------------- commands

def cmd_generate_keypair(args) -> None:
    from ..key.keys import new_key_pair
    from ..key.store import FileStore

    store = FileStore(_folder(args))
    if store.has_key_pair() and not args.force:
        raise SystemExit(f"keypair already exists in {store.key_folder} "
                         f"(--force to overwrite)")
    pair = new_key_pair(args.address, tls=args.tls)
    store.save_key_pair(pair)
    print(json.dumps({
        "address": args.address,
        "public_key": pair.public.key.to_bytes().hex(),
        "folder": store.key_folder,
    }, indent=2))


def cmd_start(args) -> None:
    asyncio.run(_run_daemon(args))


async def _run_daemon(args) -> None:
    from ..core.config import Config
    from ..core.daemon import Drand
    from ..key.store import FileStore
    from ..net.control import ControlServer
    from ..net.grpc_transport import GrpcClient, GrpcGateway
    from ..utils.logging import default_logger

    folder = _folder(args)
    ks = FileStore(folder)
    if not ks.has_key_pair():
        raise SystemExit(f"no keypair in {folder}; run generate-keypair first")
    # warm the device-backend probe off the event loop: by the time the
    # first round aggregates, engine() finds a verdict (down tunnel =>
    # permanent host fallback, never a hang — utils/backend.py)
    from ..utils.backend import probe_backend_bg

    probe_backend_bg()
    logger = default_logger("drand", level=args.verbose and "debug" or "info")
    conf = Config(folder=folder, control_port=args.control,
                  db_path=os.path.join(folder, "db", "chain.db"),
                  dkg_timeout=args.dkg_timeout)
    d = Drand.load(ks, conf, None, logger)
    priv_addr = args.private_listen or d.priv.public.addr
    # span resource attrs carry the node address ONLY under
    # DRAND_TPU_OTLP_NODE_ATTRS=1 (privacy rationale in obs/export.py)
    from ..obs import export as obs_export

    obs_export.set_node_address(d.priv.public.addr)
    tls_pair = None
    certs = None
    if args.tls:
        from ..net import tls as tls_mod

        tls_dir = os.path.join(folder, "tls")
        cert_path = os.path.join(tls_dir, "cert.pem")
        if not os.path.isfile(cert_path):
            cert_path, _ = tls_mod.generate_self_signed(
                d.priv.public.addr, tls_dir)
            print(f"generated TLS cert {cert_path} — distribute it to "
                  f"peers' tls/trusted/ folders", flush=True)
        tls_pair = (cert_path, os.path.join(tls_dir, "key.pem"))
        certs = tls_mod.CertManager()
        certs.add(cert_path)  # trust ourselves (loopback partials)
        trusted = os.path.join(tls_dir, "trusted")
        if os.path.isdir(trusted):
            for name in sorted(os.listdir(trusted)):
                if name.endswith(".pem"):
                    certs.add(os.path.join(trusted, name))
    client = GrpcClient(own_addr=d.priv.public.addr, certs=certs)
    d.client = client
    gateway = GrpcGateway(d, priv_addr, logger.named("gw"), tls=tls_pair)
    await gateway.start()
    control = ControlServer(d, args.control, logger.named("ctl"))
    await control.start()
    print(f"drand-tpu daemon up: rpc={priv_addr} control={control.port}",
          flush=True)
    if d.group is not None and d.share is not None:
        d.start_beacon(catchup=True)
        print(f"beacon resumed for group {d.group.hash().hex()[:16]}",
              flush=True)
    http_task = None
    if args.public_listen:
        http_task = asyncio.ensure_future(
            _serve_public(d, args.public_listen, logger, folder,
                          timelock=not args.no_timelock,
                          gateway=gateway))
    await control.wait_shutdown()
    if http_task:
        http_task.cancel()
    await gateway.stop()
    await control.stop()


async def _serve_public(d, listen: str, logger, folder: str,
                        timelock: bool = True, gateway=None) -> None:
    """Start the REST API once the beacon exists (daemon may still be
    pre-DKG at boot)."""
    from ..client.direct import DirectClient
    from ..http_server.server import PublicServer

    while d.beacon is None:
        await asyncio.sleep(0.5)
    host, port = listen.rsplit(":", 1)

    async def peer_metrics(addr: str) -> bytes:
        # only group members may be scraped through us (metrics.go:269)
        group = d.group
        if group is None or not any(n.address() == addr for n in group.nodes):
            raise ValueError(f"{addr} is not a group member")
        return await d.client.peer_metrics(addr)

    client = DirectClient(d.beacon)
    # incident forensics persist next to the chain db by default
    # (ISSUE 15): bundles + the SLI time-series spool survive restarts;
    # DRAND_TPU_INCIDENT_DIR overrides the location
    from ..obs.incident import configure_from_env as _incidents_env

    _incidents_env(os.path.join(folder, "db", "incidents"))
    tl_service = None
    if timelock:
        # the timelock vault rides the public API by default: pending
        # ciphertexts persist next to the chain db and reopen on
        # restart. Backend selection (SQLite default, the segment
        # vault under DRAND_TPU_TIMELOCK_STORE=segment or when the
        # segment dir already exists) lives in open_vault; the two
        # backends use sibling paths so neither shadows the other.
        from ..timelock import TimelockService, open_vault
        from ..timelock.segvault import is_segment_vault

        dbdir = os.path.join(folder, "db")
        os.makedirs(dbdir, exist_ok=True)
        seg = os.path.join(dbdir, "timelock-segments")
        backend = os.environ.get("DRAND_TPU_TIMELOCK_STORE", "").strip()
        db = (seg if backend == "segment" or is_segment_vault(seg)
              else os.path.join(dbdir, "timelock.db"))
        tl_service = TimelockService(open_vault(db), client,
                                     logger=logger.named("timelock"))
        if gateway is not None:
            # non-HTTP clients submit over the public gRPC service:
            # TimelockSubmit/TimelockStatus reuse this service verbatim
            gateway.set_timelock(tl_service)
    server = PublicServer(client, logger=logger.named("http"),
                          peer_metrics_fn=peer_metrics,
                          enable_pprof=os.environ.get("DRAND_TPU_PPROF") == "1",
                          timelock_service=tl_service)
    # auto-remediation (ISSUE 16): the daemon's embedded public server
    # has the same partition-posture knobs as a relay — register the
    # posture action so reachability_drop doesn't refuse with
    # "no action registered" on daemons
    from ..obs.remediate import attach_posture
    from ..obs.remediate import configure_from_env as _remediate_env

    attach_posture(_remediate_env(), server)
    await server.start(host or "0.0.0.0", int(port))
    logger.info("http", "serving", listen=listen, timelock=timelock)
    await asyncio.Event().wait()


def cmd_share(args) -> None:
    async def run():
        from ..net.control import ControlClient

        ctl = ControlClient(args.control)
        secret = _read_secret(args)
        try:
            if args.reshare:
                if args.leader:
                    out = await ctl.init_reshare_leader(
                        args.nodes, args.threshold, secret,
                        timeout=args.timeout)
                else:
                    old_group = None
                    if args.from_group:
                        # the daemon writes TOML group files; accept JSON too
                        from ..utils.toml_compat import tomllib

                        raw = open(args.from_group, "rb").read()
                        try:
                            old_group = tomllib.loads(raw.decode())
                        except (tomllib.TOMLDecodeError, UnicodeDecodeError):
                            try:
                                old_group = json.loads(raw)
                            except json.JSONDecodeError:
                                raise SystemExit(
                                    f"{args.from_group}: neither TOML nor "
                                    f"JSON group file")
                    out = await ctl.init_reshare_follower(
                        args.connect, secret, old_group=old_group,
                        leaving=args.leaving, timeout=args.timeout)
            elif args.leader:
                out = await ctl.init_dkg_leader(
                    args.nodes, args.threshold, args.period, secret,
                    timeout=args.timeout)
            else:
                out = await ctl.init_dkg_follower(args.connect, secret,
                                                  timeout=args.timeout)
            print(json.dumps(out, indent=2))
        finally:
            await ctl.close()

    asyncio.run(run())


def cmd_follow(args) -> None:
    async def run():
        from ..net.control import ControlClient

        ctl = ControlClient(args.control)
        try:
            out = await ctl.follow(args.sync_nodes.split(","), args.up_to,
                                   info_hash=args.chain_hash or "")
            print(json.dumps(out, indent=2))
        finally:
            await ctl.close()

    asyncio.run(run())


def cmd_stop(args) -> None:
    async def run():
        from ..net.control import ControlClient

        ctl = ControlClient(args.control)
        try:
            print(json.dumps(await ctl.shutdown()))
        finally:
            await ctl.close()

    asyncio.run(run())


def cmd_show(args) -> None:
    async def run():
        from ..net.control import ControlClient

        ctl = ControlClient(args.control)
        try:
            if args.what == "chain-info":
                out = await ctl.chain_info()
            elif args.what == "group":
                out = await ctl.group_file()
            elif args.what == "public":
                out = {"public_key": await ctl.public_key()}
            elif args.what == "status":
                out = await ctl.status()
            else:  # share: public part only (private scalar stays on disk)
                g = await ctl.group_file()
                out = {"commits": g.get("public_key", [])}
            print(json.dumps(out, indent=2))
        finally:
            await ctl.close()

    asyncio.run(run())


def cmd_get(args) -> None:
    if args.what == "private":
        # ECIES private randomness round-trip (reference cli.go getPrivateCmd;
        # core/drand_public.go:126): fetch + self-verify the node identity,
        # then run the ephemeral-key exchange
        if not args.connect:
            raise SystemExit("get private requires --connect <node-addr>")

        async def run_private():
            from ..client.private import private_rand
            from ..net.grpc_transport import GrpcClient

            import dataclasses

            client = GrpcClient(own_addr="client")
            try:
                ident = await client.get_identity(args.connect)
                if not ident.valid_signature():
                    raise SystemExit(
                        "node identity failed self-signature check")
                # dial the address the OPERATOR gave (reachable), not the
                # node's self-reported one (may be internal/NATed); the
                # identity's key still targets the ECIES encryption
                dial = dataclasses.replace(ident, addr=args.connect)
                out = await private_rand(client, dial)
                print(json.dumps({"node": ident.addr,
                                  "randomness": out.hex()}))
            finally:
                await client.close()

        asyncio.run(run_private())
        return

    if not args.url:
        raise SystemExit(f"get {args.what} requires --url")

    async def run():
        from ..client.http import HTTPClient

        src = HTTPClient(args.url)
        try:
            if args.what == "chain-info":
                info = await src.info()
                print(info.to_json())
            else:
                from ..client import new_client

                info = await src.info()
                client = new_client([src], chain_info=info)
                r = await client.get(args.round)
                print(json.dumps({
                    "round": r.round,
                    "randomness": r.randomness.hex(),
                    "signature": r.signature.hex(),
                }, indent=2))
        finally:
            await src.close()

    asyncio.run(run())


def _print_trace_timeline(data: dict) -> None:
    """Render /debug/trace/rounds JSON as per-round stage timelines."""
    rounds = data.get("rounds", [])
    if not rounds:
        print("no round traces recorded yet")
        return
    for rec in rounds:
        spans = sorted(rec.get("spans", []), key=lambda s: s["start"])
        head = f"round {rec.get('round')}  trace {rec.get('trace_id')}"
        if rec.get("dropped"):
            head += f"  ({rec['dropped']} spans dropped)"
        print(head)
        t0 = spans[0]["start"] if spans else 0.0
        for sp in spans:
            off_ms = (sp["start"] - t0) * 1000.0
            dur = sp.get("duration_ms") or 0.0
            attrs = " ".join(f"{k}={v}" for k, v in
                             (sp.get("attrs") or {}).items())
            print(f"  +{off_ms:10.3f}ms  {sp['name']:<16}"
                  f" {dur:10.3f}ms  {attrs}")
        print()


async def _fetch_json(base: str, path: str, **params) -> dict:
    import aiohttp

    base = base.rstrip("/")
    async with aiohttp.ClientSession() as s:
        async with s.get(f"{base}{path}", params=params or None) as r:
            if r.status != 200:
                raise SystemExit(f"{base}{path} -> HTTP {r.status}")
            return await r.json()


def _print_merged_timeline(merged: list[dict]) -> None:
    """Render merge_round_timelines output: one interleaved timeline per
    deterministic trace id, spans tagged with their source node."""
    if not merged:
        print("no shared round traces across the given nodes")
        return
    for rec in merged:
        head = (f"round {rec.get('round')}  trace {rec.get('trace_id')}"
                f"  nodes {','.join(rec.get('nodes', []))}")
        if rec.get("dropped"):
            head += f"  ({rec['dropped']} spans dropped)"
        print(head)
        spans = rec.get("spans", [])
        t0 = spans[0]["start"] if spans else 0.0
        for sp in spans:
            off_ms = ((sp.get("start") or t0) - t0) * 1000.0
            dur = sp.get("duration_ms") or 0.0
            attrs = " ".join(f"{k}={v}" for k, v in
                             (sp.get("attrs") or {}).items())
            print(f"  +{off_ms:10.3f}ms  [{sp.get('node', '?'):<12}] "
                  f"{sp['name']:<16} {dur:10.3f}ms  {attrs}")
        print()


def _print_flight_matrix(data: dict) -> None:
    """Render /debug/flight/rounds as the rounds × nodes contribution
    matrix: # on-time, ~ late, ! invalid, . missing (obs/flight.py
    bitmap encoding), with the quorum margin per round."""
    rounds = data.get("rounds", [])
    if not rounds:
        print("no flight records yet (no partials seen)")
        return
    width = max((len(r.get("bitmap") or "") for r in rounds), default=0)
    idx_hdr = " ".join(str(i % 10) for i in range(width))
    print("contribution matrix (# on-time  ~ late  ! invalid  . missing)")
    print(f"{'round':>10}  {idx_hdr:<{2 * width}}  "
          f"{'margin_s':>9}  quorum")
    for rec in rounds:
        bitmap = rec.get("bitmap") or ""
        cells = " ".join(bitmap) if bitmap else "?"
        margin = rec.get("margin_s")
        margin_s = f"{margin:9.3f}" if margin is not None else "        -"
        quorum = "-"
        for m in rec.get("milestones", []):
            if m.get("name") == "quorum":
                quorum = (f"{m.get('have')}/{rec.get('threshold')} "
                          f"@ +{m.get('offset_s'):.3f}s")
        print(f"{rec.get('round'):>10}  {cells:<{2 * width}}  "
              f"{margin_s}  {quorum}")
    peers = data.get("peers") or {}
    if peers:
        print(f"\n{'index':>6}  {'contributed':>11}  {'late':>6}  "
              f"{'invalid':>7}")
        for idx, st in peers.items():
            print(f"{idx:>6}  {st.get('contributed', 0):>11}  "
                  f"{st.get('late', 0):>6}  {st.get('invalid', 0):>7}")


def _print_flight_dkg(data: dict) -> None:
    """Render /debug/flight/dkg session timelines."""
    sessions = data.get("sessions", [])
    if not sessions:
        print("no DKG sessions recorded in this process")
        return
    for s in sessions:
        head = (f"dkg session {s.get('session')}  mode={s.get('mode')}  "
                f"dealers={s.get('n_dealers')} "
                f"receivers={s.get('n_receivers')} "
                f"threshold={s.get('threshold')}")
        if not s.get("done"):
            head += "  [RUNNING]"
        elif s.get("error"):
            head += f"  [FAILED: {s['error']}]"
        print(head)
        for ph in s.get("phases", []):
            end = ph.get("end_s")
            dur = (f"{end - ph['start_s']:8.3f}s"
                   if end is not None else "    open")
            seen = s.get("bundles", {}).get(ph["phase"], {})
            arrivals = " ".join(
                f"{i}@+{off:.3f}s" for i, off in
                sorted(seen.items(), key=lambda kv: kv[1]))
            print(f"  +{ph['start_s']:8.3f}s  {ph['phase']:<14} {dur}"
                  f"  {arrivals}")
        if s.get("qual") is not None:
            print(f"  QUAL: {s['qual']}")
        if s.get("complaints"):
            print(f"  open complaints: {s['complaints']}")
        print()


def _print_incidents(data: dict) -> None:
    """Render /debug/incidents: one line per incident, newest first."""
    incs = data.get("incidents", [])
    if not incs:
        print("no incidents recorded "
              f"({data.get('samples', 0)} samples ringed, 0 rules fired)")
        return
    print(f"{len(incs)} incident(s), {data.get('active', 0)} open, "
          f"{data.get('samples', 0)} samples ringed")
    print(f"{'id':<28} {'severity':<9} {'state':<7} {'round':>8}  detail")
    for inc in incs:
        rnd = inc.get("round")
        print(f"{inc.get('id', '?'):<28} {inc.get('severity', '?'):<9} "
              f"{inc.get('state', '?'):<7} "
              f"{rnd if rnd is not None else '-':>8}  "
              f"{inc.get('detail', '')}")


def _print_remediation(data: dict) -> None:
    """Render /debug/remediation: engine posture + guardrails, then
    the ledger newest-first."""
    budget = data.get("budget") or {}
    print(f"remediation mode: {data.get('mode')}  "
          f"budget {budget.get('used', 0)}/{budget.get('max', '?')} "
          f"per {budget.get('window_s', '?')}s  "
          f"attached={data.get('attached')}")
    active = data.get("active") or {}
    if active:
        for name, inc in sorted(active.items()):
            print(f"  active: {name} on {inc}")
    for pb in data.get("playbooks", []):
        marks = []
        if pb.get("annotate_only"):
            marks.append("annotate-only")
        if not pb.get("registered"):
            marks.append("UNREGISTERED")
        suffix = f"  [{', '.join(marks)}]" if marks else ""
        print(f"  {pb.get('playbook', '?'):<18} <- "
              f"{pb.get('rule', '?'):<18} "
              f"cooldown={pb.get('cooldown_s')}s "
              f"min_fired={pb.get('min_fired')}{suffix}")
    ledger = data.get("ledger", [])
    if not ledger:
        print("ledger: empty (no playbook has triggered)")
        return
    print(f"ledger ({len(ledger)} newest-first):")
    for e in ledger:
        print(f"  t={e.get('t')} {e.get('playbook', '?'):<18} "
              f"{e.get('outcome', '?'):<16} inc={e.get('incident')} "
              f"{e.get('detail', '')}")


def _print_incident_bundle(bundle: dict) -> None:
    """Render one incident's forensic bundle (headline + evidence
    inventory — `--json`/`-o` carry the full payload)."""
    print(f"incident {bundle.get('id')}  rule={bundle.get('rule')}  "
          f"severity={bundle.get('severity')}  "
          f"state={bundle.get('state')}")
    print(f"  opened_at={bundle.get('opened_at')}  "
          f"round={bundle.get('round')}  fired={bundle.get('fired')}  "
          f"closed_at={bundle.get('closed_at')}")
    print(f"  detail: {bundle.get('detail')}")
    sus = bundle.get("suspect_peers") or {}
    print(f"  suspect peers (frozen bitmap round {sus.get('round')}): "
          f"missing={sus.get('missing')} invalid={sus.get('invalid')} "
          f"late={sus.get('late')} unreachable={sus.get('unreachable')}")
    health = bundle.get("health") or {}
    print(f"  health: head={health.get('head_round')} "
          f"lag={health.get('lag_rounds')} "
          f"missed={health.get('missed_total')} "
          f"sync_stalled={health.get('sync_stalled')}")
    flight = bundle.get("flight") or {}
    for rec in (flight.get("rounds") or [])[:8]:
        margin = rec.get("margin_s")
        print(f"    round {rec.get('round'):>8}  "
              f"[{rec.get('bitmap') or '?'}]  "
              f"margin={margin if margin is not None else '-'}")
    print(f"  evidence: {len(bundle.get('timeseries') or [])} ts "
          f"samples, {len(flight.get('rounds') or [])} flight rounds, "
          f"{len(bundle.get('trace') or [])} round traces, "
          f"{len(bundle.get('dkg') or [])} dkg sessions, "
          f"{len(bundle.get('fallback_ledger') or [])} fallback entries, "
          f"config {((bundle.get('config') or {}).get('fingerprint'))}")


def _write_or_print(doc: dict, out: str | None, as_json: bool,
                    pretty) -> None:
    """-o FILE writes the JSON payload; otherwise print (pretty or
    --json)."""
    if out:
        with open(out, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=2)
        print(json.dumps({"written": out,
                          "bytes": os.path.getsize(out)}))
    elif as_json:
        print(json.dumps(doc, indent=2))
    else:
        pretty(doc)


def _print_engine_state(data: dict) -> None:
    print(f"dispatch mode: {data.get('mode')}  "
          f"min_batch={data.get('min_batch')}  "
          f"engine_created={data.get('engine_created')}")
    h2c = data.get("h2c_cache") or {}
    print(f"h2c cache: {h2c.get('hits', 0)} hits / "
          f"{h2c.get('misses', 0)} misses "
          f"(size {h2c.get('size', 0)}/{h2c.get('maxsize', 0)})")
    eng = data.get("engine")
    if eng:
        print(f"backend: {eng.get('backend')}  devices: "
              f"{', '.join(eng.get('devices', [])) or '?'}")
        print(f"buckets: verify={eng.get('buckets')} "
              f"wire={eng.get('wire_buckets')} "
              f"rlc_lanes={eng.get('rlc_lane_buckets')} "
              f"wire_rlc={eng.get('wire_rlc_buckets')}")
        for family, shapes in (eng.get("kat") or {}).items():
            if not shapes:
                continue
            verdicts = "  ".join(
                f"{shape}={'OK' if ok else 'DISABLED'}"
                for shape, ok in shapes.items())
            print(f"kat {family:<10} {verdicts}")
    elif data.get("engine_error"):
        print(f"engine introspection failed: {data['engine_error']}")
    else:
        print("device engine not created in this process "
              "(host crypto only so far)")
    ledger = data.get("fallback_ledger") or []
    print(f"fallback ledger ({len(ledger)} entries, newest last):")
    for e in ledger:
        print(f"  round={e.get('round')} op={e.get('op')} "
              f"path={e.get('path')} reason={e.get('reason')}")


def cmd_util(args) -> None:
    if args.what == "trace":
        # fetch + pretty-print round timelines; --merge interleaves
        # several nodes' rings into one timeline per deterministic
        # trace id (the cross-node stitch the blake2b ids exist for)
        urls = args.merge or ([args.url] if args.url else [])
        if not urls:
            raise SystemExit("util trace requires --url http://host:port "
                             "(or --merge url1 url2 ...)")

        async def run_trace():
            payloads = await asyncio.gather(
                *(_fetch_json(u, "/debug/trace/rounds", n=args.n)
                  for u in urls))
            if args.merge:
                from ..obs.trace import merge_round_timelines

                merged = merge_round_timelines(
                    list(zip(urls, payloads)))
                if args.json:
                    print(json.dumps({"rounds": merged}, indent=2))
                else:
                    _print_merged_timeline(merged)
            elif args.json:
                print(json.dumps(payloads[0], indent=2))
            else:
                _print_trace_timeline(payloads[0])

        asyncio.run(run_trace())
        return
    if args.what == "flight":
        # threshold flight recorder: rounds × nodes contribution matrix
        # (or --dkg for the DKG phase timeline) from /debug/flight/*
        if not args.url:
            raise SystemExit("util flight requires --url http://host:port")

        async def run_flight():
            if args.dkg:
                data = await _fetch_json(args.url, "/debug/flight/dkg")
                if args.json:
                    print(json.dumps(data, indent=2))
                else:
                    _print_flight_dkg(data)
            else:
                data = await _fetch_json(args.url, "/debug/flight/rounds",
                                         n=args.n)
                if args.json:
                    print(json.dumps(data, indent=2))
                else:
                    _print_flight_matrix(data)

        asyncio.run(run_flight())
        return
    if args.what == "incidents":
        # incident engine (ISSUE 15): list summaries, show one bundle,
        # or save a bundle's JSON to a file for a post-mortem hand-off
        if not args.url:
            raise SystemExit("util incidents requires --url "
                             "http://host:port")

        async def run_incidents():
            target = args.show or args.bundle
            if target:
                data = await _fetch_json(args.url,
                                         f"/debug/incidents/{target}")
                _write_or_print(data, args.out, args.json,
                                _print_incident_bundle)
            else:
                data = await _fetch_json(args.url, "/debug/incidents",
                                         n=args.n)
                _write_or_print(data, args.out, args.json,
                                _print_incidents)

        asyncio.run(run_incidents())
        return
    if args.what == "remediate":
        # auto-remediation plane (ISSUE 16): engine mode, budget,
        # active playbooks and the action ledger over /debug/remediation
        if not args.url:
            raise SystemExit("util remediate requires --url "
                             "http://host:port")

        async def run_remediate():
            data = await _fetch_json(args.url, "/debug/remediation",
                                     n=args.n)
            _write_or_print(data, args.out, args.json,
                            _print_remediation)

        asyncio.run(run_remediate())
        return
    if args.what == "support-bundle":
        # one-shot manual forensic capture (ISSUE 15): the node runs
        # the incident bundle writer on demand — no anomaly required
        if not args.url:
            raise SystemExit("util support-bundle requires --url "
                             "http://host:port")
        if not args.out and not args.json:
            raise SystemExit("util support-bundle requires -o FILE "
                             "(or --json to print)")

        async def run_support():
            data = await _fetch_json(args.url, "/debug/support-bundle")
            _write_or_print(data, args.out, args.json,
                            _print_incident_bundle)

        asyncio.run(run_support())
        return
    if args.what == "engine":
        # engine introspection of a running node (/debug/engine):
        # KAT-gate status, fallback ledger, backend identity
        if not args.url:
            raise SystemExit("util engine requires --url http://host:port")

        async def run_engine():
            data = await _fetch_json(args.url, "/debug/engine")
            if args.json:
                print(json.dumps(data, indent=2))
            else:
                _print_engine_state(data)

        asyncio.run(run_engine())
        return
    if args.what == "del-beacon":
        # offline rollback (reference cli.go:651 deleteBeaconCmd): daemon
        # must be stopped; removes every round >= --round. Honors the
        # store backend the daemon would open (DRAND_TPU_STORE) — a
        # rollback against the wrong backend would print success while
        # the chain the daemon serves stays untouched.
        if args.round is None:
            raise SystemExit("del-beacon requires --round (every round >= "
                             "it is deleted)")
        from ..chain.store import (StoreError, chain_store_exists,
                                   open_chain_store)

        db = os.path.join(_folder(args), "db", "chain.db")
        exists, chain_path = chain_store_exists(db)
        if not exists:
            raise SystemExit(f"no chain store at {chain_path}")
        store = open_chain_store(db)
        try:
            last = store.last().round
        except StoreError:
            raise SystemExit("chain db is empty")
        removed = store.del_from(args.round)
        store.close()
        print(json.dumps({"deleted": removed, "from_round": args.round,
                          "was_at": last}))
        return
    if args.what == "reset":
        # reference cli.go resetCmd: drop the distributed state (share,
        # group, chain) but KEEP the longterm keypair — the node can then
        # join a fresh DKG under the same identity. Daemon must be stopped.
        if not args.force:
            raise SystemExit("util reset deletes the share, group file and "
                             "beacon database (keypair kept) — re-run with "
                             "--force to confirm")
        folder = _folder(args)
        removed = []
        import shutil

        from ..key import store as key_store

        for rel in (f"{key_store.GROUP_FOLDER}/{key_store.SHARE_FILE}",
                    f"{key_store.GROUP_FOLDER}/{key_store.GROUP_FILE}",
                    f"{key_store.GROUP_FOLDER}/{key_store.DIST_KEY_FILE}"):
            p = os.path.join(folder, rel)
            if os.path.isfile(p):
                os.unlink(p)
                removed.append(rel)
        dbdir = os.path.join(folder, "db")
        if os.path.isdir(dbdir):
            shutil.rmtree(dbdir)
            removed.append("db/")
        print(json.dumps({"reset": True, "removed": removed,
                          "folder": folder}))
        return
    if args.what == "store-migrate" and args.vault:
        # Timelock vault SQLite <-> segment (timelock/segvault.py,
        # ISSUE 20). Daemon/relay must be stopped. Same verified-copy
        # contract as the chain migration: count + pending_count +
        # sampled records compared before success is reported.
        from ..timelock.segvault import (SegmentVault, is_segment_vault,
                                         migrate_vault)
        from ..timelock.vault import TimelockVault

        db = args.db or os.path.join(_folder(args), "db", "timelock.db")
        out = args.out or os.path.join(os.path.dirname(db),
                                       "timelock-segments")
        if args.reverse:
            # the SOURCE must exist in both directions — a typo'd path
            # would otherwise auto-create an empty vault and report a
            # successful 0-row migration
            if not is_segment_vault(out):
                raise SystemExit(f"no segment vault at {out}")
            vsrc: object = SegmentVault(out)
            vdst: object = TimelockVault(db)
            dst_path = db
        else:
            if not os.path.isfile(db):
                raise SystemExit(f"no timelock db at {db}")
            vsrc = TimelockVault(db)
            vdst = SegmentVault(out)
            dst_path = out
        # the DESTINATION must be empty: SegmentVault.put_rows has no
        # duplicate check, so re-running an interrupted migration would
        # append every row twice — and open_vault auto-selects an
        # existing segment dir on the next daemon start, serving the
        # doubled rows. Refuse up front (remove the remnant or point
        # --out/--db somewhere fresh)
        if len(vdst) > 0:
            n_dst = len(vdst)
            vsrc.close()
            vdst.close()
            raise SystemExit(
                f"destination {dst_path} already holds {n_dst} rows — "
                f"refusing to append a migration onto it (remove it or "
                f"choose a fresh path)")
        n = migrate_vault(vsrc, vdst)
        problems = []
        if len(vdst) != len(vsrc):
            problems.append(f"count mismatch: src={len(vsrc)} "
                            f"dst={len(vdst)}")
        if vdst.pending_count() != vsrc.pending_count():
            problems.append(f"pending mismatch: "
                            f"src={vsrc.pending_count()} "
                            f"dst={vdst.pending_count()}")
        sampled = 0
        for rec in vsrc.rows():
            got = vdst.get(rec["id"])
            src_pt = rec.get("plaintext")
            dst_pt = got.get("plaintext") if got else None
            if (got is None
                    or got["status"] != rec["status"]
                    or got["round"] != rec["round"]
                    or (bytes(src_pt) if src_pt else None)
                    != (bytes(dst_pt) if dst_pt else None)):
                problems.append(f"record {rec['id']} mismatch")
                break
            sampled += 1
            if sampled >= 64:
                break
        pending = vdst.pending_count()
        vsrc.close()
        vdst.close()
        if problems:
            # quarantine the destination we just wrote (it was empty
            # before this run): left in place, a half-verified segment
            # dir would be auto-selected by open_vault on the next
            # daemon start and served as if it were sound
            import shutil

            quarantine = dst_path + ".failed"
            if os.path.isdir(quarantine):
                shutil.rmtree(quarantine)
            elif os.path.exists(quarantine):
                os.remove(quarantine)
            os.rename(dst_path, quarantine)
            raise SystemExit("store-migrate --vault verification "
                             "failed: " + "; ".join(problems)
                             + f"; destination quarantined at "
                               f"{quarantine}")
        print(json.dumps({"migrated": n, "db": db, "segments": out,
                          "pending": pending,
                          "direction": ("segment->sqlite" if args.reverse
                                        else "sqlite->segment")}))
        return
    if args.what == "store-migrate":
        # SQLite chain db <-> packed segment store (chain/segments.py).
        # Daemon must be stopped. Default direction is sqlite->segment;
        # --reverse converts a segment store back into a SQLite db.
        # The copy is verified (count + head + sampled rounds) before
        # the command reports success.
        from ..chain.segments import SegmentStore, migrate_store
        from ..chain.store import SQLiteStore, StoreError

        from ..chain.segments import META_FILE

        db = args.db or os.path.join(_folder(args), "db", "chain.db")
        out = args.out or os.path.join(os.path.dirname(db), "segments")
        if args.reverse:
            # the SOURCE must already exist in both directions — a
            # typo'd path would otherwise auto-create an empty store
            # and report a successful 0-round migration
            if not os.path.isfile(os.path.join(out, META_FILE)):
                raise SystemExit(f"no segment store at {out}")
            src: object = SegmentStore(out)
            dst: object = SQLiteStore(db)
        else:
            if not os.path.isfile(db):
                raise SystemExit(f"no chain db at {db}")
            src = SQLiteStore(db)
            dst = SegmentStore(out)
        n = migrate_store(src, dst)
        problems = []
        if len(dst) != len(src):
            problems.append(f"count mismatch: src={len(src)} "
                            f"dst={len(dst)}")
        try:
            src_last = src.last()
            if not dst.last().equal(src_last):
                problems.append("head beacon mismatch")
            sample = {0, 1, src_last.round // 2, src_last.round}
            for rd in sorted(sample):
                a, b = src.get(rd), dst.get(rd)
                if (a is None) != (b is None) or \
                        (a is not None and not a.equal(b)):
                    problems.append(f"round {rd} mismatch")
        except StoreError:
            pass  # empty chain: nothing beyond the count to verify
        src.close()
        dst.close()
        if problems:
            raise SystemExit("store-migrate verification failed: "
                             + "; ".join(problems))
        print(json.dumps({"migrated": n, "db": db, "segments": out,
                          "direction": ("segment->sqlite" if args.reverse
                                        else "sqlite->segment")}))
        return
    if args.what == "self-sign":
        from ..key.store import FileStore

        ks = FileStore(_folder(args))
        pair = ks.load_key_pair()
        pair.self_sign()
        ks.save_key_pair(pair)
        print(json.dumps({"address": pair.public.addr, "self_signed": True}))
        return

    async def run():
        if args.what == "ping":
            from ..net.control import ControlClient

            ctl = ControlClient(args.control)
            try:
                print("pong" if await ctl.ping() else "no reply")
            finally:
                await ctl.close()
        elif args.what == "check":
            from ..net.grpc_transport import GrpcClient

            client = GrpcClient(own_addr="check")
            try:
                ident = await client.get_identity(args.address)
                ok = ident.valid_signature()
                print(json.dumps({"address": args.address,
                                  "key": ident.key.to_bytes().hex(),
                                  "valid_signature": ok}))
            finally:
                await client.close()

    asyncio.run(run())


def cmd_analyze(args) -> None:
    """Static-analysis suite (tools/analyze): loopblock, lockheld,
    threadshare, awaitatomic, secretflow, jaxhazard, asyncsanity plus
    the metrics catalogue lint — pure AST, host-only, no backend init.
    Exit 1 on unsuppressed findings at or above --fail-on."""
    import pathlib

    repo = pathlib.Path(__file__).resolve().parents[2]
    if not (repo / "tools" / "analyze" / "run.py").is_file():
        raise SystemExit("drand analyze needs a source checkout "
                         "(tools/analyze/ not found next to the package)")
    sys.path.insert(0, str(repo))
    from tools.analyze.run import main as analyze_main

    argv = ["--fail-on", args.fail_on]
    if args.json:
        argv.append("--json")
    if args.passes:
        argv += ["--passes", args.passes]
    if args.baseline:
        argv += ["--baseline", args.baseline]
    if args.sarif:
        argv += ["--sarif", args.sarif]
    if args.prune_baseline:
        argv.append("--prune-baseline")
    raise SystemExit(analyze_main(argv))


def cmd_relay(args) -> None:
    """HTTP CDN relay (reference cmd/relay): serve the public API backed by
    the VERIFIED client stack over one or more origin nodes.

    ``--workers K`` forks K INDEPENDENT worker processes sharing the
    listen port via SO_REUSEPORT (one event loop caps a box; the kernel
    load-balances new connections). Each worker runs its own watch loop
    and fan-out hub; a worker dying takes only its own watchers down.
    SIGTERM drains gracefully: open /public/latest streams end at the
    hub sentinel before the listener closes."""
    if args.workers > 1:
        _relay_parent(args)
        return

    async def run():
        import signal

        from ..client import new_client
        from ..client.http import HTTPClient
        from ..http_server.server import PublicServer

        sources = [HTTPClient(u) for u in args.url.split(",")]
        client = new_client(sources, **_client_trust(args))
        # relays opt into incident persistence via env only (no folder)
        from ..obs.incident import configure_from_env as _incidents_env

        _incidents_env(None)
        tl_service = None
        if args.timelock_db:
            # a relay can front the timelock vault too: it opens rounds
            # from its verified watch stream (no local chain store).
            # --timelock-shard i/K (set by the worker parent under the
            # segment backend) partitions the sweep: this worker opens
            # ONLY its token-range slice and appends under its own
            # writer id, so K workers sharing one vault never
            # interleave writes (timelock/segvault.py shard math)
            from ..timelock import TimelockService, open_vault

            shard = None
            writer_id = 0
            if args.timelock_shard:
                idx, _, count = args.timelock_shard.partition("/")
                shard = (int(idx), int(count))
                writer_id = shard[0]
            tl_service = TimelockService(
                open_vault(args.timelock_db, writer_id=writer_id),
                client, shard=shard)
        server = PublicServer(
            client, timelock_service=tl_service,
            timelock_sweep=not args.no_timelock_sweep)
        # auto-remediation (ISSUE 16): the relay's playbook is partition
        # posture — dry-run by default, DRAND_TPU_REMEDIATE=live arms it
        from ..obs.remediate import attach_posture
        from ..obs.remediate import configure_from_env as _remediate_env

        attach_posture(_remediate_env(), server)
        host, port = args.listen.rsplit(":", 1)
        await server.start(host or "0.0.0.0", int(port),
                           reuse_port=args.reuse_port)
        print(f"relay serving {args.listen} from {args.url} "
              f"pid={os.getpid()}", flush=True)
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGTERM, signal.SIGINT):
            loop.add_signal_handler(sig, stop.set)
        await stop.wait()
        print(f"relay pid={os.getpid()} draining", flush=True)
        await server.stop()
        await client.close()

    asyncio.run(run())


def _relay_parent(args) -> None:
    """Supervise ``--workers K`` SO_REUSEPORT relay workers. One worker
    exiting does NOT take the port down — the survivors keep serving
    their watchers (the worker-smoke contract); the parent exits when
    every worker has. SIGTERM/SIGINT fan out to the workers so the
    whole group drains together. Sweeper respawn rides the shared
    ``utils.supervise.Supervisor`` (the same budget policy the
    auto-remediation respawn playbook uses)."""
    import signal
    import subprocess
    import time as _time

    from ..utils.supervise import Supervisor

    argv = [sys.executable, "-m", "drand_tpu.cli", "relay",
            "--url", args.url, "--listen", args.listen,
            "--workers", "1", "--reuse-port"]
    if args.chain_hash:
        argv += ["--chain-hash", args.chain_hash]
    if args.insecure:
        argv += ["--insecure"]
    if args.timelock_db:
        argv += ["--timelock-db", args.timelock_db]

    # Partitioned sweeps (ISSUE 20): under the SEGMENT vault backend
    # every worker sweeps its own disjoint token-range shard (and
    # appends under its own writer id — no interleaved writes on the
    # shared directory), so a round's K·ceil(n/K) openings spread
    # across all cores instead of serializing on one sweeper. The
    # SQLite backend keeps the sole-sweeper designation: K concurrent
    # sweeps there would contend on one WAL file every round.
    partitioned = False
    if args.timelock_db:
        from ..timelock.segvault import is_segment_vault

        backend = os.environ.get(
            "DRAND_TPU_TIMELOCK_STORE", "").strip()
        partitioned = (backend == "segment"
                       or is_segment_vault(args.timelock_db))

    def _spawn(slot: int):
        worker_argv = list(argv)
        if partitioned:
            worker_argv += ["--timelock-shard",
                            f"{slot}/{args.workers}"]
        elif args.timelock_db and slot != 0:
            # ONE designated sweeping worker: all workers serve the
            # vault routes from the shared file, but only the sweeper
            # opens rounds at boundaries — K concurrent sweeps would
            # recompute the same pairing-class openings K times and
            # contend on one WAL file every round
            worker_argv.append("--no-timelock-sweep")
        return subprocess.Popen(worker_argv)

    slots = [_spawn(i) for i in range(args.workers)]
    procs = list(slots)
    sweeper = slots[0]
    crashed = False
    stopping = False
    print(f"relay parent pid={os.getpid()} workers="
          f"{[p.pid for p in procs]}"
          + (" partitioned" if partitioned else ""), flush=True)

    def _fan_out(signum, frame):
        nonlocal stopping
        stopping = True
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)

    signal.signal(signal.SIGTERM, _fan_out)
    signal.signal(signal.SIGINT, _fan_out)

    # a dead SWEEPER would silently stop vault round-opens while the
    # survivors keep serving — respawn it through the shared bounded
    # supervisor (a crash-looping sweeper must not fork-bomb the box).
    # Partitioned mode widens this to EVERY worker: each owns a token
    # shard, so any death leaves a slice of every round unopened —
    # the respawn carries the slot's shard assignment over.
    sup = Supervisor(respawn_budget=5, backoff_base_s=0.0)

    def _respawn_sweeper() -> None:
        nonlocal sweeper, crashed
        old_rc = sweeper.returncode
        crashed = crashed or old_rc != 0
        sweeper = _spawn(0)
        slots[0] = sweeper
        procs.append(sweeper)
        print(f"relay parent: sweeper died (rc={old_rc}), "
              f"respawned pid={sweeper.pid} "
              f"({sup.respawns('sweeper')}/{sup.respawn_budget})",
              flush=True)

    def _mk_respawn_shard(slot: int):
        def _respawn() -> None:
            nonlocal crashed
            old_rc = slots[slot].returncode
            crashed = crashed or old_rc != 0
            p = _spawn(slot)
            slots[slot] = p
            procs.append(p)
            print(f"relay parent: shard {slot}/{args.workers} worker "
                  f"died (rc={old_rc}), respawned pid={p.pid} "
                  f"({sup.respawns(f'shard-{slot}')}/"
                  f"{sup.respawn_budget})", flush=True)
        return _respawn

    names: list[str] = []
    if partitioned:
        for i in range(args.workers):
            name = f"shard-{i}"
            sup.register(name,
                         is_alive=lambda i=i: slots[i].poll() is None,
                         respawn=_mk_respawn_shard(i))
            names.append(name)
    else:
        sup.register("sweeper",
                     is_alive=lambda: sweeper.poll() is None,
                     respawn=_respawn_sweeper)
        names.append("sweeper")
    while any(p.poll() is None for p in procs):
        if (args.timelock_db and not stopping
                and any(p.poll() is None for p in procs)):
            for name in names:
                sup.maybe_respawn(name)
        _time.sleep(0.2)
    # any worker that did not exit cleanly — including signal deaths,
    # whose returncode is NEGATIVE — must surface to the supervisor;
    # max() would mask a segfaulted worker behind the clean drains
    raise SystemExit(
        0 if all(p.returncode == 0 for p in procs) and not crashed else 1)


def _client_trust(args) -> dict:
    """Trust-root kwargs for new_client: a pinned chain hash, or an
    EXPLICIT --insecure opt-out (the reference CLI likewise refuses to
    fetch unverified randomness by default)."""
    if args.chain_hash:
        try:
            return {"chain_hash": bytes.fromhex(args.chain_hash)}
        except ValueError:
            raise SystemExit(f"--chain-hash is not valid hex: "
                             f"{args.chain_hash!r}")
    if getattr(args, "insecure", False):
        return {"insecurely": True}
    raise SystemExit(
        "--chain-hash is required (or pass --insecure to skip verification)")


def cmd_client(args) -> None:
    """Standalone randomness consumer (reference cmd/client/lib/cli.go:97
    Create): build the full verified stack over HTTP and/or gRPC sources,
    then one-shot get or watch, printing one JSON object per round."""

    async def run():
        from ..client import new_client
        from ..client.http import HTTPClient
        from ..client.grpc_source import GrpcSource

        sources = []
        if args.url:
            sources += [HTTPClient(u) for u in args.url.split(",")]
        if args.grpc:
            sources += [GrpcSource(a) for a in args.grpc.split(",")]
        if not sources:
            raise SystemExit("need --url and/or --grpc sources")
        from ..http_server.server import result_json

        if args.watch and args.round:
            raise SystemExit("--round and --watch are mutually exclusive")
        client = new_client(sources, **_client_trust(args))
        try:
            if args.watch:
                async for r in client.watch():
                    print(json.dumps(result_json(r)), flush=True)
            else:
                print(json.dumps(result_json(await client.get(args.round)),
                                 indent=2))
        finally:
            await client.close()

    asyncio.run(run())


def _read_payload(args) -> bytes:
    """The plaintext to lock: --data literal, --in file, else stdin."""
    if args.data is not None:
        return args.data.encode()
    if getattr(args, "infile", None):
        with open(args.infile, "rb") as f:
            return f.read()
    return sys.stdin.buffer.read()


def _timelock_round(args, info) -> int:
    """Round-or-duration addressing (chain/time_math.py): --round wins;
    --duration D locks to the first round whose boundary is at least D
    seconds away."""
    import time as _time

    from ..chain import time_math

    if args.round:
        return args.round
    if not args.duration:
        raise SystemExit("timelock lock needs --round R or --duration "
                         "SECONDS")
    now = int(_time.time())
    target = now + args.duration
    rd = time_math.current_round(target, info.period, info.genesis_time) + 1
    if time_math.time_of_round(info.period, info.genesis_time, rd) == \
            time_math.TIME_OF_ROUND_ERROR_VALUE:
        raise SystemExit(f"--duration {args.duration} overflows the "
                         f"chain's round arithmetic")
    return rd


def cmd_timelock(args) -> None:
    """Timelock client surface: lock (encrypt to a round), unlock
    (decrypt with the published beacon), submit/status (the serving
    vault's POST /timelock + GET /timelock/{id})."""

    async def run():
        import aiohttp

        from ..client import timelock as client_timelock
        from ..client.http import HTTPClient

        src = HTTPClient(args.url)
        try:
            if args.what == "lock":
                info = await src.info()
                rd = _timelock_round(args, info)
                env = await asyncio.to_thread(
                    client_timelock.encrypt_to_round, info, rd,
                    _read_payload(args))
                print(client_timelock.dumps(env))
                return
            if args.what == "unlock":
                with open(args.ct, "r") as f:
                    env = client_timelock.loads(f.read())
                info = await src.info()
                result = await src.get(env.get("round", 0))
                out = await asyncio.to_thread(
                    client_timelock.decrypt_with_beacon, env, result,
                    info)
                sys.stdout.buffer.write(out)
                sys.stdout.buffer.flush()
                return
            async def read_body(r):
                # the error path may not be our JSON (proxy HTML, a
                # --no-timelock node's text/plain 404): never let
                # ContentTypeError replace the clean failure message
                text = await r.text()
                try:
                    return json.loads(text)
                except ValueError:
                    return {"error": text.strip()[:200]}

            base = args.url.rstrip("/")
            async with aiohttp.ClientSession() as s:
                if args.what == "submit":
                    with open(args.ct, "r") as f:
                        env = client_timelock.loads(f.read())
                    async with s.post(f"{base}/timelock", json=env) as r:
                        body = await read_body(r)
                        if r.status not in (200, 202):
                            raise SystemExit(
                                f"submit failed (HTTP {r.status}): "
                                f"{body.get('error', body)}")
                        print(json.dumps(body, indent=2))
                else:  # status
                    if not args.id:
                        raise SystemExit("timelock status requires --id")
                    async with s.get(f"{base}/timelock/{args.id}") as r:
                        body = await read_body(r)
                        if r.status != 200:
                            raise SystemExit(
                                f"status failed (HTTP {r.status}): "
                                f"{body.get('error', body)}")
                        print(json.dumps(body, indent=2))
        finally:
            await src.close()

    asyncio.run(run())


def cmd_relay_archive(args) -> None:
    """Archive relay (reference cmd/relay-s3): watch a chain and persist
    every beacon as a JSON object laid out like the public REST API
    (`<out>/public/<round>`, `<out>/info`), ready for static/CDN serving
    or an `aws s3 sync`. `--sync` backfills history first
    (relay-s3/main.go:142 historic sync)."""

    async def run():
        from ..client import new_client
        from ..client.http import HTTPClient
        from ..http_server.server import result_json

        sources = [HTTPClient(u) for u in args.url.split(",")]
        client = new_client(sources, **_client_trust(args))
        pub = os.path.join(args.out, "public")
        os.makedirs(pub, exist_ok=True)

        def put(r) -> None:
            path = os.path.join(pub, str(r.round))
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(result_json(r), f)
            os.replace(tmp, path)

        given_up: set[int] = set()
        heal_fails: dict[int, int] = {}
        GIVE_UP_AFTER = 5  # heal cycles before a round is abandoned
        SHIP_EVERY = 64    # archived rounds between spool shipments

        # OTLP spool shipping (the ISSUE-6 follow-on): an archive relay
        # is the natural offline shipper — when both env vars are set,
        # re-POST the spooled traces in batches at start and every
        # SHIP_EVERY archived rounds (truncated on success; a dead
        # collector leaves the spool for the next cycle)
        ship_spool_path = os.environ.get("DRAND_TPU_OTLP_SPOOL") or None
        ship_endpoint = os.environ.get("DRAND_TPU_OTLP_ENDPOINT") or None

        async def ship_traces() -> None:
            if not (ship_spool_path and ship_endpoint):
                return
            from ..obs import export as obs_export

            try:
                out = await obs_export.ship_spool(ship_spool_path,
                                                  ship_endpoint)
            except Exception as e:  # noqa: BLE001 — telemetry shipping
                # must never take down beacon archiving
                print(f"otlp spool ship failed: {e!r}", flush=True)
                return
            if out["batches"] or not out["ok"]:
                print(f"otlp spool ship: {out}", flush=True)

        async def fetch_span(start: int, end: int, width: int = 16,
                             attempts: int = 3) -> None:
            # bounded-concurrency backfill: each get() is an independent
            # verified fetch, so a small gather window cuts wall-clock.
            # Rounds already on disk (or given up on) are skipped
            # (restart-friendly); transient failures retry, persistent
            # ones raise.
            todo = [rd for rd in range(start, end + 1)
                    if rd not in given_up
                    and not os.path.exists(os.path.join(pub, str(rd)))]
            if not todo:
                return
            for attempt in range(attempts):
                failed = []
                for lo in range(0, len(todo), width):
                    rounds = todo[lo:lo + width]
                    results = await asyncio.gather(
                        *(client.get(rd) for rd in rounds),
                        return_exceptions=True)
                    for rd, r in zip(rounds, results):
                        if isinstance(r, BaseException):
                            failed.append(rd)
                        else:
                            put(r)
                if not failed:
                    return
                todo = failed
                await asyncio.sleep(1.0 * (attempt + 1))
            raise SystemExit(f"backfill failed for rounds {todo[:10]}"
                             f"{'...' if len(todo) > 10 else ''}")

        archived = 0
        try:
            info = await client.info()
            with open(os.path.join(args.out, "info"), "w") as f:
                f.write(info.to_json())
            if args.sync or args.once or args.sync_from:
                latest = (await client.get(0)).round
                archived = latest
                await fetch_span(args.sync_from or 1, latest)
                print(f"backfilled rounds {args.sync_from or 1}..{latest}",
                      flush=True)
            await ship_traces()
            if args.once:
                return
            since_ship = 0
            async for r in client.watch():
                put(r)
                print(f"archived round {r.round}", flush=True)
                since_ship += 1
                if since_ship >= SHIP_EVERY:
                    since_ship = 0
                    await ship_traces()
                # heal any hole between the watermark and this round
                # (rounds produced during backfill, watch hiccups). A
                # transient source outage is retried across GIVE_UP_AFTER
                # heal cycles (the watermark stays put so the next beacon
                # retries; on-disk rounds are skipped, so retries only
                # touch the still-missing ones); only a round that fails
                # that many cycles is abandoned — bounding the stall a
                # permanently unfetchable round can cause without turning
                # one outage into a permanent archive hole.
                if archived and r.round > archived + 1:
                    try:
                        await fetch_span(archived + 1, r.round - 1)
                    except SystemExit as e:
                        missing = [rd for rd in range(archived + 1, r.round)
                                   if rd not in given_up and not os.path.
                                   exists(os.path.join(pub, str(rd)))]
                        abandoned = []
                        for rd in missing:
                            heal_fails[rd] = heal_fails.get(rd, 0) + 1
                            if heal_fails[rd] >= GIVE_UP_AFTER:
                                given_up.add(rd)
                                heal_fails.pop(rd)
                                abandoned.append(rd)
                        if abandoned:
                            print(f"gap heal gave up on rounds "
                                  f"{abandoned}: {e}", flush=True)
                        if set(missing) - given_up:
                            print(f"gap heal deferred: {e}", flush=True)
                            continue  # keep watermark; retry next beacon
                archived = max(archived, r.round)
        finally:
            await client.close()

    asyncio.run(run())


def main(argv=None) -> None:
    p = argparse.ArgumentParser(prog="drand-tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    g = sub.add_parser("generate-keypair")
    g.add_argument("address")
    g.add_argument("--folder")
    g.add_argument("--tls", action="store_true",
                   help="mark the identity as TLS-served (start --tls)")
    g.add_argument("--force", action="store_true")
    g.set_defaults(fn=cmd_generate_keypair)

    s = sub.add_parser("start")
    s.add_argument("--folder")
    s.add_argument("--private-listen")
    s.add_argument("--public-listen")
    s.add_argument("--control", type=int, default=8888)
    s.add_argument("--no-timelock", action="store_true",
                   help="serve the public API without the timelock vault "
                        "(on by default at <folder>/db/timelock.db)")
    s.add_argument("--dkg-timeout", type=float, default=10.0)
    s.add_argument("--tls", action="store_true",
                   help="serve the node port over TLS (self-signed cert "
                        "under <folder>/tls/; peers' certs go in "
                        "<folder>/tls/trusted/*.pem)")
    s.add_argument("--verbose", action="store_true")
    s.set_defaults(fn=cmd_start)

    sh = sub.add_parser("share")
    sh.add_argument("--control", type=int, default=8888)
    sh.add_argument("--leader", action="store_true")
    sh.add_argument("--connect")
    sh.add_argument("--nodes", type=int)
    sh.add_argument("--threshold", type=int)
    sh.add_argument("--period", type=int, default=30)
    sh.add_argument("--secret-file")
    sh.add_argument("--timeout", type=float, default=60.0)
    sh.add_argument("--reshare", action="store_true")
    sh.add_argument("--leaving", action="store_true")
    sh.add_argument("--from-group", help="old group file (new joiners)")
    sh.set_defaults(fn=cmd_share)

    f = sub.add_parser("follow")
    f.add_argument("--control", type=int, default=8888)
    f.add_argument("--sync-nodes", required=True)
    f.add_argument("--up-to", type=int, default=0)
    f.add_argument("--chain-hash", default="",
                   help="hex chain-info hash to pin (peers serving a "
                        "different chain are rejected)")
    f.set_defaults(fn=cmd_follow)

    st = sub.add_parser("stop")
    st.add_argument("--control", type=int, default=8888)
    st.set_defaults(fn=cmd_stop)

    show = sub.add_parser("show")
    show.add_argument("what", choices=["share", "group", "chain-info",
                                       "public", "status"])
    show.add_argument("--control", type=int, default=8888)
    show.set_defaults(fn=cmd_show)

    get = sub.add_parser("get")
    get.add_argument("what", choices=["public", "chain-info", "private"])
    get.add_argument("--url", help="HTTP base URL (public/chain-info)")
    get.add_argument("--connect", help="node gRPC address (private)")
    get.add_argument("--round", type=int, default=0)
    get.set_defaults(fn=cmd_get)

    u = sub.add_parser("util")
    u.add_argument("what", choices=["ping", "check", "del-beacon",
                                    "self-sign", "reset", "trace",
                                    "engine", "flight", "store-migrate",
                                    "incidents", "support-bundle",
                                    "remediate"])
    u.add_argument("--control", type=int, default=8888)
    u.add_argument("--address")
    u.add_argument("--folder")
    u.add_argument("--round", type=int, default=None)
    u.add_argument("--force", action="store_true",
                   help="confirm destructive util commands (reset)")
    u.add_argument("--url", help="public HTTP base URL (trace/engine)")
    u.add_argument("--merge", nargs="+", metavar="URL",
                   help="trace: fetch several nodes' rings and "
                        "interleave spans sharing a trace id into one "
                        "cross-node timeline")
    u.add_argument("--n", type=int, default=8,
                   help="round timelines/flight records/incident "
                        "summaries/ledger entries to fetch "
                        "(trace/flight/incidents/remediate)")
    u.add_argument("--dkg", action="store_true",
                   help="flight: show the DKG phase timeline instead "
                        "of the round matrix")
    u.add_argument("--show", metavar="ID", default="",
                   help="incidents: pretty-print one incident's "
                        "forensic bundle")
    u.add_argument("--bundle", metavar="ID", default="",
                   help="incidents: fetch one incident's bundle "
                        "(pair with -o FILE to save the JSON)")
    u.add_argument("--db", default="",
                   help="store-migrate: SQLite chain db path "
                        "(default <folder>/db/chain.db)")
    u.add_argument("-o", "--out", default="",
                   help="store-migrate: segment store directory "
                        "(default <db dir>/segments); "
                        "incidents/support-bundle: write the bundle "
                        "JSON to this file")
    u.add_argument("--reverse", action="store_true",
                   help="store-migrate: convert segment->sqlite "
                        "instead of sqlite->segment")
    u.add_argument("--vault", action="store_true",
                   help="store-migrate: convert the TIMELOCK vault "
                        "(default <folder>/db/timelock.db <-> "
                        "<db dir>/timelock-segments) instead of the "
                        "chain store; honors --db/-o/--reverse")
    u.add_argument("--json", action="store_true",
                   help="raw JSON instead of the pretty rendering "
                        "(trace/engine/flight)")
    u.set_defaults(fn=cmd_util)

    an = sub.add_parser("analyze",
                        help="AST static-analysis suite (loopblock, "
                             "lockheld, threadshare, awaitatomic, "
                             "secretflow, jaxhazard, asyncsanity, "
                             "metrics lint)")
    an.add_argument("--json", action="store_true",
                    help="machine-readable output")
    an.add_argument("--fail-on", choices=["high", "medium", "low"],
                    default="high")
    an.add_argument("--passes", default="",
                    help="comma-separated pass subset")
    an.add_argument("--baseline", default="",
                    help="override the baseline-suppression file")
    an.add_argument("--sarif", default="",
                    help="write unsuppressed findings as SARIF 2.1.0 "
                         "to this path")
    an.add_argument("--prune-baseline", action="store_true",
                    help="rewrite the baseline dropping stale entries "
                         "(kept reasons preserved)")
    an.set_defaults(fn=cmd_analyze)

    r = sub.add_parser("relay")
    r.add_argument("--url", required=True,
                   help="comma-separated origin base URLs")
    r.add_argument("--listen", required=True)
    r.add_argument("--chain-hash", default="",
                   help="hex chain hash to pin (verifies all beacons)")
    r.add_argument("--insecure", action="store_true",
                   help="explicitly skip beacon verification")
    r.add_argument("--timelock-db", default="",
                   help="serve the timelock vault from this sqlite path "
                        "(opens rounds off the verified watch stream)")
    r.add_argument("--workers", type=int, default=1,
                   help="fork this many SO_REUSEPORT worker processes "
                        "sharing the listen port (each with its own "
                        "event loop, watch loop and fan-out hub)")
    r.add_argument("--reuse-port", action="store_true",
                   help=argparse.SUPPRESS)  # set by the worker parent
    r.add_argument("--no-timelock-sweep", action="store_true",
                   help=argparse.SUPPRESS)  # parent designates sweeper
    r.add_argument("--timelock-shard", default="",
                   help=argparse.SUPPRESS)  # parent assigns "i/K" shard
    r.set_defaults(fn=cmd_relay)

    tl = sub.add_parser("timelock",
                        help="timelock client: encrypt to a future round, "
                             "decrypt with its beacon, or use a node's "
                             "vault (POST /timelock)")
    tl.add_argument("what", choices=["lock", "unlock", "submit", "status"])
    tl.add_argument("--url", required=True,
                    help="public HTTP base URL of a node/relay")
    tl.add_argument("--round", type=int, default=0,
                    help="lock: target round (exclusive with --duration)")
    tl.add_argument("--duration", type=int, default=0,
                    help="lock: seconds until the ciphertext may open "
                         "(rounded up to the next round boundary)")
    tl.add_argument("--data", default=None,
                    help="lock: literal payload (else --in / stdin)")
    tl.add_argument("--in", dest="infile", default="",
                    help="lock: read the payload from this file")
    tl.add_argument("--ct", default="",
                    help="unlock/submit: envelope JSON file (from lock)")
    tl.add_argument("--id", default="",
                    help="status: ciphertext id returned by submit")
    tl.set_defaults(fn=cmd_timelock)

    c = sub.add_parser("client")
    c.add_argument("--url", default="", help="comma-separated HTTP origins")
    c.add_argument("--grpc", default="",
                   help="comma-separated gRPC node addresses")
    c.add_argument("--chain-hash", default="")
    c.add_argument("--insecure", action="store_true",
                   help="explicitly skip beacon verification")
    c.add_argument("--round", type=int, default=0)
    c.add_argument("--watch", action="store_true")
    c.set_defaults(fn=cmd_client)

    ra = sub.add_parser("relay-archive")
    ra.add_argument("--url", required=True,
                    help="comma-separated origin base URLs")
    ra.add_argument("--out", required=True,
                    help="output directory (S3-sync / CDN layout)")
    ra.add_argument("--chain-hash", default="")
    ra.add_argument("--insecure", action="store_true",
                    help="explicitly skip beacon verification")
    ra.add_argument("--sync", action="store_true",
                    help="backfill history before watching")
    ra.add_argument("--once", action="store_true",
                    help="backfill then exit (relay-s3's `sync` command)")
    ra.add_argument("--sync-from", type=int, default=0,
                    help="first round to backfill (implies --sync)")
    ra.set_defaults(fn=cmd_relay_archive)

    args = p.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
