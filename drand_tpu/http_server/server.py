"""Public REST API server.

Reference: http/server.go (New :35, routes :52-55, long-poll watch :102,
health :55,:351). JSON wire format matches the reference's public API so
existing drand consumers can point at this server unchanged:

    GET /public/latest   -> {"round","randomness","signature",
                             "previous_signature"[,"signature_v2"]}
    GET /public/{round}  -> same (long-polls if the round is the next one)
    GET /info            -> {"public_key","period","genesis_time",
                             "group_hash","hash"}
    GET /health          -> 200 {"current","expected"} | 500 when lagging

Serving stack: aiohttp over any client.Client (typically a DirectClient on
the local daemon, or a verifying client over remote nodes — the reference
relays this same way, cmd/relay).
"""

from __future__ import annotations

import asyncio
import json

from aiohttp import web

from ..chain import time_math
from ..client.interface import Client, ClientError, Result
from ..utils.clock import Clock, SystemClock
from ..utils.logging import KVLogger, default_logger


def result_json(r: Result) -> dict:
    d = {
        "round": r.round,
        "randomness": r.randomness.hex(),
        "signature": r.signature.hex(),
        "previous_signature": r.previous_signature.hex(),
    }
    if r.signature_v2:
        d["signature_v2"] = r.signature_v2.hex()
    return d


class PublicServer:
    def __init__(self, client: Client, clock: Clock | None = None,
                 logger: KVLogger | None = None,
                 watch_timeout: float = 30.0,
                 peer_metrics_fn=None,
                 enable_pprof: bool = False,
                 timelock_service=None):
        self._client = client
        self._clock = clock or SystemClock()
        self._l = logger or default_logger("http")
        self._watch_timeout = watch_timeout
        # optional async addr -> bytes hook relaying a group member's
        # metrics over the node transport (metrics.go:266 GroupHandler)
        self._peer_metrics_fn = peer_metrics_fn
        # optional timelock vault front (drand_tpu/timelock): adds the
        # submit/status routes and opens pending ciphertexts from the
        # watch loop's round boundary (covers relays with no store hook)
        self._timelock = timelock_service
        self._latest: Result | None = None
        self._next_round_event = asyncio.Event()
        self._watch_task: asyncio.Task | None = None
        self._chain_tag: bytes | None = None
        # last successfully fetched chain info: the stale-serving path
        # computes the X-Drand-Stale lag from it after the upstream dies
        self._info_cache = None
        self.app = web.Application(middlewares=[self._instrument])
        self.app.add_routes([
            web.get("/public/latest", self._handle_latest),
            web.get("/public/{round}", self._handle_round),
            web.get("/info", self._handle_info),
            web.get("/health", self._handle_health),
            web.get("/healthz", self._handle_healthz),
            web.get("/readyz", self._handle_readyz),
            web.get("/metrics", self._handle_metrics),
            web.get("/peer/{addr}/metrics", self._handle_peer_metrics),
        ])
        if timelock_service is not None:
            self.app.add_routes([
                web.post("/timelock", self._handle_timelock_submit),
                web.get("/timelock/{id}", self._handle_timelock_status),
            ])
        # the round-timeline surface is on by default (no profiling
        # cost; group topology is already public via /info and the
        # group file) but operators can opt out with
        # DRAND_TPU_TRACE_DEBUG=0; the pprof routes stay opt-in like
        # the reference (pprof.go WithProfile)
        import os

        if os.environ.get("DRAND_TPU_TRACE_DEBUG", "1") != "0":
            from .debug import add_trace_routes

            add_trace_routes(self.app)
        if enable_pprof:
            from .debug import add_debug_routes

            add_debug_routes(self.app)

    # ------------------------------------------------------------ serving
    async def start(self, host: str, port: int) -> web.TCPSite:
        self._watch_task = asyncio.ensure_future(self._watch_loop())
        if self._timelock is not None:
            await self._timelock.start()
        runner = web.AppRunner(self.app)
        await runner.setup()
        site = web.TCPSite(runner, host, port)
        await site.start()
        self._runner = runner
        return site

    async def stop(self) -> None:
        if self._watch_task is not None:
            self._watch_task.cancel()
        # stop accepting requests BEFORE closing the vault: an in-flight
        # submit against a closed sqlite handle would 500 instead of
        # being refused cleanly
        await self._runner.cleanup()
        if self._timelock is not None:
            await self._timelock.close()

    async def _watch_loop(self) -> None:
        """Track the tip so /public/{next} can long-poll (server.go:102)."""
        while True:
            try:
                async for r in self._client.watch():
                    self._latest = r
                    self._next_round_event.set()
                    self._next_round_event = asyncio.Event()
                    if self._timelock is not None:
                        # round boundary: open the round's pending
                        # timelock ciphertexts (one batched dispatch)
                        self._timelock.on_result(r)
            except asyncio.CancelledError:
                return
            except Exception as e:  # noqa: BLE001 — keep serving
                self._l.warn("http", "watch_restart", err=str(e))
                await asyncio.sleep(1.0)

    # ------------------------------------------------------------ handlers
    @web.middleware
    async def _instrument(self, request: web.Request, handler):
        from .. import metrics

        path = request.match_info.route.resource
        path = path.canonical if path else request.path
        metrics.HTTP_IN_FLIGHT.inc()
        try:
            with metrics.HTTP_LATENCY.labels(path=path).time():
                resp = await handler(request)
        finally:
            metrics.HTTP_IN_FLIGHT.dec()
        metrics.HTTP_REQUESTS.labels(path=path, code=resp.status).inc()
        return resp

    async def _handle_metrics(self, request: web.Request) -> web.Response:
        from .. import metrics

        return web.Response(body=metrics.render(),
                            content_type="text/plain")

    async def _handle_peer_metrics(self, request: web.Request) -> web.Response:
        if self._peer_metrics_fn is None:
            return web.json_response({"error": "peer metrics not enabled"},
                                     status=404)
        try:
            body = await self._peer_metrics_fn(request.match_info["addr"])
        except Exception as e:  # noqa: BLE001 — peer unreachable etc.
            return web.json_response({"error": str(e)}, status=502)
        return web.Response(body=body, content_type="text/plain")

    async def _get_info(self):
        """Chain info with the last-success cache refreshed (the
        stale-serving lag source). Raises ClientError like info()."""
        info = await self._client.info()
        self._info_cache = info
        return info

    async def _result_response(self, r: Result) -> web.Response:
        """Beacon JSON + the round-correlation id as an HTTP header, so a
        consumer can join the response to /debug/trace and the KV logs."""
        resp = web.json_response(result_json(r))
        try:
            from ..obs import trace as obs_trace

            if self._chain_tag is None:
                tag = (await self._get_info()).genesis_seed
                # re-check after the await (awaitatomic): concurrent
                # first requests must not clobber the published tag
                if self._chain_tag is None:
                    self._chain_tag = tag
            resp.headers[obs_trace.TRACEPARENT_HEADER] = \
                obs_trace.make_traceparent(
                    obs_trace.round_trace_id(r.round, self._chain_tag))
        except Exception:  # noqa: BLE001 — the header is best-effort
            pass
        return resp

    async def _handle_latest(self, request: web.Request) -> web.Response:
        try:
            r = await self._client.get(0)
        except ClientError as e:
            return await self._stale_or_error(e)
        return await self._result_response(r)

    async def _stale_or_error(self, err: ClientError) -> web.Response:
        """Degraded-mode serving (ISSUE 12): when the upstream is lost
        but a beacon was ever seen, serve the LAST-KNOWN beacon as a
        non-cacheable 200 with an explicit ``X-Drand-Stale: <lag>``
        header (lag in rounds behind the schedule, computed from the
        cached chain info; -1 when no info was ever fetched) instead of
        a 5xx/404 — a consumer that can tolerate staleness keeps
        working, one that cannot sees the header and knows. no-store
        keeps CDNs from pinning the stale answer past the outage."""
        if self._latest is None:
            return web.json_response({"error": str(err)}, status=404)
        from .. import metrics

        lag = -1
        info = self._info_cache
        if info is not None:
            expected = time_math.current_round(
                int(self._clock.now()), info.period, info.genesis_time)
            lag = max(0, expected - self._latest.round)
        resp = await self._result_response(self._latest)
        resp.headers["X-Drand-Stale"] = str(lag)
        resp.headers["Cache-Control"] = "no-store"
        metrics.RELAY_STALE_SERVED.inc()
        self._l.warn("http", "serving_stale", lag_rounds=lag,
                     round=self._latest.round)
        return resp

    async def _handle_round(self, request: web.Request) -> web.Response:
        try:
            round_no = int(request.match_info["round"])
        except ValueError:
            return web.json_response({"error": "bad round"}, status=400)
        try:
            return await self._result_response(await self._client.get(round_no))
        except ClientError:
            pass
        # long-poll ONLY the upcoming round (server.go:102); a missing
        # historical round 404s immediately — blocking the watch timeout
        # for arbitrary absent rounds would be free connection-holding
        try:
            info = await self._get_info()
        except ClientError as e:
            return web.json_response({"error": str(e)}, status=503)
        expected = time_math.current_round(
            int(self._clock.now()), info.period, info.genesis_time)
        if round_no > expected + 1 or round_no < expected:
            return web.json_response({"error": "round not available"},
                                     status=404)
        event = self._next_round_event
        try:
            await asyncio.wait_for(event.wait(), self._watch_timeout)
        except asyncio.TimeoutError:
            pass  # fall through: the round may have landed regardless
        try:
            return await self._result_response(await self._client.get(round_no))
        except ClientError as e:
            return web.json_response({"error": str(e)}, status=404)

    async def _handle_info(self, request: web.Request) -> web.Response:
        try:
            info = await self._get_info()
        except ClientError as e:
            return web.json_response({"error": str(e)}, status=503)
        return web.json_response({
            "public_key": info.public_key.to_bytes().hex(),
            "period": info.period,
            "genesis_time": info.genesis_time,
            "group_hash": info.group_hash.hex(),
            "hash": info.hash().hex(),
        })

    async def _handle_health(self, request: web.Request) -> web.Response:
        """Current vs expected round (http/server.go:351)."""
        try:
            info = await self._get_info()
        except ClientError as e:
            return web.json_response({"error": str(e)}, status=503)
        expected = time_math.current_round(
            int(self._clock.now()), info.period, info.genesis_time)
        current = await self._head_round()
        body = {"current": current, "expected": expected}
        status = 200 if current + 1 >= expected else 500
        return web.json_response(body, status=status)

    async def _head_round(self) -> int:
        """Best known chain head: the watch-loop tip, else one fetch."""
        if self._latest is not None:
            return self._latest.round
        try:
            return (await self._client.get(0)).round
        except ClientError:
            return 0

    async def _chain_health(self):
        """(snapshot, info) with the health gauges re-evaluated against
        the wall clock — the pull half of obs/health: a fully stalled
        chain (group lost threshold, peer died) stores nothing, so
        probes and scrapes must drive head-lag and the missed-round
        counter. Raises ClientError while there is no chain info yet
        (pre-DKG / relay origin down)."""
        from ..obs.health import HEALTH

        info = await self._get_info()
        head = await self._head_round()
        HEALTH.observe_chain(self._clock.now(), info.period,
                             info.genesis_time, head)
        snap = HEALTH.snapshot()
        snap["period"] = info.period
        return snap, info

    async def _handle_healthz(self, request: web.Request) -> web.Response:
        """Chain-health SLO surface (ISSUE 6): head/lag/missed/SLO
        snapshot; 200 while the head lags by at most
        DRAND_TPU_READY_MAX_LAG rounds, 503 otherwise (and while no
        chain info exists yet)."""
        from ..obs.health import READY_MAX_LAG, HEALTH, is_ready

        try:
            snap, _ = await self._chain_health()
        except ClientError as e:
            body = HEALTH.snapshot()
            body.update(status="no_chain", error=str(e))
            return web.json_response(body, status=503)
        ok = is_ready(snap)
        snap["status"] = "ok" if ok else "lagging"
        snap["max_lag"] = READY_MAX_LAG
        return web.json_response(snap, status=200 if ok else 503)

    # ------------------------------------------------------------ timelock
    async def _handle_timelock_submit(self, request: web.Request
                                      ) -> web.Response:
        """POST /timelock: accept a ciphertext locked to a future round
        into the vault. Body = the client envelope JSON
        (client/timelock.encrypt_to_round). 202 with the status record;
        400 on validation failure, 503 while the chain is unknown."""
        from ..timelock.service import TimelockError

        try:
            envelope = await request.json()
        except (ValueError, UnicodeDecodeError):
            return web.json_response({"error": "body is not JSON"},
                                     status=400)
        try:
            rec = await self._timelock.submit(envelope)
        except TimelockError as e:
            msg = str(e)
            status = 503 if "chain info unavailable" in msg else 400
            return web.json_response({"error": msg}, status=status)
        return web.json_response(rec, status=202)

    async def _handle_timelock_status(self, request: web.Request
                                      ) -> web.Response:
        """GET /timelock/{id}: the ciphertext's status record. Opened
        and rejected records are IMMUTABLE — served with an ETag and
        Cache-Control: immutable so a CDN can absorb result polling the
        same way it absorbs /public/{round}; pending records are
        no-store (they change at the round boundary)."""
        token = request.match_info["id"]
        rec = await self._timelock.status(token)
        if rec is None:
            return web.json_response({"error": "unknown ciphertext id"},
                                     status=404)
        if rec["status"] == "pending":
            resp = web.json_response(rec)
            resp.headers["Cache-Control"] = "no-store"
            return resp
        etag = f'"tl-{rec["id"]}-{rec["status"]}"'
        if request.headers.get("If-None-Match") == etag:
            return web.Response(status=304, headers={
                "ETag": etag,
                "Cache-Control": "public, max-age=31536000, immutable"})
        resp = web.json_response(rec)
        resp.headers["ETag"] = etag
        resp.headers["Cache-Control"] = \
            "public, max-age=31536000, immutable"
        return resp

    async def _handle_readyz(self, request: web.Request) -> web.Response:
        """Readiness: chain info servable (the DKG-complete signal at
        this layer — a relay has no DKG, and a daemon cannot serve info
        before its DKG finished) AND head-lag within bound. The
        daemon-recorded dkg_complete flag rides along for operators."""
        from ..obs.health import READY_MAX_LAG, is_ready

        try:
            snap, _ = await self._chain_health()
        except ClientError as e:
            return web.json_response(
                {"ready": False, "reason": f"no chain info: {e}"},
                status=503)
        ready = is_ready(snap)
        snap["ready"] = ready
        snap["max_lag"] = READY_MAX_LAG
        if not ready:
            snap["reason"] = (f"head lag {snap['lag_rounds']} > "
                              f"{READY_MAX_LAG} rounds")
        return web.json_response(snap, status=200 if ready else 503)
