"""Public REST API server.

Reference: http/server.go (New :35, routes :52-55, long-poll watch :102,
health :55,:351). JSON wire format matches the reference's public API so
existing drand consumers can point at this server unchanged:

    GET /public/latest   -> {"round","randomness","signature",
                             "previous_signature"[,"signature_v2"]}
    GET /public/{round}  -> same (long-polls if the round is the next one)
    GET /info            -> {"public_key","period","genesis_time",
                             "group_hash","hash"}
    GET /health          -> 200 {"current","expected"} | 500 when lagging
    GET /checkpoints/latest -> {"round","signature","chain_hash",
                             "checkpoint_sig"} | 404 before the first one

Serving stack: aiohttp over any client.Client (typically a DirectClient on
the local daemon, or a verifying client over remote nodes — the reference
relays this same way, cmd/relay).
"""

from __future__ import annotations

import asyncio
import json
import math
import os

from aiohttp import web

from ..chain import time_math
from ..client.interface import Client, ClientError, Result
from ..utils.clock import Clock, SystemClock
from ..utils.logging import KVLogger, default_logger
from ..utils.retry import RetryPolicy, retry
from . import fanout

# watch-loop restart policy: decorrelated jitter on the INJECTABLE
# clock (the raw `await asyncio.sleep(1.0)` it replaces was invisible
# to FakeClock runs and hammered a dead upstream at a fixed rate).
# attempts bounds one retry() cycle; the loop re-enters on exhaustion,
# so a dead upstream is probed ~attempts times per backoff ramp forever.
_WATCH_RETRY = RetryPolicy(attempts=6, base_s=0.5, cap_s=15.0)

# connection cap for `/public/latest` stream watchers: a cheap
# counter check before ANY handler work (each watcher holds one fd;
# shedding at the door is what keeps an overload from starving the
# poll handlers sharing the loop)
DEFAULT_MAX_WATCHERS = int(os.environ.get(
    "DRAND_TPU_RELAY_MAX_WATCHERS", "4096"))

# partition posture (ISSUE 16): the watcher cap is multiplied by this
# while the posture holds — a minority-partition relay serving stale
# data should also carry fewer streams, so capacity stays for the
# pollers that tolerate staleness
POSTURE_WATCHER_FRACTION = 0.5

# /public/span page cap: one request serves at most this many beacons
# (the adaptive RLC catch-up client pages through larger windows) —
# bounds per-request memory and upstream fan-in on the serving side
DEFAULT_SPAN_CAP = int(os.environ.get("DRAND_TPU_SPAN_CAP", "1024"))


def _etag_matches(if_none_match: str | None, etag: str) -> bool:
    """RFC 7232 If-None-Match: member-wise WEAK comparison — caches
    legitimately send lists (`"r99", "r100"`), weak validators
    (`W/"r100"`), or `*`; exact string equality would silently defeat
    the 304 path for exactly the shared caches the ETag targets."""
    if not if_none_match:
        return False
    if if_none_match.strip() == "*":
        return True
    for member in if_none_match.split(","):
        member = member.strip()
        if member.startswith("W/"):
            member = member[2:]
        if member == etag:
            return True
    return False


def result_json(r: Result) -> dict:
    d = {
        "round": r.round,
        "randomness": r.randomness.hex(),
        "signature": r.signature.hex(),
        "previous_signature": r.previous_signature.hex(),
    }
    if r.signature_v2:
        d["signature_v2"] = r.signature_v2.hex()
    return d


class PublicServer:
    def __init__(self, client: Client, clock: Clock | None = None,
                 logger: KVLogger | None = None,
                 watch_timeout: float = 30.0,
                 peer_metrics_fn=None,
                 enable_pprof: bool = False,
                 timelock_service=None,
                 timelock_sweep: bool = True,
                 max_watchers: int | None = None,
                 fanout_queue_max: int = fanout.DEFAULT_QUEUE_MAX):
        self._client = client
        self._clock = clock or SystemClock()
        self._l = logger or default_logger("http")
        self._watch_timeout = watch_timeout
        # optional async addr -> bytes hook relaying a group member's
        # metrics over the node transport (metrics.go:266 GroupHandler)
        self._peer_metrics_fn = peer_metrics_fn
        # optional timelock vault front (drand_tpu/timelock): adds the
        # submit/status routes and opens pending ciphertexts from the
        # watch loop's round boundary (covers relays with no store
        # hook). timelock_sweep=False serves the vault routes WITHOUT
        # sweeping at boundaries — the non-designated members of a
        # multi-worker relay group sharing one vault file (one sweeper
        # avoids K workers re-opening the same rounds concurrently)
        self._timelock = timelock_service
        self._timelock_sweep = timelock_sweep
        # multi-worker open-notify fallback: when the open for a
        # watched token commits in ANOTHER worker process (the sole
        # sweeper under the shared-SQLite mode, the shard owner under
        # partitioned segment sweeps), this worker's hub never
        # publishes it — the watch handler polls the SHARED vault at
        # this interval instead of hanging forever
        self._tl_watch_poll = float(os.environ.get(
            "DRAND_TPU_TIMELOCK_WATCH_POLL") or 2.0)
        self._latest: Result | None = None
        self._next_round_event = asyncio.Event()
        self._watch_task: asyncio.Task | None = None
        self._chain_tag: bytes | None = None
        # push tier (ISSUE 14): SSE / NDJSON watchers on /public/latest
        # share one broadcast hub — one publish per round, not N polls
        self._hub = fanout.FanoutHub(queue_max=fanout_queue_max)
        self._max_watchers = (max_watchers if max_watchers is not None
                              else DEFAULT_MAX_WATCHERS)
        self._span_cap = DEFAULT_SPAN_CAP
        # partition posture (ISSUE 16): applied by the remediation
        # engine on a majority reachability drop, reverted on incident
        # close — serve stale from the cache without hammering the dead
        # upstream, and shed new watchers earlier
        self._posture = False
        self._max_watchers_normal = self._max_watchers
        # last successfully fetched chain info: the stale-serving path
        # computes the X-Drand-Stale lag from it after the upstream dies
        self._info_cache = None
        self.app = web.Application(middlewares=[self._instrument])
        self.app.add_routes([
            web.get("/public/latest", self._handle_latest),
            web.get("/public/span", self._handle_span),
            web.get("/public/{round}", self._handle_round),
            web.get("/info", self._handle_info),
            web.get("/checkpoints/latest", self._handle_checkpoint),
            web.get("/health", self._handle_health),
            web.get("/healthz", self._handle_healthz),
            web.get("/readyz", self._handle_readyz),
            web.get("/metrics", self._handle_metrics),
            web.get("/peer/{addr}/metrics", self._handle_peer_metrics),
        ])
        # open-notify leg (ISSUE 20): GET /timelock with a stream Accept
        # pushes (token, status) at open time — wired as the service's
        # notifier so events fire right after each chunk's vault commit
        self._tl_hub = fanout.TimelockNotifyHub(queue_max=fanout_queue_max)
        if timelock_service is not None:
            timelock_service.set_notifier(self._tl_hub.publish_open)
            self.app.add_routes([
                web.post("/timelock", self._handle_timelock_submit),
                web.get("/timelock", self._handle_timelock_watch),
                web.get("/timelock/{id}", self._handle_timelock_status),
            ])
        # the round-timeline surface is on by default (no profiling
        # cost; group topology is already public via /info and the
        # group file) but operators can opt out with
        # DRAND_TPU_TRACE_DEBUG=0; the pprof routes stay opt-in like
        # the reference (pprof.go WithProfile)
        if os.environ.get("DRAND_TPU_TRACE_DEBUG", "1") != "0":
            from .debug import add_trace_routes

            add_trace_routes(self.app)
        if enable_pprof:
            from .debug import add_debug_routes

            add_debug_routes(self.app)

    # ------------------------------------------------------------ serving
    async def start(self, host: str, port: int,
                    reuse_port: bool = False) -> web.TCPSite:
        """``reuse_port=True`` lets K relay worker processes share one
        listen port via SO_REUSEPORT (`drand-tpu relay --workers K`) —
        the kernel load-balances new connections across workers, each
        of which runs its own event loop, watch loop and fan-out hub."""
        self._watch_task = asyncio.ensure_future(self._watch_loop())
        if self._timelock is not None:
            await self._timelock.start()
        # short shutdown grace: draining streams end at the hub sentinel,
        # so nothing needs aiohttp's default 60 s lingering-handler wait
        runner = web.AppRunner(self.app, shutdown_timeout=5.0)
        await runner.setup()
        site = web.TCPSite(runner, host, port,
                           reuse_port=reuse_port or None)
        await site.start()
        self._runner = runner
        return site

    async def stop(self) -> None:
        if self._watch_task is not None:
            self._watch_task.cancel()
        # graceful drain order: close the watcher streams FIRST (each
        # handler wakes to the hub sentinel and finishes its response),
        # then stop accepting requests, then close the vault — an
        # in-flight submit against a closed sqlite handle would 500
        # instead of being refused cleanly
        self._hub.close_all()
        self._tl_hub.close_all()
        await self._runner.cleanup()
        if self._timelock is not None:
            await self._timelock.close()

    async def _watch_loop(self) -> None:
        """Track the tip so /public/{next} can long-poll (server.go:102)
        and feed the fan-out hub. Restarts ride the injectable-clock
        retry policy (decorrelated jitter) instead of a raw
        asyncio.sleep — the analyzer's retry-sleep rule covers
        http_server/ like net/ and chain/."""
        while True:
            try:
                await retry(self._watch_pass, op="watch",
                            policy=_WATCH_RETRY, clock=self._clock)
            except asyncio.CancelledError:
                return
            except Exception as e:  # noqa: BLE001 — keep serving
                self._l.warn("http", "watch_restart", err=str(e))

    async def _watch_pass(self) -> None:
        # refresh the info cache first: the stale-lag and Retry-After
        # math need period/genesis, and a dead upstream fails fast here
        # instead of inside the watch iterator
        try:
            await self._get_info()
        except ClientError:
            pass  # tolerated: some test doubles serve watch() only
        async for r in self._client.watch():
            self._publish(r)

    def _publish(self, r: Result) -> None:
        """One round boundary: wake the long-pollers, the timelock
        sweep, and every stream watcher from a single hub publish."""
        self._latest = r
        self._next_round_event.set()
        self._next_round_event = asyncio.Event()
        if self._timelock is not None and self._timelock_sweep:
            # round boundary: open the round's pending
            # timelock ciphertexts (one batched dispatch)
            self._timelock.on_result(r)
        delay = None
        info = self._info_cache
        if info is not None:
            boundary = time_math.time_of_round(info.period,
                                               info.genesis_time, r.round)
            delay = self._clock.now() - boundary
        self._hub.publish(result_json(r), r.round, boundary_delay_s=delay)

    # ------------------------------------------------------------ handlers
    @web.middleware
    async def _instrument(self, request: web.Request, handler):
        from .. import metrics

        path = request.match_info.route.resource
        path = path.canonical if path else request.path
        metrics.HTTP_IN_FLIGHT.inc()
        try:
            with metrics.HTTP_LATENCY.labels(path=path).time():
                resp = await handler(request)
        finally:
            metrics.HTTP_IN_FLIGHT.dec()
        metrics.HTTP_REQUESTS.labels(path=path, code=resp.status).inc()
        return resp

    async def _handle_metrics(self, request: web.Request) -> web.Response:
        from .. import metrics

        return web.Response(body=metrics.render(),
                            content_type="text/plain")

    async def _handle_peer_metrics(self, request: web.Request) -> web.Response:
        if self._peer_metrics_fn is None:
            return web.json_response({"error": "peer metrics not enabled"},
                                     status=404)
        try:
            body = await self._peer_metrics_fn(request.match_info["addr"])
        except Exception as e:  # noqa: BLE001 — peer unreachable etc.
            return web.json_response({"error": str(e)}, status=502)
        return web.Response(body=body, content_type="text/plain")

    async def _get_info(self):
        """Chain info with the last-success cache refreshed (the
        stale-serving lag source). Raises ClientError like info()."""
        info = await self._client.info()
        self._info_cache = info
        return info

    async def _result_response(self, r: Result) -> web.Response:
        """Beacon JSON + the round-correlation id as an HTTP header, so a
        consumer can join the response to /debug/trace and the KV logs."""
        resp = web.json_response(result_json(r))
        try:
            from ..obs import trace as obs_trace

            if self._chain_tag is None:
                tag = (await self._get_info()).genesis_seed
                # re-check after the await (awaitatomic): concurrent
                # first requests must not clobber the published tag
                if self._chain_tag is None:
                    self._chain_tag = tag
            resp.headers[obs_trace.TRACEPARENT_HEADER] = \
                obs_trace.make_traceparent(
                    obs_trace.round_trace_id(r.round, self._chain_tag))
        except Exception:  # noqa: BLE001 — the header is best-effort
            pass
        return resp

    async def _handle_latest(self, request: web.Request) -> web.Response:
        proto = self._stream_proto(request)
        if proto is not None:
            return await self._handle_latest_stream(request, proto)
        if self._posture and self._latest is not None:
            # partition posture: the upstream is known-partitioned —
            # serve the last-known beacon (X-Drand-Stale) immediately
            # instead of paying a doomed upstream round-trip per poll
            return await self._stale_or_error(
                ClientError("partition posture"))
        try:
            r = await self._client.get(0)
        except ClientError as e:
            return await self._stale_or_error(e)
        # round-keyed ETag (ISSUE 14 satellite): the pollers that remain
        # on plain GET revalidate with If-None-Match and cost a header,
        # not a body, between rounds. no-cache (NOT no-store) so shared
        # caches may hold the entity but must revalidate it — the round
        # advances every period. The stale/degraded path above keeps
        # no-store and never carries an ETag.
        etag = f'"r{r.round}"'
        if _etag_matches(request.headers.get("If-None-Match"), etag):
            return web.Response(status=304, headers={
                "ETag": etag, "Cache-Control": "no-cache",
                "Vary": "Accept"})
        resp = await self._result_response(r)
        resp.headers["ETag"] = etag
        resp.headers["Cache-Control"] = "no-cache"
        # /public/latest is content-negotiated (JSON vs SSE/NDJSON
        # streams): a shared cache must never serve the JSON entity to
        # an EventSource client or vice versa
        resp.headers["Vary"] = "Accept"
        return resp

    # ------------------------------------------------------------ push tier
    @staticmethod
    def _stream_proto(request: web.Request) -> str | None:
        """Watch-protocol content negotiation on /public/latest: SSE for
        ``Accept: text/event-stream``, chunked NDJSON for ``Accept:
        application/x-ndjson``. Plain GET pollers are untouched."""
        accept = request.headers.get("Accept", "")
        if "text/event-stream" in accept:
            return fanout.PROTO_SSE
        if "application/x-ndjson" in accept:
            return fanout.PROTO_NDJSON
        return None

    def _shed_response(self) -> web.Response:
        """429 + Retry-After aligned to the NEXT round boundary
        (chain/time_math): a shed watcher that comes back any earlier
        would only re-join the same queue for the same round — this
        way the retry lands exactly when there is something new. Uses
        only the cached chain info: shedding must never cost an
        upstream fetch."""
        from .. import metrics

        metrics.RELAY_SHED.labels(reason="watcher_cap").inc()
        retry_after = 1
        info = self._info_cache
        if info is not None:
            now = self._clock.now()
            _, next_t = time_math.next_round(int(now), info.period,
                                             info.genesis_time)
            retry_after = max(1, math.ceil(next_t - now))
        return web.json_response(
            {"error": "watcher capacity reached, retry at the next round"},
            status=429,
            headers={"Retry-After": str(retry_after), "Vary": "Accept"})

    async def _handle_latest_stream(self, request: web.Request,
                                    proto: str) -> web.StreamResponse:
        """Push-tier /public/latest: subscribe the connection to the
        fan-out hub and stream rounds as the watch loop publishes them.
        The initial snapshot (last-known beacon, possibly stale) is
        framed per-connection; everything after it is the hub's
        shared-once-per-round payload."""
        # load shedding happens BEFORE any handler work: one integer
        # compare guards the fd/queue cost of a new watcher
        if self._hub.watcher_count() >= self._max_watchers:
            return self._shed_response()
        resp = web.StreamResponse()
        resp.headers["Content-Type"] = (
            "text/event-stream" if proto == fanout.PROTO_SSE
            else "application/x-ndjson")
        resp.headers["Cache-Control"] = "no-store"
        resp.headers["Vary"] = "Accept"
        resp.headers["X-Accel-Buffering"] = "no"
        # the serving worker's pid: lets operators (and the worker
        # smoke test) see which SO_REUSEPORT worker holds the stream
        resp.headers["X-Drand-Worker"] = str(os.getpid())
        # degraded-mode marker at connect time (ISSUE 12 semantics
        # carried onto streams): when the last-known beacon is behind
        # the schedule, say by how many rounds
        info = self._info_cache
        if info is not None and self._latest is not None:
            expected = time_math.current_round(
                int(self._clock.now()), info.period, info.genesis_time)
            lag = max(0, expected - self._latest.round)
            if lag > 0:
                resp.headers["X-Drand-Stale"] = str(lag)
        sub = self._hub.subscribe(proto)
        try:
            await resp.prepare(request)
            snap_round = -1
            if self._latest is not None:
                snap = self._latest
                snap_round = snap.round
                payload = json.dumps(result_json(snap)).encode()
                frame = (fanout.sse_frame(snap.round, payload)
                         if proto == fanout.PROTO_SSE
                         else fanout.ndjson_frame(payload))
                await resp.write(frame)
            while True:
                item = await sub.next()
                if item is None:
                    break  # shed as a slow consumer, or server drain
                round_no, frame = item
                if round_no <= snap_round:
                    # a publish that raced the prepare() await already
                    # went out as the snapshot — never send it twice
                    continue
                await resp.write(frame)
            await resp.write_eof()
        except (ConnectionResetError, ConnectionError):
            pass  # the client went away mid-stream; nothing to salvage
        finally:
            self._hub.unsubscribe(sub)
        return resp

    def set_partition_posture(self, on: bool) -> str:
        """Apply/revert partition posture (the ``partition_posture``
        remediation playbook): while on, ``/public/latest`` serves the
        last-known beacon from the cache (the ``X-Drand-Stale`` path)
        without trying the partitioned upstream, and the watcher-shed
        cap drops to ``POSTURE_WATCHER_FRACTION`` of normal. Idempotent
        both ways; returns the ledger detail."""
        if on:
            if self._posture:
                return "partition posture already on"
            self._posture = True
            self._max_watchers_normal = self._max_watchers
            self._max_watchers = max(
                1, int(self._max_watchers * POSTURE_WATCHER_FRACTION))
            return (f"partition posture on: serving stale from cache, "
                    f"watcher cap {self._max_watchers_normal} -> "
                    f"{self._max_watchers}")
        if not self._posture:
            return "partition posture already off"
        self._posture = False
        self._max_watchers = self._max_watchers_normal
        return (f"partition posture off: live serving restored, "
                f"watcher cap back to {self._max_watchers}")

    async def _stale_or_error(self, err: ClientError) -> web.Response:
        """Degraded-mode serving (ISSUE 12): when the upstream is lost
        but a beacon was ever seen, serve the LAST-KNOWN beacon as a
        non-cacheable 200 with an explicit ``X-Drand-Stale: <lag>``
        header (lag in rounds behind the schedule, computed from the
        cached chain info; -1 when no info was ever fetched) instead of
        a 5xx/404 — a consumer that can tolerate staleness keeps
        working, one that cannot sees the header and knows. no-store
        keeps CDNs from pinning the stale answer past the outage."""
        if self._latest is None:
            return web.json_response({"error": str(err)}, status=404)
        from .. import metrics

        lag = -1
        info = self._info_cache
        if info is not None:
            expected = time_math.current_round(
                int(self._clock.now()), info.period, info.genesis_time)
            lag = max(0, expected - self._latest.round)
        resp = await self._result_response(self._latest)
        resp.headers["X-Drand-Stale"] = str(lag)
        resp.headers["Cache-Control"] = "no-store"
        resp.headers["Vary"] = "Accept"
        metrics.RELAY_STALE_SERVED.inc()
        self._l.warn("http", "serving_stale", lag_rounds=lag,
                     round=self._latest.round)
        return resp

    async def _handle_round(self, request: web.Request) -> web.Response:
        try:
            round_no = int(request.match_info["round"])
        except ValueError:
            return web.json_response({"error": "bad round"}, status=400)
        try:
            return await self._result_response(await self._client.get(round_no))
        except ClientError:
            pass
        # long-poll ONLY the upcoming round (server.go:102); a missing
        # historical round 404s immediately — blocking the watch timeout
        # for arbitrary absent rounds would be free connection-holding
        try:
            info = await self._get_info()
        except ClientError as e:
            return web.json_response({"error": str(e)}, status=503)
        expected = time_math.current_round(
            int(self._clock.now()), info.period, info.genesis_time)
        if round_no > expected + 1 or round_no < expected:
            return web.json_response({"error": "round not available"},
                                     status=404)
        event = self._next_round_event
        try:
            await asyncio.wait_for(event.wait(), self._watch_timeout)
        except asyncio.TimeoutError:
            pass  # fall through: the round may have landed regardless
        try:
            return await self._result_response(await self._client.get(round_no))
        except ClientError as e:
            return web.json_response({"error": str(e)}, status=404)

    async def _handle_span(self, request: web.Request) -> web.Response:
        """GET /public/span?from=&count=: a contiguous beacon window in
        one request — the wire surface for the adaptive RLC catch-up
        fast path (client/verify.py span batches, ROADMAP #7). Serves
        at most DRAND_TPU_SPAN_CAP beacons per request (the client
        pages); a partially available window returns its PREFIX, so the
        caller always makes progress and retries the rest. 404 when the
        first round is not servable at all."""
        from ..client.interface import result_from_beacon

        try:
            frm = int(request.query.get("from", ""))
            count = int(request.query.get("count", ""))
        except (TypeError, ValueError):
            return web.json_response(
                {"error": "span needs integer from= and count="},
                status=400)
        if frm < 1 or count < 1:
            return web.json_response(
                {"error": "span needs from >= 1 and count >= 1"},
                status=400)
        capped = min(count, self._span_cap)
        results: list[Result] = []
        get_span = getattr(self._client, "get_span", None)
        if get_span is not None:
            # bulk path (DirectClient over the local store): all-or-
            # nothing, so fall through to the prefix loop on a miss
            try:
                results = [result_from_beacon(b)
                           for b in await get_span(frm, frm + capped)]
            except ClientError:
                results = []
        if not results:
            for rn in range(frm, frm + capped):
                try:
                    results.append(await self._client.get(rn))
                except ClientError:
                    break
        if not results:
            return web.json_response(
                {"error": "span not available"}, status=404)
        # server-side round echo: a confused upstream must never ship
        # a window whose positions disagree with the request
        for i, r in enumerate(results):
            if r.round != frm + i:
                return web.json_response(
                    {"error": f"upstream served round {r.round} at "
                              f"position {frm + i}"}, status=502)
        resp = web.json_response({
            "from": frm, "count": len(results),
            "beacons": [result_json(r) for r in results]})
        if len(results) == capped:
            # every requested round exists: beacons are immutable, the
            # window can never change — CDN-cacheable like /public/{n}
            resp.headers["ETag"] = f'"span-{frm}-{len(results)}"'
            resp.headers["Cache-Control"] = \
                "public, max-age=31536000, immutable"
        else:
            resp.headers["Cache-Control"] = "no-store"
        return resp

    async def _handle_info(self, request: web.Request) -> web.Response:
        try:
            info = await self._get_info()
        except ClientError as e:
            return web.json_response({"error": str(e)}, status=503)
        return web.json_response({
            "public_key": info.public_key.to_bytes().hex(),
            "period": info.period,
            "genesis_time": info.genesis_time,
            "group_hash": info.group_hash.hex(),
            "hash": info.hash().hex(),
        })

    async def _handle_checkpoint(self, request: web.Request) -> web.Response:
        """Latest signed checkpoint (ISSUE 17): the O(1) trust-bootstrap
        anchor for catching-up VerifyingClients. 404 while no checkpoint
        has been recovered yet or the backing client has no checkpoint
        surface (e.g. a relay over a plain HTTP upstream without one)."""
        from ..client.checkpoint import checkpoint_json

        get_ckpt = getattr(self._client, "get_checkpoint", None)
        if get_ckpt is None:
            return web.json_response(
                {"error": "checkpoints not available"}, status=404)
        try:
            ckpt = await get_ckpt()
        except ClientError as e:
            return web.json_response({"error": str(e)}, status=404)
        resp = web.json_response(checkpoint_json(ckpt))
        # a checkpoint is immutable once issued, but "latest" moves every
        # interval — revalidate like /public/latest
        resp.headers["ETag"] = f'"ckpt-{ckpt.round}"'
        resp.headers["Cache-Control"] = "no-cache"
        return resp

    async def _handle_health(self, request: web.Request) -> web.Response:
        """Current vs expected round (http/server.go:351)."""
        try:
            info = await self._get_info()
        except ClientError as e:
            return web.json_response({"error": str(e)}, status=503)
        expected = time_math.current_round(
            int(self._clock.now()), info.period, info.genesis_time)
        current = await self._head_round()
        body = {"current": current, "expected": expected}
        status = 200 if current + 1 >= expected else 500
        return web.json_response(body, status=status)

    async def _head_round(self) -> int:
        """Best known chain head: the watch-loop tip, else one fetch."""
        if self._latest is not None:
            return self._latest.round
        try:
            return (await self._client.get(0)).round
        except ClientError:
            return 0

    async def _chain_health(self):
        """(snapshot, info) with the health gauges re-evaluated against
        the wall clock — the pull half of obs/health: a fully stalled
        chain (group lost threshold, peer died) stores nothing, so
        probes and scrapes must drive head-lag and the missed-round
        counter. Raises ClientError while there is no chain info yet
        (pre-DKG / relay origin down)."""
        from ..obs.health import HEALTH

        info = await self._get_info()
        head = await self._head_round()
        HEALTH.observe_chain(self._clock.now(), info.period,
                             info.genesis_time, head)
        # on-demand incident sample (ISSUE 15, same pull model): a
        # fully stalled chain stores nothing, so probes must drive the
        # missed-round/readiness detectors too (rate-limited inside)
        from ..obs.incident import INCIDENTS

        INCIDENTS.poll(self._clock.now(), info.period)
        snap = HEALTH.snapshot()
        snap["period"] = info.period
        return snap, info

    async def _handle_healthz(self, request: web.Request) -> web.Response:
        """Chain-health SLO surface (ISSUE 6): head/lag/missed/SLO
        snapshot; 200 while the head lags by at most
        DRAND_TPU_READY_MAX_LAG rounds, 503 otherwise (and while no
        chain info exists yet)."""
        from ..obs.health import READY_MAX_LAG, HEALTH, is_ready

        try:
            snap, _ = await self._chain_health()
        except ClientError as e:
            body = HEALTH.snapshot()
            body.update(status="no_chain", error=str(e))
            return web.json_response(body, status=503)
        ok = is_ready(snap)
        snap["status"] = "ok" if ok else "lagging"
        snap["max_lag"] = READY_MAX_LAG
        return web.json_response(snap, status=200 if ok else 503)

    # ------------------------------------------------------------ timelock
    async def _handle_timelock_submit(self, request: web.Request
                                      ) -> web.Response:
        """POST /timelock: accept a ciphertext locked to a future round
        into the vault. Body = the client envelope JSON
        (client/timelock.encrypt_to_round). 202 with the status record;
        400 on validation failure, 503 while the chain is unknown."""
        from ..timelock.service import TimelockError

        try:
            envelope = await request.json()
        except (ValueError, UnicodeDecodeError):
            return web.json_response({"error": "body is not JSON"},
                                     status=400)
        try:
            rec = await self._timelock.submit(envelope)
        except TimelockError as e:
            msg = str(e)
            status = 503 if "chain info unavailable" in msg else 400
            return web.json_response({"error": msg}, status=status)
        return web.json_response(rec, status=202)

    async def _handle_timelock_status(self, request: web.Request
                                      ) -> web.Response:
        """GET /timelock/{id}: the ciphertext's status record. Opened
        and rejected records are IMMUTABLE — served with an ETag and
        Cache-Control: immutable so a CDN can absorb result polling the
        same way it absorbs /public/{round}; pending records are
        no-store (they change at the round boundary)."""
        token = request.match_info["id"]
        rec = await self._timelock.status(token)
        if rec is None:
            return web.json_response({"error": "unknown ciphertext id"},
                                     status=404)
        if rec["status"] == "pending":
            resp = web.json_response(rec)
            resp.headers["Cache-Control"] = "no-store"
            return resp
        etag = f'"tl-{rec["id"]}-{rec["status"]}"'
        if request.headers.get("If-None-Match") == etag:
            return web.Response(status=304, headers={
                "ETag": etag,
                "Cache-Control": "public, max-age=31536000, immutable"})
        resp = web.json_response(rec)
        resp.headers["ETag"] = etag
        resp.headers["Cache-Control"] = \
            "public, max-age=31536000, immutable"
        return resp

    @staticmethod
    def _tl_frame(proto: str, rec: dict) -> bytes:
        """One open-notify frame from a status record."""
        payload = json.dumps({"id": rec["id"], "status": rec["status"],
                              "round": rec["round"]}).encode()
        return (fanout.sse_frame(rec["round"], payload)
                if proto == fanout.PROTO_SSE
                else fanout.ndjson_frame(payload))

    async def _handle_timelock_watch(self, request: web.Request
                                     ) -> web.StreamResponse:
        """GET /timelock (stream Accept): open-notify push — "tell me
        when my ciphertext opens" (``?id=<token>``) without polling
        ``GET /timelock/{id}``; the frame is ``{"id","status","round"}``
        and a token-scoped stream ENDS after delivering its event (the
        row is immutable — there is nothing more to say). Without
        ``?id=`` the stream is the firehose: every decided ciphertext
        THIS worker opens (the firehose is per-process — on a
        multi-worker relay an operator watching a partitioned sweep
        drain should tail each worker, or poll pending_count).
        Shedding (429 at the shared watcher cap, disconnect on queue
        overflow) and protocol negotiation are inherited from the
        /public/latest push tier.

        Multi-worker delivery: a ``?id=`` watcher's connection lands on
        an ARBITRARY worker (SO_REUSEPORT), but the open for its token
        commits in exactly one — the sole sweeper (shared-SQLite mode)
        or the shard owner (partitioned segment mode). When this worker
        is not that one, the hub wait is backstopped by polling the
        SHARED vault every ``DRAND_TPU_TIMELOCK_WATCH_POLL`` seconds
        (decided rows are visible to every worker through the shared
        store), so the watcher is notified within one poll interval of
        the commit instead of hanging forever."""
        proto = self._stream_proto(request)
        if proto is None:
            return web.json_response(
                {"error": "stream endpoint: set Accept: "
                          "text/event-stream or application/x-ndjson "
                          "(POST submits a ciphertext)"}, status=400)
        # both stream legs share one fd budget — the cap is per worker,
        # not per endpoint
        if (self._hub.watcher_count()
                + self._tl_hub.watcher_count()) >= self._max_watchers:
            return self._shed_response()
        token = request.query.get("id")
        poll = None
        if token is not None and not (
                self._timelock_sweep
                and self._timelock.opens_locally(token)):
            poll = self._tl_watch_poll
        resp = web.StreamResponse()
        resp.headers["Content-Type"] = (
            "text/event-stream" if proto == fanout.PROTO_SSE
            else "application/x-ndjson")
        resp.headers["Cache-Control"] = "no-store"
        resp.headers["Vary"] = "Accept"
        resp.headers["X-Accel-Buffering"] = "no"
        resp.headers["X-Drand-Worker"] = str(os.getpid())
        # subscribe BEFORE the snapshot probe: an open committing
        # between the two lands either in the snapshot or the queue,
        # never in neither
        sub = self._tl_hub.subscribe(proto, token)
        try:
            await resp.prepare(request)
            if token is not None:
                rec = await self._timelock.status(token)
                if rec is not None and rec["status"] != "pending":
                    await resp.write(self._tl_frame(proto, rec))
                    await resp.write_eof()
                    return resp
            while True:
                if poll is None:
                    item = await sub.next()
                else:
                    # another process owns this token's open: race the
                    # (possible, if an opportunistic local sweep gets
                    # there first) hub event against a shared-vault
                    # poll — whichever decides first ends the stream.
                    # The poll also self-heals a lost hub wakeup.
                    try:
                        item = await asyncio.wait_for(sub.next(),
                                                      timeout=poll)
                    except asyncio.TimeoutError:
                        rec = await self._timelock.status(token)
                        if rec is None or rec["status"] == "pending":
                            continue
                        item = (rec["round"],
                                self._tl_frame(proto, rec))
                if item is None:
                    break  # shed as a slow consumer, or server drain
                _, frame = item
                await resp.write(frame)
                if sub.token is not None:
                    break  # the one event this watcher waited for
            await resp.write_eof()
        except (ConnectionResetError, ConnectionError):
            pass  # the client went away mid-stream; nothing to salvage
        finally:
            self._tl_hub.unsubscribe(sub)
        return resp

    async def _handle_readyz(self, request: web.Request) -> web.Response:
        """Readiness: chain info servable (the DKG-complete signal at
        this layer — a relay has no DKG, and a daemon cannot serve info
        before its DKG finished) AND head-lag within bound. The
        daemon-recorded dkg_complete flag rides along for operators."""
        from ..obs.health import READY_MAX_LAG, is_ready

        try:
            snap, _ = await self._chain_health()
        except ClientError as e:
            return web.json_response(
                {"ready": False, "reason": f"no chain info: {e}"},
                status=503)
        ready = is_ready(snap)
        snap["ready"] = ready
        snap["max_lag"] = READY_MAX_LAG
        if not ready:
            snap["reason"] = (f"head lag {snap['lag_rounds']} > "
                              f"{READY_MAX_LAG} rounds")
        return web.json_response(snap, status=200 if ready else 503)
