"""Opt-in debug/profiling endpoints.

Reference: metrics/pprof/pprof.go:13-24 (profile/symbol/trace mux, opt-in
via WithProfile) and the /debug/gc handler (metrics/metrics.go:256). The
Python analogues: cProfile for CPU profiles, per-thread stack dumps, gc
stats, and — when jax is loaded — the JAX profiler for device traces.

    GET /debug/pprof/profile?seconds=5   cProfile over the window (text)
    GET /debug/pprof/stacks              every thread's current stack
    GET /debug/gc                        run a collection, report counts
    GET /debug/jax/trace?seconds=2       JAX device trace -> path on disk

The round-timeline endpoint is ALWAYS on (span recording is a dict
append — there is no profiling cost to gate):

    GET /debug/trace/rounds?n=K          last K round traces (obs/trace.py)
"""

from __future__ import annotations

import asyncio
import cProfile
import gc
import io
import pstats
import sys
import tempfile
import traceback

from aiohttp import web


def add_debug_routes(app: web.Application) -> None:
    app.add_routes([
        web.get("/debug/pprof/profile", _profile),
        web.get("/debug/pprof/stacks", _stacks),
        web.get("/debug/gc", _gc),
        web.get("/debug/jax/trace", _jax_trace),
    ])


def add_trace_routes(app: web.Application) -> None:
    """The always-on introspection surface: round timelines, engine
    state, the threshold flight recorder and the incident engine (all
    dict reads — no profiling cost to gate)."""
    app.add_routes([
        web.get("/debug/trace/rounds", _trace_rounds),
        web.get("/debug/engine", _engine_state),
        web.get("/debug/flight/rounds", _flight_rounds),
        web.get("/debug/flight/dkg", _flight_dkg),
        web.get("/debug/incidents", _incidents),
        web.get("/debug/incidents/{id}", _incident_bundle),
        web.get("/debug/support-bundle", _support_bundle),
        web.get("/debug/remediation", _remediation),
    ])


async def _trace_rounds(request: web.Request) -> web.Response:
    """The last n completed round timelines from the in-process tracer
    ring — `drand util trace` pretty-prints this payload.

    ``n`` is untrusted public input, validated by the shared
    ``obs.query.ring_n`` helper (plain base-10 only, clamped to
    [1, ring size]; anything else 400s)."""
    from ..obs.query import ring_n
    from ..obs.trace import TRACER

    n = ring_n(request.query.get("n"), default=8, cap=TRACER.max_rounds)
    if n is None:
        return web.json_response({"error": "bad n"}, status=400)
    return web.json_response({"rounds": TRACER.rounds(n)})


async def _flight_rounds(request: web.Request) -> web.Response:
    """The flight recorder's per-round partial-arrival records
    (`drand util flight` renders the rounds × nodes matrix from this).
    ``n`` validates via the shared obs.query.ring_n helper."""
    from ..obs.flight import FLIGHT
    from ..obs.query import ring_n

    n = ring_n(request.query.get("n"), default=16, cap=FLIGHT.max_rounds)
    if n is None:
        return web.json_response({"error": "bad n"}, status=400)
    return web.json_response({"rounds": FLIGHT.rounds(n),
                              "peers": FLIGHT.peers(),
                              "reach": FLIGHT.reachability()})


async def _incidents(request: web.Request) -> web.Response:
    """The incident engine's summaries, most recent first (ISSUE 15):
    what fired, when, at what severity, open/closed. ``n`` validates
    via the shared obs.query.ring_n helper like the other ring
    routes."""
    from ..obs.incident import INCIDENTS
    from ..obs.query import ring_n

    n = ring_n(request.query.get("n"), default=32,
               cap=INCIDENTS.max_incidents)
    if n is None:
        return web.json_response({"error": "bad n"}, status=400)
    return web.json_response({"incidents": INCIDENTS.incidents(n),
                              "active": INCIDENTS.active_count(),
                              "samples": len(INCIDENTS.ring)})


async def _remediation(request: web.Request) -> web.Response:
    """The auto-remediation plane (ISSUE 16): engine mode (dry-run vs
    live), the action budget, active playbooks + cooldowns, and the
    last ``n`` remediation-ledger entries (`drand-tpu util remediate`
    renders this). ``n`` validates via the shared obs.query.ring_n
    helper like every other ring route."""
    from ..obs.query import ring_n
    from ..obs.remediate import ENGINE

    n = ring_n(request.query.get("n"), default=32, cap=ENGINE.ledger_max)
    if n is None:
        return web.json_response({"error": "bad n"}, status=400)
    return web.json_response(ENGINE.status(n))


async def _incident_bundle(request: web.Request) -> web.Response:
    """One incident's full forensic bundle — the frozen evidence
    (`drand-tpu util incidents --bundle ID -o FILE` fetches this)."""
    from ..obs.incident import INCIDENTS

    bundle = INCIDENTS.get_bundle(request.match_info["id"])
    if bundle is None:
        return web.json_response({"error": "unknown incident id"},
                                 status=404)
    return web.json_response(bundle)


async def _support_bundle(request: web.Request) -> web.Response:
    """One-shot manual forensic capture — the incident bundle writer
    run on demand (`drand-tpu util support-bundle -o FILE`). Mints no
    incident; just freezes the current evidence."""
    from ..obs.incident import INCIDENTS

    return web.json_response(INCIDENTS.capture_bundle())


async def _flight_dkg(request: web.Request) -> web.Response:
    """The flight recorder's DKG/reshare session timelines — phase
    transitions, per-issuer bundle arrivals, QUAL evolution."""
    from ..obs.flight import FLIGHT

    return web.json_response({"sessions": FLIGHT.dkg.sessions()})


async def _engine_state(request: web.Request) -> web.Response:
    """Engine introspection (ISSUE 6): dispatch policy, the bounded
    fallback ledger, h2c-LRU stats, and — when the device engine has
    been created — backend/device identity plus every graph family's
    per-bucket KAT-gate verdicts. Deliberately never CREATES the
    engine: batch.engine() initializes the jax backend, which can hang
    on a dead tunnel; this endpoint only reports what already exists."""
    from ..crypto import batch
    from ..crypto.hash_to_curve import h2c_cache_info

    payload = {
        "mode": batch._MODE,
        "min_batch": batch._MIN_BATCH,
        "engine_created": batch._ENGINE is not None,
        "fallback_ledger": batch.fallback_ledger(),
        "h2c_cache": h2c_cache_info(),
        "warm_shapes": sorted("/".join(k) for k in batch._WARM_SHAPES),
    }
    if batch._ENGINE is not None:
        try:
            payload["engine"] = batch._ENGINE.introspect()
        except Exception as e:  # noqa: BLE001 — introspection must not 500
            payload["engine_error"] = repr(e)
    return web.json_response(payload)


_PROFILE_LOCK = asyncio.Lock()  # cProfile and the JAX tracer cannot nest


async def _profile(request: web.Request) -> web.Response:
    if _PROFILE_LOCK.locked():
        return web.json_response({"error": "a profile is already running"},
                                 status=409)
    async with _PROFILE_LOCK:
        seconds = min(float(request.query.get("seconds", "5")), 60.0)
        prof = cProfile.Profile()
        prof.enable()
        await asyncio.sleep(seconds)
        prof.disable()
    buf = io.StringIO()
    pstats.Stats(prof, stream=buf).sort_stats("cumulative").print_stats(50)
    return web.Response(text=buf.getvalue(), content_type="text/plain")


async def _stacks(request: web.Request) -> web.Response:
    out = []
    for tid, frame in sys._current_frames().items():
        out.append(f"--- thread {tid} ---")
        out.extend(traceback.format_stack(frame))
    return web.Response(text="\n".join(out), content_type="text/plain")


async def _gc(request: web.Request) -> web.Response:
    collected = gc.collect()
    return web.json_response({
        "collected": collected,
        "counts": gc.get_count(),
        "tracked": len(gc.get_objects()),
    })


async def _jax_trace(request: web.Request) -> web.Response:
    if "jax" not in sys.modules:
        return web.json_response({"error": "jax not loaded in this process"},
                                 status=404)
    import jax

    if _PROFILE_LOCK.locked():
        return web.json_response({"error": "a profile is already running"},
                                 status=409)
    async with _PROFILE_LOCK:
        seconds = min(float(request.query.get("seconds", "2")), 30.0)
        out_dir = tempfile.mkdtemp(prefix="drand-tpu-jaxtrace-")
        jax.profiler.start_trace(out_dir)
        await asyncio.sleep(seconds)
        jax.profiler.stop_trace()
    return web.json_response({"trace_dir": out_dir, "seconds": seconds})
