"""Opt-in debug/profiling endpoints.

Reference: metrics/pprof/pprof.go:13-24 (profile/symbol/trace mux, opt-in
via WithProfile) and the /debug/gc handler (metrics/metrics.go:256). The
Python analogues: cProfile for CPU profiles, per-thread stack dumps, gc
stats, and — when jax is loaded — the JAX profiler for device traces.

    GET /debug/pprof/profile?seconds=5   cProfile over the window (text)
    GET /debug/pprof/stacks              every thread's current stack
    GET /debug/gc                        run a collection, report counts
    GET /debug/jax/trace?seconds=2       JAX device trace -> path on disk

The round-timeline endpoint is ALWAYS on (span recording is a dict
append — there is no profiling cost to gate):

    GET /debug/trace/rounds?n=K          last K round traces (obs/trace.py)
"""

from __future__ import annotations

import asyncio
import cProfile
import gc
import io
import pstats
import sys
import tempfile
import traceback

from aiohttp import web


def add_debug_routes(app: web.Application) -> None:
    app.add_routes([
        web.get("/debug/pprof/profile", _profile),
        web.get("/debug/pprof/stacks", _stacks),
        web.get("/debug/gc", _gc),
        web.get("/debug/jax/trace", _jax_trace),
    ])


def add_trace_routes(app: web.Application) -> None:
    app.add_routes([web.get("/debug/trace/rounds", _trace_rounds)])


async def _trace_rounds(request: web.Request) -> web.Response:
    """The last n completed round timelines from the in-process tracer
    ring — `drand util trace` pretty-prints this payload."""
    from ..obs.trace import TRACER

    try:
        n = int(request.query.get("n", "8"))
    except ValueError:
        return web.json_response({"error": "bad n"}, status=400)
    n = max(1, min(n, TRACER.max_rounds))
    return web.json_response({"rounds": TRACER.rounds(n)})


_PROFILE_LOCK = asyncio.Lock()  # cProfile and the JAX tracer cannot nest


async def _profile(request: web.Request) -> web.Response:
    if _PROFILE_LOCK.locked():
        return web.json_response({"error": "a profile is already running"},
                                 status=409)
    async with _PROFILE_LOCK:
        seconds = min(float(request.query.get("seconds", "5")), 60.0)
        prof = cProfile.Profile()
        prof.enable()
        await asyncio.sleep(seconds)
        prof.disable()
    buf = io.StringIO()
    pstats.Stats(prof, stream=buf).sort_stats("cumulative").print_stats(50)
    return web.Response(text=buf.getvalue(), content_type="text/plain")


async def _stacks(request: web.Request) -> web.Response:
    out = []
    for tid, frame in sys._current_frames().items():
        out.append(f"--- thread {tid} ---")
        out.extend(traceback.format_stack(frame))
    return web.Response(text="\n".join(out), content_type="text/plain")


async def _gc(request: web.Request) -> web.Response:
    collected = gc.collect()
    return web.json_response({
        "collected": collected,
        "counts": gc.get_count(),
        "tracked": len(gc.get_objects()),
    })


async def _jax_trace(request: web.Request) -> web.Response:
    if "jax" not in sys.modules:
        return web.json_response({"error": "jax not loaded in this process"},
                                 status=404)
    import jax

    if _PROFILE_LOCK.locked():
        return web.json_response({"error": "a profile is already running"},
                                 status=409)
    async with _PROFILE_LOCK:
        seconds = min(float(request.query.get("seconds", "2")), 30.0)
        out_dir = tempfile.mkdtemp(prefix="drand-tpu-jaxtrace-")
        jax.profiler.start_trace(out_dir)
        await asyncio.sleep(seconds)
        jax.profiler.stop_trace()
    return web.json_response({"trace_dir": out_dir, "seconds": seconds})
