"""Round-boundary fan-out hub for `/public/latest` watchers (ISSUE 14).

The poll/long-poll serving model costs one handler invocation — or one
held connection slot — per watcher per round. This hub inverts it: the
watch loop publishes each new round ONCE, the payload is serialized
ONCE per stream protocol, and every subscribed connection receives the
pre-framed bytes through its own small bounded queue. N watchers cost
one wakeup per round (per protocol), not N polls.

Backpressure is explicit, never unbounded: a subscriber whose queue is
full when a round is published (a consumer slower than the round
period times the queue depth) is DISCONNECTED — its queue is drained
and replaced with the close sentinel, and `relay_shed_total
{reason="slow_consumer"}` counts it. A beacon is ~300 bytes of JSON at
one frame per period; any real consumer drains instantly, so a full
queue means a dead or wedged peer holding server memory.

Protocol framing (both carry the same `/public/latest` JSON object):

- ``sse``    — ``text/event-stream``: ``id: <round>`` + ``data: <json>``
  frames, consumable by every EventSource client.
- ``ndjson`` — ``application/x-ndjson``: one JSON object per line over
  a chunked response.

Single-threaded by design: subscribe/publish/unsubscribe all run on
the serving event loop (the aiohttp handlers and the watch loop), so
the subscriber set needs no lock — the analyzer's threadshare pass
holds this by construction (nothing here is reached from a thread).
"""

from __future__ import annotations

import asyncio
import json

PROTO_SSE = "sse"
PROTO_NDJSON = "ndjson"

# per-connection queue depth: one beacon frame per round means depth 4
# tolerates a consumer a few periods behind before it is shed
DEFAULT_QUEUE_MAX = 4


def _wakeup_counter(proto: str):
    """Branch-literal proto labels (check_metrics KNOWN_LABEL_VALUES)."""
    from .. import metrics

    if proto == PROTO_SSE:
        return metrics.RELAY_WAKEUPS.labels(proto="sse")
    return metrics.RELAY_WAKEUPS.labels(proto="ndjson")


def sse_frame(round_no: int, payload: bytes) -> bytes:
    """One SSE event; ``id`` carries the round so reconnecting clients
    know where they left off (Last-Event-ID semantics are the client's
    to use — rounds are fetchable by number from `/public/{round}`)."""
    return b"id: %d\ndata: %s\n\n" % (round_no, payload)


def ndjson_frame(payload: bytes) -> bytes:
    return payload + b"\n"


class Subscription:
    """One watcher connection's end of the hub: a bounded queue of
    ``(round, framed bytes)`` items — the round rides along so a
    consumer that wrote a connect-time snapshot can skip a publish of
    the same round that raced in while its response was being
    prepared. ``None`` from :meth:`next` means the stream is over —
    the hub shed this subscriber or the server is draining."""

    __slots__ = ("proto", "_queue", "shed", "token")

    def __init__(self, proto: str, queue_max: int,
                 token: str | None = None):
        self.proto = proto
        # asyncio.Queue(0) means UNBOUNDED — exactly the failure mode
        # this hub exists to rule out; clamp to at least one slot
        self._queue: asyncio.Queue = asyncio.Queue(max(1, queue_max))
        self.shed = False
        # open-notify filter (TimelockNotifyHub): only events for this
        # ciphertext id reach the queue; None = the firehose
        self.token = token

    async def next(self) -> tuple[int, bytes] | None:
        return await self._queue.get()

    def _push(self, item: tuple[int, bytes]) -> bool:
        try:
            self._queue.put_nowait(item)
            return True
        except asyncio.QueueFull:
            return False

    def _close(self) -> None:
        """Drain + sentinel: the consumer wakes to None and ends the
        response. Runs only from the publishing loop (no await between
        the drain and the put, so the consumer cannot interleave a get
        that would let the sentinel put fail)."""
        while True:
            try:
                self._queue.get_nowait()
            except asyncio.QueueEmpty:
                break
        self._queue.put_nowait(None)


class FanoutHub:
    """Publish-once round broadcast to bounded per-connection queues."""

    def __init__(self, queue_max: int = DEFAULT_QUEUE_MAX):
        self._queue_max = queue_max
        self._subs: set[Subscription] = set()
        self.publishes = 0  # rounds published (the per-worker wakeup meter)

    # --------------------------------------------------------- membership
    def watcher_count(self) -> int:
        return len(self._subs)

    def subscribe(self, proto: str) -> Subscription:
        from .. import metrics

        sub = Subscription(proto, self._queue_max)
        self._subs.add(sub)
        metrics.RELAY_WATCHERS.set(len(self._subs))
        return sub

    def unsubscribe(self, sub: Subscription) -> None:
        from .. import metrics

        self._subs.discard(sub)
        metrics.RELAY_WATCHERS.set(len(self._subs))

    # ---------------------------------------------------------- publishing
    def publish(self, result_dict: dict, round_no: int,
                boundary_delay_s: float | None = None) -> int:
        """Fan one round out to every subscriber. The JSON payload is
        serialized once, framed once per protocol that has subscribers,
        and delivered by reference — per-watcher cost is one queue put.
        Returns the number of subscribers reached."""
        from .. import metrics

        self.publishes += 1
        if boundary_delay_s is not None:
            metrics.RELAY_BOUNDARY_DELIVERY.observe(
                max(0.0, boundary_delay_s))
        if not self._subs:
            return 0
        payload = json.dumps(result_dict).encode()
        frames: dict[str, bytes] = {}
        woken: set[str] = set()
        reached = 0
        for sub in list(self._subs):
            frame = frames.get(sub.proto)
            if frame is None:
                frame = (sse_frame(round_no, payload)
                         if sub.proto == PROTO_SSE
                         else ndjson_frame(payload))
                frames[sub.proto] = frame
            if sub._push((round_no, frame)):
                reached += 1
                woken.add(sub.proto)
            else:
                # slow consumer: bounded send queues mean we disconnect,
                # never buffer unboundedly
                sub.shed = True
                sub._close()
                self._subs.discard(sub)
                metrics.RELAY_SHED.labels(reason="slow_consumer").inc()
        metrics.RELAY_WATCHERS.set(len(self._subs))
        for proto in woken:
            _wakeup_counter(proto).inc()
        return reached

    def close_all(self) -> None:
        """Graceful drain: every open stream ends cleanly (the SIGTERM
        path — workers stop accepting, then close watchers)."""
        from .. import metrics

        for sub in list(self._subs):
            sub._close()
        self._subs.clear()
        metrics.RELAY_WATCHERS.set(0)


class TimelockNotifyHub:
    """Open-notify leg on the fan-out model (ISSUE 20): "tell me when
    MY ciphertext opens" without 100k watchers polling
    ``GET /timelock/{id}``. The timelock service pushes
    ``(token, status, round)`` after each chunk's vault COMMITS, so a
    subscriber that re-fetches the status route on notify always sees
    the decided, immutable row.

    Same discipline as :class:`FanoutHub` — single-threaded on the
    serving loop, bounded per-connection queues, slow consumers shed
    (``relay_shed_total{reason="timelock_slow"}``) — but delivery is
    token-KEYED: a subscription watching one id only ever receives that
    id's event (most watchers see exactly one frame, then the stream
    ends). A token-less subscription is the firehose: every decided
    ciphertext, for operators watching a sweep drain."""

    def __init__(self, queue_max: int = DEFAULT_QUEUE_MAX):
        self._queue_max = queue_max
        self._by_token: dict[str, set[Subscription]] = {}
        self._firehose: set[Subscription] = set()
        self.publishes = 0  # decided-ciphertext events published

    # --------------------------------------------------------- membership
    def watcher_count(self) -> int:
        return (sum(len(s) for s in self._by_token.values())
                + len(self._firehose))

    def subscribe(self, proto: str,
                  token: str | None = None) -> Subscription:
        sub = Subscription(proto, self._queue_max, token=token)
        if token is None:
            self._firehose.add(sub)
        else:
            self._by_token.setdefault(token, set()).add(sub)
        self._gauge()
        return sub

    def unsubscribe(self, sub: Subscription) -> None:
        if sub.token is None:
            self._firehose.discard(sub)
        else:
            subs = self._by_token.get(sub.token)
            if subs is not None:
                subs.discard(sub)
                if not subs:
                    del self._by_token[sub.token]
        self._gauge()

    def _gauge(self) -> None:
        from .. import metrics

        metrics.TIMELOCK_WATCHERS.set(self.watcher_count())

    # ---------------------------------------------------------- publishing
    def publish_open(self, events: list[tuple[str, str, int]]) -> int:
        """Push a committed chunk's decided ciphertexts to whoever is
        watching them: ``(token, status, round)`` per event. Framing is
        per event + protocol (events go to DIFFERENT subscribers, so
        there is no shared payload to amortize the way round fan-out
        has); per-event cost without watchers is two dict probes.
        Returns the number of subscribers reached."""
        from .. import metrics

        reached = 0
        shed: list[Subscription] = []
        for token, status, round_no in events:
            self.publishes += 1
            if status == "opened":
                metrics.TIMELOCK_NOTIFY.labels(event="opened").inc()
            else:
                metrics.TIMELOCK_NOTIFY.labels(event="rejected").inc()
            watchers = self._by_token.get(token)
            if not watchers and not self._firehose:
                continue
            payload = json.dumps({"id": token, "status": status,
                                  "round": round_no}).encode()
            frames: dict[str, bytes] = {}
            targets = list(watchers or ())
            targets.extend(self._firehose)
            for sub in targets:
                if sub.shed:
                    # shed by an EARLIER event in this batch (its slot
                    # already holds the close sentinel) — one shed, one
                    # counter increment, per connection
                    continue
                frame = frames.get(sub.proto)
                if frame is None:
                    frame = (sse_frame(round_no, payload)
                             if sub.proto == PROTO_SSE
                             else ndjson_frame(payload))
                    frames[sub.proto] = frame
                if sub._push((round_no, frame)):
                    reached += 1
                else:
                    sub.shed = True
                    sub._close()
                    shed.append(sub)
                    metrics.RELAY_SHED.labels(
                        reason="timelock_slow").inc()
        for sub in shed:
            self.unsubscribe(sub)
        return reached

    def close_all(self) -> None:
        from .. import metrics

        for subs in list(self._by_token.values()):
            for sub in list(subs):
                sub._close()
        for sub in list(self._firehose):
            sub._close()
        self._by_token.clear()
        self._firehose.clear()
        metrics.TIMELOCK_WATCHERS.set(0)
