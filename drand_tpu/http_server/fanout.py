"""Round-boundary fan-out hub for `/public/latest` watchers (ISSUE 14).

The poll/long-poll serving model costs one handler invocation — or one
held connection slot — per watcher per round. This hub inverts it: the
watch loop publishes each new round ONCE, the payload is serialized
ONCE per stream protocol, and every subscribed connection receives the
pre-framed bytes through its own small bounded queue. N watchers cost
one wakeup per round (per protocol), not N polls.

Backpressure is explicit, never unbounded: a subscriber whose queue is
full when a round is published (a consumer slower than the round
period times the queue depth) is DISCONNECTED — its queue is drained
and replaced with the close sentinel, and `relay_shed_total
{reason="slow_consumer"}` counts it. A beacon is ~300 bytes of JSON at
one frame per period; any real consumer drains instantly, so a full
queue means a dead or wedged peer holding server memory.

Protocol framing (both carry the same `/public/latest` JSON object):

- ``sse``    — ``text/event-stream``: ``id: <round>`` + ``data: <json>``
  frames, consumable by every EventSource client.
- ``ndjson`` — ``application/x-ndjson``: one JSON object per line over
  a chunked response.

Single-threaded by design: subscribe/publish/unsubscribe all run on
the serving event loop (the aiohttp handlers and the watch loop), so
the subscriber set needs no lock — the analyzer's threadshare pass
holds this by construction (nothing here is reached from a thread).
"""

from __future__ import annotations

import asyncio
import json

PROTO_SSE = "sse"
PROTO_NDJSON = "ndjson"

# per-connection queue depth: one beacon frame per round means depth 4
# tolerates a consumer a few periods behind before it is shed
DEFAULT_QUEUE_MAX = 4


def _wakeup_counter(proto: str):
    """Branch-literal proto labels (check_metrics KNOWN_LABEL_VALUES)."""
    from .. import metrics

    if proto == PROTO_SSE:
        return metrics.RELAY_WAKEUPS.labels(proto="sse")
    return metrics.RELAY_WAKEUPS.labels(proto="ndjson")


def sse_frame(round_no: int, payload: bytes) -> bytes:
    """One SSE event; ``id`` carries the round so reconnecting clients
    know where they left off (Last-Event-ID semantics are the client's
    to use — rounds are fetchable by number from `/public/{round}`)."""
    return b"id: %d\ndata: %s\n\n" % (round_no, payload)


def ndjson_frame(payload: bytes) -> bytes:
    return payload + b"\n"


class Subscription:
    """One watcher connection's end of the hub: a bounded queue of
    ``(round, framed bytes)`` items — the round rides along so a
    consumer that wrote a connect-time snapshot can skip a publish of
    the same round that raced in while its response was being
    prepared. ``None`` from :meth:`next` means the stream is over —
    the hub shed this subscriber or the server is draining."""

    __slots__ = ("proto", "_queue", "shed")

    def __init__(self, proto: str, queue_max: int):
        self.proto = proto
        # asyncio.Queue(0) means UNBOUNDED — exactly the failure mode
        # this hub exists to rule out; clamp to at least one slot
        self._queue: asyncio.Queue = asyncio.Queue(max(1, queue_max))
        self.shed = False

    async def next(self) -> tuple[int, bytes] | None:
        return await self._queue.get()

    def _push(self, item: tuple[int, bytes]) -> bool:
        try:
            self._queue.put_nowait(item)
            return True
        except asyncio.QueueFull:
            return False

    def _close(self) -> None:
        """Drain + sentinel: the consumer wakes to None and ends the
        response. Runs only from the publishing loop (no await between
        the drain and the put, so the consumer cannot interleave a get
        that would let the sentinel put fail)."""
        while True:
            try:
                self._queue.get_nowait()
            except asyncio.QueueEmpty:
                break
        self._queue.put_nowait(None)


class FanoutHub:
    """Publish-once round broadcast to bounded per-connection queues."""

    def __init__(self, queue_max: int = DEFAULT_QUEUE_MAX):
        self._queue_max = queue_max
        self._subs: set[Subscription] = set()
        self.publishes = 0  # rounds published (the per-worker wakeup meter)

    # --------------------------------------------------------- membership
    def watcher_count(self) -> int:
        return len(self._subs)

    def subscribe(self, proto: str) -> Subscription:
        from .. import metrics

        sub = Subscription(proto, self._queue_max)
        self._subs.add(sub)
        metrics.RELAY_WATCHERS.set(len(self._subs))
        return sub

    def unsubscribe(self, sub: Subscription) -> None:
        from .. import metrics

        self._subs.discard(sub)
        metrics.RELAY_WATCHERS.set(len(self._subs))

    # ---------------------------------------------------------- publishing
    def publish(self, result_dict: dict, round_no: int,
                boundary_delay_s: float | None = None) -> int:
        """Fan one round out to every subscriber. The JSON payload is
        serialized once, framed once per protocol that has subscribers,
        and delivered by reference — per-watcher cost is one queue put.
        Returns the number of subscribers reached."""
        from .. import metrics

        self.publishes += 1
        if boundary_delay_s is not None:
            metrics.RELAY_BOUNDARY_DELIVERY.observe(
                max(0.0, boundary_delay_s))
        if not self._subs:
            return 0
        payload = json.dumps(result_dict).encode()
        frames: dict[str, bytes] = {}
        woken: set[str] = set()
        reached = 0
        for sub in list(self._subs):
            frame = frames.get(sub.proto)
            if frame is None:
                frame = (sse_frame(round_no, payload)
                         if sub.proto == PROTO_SSE
                         else ndjson_frame(payload))
                frames[sub.proto] = frame
            if sub._push((round_no, frame)):
                reached += 1
                woken.add(sub.proto)
            else:
                # slow consumer: bounded send queues mean we disconnect,
                # never buffer unboundedly
                sub.shed = True
                sub._close()
                self._subs.discard(sub)
                metrics.RELAY_SHED.labels(reason="slow_consumer").inc()
        metrics.RELAY_WATCHERS.set(len(self._subs))
        for proto in woken:
            _wakeup_counter(proto).inc()
        return reached

    def close_all(self) -> None:
        """Graceful drain: every open stream ends cleanly (the SIGTERM
        path — workers stop accepting, then close watchers)."""
        from .. import metrics

        for sub in list(self._subs):
            sub._close()
        self._subs.clear()
        metrics.RELAY_WATCHERS.set(0)
