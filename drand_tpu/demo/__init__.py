"""Demo orchestrator: spawn a real local network and exercise it.

Reference: demo/lib/orchestrator.go:61 — spawns N daemon processes, runs
the DKG, checks beacons every period by querying every node and
independently re-verifying the signature chain (incl. over plain HTTP),
kills/restarts nodes, and runs a resharing. `python -m drand_tpu.demo`.
"""
