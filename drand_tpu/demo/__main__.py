"""Run the demo orchestration: a real local drand-tpu network.

    python -m drand_tpu.demo --nodes 4 --threshold 3 --period 3 \
        [--rounds 5] [--kill-one] [--workdir DIR]

Spawns N daemons (subprocesses, real gRPC), runs the DKG through the
control plane, waits for beacons, verifies every node agrees and every
signature checks out against the distributed key (independently, over
HTTP), optionally kills and restarts a node mid-run, then shuts down.
Exit code 0 = every check passed. Reference: demo/lib/orchestrator.go.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import time
import urllib.request


def log(*a):
    print("[demo]", *a, flush=True)


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def cli_env():
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    return env


def cli(*args, timeout=120):
    return subprocess.run([sys.executable, "-m", "drand_tpu.cli", *args],
                         capture_output=True, text=True, timeout=timeout,
                         env=cli_env())


class DemoNode:
    def __init__(self, i: int, workdir: str):
        self.i = i
        self.folder = os.path.join(workdir, f"node{i}")
        self.rpc = free_port()
        self.ctl = free_port()
        self.http = free_port()
        self.addr = f"127.0.0.1:{self.rpc}"
        self.proc: subprocess.Popen | None = None

    def keygen(self):
        out = cli("generate-keypair", "--folder", self.folder, self.addr)
        if out.returncode != 0:
            raise RuntimeError(f"keygen failed: {out.stderr}")

    def start(self, dkg_timeout: float):
        logfile = open(os.path.join(self.folder, "daemon.log"), "a")
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "drand_tpu.cli", "start",
             "--folder", self.folder, "--control", str(self.ctl),
             "--public-listen", f"127.0.0.1:{self.http}",
             "--dkg-timeout", str(dkg_timeout)],
            stdout=logfile, stderr=subprocess.STDOUT, env=cli_env())
        deadline = time.time() + 45
        while time.time() < deadline:
            ping = cli("util", "ping", "--control", str(self.ctl), timeout=10)
            if ping.returncode == 0 and "pong" in ping.stdout:
                return
            time.sleep(0.3)
        raise TimeoutError(f"daemon {self.addr} did not start")

    def kill(self):
        if self.proc is not None:
            self.proc.send_signal(signal.SIGKILL)
            self.proc.wait(timeout=10)
            self.proc = None

    def get(self, path: str):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{self.http}{path}", timeout=10) as r:
            return json.loads(r.read())


def verify_round(pub_hex: str, beacon: dict) -> bool:
    """Independent verification against the distributed key (the demo's
    CheckCurrentBeacon analogue, orchestrator.go:267-338)."""
    from drand_tpu.chain.beacon import Beacon, verify_beacon, verify_beacon_v2
    from drand_tpu.crypto.curves import PointG1

    pub = PointG1.from_bytes(bytes.fromhex(pub_hex))
    b = Beacon(round=beacon["round"],
               previous_sig=bytes.fromhex(beacon["previous_signature"]),
               signature=bytes.fromhex(beacon["signature"]),
               signature_v2=bytes.fromhex(beacon.get("signature_v2", "")))
    ok = verify_beacon(pub, b)
    if ok and b.is_v2():
        ok = verify_beacon_v2(pub, b)
    return ok


def share_budget(args) -> tuple[str, int]:
    """(CLI --timeout for `share`, orchestrator communicate() timeout):
    the control call must outlive all three DKG phases plus slack, and
    the outer wait must outlive the control call."""
    cli = int(max(45, args.dkg_timeout * 3 + 30))
    return str(cli), max(300, cli + 60)


def run_reshare(args, nodes, workdir, secret_file, pub_hex, group) -> None:
    """Reshare plan (orchestrator.go:398 RunResharing): add K fresh nodes,
    run the resharing through the control plane, cross the transition, and
    verify the distributed key is UNCHANGED while the group grew."""
    import json as _json

    k = args.reshare_add
    new_n = len(nodes) + k
    new_thr = max(args.threshold + k // 2, new_n // 2 + 1)
    share_timeout, outer_timeout = share_budget(args)
    log(f"resharing to {new_n} nodes (threshold {new_thr})...")
    joiners = [DemoNode(len(nodes) + j, workdir) for j in range(k)]
    for j in joiners:
        j.keygen()
        j.start(args.dkg_timeout)
    group_file = os.path.join(workdir, "old_group.json")
    with open(group_file, "w") as f:
        _json.dump(group, f)
    procs = [subprocess.Popen(
        [sys.executable, "-m", "drand_tpu.cli", "share",
         "--control", str(nodes[0].ctl), "--leader", "--reshare",
         "--nodes", str(new_n), "--threshold", str(new_thr),
         "--secret-file", secret_file, "--timeout", share_timeout],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=cli_env())]
    for n in nodes[1:]:
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "drand_tpu.cli", "share",
             "--control", str(n.ctl), "--connect", nodes[0].addr,
             "--reshare", "--secret-file", secret_file, "--timeout", share_timeout],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=cli_env()))
    for j in joiners:
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "drand_tpu.cli", "share",
             "--control", str(j.ctl), "--connect", nodes[0].addr,
             "--reshare", "--from-group", group_file,
             "--secret-file", secret_file, "--timeout", share_timeout],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=cli_env()))
    outs = [sp.communicate(timeout=outer_timeout) for sp in procs]
    for sp, (so, se) in zip(procs, outs):
        if sp.returncode != 0:
            raise RuntimeError(f"reshare share failed:\n{so}\n{se}")
    new_group = _json.loads(outs[0][0])["group"]
    assert new_group["public_key"][0] == pub_hex, \
        "distributed key changed across reshare!"
    assert len(new_group["nodes"]) == new_n
    log(f"reshare done; key preserved, transition at "
        f"{new_group['transition_time']}")
    # cross the transition and verify a post-transition round on a joiner
    deadline = new_group["transition_time"] + args.period * 3 + 60
    target = None
    while time.time() < deadline:
        try:
            latest = joiners[0].get("/public/latest")
            if latest["round"] and time.time() > new_group["transition_time"]:
                target = latest
                break
        except Exception:
            pass
        time.sleep(1)
    assert target is not None, "joiner never served post-transition rounds"
    assert verify_round(pub_hex, target), "post-transition beacon invalid"
    log(f"post-transition round {target['round']} verified on a joiner")
    nodes.extend(joiners)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="drand-tpu-demo")
    p.add_argument("--nodes", type=int, default=4)
    p.add_argument("--threshold", type=int, default=3)
    p.add_argument("--period", type=int, default=3)
    p.add_argument("--rounds", type=int, default=4)
    p.add_argument("--dkg-timeout", type=float, default=5.0)
    p.add_argument("--kill-one", action="store_true",
                   help="kill + restart one node mid-run")
    p.add_argument("--reshare-add", type=int, default=0, metavar="K",
                   help="after the rounds, reshare to nodes+K members "
                        "(threshold grows by K//2) and verify the chain "
                        "identity survives")
    p.add_argument("--workdir")
    args = p.parse_args(argv)

    workdir = args.workdir or tempfile.mkdtemp(prefix="drand-tpu-demo-")
    log(f"workdir {workdir}")
    nodes = [DemoNode(i, workdir) for i in range(args.nodes)]
    try:
        for n in nodes:
            n.keygen()
            n.start(args.dkg_timeout)
        log(f"{args.nodes} daemons up")

        secret_file = os.path.join(workdir, "secret")
        with open(secret_file, "w") as f:
            f.write("demo-secret-0123456789abcdef0000")

        log("running DKG...")
        share_timeout, outer_timeout = share_budget(args)
        share_procs = []
        leader_args = ["share", "--control", str(nodes[0].ctl), "--leader",
                       "--nodes", str(args.nodes),
                       "--threshold", str(args.threshold),
                       "--period", str(args.period),
                       "--secret-file", secret_file, "--timeout", share_timeout]
        share_procs.append(subprocess.Popen(
            [sys.executable, "-m", "drand_tpu.cli", *leader_args],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=cli_env()))
        for n in nodes[1:]:
            share_procs.append(subprocess.Popen(
                [sys.executable, "-m", "drand_tpu.cli", "share",
                 "--control", str(n.ctl), "--connect", nodes[0].addr,
                 "--secret-file", secret_file, "--timeout", share_timeout],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
                env=cli_env()))
        outs = [sp.communicate(timeout=outer_timeout) for sp in share_procs]
        for sp, (so, se) in zip(share_procs, outs):
            if sp.returncode != 0:
                raise RuntimeError(f"share failed:\n{so}\n{se}")
        group = json.loads(outs[0][0])["group"]
        pub_hex = group["public_key"][0]
        log(f"DKG done; group key {pub_hex[:16]}… genesis "
            f"{group['genesis_time']}")

        log("waiting for beacons...")
        deadline = time.time() + 120
        while time.time() < deadline:
            try:
                if nodes[0].get("/public/latest")["round"] >= 1:
                    break
            except Exception:
                pass
            time.sleep(1)

        killed = None
        recovering: set = set()
        for target in range(1, args.rounds + 1):
            deadline = time.time() + 60
            while time.time() < deadline:
                try:
                    if nodes[0].get("/public/latest")["round"] >= target:
                        break
                except Exception:
                    pass
                time.sleep(0.5)
            checks = []
            for n in nodes:
                if n.proc is None:
                    continue
                # ONLY a freshly-restarted node gets a catch-up window;
                # everyone else must serve the round on the first try
                # (a healthy-looking node that cannot is the bug this
                # check exists to catch). A dead process fails fast.
                fetch_deadline = time.time() + (45 if n in recovering else 0)
                while True:
                    try:
                        b = n.get(f"/public/{target}")
                        recovering.discard(n)
                        break
                    except Exception:
                        if n.proc is not None and n.proc.poll() is not None:
                            raise RuntimeError(
                                f"daemon {n.addr} exited rc="
                                f"{n.proc.returncode} mid-run")
                        if time.time() > fetch_deadline:
                            raise
                        time.sleep(1)
                checks.append((n.addr, b["randomness"],
                               verify_round(pub_hex, b)))
            vals = {c[1] for c in checks}
            oks = all(c[2] for c in checks)
            log(f"round {target}: {len(checks)} nodes agree={len(vals) == 1} "
                f"signatures_valid={oks}")
            if len(vals) != 1 or not oks:
                raise RuntimeError(f"round {target} check failed: {checks}")
            if args.kill_one and target == 2 and killed is None:
                killed = nodes[-1]
                log(f"killing {killed.addr}")
                killed.kill()
            if args.kill_one and target == args.rounds - 1 and killed is not None:
                log(f"restarting {killed.addr}")
                killed.start(args.dkg_timeout)
                recovering.add(killed)
                killed = None

        if args.reshare_add:
            run_reshare(args, nodes, workdir, secret_file, pub_hex, group)

        log("all checks passed")
        for n in nodes:
            if n.proc is not None:
                cli("stop", "--control", str(n.ctl), timeout=20)
        return 0
    finally:
        for n in nodes:
            try:
                n.kill()
            except Exception:
                pass
        if args.workdir is None:
            shutil.rmtree(workdir, ignore_errors=True)


if __name__ == "__main__":
    raise SystemExit(main())
