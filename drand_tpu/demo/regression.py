"""Regression rig: run the orchestration plans and emit a markdown report.

Reference: demo/regression/main.go:14-22 — plans {startup, reshare,
upgrade} over a mixed-version cluster, with a markdown report for CI. A
second version directory can be supplied for the mixed-version upgrade
plan (`--candidate /path/to/other/checkout`): half the daemons run from
the candidate tree, exercising wire/protocol compatibility across
versions; with a single tree the plan still exercises rolling restarts.

    python -m drand_tpu.demo.regression [--report report.md]
"""

from __future__ import annotations

import argparse
import subprocess
import sys
import time

PLANS = [
    ("startup", ["--nodes", "3", "--threshold", "2", "--period", "3",
                 "--rounds", "3"]),
    ("kill-restart", ["--nodes", "3", "--threshold", "2", "--period", "3",
                      "--rounds", "4", "--kill-one"]),
    ("reshare", ["--nodes", "3", "--threshold", "2", "--period", "3",
                 "--rounds", "2", "--reshare-add", "1"]),
    # reference regression scale: n=5, t=4, period 10
    # (demo/regression/main.go:79-81; the period also keeps 6 host-crypto
    # daemons under one core's pairing budget during the reshare)
    ("startup-5", ["--nodes", "5", "--threshold", "4", "--period", "10",
                   "--rounds", "2"]),
    ("reshare-5", ["--nodes", "5", "--threshold", "4", "--period", "10",
                   "--rounds", "2", "--reshare-add", "1",
                   "--dkg-timeout", "12"]),
]


def run_plan(name: str, extra: list[str], env=None) -> tuple[bool, float, str]:
    t0 = time.time()
    proc = subprocess.run(
        [sys.executable, "-m", "drand_tpu.demo", *extra],
        capture_output=True, text=True, timeout=900, env=env)
    return proc.returncode == 0, time.time() - t0, proc.stdout + proc.stderr


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="drand-tpu-regression")
    p.add_argument("--report", default="")
    p.add_argument("--plans", default=",".join(n for n, _ in PLANS))
    args = p.parse_args(argv)
    wanted = set(args.plans.split(","))

    rows = []
    failed = False
    for name, extra in PLANS:
        if name not in wanted:
            continue
        print(f"== plan {name}", flush=True)
        ok, dt, out = run_plan(name, extra)
        rows.append((name, ok, dt))
        if not ok:
            failed = True
            print(out[-4000:], flush=True)
        print(f"== plan {name}: {'PASS' if ok else 'FAIL'} ({dt:.0f}s)",
              flush=True)

    report = ["# drand-tpu regression report", "",
              "| plan | result | seconds |", "|---|---|---|"]
    for name, ok, dt in rows:
        report.append(f"| {name} | {'✅ pass' if ok else '❌ FAIL'} | {dt:.0f} |")
    text = "\n".join(report) + "\n"
    if args.report:
        with open(args.report, "w") as f:
            f.write(text)
    print(text)
    return 1 if failed else 0


if __name__ == "__main__":
    raise SystemExit(main())
