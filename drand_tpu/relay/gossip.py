"""Gossip distribution: flood-pubsub beacon relay with validation.

Reference: lp2p/ — a relay node watches a source and republishes beacons
on a pubsub topic (relaynode.go:48); subscribers VALIDATE before accepting
or re-forwarding (client/validator.go:16-69 rejects future rounds and bad
signatures so invalid data never propagates). libp2p is not in this image,
so the mesh is explicit peers over a grpc.aio "drand.Gossip" service with
hash dedup — the same flood/validate semantics on a static topology.

Delta vs the reference's libp2p gossipsub, for operators:
- NO peer discovery (lp2p uses DHT bootstrap + pubsub peer exchange):
  the mesh topology is the --peers list; adding a relay means telling
  its neighbours. The public-topic interop surface
  (/drand/pubsub/v0.0.0/<chainHash>) therefore cannot be joined — use
  the drand.Public protobuf service (net/protowire.py) for ecosystem
  interop instead.
- Peer scoring/pruning is a BOUNDED analogue of gossipsub v1.1's, not
  the full behavioural score: an ingress SOURCE IP is banned for a
  cooloff window after SCORE_INVALID_LIMIT validation-rejected
  deliveries (attribution by connection source address — gossipsub's
  IP-colocation factor; there is no libp2p peer identity on this
  plane, and a sender-claimed header would let anyone frame a victim),
  and a mesh peer is evicted after SCORE_FAIL_LIMIT consecutive
  CONNECTIVITY failures (application rejections like a remote's own
  cooloff do NOT count), redialed after EVICT_COOLOFF. Co-located
  peers share ban fate (the IP-colocation tradeoff); validation still
  bounds the damage regardless (invalid beacons never forward; hash
  dedup caps amplification at one delivery per peer per message).
- Flood (every message to every peer) instead of mesh-degree-bounded
  gossip: per-message cost is O(peers), the right trade at the handful-
  of-relays scale this deployment targets.
"""

from __future__ import annotations

import asyncio
import functools
import hashlib
import os
import socket

import grpc
import grpc.aio

from ..chain import beacon as chain_beacon
from ..chain import time_math
from ..chain.beacon import Beacon
from ..chain.info import Info
from ..client.interface import Client, ClientError, result_from_beacon
from ..net import wire
from ..obs import flight as obs_flight
from ..obs import trace as obs_trace
from ..utils.aio import spawn
from ..utils.clock import Clock, SystemClock
from ..utils.logging import KVLogger, default_logger

SERVICE = "drand.Gossip"

# per-process secret for the sender tags on the /debug/trace surface —
# stable within a run (same peer -> same tag), worthless offline
_SENDER_TAG_KEY = os.urandom(16)

# scoring bounds (gossipsub v1.1 pruning analogue)
SCORE_INVALID_LIMIT = 20   # validation-rejected deliveries before ban
SCORE_FAIL_LIMIT = 10      # consecutive forward failures before ban
EVICT_COOLOFF = 300.0      # seconds before a banned peer is redialed


class _PeerState:
    __slots__ = ("channel", "fails", "banned_until", "ban_key")

    def __init__(self, channel, ban_key: str = ""):
        self.channel = channel
        self.fails = 0
        self.banned_until = 0.0
        # the peer host in _peer_ip's bare-IP form — the _ip_scores key
        # for the egress ban cross-check, resolved ONCE at add_peer time
        # (a DNS lookup in the per-message forward path would stall the
        # event loop)
        self.ban_key = ban_key


class _IpScore:
    __slots__ = ("invalid", "banned_until")

    def __init__(self):
        self.invalid = 0
        self.banned_until = 0.0


def _peer_ip(grpc_peer: str) -> str:
    """'ipv4:1.2.3.4:567' / 'ipv6:[::1]:8' -> address without the port."""
    if grpc_peer.startswith("ipv6:"):
        body = grpc_peer[5:]
        return body[1:body.rfind("]")] if "[" in body else body
    if ":" in grpc_peer:
        kind, _, rest = grpc_peer.partition(":")
        return rest.rsplit(":", 1)[0] if kind == "ipv4" else grpc_peer
    return grpc_peer


@functools.lru_cache(maxsize=256)
def _resolve_host(host: str) -> str:
    """Configured-peer host -> the bare-IP form _peer_ip yields for the
    same machine, so the egress ban cross-check in _live_channel keys
    the SAME table entries the ingress scorer writes: IPv6 brackets
    stripped, hostnames resolved (first A/AAAA record; called from
    add_peer only — configuration time, never the per-message forward
    path — and cached. Resolution failures fall back to the literal
    host, which then simply never matches an IP-keyed ban, the pre-fix
    behavior)."""
    if host.startswith("[") and host.endswith("]"):
        host = host[1:-1]
    try:
        import ipaddress

        ipaddress.ip_address(host)
        return host  # already a literal IP
    except ValueError:
        pass
    try:
        infos = socket.getaddrinfo(host, None)
        return infos[0][4][0]
    except (OSError, IndexError):
        return host


class GossipNode(Client):
    """One pubsub participant: subscribe/publish beacons for one chain.

    - `serve(listen)` starts the ingress port.
    - `add_peer(addr)` joins a static mesh (both directions flood).
    - `publish(beacon)` injects locally (the relay side feeds this from a
      watched client source).
    - Client surface: `watch()` yields validated incoming beacons; `get`
    returns the best-seen tip (relays keep a window, not the full chain).
    """

    def __init__(self, info: Info, clock: Clock | None = None,
                 logger: KVLogger | None = None, cache_rounds: int = 128):
        self.chain_info = info
        self._clock = clock or SystemClock()
        self._l = logger or default_logger("gossip")
        self._peers: dict[str, _PeerState] = {}
        self._ip_scores: dict[str, _IpScore] = {}
        self._seen: dict[bytes, None] = {}  # insertion-ordered for FIFO evict
        # msg_ids whose validation is in flight: the to_thread hand-off
        # in _accept_beacon suspends between the _seen check and the
        # _seen insert, so without this guard N concurrent deliveries
        # of one flooded beacon would all validate and all re-flood.
        # value = {"round", "max_live" (the running validation's clock
        # snapshot), "retry" (a duplicate saw a fresher clock admit the
        # round — revalidate before giving up)}
        self._inflight: dict[bytes, dict] = {}
        self._cache: dict[int, Beacon] = {}
        self._cache_rounds = cache_rounds
        self._tip = 0
        self._subs: list[asyncio.Queue] = []
        self._server: grpc.aio.Server | None = None
        self.port: int | None = None

    # ------------------------------------------------------------- mesh
    async def serve(self, listen: str) -> None:
        server = grpc.aio.server()
        handlers = {"Publish": grpc.unary_unary_rpc_method_handler(
            self._handle_publish)}
        server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SERVICE, handlers),))
        self.port = server.add_insecure_port(listen)
        await server.start()
        self._server = server

    async def stop(self) -> None:
        if self._server is not None:
            await self._server.stop(0.2)
        for st in self._peers.values():
            if st.channel is not None:
                await st.channel.close()

    def add_peer(self, addr: str) -> None:
        if addr not in self._peers:
            self._peers[addr] = _PeerState(
                grpc.aio.insecure_channel(addr),
                ban_key=_resolve_host(addr.rsplit(":", 1)[0]))

    # ---------------------------------------------------------- scoring
    def _ban_peer(self, addr: str, st: _PeerState, why: str) -> None:
        st.banned_until = self._clock.now() + EVICT_COOLOFF
        st.fails = 0
        if st.channel is not None:
            spawn(st.channel.close())
            st.channel = None
        self._l.warn("gossip", "peer_evicted", peer=addr, why=why,
                     cooloff_s=EVICT_COOLOFF)

    def _live_channel(self, addr: str, st: _PeerState):
        """Peer's channel if not banned; redials after the cooloff. A
        peer whose host is an ingress-banned IP is also skipped (no
        point feeding a co-located flooder)."""
        now = self._clock.now()
        if st.banned_until:
            if now < st.banned_until:
                return None
            st.banned_until = 0.0
            self._l.info("gossip", "peer_redialed", peer=addr)
        # _ip_scores is keyed by ingress source IP: look up the peer's
        # add_peer-time normalized host ('[::1]:port' / hostname peers
        # must not silently never match)
        sc = self._ip_scores.get(st.ban_key)
        if sc is not None and now < sc.banned_until:
            return None
        if st.channel is None:
            st.channel = grpc.aio.insecure_channel(addr)
        return st.channel

    def _ip_banned(self, ip: str) -> bool:
        sc = self._ip_scores.get(ip)
        return sc is not None and self._clock.now() < sc.banned_until

    def _note_invalid(self, ip: str) -> None:
        if not ip:
            return
        sc = self._ip_scores.setdefault(ip, _IpScore())
        if self._clock.now() < sc.banned_until:
            return
        sc.invalid += 1
        if sc.invalid >= SCORE_INVALID_LIMIT:
            sc.invalid = 0
            sc.banned_until = self._clock.now() + EVICT_COOLOFF
            self._l.warn("gossip", "source_ip_banned", ip=ip,
                         cooloff_s=EVICT_COOLOFF)

    # ---------------------------------------------------------- validation
    def _max_live_round(self) -> int:
        """Far-future drift bound (validator.go:16): the clock-expected
        next round. Shared by _validate (reject beyond it) and the
        trace-ring retain window in _accept, so the two cannot diverge."""
        return time_math.current_round(int(self._clock.now()),
                                       self.chain_info.period,
                                       self.chain_info.genesis_time) + 1

    def _validate(self, b: Beacon, max_live: int | None = None) -> bool:
        """lp2p/client/validator.go:16-69: reject far-future rounds and
        invalid signatures BEFORE caching or re-flooding."""
        if b.round > (self._max_live_round() if max_live is None
                      else max_live):
            return False
        ok = chain_beacon.verify_beacon(self.chain_info.public_key, b)
        if ok and b.is_v2():
            ok = chain_beacon.verify_beacon_v2(self.chain_info.public_key, b)
        return ok

    # ------------------------------------------------------------- pubsub
    async def publish(self, b: Beacon) -> None:
        await self._accept(wire.encode(b), validate=True)

    async def _handle_publish(self, request: bytes, context) -> bytes:
        ip = _peer_ip(context.peer() or "")
        if self._ip_banned(ip):
            await context.abort(grpc.StatusCode.PERMISSION_DENIED,
                                "gossip: source is in eviction cooloff")
        tp = obs_trace.traceparent_from_context(context)
        try:
            with obs_trace.TRACER.activate_traceparent(tp):
                await self._accept(request, validate=True, sender=ip)
        except wire.WireError as e:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        return b"{}"

    async def _accept(self, raw: bytes, validate: bool,
                      sender: str = "") -> None:
        msg_id = hashlib.blake2b(raw, digest_size=16).digest()
        if msg_id in self._seen:
            return
        entry = self._inflight.get(msg_id)
        if entry is not None:
            # same bytes, so the SIGNATURE half of the running
            # validation's verdict transfers — but the liveness bound is
            # snapshotted from the clock at arrival, and the round
            # boundary can cross mid-validation (the pairing runs on a
            # worker thread). If the running bound already admits the
            # round, or the round is still future by OUR clock, the
            # duplicate's verdict would match: drop it. Otherwise ask
            # the running call to revalidate with a fresh bound — the
            # flooded copies are this relay's only chance at the round
            # (peers mark the message seen and will not re-send)
            if entry["round"] > entry["max_live"] \
                    and entry["round"] <= self._max_live_round():
                entry["retry"] = True
            return
        msg, _ = wire.decode(raw)
        if not isinstance(msg, Beacon):
            raise wire.WireError("gossip: not a beacon")
        # retain only the plausibly-live window — a replayed burst of
        # historical beacons OR a flood of far-future invalid rounds
        # (not yet validated here) must not evict live timelines from
        # the ring. The lower bound is clock-derived, not just _tip:
        # _tip starts at 0 on a fresh relay, and an ascending replay
        # would keep it one round behind the burst
        max_live = self._max_live_round()
        entry = {"round": msg.round, "max_live": max_live, "retry": False}
        self._inflight[msg_id] = entry
        try:
            while True:
                ring_lo = max(self._tip,
                              max_live - obs_trace.TRACER.max_rounds)
                with obs_trace.TRACER.activate(
                        round_no=msg.round,
                        chain=self.chain_info.genesis_seed,
                        retain=ring_lo <= msg.round <= max_live):
                    await self._accept_beacon(msg, msg_id, raw, validate,
                                              sender, max_live)
                if msg_id in self._seen or not entry["retry"]:
                    return
                # a duplicate arrived after the boundary crossed: its
                # clock admits the round our snapshot rejected. One
                # retry per crossing — the bound is strictly larger
                max_live = self._max_live_round()
                if msg.round > max_live:
                    return
                entry["max_live"] = max_live
                entry["retry"] = False
                # the sender took its invalid strike on the first pass;
                # a retry failure must not charge the same delivery twice
                sender = ""
        finally:
            self._inflight.pop(msg_id, None)

    async def _accept_beacon(self, msg: Beacon, msg_id: bytes, raw: bytes,
                             validate: bool, sender: str,
                             max_live: int | None = None) -> None:
        if validate:
            # a stable per-process KEYED hash, not the raw peer IP: the
            # span lands on the default-on /debug/trace surface, and
            # mesh neighbors (unlike group members) are not public
            # topology. The key blocks offline inversion — an unkeyed
            # 4-byte digest of an IPv4 is brute-forceable in seconds
            sender_tag = hashlib.blake2b(
                sender.encode(), digest_size=4,
                key=_SENDER_TAG_KEY).hexdigest()
            with obs_trace.TRACER.span("gossip_validate", sender=sender_tag,
                                       v2=msg.is_v2()) as sp:
                # pairings off the loop: a mesh node validates every
                # flooded beacon, and the same loop serves the pubsub
                # streams and /healthz
                ok = await asyncio.to_thread(self._validate, msg, max_live)
                sp.attrs["ok"] = ok
            # the gossip hop's flight event: arrival offset + verdict
            # under source="gossip" (same hashed sender tag as the
            # span — mesh neighbours are not public topology). A ring
            # append under one lock, back on the loop after the
            # to_thread verification.
            obs_flight.FLIGHT.note_partial(
                msg.round, index=None, source="gossip",
                verdict="valid" if ok else "invalid",
                now=self._clock.now(), period=self.chain_info.period,
                genesis=self.chain_info.genesis_time, sender=sender_tag)
        else:
            ok = True
        if not ok:
            # do NOT record rejected messages as seen: a beacon dropped for
            # clock skew must be acceptable when it arrives again later
            self._l.warn("gossip", "invalid_beacon_dropped", round=msg.round)
            if sender:
                self._note_invalid(sender)
            return
        self._seen[msg_id] = None
        while len(self._seen) > 4096:  # FIFO eviction (oldest first)
            self._seen.pop(next(iter(self._seen)))
        self._cache[msg.round] = msg
        self._tip = max(self._tip, msg.round)
        for r in list(self._cache):
            if r < self._tip - self._cache_rounds:
                del self._cache[r]
        for q in list(self._subs):
            try:
                q.put_nowait(msg)
            except asyncio.QueueFull:
                pass
        for addr, st in self._peers.items():
            if self._live_channel(addr, st) is not None:
                spawn(self._forward(addr, st, raw))

    async def _forward(self, addr: str, st: _PeerState, raw: bytes) -> None:
        from ..utils.retry import RetryPolicy, retry

        ch = st.channel
        if ch is None:
            return
        # the forward task copied the accept-time trace context, so the
        # round-correlation id rides the mesh hop as gRPC metadata
        md = obs_trace.outbound_metadata()
        try:
            # a gossip hop retries transient connectivity once before
            # charging the peer's fail score (ISSUE 12); answered
            # rejections give up immediately — retrying a remote's own
            # cooloff reject would look like a flood to it. Backoff on
            # the system clock deliberately: the gossip validation
            # clock is a fake in tests and nobody advances it here.
            await retry(
                lambda: ch.unary_unary(f"/{SERVICE}/Publish")(
                    raw, timeout=5.0, metadata=md),
                op="gossip",
                policy=RetryPolicy(attempts=2, base_s=0.05, cap_s=0.25),
                retry_on=(grpc.aio.AioRpcError,),
                giveup=lambda e: e.code() in (
                    grpc.StatusCode.PERMISSION_DENIED,
                    grpc.StatusCode.INVALID_ARGUMENT))
            st.fails = 0
        except grpc.aio.AioRpcError as e:
            self._l.debug("gossip", "forward_failed", to=addr,
                          code=e.code().name)
            # application-level rejections (e.g. the remote's own
            # cooloff) are NOT connectivity failures — counting them
            # would turn one ban into a mutual-ban cascade
            if e.code() in (grpc.StatusCode.PERMISSION_DENIED,
                            grpc.StatusCode.INVALID_ARGUMENT):
                return
            st.fails += 1
            if st.fails >= SCORE_FAIL_LIMIT and not st.banned_until:
                self._ban_peer(addr, st, "unreachable")

    # ------------------------------------------------------------- Client
    async def get(self, round_no: int = 0):
        b = self._cache.get(round_no or self._tip)
        if b is None:
            raise ClientError(f"gossip: round {round_no or self._tip} "
                              f"not in window")
        return result_from_beacon(b)

    async def watch(self):
        q: asyncio.Queue = asyncio.Queue(maxsize=32)
        self._subs.append(q)
        try:
            while True:
                yield result_from_beacon(await q.get())
        finally:
            self._subs.remove(q)

    async def info(self) -> Info:  # Client surface
        return self.chain_info

    def round_at(self, t: float) -> int:
        return time_math.current_round(int(t), self.chain_info.period,
                                       self.chain_info.genesis_time)


class GossipRelay:
    """Relay: watch a client source, publish every beacon into the mesh
    (lp2p/relaynode.go:48)."""

    def __init__(self, source: Client, node: GossipNode):
        self._src = source
        self.node = node
        self._task: asyncio.Task | None = None

    def start(self) -> None:
        self._task = asyncio.ensure_future(self._run())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()

    async def _run(self) -> None:
        from ..utils.retry import RetryPolicy, retry

        # restart rides the retry policy (decorrelated jitter) instead
        # of a raw fixed sleep — the analyzer's retry-sleep rule covers
        # relay/ like net/ and http_server/ (ISSUE 14). System clock on
        # purpose, like the gossip forward path: the gossip validation
        # clock is a per-test fake nobody advances.
        policy = RetryPolicy(attempts=6, base_s=0.5, cap_s=15.0)
        while True:
            try:
                await retry(self._watch_pass, op="gossip", policy=policy)
            except asyncio.CancelledError:
                return
            except Exception:  # noqa: BLE001 — keep relaying
                continue

    async def _watch_pass(self) -> None:
        async for r in self._src.watch():
            await self.node.publish(Beacon(
                round=r.round, previous_sig=r.previous_signature,
                signature=r.signature,
                signature_v2=r.signature_v2))
