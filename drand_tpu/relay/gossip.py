"""Gossip distribution: flood-pubsub beacon relay with validation.

Reference: lp2p/ — a relay node watches a source and republishes beacons
on a pubsub topic (relaynode.go:48); subscribers VALIDATE before accepting
or re-forwarding (client/validator.go:16-69 rejects future rounds and bad
signatures so invalid data never propagates). libp2p is not in this image,
so the mesh is explicit peers over a grpc.aio "drand.Gossip" service with
hash dedup — the same flood/validate semantics on a static topology.

Delta vs the reference's libp2p gossipsub, for operators:
- NO peer discovery (lp2p uses DHT bootstrap + pubsub peer exchange):
  the mesh topology is the --peers list; adding a relay means telling
  its neighbours. The public-topic interop surface
  (/drand/pubsub/v0.0.0/<chainHash>) therefore cannot be joined — use
  the drand.Public protobuf service (net/protowire.py) for ecosystem
  interop instead.
- NO peer scoring/pruning (gossipsub v1.1): a misbehaving peer is
  bounded by validation (invalid beacons never forward; per-message
  hash dedup caps amplification at one delivery per peer per message)
  but stays in the mesh; drop it from --peers to evict.
- Flood (every message to every peer) instead of mesh-degree-bounded
  gossip: per-message cost is O(peers), the right trade at the handful-
  of-relays scale this deployment targets.
"""

from __future__ import annotations

import asyncio
import hashlib

import grpc
import grpc.aio

from ..chain import beacon as chain_beacon
from ..chain import time_math
from ..chain.beacon import Beacon
from ..chain.info import Info
from ..client.interface import Client, ClientError, result_from_beacon
from ..net import wire
from ..utils.clock import Clock, SystemClock
from ..utils.logging import KVLogger, default_logger

SERVICE = "drand.Gossip"


class GossipNode(Client):
    """One pubsub participant: subscribe/publish beacons for one chain.

    - `serve(listen)` starts the ingress port.
    - `add_peer(addr)` joins a static mesh (both directions flood).
    - `publish(beacon)` injects locally (the relay side feeds this from a
      watched client source).
    - Client surface: `watch()` yields validated incoming beacons; `get`
    returns the best-seen tip (relays keep a window, not the full chain).
    """

    def __init__(self, info: Info, clock: Clock | None = None,
                 logger: KVLogger | None = None, cache_rounds: int = 128):
        self.chain_info = info
        self._clock = clock or SystemClock()
        self._l = logger or default_logger("gossip")
        self._peers: dict[str, grpc.aio.Channel] = {}
        self._seen: dict[bytes, None] = {}  # insertion-ordered for FIFO evict
        self._cache: dict[int, Beacon] = {}
        self._cache_rounds = cache_rounds
        self._tip = 0
        self._subs: list[asyncio.Queue] = []
        self._server: grpc.aio.Server | None = None
        self.port: int | None = None

    # ------------------------------------------------------------- mesh
    async def serve(self, listen: str) -> None:
        server = grpc.aio.server()
        handlers = {"Publish": grpc.unary_unary_rpc_method_handler(
            self._handle_publish)}
        server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SERVICE, handlers),))
        self.port = server.add_insecure_port(listen)
        await server.start()
        self._server = server

    async def stop(self) -> None:
        if self._server is not None:
            await self._server.stop(0.2)
        for ch in self._peers.values():
            await ch.close()

    def add_peer(self, addr: str) -> None:
        if addr not in self._peers:
            self._peers[addr] = grpc.aio.insecure_channel(addr)

    # ---------------------------------------------------------- validation
    def _validate(self, b: Beacon) -> bool:
        """lp2p/client/validator.go:16-69: reject far-future rounds and
        invalid signatures BEFORE caching or re-flooding."""
        current = time_math.current_round(int(self._clock.now()),
                                          self.chain_info.period,
                                          self.chain_info.genesis_time)
        if b.round > current + 1:
            return False
        ok = chain_beacon.verify_beacon(self.chain_info.public_key, b)
        if ok and b.is_v2():
            ok = chain_beacon.verify_beacon_v2(self.chain_info.public_key, b)
        return ok

    # ------------------------------------------------------------- pubsub
    async def publish(self, b: Beacon) -> None:
        await self._accept(wire.encode(b), validate=True)

    async def _handle_publish(self, request: bytes, context) -> bytes:
        try:
            await self._accept(request, validate=True)
        except wire.WireError as e:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        return b"{}"

    async def _accept(self, raw: bytes, validate: bool) -> None:
        msg_id = hashlib.blake2b(raw, digest_size=16).digest()
        if msg_id in self._seen:
            return
        msg, _ = wire.decode(raw)
        if not isinstance(msg, Beacon):
            raise wire.WireError("gossip: not a beacon")
        if validate and not self._validate(msg):
            # do NOT record rejected messages as seen: a beacon dropped for
            # clock skew must be acceptable when it arrives again later
            self._l.warn("gossip", "invalid_beacon_dropped", round=msg.round)
            return
        self._seen[msg_id] = None
        while len(self._seen) > 4096:  # FIFO eviction (oldest first)
            self._seen.pop(next(iter(self._seen)))
        self._cache[msg.round] = msg
        self._tip = max(self._tip, msg.round)
        for r in list(self._cache):
            if r < self._tip - self._cache_rounds:
                del self._cache[r]
        for q in list(self._subs):
            try:
                q.put_nowait(msg)
            except asyncio.QueueFull:
                pass
        for addr, ch in self._peers.items():
            asyncio.ensure_future(self._forward(addr, ch, raw))

    async def _forward(self, addr: str, ch: grpc.aio.Channel,
                       raw: bytes) -> None:
        try:
            await ch.unary_unary(f"/{SERVICE}/Publish")(raw, timeout=5.0)
        except grpc.aio.AioRpcError as e:
            self._l.debug("gossip", "forward_failed", to=addr,
                          code=e.code().name)

    # ------------------------------------------------------------- Client
    async def get(self, round_no: int = 0):
        b = self._cache.get(round_no or self._tip)
        if b is None:
            raise ClientError(f"gossip: round {round_no or self._tip} "
                              f"not in window")
        return result_from_beacon(b)

    async def watch(self):
        q: asyncio.Queue = asyncio.Queue(maxsize=32)
        self._subs.append(q)
        try:
            while True:
                yield result_from_beacon(await q.get())
        finally:
            self._subs.remove(q)

    async def info(self) -> Info:  # Client surface
        return self.chain_info

    def round_at(self, t: float) -> int:
        return time_math.current_round(int(t), self.chain_info.period,
                                       self.chain_info.genesis_time)


class GossipRelay:
    """Relay: watch a client source, publish every beacon into the mesh
    (lp2p/relaynode.go:48)."""

    def __init__(self, source: Client, node: GossipNode):
        self._src = source
        self.node = node
        self._task: asyncio.Task | None = None

    def start(self) -> None:
        self._task = asyncio.ensure_future(self._run())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()

    async def _run(self) -> None:
        while True:
            try:
                async for r in self._src.watch():
                    await self.node.publish(Beacon(
                        round=r.round, previous_sig=r.previous_signature,
                        signature=r.signature,
                        signature_v2=r.signature_v2))
            except asyncio.CancelledError:
                return
            except Exception:  # noqa: BLE001 — keep relaying
                await asyncio.sleep(1.0)
