"""Beacon distribution relays.

Reference: cmd/relay (HTTP CDN relay — covered by `drand_tpu.cli relay`),
lp2p/ (gossipsub relay + validating client — `gossip.py` here, over a
flood-pubsub gRPC mesh instead of libp2p, which this image lacks).
"""
