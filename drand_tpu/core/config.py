"""Daemon configuration.

Reference: core/config.go (Config :20, NewConfig :44, options :60-230) and
core/constants.go (default period :27, DKG timeout :36, control port :30).
Python keyword arguments replace Go's functional options.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable

from ..chain.beacon import Beacon
from ..utils.clock import Clock, SystemClock

DEFAULT_BEACON_PERIOD = 60          # core/constants.go:27
DEFAULT_DKG_TIMEOUT = 10.0          # core/constants.go:36 (per phase)
DEFAULT_CONTROL_PORT = 8888         # core/constants.go:30
DEFAULT_GENESIS_OFFSET = 20         # group_setup.go: genesis placed beyond
                                    # 3 DKG phases + offset


@dataclass
class Config:
    folder: str = ""                      # key/group/chain storage root
    private_listen: str = ""              # host:port for node->node RPC
    public_listen: str = ""               # host:port for the public REST API
    control_port: int = DEFAULT_CONTROL_PORT
    dkg_timeout: float = DEFAULT_DKG_TIMEOUT
    clock: Clock = field(default_factory=SystemClock)
    beacon_callbacks: list[Callable[[Beacon], None]] = field(default_factory=list)
    dkg_callback: Callable | None = None
    db_path: str = ""                     # beacon chain store path; "" = memory
    insecure: bool = False                # no TLS (reference --tls-disable)

    def db_file(self) -> str:
        if self.db_path:
            return self.db_path
        if self.folder:
            import os

            return os.path.join(self.folder, "db", "drand.db")
        return ""
