"""The drand daemon: node lifecycle, DKG orchestration, beacon control.

Reference: core/drand.go (Drand :25, NewDrand :68, LoadDrand :144, WaitDKG
:166, StartBeacon :220, transition :243) and core/drand_control.go (InitDKG
:33, leaderRunSetup :72, runDKG :123, runResharing :196, setupAutomaticDKG
:291, InitReshare :500, pushDKGInfo :712).

A Drand instance implements the node->node ProtocolService; callers
register it on a transport (LocalNetwork in-process, gRPC gateway across
hosts) and drive it through the control methods (`init_dkg`,
`init_reshare`, `stop`) that the CLI/control plane exposes.
"""

from __future__ import annotations

import asyncio
import os
from typing import AsyncIterator

from ..chain.beacon import Beacon
from ..chain.engine.handler import BeaconConfig, Handler
from ..chain.store import MemStore, Store, open_chain_store
from ..dkg import BroadcastBoard, DKGConfig, DKGError, DKGProtocol, DistKeyShare
from ..key.group import Group
from ..key.keys import Node, Pair, Share
from ..key.store import FileStore
from ..utils.aio import spawn
from ..net.packets import (
    GroupPacket,
    PartialBeaconPacket,
    SignalDKGPacket,
    SyncRequest,
)
from ..net.transport import ProtocolClient, ProtocolService, TransportError
from ..utils.logging import KVLogger, default_logger
from .config import Config
from .setup import (
    SetupConfig,
    SetupManager,
    check_secret,
    dkg_nonce,
    sign_group,
    verify_group_packet,
)


class DrandError(Exception):
    pass


class Drand(ProtocolService):
    def __init__(self, key_store: FileStore | None, conf: Config,
                 client: ProtocolClient, priv: Pair,
                 logger: KVLogger | None = None):
        self.store = key_store
        self.conf = conf
        self.client = client
        self.priv = priv
        self._l = (logger or default_logger("drand")).named(
            priv.public.addr.split(":")[0])
        self.group: Group | None = None
        self.share: Share | None = None
        self.beacon: Handler | None = None
        # DKG-in-progress state
        self._setup_mgr: SetupManager | None = None
        self._setup_token: object | None = None  # whole-flow setup slot
        self._board: BroadcastBoard | None = None
        # bundles that raced ahead of board creation (a dealer can push its
        # deals before a follower finished processing the group push);
        # replayed into the board once the DKG starts
        self._pending_dkg: list[tuple[str, object]] = []
        self._group_packet: asyncio.Future | None = None
        self._expected_secret: bytes | None = None
        self._stopped = False

    # ------------------------------------------------------------ factory
    @classmethod
    def fresh(cls, key_store: FileStore, conf: Config,
              client: ProtocolClient, address: str,
              logger: KVLogger | None = None) -> "Drand":
        """New node: create + persist a keypair (core/drand.go:68)."""
        from ..key.keys import new_key_pair

        priv = new_key_pair(address)
        key_store.save_key_pair(priv)
        return cls(key_store, conf, client, priv, logger)

    @classmethod
    def load(cls, key_store: FileStore, conf: Config,
             client: ProtocolClient,
             logger: KVLogger | None = None) -> "Drand":
        """Restart: load keypair + share + group; caller then invokes
        ``start_beacon(catchup=True)`` (core/drand.go:144, daemon.go:36)."""
        priv = key_store.load_key_pair()
        d = cls(key_store, conf, client, priv, logger)
        if key_store.has_group():
            d.group = key_store.load_group()
        if key_store.has_share():
            d.share = key_store.load_share()
        return d

    # ----------------------------------------------------- control plane
    def _acquire_setup(self, force: bool) -> object:
        """Claim the single setup/DKG slot for a WHOLE init flow
        (drand_control.go:41 'force' flag): a second InitDKG/InitReshare
        errors unless forced; force cancels a setup still collecting
        participants but cannot abort a DKG already running."""
        if self._setup_token is not None:
            if not force:
                raise DrandError(
                    "a setup phase is already in progress (pass force "
                    "to preempt it)")
            if self._setup_mgr is not None:
                self._setup_mgr.cancel()
            elif (self._group_packet is not None
                  and not self._group_packet.done()):
                # a FOLLOWER setup holds the slot while awaiting the
                # leader's group packet — no SetupManager, no DKG
                # running yet. That phase is preemptable too: cancel the
                # future so the waiting init unwinds (it releases only
                # its own token; ours survives).
                self._group_packet.cancel()
            else:
                raise DrandError(
                    "cannot preempt: the DKG phase is already running")
        token = object()
        self._setup_token = token
        return token

    def _release_setup(self, token: object) -> None:
        # a forced successor may already own the slot — release only ours
        if self._setup_token is token:
            self._setup_token = None

    def _begin_setup(self, sc: SetupConfig) -> SetupManager:
        mgr = SetupManager(sc, self.priv.public, self.conf.clock,
                           self._l.named("setup"))
        self._setup_mgr = mgr
        return mgr

    async def _wait_setup(self, mgr: SetupManager, timeout: float):
        try:
            return await mgr.wait_participants(timeout)
        finally:
            # a forced successor may already have installed ITS manager —
            # only clear our own
            if self._setup_mgr is mgr:
                self._setup_mgr = None

    async def init_dkg_leader(self, expected_n: int, threshold: int,
                              period: int, secret: bytes,
                              timeout: float = 60.0,
                              catchup_period: int = 0,
                              force: bool = False) -> Group:
        """Leader: collect participants, push the group, run the DKG,
        start the beacon (InitDKG :33 + leaderRunSetup :72)."""
        sc = SetupConfig(expected_n=expected_n, threshold=threshold,
                         period=period, secret=secret,
                         catchup_period=catchup_period,
                         dkg_timeout=self.conf.dkg_timeout)
        token = self._acquire_setup(force)
        try:
            mgr = self._begin_setup(sc)
            idents = await self._wait_setup(mgr, timeout)
            group = mgr.make_group(idents)
            await self._push_group(group, secret)
            result = await self._run_dkg(group)
            return await self._adopt_dkg_output(group, result, fresh=True)
        finally:
            self._release_setup(token)

    async def init_dkg_follower(self, leader: Node | str, secret: bytes,
                                timeout: float = 60.0,
                                force: bool = False) -> Group:
        """Follower: signal the leader, await the signed group, run the DKG
        (setupAutomaticDKG :291)."""
        token = self._acquire_setup(force)
        try:
            self._expected_secret = secret
            # bind the future locally: a forced preemptor cancels it and
            # installs ITS OWN as self._group_packet — re-reading the
            # attribute here would make a preempted init await (and
            # consume) the successor's packet, running two DKGs at once
            fut = self._group_packet = \
                asyncio.get_event_loop().create_future()
            await self._signal_leader(leader, secret, b"", timeout)
            packet, leader_ident = await asyncio.wait_for(fut, timeout)
            group = verify_group_packet(leader_ident, packet)
            if group.find(self.priv.public) is None:
                raise DrandError("we are not part of the pushed group")
            result = await self._run_dkg(group)
            return await self._adopt_dkg_output(group, result, fresh=True)
        finally:
            self._release_setup(token)

    async def init_reshare_leader(self, expected_n: int, threshold: int,
                                  secret: bytes, timeout: float = 60.0,
                                  force: bool = False) -> Group:
        """Leader of a resharing epoch; must hold the old group+share
        (InitReshare :500)."""
        old_group, old_share = self._require_running()
        sc = SetupConfig(expected_n=expected_n, threshold=threshold,
                         period=old_group.period, secret=secret,
                         dkg_timeout=self.conf.dkg_timeout)
        token = self._acquire_setup(force)
        try:
            mgr = self._begin_setup(sc)
            idents = await self._wait_setup(mgr, timeout)
            group = mgr.make_group(idents, old_group=old_group)
            # push to the union of old and new members so leavers learn too
            await self._push_group(group, secret, extra=old_group.nodes)
            result = await self._run_dkg(group, old_group=old_group,
                                         old_share=old_share)
            return await self._transition(old_group, group, result)
        finally:
            self._release_setup(token)

    async def init_reshare_follower(self, leader: Node | str, secret: bytes,
                                    old_group: Group | None = None,
                                    leaving: bool = False,
                                    timeout: float = 60.0,
                                    force: bool = False) -> Group:
        """Existing member, new joiner, or leaver in a resharing epoch
        (setupAutomaticResharing :371). New joiners pass the old group file
        (they need its public coefficients); members use their stored one.
        A leaver sets ``leaving=True``: it does NOT signal (signalling joins
        the new group) but still deals its old share and stops at T."""
        if old_group is None:
            old_group = self.group
        if old_group is None:
            raise DrandError("resharing needs the old group file")
        token = self._acquire_setup(force)
        try:
            self._expected_secret = secret
            # local binding: see init_dkg_follower (forced-preemption race)
            fut = self._group_packet = \
                asyncio.get_event_loop().create_future()
            if not leaving:
                await self._signal_leader(leader, secret, old_group.hash(),
                                          timeout)
            packet, leader_ident = await asyncio.wait_for(fut, timeout)
            group = verify_group_packet(leader_ident, packet)
            if old_group.find(leader_ident) is None:
                raise DrandError("reshare leader not part of the old group")
            result = await self._run_dkg(group, old_group=old_group,
                                         old_share=self.share)
            return await self._transition(old_group, group, result)
        finally:
            self._release_setup(token)

    def start_beacon(self, catchup: bool = True) -> None:
        """Boot the beacon from persisted state (core/drand.go:220)."""
        group, share = self._require_loaded()
        # a loaded group+share IS a completed DKG (readiness gate,
        # obs/health — the restart twin of _adopt_dkg_output)
        from ..obs.health import HEALTH

        HEALTH.note_dkg_complete()
        self._make_handler(group, share)
        if catchup:
            spawn(self.beacon.catchup())
        else:
            spawn(self.beacon.start())

    def stop(self) -> None:
        self._stopped = True
        if self.beacon is not None:
            self.beacon.stop()

    async def follow_chain(self, peers: list[str], up_to: int = 0,
                           info_hash: bytes | None = None) -> bool:
        """Sync the chain from peers without participating
        (core/drand_control.go:783 StartFollowChain): fetch+pin the chain
        info, then stream/verify/store beacons.

        ``info_hash``: the operator-supplied chain hash — the SOLE trust
        anchor of a follow (the peers themselves are untrusted). Chain
        info served by a peer is validated against it before anything is
        pinned or stored (core/drand_control.go:822-829); a peer serving
        mismatched info is skipped like an unreachable one, and the
        follow aborts when no peer serves matching info."""
        from ..chain.engine.sync import Syncer
        from ..chain.store import CallbackStore, genesis_beacon

        if not peers:
            raise DrandError("follow needs at least one peer")
        info = None
        mismatched = 0
        for p in peers:
            try:
                got = await self.client.chain_info(_addr_peer(p))
            except TransportError:
                continue
            if info_hash and got.hash() != info_hash:
                mismatched += 1
                self._l.warn("follow", "chain_info_hash_mismatch", peer=p,
                             expected=info_hash.hex(),
                             got=got.hash().hex())
                continue
            info = got
            break
        if info is None:
            if mismatched:
                raise DrandError(
                    f"chain info hash mismatch on {mismatched} peer(s) — "
                    "refusing to follow an unpinned chain")
            raise DrandError("no peer served chain info")
        db = self.conf.db_file()
        if db:
            os.makedirs(os.path.dirname(db), exist_ok=True)
            store: Store = open_chain_store(db)
        else:
            store = MemStore()
        store.put(genesis_beacon(info))
        cb_store = CallbackStore(store)
        syncer = Syncer(self._l.named("follow"), cb_store, info, self.client)
        self._follow_store = cb_store  # kept for status/resume inspection
        return await syncer.follow(up_to, [_addr_peer(p) for p in peers])

    # ------------------------------------------------------- DKG internals
    async def _signal_leader(self, leader, secret: bytes, prev_hash: bytes,
                             timeout: float, retry_every: float = 0.5) -> None:
        packet = SignalDKGPacket(identity=self.priv.public, secret=secret,
                                 previous_group_hash=prev_hash)
        deadline = self.conf.clock.now() + timeout
        while True:
            try:
                await self.client.signal_dkg_participant(leader, packet)
                return
            except (TransportError, PermissionError):
                if self.conf.clock.now() >= deadline:
                    raise
                await self.conf.clock.sleep(retry_every)

    async def _push_group(self, group: Group, secret: bytes,
                          extra: list[Node] | None = None) -> None:
        """Sign + deliver the group to every other member; require a
        threshold of successful pushes (pushDKGInfo :712-770)."""
        packet = GroupPacket(group=group.to_dict(),
                             signature=sign_group(self.priv.key, group),
                             secret=secret,
                             dkg_timeout=self.conf.dkg_timeout)
        targets: dict[str, Node] = {n.address(): n for n in group.nodes}
        for n in extra or []:
            targets.setdefault(n.address(), n)
        targets.pop(self.priv.public.addr, None)

        async def push_one(node: Node):
            try:
                await self.client.push_dkg_info(node.identity, packet)
                return None
            except TransportError as e:
                return node, e

        # all pushes CONCURRENT (reference sendout's per-peer goroutines,
        # broadcast.go:143): a sequential pass would stall the leader's
        # DKG start by up to client-timeout x n while followers that got
        # the packet burn their phase clocks. One concurrent retry round
        # for the misses; a lost push costs a whole DKG epoch.
        pending: list[Node] = list(targets.values())
        oks = 0
        for attempt in ("failed", "retry_failed"):
            results = await asyncio.gather(*(push_one(n) for n in pending))
            pending = []
            for r in results:
                if r is None:
                    oks += 1
                else:
                    node, err = r
                    self._l.warn("push_group", attempt,
                                 to=node.address(), err=str(err))
                    pending.append(node)
            if not pending:
                break
        if oks + 1 < group.threshold:
            raise DrandError(
                f"group push reached only {oks + 1} < threshold "
                f"{group.threshold}")

    async def _run_dkg(self, group: Group, old_group: Group | None = None,
                       old_share: Share | None = None) -> DistKeyShare:
        nonce = dkg_nonce(group)
        dealers = old_group.nodes if old_group is not None else group.nodes
        self._board = BroadcastBoard(
            self.client, self.priv.public.addr, dealers, group.nodes, nonce,
            self._l.named("board"))
        pending, self._pending_dkg = self._pending_dkg, []
        for from_addr, pkt in pending:
            await self._board.receive(from_addr, pkt)
        try:
            conf = DKGConfig(
                longterm=self.priv, nonce=nonce, new_nodes=group.nodes,
                threshold=group.threshold,
                old_nodes=old_group.nodes if old_group else None,
                public_coeffs=(old_group.public_key.coefficients
                               if old_group else None),
                old_threshold=old_group.threshold if old_group else 0,
                share=(old_share.pri_share if old_share else None),
                fast_sync=True, phase_timeout=self.conf.dkg_timeout,
                clock=self.conf.clock, logger=self._l)
            result = await DKGProtocol(conf, self._board).run()
        finally:
            self._board = None
        if self.conf.dkg_callback is not None:
            self.conf.dkg_callback(result)
        return result

    async def _adopt_dkg_output(self, group: Group, result: DistKeyShare,
                                fresh: bool) -> Group:
        from ..key.keys import DistPublic

        group.public_key = DistPublic(list(result.commits))
        self.group = group
        self.share = Share(commits=list(result.commits),
                           pri_share=result.pri_share)
        if self.store is not None:
            self.store.save_group(group)
            self.store.save_share(self.share)
        self._make_handler(group, self.share)
        spawn(self.beacon.start())
        from ..obs.health import HEALTH

        HEALTH.note_dkg_complete()
        self._l.info("dkg", "finished", qual=result.qual,
                     genesis=group.genesis_time)
        return group

    async def _transition(self, old_group: Group, new_group: Group,
                          result: DistKeyShare) -> Group:
        """Post-reshare transition (core/drand.go:243-277): members swap
        shares at T-1, joiners sync then start at T, leavers stop at T."""
        was_member = old_group.find(self.priv.public) is not None
        is_member = result.pri_share is not None
        if self.store is not None and is_member:
            new_share = Share(commits=list(result.commits),
                              pri_share=result.pri_share)
            self.store.save_group(new_group)
            self.store.save_share(new_share)
        if not is_member:
            # leaving: stop right before the transition round fires
            if self.beacon is not None:
                spawn(self.beacon.stop_at(new_group.transition_time - 1))
            self._l.info("reshare", "leaving_at",
                         t=new_group.transition_time)
            self.group = new_group
            return new_group
        new_share = Share(commits=list(result.commits),
                          pri_share=result.pri_share)
        if was_member and self.beacon is not None:
            self.beacon.transition_new_group(new_share, new_group)
        else:
            self._make_handler(new_group, new_share)
            spawn(self.beacon.transition(old_group))
        self.group, self.share = new_group, new_share
        return new_group

    # --------------------------------------------------- beacon plumbing
    def _make_handler(self, group: Group, share: Share) -> None:
        node = group.find(self.priv.public)
        if node is None:
            raise DrandError("keypair not in group")
        db = self.conf.db_file()
        if db:
            os.makedirs(os.path.dirname(db), exist_ok=True)
            store: Store = open_chain_store(db)
        else:
            store = MemStore()
        bconf = BeaconConfig(public=Node(identity=self.priv.public,
                                         index=node.index),
                             share=share, group=group, clock=self.conf.clock)
        self.beacon = Handler(client=self.client, store=store, conf=bconf,
                              logger=self._l.named("beacon"))
        for cb in self.conf.beacon_callbacks:
            self.beacon.chain.add_callback(f"conf-{id(cb)}", cb)
        # auto-remediation (ISSUE 16): wire the node playbooks
        # (sync_resume, quorum_pull, reshare_recommend) onto this
        # handler — dry-run unless DRAND_TPU_REMEDIATE=live
        from ..obs.remediate import attach_node
        from ..obs.remediate import configure_from_env as _remediate_env

        attach_node(_remediate_env(), self.beacon)

    def _require_loaded(self) -> tuple[Group, Share]:
        if self.group is None or self.share is None:
            raise DrandError("no group/share loaded")
        return self.group, self.share

    def _require_running(self) -> tuple[Group, Share]:
        group, share = self._require_loaded()
        return group, share

    # ------------------------------------------------- ProtocolService in
    async def process_partial_beacon(self, from_addr: str,
                                     p: PartialBeaconPacket) -> None:
        if self.beacon is None:
            raise TransportError("no beacon running")
        await self.beacon.process_partial_beacon(from_addr, p)

    def sync_chain(self, from_addr: str, req: SyncRequest) -> AsyncIterator[Beacon]:
        if self.beacon is None:
            raise TransportError("no beacon running")
        return self.beacon.sync_chain(from_addr, req)

    async def chain_info(self, from_addr: str):
        if self.beacon is not None:
            return await self.beacon.chain_info(from_addr)
        if self.group is not None and self.group.public_key is not None:
            from ..chain.info import Info

            return Info.from_group(self.group)
        raise TransportError("no chain info yet")

    async def get_identity(self, from_addr: str):
        return self.priv.public

    async def public_rand(self, from_addr: str, round_no: int):
        """Public randomness over gRPC (core/drand_public.go:52): round 0
        means latest; raises while the chain is empty."""
        from ..chain.store import StoreError

        if self.beacon is None:
            raise TransportError("no beacon running")
        store = self.beacon.chain
        try:
            b = store.last() if round_no == 0 else store.get(round_no)
        except StoreError as e:
            raise TransportError(f"chain empty: {e}") from e
        if b is None or b.round == 0:
            raise TransportError(f"no beacon for round {round_no}")
        return b

    async def public_rand_stream(self, from_addr: str):
        """Server-streaming watch (core/drand_public.go:76): every new
        beacon from now on."""
        if self.beacon is None:
            raise TransportError("no beacon running")
        queue: asyncio.Queue = asyncio.Queue(maxsize=32)
        cb_id = f"public-stream-{from_addr}-{id(queue)}"
        self.beacon.chain.add_callback(
            cb_id, lambda b: queue.put_nowait(b)
            if not queue.full() else None)
        try:
            while True:
                yield await queue.get()
        finally:
            self.beacon.chain.remove_callback(cb_id)

    async def peer_metrics(self, from_addr: str) -> bytes:
        """Serve our prometheus metrics to group members over the node
        transport (core/drand_metrics.go:12 PeerMetrics)."""
        from .. import metrics

        return metrics.render()

    async def private_rand(self, from_addr: str, request: bytes) -> bytes:
        """ECIES private randomness (core/drand_public.go:126-160): decrypt
        the requester's ephemeral key with our longterm key, return 32
        fresh bytes encrypted to it."""
        from ..crypto import ecies
        from ..crypto.curves import PointG1
        from ..utils import entropy

        # the whole exchange off the loop: ECIES point muls AND the
        # entropy read (a configured entropy source is a subprocess
        # wait) — this is public ingress on the same loop that drives
        # the beacon round
        def _decode(raw: bytes) -> PointG1:
            return PointG1.from_bytes(ecies.decrypt(self.priv.key, raw))

        def _reply(key: PointG1) -> bytes:
            return ecies.encrypt(key, entropy.get_random(32))

        try:
            client_key = await asyncio.to_thread(_decode, bytes(request))
        except Exception as e:  # noqa: BLE001 — untrusted ingress
            raise TransportError(f"private rand: bad request: {e!r}") from e
        return await asyncio.to_thread(_reply, client_key)

    async def signal_dkg_participant(self, from_addr: str,
                                     packet: SignalDKGPacket) -> None:
        if self._setup_mgr is None:
            raise TransportError("no setup in progress")
        self._setup_mgr.received_key(from_addr, packet)

    async def push_dkg_info(self, from_addr: str, packet: GroupPacket) -> None:
        if self._group_packet is None or self._group_packet.done():
            raise TransportError("not expecting a group push")
        if self._expected_secret is None or \
                not check_secret(self._expected_secret, packet.secret):
            raise TransportError("push group: wrong secret")
        leader_ident = await self.client.get_identity(_addr_peer(from_addr))
        self._group_packet.set_result((packet, leader_ident))

    async def broadcast_dkg(self, from_addr: str, packet) -> None:
        if self._board is None:
            if len(self._pending_dkg) < 1024:
                self._pending_dkg.append((from_addr, packet))
                return
            raise TransportError("no DKG in progress")
        await self._board.receive(from_addr, packet)


class _addr_peer(str):
    """Minimal Peer: an address string with .address()."""

    def address(self) -> str:
        return str(self)
