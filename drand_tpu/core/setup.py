"""Group setup: leader collects participants, forms and distributes the
signed group file.

Reference: core/group_setup.go — setupManager (:42) gathers
SignalDKGParticipant keys gated by a shared secret (constant-time compare
:369), creates the group with an aligned genesis/transition time
(:218-242), and PushDKGInfo (:319) delivers it under the leader's
DKGAuthScheme (schnorr) signature.
"""

from __future__ import annotations

import asyncio
import hashlib
import hmac
from dataclasses import dataclass

from ..crypto import schnorr
from ..key.group import Group
from ..key.keys import DistPublic, Identity, Node
from ..net.packets import GroupPacket, SignalDKGPacket
from ..utils.clock import Clock
from ..utils.logging import KVLogger
from .config import DEFAULT_GENESIS_OFFSET


def dkg_nonce(group: Group) -> bytes:
    """Session nonce binding DKG bundles to this exact group epoch."""
    h = hashlib.sha256()
    h.update(b"drand-tpu-dkg-nonce")
    h.update(group.hash())
    h.update(int(group.transition_time).to_bytes(8, "big", signed=True))
    return h.digest()


def check_secret(expected: bytes, got: bytes) -> bool:
    return hmac.compare_digest(expected, got)


@dataclass
class SetupConfig:
    expected_n: int
    threshold: int
    period: int
    secret: bytes
    catchup_period: int = 0
    dkg_timeout: float = 10.0
    genesis_offset: int = DEFAULT_GENESIS_OFFSET


class SetupPreempted(RuntimeError):
    """A forced second setup cancelled this one (control.proto force)."""


class SetupManager:
    """Leader-side participant collection (one setup at a time)."""

    def __init__(self, conf: SetupConfig, leader_identity: Identity,
                 clock: Clock, logger: KVLogger):
        self.conf = conf
        self.clock = clock
        self._l = logger
        self._identities: dict[str, Identity] = {
            leader_identity.addr: leader_identity}
        self._done: asyncio.Future = asyncio.get_event_loop().create_future()

    def received_key(self, from_addr: str, packet: SignalDKGPacket) -> None:
        """SignalDKGParticipant ingress (group_setup.go:140)."""
        if not check_secret(self.conf.secret, packet.secret):
            raise PermissionError("setup: wrong secret")
        ident = packet.identity
        if not ident.valid_signature():
            raise ValueError("setup: invalid identity self-signature")
        if ident.addr not in self._identities:
            self._identities[ident.addr] = ident
            self._l.info("setup", "participant", addr=ident.addr,
                         have=len(self._identities), want=self.conf.expected_n)
        if len(self._identities) == self.conf.expected_n and \
                not self._done.done():
            self._done.set_result(None)

    def cancel(self, reason: str = "setup preempted by a forced restart"):
        if not self._done.done():
            self._done.set_exception(SetupPreempted(reason))

    async def wait_participants(self, timeout: float) -> list[Identity]:
        try:
            await asyncio.wait_for(asyncio.shield(self._done), timeout)
        except asyncio.TimeoutError:
            raise TimeoutError(
                f"setup: only {len(self._identities)} of "
                f"{self.conf.expected_n} participants signalled")
        return sorted(self._identities.values(), key=lambda i: i.addr)

    def make_group(self, identities: list[Identity],
                   old_group: Group | None = None,
                   public_key: DistPublic | None = None) -> Group:
        """Form the group; genesis (or transition) is placed after the DKG's
        three phases and aligned to a period boundary (group_setup.go:218)."""
        nodes = [Node(identity=ident, index=i)
                 for i, ident in enumerate(identities)]
        earliest = int(self.clock.now()) + int(3 * self.conf.dkg_timeout) + \
            self.conf.genesis_offset
        if old_group is None:
            genesis = earliest
            group = Group(nodes=nodes, threshold=self.conf.threshold,
                          period=self.conf.period, genesis_time=genesis,
                          catchup_period=self.conf.catchup_period)
            group.get_genesis_seed()
            return group
        # reshare: keep chain identity; transition on a round boundary
        period = old_group.period
        from ..chain import time_math

        t_round = time_math.current_round(earliest, period,
                                          old_group.genesis_time) + 1
        t_time = time_math.time_of_round(period, old_group.genesis_time,
                                         t_round)
        group = Group(nodes=nodes, threshold=self.conf.threshold,
                      period=period, genesis_time=old_group.genesis_time,
                      genesis_seed=old_group.get_genesis_seed(),
                      transition_time=t_time,
                      catchup_period=old_group.catchup_period,
                      public_key=public_key or old_group.public_key)
        return group


def sign_group(leader_key: int, group: Group) -> bytes:
    return schnorr.sign(leader_key, group.hash())


def verify_group_packet(leader: Identity, packet: GroupPacket) -> Group:
    """Follower side: parse + verify the leader-signed group
    (group_setup.go:319-339 setupReceiver.PushDKGInfo)."""
    group = Group.from_dict(packet.group)
    if not schnorr.verify(leader.key, group.hash(), packet.signature):
        raise ValueError("push group: invalid leader signature")
    return group
