"""The timelock vault: ciphertexts keyed by their unlock round.

Same storage discipline as the chain store (chain/store.py SQLiteStore):
single-writer append-mostly workload, stdlib sqlite3 with WAL, every
statement under one lock, ``check_same_thread=False`` because callers
reach it through ``asyncio.to_thread`` workers. State survives daemon
restart — a pending ciphertext submitted before a crash opens at the
next boundary sweep (service.py).

Rows are immutable once opened/rejected (the HTTP layer serves them with
an ETag and ``Cache-Control: immutable``): ``set_opened``/``set_rejected``
only ever transition ``pending`` rows.
"""

from __future__ import annotations

import json
import sqlite3
import threading
import time


class VaultError(Exception):
    pass


_SCHEMA = """
CREATE TABLE IF NOT EXISTS timelock (
  id        TEXT PRIMARY KEY,
  round     INTEGER NOT NULL,
  envelope  TEXT NOT NULL,
  status    TEXT NOT NULL DEFAULT 'pending',
  plaintext BLOB,
  error     TEXT,
  submitted REAL NOT NULL,
  opened    REAL
);
CREATE INDEX IF NOT EXISTS timelock_round ON timelock (round, status);
-- pending_count() runs on EVERY submit (the backlog cap) and after
-- every round open (the gauge): a partial index keeps it O(pending)
-- instead of scanning a lifetime of opened/rejected rows
CREATE INDEX IF NOT EXISTS timelock_pending ON timelock (status)
  WHERE status = 'pending';
"""


class TimelockVault:
    """Persistent round-keyed ciphertext store (``:memory:`` for tests)."""

    def __init__(self, path: str):
        self._path = path
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.executescript(_SCHEMA)
        self._conn.execute("PRAGMA journal_mode=WAL")
        self._conn.execute("PRAGMA synchronous=NORMAL")
        self._conn.commit()
        self._lock = threading.Lock()
        # bound child resolved once (same hoist as the segment
        # backend: labels() is a lock + dict probe per get)
        from .. import metrics

        self._reads_inc = metrics.VAULT_READS.labels(
            backend="sqlite").inc

    def __len__(self) -> int:
        with self._lock:
            (n,) = self._conn.execute(
                "SELECT COUNT(*) FROM timelock").fetchone()
        return n

    def submit(self, token: str, round_no: int, envelope: dict) -> bool:
        """Insert a pending ciphertext; False when the token already
        exists (idempotent resubmission — the token is derived from the
        envelope content, so a retry is a no-op, not a duplicate)."""
        with self._lock:
            cur = self._conn.execute(
                "INSERT OR IGNORE INTO timelock"
                " (id, round, envelope, status, submitted)"
                " VALUES (?, ?, ?, 'pending', ?)",
                (token, round_no, json.dumps(envelope, sort_keys=True),
                 time.time()))
            self._conn.commit()
            return cur.rowcount == 1

    def get(self, token: str, with_envelope: bool = True) -> dict | None:
        """One record by id. ``with_envelope=False`` skips decoding the
        envelope JSON (the status() serving path never returns it)."""
        with self._lock:
            row = self._conn.execute(
                "SELECT id, round, envelope, status, plaintext, error,"
                " submitted, opened FROM timelock WHERE id = ?",
                (token,)).fetchone()
        if row is None:
            return None
        self._reads_inc()
        return {
            "id": row[0], "round": row[1],
            "envelope": json.loads(row[2]) if with_envelope else None,
            "status": row[3],
            "plaintext": row[4], "error": row[5],
            "submitted": row[6], "opened": row[7],
        }

    def pending_rounds(self, up_to: int | None = None) -> list[int]:
        """Distinct rounds with pending ciphertexts, ascending; bounded
        by ``up_to`` (the chain head) when given."""
        q = ("SELECT DISTINCT round FROM timelock WHERE status = 'pending'")
        args: tuple = ()
        if up_to is not None:
            q += " AND round <= ?"
            args = (up_to,)
        with self._lock:
            rows = self._conn.execute(q + " ORDER BY round", args).fetchall()
        return [r[0] for r in rows]

    def pending_for_round(self, round_no: int,
                          shard: tuple[int, int] | None = None
                          ) -> list[tuple[str, dict]]:
        """(token, envelope) of every pending ciphertext for a round;
        ``shard=(index, count)`` restricts to that token-range partition
        (segvault.shard_hex_bounds — hex ids of equal length order like
        the integers, so plain string compares partition exactly)."""
        q = ("SELECT id, envelope FROM timelock"
             " WHERE round = ? AND status = 'pending'")
        args: list = [round_no]
        if shard is not None:
            from .segvault import shard_hex_bounds

            lo_hex, hi_hex = shard_hex_bounds(*shard)
            q += " AND id >= ?"
            args.append(lo_hex)
            if hi_hex is not None:
                q += " AND id < ?"
                args.append(hi_hex)
        with self._lock:
            rows = self._conn.execute(
                q + " ORDER BY submitted, id", args).fetchall()
        return [(r[0], json.loads(r[1])) for r in rows]

    def pending_count(self) -> int:
        with self._lock:
            (n,) = self._conn.execute(
                "SELECT COUNT(*) FROM timelock WHERE status = 'pending'"
            ).fetchone()
        return n

    def finish_round(self, results: list[tuple[str, bool, bytes, str]],
                     round_no: int | None = None) -> tuple[int, int]:
        """Persist a round's open outcomes (one chunk's worth) in ONE
        transaction: ``(token, ok, plaintext, error)`` rows become
        opened/rejected. Returns (opened, rejected) counts. Only
        ``pending`` rows transition (immutability as in
        :meth:`_finish`); rows already decided by a concurrent sweep
        are skipped, not an error. ``round_no`` is the segment
        backend's torn-index recovery hint — unused here, the PK index
        finds rows regardless."""
        now = time.time()
        opened = rejected = 0
        with self._lock:
            for token, ok, plaintext, error in results:
                cur = self._conn.execute(
                    "UPDATE timelock SET status = ?, plaintext = ?,"
                    " error = ?, opened = ?"
                    " WHERE id = ? AND status = 'pending'",
                    ("opened" if ok else "rejected",
                     plaintext if ok else None,
                     None if ok else (error or "")[:300], now, token))
                if cur.rowcount == 1:
                    if ok:
                        opened += 1
                    else:
                        rejected += 1
            self._conn.commit()
        return opened, rejected

    def set_opened(self, token: str, plaintext: bytes) -> None:
        self._finish(token, "opened", plaintext, None)

    def set_rejected(self, token: str, error: str) -> None:
        self._finish(token, "rejected", None, error[:300])

    def _finish(self, token: str, status: str, plaintext: bytes | None,
                error: str | None) -> None:
        """pending -> opened|rejected, exactly once (opened rows are
        immutable — the HTTP layer's ETag depends on it)."""
        with self._lock:
            cur = self._conn.execute(
                "UPDATE timelock SET status = ?, plaintext = ?, error = ?,"
                " opened = ? WHERE id = ? AND status = 'pending'",
                (status, plaintext, error, time.time(), token))
            self._conn.commit()
            if cur.rowcount != 1:
                raise VaultError(
                    f"ciphertext {token} is not pending (double open?)")

    def rows(self):
        """Every record in INSERTION (rowid) order — the migration
        surface (segvault.migrate_vault; the segment backend's rows()
        orders by (round, submitted, token) instead, so callers must
        not rely on a cross-backend order). Envelopes come back as
        their RAW stored JSON string so SQLite<->segment round-trips
        are byte-exact with zero re-encoding."""
        last_rowid = 0
        while True:
            with self._lock:
                batch = self._conn.execute(
                    "SELECT rowid, id, round, envelope, status,"
                    " plaintext, error, submitted, opened FROM timelock"
                    " WHERE rowid > ? ORDER BY rowid LIMIT 4096",
                    (last_rowid,)).fetchall()
            if not batch:
                return
            last_rowid = batch[-1][0]
            for r in batch:
                yield {
                    "id": r[1], "round": r[2], "envelope": r[3],
                    "status": r[4], "plaintext": r[5], "error": r[6],
                    "submitted": r[7], "opened": r[8],
                }

    def put_rows(self, rows) -> int:
        """Bulk-load full records (migration / bench fixtures),
        batched executemany transactions. Envelope may arrive as its
        raw JSON string (the rows() shape) or a dict."""
        count = 0
        batch: list[tuple] = []

        def _flush() -> None:
            with self._lock:
                self._conn.executemany(
                    "INSERT OR IGNORE INTO timelock (id, round,"
                    " envelope, status, plaintext, error, submitted,"
                    " opened) VALUES (?, ?, ?, ?, ?, ?, ?, ?)", batch)
                self._conn.commit()
            batch.clear()

        for rec in rows:
            env = rec["envelope"]
            if not isinstance(env, str):
                env = json.dumps(env, sort_keys=True)
            batch.append((rec["id"], rec["round"], env, rec["status"],
                          rec["plaintext"], rec["error"],
                          rec["submitted"], rec["opened"]))
            count += 1
            if len(batch) >= 10_000:
                _flush()
        if batch:
            _flush()
        return count

    def close(self) -> None:
        with self._lock:
            self._conn.close()
