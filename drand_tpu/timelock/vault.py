"""The timelock vault: ciphertexts keyed by their unlock round.

Same storage discipline as the chain store (chain/store.py SQLiteStore):
single-writer append-mostly workload, stdlib sqlite3 with WAL, every
statement under one lock, ``check_same_thread=False`` because callers
reach it through ``asyncio.to_thread`` workers. State survives daemon
restart — a pending ciphertext submitted before a crash opens at the
next boundary sweep (service.py).

Rows are immutable once opened/rejected (the HTTP layer serves them with
an ETag and ``Cache-Control: immutable``): ``set_opened``/``set_rejected``
only ever transition ``pending`` rows.
"""

from __future__ import annotations

import json
import sqlite3
import threading
import time


class VaultError(Exception):
    pass


_SCHEMA = """
CREATE TABLE IF NOT EXISTS timelock (
  id        TEXT PRIMARY KEY,
  round     INTEGER NOT NULL,
  envelope  TEXT NOT NULL,
  status    TEXT NOT NULL DEFAULT 'pending',
  plaintext BLOB,
  error     TEXT,
  submitted REAL NOT NULL,
  opened    REAL
);
CREATE INDEX IF NOT EXISTS timelock_round ON timelock (round, status);
-- pending_count() runs on EVERY submit (the backlog cap) and after
-- every round open (the gauge): a partial index keeps it O(pending)
-- instead of scanning a lifetime of opened/rejected rows
CREATE INDEX IF NOT EXISTS timelock_pending ON timelock (status)
  WHERE status = 'pending';
"""


class TimelockVault:
    """Persistent round-keyed ciphertext store (``:memory:`` for tests)."""

    def __init__(self, path: str):
        self._path = path
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.executescript(_SCHEMA)
        self._conn.execute("PRAGMA journal_mode=WAL")
        self._conn.execute("PRAGMA synchronous=NORMAL")
        self._conn.commit()
        self._lock = threading.Lock()

    def __len__(self) -> int:
        with self._lock:
            (n,) = self._conn.execute(
                "SELECT COUNT(*) FROM timelock").fetchone()
        return n

    def submit(self, token: str, round_no: int, envelope: dict) -> bool:
        """Insert a pending ciphertext; False when the token already
        exists (idempotent resubmission — the token is derived from the
        envelope content, so a retry is a no-op, not a duplicate)."""
        with self._lock:
            cur = self._conn.execute(
                "INSERT OR IGNORE INTO timelock"
                " (id, round, envelope, status, submitted)"
                " VALUES (?, ?, ?, 'pending', ?)",
                (token, round_no, json.dumps(envelope, sort_keys=True),
                 time.time()))
            self._conn.commit()
            return cur.rowcount == 1

    def get(self, token: str) -> dict | None:
        with self._lock:
            row = self._conn.execute(
                "SELECT id, round, envelope, status, plaintext, error,"
                " submitted, opened FROM timelock WHERE id = ?",
                (token,)).fetchone()
        if row is None:
            return None
        return {
            "id": row[0], "round": row[1],
            "envelope": json.loads(row[2]), "status": row[3],
            "plaintext": row[4], "error": row[5],
            "submitted": row[6], "opened": row[7],
        }

    def pending_rounds(self, up_to: int | None = None) -> list[int]:
        """Distinct rounds with pending ciphertexts, ascending; bounded
        by ``up_to`` (the chain head) when given."""
        q = ("SELECT DISTINCT round FROM timelock WHERE status = 'pending'")
        args: tuple = ()
        if up_to is not None:
            q += " AND round <= ?"
            args = (up_to,)
        with self._lock:
            rows = self._conn.execute(q + " ORDER BY round", args).fetchall()
        return [r[0] for r in rows]

    def pending_for_round(self, round_no: int) -> list[tuple[str, dict]]:
        """(token, envelope) of every pending ciphertext for a round."""
        with self._lock:
            rows = self._conn.execute(
                "SELECT id, envelope FROM timelock"
                " WHERE round = ? AND status = 'pending' ORDER BY submitted",
                (round_no,)).fetchall()
        return [(r[0], json.loads(r[1])) for r in rows]

    def pending_count(self) -> int:
        with self._lock:
            (n,) = self._conn.execute(
                "SELECT COUNT(*) FROM timelock WHERE status = 'pending'"
            ).fetchone()
        return n

    def finish_round(self, results: list[tuple[str, bool, bytes, str]]
                     ) -> tuple[int, int]:
        """Persist a whole round's open outcomes in ONE transaction:
        ``(token, ok, plaintext, error)`` rows become opened/rejected.
        Returns (opened, rejected) counts. Only ``pending`` rows
        transition (immutability as in :meth:`_finish`); rows already
        decided by a concurrent sweep are skipped, not an error."""
        now = time.time()
        opened = rejected = 0
        with self._lock:
            for token, ok, plaintext, error in results:
                cur = self._conn.execute(
                    "UPDATE timelock SET status = ?, plaintext = ?,"
                    " error = ?, opened = ?"
                    " WHERE id = ? AND status = 'pending'",
                    ("opened" if ok else "rejected",
                     plaintext if ok else None,
                     None if ok else (error or "")[:300], now, token))
                if cur.rowcount == 1:
                    if ok:
                        opened += 1
                    else:
                        rejected += 1
            self._conn.commit()
        return opened, rejected

    def set_opened(self, token: str, plaintext: bytes) -> None:
        self._finish(token, "opened", plaintext, None)

    def set_rejected(self, token: str, error: str) -> None:
        self._finish(token, "rejected", None, error[:300])

    def _finish(self, token: str, status: str, plaintext: bytes | None,
                error: str | None) -> None:
        """pending -> opened|rejected, exactly once (opened rows are
        immutable — the HTTP layer's ETag depends on it)."""
        with self._lock:
            cur = self._conn.execute(
                "UPDATE timelock SET status = ?, plaintext = ?, error = ?,"
                " opened = ? WHERE id = ? AND status = 'pending'",
                (status, plaintext, error, time.time(), token))
            self._conn.commit()
            if cur.rowcount != 1:
                raise VaultError(
                    f"ciphertext {token} is not pending (double open?)")

    def close(self) -> None:
        with self._lock:
            self._conn.close()
