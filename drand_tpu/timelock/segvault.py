"""Segment-backed timelock vault: planet-scale write-once rows.

The SQLite vault (vault.py) is perfect for a demo backlog and falls
over at 10M rows exactly where the beacon store did at 1M rounds
(chain/segments.py, PR 14): ``pending_count()`` becomes an index scan
that runs on EVERY submit, and ``status(token)`` walks a multi-GB
B-tree. Timelock rows have the same shape that made segments work for
beacons — write-once, append-mostly, immutable once decided — so the
same record/epoch layout applies:

``<dir>/meta.json``                 version guard
``<dir>/w<NN>/``                    one directory per WRITER (see below)
``    rounds/r-<round>.idx``        fixed 64-byte records, one per row
``    rounds/r-<round>.dat``        append-only envelope JSON blobs
``    rounds/r-<round>.out``        append-only outcome blobs (plaintext
                                    or reject error) written by THIS
                                    writer acting as the OPENER — the
                                    flipped idx entry may live in
                                    another writer's file
``    rounds/r-<round>.done``       marker: total entry count across all
                                    writers when the round fully decided
                                    (stale the moment a later submit
                                    grows the total — compared, never
                                    trusted blindly)
``    index.tbl``                   open-addressing token hash (a HINT:
                                    every candidate is verified against
                                    the full 16-byte token in the idx
                                    record; rebuilt from idx files when
                                    torn)
``    counters.bin``                24 bytes: submitted/opened/rejected
                                    totals for THIS writer's operations

Writers: multi-worker relays sharing one ``--timelock-db`` under
``relay --workers K`` each construct ``SegmentVault(path, writer_id=i)``
and append ONLY inside their own ``w<NN>/`` directory — no two
processes ever append to the same file, which is what makes the shared
vault safe without cross-process locking. Everyone READS every writer's
files; the only cross-writer WRITE is the entry flip in
:meth:`finish_round` (a 64-byte pwrite at a fixed offset — disjoint
offsets per row, and only this worker's token shard flips here, so two
sweepers never race one entry). Two processes claiming the SAME
``writer_id`` would interleave appends and corrupt that directory —
the relay parent hands each worker a distinct shard index.

O(1)-at-depth: ``status(token)`` is one hash probe + one 64-byte pread;
``pending_count()`` sums three counters per writer (no scan). Counter
drift after a crash between an append and its counter write is bounded
by the in-flight batch and self-heals as those rows decide; the
authoritative state is always the idx records.

Durability matches segments.py: raw-fd writes reach the OS per
operation (no user-space buffering), no fsync — a crash can lose the
last instants of writes but never corrupts earlier records, and a row
whose hash insert was lost is still found by the sweep (idx scan) and
re-indexed when it decides.
"""

from __future__ import annotations

import json
import mmap
import os
import struct
import threading
import time

from .vault import TimelockVault, VaultError

META_FILE = "meta.json"
_META_VERSION = 1

# record statuses (never 0: a zero status byte marks a torn append)
_S_PENDING = 1
_S_OPENED = 2
_S_REJECTED = 3
_STATUS_NAME = {_S_PENDING: "pending", _S_OPENED: "opened",
                _S_REJECTED: "rejected"}

# 64-byte idx record: status, out_writer, reserved, token, envelope
# blob (off, len) in .dat, outcome blob (off, len) in out_writer's
# .out, submitted/opened timestamps
_REC = struct.Struct("<BBH16sQIQIdd4x")
REC_SIZE = _REC.size
_IDX_HDR = b"DTVRIDX1" + b"\x00" * 8
IDX_HDR_SIZE = len(_IDX_HDR)

_MAX_OPEN_FDS = 64

assert REC_SIZE == 64, REC_SIZE
assert IDX_HDR_SIZE == 16


# ---------------------------------------------------------------- shards
# Token-range partitioning for multi-worker sweeps. The shard space is
# [0, 2^256) per the serving spec; tokens are 128-bit blake2b digests
# (service.envelope_token) that embed at the TOP of the space, so the
# 256-bit shard bounds project onto 32-hex-char token bounds exactly
# (shard k's token range is [ceil(lo/2^128), ceil(hi/2^128)) — adjacent
# shards share the ceiling, so the projection stays disjoint+covering).

SHARD_SPACE_BITS = 256
TOKEN_HEX_CHARS = 32
_SPACE = 1 << SHARD_SPACE_BITS
_TOKEN_SPACE = 1 << (4 * TOKEN_HEX_CHARS)
_PROJ = _SPACE // _TOKEN_SPACE


def shard_bounds(index: int, count: int) -> tuple[int, int]:
    """[lo, hi) of shard ``index`` of ``count`` over [0, 2^256)."""
    if count < 1 or not 0 <= index < count:
        raise ValueError(f"bad shard {index}/{count}")
    return _SPACE * index // count, _SPACE * (index + 1) // count


def shard_hex_bounds(index: int, count: int) -> tuple[str, str | None]:
    """Shard bounds projected onto 32-hex-char tokens: ``(lo_hex,
    hi_hex)`` with ``hi_hex None`` for the top shard (no upper bound).
    Both backends filter with plain string compares — lowercase hex of
    equal length orders identically to the integers."""
    lo, hi = shard_bounds(index, count)
    lo_t = -(-lo // _PROJ)
    hi_t = -(-hi // _PROJ)
    lo_hex = format(lo_t, "032x")
    hi_hex = None if hi_t >= _TOKEN_SPACE else format(hi_t, "032x")
    return lo_hex, hi_hex


def token_in_shard(token: str, index: int, count: int) -> bool:
    lo_hex, hi_hex = shard_hex_bounds(index, count)
    return token >= lo_hex and (hi_hex is None or token < hi_hex)


def _raw_token(token: str) -> bytes:
    """Tokens are 32-hex blake2b digests (service.envelope_token); the
    fixed-width record embeds the 16 raw bytes. Anything else cannot
    round-trip through the record and is rejected up front."""
    if not isinstance(token, str) or len(token) != TOKEN_HEX_CHARS:
        raise VaultError(
            f"segment vault tokens are {TOKEN_HEX_CHARS}-char hex "
            f"ciphertext ids, got {token!r}")
    try:
        return bytes.fromhex(token)
    except ValueError:
        raise VaultError(
            f"segment vault tokens are {TOKEN_HEX_CHARS}-char hex "
            f"ciphertext ids, got {token!r}")


# ------------------------------------------------------------ hash index

class _TableTorn(Exception):
    """index.tbl unreadable/mismatched — rebuild from idx files."""


class _HashIndex:
    """Open-addressing token index: mmap'd file of 24-byte slots.

    slot = (token_prefix8, round+1, seq, writer) — ``round+1`` doubles
    as the occupancy flag (rounds start at 1, so 0 = empty). Linear
    probing; no deletes (rows are write-once). The prefix is only a
    filter: the caller verifies the full token against the idx record,
    so a prefix collision just probes on. Grows by rewrite+rename at
    load 0.5 so foreign readers can detect replacement via st_ino."""

    _HDR = struct.Struct("<8sQQ8x")
    _SLOT = struct.Struct("<8sQIH2x")
    _MAGIC = b"DTVLTBL1"
    _MIN_SLOTS = 1024

    def __init__(self, path: str, create: bool):
        self._path = path
        self._mm: mmap.mmap | None = None
        self._fd = -1
        self.nslots = 0
        self.used = 0
        if not os.path.exists(path):
            if not create:
                raise _TableTorn(f"no table at {path}")
            self._write_fresh(self._MIN_SLOTS)
        self._open()

    def _write_fresh(self, nslots: int) -> None:
        tmp = self._path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(self._HDR.pack(self._MAGIC, nslots, 0))
            f.truncate(self._HDR.size + nslots * self._SLOT.size)
        os.replace(tmp, self._path)

    def _open(self) -> None:
        self.close()
        self._fd = os.open(self._path, os.O_RDWR)
        size = os.fstat(self._fd).st_size
        if size < self._HDR.size:
            os.close(self._fd)
            self._fd = -1
            raise _TableTorn(f"truncated table {self._path}")
        self._mm = mmap.mmap(self._fd, size)
        magic, nslots, used = self._HDR.unpack_from(self._mm, 0)
        if (magic != self._MAGIC or nslots < 1
                or nslots & (nslots - 1)
                or size != self._HDR.size + nslots * self._SLOT.size):
            self.close()
            raise _TableTorn(f"bad table header {self._path}")
        self.nslots = nslots
        self.used = used

    def close(self) -> None:
        if self._mm is not None:
            self._mm.flush()
            self._mm.close()
            self._mm = None
        if self._fd >= 0:
            os.close(self._fd)
            self._fd = -1

    def flush(self) -> None:
        if self._mm is not None:
            self._HDR.pack_into(self._mm, 0, self._MAGIC, self.nslots,
                                self.used)

    # -- probing ------------------------------------------------------
    _ZERO8 = b"\x00" * 8

    def candidates(self, raw: bytes) -> list[tuple[int, int, int]]:
        """Every (writer, round, seq) whose prefix matches ``raw`` —
        verified against the idx record by the caller. The probe loop
        compares raw bytes and unpacks a slot only on a prefix hit, so
        the displacement path (every probe but the last at load 0.5)
        costs two 8-byte slices, not a 4-object struct tuple — this is
        the innermost loop of every status() read."""
        mm, hdr, size = self._mm, self._HDR.size, self._SLOT.size
        mask = self.nslots - 1
        p8 = raw[:8]
        i = int.from_bytes(p8, "big") & mask
        out = []
        for _ in range(self.nslots):
            off = hdr + i * size
            if mm[off + 8:off + 16] == self._ZERO8:  # round+1 == 0: empty
                break
            if mm[off:off + 8] == p8:
                _, rd1, seq, wid = self._SLOT.unpack_from(mm, off)
                out.append((wid, rd1 - 1, seq))
            i = (i + 1) & mask
        return out

    def insert(self, raw: bytes, round_no: int, seq: int,
               writer: int) -> None:
        if (self.used + 1) * 2 >= self.nslots:
            self._grow(self.nslots * 4)
        mm, hdr, slot = self._mm, self._HDR.size, self._SLOT
        mask = self.nslots - 1
        i = int.from_bytes(raw[:8], "big") & mask
        rec = slot.pack(raw[:8], round_no + 1, seq, writer)
        for _ in range(self.nslots + 1):
            off = hdr + i * slot.size
            if mm[off + 8:off + 16] == b"\x00" * 8:  # round+1 == 0
                mm[off:off + slot.size] = rec
                self.used += 1
                return
            if mm[off:off + slot.size] == rec:
                return  # exact duplicate (heal replay)
            i = (i + 1) & mask
        raise VaultError("token index full (grow failed?)")

    def reserve(self, extra: int) -> None:
        """Pre-size for ``extra`` further inserts (bulk loads: one
        rebuild instead of log-many)."""
        need = (self.used + extra) * 2 + 1
        target = self.nslots
        while target < need:
            target *= 2
        if target > self.nslots:
            self._grow(target)

    def _grow(self, nslots: int) -> None:
        hdr, slot = self._HDR.size, self._SLOT
        buf = bytearray(hdr + nslots * slot.size)
        self._HDR.pack_into(buf, 0, self._MAGIC, nslots, self.used)
        mask = nslots - 1
        old = self._mm
        for j in range(self.nslots):
            off = hdr + j * slot.size
            rec = old[off:off + slot.size]
            if rec[8:16] == b"\x00" * 8:
                continue
            i = int.from_bytes(rec[:8], "big") & mask
            while True:
                noff = hdr + i * slot.size
                if buf[noff + 8:noff + 16] == b"\x00" * 8:
                    buf[noff:noff + slot.size] = rec
                    break
                i = (i + 1) & mask
        tmp = self._path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(buf)
        os.replace(tmp, self._path)
        self._open()


# ----------------------------------------------------------- the vault

class _Fd:
    """Raw fd + tracked end offset (appends are pwrites at the end —
    no user-space buffer, so every write reaches the OS immediately)."""

    __slots__ = ("fd", "end")

    def __init__(self, fd: int):
        self.fd = fd
        self.end = os.fstat(fd).st_size


class SegmentVault:
    """Drop-in :class:`~.vault.TimelockVault` replacement over per-round
    segment files (module docstring has the layout). ``writer_id``
    names this process's exclusive append directory."""

    def __init__(self, path: str, writer_id: int = 0):
        if not 0 <= int(writer_id) < 100:
            raise VaultError(f"writer_id out of range: {writer_id}")
        self._dir = path
        self._wid = int(writer_id)
        os.makedirs(path, exist_ok=True)
        self._check_meta()
        self._wdir = os.path.join(path, f"w{self._wid:02d}")
        os.makedirs(os.path.join(self._wdir, "rounds"), exist_ok=True)
        self._lock = threading.Lock()
        self._fds: dict[tuple[int, int, str], _Fd] = {}  # LRU, cap 64
        self._tables: dict[int, _HashIndex] = {}
        self._table_sig: dict[int, tuple] = {}
        self._counter_fds: dict[int, _Fd] = {}
        self._writer_ids: list[int] = []
        self._closed = False
        # bound child resolved once: labels() is a lock + dict probe
        # per call, measurable on the O(1) get path it would meter
        from .. import metrics

        self._reads_inc = metrics.VAULT_READS.labels(
            backend="segment").inc
        self._refresh_writers()
        with self._lock:
            self._own_table()
            sub, op, rej = self._read_counters(self._wid)
            self._c_sub, self._c_op, self._c_rej = sub, op, rej

    # ------------------------------------------------------- plumbing
    def _check_meta(self) -> None:
        meta_path = os.path.join(self._dir, META_FILE)
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                meta = json.load(f)
            if meta.get("version") != _META_VERSION:
                raise VaultError(
                    f"vault segment dir {self._dir} is version "
                    f"{meta.get('version')!r}, this build speaks "
                    f"v{_META_VERSION}")
        else:
            with open(meta_path, "w") as f:
                json.dump({"version": _META_VERSION,
                           "kind": "timelock-vault"}, f)

    def _refresh_writers(self) -> None:
        ids = []
        for name in os.listdir(self._dir):
            if len(name) == 3 and name[0] == "w" and name[1:].isdigit():
                ids.append(int(name[1:]))
        if self._wid not in ids:
            ids.append(self._wid)
        self._writer_ids = sorted(ids)

    def _round_path(self, wid: int, rd: int, ext: str) -> str:
        wdir = self._wdir if wid == self._wid else \
            os.path.join(self._dir, f"w{wid:02d}")
        return os.path.join(wdir, "rounds", f"r-{rd:012d}.{ext}")

    def _fh(self, wid: int, rd: int, ext: str,
            create: bool = False) -> _Fd | None:
        key = (wid, rd, ext)
        fh = self._fds.get(key)
        if fh is not None:
            # LRU re-insert (dicts preserve order; pop+set = move to end)
            del self._fds[key]
            self._fds[key] = fh
            return fh
        path = self._round_path(wid, rd, ext)
        flags = os.O_RDWR
        if create and wid == self._wid:
            flags |= os.O_CREAT
        try:
            fd = os.open(path, flags, 0o644)
        except FileNotFoundError:
            return None
        fh = _Fd(fd)
        if ext == "idx" and fh.end == 0:
            os.pwrite(fd, _IDX_HDR, 0)
            fh.end = IDX_HDR_SIZE
        while len(self._fds) >= _MAX_OPEN_FDS:
            oldest = next(iter(self._fds))
            os.close(self._fds.pop(oldest).fd)
        self._fds[key] = fh
        return fh

    def _append(self, fh: _Fd, blob: bytes) -> tuple[int, int]:
        os.pwrite(fh.fd, blob, fh.end)
        off = fh.end
        fh.end += len(blob)
        return off, len(blob)

    def _idx_count(self, fh: _Fd, wid: int) -> int:
        end = fh.end if wid == self._wid else os.fstat(fh.fd).st_size
        return max(0, (end - IDX_HDR_SIZE)) // REC_SIZE

    def _read_entry(self, fh: _Fd, seq: int):
        data = os.pread(fh.fd, REC_SIZE, IDX_HDR_SIZE + seq * REC_SIZE)
        if len(data) != REC_SIZE:
            return None
        return _REC.unpack(data)

    def _write_entry(self, fh: _Fd, seq: int, rec: bytes) -> None:
        os.pwrite(fh.fd, rec, IDX_HDR_SIZE + seq * REC_SIZE)

    # -- hash tables --------------------------------------------------
    def _own_table(self) -> _HashIndex:
        tbl = self._tables.get(self._wid)
        if tbl is None:
            path = os.path.join(self._wdir, "index.tbl")
            try:
                tbl = _HashIndex(path, create=True)
            except _TableTorn:
                os.unlink(path)
                tbl = _HashIndex(path, create=True)
                self._rebuild_table(tbl)
            self._tables[self._wid] = tbl
        return tbl

    def _rebuild_table(self, tbl: _HashIndex) -> None:
        """Re-index every own idx record (torn table recovery)."""
        for rd in self._rounds_of(self._wid):
            fh = self._fh(self._wid, rd, "idx")
            if fh is None:
                continue
            for seq in range(self._idx_count(fh, self._wid)):
                e = self._read_entry(fh, seq)
                if e is not None and e[0] in _STATUS_NAME:
                    tbl.insert(e[3], rd, seq, self._wid)
        tbl.flush()

    def _table(self, wid: int) -> _HashIndex | None:
        if wid == self._wid:
            return self._own_table()
        path = os.path.join(self._dir, f"w{wid:02d}", "index.tbl")
        try:
            st = os.stat(path)
        except FileNotFoundError:
            tbl = self._tables.pop(wid, None)
            if tbl is not None:
                tbl.close()
            return None
        sig = (st.st_ino, st.st_size)
        if self._table_sig.get(wid) != sig:
            tbl = self._tables.pop(wid, None)
            if tbl is not None:
                tbl.close()
            try:
                self._tables[wid] = _HashIndex(path, create=False)
                self._table_sig[wid] = sig
            except _TableTorn:
                return None
        return self._tables.get(wid)

    # -- counters -----------------------------------------------------
    def _counter_fh(self, wid: int) -> _Fd | None:
        fh = self._counter_fds.get(wid)
        if fh is None:
            path = os.path.join(self._dir, f"w{wid:02d}", "counters.bin")
            flags = os.O_RDWR | (os.O_CREAT if wid == self._wid else 0)
            try:
                fd = os.open(path, flags, 0o644)
            except FileNotFoundError:
                return None
            fh = _Fd(fd)
            self._counter_fds[wid] = fh
        return fh

    def _read_counters(self, wid: int) -> tuple[int, int, int]:
        fh = self._counter_fh(wid)
        if fh is None:
            return 0, 0, 0
        data = os.pread(fh.fd, 24, 0)
        if len(data) < 24:
            return 0, 0, 0
        return struct.unpack("<QQQ", data)

    def _write_counters(self) -> None:
        fh = self._counter_fh(self._wid)
        os.pwrite(fh.fd, struct.pack(
            "<QQQ", self._c_sub, self._c_op, self._c_rej), 0)

    # -- rounds -------------------------------------------------------
    def _rounds_of(self, wid: int) -> list[int]:
        rdir = os.path.join(self._dir, f"w{wid:02d}", "rounds")
        try:
            names = os.listdir(rdir)
        except FileNotFoundError:
            return []
        out = []
        for name in names:
            if name.startswith("r-") and name.endswith(".idx"):
                out.append(int(name[2:-4]))
        return sorted(out)

    def _all_rounds(self) -> list[int]:
        rounds: set[int] = set()
        for wid in self._writer_ids:
            rounds.update(self._rounds_of(wid))
        return sorted(rounds)

    def _done_total(self, rd: int) -> int | None:
        """Max recorded done-marker count for a round, None if none."""
        best = None
        for wid in self._writer_ids:
            path = self._round_path(wid, rd, "done")
            try:
                with open(path) as f:
                    n = int(f.read().strip() or 0)
            except (FileNotFoundError, ValueError):
                continue
            best = n if best is None else max(best, n)
        return best

    def _round_totals(self, rd: int) -> tuple[int, int]:
        """(total entries, pending entries) for a round, all writers."""
        total = pending = 0
        for wid in self._writer_ids:
            fh = self._fh(wid, rd, "idx")
            if fh is None:
                continue
            n = self._idx_count(fh, wid)
            total += n
            if n:
                data = os.pread(fh.fd, n * REC_SIZE, IDX_HDR_SIZE)
                pending += sum(
                    1 for i in range(len(data) // REC_SIZE)
                    if data[i * REC_SIZE] == _S_PENDING)
        return total, pending

    def _mark_done(self, rd: int) -> None:
        total, pending = self._round_totals(rd)
        if pending == 0 and total > 0:
            path = self._round_path(self._wid, rd, "done")
            with open(path, "w") as f:
                f.write(str(total))

    # -- location -----------------------------------------------------
    def _locate(self, raw: bytes, round_hint: int | None = None
                ) -> list[tuple[int, int, int, tuple, bool]]:
        """Every idx record holding this token, across writers:
        (entry_writer, round, seq, record, via_scan). Retries once
        after a writer-list refresh (another worker's directory may
        have appeared since init)."""
        for attempt in (0, 1):
            seen: set[tuple[int, int, int]] = set()
            out = []
            for wid in self._writer_ids:
                tbl = self._table(wid)
                if tbl is None:
                    continue
                for ewid, rd, seq in tbl.candidates(raw):
                    key = (ewid, rd, seq)
                    if key in seen:
                        continue
                    seen.add(key)
                    fh = self._fh(ewid, rd, "idx")
                    if fh is None:
                        continue
                    e = self._read_entry(fh, seq)
                    if e is not None and e[3] == raw:
                        out.append((ewid, rd, seq, e, False))
            if not out and round_hint is not None:
                # torn hash (crash between the idx append and the
                # insert): the record is still authoritative — scan
                # the hinted round
                for wid in self._writer_ids:
                    fh = self._fh(wid, round_hint, "idx")
                    if fh is None:
                        continue
                    for seq in range(self._idx_count(fh, wid)):
                        e = self._read_entry(fh, seq)
                        if e is not None and e[3] == raw:
                            out.append((wid, round_hint, seq, e, True))
            if out or attempt:
                return out
            self._refresh_writers()
        return []

    # ------------------------------------------------------ public API
    def __len__(self) -> int:
        with self._lock:
            self._refresh_writers()
            total = 0
            for wid in self._writer_ids:
                if wid == self._wid:
                    total += self._c_sub
                else:
                    total += self._read_counters(wid)[0]
            return total

    def submit(self, token: str, round_no: int, envelope: dict) -> bool:
        raw = _raw_token(token)
        with self._lock:
            if self._locate(raw):
                return False
            blob = json.dumps(envelope, sort_keys=True).encode()
            dat = self._fh(self._wid, round_no, "dat", create=True)
            off, ln = self._append(dat, blob)
            idx = self._fh(self._wid, round_no, "idx", create=True)
            seq = self._idx_count(idx, self._wid)
            rec = _REC.pack(_S_PENDING, 0, 0, raw, off, ln, 0, 0,
                            time.time(), 0.0)
            self._write_entry(idx, seq, rec)
            idx.end = IDX_HDR_SIZE + (seq + 1) * REC_SIZE
            tbl = self._own_table()
            tbl.insert(raw, round_no, seq, self._wid)
            tbl.flush()
            self._c_sub += 1
            self._write_counters()
            return True

    def get(self, token: str, with_envelope: bool = True) -> dict | None:
        try:
            raw = _raw_token(token)
        except VaultError:
            return None  # a shape no row can have = unknown id
        with self._lock:
            locs = self._locate(raw)
            if not locs:
                return None
            self._reads_inc()
            # a decided copy wins over a pending duplicate (immutable
            # rows are the serving surface; duplicates only arise from
            # a cross-worker double-submit race)
            if len(locs) > 1:
                locs.sort(
                    key=lambda loc: 0 if loc[3][0] != _S_PENDING else 1)
            ewid, rd, seq, e, _ = locs[0]
            (status, out_writer, _r0, _tok, env_off, env_len,
             out_off, out_len, submitted, opened_ts) = e
            rec = {"id": token, "round": rd, "envelope": None,
                   "status": _STATUS_NAME.get(status, "pending"),
                   "plaintext": None, "error": None,
                   "submitted": submitted,
                   "opened": opened_ts if status != _S_PENDING else None}
            if with_envelope:
                dat = self._fh(ewid, rd, "dat")
                if dat is not None:
                    rec["envelope"] = json.loads(
                        os.pread(dat.fd, env_len, env_off))
            if status != _S_PENDING:
                out = self._fh(out_writer, rd, "out")
                blob = (os.pread(out.fd, out_len, out_off)
                        if out is not None else b"")
                if status == _S_OPENED:
                    rec["plaintext"] = blob
                else:
                    rec["error"] = blob.decode("utf-8", "replace")
            return rec

    def pending_rounds(self, up_to: int | None = None) -> list[int]:
        with self._lock:
            self._refresh_writers()
            out = []
            for rd in self._all_rounds():
                if up_to is not None and rd > up_to:
                    continue
                total, pending = self._round_totals(rd)
                done = self._done_total(rd)
                if done is not None and done == total and pending == 0:
                    continue
                if pending:
                    out.append(rd)
                elif total:
                    # fully decided but unmarked (opener crashed before
                    # its marker): write ours so the sweep stops
                    # rescanning this round forever
                    self._mark_done(rd)
            return out

    def pending_for_round(self, round_no: int,
                          shard: tuple[int, int] | None = None
                          ) -> list[tuple[str, dict]]:
        lo_hex = hi_hex = None
        if shard is not None:
            lo_hex, hi_hex = shard_hex_bounds(*shard)
        with self._lock:
            self._refresh_writers()
            out = []
            for wid in self._writer_ids:
                fh = self._fh(wid, round_no, "idx")
                if fh is None:
                    continue
                dat = self._fh(wid, round_no, "dat")
                for seq in range(self._idx_count(fh, wid)):
                    e = self._read_entry(fh, seq)
                    if e is None or e[0] != _S_PENDING:
                        continue
                    tok = e[3].hex()
                    if lo_hex is not None and (
                            tok < lo_hex
                            or (hi_hex is not None and tok >= hi_hex)):
                        continue
                    env = json.loads(os.pread(dat.fd, e[5], e[4]))
                    out.append((e[8], tok, env))
            out.sort(key=lambda t: (t[0], t[1]))
            return [(tok, env) for _, tok, env in out]

    def pending_count(self) -> int:
        with self._lock:
            self._refresh_writers()
            sub = op = rej = 0
            for wid in self._writer_ids:
                if wid == self._wid:
                    sub += self._c_sub
                    op += self._c_op
                    rej += self._c_rej
                else:
                    s, o, r = self._read_counters(wid)
                    sub += s
                    op += o
                    rej += r
            return max(0, sub - op - rej)

    def finish_round(self, results: list[tuple[str, bool, bytes, str]],
                     round_no: int | None = None) -> tuple[int, int]:
        """Persist open outcomes; only pending records transition (rows
        already decided by a concurrent sweep are skipped, matching the
        SQLite backend). Outcome blobs land in THIS writer's .out files
        first, then the 64-byte entry flips in place — a crash between
        the two leaves the row pending and the next sweep re-opens it
        (the orphan blob is harmless)."""
        now = time.time()
        opened = rejected = 0
        touched: set[int] = set()
        with self._lock:
            for token, ok, plaintext, error in results:
                raw = _raw_token(token)
                locs = [loc for loc in self._locate(raw, round_no)
                        if loc[3][0] == _S_PENDING]
                if not locs:
                    continue
                blob = (plaintext if ok
                        else (error or "")[:300].encode())
                out_fh = self._fh(self._wid, locs[0][1], "out",
                                  create=True)
                out_off, out_len = self._append(out_fh, blob)
                for ewid, rd, seq, e, via_scan in locs:
                    rec = _REC.pack(
                        _S_OPENED if ok else _S_REJECTED, self._wid, 0,
                        raw, e[4], e[5], out_off, out_len, e[8], now)
                    fh = self._fh(ewid, rd, "idx")
                    self._write_entry(fh, seq, rec)
                    touched.add(rd)
                    if via_scan:
                        # heal the torn hash so status() finds the
                        # decided row without a hint
                        tbl = self._own_table()
                        tbl.insert(raw, rd, seq, ewid)
                        tbl.flush()
                if ok:
                    opened += 1
                else:
                    rejected += 1
            self._c_op += opened
            self._c_rej += rejected
            self._write_counters()
            for rd in touched:
                self._mark_done(rd)
        return opened, rejected

    def set_opened(self, token: str, plaintext: bytes) -> None:
        self._finish_one(token, True, plaintext, None)

    def set_rejected(self, token: str, error: str) -> None:
        self._finish_one(token, False, None, error)

    def _finish_one(self, token: str, ok: bool,
                    plaintext: bytes | None, error: str | None) -> None:
        opened, rejected = self.finish_round(
            [(token, ok, plaintext or b"", error or "")])
        if opened + rejected != 1:
            raise VaultError(
                f"ciphertext {token} is not pending (double open?)")

    # -- migration ----------------------------------------------------
    def rows(self):
        """Every record, ordered by (round, submitted, token) — the
        migration surface. Envelopes come back as their RAW stored JSON
        string so SQLite<->segment round-trips are byte-exact with zero
        re-encoding."""
        with self._lock:
            self._refresh_writers()
            rounds = self._all_rounds()
        for rd in rounds:
            with self._lock:
                recs = []
                for wid in self._writer_ids:
                    fh = self._fh(wid, rd, "idx")
                    if fh is None:
                        continue
                    dat = self._fh(wid, rd, "dat")
                    for seq in range(self._idx_count(fh, wid)):
                        e = self._read_entry(fh, seq)
                        if e is None or e[0] not in _STATUS_NAME:
                            continue
                        env = os.pread(dat.fd, e[5], e[4]).decode()
                        plaintext = error = None
                        if e[0] != _S_PENDING:
                            out = self._fh(e[1], rd, "out")
                            blob = (os.pread(out.fd, e[7], e[6])
                                    if out is not None else b"")
                            if e[0] == _S_OPENED:
                                plaintext = blob
                            else:
                                error = blob.decode("utf-8", "replace")
                        recs.append({
                            "id": e[3].hex(), "round": rd,
                            "envelope": env,
                            "status": _STATUS_NAME[e[0]],
                            "plaintext": plaintext, "error": error,
                            "submitted": e[8],
                            "opened": e[9] if e[0] != _S_PENDING
                            else None,
                        })
            recs.sort(key=lambda r: (r["submitted"], r["id"]))
            yield from recs

    def put_rows(self, rows, size_hint: int | None = None) -> int:
        """Bulk-load full records (migration / bench fixtures) into
        THIS writer's directory. No per-row duplicate check — sources
        are vaults, whose ids are unique by construction."""
        count = 0
        with self._lock:
            tbl = self._own_table()
            if size_hint:
                tbl.reserve(size_hint)
            touched: set[int] = set()
            for rec in rows:
                raw = _raw_token(rec["id"])
                rd = rec["round"]
                env = rec["envelope"]
                blob = (env.encode() if isinstance(env, str)
                        else json.dumps(env, sort_keys=True).encode())
                dat = self._fh(self._wid, rd, "dat", create=True)
                env_off, env_len = self._append(dat, blob)
                status = {"pending": _S_PENDING, "opened": _S_OPENED,
                          "rejected": _S_REJECTED}.get(rec["status"])
                if status is None:
                    raise VaultError(
                        f"unknown row status {rec['status']!r}")
                out_off = out_len = 0
                if status != _S_PENDING:
                    ob = (rec["plaintext"] if status == _S_OPENED
                          else (rec["error"] or "").encode())
                    out = self._fh(self._wid, rd, "out", create=True)
                    out_off, out_len = self._append(out, ob or b"")
                idx = self._fh(self._wid, rd, "idx", create=True)
                seq = self._idx_count(idx, self._wid)
                self._write_entry(idx, seq, _REC.pack(
                    status, self._wid, 0, raw, env_off, env_len,
                    out_off, out_len, rec["submitted"],
                    rec["opened"] or 0.0))
                idx.end = IDX_HDR_SIZE + (seq + 1) * REC_SIZE
                tbl.insert(raw, rd, seq, self._wid)
                self._c_sub += 1
                if status == _S_OPENED:
                    self._c_op += 1
                elif status == _S_REJECTED:
                    self._c_rej += 1
                touched.add(rd)
                count += 1
            tbl.flush()
            self._write_counters()
            for rd in touched:
                self._mark_done(rd)
        return count

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            for fh in self._fds.values():
                os.close(fh.fd)
            self._fds.clear()
            for tbl in self._tables.values():
                tbl.flush()
                tbl.close()
            self._tables.clear()
            for fh in self._counter_fds.values():
                os.close(fh.fd)
            self._counter_fds.clear()


# ------------------------------------------------------------- factory

def is_segment_vault(path: str) -> bool:
    return os.path.isdir(path) and os.path.exists(
        os.path.join(path, META_FILE))


def open_vault(path: str, writer_id: int = 0):
    """The one place backend selection happens: explicit
    ``DRAND_TPU_TIMELOCK_STORE=segment`` opts in, an existing segment
    dir at ``path`` keeps opening as one (a daemon restarted WITHOUT
    the env var must not silently start a fresh SQLite vault next to
    its data), SQLite stays the default."""
    backend = os.environ.get("DRAND_TPU_TIMELOCK_STORE", "").strip()
    if backend not in ("", "sqlite", "segment"):
        raise VaultError(
            f"unknown DRAND_TPU_TIMELOCK_STORE={backend!r} "
            f"(sqlite|segment)")
    if backend == "segment" or is_segment_vault(path):
        return SegmentVault(path, writer_id=writer_id)
    return TimelockVault(path)


def migrate_vault(src, dst) -> int:
    """Copy every row src -> dst (either backend direction). Returns
    the row count."""
    size_hint = len(src)
    if isinstance(dst, SegmentVault):
        return dst.put_rows(src.rows(), size_hint=size_hint)
    return dst.put_rows(src.rows())
