"""Timelock serving tier (ISSUE 9): a round-boundary decryption vault.

Clients POST ciphertexts locked to future rounds (the tlock "encrypt to
the future" scheme over unchained V2 signatures — crypto/timelock.py,
client/timelock.py); the vault persists them keyed by round and, when the
chain reaches a round, opens EVERY pending ciphertext for it in one
batched dispatch (crypto/batch.decrypt_round_batch: device GT graph with
the round signature's Miller lines computed once, host shared-signature
tier otherwise).

- :class:`TimelockVault` (vault.py): the persistent store — the
  chain/store.py single-writer SQLite pattern, surviving daemon restart.
- :class:`SegmentVault` (segvault.py, ISSUE 20): the planet-scale
  backend — per-round segment files with fixed-width records, an O(1)
  token index and counter-backed ``pending_count``; opt-in via
  ``DRAND_TPU_TIMELOCK_STORE=segment``, convertible both ways with
  ``util store-migrate --vault``.
- :class:`TimelockService` (service.py): submit validation, the
  round-boundary open (hooked off the DiscrepancyStore
  ``note_round_complete`` path AND the PublicServer watch loop, so both
  daemons and relays open at the boundary), and the catch-up sweep that
  opens rounds missed while the process was down.
- HTTP surface: ``POST /timelock`` + ``GET /timelock/{id}`` on
  ``PublicServer`` (http_server/server.py) — opened results are
  immutable and served with an ETag.
"""

from .vault import TimelockVault, VaultError
from .segvault import SegmentVault, migrate_vault, open_vault
from .service import TimelockService, TimelockError, note_round_complete

__all__ = ["TimelockVault", "VaultError", "SegmentVault",
           "migrate_vault", "open_vault", "TimelockService",
           "TimelockError", "note_round_complete"]
