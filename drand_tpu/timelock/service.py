"""The timelock serving logic: submit validation + round-boundary opens.

One :class:`TimelockService` fronts a :class:`~.vault.TimelockVault` and
a chain client. Submissions are validated against the chain (scheme
version, envelope shape, the cross-chain ``chain_hash`` binding, size
caps) and persisted pending; when the chain reaches a round, every
pending ciphertext for it (this worker's token shard of them, when the
sweep is partitioned) opens in ceil(K/DRAND_TPU_TIMELOCK_OPEN_CHUNK)
``crypto/batch.decrypt_round_batch`` dispatches (device GT graph or
host shared-signature tier — both hoist the round signature's Miller
work out of the per-item loop), each followed by its own vault commit
and a cooperative yield (ISSUE 20 bounded opens).

Round boundaries arrive two ways, both funnelling into the same
idempotent sweep:

- the daemon's store path: ``DiscrepancyStore.put`` calls this module's
  :func:`note_round_complete` next to the OTLP exporter's (the "existing
  note_round_complete path" — ISSUE 9), thread-safe because aggregation
  runs in ``asyncio.to_thread`` workers;
- the PublicServer watch loop (:meth:`TimelockService.on_result`), which
  also covers relays that have no local store.

A catch-up sweep at service start (and on every boundary) opens rounds
that passed while the process was down — vault state survives restarts.

Event-loop discipline (tools/analyze loopblock): every vault/sqlite call
from async code goes through ``asyncio.to_thread``; the batched decrypt
(pairing-class) likewise; fire-and-forget opens go through
``drand_tpu.utils.aio.spawn``.
"""

from __future__ import annotations

import asyncio
import hashlib
import os
import weakref

from ..chain.beacon import Beacon
from ..client import timelock as client_timelock
from ..client.interface import Client, ClientError, Result, \
    result_from_beacon
from ..crypto import batch
from ..utils.aio import spawn
from ..utils.logging import KVLogger, default_logger
from ..utils.retry import RetryPolicy, retry
from .vault import TimelockVault

# upstream round fetches inside the sweep retry under the shared policy
# (ISSUE 12): a transient relay/origin blip must not leave a whole
# round's ciphertexts pending until the NEXT boundary
_FETCH_POLICY = RetryPolicy(attempts=3, base_s=0.2, cap_s=2.0)

# submission caps: W (the masked payload) and the global pending backlog
MAX_PLAINTEXT = int(os.environ.get("DRAND_TPU_TIMELOCK_MAX_BYTES",
                                   str(64 * 1024)))
MAX_PENDING = int(os.environ.get("DRAND_TPU_TIMELOCK_MAX_PENDING",
                                 str(100_000)))


class TimelockError(Exception):
    """Submission/validation failure (HTTP layer maps it to 4xx)."""


def canonical_envelope(envelope: dict, parsed) -> dict:
    """The envelope re-encoded from its PARSED values — what the vault
    stores and the token hashes. Tokenizing the client's strings would
    let one ciphertext mint unlimited distinct vault rows (junk keys,
    hex case, non-canonical base64 trailing bits, omitted-vs-explicit
    version, bool-typed round) — re-encoding collapses every malleable
    representation of the same ciphertext to one row."""
    import base64

    canon = {
        "v": client_timelock.SCHEME_VERSION,
        "round": int(envelope["round"]),
        "U": parsed.u.hex(),
        "V": base64.b64encode(parsed.v).decode(),
        "W": base64.b64encode(parsed.w).decode(),
    }
    bound = envelope.get("chain_hash")
    if bound:
        canon["chain_hash"] = bound.lower()
    return canon


def _token_of_canonical(canon: dict) -> str:
    return hashlib.blake2b(client_timelock.dumps(canon).encode(),
                           digest_size=16).hexdigest()


def envelope_token(envelope: dict) -> str:
    """Deterministic ciphertext id: the blake2b of the canonical
    (parsed-value) envelope JSON — a client retrying a submit gets the
    same id back instead of a duplicate vault row, in ANY equivalent
    encoding of the same ciphertext."""
    parsed = client_timelock.parse_envelope(envelope)
    return _token_of_canonical(canonical_envelope(envelope, parsed))


class TimelockService:
    def __init__(self, vault: TimelockVault, client: Client,
                 logger: KVLogger | None = None,
                 shard: tuple[int, int] | None = None):
        self._vault = vault
        self._client = client
        self._l = logger or default_logger("timelock")
        self._info = None
        self._opening: set[int] = set()
        self._head = 0  # last chain head this service has seen
        self._tasks: set[asyncio.Future] = set()  # in-flight sweeps
        self._loop: asyncio.AbstractEventLoop | None = None
        # sweep partition (ISSUE 20): (index, count) restricts every
        # open to that token-range shard so `relay --workers K` workers
        # each drain a disjoint slice of a round instead of electing
        # worker 0 the sole sweeper; None = the whole token space
        if shard is not None and not 0 <= shard[0] < shard[1]:
            raise ValueError(f"bad timelock shard {shard}")
        self._shard = shard
        # bounded boundary opens: at most this many ciphertexts per
        # batched dispatch, a vault commit + cooperative yield between
        # chunks. Unset OR set-but-empty both mean the bounded default
        # (clearing the var is "reset", not an escape hatch); only an
        # explicit 0 selects the pre-ISSUE-20 unbounded monolithic open
        self._open_chunk = int(os.environ.get(
            "DRAND_TPU_TIMELOCK_OPEN_CHUNK") or 2048)
        # open-notify hook (http_server/fanout.TimelockNotifyHub):
        # called on the service loop with [(token, status, round)]
        # after each chunk COMMITS — a notified client re-fetching
        # GET /timelock/{id} always sees the decided row
        self._notify = None

    # ------------------------------------------------------------ lifecycle
    async def start(self) -> None:
        """Bind to the running loop and kick the catch-up sweep for
        rounds that completed while the process was down (restart
        persistence). The sweep is SPAWNED, not awaited: a large missed
        backlog must not hold the HTTP port unbound (PublicServer.start
        awaits this) while orchestrators probe a dead /healthz."""
        self._loop = asyncio.get_running_loop()
        register(self)
        from .. import metrics

        metrics.TIMELOCK_PENDING.set(
            await asyncio.to_thread(self._vault.pending_count))
        metrics.TIMELOCK_SWEEP_SHARDS.set(
            self._shard[1] if self._shard else 1)
        self._spawn_sweep(name="timelock-catchup")

    async def close(self) -> None:
        """Unhook, cancel in-flight sweeps, release the vault's sqlite
        handle (a daemon restart must not leak WAL connections)."""
        unregister(self)
        for task in list(self._tasks):
            task.cancel()
        if self._tasks:
            await asyncio.gather(*self._tasks, return_exceptions=True)
        await asyncio.to_thread(self._vault.close)

    def _spawn_sweep(self, result: Result | None = None,
                     name: str = "timelock-sweep") -> None:
        task = spawn(self._sweep(result), name=name)
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    def set_notifier(self, cb) -> None:
        """Wire the open-notify hub (PublicServer does this before
        start): ``cb([(token, status, round), ...])`` fires on the
        service loop after each chunk's vault commit."""
        self._notify = cb

    def opens_locally(self, token: str) -> bool:
        """True when THIS service's sweep is the one that decides
        ``token`` — unsharded, or the token falls inside this worker's
        token-range shard — so its open event will reach this
        process's notify hub. False means the open commits in ANOTHER
        worker process; the watch handler then falls back to polling
        the shared vault instead of waiting on a hub that will never
        publish for this id."""
        if self._shard is None:
            return True
        from .segvault import token_in_shard

        return token_in_shard(token, *self._shard)

    async def info(self):
        if self._info is None:
            got = await self._client.info()
            # re-check after the await (tools/analyze awaitatomic): a
            # boundary-burst of concurrent submits all see None and all
            # fetch — only the first result is published, so a slow
            # duplicate fetch can never clobber the cached info mid-use
            if self._info is None:
                self._info = got
        return self._info

    # ------------------------------------------------------------ submit
    async def submit(self, envelope: dict) -> dict:
        """Validate + persist one ciphertext; returns the status record.
        Raises :class:`TimelockError` on rejection."""
        try:
            parsed = client_timelock.parse_envelope(envelope)
        except ClientError as e:
            raise TimelockError(str(e))
        if len(parsed.w) > MAX_PLAINTEXT:
            raise TimelockError(
                f"payload too large: {len(parsed.w)} > {MAX_PLAINTEXT} "
                f"bytes (DRAND_TPU_TIMELOCK_MAX_BYTES)")
        try:
            info = await self.info()
        except ClientError as e:
            raise TimelockError(f"chain info unavailable: {e}")
        try:
            client_timelock.check_chain(envelope, info)
        except ClientError as e:
            raise TimelockError(str(e))
        envelope = canonical_envelope(envelope, parsed)
        token = _token_of_canonical(envelope)
        # idempotent-retry lookup BEFORE the backlog cap: a client
        # retrying an already-accepted submission must get its status
        # back even when the vault is full (retries cluster under load)
        if await asyncio.to_thread(self._vault.get, token,
                                   False) is not None:
            return await self.status(token)
        pending = await asyncio.to_thread(self._vault.pending_count)
        if pending >= MAX_PENDING:
            raise TimelockError(
                f"vault backlog full ({pending} pending ciphertexts)")
        fresh = await asyncio.to_thread(
            self._vault.submit, token, envelope["round"], envelope)
        from .. import metrics

        if fresh:
            metrics.TIMELOCK_CIPHERTEXTS.labels(result="submitted").inc()
            metrics.TIMELOCK_PENDING.set(pending + 1)
            self._l.info("timelock", "submitted", id=token,
                         round=envelope["round"])
            # the round may already be on chain (locked to the past, or
            # submitted in the boundary race) — sweep opportunistically,
            # but not for rounds beyond the last-seen head: the common
            # future-round submit must not cost a head fetch per POST
            # (head 0 = no boundary seen yet; the sweep resolves it)
            if self._head == 0 or envelope["round"] <= self._head:
                self._spawn_sweep(name=f"timelock-sweep-{token[:8]}")
        return await self.status(token)

    async def status(self, token: str) -> dict | None:
        """The public status record for one ciphertext id (None =
        unknown id)."""
        # with_envelope=False: the status record never returns the
        # envelope, and skipping it keeps the lookup one O(1) seek on
        # the segment backend
        rec = await asyncio.to_thread(self._vault.get, token, False)
        if rec is None:
            return None
        out = {"id": rec["id"], "round": rec["round"],
               "status": rec["status"], "submitted": rec["submitted"]}
        if rec["status"] == "opened":
            import base64

            out["plaintext"] = base64.b64encode(rec["plaintext"]).decode()
            out["opened"] = rec["opened"]
        elif rec["status"] == "rejected":
            out["error"] = rec["error"]
            out["opened"] = rec["opened"]
        return out

    # ------------------------------------------------- round boundaries
    def on_result(self, r: Result) -> None:
        """PublicServer watch-loop hook (loop thread): a new beacon
        landed — open everything due, carrying the fresh signature so
        the common case needs no extra fetch."""
        self._spawn_sweep(r, name=f"timelock-open-{r.round}")

    def note_beacon(self, b: Beacon) -> None:
        """DiscrepancyStore hook — may fire from a to_thread aggregation
        worker, so hop onto the service loop before spawning."""
        if b.round == 0:
            return
        r = result_from_beacon(b)
        loop = self._loop
        if loop is None or loop.is_closed():
            return
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if running is loop:
            self.on_result(r)
        else:
            loop.call_soon_threadsafe(self.on_result, r)

    async def _sweep(self, result: Result | None = None) -> None:
        """Open every pending round the chain has reached. Idempotent
        and double-dispatch-guarded: the store hook, the watch hook and
        the start-up catch-up can all fire for the same round."""
        head = result.round if result is not None else 0
        if head == 0:
            try:
                head = (await self._client.get(0)).round
            except ClientError:
                return  # no chain yet; the next boundary retries
        self._head = max(self._head, head)
        rounds = await asyncio.to_thread(self._vault.pending_rounds, head)
        for rd in rounds:
            if rd in self._opening:
                continue
            self._opening.add(rd)
            try:
                if result is not None and result.round == rd:
                    r = result
                else:
                    try:
                        r = await retry(
                            lambda rd=rd: self._client.get(rd),
                            op="timelock", policy=_FETCH_POLICY,
                            retry_on=(ClientError,))
                    except ClientError as e:
                        self._l.warn("timelock", "round_fetch_failed",
                                     round=rd, err=str(e))
                        continue
                await self._open_round(rd, r)
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — isolate per round
                # one bad round (garbage signature from an --insecure
                # upstream, an unparseable stored envelope) must not
                # wedge the ascending sweep and starve every LATER
                # round forever; the round stays pending and retries
                # at the next boundary
                self._l.warn("timelock", "round_open_failed", round=rd,
                             err=f"{type(e).__name__}: {e}")
            finally:
                self._opening.discard(rd)

    async def _open_round(self, round_no: int, r: Result) -> None:
        """Drain the round's pending set (this worker's shard of it) in
        ceil(K/chunk) batched dispatches. Each chunk is one
        decrypt_round_batch dispatch followed by ITS OWN vault commit
        and a cooperative yield — the loop is never held across a
        chunk (p99 submit latency during a sweep stays bounded), and a
        crash mid-open resumes from the last committed chunk because
        committed rows are no longer pending."""
        items = await asyncio.to_thread(
            self._vault.pending_for_round, round_no, self._shard)
        if not items:
            return
        from .. import metrics

        if not r.signature_v2:
            # no V2 signature: pre-V2 era round — OR a source that
            # simply omitted the field (a relay upstream serving the
            # legacy JSON shape). Opened/rejected rows are immutable,
            # so a terminal reject here would permanently burn
            # ciphertexts another source could still open: keep them
            # pending (one fetch per boundary sweep, bounded) and warn.
            self._l.warn("timelock", "round_without_v2_signature",
                         round=round_no, pending=len(items))
            return
        cts, good = [], []
        for token, env in items:
            try:
                cts.append(client_timelock.parse_envelope(env))
                good.append(token)
            except ClientError as e:
                # a stored envelope THIS build can't parse (vault file
                # shared across versions): leave it pending for a build
                # that can, never let it abort the round's open
                self._l.warn("timelock", "stored_envelope_unparseable",
                             id=token, err=str(e))
        if not cts:
            return
        chunk = self._open_chunk if self._open_chunk > 0 else len(cts)
        opened = rejected = 0
        for base in range(0, len(cts), chunk):
            # the slice is already <= chunk; chunk=0 tells batch not to
            # re-split (the commit-per-chunk discipline lives HERE)
            outcomes = await asyncio.to_thread(
                batch.decrypt_round_batch, r.signature_v2,
                cts[base:base + chunk], 0)
            metrics.TIMELOCK_OPEN_DISPATCHES.inc()
            results = [(token, ok, plaintext, err)
                       for token, (ok, plaintext, err)
                       in zip(good[base:base + chunk], outcomes)]
            # one vault transaction PER CHUNK: rows decided so far stay
            # decided if the next dispatch (or the process) dies, and a
            # restart's catch-up sweep only re-opens the remainder
            c_opened, c_rejected = await asyncio.to_thread(
                self._vault.finish_round, results, round_no)
            opened += c_opened
            rejected += c_rejected
            if self._notify is not None:
                try:
                    self._notify(
                        [(token, "opened" if ok else "rejected",
                          round_no) for token, ok, _, _ in results])
                except Exception as e:  # noqa: BLE001 — push is best-effort
                    self._l.warn("timelock", "notify_failed",
                                 round=round_no,
                                 err=f"{type(e).__name__}: {e}")
            # cooperative yield between chunks: queued submits/status
            # reads run before the next dispatch is scheduled
            await asyncio.sleep(0)
        if opened:
            metrics.TIMELOCK_CIPHERTEXTS.labels(result="opened").inc(opened)
        if rejected:
            metrics.TIMELOCK_CIPHERTEXTS.labels(
                result="rejected").inc(rejected)
        metrics.TIMELOCK_PENDING.set(
            await asyncio.to_thread(self._vault.pending_count))
        self._l.info("timelock", "round_opened", round=round_no,
                     opened=opened, rejected=rejected)


# ---------------------------------------------------------------------------
# The DiscrepancyStore hook (the "existing note_round_complete path"):
# chain/store.py calls note_round_complete(beacon) for every stored
# beacon, next to the OTLP exporter's flush. A weak registry keeps the
# store layer decoupled from service lifetime — no service, no work.
# ---------------------------------------------------------------------------

_ACTIVE: "weakref.ref[TimelockService] | None" = None


def register(svc: TimelockService) -> None:
    global _ACTIVE
    _ACTIVE = weakref.ref(svc)


def unregister(svc: TimelockService) -> None:
    global _ACTIVE
    if _ACTIVE is not None and _ACTIVE() is svc:
        _ACTIVE = None


def note_round_complete(b: Beacon) -> None:
    """Store-path boundary hook (chain/store.DiscrepancyStore.put)."""
    svc = _ACTIVE() if _ACTIVE is not None else None
    if svc is not None:
        svc.note_beacon(b)
