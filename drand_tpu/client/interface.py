"""Client interfaces: Result and the composable Client contract.

Reference: client/interface.go (Client :13, Result :37). A Client yields
Results; layered implementations (verifying, caching, optimizing,
aggregating — client/client.go:44 makeClient) wrap an underlying source.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import AsyncIterator

from ..chain.info import Info


class ClientError(Exception):
    pass


@dataclass
class Result:
    """One round of randomness (client/interface.go:37)."""

    round: int
    signature: bytes
    previous_signature: bytes = b""
    signature_v2: bytes = b""
    randomness: bytes = b""

    def __post_init__(self):
        if not self.randomness and self.signature:
            self.randomness = hashlib.sha256(self.signature).digest()


class Client:
    """Async client contract. ``get(0)`` means the latest round."""

    async def get(self, round_no: int = 0) -> Result:
        raise NotImplementedError

    def watch(self) -> AsyncIterator[Result]:
        raise NotImplementedError

    async def info(self) -> Info:
        raise NotImplementedError

    def round_at(self, t: float) -> int:
        raise NotImplementedError

    async def close(self) -> None:
        pass


def result_from_beacon(b) -> Result:
    return Result(round=b.round, signature=b.signature,
                  previous_signature=b.previous_sig,
                  signature_v2=b.signature_v2)
