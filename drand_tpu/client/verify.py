"""Verifying client: every Result is cryptographically checked.

Reference: client/verify.go — verify (:176) with the V1/V2 switchover
(WithV1VerificationUntil, client/client.go:367-377) and the trusted-
previous-signature catch-up walk (:115, loop :146-163). The catch-up walk
is THE bulk-verify hot path BASELINE.json names: here it runs as batched
RLC chunks through crypto.batch (one product check per chunk; corruption
anywhere is caught by the fresh-scalar bisection inside
crypto/batch_verify, bit-identical to per-item verdicts) with

- ADAPTIVE chunks: start at ``CATCHUP_CHUNK``, double while chunks
  verify clean up to ``CATCHUP_CHUNK_MAX``, halve on failure — a year of
  a 3 s chain costs thousands of product checks, not millions of
  pairings;
- PIPELINED fetch/verify: chunk k+1 prefetches while chunk k verifies on
  its worker thread, so the walk is bounded by max(fetch, verify), not
  their sum;
- a bounded TRUST RING of verified ``(round, signature)`` points, so an
  old-round re-fetch resumes from the nearest prior trust point instead
  of re-walking from genesis;
- optional CHECKPOINT bootstrap (client/checkpoint.py): a fresh client
  verifies one group-signed head attestation plus a spot-check sample
  instead of walking the whole chain.
"""

from __future__ import annotations

import asyncio
import hashlib
import os
import random

from ..chain import beacon as chain_beacon
from ..chain.beacon import Beacon
from ..crypto import batch
from ..net.transport import TransportError
from ..utils.logging import KVLogger, default_logger
from . import checkpoint as ckpt_mod
from .interface import Client, ClientError, Result

# rounds per batched verification chunk during catch-up (the adaptive
# walk's FLOOR and starting size)
CATCHUP_CHUNK = int(os.environ.get("DRAND_TPU_CATCHUP_CHUNK", "64"))
# adaptive growth ceiling: chunks double while they verify clean, up to
# this many rounds per RLC product check
CATCHUP_CHUNK_MAX = max(CATCHUP_CHUNK, int(os.environ.get(
    "DRAND_TPU_CATCHUP_CHUNK_MAX", str(64 * 1024))))
# concurrent fetches while filling a chunk (per-round fallback path —
# sources exposing ``get_span`` fetch a whole chunk in one call)
FETCH_CONCURRENCY = 16
# bounded count of verified (round, signature) trust points kept for
# old-round re-fetch resume
TRUST_RING = 64


class VerifyingClient(Client):
    """Wraps a source; strict-rounds mode walks the signature chain from
    the last point of trust (verify.go:25 verifyingClient)."""

    def __init__(self, source: Client, strict_rounds: bool = False,
                 v1_until: int | None = None,
                 use_checkpoints: bool = True,
                 logger: KVLogger | None = None):
        self._src = source
        self._strict = strict_rounds
        # rounds <= v1_until verify via the chained V1 equation; later
        # rounds via the unchained V2 one. None = V1 forever (upstream
        # behavior); 0 = V2 from round 1.
        self._v1_until = v1_until
        self._use_ckpt = use_checkpoints and os.environ.get(
            "DRAND_TPU_CKPT_BOOTSTRAP", "1") != "0"
        self._l = logger or default_logger("client.verify")
        # point of trust: (round, signature) with round 0 = genesis
        self._trust: tuple[int, bytes] | None = None
        # bounded insertion-ordered ring of verified (round, signature)
        # points — chunk tails and verified heads — so get(old_round)
        # resumes from the nearest prior point instead of genesis
        self._ring: dict[int, bytes] = {}
        # adaptive chunk size, persisted across walks on this client
        self._chunk = CATCHUP_CHUNK
        self._lock = asyncio.Lock()

    # ------------------------------------------------------------- Client
    async def get(self, round_no: int = 0) -> Result:
        r = await self._src.get(round_no)
        return await self._verified(r)

    async def watch(self):
        async for r in self._src.watch():
            try:
                res = await self._verified(r)
            except asyncio.CancelledError:
                raise
            except (ClientError, TransportError, OSError) as e:
                # a bad beacon OR a transport failure during the strict
                # catch-up walk drops THIS round and keeps the stream
                # alive — killing the generator over one flaky fetch
                # would silently end every downstream watcher
                self._l.warn("verify", "dropping_beacon", round=r.round,
                             err=str(e))
                continue
            yield res

    async def info(self):
        return await self._src.info()

    def round_at(self, t: float) -> int:
        return self._src.round_at(t)

    async def close(self) -> None:
        await self._src.close()

    # ------------------------------------------------------------ verify
    def _is_v2_era(self, round_no: int) -> bool:
        return self._v1_until is not None and round_no > self._v1_until

    async def _verified(self, r: Result) -> Result:
        info = await self._src.info()
        b = Beacon(round=r.round, previous_sig=r.previous_signature,
                   signature=r.signature, signature_v2=r.signature_v2)
        if self._is_v2_era(r.round):
            # unchained era: the V2 signature alone proves the round
            if not b.signature_v2:
                raise ClientError(f"round {r.round}: missing V2 signature")
            # pairings run on a worker thread: a client embedded in a
            # serving process (relay, gossip node) must not stall its
            # event loop for per-round verification
            if not await asyncio.to_thread(chain_beacon.verify_beacon_v2,
                                           info.public_key, b):
                raise ClientError(f"round {r.round}: invalid V2 signature")
            return self._finish(r)
        if self._strict:
            prev = await self._trusted_previous_signature(info, r.round)
            if r.previous_signature != prev:
                raise ClientError(
                    f"round {r.round}: previous signature does not chain "
                    f"to the trusted history")
        ok = await asyncio.to_thread(self._check_sigs, info.public_key, b)
        if not ok:
            raise ClientError(f"round {r.round}: invalid signature")
        if self._strict:
            async with self._lock:
                self._record_trust(r.round, r.signature)
        return self._finish(r)

    @staticmethod
    def _check_sigs(pubkey, b: Beacon) -> bool:
        """Dual V1(+V2) pairing check, shaped for ``asyncio.to_thread``."""
        ok = chain_beacon.verify_beacon(pubkey, b)
        if ok and b.is_v2():
            ok = chain_beacon.verify_beacon_v2(pubkey, b)
        return ok

    @staticmethod
    def _finish(r: Result) -> Result:
        r.randomness = hashlib.sha256(r.signature).digest()
        return r

    # ------------------------------------------------------- trust points
    def _record_trust(self, round_no: int, sig: bytes) -> None:
        """Record a verified point (caller holds the lock): the ring for
        re-fetch resume, ``_trust`` as the monotone head."""
        if self._trust is None or round_no > self._trust[0]:
            self._trust = (round_no, sig)
        if round_no in self._ring:
            return
        self._ring[round_no] = sig
        if len(self._ring) > TRUST_RING:
            # FIFO: evict the oldest-recorded point (never the genesis —
            # round 0 is implicit, not stored)
            self._ring.pop(next(iter(self._ring)))

    def _best_trust(self, round_no: int, info) -> tuple[int, bytes]:
        """Nearest verified point at or below round_no - 1 (caller holds
        the lock); genesis when nothing closer is known."""
        best_round, best_sig = 0, info.genesis_seed
        t = self._trust
        if t is not None and t[0] <= round_no - 1 and t[0] > best_round:
            best_round, best_sig = t
        for rn, sig in self._ring.items():
            if best_round < rn <= round_no - 1:
                best_round, best_sig = rn, sig
        return best_round, best_sig

    # ---------------------------------------------------------- catch-up
    async def _trusted_previous_signature(self, info, round_no: int) -> bytes:
        """Walk trust forward to round_no-1 (verify.go:115): fetch the gap
        rounds and verify them in adaptive batched RLC chunks, pipelining
        the next chunk's fetch under the current chunk's verification."""
        from .. import metrics

        async with self._lock:
            trust_round, trust_sig = self._best_trust(round_no, info)
            if trust_round == round_no - 1:
                # re-fetch of an already-walked round: the ring holds its
                # predecessor — zero span verifications
                return trust_sig
            trust_round, trust_sig = await self._maybe_bootstrap(
                info, round_no, trust_round, trust_sig)
            start = trust_round + 1
            if start >= round_no:
                return trust_sig
            self._l.info("verify", "catchup", from_round=start,
                         to_round=round_no - 1, chunk=self._chunk)
            chunk = self._chunk
            prev = trust_sig
            lo = start
            pending: tuple[asyncio.Task, int, int] | None = None
            pending = self._spawn_fetch(lo, min(lo + chunk, round_no))
            try:
                while lo < round_no:
                    task, flo, fhi = pending
                    pending = None
                    try:
                        beacons = await task
                    except BaseException:
                        # fetch failure: shrink before propagating — the
                        # next attempt re-probes with a smaller span
                        self._chunk = max(CATCHUP_CHUNK, chunk // 2)
                        raise
                    # optimistic prefetch of the NEXT chunk at the grown
                    # size while THIS chunk verifies on a worker thread;
                    # if this chunk fails, the finally-cancel reaps it
                    grown = min(chunk * 2, CATCHUP_CHUNK_MAX)
                    if fhi < round_no:
                        pending = self._spawn_fetch(
                            fhi, min(fhi + grown, round_no))
                    # linkage first (cheap), then one batched RLC check;
                    # the clean-path scan is one C-level pass — walks
                    # touch millions of rounds, so the per-beacon Python
                    # loop runs only when a break needs naming
                    if beacons[0].previous_sig != prev or any(
                            a.signature != b.previous_sig
                            for a, b in zip(beacons, beacons[1:])):
                        self._chunk = max(CATCHUP_CHUNK, chunk // 2)
                        for b in beacons:
                            if b.previous_sig != prev:
                                raise ClientError(
                                    f"round {b.round}: broken signature "
                                    f"chain")
                            prev = b.signature
                    prev = beacons[-1].signature
                    # the chunk's product check runs off the loop —
                    # catch-up walks can be millions of rounds long
                    oks = await asyncio.to_thread(
                        batch.verify_beacons, info.public_key, beacons)
                    if not oks.all():
                        # the RLC bisection already resolved per-item
                        # verdicts; name the first bad round and shrink
                        bad = beacons[int((~oks).argmax())]
                        self._chunk = max(CATCHUP_CHUNK, chunk // 2)
                        raise ClientError(
                            f"round {bad.round}: invalid signature in "
                            f"history")
                    # persist trust PER CHUNK (never regressing): if the
                    # walk is cancelled mid-way (the optimizing client's
                    # per-request timeout wraps the whole get), the next
                    # attempt resumes from the last verified chunk
                    self._record_trust(beacons[-1].round,
                                       beacons[-1].signature)
                    metrics.CLIENT_CATCHUP_ROUNDS.inc(len(beacons))
                    chunk = grown
                    self._chunk = chunk
                    metrics.CLIENT_CATCHUP_CHUNK.set(chunk)
                    lo = fhi
            finally:
                if pending is not None:
                    task, _, _ = pending
                    task.cancel()
                    await asyncio.gather(task, return_exceptions=True)
            return prev

    async def _maybe_bootstrap(self, info, round_no: int, trust_round: int,
                               trust_sig: bytes) -> tuple[int, bytes]:
        """Checkpoint bootstrap (caller holds the lock): when the gap is
        long and the source serves checkpoints, verify ONE group-signed
        head attestation (one product check) plus a spot-check sample of
        the skipped history (one batched product check) instead of
        walking it. Any failure falls back to the full walk — the
        checkpoint path can only ever SKIP work, never accept less."""
        from .. import metrics

        gap = round_no - 1 - trust_round
        if not self._use_ckpt or gap <= 2 * CATCHUP_CHUNK:
            return trust_round, trust_sig
        fetch = getattr(self._src, "get_checkpoint", None)
        if fetch is None:
            return trust_round, trust_sig
        try:
            ckpt = await fetch()
        except (ClientError, TransportError, OSError) as e:
            self._l.debug("verify", "checkpoint_unavailable", err=str(e))
            return trust_round, trust_sig
        if ckpt is None or not (trust_round < ckpt.round < round_no):
            return trust_round, trust_sig
        chain_hash = info.hash()
        ok = await asyncio.to_thread(
            ckpt_mod.verify_checkpoint, info.public_key, chain_hash, ckpt)
        if not ok:
            metrics.CKPT_BOOTSTRAPS.labels(result="rejected").inc()
            self._l.warn("verify", "checkpoint_rejected", round=ckpt.round)
            return trust_round, trust_sig
        # spot-check a random sample of the skipped history as ONE RLC
        # batch: each sampled beacon's signature must bind (round, prev)
        # under the group key
        k = min(ckpt_mod.SPOT_CHECKS, max(0, ckpt.round - 1 - trust_round))
        if k > 0:
            rounds = sorted(random.sample(
                range(trust_round + 1, ckpt.round), k))
            beacons = await self._fetch_rounds(rounds)
            oks = await asyncio.to_thread(
                batch.verify_beacons, info.public_key, beacons)
            if not oks.all():
                bad = beacons[int((~oks).argmax())]
                raise ClientError(
                    f"round {bad.round}: invalid signature in history "
                    f"(checkpoint spot-check)")
        metrics.CKPT_BOOTSTRAPS.labels(result="ok").inc()
        self._l.info("verify", "checkpoint_bootstrap", round=ckpt.round,
                     skipped=ckpt.round - trust_round, spot_checks=k)
        self._record_trust(ckpt.round, ckpt.signature)
        return ckpt.round, ckpt.signature

    # ------------------------------------------------------------ fetching
    def _spawn_fetch(self, lo: int, hi: int) -> tuple[asyncio.Task, int, int]:
        return (asyncio.ensure_future(self._fetch_span(lo, hi)), lo, hi)

    async def _fetch_span(self, lo: int, hi: int) -> list[Beacon]:
        span = getattr(self._src, "get_span", None)
        if span is not None:
            # bulk fast path: one source call per chunk (DirectClient
            # reads the store; a range-serving HTTP source maps here)
            beacons = list(await span(lo, hi))
            if len(beacons) != hi - lo:
                raise ClientError(
                    f"source returned {len(beacons)} rounds for span "
                    f"[{lo}, {hi})")
            for rn, b in zip(range(lo, hi), beacons):
                if b.round != rn:
                    raise ClientError(
                        f"source returned round {b.round} for {rn}")
            return beacons
        return await self._fetch_rounds(range(lo, hi))

    async def _fetch_rounds(self, rounds) -> list[Beacon]:
        """Concurrent bounded per-round fetch, cancellation-safe: the
        first failure cancels AND awaits every sibling before it
        propagates, so no semaphore-queued fetch keeps running against
        the source after the caller saw the error."""
        sem = asyncio.Semaphore(FETCH_CONCURRENCY)

        async def fetch(rn: int) -> Beacon:
            async with sem:
                r = await self._src.get(rn)
            if r.round != rn:
                raise ClientError(f"source returned round {r.round} for {rn}")
            return Beacon(round=r.round, previous_sig=r.previous_signature,
                          signature=r.signature,
                          signature_v2=r.signature_v2)

        tasks = [asyncio.ensure_future(fetch(rn)) for rn in rounds]
        try:
            return list(await asyncio.gather(*tasks))
        except BaseException:
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)
            raise
