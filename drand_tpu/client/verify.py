"""Verifying client: every Result is cryptographically checked.

Reference: client/verify.go — verify (:176) with the V1/V2 switchover
(WithV1VerificationUntil, client/client.go:367-377) and the trusted-
previous-signature catch-up walk (:115, loop :146-163). The catch-up walk
is THE bulk-verify hot path BASELINE.json names: here it runs as batched
multi-pairing chunks through crypto.batch (device engine when active)
instead of one sequential pairing pair per historical round.
"""

from __future__ import annotations

import asyncio
import hashlib
import os

from ..chain import beacon as chain_beacon
from ..chain.beacon import Beacon
from ..crypto import batch
from ..utils.logging import KVLogger, default_logger
from .interface import Client, ClientError, Result

# rounds per batched verification chunk during catch-up
CATCHUP_CHUNK = int(os.environ.get("DRAND_TPU_CATCHUP_CHUNK", "64"))
# concurrent fetches while filling a chunk
FETCH_CONCURRENCY = 16


class VerifyingClient(Client):
    """Wraps a source; strict-rounds mode walks the signature chain from
    the last point of trust (verify.go:25 verifyingClient)."""

    def __init__(self, source: Client, strict_rounds: bool = False,
                 v1_until: int | None = None,
                 logger: KVLogger | None = None):
        self._src = source
        self._strict = strict_rounds
        # rounds <= v1_until verify via the chained V1 equation; later
        # rounds via the unchained V2 one. None = V1 forever (upstream
        # behavior); 0 = V2 from round 1.
        self._v1_until = v1_until
        self._l = logger or default_logger("client.verify")
        # point of trust: (round, signature) with round 0 = genesis
        self._trust: tuple[int, bytes] | None = None
        self._lock = asyncio.Lock()

    # ------------------------------------------------------------- Client
    async def get(self, round_no: int = 0) -> Result:
        r = await self._src.get(round_no)
        return await self._verified(r)

    async def watch(self):
        async for r in self._src.watch():
            try:
                yield await self._verified(r)
            except ClientError as e:
                self._l.warn("verify", "dropping_beacon", round=r.round,
                             err=str(e))

    async def info(self):
        return await self._src.info()

    def round_at(self, t: float) -> int:
        return self._src.round_at(t)

    async def close(self) -> None:
        await self._src.close()

    # ------------------------------------------------------------ verify
    def _is_v2_era(self, round_no: int) -> bool:
        return self._v1_until is not None and round_no > self._v1_until

    async def _verified(self, r: Result) -> Result:
        info = await self._src.info()
        b = Beacon(round=r.round, previous_sig=r.previous_signature,
                   signature=r.signature, signature_v2=r.signature_v2)
        if self._is_v2_era(r.round):
            # unchained era: the V2 signature alone proves the round
            if not b.signature_v2:
                raise ClientError(f"round {r.round}: missing V2 signature")
            # pairings run on a worker thread: a client embedded in a
            # serving process (relay, gossip node) must not stall its
            # event loop for per-round verification
            if not await asyncio.to_thread(chain_beacon.verify_beacon_v2,
                                           info.public_key, b):
                raise ClientError(f"round {r.round}: invalid V2 signature")
            return self._finish(r)
        if self._strict:
            prev = await self._trusted_previous_signature(info, r.round)
            if r.previous_signature != prev:
                raise ClientError(
                    f"round {r.round}: previous signature does not chain "
                    f"to the trusted history")
        ok = await asyncio.to_thread(self._check_sigs, info.public_key, b)
        if not ok:
            raise ClientError(f"round {r.round}: invalid signature")
        if self._strict:
            async with self._lock:
                if self._trust is None or r.round > self._trust[0]:
                    self._trust = (r.round, r.signature)
        return self._finish(r)

    @staticmethod
    def _check_sigs(pubkey, b: Beacon) -> bool:
        """Dual V1(+V2) pairing check, shaped for ``asyncio.to_thread``."""
        ok = chain_beacon.verify_beacon(pubkey, b)
        if ok and b.is_v2():
            ok = chain_beacon.verify_beacon_v2(pubkey, b)
        return ok

    @staticmethod
    def _finish(r: Result) -> Result:
        r.randomness = hashlib.sha256(r.signature).digest()
        return r

    async def _trusted_previous_signature(self, info, round_no: int) -> bytes:
        """Walk trust forward to round_no-1 (verify.go:115): fetch the gap
        rounds and verify them in batched multi-pairing chunks."""
        async with self._lock:
            trust_round, trust_sig = self._trust or (0, info.genesis_seed)
            if round_no <= trust_round:
                # re-fetch of an old round: walk from genesis (we only keep
                # one point of trust, like the reference's trustRound logic)
                trust_round, trust_sig = 0, info.genesis_seed
            start = trust_round + 1
            if start >= round_no:
                return trust_sig
            self._l.info("verify", "catchup", from_round=start,
                         to_round=round_no - 1)
            for lo in range(start, round_no, CATCHUP_CHUNK):
                hi = min(lo + CATCHUP_CHUNK, round_no)
                beacons = await self._fetch_span(lo, hi)
                # linkage first (cheap), then one batched verification
                prev = trust_sig
                for b in beacons:
                    if b.previous_sig != prev:
                        raise ClientError(
                            f"round {b.round}: broken signature chain")
                    prev = b.signature
                # the chunk's multi-pairing span runs off the loop —
                # catch-up walks can be thousands of rounds long
                oks = await asyncio.to_thread(
                    batch.verify_beacons, info.public_key, beacons)
                if not oks.all():
                    bad = beacons[int((~oks).argmax())]
                    raise ClientError(
                        f"round {bad.round}: invalid signature in history")
                trust_round, trust_sig = beacons[-1].round, beacons[-1].signature
                # persist trust PER CHUNK (never regressing): if the walk is
                # cancelled mid-way (the optimizing client's per-request
                # timeout wraps the whole get), the next attempt resumes
                # from the last verified chunk instead of genesis
                if self._trust is None or trust_round > self._trust[0]:
                    self._trust = (trust_round, trust_sig)
            return trust_sig

    async def _fetch_span(self, lo: int, hi: int) -> list[Beacon]:
        sem = asyncio.Semaphore(FETCH_CONCURRENCY)

        async def fetch(rn: int) -> Beacon:
            async with sem:
                r = await self._src.get(rn)
            if r.round != rn:
                raise ClientError(f"source returned round {r.round} for {rn}")
            return Beacon(round=r.round, previous_sig=r.previous_signature,
                          signature=r.signature,
                          signature_v2=r.signature_v2)

        return list(await asyncio.gather(*(fetch(rn)
                                           for rn in range(lo, hi))))
