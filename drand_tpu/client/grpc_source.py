"""gRPC client source: consumes a node's Public service directly.

Reference: client/grpc/client.go (New :30, Watch :82 server-streaming).
Wraps the node transport's PublicRand/PublicRandStream/ChainInfo into the
client.Client surface, so the verified stack can sit on raw gRPC instead
of (or racing against) HTTP.
"""

from __future__ import annotations

from ..chain import time_math
from ..chain.info import Info
from ..net.grpc_transport import GrpcClient
from ..net.transport import TransportError
from .interface import Client, ClientError, result_from_beacon


class GrpcSource(Client):
    def __init__(self, address: str, own_addr: str = "client", certs=None):
        self._addr = address
        # certs: a net.tls.CertManager to trust a TLS-serving node
        self._client = GrpcClient(own_addr=own_addr, certs=certs)
        self._info: Info | None = None

    async def get(self, round_no: int = 0):
        try:
            b = await self._client.public_rand(self._addr, round_no)
        except TransportError as e:
            raise ClientError(str(e)) from e
        return result_from_beacon(b)

    async def watch(self):
        try:
            async for b in self._client.public_rand_stream(self._addr):
                yield result_from_beacon(b)
        except TransportError as e:
            raise ClientError(str(e)) from e

    async def info(self) -> Info:
        if self._info is None:
            try:
                got = await self._client.chain_info(self._addr)
            except TransportError as e:
                raise ClientError(str(e)) from e
            # re-check after the await (awaitatomic): first caller wins
            if self._info is None:
                self._info = got
        return self._info

    def round_at(self, t: float) -> int:
        if self._info is None:
            raise ClientError("info not fetched yet")
        return time_math.current_round(int(t), self._info.period,
                                       self._info.genesis_time)

    async def close(self) -> None:
        await self._client.close()
