"""Protobuf gRPC client source — consumes the STANDARD drand Public
service (ecosystem nodes, or a drand-tpu node's drand.Public interop
surface) over the reference byte layouts (net/protowire.py).

Reference: client/grpc/client.go (New :30, Watch :82) and
protobuf/drand/api.proto — this source lets the verified client stack
sit on any stock drand deployment.
"""

from __future__ import annotations

import grpc
import grpc.aio

from ..chain import time_math
from ..chain.beacon import Beacon
from ..chain.info import Info
from ..crypto.curves import PointG1
from ..net import protowire as pw
from .interface import Client, ClientError, result_from_beacon

_SERVICE = "drand.Public"


def _beacon_of(resp: dict) -> Beacon:
    return Beacon(round=resp["round"], signature=resp["signature"],
                  previous_sig=resp["previous_signature"],
                  signature_v2=resp["signature_v2"])


class GrpcInteropSource(Client):
    """client.Client over /drand.Public/* with protobuf bodies."""

    def __init__(self, address: str, credentials=None,
                 timeout: float = 5.0):
        self._addr = address
        self._timeout = timeout
        if credentials is not None:
            self._channel = grpc.aio.secure_channel(address, credentials)
        else:
            self._channel = grpc.aio.insecure_channel(address)
        self._info: Info | None = None

    def _unary(self, method: str):
        return self._channel.unary_unary(f"/{_SERVICE}/{method}")

    async def get(self, round_no: int = 0):
        try:
            raw = await self._unary("PublicRand")(
                pw.encode(pw.PUBLIC_RAND_REQUEST, {"round": round_no}),
                timeout=self._timeout)
        except grpc.aio.AioRpcError as e:
            raise ClientError(f"PublicRand: {e.code()}") from e
        return result_from_beacon(_beacon_of(
            pw.decode(pw.PUBLIC_RAND_RESPONSE, raw)))

    async def watch(self):
        stream = self._channel.unary_stream(
            f"/{_SERVICE}/PublicRandStream")(
            pw.encode(pw.PUBLIC_RAND_REQUEST, {}))
        try:
            async for raw in stream:
                yield result_from_beacon(_beacon_of(
                    pw.decode(pw.PUBLIC_RAND_RESPONSE, raw)))
        except grpc.aio.AioRpcError as e:
            raise ClientError(f"PublicRandStream: {e.code()}") from e

    async def info(self) -> Info:
        if self._info is None:
            try:
                raw = await self._unary("ChainInfo")(
                    pw.encode(pw.CHAIN_INFO_REQUEST, {}),
                    timeout=self._timeout)
            except grpc.aio.AioRpcError as e:
                raise ClientError(f"ChainInfo: {e.code()}") from e
            packet = pw.decode(pw.CHAIN_INFO_PACKET, raw)
            # ChainInfoPacket carries no genesis_seed (common.proto:48);
            # the seed is only needed to re-derive the genesis beacon
            got = Info(
                public_key=PointG1.from_bytes(packet["public_key"]),
                period=packet["period"],
                genesis_time=packet["genesis_time"],
                genesis_seed=b"",
                group_hash=packet["group_hash"])
            # re-check after the await (awaitatomic): first caller wins
            if self._info is None:
                self._info = got
        return self._info

    def round_at(self, t: float) -> int:
        if self._info is None:
            raise ClientError("info not fetched yet")
        return time_math.current_round(int(t), self._info.period,
                                       self._info.genesis_time)

    async def close(self) -> None:
        await self._channel.close()
