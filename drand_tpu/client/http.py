"""HTTP client source: consumes the public REST API.

Reference: client/http/http.go (New :29, Get :248, Watch :300 via
PollingWatcher, poll.go:13). Speaks the same JSON wire format as
http_server/server.py and the reference's public endpoints.
"""

from __future__ import annotations

import asyncio

import aiohttp

from ..chain import time_math
from ..chain.info import Info
from ..crypto.curves import PointG1
from ..utils.clock import Clock, SystemClock
from .interface import Client, ClientError, Result


def result_from_json(d: dict) -> Result:
    try:
        return Result(
            round=int(d["round"]),
            signature=bytes.fromhex(d.get("signature", "")),
            previous_signature=bytes.fromhex(d.get("previous_signature", "")),
            signature_v2=bytes.fromhex(d.get("signature_v2", "")),
            randomness=bytes.fromhex(d.get("randomness", "")),
        )
    except (KeyError, ValueError, TypeError) as e:
        # a ClientError keeps the optimizing client's failover working
        raise ClientError(f"malformed beacon JSON: {e!r}") from e


class HTTPClient(Client):
    def __init__(self, base_url: str, clock: Clock | None = None,
                 timeout: float = 10.0):
        self._base = base_url.rstrip("/")
        self._clock = clock or SystemClock()
        self._timeout = aiohttp.ClientTimeout(total=timeout)
        self._session: aiohttp.ClientSession | None = None
        self._info: Info | None = None

    async def _sess(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession(timeout=self._timeout)
        return self._session

    async def _get_json(self, path: str) -> dict:
        from .. import metrics

        sess = await self._sess()
        metrics.CLIENT_IN_FLIGHT.labels(url=self._base).inc()
        try:
            with metrics.CLIENT_REQUEST_DURATION.labels(
                    url=self._base).time():
                async with sess.get(self._base + path) as resp:
                    body = await resp.json()
                    metrics.CLIENT_REQUESTS.labels(
                        url=self._base, code=str(resp.status)).inc()
                    if resp.status != 200:
                        raise ClientError(
                            f"GET {path}: {resp.status} "
                            f"{body.get('error', '')}")
                    return body
        except (aiohttp.ClientError, ValueError) as e:
            # ValueError covers json.JSONDecodeError from malformed bodies:
            # a ClientError keeps the optimizing client's failover working
            metrics.CLIENT_REQUESTS.labels(url=self._base,
                                           code="err").inc()
            raise ClientError(f"GET {path}: {e!r}") from e
        finally:
            metrics.CLIENT_IN_FLIGHT.labels(url=self._base).dec()

    # ------------------------------------------------------------- Client
    async def get(self, round_no: int = 0) -> Result:
        path = "/public/latest" if round_no == 0 else f"/public/{round_no}"
        return result_from_json(await self._get_json(path))

    async def get_checkpoint(self):
        """Latest group-signed checkpoint the node serves — the strict
        client's O(1) trust bootstrap (client/checkpoint.py)."""
        from .checkpoint import checkpoint_from_json

        return checkpoint_from_json(
            await self._get_json("/checkpoints/latest"))

    async def get_span(self, lo: int, hi: int) -> list:
        """Bulk catch-up fast path over the wire: ``[lo, hi)`` as
        Beacons via ``GET /public/span`` (the VerifyingClient's chunk
        fetch — one request per server span-cap page instead of one
        per round). Validates length and the per-position round echo;
        raises ClientError unless the WHOLE span is served (matching
        DirectClient.get_span — the catch-up walk needs contiguous
        windows)."""
        from ..chain.beacon import Beacon

        if hi <= lo:
            return []
        out: list = []
        rn = lo
        while rn < hi:
            body = await self._get_json(
                f"/public/span?from={rn}&count={hi - rn}")
            beacons = body.get("beacons") or []
            if not beacons:
                raise ClientError(
                    f"span [{rn}, {hi}): server returned no beacons")
            for d in beacons:
                r = result_from_json(d)
                if r.round != rn:
                    raise ClientError(
                        f"span position {rn} carried round {r.round}")
                out.append(Beacon(
                    round=r.round, previous_sig=r.previous_signature,
                    signature=r.signature,
                    signature_v2=r.signature_v2))
                rn += 1
                if rn > hi:
                    raise ClientError(
                        f"span [{lo}, {hi}): server overshot to {rn}")
        return out

    async def watch(self):
        """Poll for each upcoming round (client/http/poll.go:13): sleep to
        the next round boundary, then long-poll GET it."""
        info = await self.info()
        while True:
            now = self._clock.now()
            next_round, next_time = time_math.next_round(
                int(now), info.period, info.genesis_time)
            await self._clock.sleep(max(0.0, next_time - now))
            try:
                yield await self.get(next_round)
            except ClientError:
                # missed it (node lagging); try the next boundary
                await self._clock.sleep(min(1.0, info.period / 10))

    async def info(self) -> Info:
        if self._info is None:
            d = await self._get_json("/info")
            group_hash = bytes.fromhex(d.get("group_hash", ""))
            got = Info(
                public_key=PointG1.from_bytes(bytes.fromhex(d["public_key"])),
                period=d["period"],
                genesis_time=d["genesis_time"],
                # reference semantics: group_hash IS the genesis seed
                genesis_seed=group_hash,
                group_hash=group_hash,
            )
            # re-check after the await (awaitatomic): first caller wins
            if self._info is None:
                self._info = got
        return self._info

    def round_at(self, t: float) -> int:
        if self._info is None:
            raise ClientError("info not fetched yet")
        return time_math.current_round(int(t), self._info.period,
                                       self._info.genesis_time)

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()
