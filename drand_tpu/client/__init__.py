"""Composable randomness client.

Reference: client/client.go:21 New / :44 makeClient — the stack built here
is watch-aggregator(caching(optimizing([verifying(source)…]))), matching
the reference's layering. Options become keyword arguments of
:func:`new_client`.
"""

from __future__ import annotations

from ..chain.info import Info
from .aggregator import WatchAggregator
from .cache import CachingClient
from .direct import DirectClient
from .interface import Client, ClientError, Result  # noqa: F401
from .optimizing import OptimizingClient
from .verify import VerifyingClient


def new_client(
    sources: list[Client],
    chain_info: Info | None = None,
    chain_hash: bytes = b"",
    strict_rounds: bool = False,
    v1_verification_until: int | None = None,
    cache_size: int = 256,
    insecurely: bool = False,
    checkpoints: bool = True,
) -> Client:
    """Build the verified client stack over one or more sources.

    - ``chain_info`` / ``chain_hash``: the point of trust. One of them is
      required unless ``insecurely`` (client/client.go:95 trust root rules);
      with only a hash, the first source's info is fetched and pinned
      against it at first use.
    - ``strict_rounds``: verify the full signature chain from the trust
      point (verify.go getTrustedPreviousSignature).
    - ``v1_verification_until``: rounds after this verify via the unchained
      V2 signature (client/client.go:367 WithV1VerificationUntil).
    - ``checkpoints``: let the strict walk bootstrap head trust from a
      group-signed checkpoint when the source serves one
      (client/checkpoint.py; falls back to the full walk on any doubt).
    """
    if not sources:
        raise ValueError("need at least one source")
    if chain_info is None and not chain_hash and not insecurely:
        raise ValueError(
            "a chain hash or chain info is required (or pass insecurely)")
    if chain_info is not None and chain_hash and \
            chain_info.hash() != chain_hash:
        raise ValueError("chain_info does not match the pinned chain_hash")
    wrapped: list[Client] = [
        VerifyingClient(_pinned(s, chain_info, chain_hash),
                        strict_rounds=strict_rounds,
                        v1_until=v1_verification_until,
                        use_checkpoints=checkpoints)
        for s in sources
    ]
    inner = wrapped[0] if len(wrapped) == 1 else OptimizingClient(wrapped)
    return WatchAggregator(CachingClient(inner, size=cache_size))


def _pinned(source: Client, info: Info | None, chain_hash: bytes) -> Client:
    if info is None and not chain_hash:
        return source
    return _PinnedClient(source, info, chain_hash)


class _PinnedClient(Client):
    """Enforces the trust root: the source's chain info must match the
    configured info/hash (client/client.go:95)."""

    def __init__(self, source: Client, info: Info | None, chain_hash: bytes):
        self._src = source
        self._info = info
        self._hash = chain_hash or (info.hash() if info else b"")

    async def info(self) -> Info:
        if self._info is None:
            got = await self._src.info()
            if got.hash() != self._hash:
                raise ClientError("source chain info does not match "
                                  "the pinned chain hash")
            # re-check after the await (tools/analyze awaitatomic):
            # concurrent first callers both fetch, but only the winner
            # publishes — the info is immutable, so a duplicate fetch
            # is cheap and a clobbering write is not
            if self._info is None:
                self._info = got
        return self._info

    async def get(self, round_no: int = 0) -> Result:
        await self.info()
        return await self._src.get(round_no)

    async def watch(self):
        await self.info()
        async for r in self._src.watch():
            yield r

    def round_at(self, t: float) -> int:
        return self._src.round_at(t)

    async def close(self) -> None:
        await self._src.close()

    def __getattr__(self, name: str):
        # OPTIONAL source capabilities (get_span bulk fetch,
        # get_checkpoint) pass through the pin transparently — but only
        # when the wrapped source actually has them, so feature probes
        # via getattr(src, ..., None) see the truth. The trust root
        # still gates every forwarded call.
        if name in ("get_span", "get_checkpoint"):
            inner = getattr(self._src, name)  # AttributeError when absent

            async def forward(*args, **kwargs):
                await self.info()
                return await inner(*args, **kwargs)

            return forward
        raise AttributeError(name)


__all__ = [
    "CachingClient",
    "Client",
    "ClientError",
    "DirectClient",
    "OptimizingClient",
    "Result",
    "VerifyingClient",
    "WatchAggregator",
    "new_client",
]
