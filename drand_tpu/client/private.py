"""Private randomness: ECIES round-trip with a node.

Reference: core/drand_public.go:126 PrivateRand and client usage — the
caller sends an ephemeral public key encrypted to the node's longterm
identity key; the node answers with 32 fresh bytes encrypted to the
ephemeral key. Neither side learns anything from transit observation.
"""

from __future__ import annotations

import asyncio

from ..crypto import bls, ecies
from ..key.keys import Identity
from .interface import ClientError


async def private_rand(client, node_identity: Identity) -> bytes:
    """Fetch 32 private random bytes from the node over the transport.
    The G1 point work runs off the event loop (loopblock discipline:
    this client may be embedded in a serving process)."""
    eph_sk, eph_pub = await asyncio.to_thread(bls.keygen)
    request = await asyncio.to_thread(
        ecies.encrypt, node_identity.key, eph_pub.to_bytes())
    reply = await client.private_rand(node_identity, request)
    try:
        out = await asyncio.to_thread(ecies.decrypt, eph_sk, reply)
    except Exception as e:  # noqa: BLE001
        raise ClientError(f"private rand: bad reply: {e!r}") from e
    if len(out) != 32:
        raise ClientError(f"private rand: expected 32 bytes, got {len(out)}")
    return out
