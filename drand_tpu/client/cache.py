"""Caching client: LRU of verified rounds (reference client/cache.go:22
makeCache/NewCachingClient — ARC there, LRU here; the eviction policy is
not part of the behavior contract)."""

from __future__ import annotations

from collections import OrderedDict

from .interface import Client, Result


class CachingClient(Client):
    def __init__(self, source: Client, size: int = 256):
        self._src = source
        self._size = size
        self._cache: OrderedDict[int, Result] = OrderedDict()

    async def get(self, round_no: int = 0) -> Result:
        if round_no:
            hit = self._cache.get(round_no)
            if hit is not None:
                self._cache.move_to_end(round_no)
                return hit
        r = await self._src.get(round_no)
        self._remember(r)
        return r

    def _remember(self, r: Result) -> None:
        self._cache[r.round] = r
        self._cache.move_to_end(r.round)
        while len(self._cache) > self._size:
            self._cache.popitem(last=False)

    async def watch(self):
        async for r in self._src.watch():
            self._remember(r)
            yield r

    async def info(self):
        return await self._src.info()

    def round_at(self, t: float) -> int:
        return self._src.round_at(t)

    async def close(self) -> None:
        await self._src.close()
