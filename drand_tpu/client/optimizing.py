"""Optimizing client: race multiple sources, prefer the fastest, demote
failing endpoints.

Reference: client/optimizing.go (newOptimizingClient :52, Get :231,
testSpeed :170, Watch :398): sources are tried in speed order; a failure
pushes a source to the back; periodic speed tests re-rank.
"""

from __future__ import annotations

import asyncio
import time

from ..utils.aio import spawn
from ..utils.logging import KVLogger, default_logger
from .interface import Client, ClientError, Result

SPEED_TEST_INTERVAL = 300.0


class OptimizingClient(Client):
    def __init__(self, sources: list[Client], request_timeout: float = 5.0,
                 logger: KVLogger | None = None):
        if not sources:
            raise ValueError("optimizing client needs at least one source")
        self._sources = list(sources)
        self._timeout = request_timeout
        self._l = logger or default_logger("client.optimizing")
        self._last_ranked = 0.0

    # ------------------------------------------------------------- Client
    async def get(self, round_no: int = 0) -> Result:
        await self._maybe_rank()
        last_err: Exception | None = None
        for src in list(self._sources):
            try:
                return await asyncio.wait_for(src.get(round_no),
                                              self._timeout)
            except (ClientError, asyncio.TimeoutError, OSError) as e:
                last_err = e
                self._demote(src)
        raise ClientError(f"all sources failed: {last_err!r}")

    async def watch(self):
        """Watch the current best source; on failure, fail over to the
        next and continue from there (optimizing.go:398)."""
        while True:
            src = self._sources[0]
            try:
                async for r in src.watch():
                    yield r
                return
            except (ClientError, OSError) as e:
                self._l.warn("optimizing", "watch_failover", err=str(e))
                self._demote(src)
                await asyncio.sleep(0.5)

    async def info(self):
        for src in list(self._sources):
            try:
                return await asyncio.wait_for(src.info(), self._timeout)
            except (ClientError, asyncio.TimeoutError, OSError):
                self._demote(src)
        raise ClientError("all sources failed for info")

    def round_at(self, t: float) -> int:
        return self._sources[0].round_at(t)

    async def close(self) -> None:
        for src in self._sources:
            await src.close()

    # ----------------------------------------------------------- ranking
    def _demote(self, src: Client) -> None:
        if src in self._sources and len(self._sources) > 1:
            self._sources.remove(src)
            self._sources.append(src)

    async def _maybe_rank(self) -> None:
        """Kick a BACKGROUND speed test when due (optimizing.go:170 runs
        them in a goroutine) — foreground requests never pay for probing
        slow sources."""
        now = time.monotonic()
        if now - self._last_ranked < SPEED_TEST_INTERVAL or \
                len(self._sources) == 1:
            return
        self._last_ranked = now
        spawn(self._rank())

    async def _rank(self) -> None:
        from .. import metrics

        async def probe(src: Client) -> tuple[float, Client]:
            # the speed test doubles as the client heartbeat
            # (client/http/metric.go:14 startObserve)
            url = getattr(src, "_base", None) or type(src).__name__
            t0 = time.monotonic()
            try:
                await asyncio.wait_for(src.get(0), self._timeout)
                dt = time.monotonic() - t0
                metrics.CLIENT_HEARTBEAT_SUCCESS.labels(url=url).inc()
                metrics.CLIENT_HEARTBEAT_LATENCY.labels(url=url).set(dt)
                return (dt, src)
            except (ClientError, asyncio.TimeoutError, OSError):
                metrics.CLIENT_HEARTBEAT_FAILURE.labels(url=url).inc()
                return (float("inf"), src)

        timings = await asyncio.gather(*(probe(s) for s in list(self._sources)))
        order = sorted(timings, key=lambda p: p[0])
        self._sources = [s for _, s in order]
