"""Checkpointed trust: group-signed chain-head attestations.

A checkpoint binds ``(chain_hash, round, signature)`` under the group
key: the threshold of nodes that recovered round ``round`` also
threshold-signs a domain-separated checkpoint message over the round's
recovered signature, and a fresh strict client that verifies ONE
checkpoint signature (one product check) holds exactly the trust a full
catch-up walk to ``round`` would have produced — under the same
honest-threshold assumption both rest on (see README "Client
verification economics" for the soundness argument).

Domain separation lives in the MESSAGE, not the DST, so checkpoint
partials ride the existing tbls machinery (sign_partial /
verify_partial / aggregate_round) unchanged: beacon V1 preimages are
``prev_sig(96B) || round(8B)``, V2 preimages ``round(8B)``, checkpoint
preimages ``TAG(23B) || chain_hash(32B) || round(8B) || sig(96B)`` —
three pairwise-distinct input lengths, so no cross-family sha256 input
can collide and the group never signs one digest meaning two things.
"""

from __future__ import annotations

import hashlib
import os
from dataclasses import dataclass

from ..chain.beacon import round_to_bytes
from ..crypto import tbls

# the checkpoint message tag — 23 bytes, making the checkpoint preimage
# length distinct from both beacon preimage families (see module doc)
CKPT_TAG = b"drand-tpu/checkpoint/v1"

# daemon: issue a checkpoint every this-many rounds (0 disables).
# Cost per interval round: one extra partial sign per node and one
# extra Lagrange recovery + product check on the aggregator.
CKPT_INTERVAL = int(os.environ.get("DRAND_TPU_CKPT_INTERVAL", "32"))

# client: how many random skipped-history rounds a checkpoint bootstrap
# spot-checks (one batched RLC product check for the whole sample;
# 0 = trust the checkpoint alone)
SPOT_CHECKS = int(os.environ.get("DRAND_TPU_CKPT_SPOT_CHECKS", "8"))


def checkpoint_message(chain_hash: bytes, round_no: int,
                       signature: bytes) -> bytes:
    """The digest the group threshold-signs for a checkpoint."""
    h = hashlib.sha256()
    h.update(CKPT_TAG)
    h.update(chain_hash)
    h.update(round_to_bytes(round_no))
    h.update(signature)
    return h.digest()


@dataclass(frozen=True)
class Checkpoint:
    """A group-signed chain-head attestation.

    ``signature`` is round ``round``'s recovered beacon signature (the
    trust point a walk would end on); ``ckpt_sig`` is the group BLS
    signature over :func:`checkpoint_message`.
    """

    round: int
    signature: bytes
    chain_hash: bytes
    ckpt_sig: bytes


def verify_checkpoint(pubkey, chain_hash: bytes, ckpt: Checkpoint) -> bool:
    """Client-side acceptance: the checkpoint must name OUR chain and
    carry a valid group signature over its canonical message. False on
    any mismatch — checkpoints arrive from untrusted relays."""
    if ckpt.round < 1 or ckpt.chain_hash != chain_hash:
        return False
    if not ckpt.signature or not ckpt.ckpt_sig:
        return False
    msg = checkpoint_message(chain_hash, ckpt.round, ckpt.signature)
    return tbls.verify_recovered(pubkey, msg, ckpt.ckpt_sig)


def checkpoint_json(c: Checkpoint) -> dict:
    return {
        "round": c.round,
        "signature": c.signature.hex(),
        "chain_hash": c.chain_hash.hex(),
        "checkpoint_sig": c.ckpt_sig.hex(),
    }


def checkpoint_from_json(d: dict) -> Checkpoint:
    from .interface import ClientError

    try:
        return Checkpoint(
            round=int(d["round"]),
            signature=bytes.fromhex(d["signature"]),
            chain_hash=bytes.fromhex(d["chain_hash"]),
            ckpt_sig=bytes.fromhex(d["checkpoint_sig"]),
        )
    except (KeyError, ValueError, TypeError) as e:
        raise ClientError(f"malformed checkpoint JSON: {e!r}") from e
