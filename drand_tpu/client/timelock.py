"""Timelock ("encrypt to the future") helpers over the beacon chain.

The fork-specific headline feature (SURVEY.md: core/timelock_test.go:17-72):
the unchained V2 signature over H(round) acts as an IBE private key for
identity = MessageV2(round), so anyone can encrypt a message that becomes
decryptable exactly when the network publishes that round.

Envelope format (JSON, scheme version 1):

    {"v": 1, "round": N, "chain_hash": <hex>,
     "U": <hex G1>, "V": <b64>, "W": <b64>}

``chain_hash`` binds the ciphertext to one chain: a ciphertext encrypted
under chain A's public key never decrypts under chain B's signatures, but
silently ATTEMPTING it burns a pairing and yields a confusing FO-check
error — so :func:`decrypt_with_beacon` rejects cross-chain envelopes up
front when the caller supplies its chain info. ``v`` lets the envelope
evolve; decrypting an envelope from a future scheme version fails closed.
"""

from __future__ import annotations

import base64
import json

from ..chain.beacon import message_v2
from ..chain.info import Info
from ..crypto import timelock
from .interface import ClientError, Result

SCHEME_VERSION = 1


def encrypt_to_round(info: Info, round_no: int, plaintext: bytes) -> dict:
    """Encrypt so that the round's V2 signature decrypts
    (kyber/encrypt/timelock analogue, core/timelock_test.go:43-48)."""
    ct = timelock.encrypt(info.public_key, message_v2(round_no), plaintext)
    return {
        "v": SCHEME_VERSION,
        "round": round_no,
        "chain_hash": info.hash().hex(),
        "U": ct.u.hex(),
        "V": base64.b64encode(ct.v).decode(),
        "W": base64.b64encode(ct.w).decode(),
    }


def parse_envelope(ct: dict) -> timelock.Ciphertext:
    """Envelope -> wire ciphertext, validating shape and scheme version
    (shared by the client decrypt path and the serving vault). Raises
    :class:`ClientError` on anything malformed."""
    if not isinstance(ct, dict):
        raise ClientError("timelock envelope must be a JSON object")
    version = ct.get("v", 1)
    if version != SCHEME_VERSION:
        raise ClientError(
            f"unsupported timelock scheme version {version!r} "
            f"(this build speaks v{SCHEME_VERSION})")
    if not isinstance(ct.get("round"), int) or ct["round"] < 1:
        raise ClientError("timelock envelope needs an integer round >= 1")
    try:
        u = bytes.fromhex(ct["U"])
        v = base64.b64decode(ct["V"], validate=True)
        w = base64.b64decode(ct["W"], validate=True)
    except (KeyError, TypeError, ValueError) as e:
        raise ClientError(f"malformed timelock envelope: {e}")
    if len(u) != 48:
        raise ClientError("timelock envelope U must be 48 bytes of hex")
    if len(v) != timelock.SIGMA_LEN:
        raise ClientError(
            f"timelock envelope V must be {timelock.SIGMA_LEN} bytes")
    return timelock.Ciphertext(u=u, v=v, w=w)


def check_chain(ct: dict, info: Info) -> None:
    """Reject a ciphertext bound to a DIFFERENT chain than ``info``'s.
    Envelopes always carry ``chain_hash`` (encrypt_to_round writes it);
    an envelope without one predates this check and is let through."""
    bound = ct.get("chain_hash")
    if not bound:
        return
    if not isinstance(bound, str):
        # the field arrives from unauthenticated POST bodies: a
        # non-string must be a 4xx validation error, not an
        # AttributeError 500 out of the handler
        raise ClientError("timelock envelope chain_hash must be a "
                          "hex string")
    if bound.lower() != info.hash().hex():
        raise ClientError(
            f"cross-chain timelock ciphertext: bound to chain "
            f"{bound[:16]}..., this chain is {info.hash().hex()[:16]}...")


def decrypt_with_beacon(ct: dict, result: Result,
                        info: Info | None = None) -> bytes:
    """Decrypt once the round is out, using its unchained V2 signature.
    Pass the chain ``info`` the beacon came from to reject cross-chain
    ciphertexts up front (the envelope's ``chain_hash`` binding)."""
    parsed = parse_envelope(ct)
    if info is not None:
        check_chain(ct, info)
    if result.round != ct["round"]:
        raise ClientError(
            f"need round {ct['round']}, got {result.round}")
    if not result.signature_v2:
        raise ClientError("beacon carries no V2 signature (pre-V2 era)")
    return timelock.decrypt(result.signature_v2, parsed)


def dumps(ct: dict) -> str:
    return json.dumps(ct, sort_keys=True)


def loads(data: str | bytes) -> dict:
    return json.loads(data)
