"""Timelock ("encrypt to the future") helpers over the beacon chain.

The fork-specific headline feature (SURVEY.md: core/timelock_test.go:17-72):
the unchained V2 signature over H(round) acts as an IBE private key for
identity = MessageV2(round), so anyone can encrypt a message that becomes
decryptable exactly when the network publishes that round.
"""

from __future__ import annotations

import base64
import json

from ..chain.beacon import message_v2
from ..chain.info import Info
from ..crypto import timelock
from .interface import ClientError, Result


def encrypt_to_round(info: Info, round_no: int, plaintext: bytes) -> dict:
    """Encrypt so that the round's V2 signature decrypts
    (kyber/encrypt/timelock analogue, core/timelock_test.go:43-48)."""
    ct = timelock.encrypt(info.public_key, message_v2(round_no), plaintext)
    return {
        "round": round_no,
        "chain_hash": info.hash().hex(),
        "U": ct.u.hex(),
        "V": base64.b64encode(ct.v).decode(),
        "W": base64.b64encode(ct.w).decode(),
    }


def decrypt_with_beacon(ct: dict, result: Result) -> bytes:
    """Decrypt once the round is out, using its unchained V2 signature."""
    if result.round != ct["round"]:
        raise ClientError(
            f"need round {ct['round']}, got {result.round}")
    if not result.signature_v2:
        raise ClientError("beacon carries no V2 signature (pre-V2 era)")
    parsed = timelock.Ciphertext(
        u=bytes.fromhex(ct["U"]),
        v=base64.b64decode(ct["V"]),
        w=base64.b64decode(ct["W"]),
    )
    return timelock.decrypt(result.signature_v2, parsed)


def dumps(ct: dict) -> str:
    return json.dumps(ct, sort_keys=True)


def loads(data: str | bytes) -> dict:
    return json.loads(data)
