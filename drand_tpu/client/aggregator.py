"""Watch aggregation: one upstream watch fanned out to N subscribers.

Reference: client/aggregator.go:26 newWatchAggregator — subscribers come
and go; the single upstream subscription starts with the first subscriber
and stops with the last.
"""

from __future__ import annotations

import asyncio

from .interface import Client, Result


class WatchAggregator(Client):
    def __init__(self, source: Client):
        self._src = source
        self._subs: list[asyncio.Queue] = []
        self._pump: asyncio.Task | None = None
        self._watch_info = None  # chain Info for the latency gauge

    async def get(self, round_no: int = 0) -> Result:
        return await self._src.get(round_no)

    async def info(self):
        return await self._src.info()

    def round_at(self, t: float) -> int:
        return self._src.round_at(t)

    async def watch(self):
        q: asyncio.Queue = asyncio.Queue(maxsize=32)
        self._subs.append(q)
        if self._pump is None or self._pump.done():
            self._pump = asyncio.ensure_future(self._run())
        try:
            while True:
                yield await q.get()
        finally:
            self._subs.remove(q)
            if not self._subs and self._pump is not None:
                self._pump.cancel()
                self._pump = None

    async def _run(self) -> None:
        """Pump upstream rounds to subscribers; survives upstream watch
        failures/end-of-stream by resubscribing (a dead pump would hang
        every subscriber forever)."""
        while True:
            try:
                if self._watch_info is None:
                    try:
                        got = await self._src.info()
                    except Exception:  # noqa: BLE001 — latency metric only
                        got = None
                    # re-check after the await (awaitatomic): the pump
                    # is single-task today, but the publish must stay
                    # safe if a second pump ever races the fetch
                    if got is not None and self._watch_info is None:
                        self._watch_info = got
                async for r in self._src.watch():
                    self._observe_latency(r)
                    for q in list(self._subs):
                        try:
                            q.put_nowait(r)
                        except asyncio.QueueFull:
                            pass  # slow subscriber skips rounds
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — retry upstream
                pass
            await asyncio.sleep(1.0)

    def _observe_latency(self, r) -> None:
        """client_watch_latency: ms between receipt and the round's
        expected time (client/http/metric.go:14 observe loop)."""
        try:
            import time as _time

            from ..chain import time_math
            from .. import metrics

            info = self._watch_info
            if info is None:
                return
            expected = time_math.time_of_round(info.period,
                                               info.genesis_time, r.round)
            metrics.CLIENT_WATCH_LATENCY.set(
                (_time.time() - expected) * 1000.0)
        except Exception:  # noqa: BLE001 — metrics never break the pump
            pass

    async def close(self) -> None:
        if self._pump is not None:
            self._pump.cancel()
            self._pump = None
        await self._src.close()
