"""Watch aggregation: one upstream watch fanned out to N subscribers.

Reference: client/aggregator.go:26 newWatchAggregator — subscribers come
and go; the single upstream subscription starts with the first subscriber
and stops with the last.
"""

from __future__ import annotations

import asyncio

from .interface import Client, Result


class WatchAggregator(Client):
    def __init__(self, source: Client):
        self._src = source
        self._subs: list[asyncio.Queue] = []
        self._pump: asyncio.Task | None = None

    async def get(self, round_no: int = 0) -> Result:
        return await self._src.get(round_no)

    async def info(self):
        return await self._src.info()

    def round_at(self, t: float) -> int:
        return self._src.round_at(t)

    async def watch(self):
        q: asyncio.Queue = asyncio.Queue(maxsize=32)
        self._subs.append(q)
        if self._pump is None or self._pump.done():
            self._pump = asyncio.ensure_future(self._run())
        try:
            while True:
                yield await q.get()
        finally:
            self._subs.remove(q)
            if not self._subs and self._pump is not None:
                self._pump.cancel()
                self._pump = None

    async def _run(self) -> None:
        """Pump upstream rounds to subscribers; survives upstream watch
        failures/end-of-stream by resubscribing (a dead pump would hang
        every subscriber forever)."""
        while True:
            try:
                async for r in self._src.watch():
                    for q in list(self._subs):
                        try:
                            q.put_nowait(r)
                        except asyncio.QueueFull:
                            pass  # slow subscriber skips rounds
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — retry upstream
                pass
            await asyncio.sleep(1.0)

    async def close(self) -> None:
        if self._pump is not None:
            self._pump.cancel()
            self._pump = None
        await self._src.close()
