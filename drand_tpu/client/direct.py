"""Direct in-process source: reads a running node's chain store.

The in-process analogue of the reference's gRPC public client
(client/grpc/client.go:30): the REST server (http_server/) and tests both
consume a node this way; the network clients (client/http.py) expose the
same surface over the wire.
"""

from __future__ import annotations

import asyncio

from ..chain import time_math
from ..chain.info import Info
from .interface import Client, ClientError, Result, result_from_beacon


class DirectClient(Client):
    """Wraps a beacon Handler (chain store + chain info)."""

    def __init__(self, handler):
        self._h = handler

    async def get(self, round_no: int = 0) -> Result:
        store = self._h.chain
        b = store.last() if round_no == 0 else store.get(round_no)
        if b is None:
            raise ClientError(f"round {round_no} not in chain")
        if round_no == 0 and b.round == 0:
            raise ClientError("chain has no rounds yet")
        return result_from_beacon(b)

    async def get_span(self, lo: int, hi: int) -> list:
        """Bulk catch-up fast path: the verifying client's chunk fetch
        reads ``[lo, hi)`` in one call instead of hi-lo round trips."""
        store = self._h.chain
        out = []
        for rn in range(lo, hi):
            b = store.get(rn)
            if b is None:
                raise ClientError(f"round {rn} not in chain")
            out.append(b)
        return out

    async def get_checkpoint(self):
        """Latest group-signed checkpoint the node's aggregator
        recovered (client/checkpoint.py Checkpoint)."""
        c = self._h.checkpoint()
        if c is None:
            raise ClientError("no checkpoint recovered yet")
        return c

    async def watch(self):
        q: asyncio.Queue = asyncio.Queue(maxsize=32)
        cb_id = f"client-watch-{id(q)}"

        def _cb(b) -> None:
            try:
                q.put_nowait(result_from_beacon(b))
            except asyncio.QueueFull:
                pass

        self._h.chain.add_callback(cb_id, _cb)
        try:
            while True:
                yield await q.get()
        finally:
            self._h.chain.remove_callback(cb_id)

    async def info(self) -> Info:
        return self._h.crypto.chain_info

    def round_at(self, t: float) -> int:
        info = self._h.crypto.chain_info
        return time_math.current_round(int(t), info.period, info.genesis_time)
