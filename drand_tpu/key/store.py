"""On-disk persistence of key material: keypair, DKG share, group file.

Reference: key/store.go (Store :16, NewFileStore :63, Save/Load :131-160)
— TOML files under <base>/key and <base>/groups, 0700 directories and 0600
files. File names match the reference (drand_id.{private,public},
dist_key.private, drand_group.toml) so operators find familiar layouts.
"""

from __future__ import annotations

import os

from ..utils.toml_compat import tomllib

from ..crypto.curves import PointG1
from ..crypto.poly import PriShare
from ..utils import fs
from .group import Group
from .keys import DistPublic, Identity, Pair, Share

KEY_FOLDER = "key"
GROUP_FOLDER = "groups"
KEY_FILE = "drand_id"
SHARE_FILE = "dist_key.private"
GROUP_FILE = "drand_group.toml"
DIST_KEY_FILE = "dist_key.public"


class KeyStoreError(Exception):
    pass


def _toml_escape(s: str) -> str:
    return s.replace("\\", "\\\\").replace('"', '\\"')


def _emit(d: dict, out: list[str], table: str | None = None) -> None:
    """Minimal TOML writer for the flat(+array-of-tables) shapes we store."""
    scalars = {k: v for k, v in d.items() if not isinstance(v, (dict, list))
               or (isinstance(v, list) and all(isinstance(x, str) for x in v))}
    tables = {k: v for k, v in d.items() if k not in scalars}
    if table:
        out.append(f"[{table}]")
    for k, v in scalars.items():
        if isinstance(v, bool):
            out.append(f"{k} = {'true' if v else 'false'}")
        elif isinstance(v, int):
            out.append(f"{k} = {v}")
        elif isinstance(v, list):
            items = ", ".join(f'"{_toml_escape(x)}"' for x in v)
            out.append(f"{k} = [{items}]")
        else:
            out.append(f'{k} = "{_toml_escape(str(v))}"')
    out.append("")
    for k, v in tables.items():
        if isinstance(v, list):  # array of tables
            for entry in v:
                out.append(f"[[{k}]]")
                for ek, ev in entry.items():
                    if isinstance(ev, bool):
                        out.append(f"{ek} = {'true' if ev else 'false'}")
                    elif isinstance(ev, int):
                        out.append(f"{ek} = {ev}")
                    else:
                        out.append(f'{ek} = "{_toml_escape(str(ev))}"')
                out.append("")
        else:
            _emit(v, out, table=k)


def dump_toml(d: dict) -> str:
    out: list[str] = []
    _emit(d, out)
    return "\n".join(out) + "\n"


class FileStore:
    """key.Store implementation over TOML files (key/store.go:63)."""

    def __init__(self, base_folder: str):
        self.base = base_folder
        self.key_folder = fs.create_secure_folder(
            os.path.join(base_folder, KEY_FOLDER))
        self.group_folder = fs.create_secure_folder(
            os.path.join(base_folder, GROUP_FOLDER))
        self.private_key_file = os.path.join(self.key_folder, KEY_FILE + ".private")
        self.public_key_file = os.path.join(self.key_folder, KEY_FILE + ".public")
        self.share_file = os.path.join(self.group_folder, SHARE_FILE)
        self.group_file = os.path.join(self.group_folder, GROUP_FILE)
        self.dist_key_file = os.path.join(self.group_folder, DIST_KEY_FILE)

    # ------------------------------------------------------------- keypair
    def save_key_pair(self, pair: Pair) -> None:
        priv = {
            "Key": hex(pair.key)[2:].zfill(64),
            "Public": pair.public.key.to_bytes().hex(),
            "Address": pair.public.addr,
            "TLS": pair.public.tls,
            "Signature": pair.public.signature.hex(),
        }
        fs.write_secure_file(self.private_key_file,
                             dump_toml(priv).encode())
        pub = {
            "Address": pair.public.addr,
            "Key": pair.public.key.to_bytes().hex(),
            "TLS": pair.public.tls,
            "Signature": pair.public.signature.hex(),
        }
        fs.write_secure_file(self.public_key_file, dump_toml(pub).encode())

    def load_key_pair(self) -> Pair:
        d = self._read(self.private_key_file)
        ident = Identity(
            key=PointG1.from_bytes(bytes.fromhex(d["Public"])),
            addr=d.get("Address", ""),
            tls=bool(d.get("TLS", False)),
            signature=bytes.fromhex(d.get("Signature", "")),
        )
        return Pair(key=int(d["Key"], 16), public=ident)

    # --------------------------------------------------------------- share
    def save_share(self, share: Share) -> None:
        d = {
            "Index": share.pri_share.index,
            "Share": hex(share.pri_share.value)[2:].zfill(64),
            "Commits": [c.to_bytes().hex() for c in share.commits],
        }
        fs.write_secure_file(self.share_file, dump_toml(d).encode())

    def load_share(self) -> Share:
        d = self._read(self.share_file)
        return Share(
            commits=[PointG1.from_bytes(bytes.fromhex(c))
                     for c in d["Commits"]],
            pri_share=PriShare(index=int(d["Index"]),
                               value=int(d["Share"], 16)),
        )

    # --------------------------------------------------------------- group
    def save_group(self, group: Group) -> None:
        fs.write_secure_file(self.group_file,
                             dump_toml(group.to_dict()).encode())
        if group.public_key is not None:
            d = {"Coefficients": [c.to_bytes().hex()
                                  for c in group.public_key.coefficients]}
            fs.write_secure_file(self.dist_key_file, dump_toml(d).encode())

    def load_group(self) -> Group:
        return Group.from_dict(self._read(self.group_file))

    def load_dist_public(self) -> DistPublic:
        d = self._read(self.dist_key_file)
        return DistPublic([PointG1.from_bytes(bytes.fromhex(c))
                           for c in d["Coefficients"]])

    # ------------------------------------------------------------ plumbing
    def has_key_pair(self) -> bool:
        return fs.file_exists(self.private_key_file)

    def has_share(self) -> bool:
        return fs.file_exists(self.share_file)

    def has_group(self) -> bool:
        return fs.file_exists(self.group_file)

    @staticmethod
    def _read(path: str) -> dict:
        if not fs.file_exists(path):
            raise KeyStoreError(f"no such file: {path}")
        with open(path, "rb") as f:
            return tomllib.load(f)
