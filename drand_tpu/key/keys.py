"""Long-term identity keys, DKG shares, distributed public key.

Reference: key/keys.go (Pair :20, Identity :28, NewKeyPair :88, Share :235,
DistPublic :311) and key/node.go (Node :22). Keys live on G1 (48 bytes),
identity self-signatures are BLS on G2 (AuthScheme — key/curve.go:34).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

from ..crypto import bls
from ..crypto.curves import PointG1
from ..crypto.poly import PriShare, PubPoly


@dataclass
class Identity:
    """Public identity: key + reachable address (key/keys.go:28)."""

    key: PointG1
    addr: str = ""
    tls: bool = False
    signature: bytes = b""

    def address(self) -> str:
        return self.addr

    def hash(self) -> bytes:
        """Hash of the public key only — the self-signature input
        (key/keys.go:54: address/tls excluded so they can change)."""
        return hashlib.blake2b(self.key.to_bytes(), digest_size=32).digest()

    def valid_signature(self) -> bool:
        return bls.verify(self.key, self.hash(), self.signature)

    def equal(self, other: "Identity") -> bool:
        return (
            self.addr == other.addr
            and self.tls == other.tls
            and self.key == other.key
        )

    def __str__(self) -> str:
        return f"{{{self.addr} - {self.key.to_bytes()[:8].hex()}}}"


@dataclass
class Pair:
    """Private/public keypair (key/keys.go:20)."""

    key: int  # Fr scalar
    public: Identity

    def self_sign(self) -> None:
        self.public.signature = bls.sign(self.key, self.public.hash())


def new_key_pair(address: str, tls: bool = False, seed: bytes | None = None) -> Pair:
    """Fresh self-signed keypair (key/keys.go:88)."""
    sk, pub = bls.keygen(seed=seed)
    pair = Pair(key=sk, public=Identity(key=pub, addr=address, tls=tls))
    pair.self_sign()
    return pair


@dataclass
class Node:
    """Identity with its DKG index (key/node.go:22)."""

    identity: Identity
    index: int

    def address(self) -> str:
        return self.identity.addr

    def hash(self) -> bytes:
        h = hashlib.blake2b(digest_size=32)
        h.update(self.index.to_bytes(2, "big"))
        h.update(self.identity.key.to_bytes())
        return h.digest()


@dataclass
class DistPublic:
    """The distributed public key: commitments of the collective secret
    polynomial; coefficient 0 is the collective key (key/keys.go:311)."""

    coefficients: list[PointG1]

    def key(self) -> PointG1:
        return self.coefficients[0]

    def pub_poly(self) -> PubPoly:
        return PubPoly(list(self.coefficients))

    def hash(self) -> bytes:
        h = hashlib.blake2b(digest_size=32)
        for c in self.coefficients:
            h.update(c.to_bytes())
        return h.digest()

    def equal(self, other: "DistPublic") -> bool:
        return self.coefficients == other.coefficients


@dataclass
class Share:
    """Output of the DKG for one node: its private share plus the public
    polynomial commitments (key/keys.go:235)."""

    commits: list[PointG1]
    pri_share: PriShare

    def public(self) -> DistPublic:
        return DistPublic(list(self.commits))

    def pub_poly(self) -> PubPoly:
        return PubPoly(list(self.commits))
