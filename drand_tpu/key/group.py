"""Group file: the canonical network configuration.

Reference: key/group.go — nodes, threshold, period, genesis time/seed,
transition time, distributed key, and a canonical blake2b hash that pins
the network identity (the genesis seed of the chain).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

from .keys import DistPublic, Identity, Node
from ..crypto.poly import minimum_threshold
from ..chain import time_math


@dataclass
class Group:
    nodes: list[Node]
    threshold: int
    period: int  # seconds
    genesis_time: int
    genesis_seed: bytes = b""
    transition_time: int = 0
    catchup_period: int = 0
    public_key: DistPublic | None = None

    def __post_init__(self):
        self.nodes = sorted(self.nodes, key=lambda n: n.index)
        if self.threshold < minimum_threshold(len(self.nodes)):
            raise ValueError(
                f"threshold {self.threshold} below minimum "
                f"{minimum_threshold(len(self.nodes))} for n={len(self.nodes)}"
            )
        if self.catchup_period == 0:
            self.catchup_period = max(1, self.period // 2)

    def __len__(self) -> int:
        return len(self.nodes)

    def find(self, ident: Identity) -> Node | None:
        for n in self.nodes:
            if n.identity.equal(ident):
                return n
        return None

    def find_index(self, ident: Identity) -> int | None:
        n = self.find(ident)
        return None if n is None else n.index

    def node(self, index: int) -> Node | None:
        for n in self.nodes:
            if n.index == index:
                return n
        return None

    def hash(self) -> bytes:
        """Canonical group hash (key/group.go:89): nodes sorted by index,
        then threshold, genesis time, transition time, dist key."""
        h = hashlib.blake2b(digest_size=32)
        for n in self.nodes:
            h.update(n.hash())
        h.update(self.threshold.to_bytes(4, "little"))
        h.update(int(self.genesis_time).to_bytes(8, "little", signed=True))
        if self.transition_time:
            h.update(int(self.transition_time).to_bytes(8, "little", signed=True))
        if self.public_key is not None:
            h.update(self.public_key.hash())
        return h.digest()

    def get_genesis_seed(self) -> bytes:
        """The chain's genesis seed: fixed at first-group creation
        (key/group.go GetGenesisSeed — the hash of the group)."""
        if not self.genesis_seed:
            self.genesis_seed = self.hash()
        return self.genesis_seed

    def current_round(self, now: float) -> int:
        return time_math.current_round(int(now), self.period, self.genesis_time)

    def equal(self, other: "Group") -> bool:
        return self.hash() == other.hash() and self.period == other.period

    # -- codec (the TOML-file analogue; JSON here) ---------------------------
    def to_dict(self) -> dict:
        d = {
            "threshold": self.threshold,
            "period": self.period,
            "catchup_period": self.catchup_period,
            "genesis_time": self.genesis_time,
            "transition_time": self.transition_time,
            "genesis_seed": self.get_genesis_seed().hex(),
            "nodes": [
                {
                    "index": n.index,
                    "address": n.identity.addr,
                    "tls": n.identity.tls,
                    "key": n.identity.key.to_bytes().hex(),
                    "signature": n.identity.signature.hex(),
                }
                for n in self.nodes
            ],
        }
        if self.public_key is not None:
            d["public_key"] = [c.to_bytes().hex() for c in self.public_key.coefficients]
        return d

    def to_proto_dict(self) -> dict:
        """common.proto GroupPacket field dict (key/group.go GroupToProto
        analogue) — encodable with protowire.GROUP_PACKET."""
        d = {
            "nodes": [{
                "public": {
                    "address": n.identity.addr,
                    "key": n.identity.key.to_bytes(),
                    "tls": n.identity.tls,
                    "signature": n.identity.signature,
                },
                "index": n.index,
            } for n in self.nodes],
            "threshold": self.threshold,
            "period": self.period,
            "genesis_time": self.genesis_time,
            "transition_time": self.transition_time,
            "genesis_seed": self.get_genesis_seed(),
            "catchup_period": self.catchup_period,
            "dist_key": [],
        }
        if self.public_key is not None:
            d["dist_key"] = [c.to_bytes()
                             for c in self.public_key.coefficients]
        return d

    @staticmethod
    def from_proto_dict(d: dict) -> "Group":
        """Inverse of :meth:`to_proto_dict` (key/group.go:317
        GroupFromProto analogue)."""
        from ..crypto.curves import PointG1

        nodes = [
            Node(identity=Identity(
                key=PointG1.from_bytes(nd["public"]["key"]),
                addr=nd["public"]["address"],
                tls=bool(nd["public"].get("tls", False)),
                signature=nd["public"].get("signature", b"")),
                index=nd["index"])
            for nd in d.get("nodes", [])
        ]
        pk = None
        if d.get("dist_key"):
            pk = DistPublic([PointG1.from_bytes(c) for c in d["dist_key"]])
        return Group(
            nodes=nodes,
            threshold=d["threshold"],
            period=d["period"],
            genesis_time=d.get("genesis_time", 0),
            genesis_seed=d.get("genesis_seed", b""),
            transition_time=d.get("transition_time", 0),
            catchup_period=d.get("catchup_period", 0),
            public_key=pk,
        )

    @staticmethod
    def from_dict(d: dict) -> "Group":
        from ..crypto.curves import PointG1

        nodes = [
            Node(
                identity=Identity(
                    key=PointG1.from_bytes(bytes.fromhex(nd["key"])),
                    addr=nd["address"],
                    tls=nd.get("tls", False),
                    signature=bytes.fromhex(nd.get("signature", "")),
                ),
                index=nd["index"],
            )
            for nd in d["nodes"]
        ]
        pk = None
        if "public_key" in d:
            pk = DistPublic(
                [PointG1.from_bytes(bytes.fromhex(c)) for c in d["public_key"]]
            )
        return Group(
            nodes=nodes,
            threshold=d["threshold"],
            period=d["period"],
            genesis_time=d["genesis_time"],
            genesis_seed=bytes.fromhex(d.get("genesis_seed", "")),
            transition_time=d.get("transition_time", 0),
            catchup_period=d.get("catchup_period", 0),
            public_key=pk,
        )
