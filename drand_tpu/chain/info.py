"""Public chain descriptor (reference: chain/info.go:16-50).

Everything a client needs to verify the chain: collective key, period,
genesis time, and the pinned hashes.
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass

from ..crypto.curves import PointG1


@dataclass
class Info:
    public_key: PointG1
    period: int
    genesis_time: int
    genesis_seed: bytes
    group_hash: bytes = b""

    def hash(self) -> bytes:
        """Canonical chain hash (chain/info.go:36): clients pin this."""
        h = hashlib.sha256()
        h.update(self.period.to_bytes(4, "big"))
        h.update(int(self.genesis_time).to_bytes(8, "big", signed=True))
        h.update(self.public_key.to_bytes())
        h.update(self.group_hash)
        return h.digest()

    def equal(self, other: "Info") -> bool:
        return (
            self.public_key == other.public_key
            and self.period == other.period
            and self.genesis_time == other.genesis_time
            and self.genesis_seed == other.genesis_seed
        )

    def to_json(self) -> str:
        return json.dumps(
            {
                "public_key": self.public_key.to_bytes().hex(),
                "period": self.period,
                "genesis_time": self.genesis_time,
                "genesis_seed": self.genesis_seed.hex(),
                "group_hash": self.group_hash.hex(),
                "hash": self.hash().hex(),
            },
            sort_keys=True,
        )

    @staticmethod
    def from_json(data: str | bytes) -> "Info":
        d = json.loads(data)
        return Info(
            public_key=PointG1.from_bytes(bytes.fromhex(d["public_key"])),
            period=d["period"],
            genesis_time=d["genesis_time"],
            genesis_seed=bytes.fromhex(d["genesis_seed"]),
            group_hash=bytes.fromhex(d.get("group_hash", "")),
        )

    @staticmethod
    def from_group(group) -> "Info":
        """chain.NewChainInfo analogue."""
        if group.public_key is None:
            raise ValueError("group has no distributed public key")
        return Info(
            public_key=group.public_key.key(),
            period=group.period,
            genesis_time=group.genesis_time,
            genesis_seed=group.get_genesis_seed(),
            # reference semantics (chain/info.go:29): GroupHash is the
            # GENESIS seed, not the current group hash — the chain hash
            # must stay invariant across reshares
            group_hash=group.get_genesis_seed(),
        )
