"""Packed append-only segment storage for multi-million-round chains
(ISSUE 14).

The SQLite backend pays a B-tree descent plus a hex-JSON parse per
beacon — fine at League-of-Entropy depths, measurable churn at 10M+
rounds (every `cursor_from` walk re-touches interior pages, every row
re-parses JSON). This backend replaces both costs with arithmetic:

- the chain is split into per-epoch SEGMENT FILES of ``seg_rounds``
  consecutive rounds (``seg-%08d.drs``, ~19 MiB each at the default
  65 536 rounds/segment);
- every round occupies one FIXED-WIDTH record at
  ``(round % seg_rounds) * record_size`` — ``get`` and ``cursor_from``
  are a divmod and an ``lseek``, O(1) at any depth, with no index
  pages to cache or split;
- records are packed binary (no JSON): a flags byte, three length
  bytes, and three fixed ``slot``-byte signature fields
  (previous_sig, signature, signature_v2). Absent rounds are
  all-zero records — sparse files make holes free.

Same niche and discipline as :class:`..chain.store.SQLiteStore`:
append-mostly single writer, read-mostly serving, one lock, safe to
call from ``asyncio.to_thread`` workers. SQLite STAYS THE DEFAULT
(``DRAND_TPU_STORE=segment`` or `drand-tpu util store-migrate` opt
in); the formats are losslessly inter-convertible via
:func:`migrate_store`.

Durability: writes are flushed to the OS per put (like WAL +
synchronous=NORMAL, a crash can lose the last instants of writes but
not corrupt the format — records are self-contained and a torn record
reads as absent-or-short, never as a wrong beacon).
"""

from __future__ import annotations

import json
import os
import threading
from typing import Iterator

from .beacon import Beacon
from .store import Store, StoreError

META_FILE = "meta.json"
SEG_PATTERN = "seg-%08d.drs"
DEFAULT_SEG_ROUNDS = 1 << 16
# BLS G2 signatures are 96 bytes compressed; the slot also fits the
# 32-byte genesis seed and the chaos harness's structural stand-ins
DEFAULT_SLOT = 96
_F_PRESENT = 0x01
# open-handle LRU: 64 handles cover a ~4M-round working set; deeper
# random-access patterns evict (an open() per miss), sequential walks
# always hit
_MAX_OPEN_SEGMENTS = 64


class SegmentStore(Store):
    """Fixed-width per-epoch segment files behind the Store interface."""

    def __init__(self, path: str, seg_rounds: int = DEFAULT_SEG_ROUNDS,
                 slot: int = DEFAULT_SLOT):
        self._dir = path
        os.makedirs(path, exist_ok=True)
        meta_path = os.path.join(path, META_FILE)
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                meta = json.load(f)
            if meta.get("version") != 1:
                raise StoreError(
                    f"unsupported segment format version: {meta}")
            self._seg_rounds = int(meta["seg_rounds"])
            self._slot = int(meta["slot"])
        else:
            if not 1 <= slot <= 255:
                # field lengths are single bytes in the record header;
                # a larger slot would pass _pack's size check and then
                # blow up encoding the length
                raise StoreError(f"segment slot must be 1..255, "
                                 f"got {slot}")
            if seg_rounds < 1:
                raise StoreError(f"seg_rounds must be >= 1, "
                                 f"got {seg_rounds}")
            self._seg_rounds = seg_rounds
            self._slot = slot
            with open(meta_path, "w") as f:
                json.dump({"version": 1, "seg_rounds": seg_rounds,
                           "slot": slot}, f)
        self._rec = 4 + 3 * self._slot
        self._lock = threading.Lock()
        self._handles: dict[int, object] = {}  # seg index -> file, LRU
        self._count: int | None = None  # lazy: first __len__ scans
        self._last: Beacon | None = self._scan_last()

    # ------------------------------------------------------------ codec
    def _pack(self, b: Beacon) -> bytes:
        slot = self._slot
        for name, field in (("previous_sig", b.previous_sig),
                            ("signature", b.signature),
                            ("signature_v2", b.signature_v2)):
            if len(field) > slot:
                raise StoreError(
                    f"{name} of round {b.round} is {len(field)} bytes; "
                    f"segment slot is {slot} (re-create the store with "
                    f"a larger slot, max 255)")
        return b"".join((
            bytes((_F_PRESENT, len(b.previous_sig), len(b.signature),
                   len(b.signature_v2))),
            b.previous_sig.ljust(slot, b"\0"),
            b.signature.ljust(slot, b"\0"),
            b.signature_v2.ljust(slot, b"\0"),
        ))

    def _unpack(self, round_no: int, rec: bytes) -> Beacon | None:
        if len(rec) < self._rec or not rec[0] & _F_PRESENT:
            return None
        slot = self._slot
        lp, ls, lv = rec[1], rec[2], rec[3]
        off = 4
        return Beacon(
            round=round_no,
            previous_sig=rec[off:off + lp],
            signature=rec[off + slot:off + slot + ls],
            signature_v2=rec[off + 2 * slot:off + 2 * slot + lv],
        )

    # --------------------------------------------------------- file layer
    def _seg_path(self, seg: int) -> str:
        return os.path.join(self._dir, SEG_PATTERN % seg)

    def _seg_indices(self) -> list[int]:
        out = []
        for name in os.listdir(self._dir):
            if name.startswith("seg-") and name.endswith(".drs"):
                try:
                    out.append(int(name[4:-4]))
                except ValueError:
                    continue
        return sorted(out)

    def _handle(self, seg: int, create: bool):
        """Open (or reuse) the segment's file handle; LRU-capped so a
        deep cursor walk doesn't accumulate thousands of fds."""
        fh = self._handles.pop(seg, None)
        if fh is None:
            path = self._seg_path(seg)
            if not os.path.exists(path):
                if not create:
                    return None
                open(path, "xb").close()
            fh = open(path, "r+b")
        self._handles[seg] = fh  # re-insert: dict order is the LRU order
        while len(self._handles) > _MAX_OPEN_SEGMENTS:
            oldest = next(iter(self._handles))
            self._handles.pop(oldest).close()
        return fh

    def _scan_last(self) -> Beacon | None:
        """Highest present record: read the top segment backwards (only
        the newest segment is scanned — opening a 10M-round chain costs
        one ~19 MiB read, not a walk of the whole directory)."""
        for seg in reversed(self._seg_indices()):
            with open(self._seg_path(seg), "rb") as fh:
                data = fh.read()
            n_recs = len(data) // self._rec
            base = seg * self._seg_rounds
            for i in range(n_recs - 1, -1, -1):
                b = self._unpack(base + i, data[i * self._rec:
                                                (i + 1) * self._rec])
                if b is not None:
                    return b
        return None

    # ------------------------------------------------------------- Store
    def __len__(self) -> int:
        with self._lock:
            if self._count is None:
                count = 0
                for seg in self._seg_indices():
                    with open(self._seg_path(seg), "rb") as fh:
                        data = fh.read()
                    count += sum(
                        1 for i in range(0, len(data) - self._rec + 1,
                                         self._rec)
                        if data[i] & _F_PRESENT)
                self._count = count
            return self._count

    def put(self, b: Beacon) -> None:
        rec = self._pack(b)
        with self._lock:
            seg, idx = divmod(b.round, self._seg_rounds)
            fh = self._handle(seg, create=True)
            fh.seek(idx * self._rec)
            if self._count is not None:
                old = fh.read(1)
                if not (old and old[0] & _F_PRESENT):
                    self._count += 1
                fh.seek(idx * self._rec)
            fh.write(rec)
            fh.flush()
            if self._last is None or b.round >= self._last.round:
                self._last = b

    def last(self) -> Beacon:
        with self._lock:
            if self._last is None:
                raise StoreError("store is empty")
            return self._last

    def get(self, round_no: int) -> Beacon | None:
        if round_no < 0:
            return None
        from .. import metrics

        with self._lock:
            seg, idx = divmod(round_no, self._seg_rounds)
            fh = self._handle(seg, create=False)
            if fh is None:
                return None
            fh.seek(idx * self._rec)
            rec = fh.read(self._rec)
        metrics.CHAIN_STORE_READS.labels(backend="segment").inc()
        return self._unpack(round_no, rec)

    def cursor(self) -> Iterator[Beacon]:
        return self.cursor_from(0)

    def cursor_from(self, from_round: int,
                    batch: int = 2048) -> Iterator[Beacon]:
        """Stream in record batches: one contiguous read per batch (the
        record offset is round arithmetic, so a batch is one slice of
        one segment file), lock released between batches, holes
        skipped. A multi-million-round walk never materializes the
        chain nor touches an index."""
        from .. import metrics

        round_no = max(0, from_round)
        top_seg = None
        while True:
            with self._lock:
                segs = self._seg_indices()
                if not segs:
                    return
                top_seg = segs[-1]
                seg, idx = divmod(round_no, self._seg_rounds)
                if seg > top_seg:
                    return
                if seg not in segs:
                    # hole spanning a whole absent segment: skip ahead
                    nxt = [s for s in segs if s > seg]
                    if not nxt:
                        return
                    round_no = nxt[0] * self._seg_rounds
                    seg, idx = nxt[0], 0
                n = min(batch, self._seg_rounds - idx)
                fh = self._handle(seg, create=False)
                fh.seek(idx * self._rec)
                data = fh.read(n * self._rec)
            out = []
            for i in range(len(data) // self._rec):
                b = self._unpack(round_no + i,
                                 data[i * self._rec:(i + 1) * self._rec])
                if b is not None:
                    out.append(b)
            if out:
                metrics.CHAIN_STORE_READS.labels(
                    backend="segment").inc(len(out))
            yield from out
            round_no += n
            if len(data) < n * self._rec and seg == top_seg:
                return  # past the end of the newest segment

    def put_many(self, beacons) -> int:
        """Bulk append: consecutive-round runs become single contiguous
        writes (one seek + one write per ~4096 records instead of one
        per beacon) — the migration and synthetic-chain path. Holds the
        lock per run, not per beacon."""
        n = 0
        run: list[bytes] = []
        run_start = 0
        last: Beacon | None = None

        def _flush() -> None:
            nonlocal run
            if not run:
                return
            seg, idx = divmod(run_start, self._seg_rounds)
            blob = b"".join(run)
            with self._lock:
                fh = self._handle(seg, create=True)
                if self._count is not None:
                    fh.seek(idx * self._rec)
                    old = fh.read(len(blob))
                    replaced = sum(1 for i in range(0, len(old), self._rec)
                                   if old[i] & _F_PRESENT)
                    self._count += len(run) - replaced
                fh.seek(idx * self._rec)
                fh.write(blob)
                fh.flush()
            run = []

        prev = None
        for b in beacons:
            rec = self._pack(b)
            boundary = b.round % self._seg_rounds == 0
            if run and (prev is None or b.round != prev + 1
                        or boundary or len(run) >= 4096):
                _flush()
            if not run:
                run_start = b.round
            run.append(rec)
            prev = b.round
            n += 1
            if last is None or b.round >= last.round:
                last = b
        _flush()
        if last is not None:
            with self._lock:
                if self._last is None or last.round >= self._last.round:
                    self._last = last
        return n

    def del_round(self, round_no: int) -> None:
        with self._lock:
            seg, idx = divmod(round_no, self._seg_rounds)
            fh = self._handle(seg, create=False)
            if fh is None:
                return
            fh.seek(idx * self._rec)
            old = fh.read(1)
            if not (old and old[0] & _F_PRESENT):
                return
            fh.seek(idx * self._rec)
            fh.write(b"\0")
            fh.flush()
            if self._count is not None:
                self._count -= 1
            if self._last is not None and self._last.round == round_no:
                self._last = None
        # rescan outside the lock-held write path (reads re-acquire)
        if self._last is None:
            last = self._scan_last()
            with self._lock:
                if self._last is None:
                    self._last = last

    def del_from(self, round_no: int) -> int:
        """Rollback: remove every round >= round_no (`drand util
        del-beacon` on a segment chain). Whole segments past the cut
        are deleted, the partial one is truncated at the cut record —
        one truncate instead of per-round flag clears. Returns the
        number of present records removed."""
        removed = 0
        with self._lock:
            cut_seg, cut_idx = divmod(max(0, round_no), self._seg_rounds)
            for seg in self._seg_indices():
                if seg < cut_seg:
                    continue
                path = self._seg_path(seg)
                start = cut_idx * self._rec if seg == cut_seg else 0
                with open(path, "rb") as fh:
                    fh.seek(start)
                    data = fh.read()
                removed += sum(1 for i in range(0, len(data), self._rec)
                               if data[i] & _F_PRESENT)
                fh2 = self._handles.pop(seg, None)
                if fh2 is not None:
                    fh2.close()
                if seg == cut_seg and start > 0:
                    with open(path, "r+b") as fh:
                        fh.truncate(start)
                else:
                    os.remove(path)
            if self._count is not None:
                self._count -= removed
            self._last = None
        last = self._scan_last()
        with self._lock:
            if self._last is None:
                self._last = last
        return removed

    def close(self) -> None:
        with self._lock:
            for fh in self._handles.values():
                fh.close()
            self._handles.clear()


def migrate_store(src: Store, dst: Store) -> int:
    """Copy every beacon from ``src`` to ``dst`` in round order via the
    bulk path (batched transactions / contiguous segment writes).
    Lossless both ways (the fixed-width codec preserves every field
    byte-for-byte); returns the number of rounds copied."""
    return dst.put_many(src.cursor())
