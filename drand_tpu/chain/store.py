"""Beacon chain storage: interface, in-memory and SQLite backends, and the
append/callback decorators the beacon engine stacks on top.

Reference: chain/store.go (Store/Cursor/GenesisBeacon), chain/boltdb/store.go
(durable KV store, 8-byte BE round keys), chain/beacon/store.go (appendStore
monotonicity :26, callbackStore fan-out :85).

The SQLite backend replaces bbolt: single-writer append workload, read-mostly
serving — same niche, stdlib-available.
"""

from __future__ import annotations

import asyncio
import sqlite3
import threading
from typing import Callable, Iterator

from ..utils.aio import spawn
from .beacon import Beacon
from .info import Info


class StoreError(Exception):
    pass


class Store:
    """Append-oriented beacon store (reference chain/store.go:14)."""

    def __len__(self) -> int:
        raise NotImplementedError

    def put(self, b: Beacon) -> None:
        raise NotImplementedError

    def last(self) -> Beacon:
        raise NotImplementedError

    def get(self, round_no: int) -> Beacon | None:
        raise NotImplementedError

    def cursor(self) -> Iterator[Beacon]:
        """Iterate beacons in round order."""
        raise NotImplementedError

    def cursor_from(self, from_round: int) -> Iterator[Beacon]:
        raise NotImplementedError

    def del_round(self, round_no: int) -> None:
        """Rollback support (`drand util del-beacon`, cli.go:651)."""
        raise NotImplementedError

    def put_many(self, beacons) -> int:
        """Bulk append (migration, archives, synthetic chains). The
        default is a put() loop so every decorator's hooks and guards
        still run; backends override with batched writes."""
        n = 0
        for b in beacons:
            self.put(b)
            n += 1
        return n

    def close(self) -> None:
        pass


def genesis_beacon(info: Info) -> Beacon:
    """Round 0: fixed, signature = genesis seed (chain/store.go:47)."""
    return Beacon(round=0, previous_sig=b"", signature=info.genesis_seed)


class WrappedStore(Store):
    """Base for store decorators: delegates everything to ``_inner``;
    subclasses override what they decorate."""

    def __init__(self, inner: Store):
        self._inner = inner

    def __len__(self):
        return len(self._inner)

    def put(self, b: Beacon) -> None:
        self._inner.put(b)

    def last(self):
        return self._inner.last()

    def get(self, r):
        return self._inner.get(r)

    def cursor(self):
        return self._inner.cursor()

    def cursor_from(self, r):
        return self._inner.cursor_from(r)

    def del_round(self, r):
        self._inner.del_round(r)

    def close(self):
        self._inner.close()


class MemStore(Store):
    """Dict-backed store for tests and relays."""

    def __init__(self):
        self._by_round: dict[int, Beacon] = {}
        self._last: Beacon | None = None
        self._lock = threading.Lock()

    def __len__(self) -> int:
        with self._lock:
            return len(self._by_round)

    def put(self, b: Beacon) -> None:
        with self._lock:
            self._by_round[b.round] = b
            if self._last is None or b.round >= self._last.round:
                self._last = b

    def last(self) -> Beacon:
        with self._lock:
            if self._last is None:
                raise StoreError("store is empty")
            return self._last

    def get(self, round_no: int) -> Beacon | None:
        with self._lock:
            return self._by_round.get(round_no)

    def cursor(self) -> Iterator[Beacon]:
        with self._lock:
            rounds = sorted(self._by_round)
            items = [self._by_round[r] for r in rounds]
        yield from items

    def cursor_from(self, from_round: int) -> Iterator[Beacon]:
        for b in self.cursor():
            if b.round >= from_round:
                yield b

    def del_round(self, round_no: int) -> None:
        with self._lock:
            self._by_round.pop(round_no, None)
            if self._last is not None and self._last.round == round_no:
                self._last = (
                    self._by_round[max(self._by_round)] if self._by_round else None
                )


class SQLiteStore(Store):
    """Durable chain store (boltdb replacement). Key = round, value =
    hex-JSON beacon, mirroring chain/boltdb/store.go:21-85."""

    def __init__(self, path: str):
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS beacons ("
            " round INTEGER PRIMARY KEY,"
            " data BLOB NOT NULL)"
        )
        self._conn.execute("PRAGMA journal_mode=WAL")
        self._conn.execute("PRAGMA synchronous=NORMAL")
        self._conn.commit()
        self._lock = threading.Lock()

    def __len__(self) -> int:
        with self._lock:
            (n,) = self._conn.execute("SELECT COUNT(*) FROM beacons").fetchone()
        return n

    def put(self, b: Beacon) -> None:
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO beacons (round, data) VALUES (?, ?)",
                (b.round, b.marshal()),
            )
            self._conn.commit()

    def last(self) -> Beacon:
        with self._lock:
            row = self._conn.execute(
                "SELECT data FROM beacons ORDER BY round DESC LIMIT 1"
            ).fetchone()
        if row is None:
            raise StoreError("store is empty")
        return Beacon.unmarshal(row[0])

    def get(self, round_no: int) -> Beacon | None:
        from .. import metrics

        with self._lock:
            row = self._conn.execute(
                "SELECT data FROM beacons WHERE round = ?", (round_no,)
            ).fetchone()
        metrics.CHAIN_STORE_READS.labels(backend="sqlite").inc()
        return None if row is None else Beacon.unmarshal(row[0])

    def cursor(self) -> Iterator[Beacon]:
        return self.cursor_from(0)

    def cursor_from(self, from_round: int, batch: int = 512) -> Iterator[Beacon]:
        """Streams in batches: a sync of a multi-million-round chain must not
        materialize it in memory or hold the lock for the whole walk."""
        from .. import metrics

        next_round = from_round
        while True:
            with self._lock:
                rows = self._conn.execute(
                    "SELECT round, data FROM beacons WHERE round >= ?"
                    " ORDER BY round LIMIT ?",
                    (next_round, batch),
                ).fetchall()
            if not rows:
                return
            metrics.CHAIN_STORE_READS.labels(backend="sqlite").inc(len(rows))
            for r, data in rows:
                yield Beacon.unmarshal(data)
            next_round = rows[-1][0] + 1

    def put_many(self, beacons) -> int:
        """Bulk insert in chunked transactions — a 1M-round migration
        must not fsync per round."""
        n = 0
        it = iter(beacons)
        while True:
            chunk = [(b.round, b.marshal())
                     for _, b in zip(range(4096), it)]
            if not chunk:
                return n
            with self._lock:
                self._conn.executemany(
                    "INSERT OR REPLACE INTO beacons (round, data) "
                    "VALUES (?, ?)", chunk)
                self._conn.commit()
            n += len(chunk)

    def del_round(self, round_no: int) -> None:
        with self._lock:
            self._conn.execute("DELETE FROM beacons WHERE round = ?", (round_no,))
            self._conn.commit()

    def del_from(self, round_no: int) -> int:
        """Rollback: remove every round >= round_no in ONE transaction
        (`drand util del-beacon` on a long chain must not fsync per round)."""
        with self._lock:
            cur = self._conn.execute(
                "DELETE FROM beacons WHERE round >= ?", (round_no,))
            self._conn.commit()
            return cur.rowcount

    def close(self) -> None:
        with self._lock:
            self._conn.close()


def _chain_backend(db_path: str) -> tuple[str, str]:
    """``(backend, path)`` resolution shared by the factory and the
    existence probe — the ONE place that knows the DRAND_TPU_STORE
    default and the segments-dir layout, so the offline CLI commands
    (del-beacon) always probe exactly what the factory opens."""
    import os

    if os.environ.get("DRAND_TPU_STORE", "sqlite") == "segment":
        return "segment", os.path.join(os.path.dirname(db_path),
                                       "segments")
    return "sqlite", db_path


def open_chain_store(db_path: str) -> Store:
    """The daemon/CLI chain-store factory. SQLite is the default;
    ``DRAND_TPU_STORE=segment`` selects the packed per-epoch segment
    backend (chain/segments.py) in a ``segments/`` directory next to
    the SQLite path — `drand-tpu util store-migrate` converts an
    existing chain between the two."""
    backend, path = _chain_backend(db_path)
    if backend == "segment":
        from .segments import SegmentStore

        return SegmentStore(path)
    return SQLiteStore(path)


def chain_store_exists(db_path: str) -> tuple[bool, str]:
    """``(exists, path)`` for the backend :func:`open_chain_store`
    would open for ``db_path``."""
    import os

    backend, path = _chain_backend(db_path)
    if backend == "segment":
        from .segments import META_FILE

        return os.path.isfile(os.path.join(path, META_FILE)), path
    return os.path.isfile(path), path


class AppendStore(WrappedStore):
    """Monotonicity guard: only round+1 with matching previous signature
    (chain/beacon/store.go:26-53)."""

    def __init__(self, inner: Store):
        super().__init__(inner)
        self._lock = threading.Lock()
        try:
            self._last: Beacon | None = inner.last()
        except StoreError:
            self._last = None

    def put(self, b: Beacon) -> None:
        with self._lock:
            if self._last is not None:
                if b.round != self._last.round + 1:
                    raise StoreError(
                        f"invalid round inserted: last {self._last.round}, new {b.round}"
                    )
                if self._last.signature != b.previous_sig:
                    raise StoreError("invalid previous signature")
            self._inner.put(b)
            self._last = b

    def del_round(self, r):
        with self._lock:
            self._inner.del_round(r)
            try:
                self._last = self._inner.last()
            except StoreError:
                self._last = None


class DiscrepancyStore(WrappedStore):
    """Observability decorator (chain/beacon/store.go:57-82): on every
    stored beacon, record how late it landed vs its scheduled round time
    and the new chain tip — the reference gauges plus the chain-health
    tier (lateness histogram, head/lag/missed, SLO window; obs/health)
    — and hand the completed round's timeline to the OTLP exporter
    (obs/export, flushed off the hot path)."""

    def __init__(self, inner: Store, group, clock, health=None,
                 incidents=None):
        super().__init__(inner)
        self._group = group
        self._clock = clock
        # health-state override: the per-process HEALTH singleton unless
        # an in-process multi-node harness injected one PER NODE (the
        # chaos simulator) — without it, the singleton's monotonic-max
        # head makes a minority-partition node's observations read the
        # majority's progress
        self._health = health
        # incident-manager override, same per-node rule (obs/incident):
        # None = the per-process INCIDENTS singleton
        self._incidents = incidents

    def put(self, b: Beacon) -> None:
        self._inner.put(b)
        if b.round == 0:
            return
        from .. import metrics
        from ..obs import export as obs_export
        from ..obs import incident as obs_incident
        from ..obs.health import HEALTH
        from ..timelock import service as timelock_service
        from . import time_math

        health = self._health if self._health is not None else HEALTH
        now = self._clock.now()
        expected = time_math.time_of_round(self._group.period,
                                           self._group.genesis_time, b.round)
        metrics.BEACON_DISCREPANCY_LATENCY.set((now - expected) * 1000.0)
        metrics.LAST_BEACON_ROUND.set(b.round)
        health.note_round_stored(b.round, now - expected,
                                 self._group.period)
        health.observe_chain(now, self._group.period,
                             self._group.genesis_time, b.round)
        obs_export.note_round_complete(b.round,
                                       self._group.get_genesis_seed())
        # round-boundary hook for the incident engine (obs/incident):
        # one SLI time-series sample + rule evaluation per stored round
        # — failures log once and never take the store path down
        obs_incident.note_round_stored(b.round, now=now,
                                       period=self._group.period,
                                       incidents=self._incidents)
        # round-boundary hook for the timelock vault (drand_tpu/timelock):
        # a registered service opens the round's pending ciphertexts in
        # one batched dispatch — a no-op when no vault is serving
        timelock_service.note_round_complete(b)


class CallbackStore(WrappedStore):
    """Fans every stored beacon out to registered callbacks
    (chain/beacon/store.go:85; worker pool replaced by asyncio tasks).
    Callbacks may be sync or async; they never run for the genesis round."""

    def __init__(self, inner: Store):
        super().__init__(inner)
        self._callbacks: dict[str, Callable] = {}
        self._lock = threading.Lock()

    def add_callback(self, cb_id: str, fn: Callable) -> None:
        with self._lock:
            self._callbacks[cb_id] = fn

    def remove_callback(self, cb_id: str) -> None:
        with self._lock:
            self._callbacks.pop(cb_id, None)

    def put(self, b: Beacon) -> None:
        self._inner.put(b)
        if b.round == 0:
            return
        with self._lock:
            cbs = list(self._callbacks.values())
        for cb in cbs:
            res = cb(b)
            if asyncio.iscoroutine(res):
                spawn(res)
