"""Chain synchronization: follow peers' chains (client) and serve sync
streams (server). Reference: chain/beacon/sync.go.
"""

from __future__ import annotations

import asyncio
import os
import random
import time as _time
from typing import AsyncIterator

from ...crypto import batch
from ...net.packets import SyncRequest
from ...net.transport import ProtocolClient, TransportError
from ...obs.trace import TRACER
from ...utils.clock import Clock
from ...utils.logging import KVLogger
from ...utils.retry import RetryPolicy, retry
from ..beacon import Beacon
from ..info import Info
from ..store import CallbackStore, StoreError

# beacons buffered per batched verification during follow; the device engine
# verifies a whole chunk in one multi-pairing call (client/verify.go:146-163
# made parallel). Chunk boundaries never change semantics — only batch size.
SYNC_CHUNK = int(os.environ.get("DRAND_TPU_SYNC_CHUNK", "64"))
# full passes over the upstream list per follow (ISSUE 12): a follow no
# longer dies with one bad pass — it backs off under the shared retry
# policy (injectable-clock sleeps) and tries the whole list again,
# resuming from the stored checkpoint
SYNC_PASSES = int(os.environ.get("DRAND_TPU_SYNC_RETRIES", "3"))


def _verify_chunk_size() -> int:
    """SYNC_CHUNK rounded UP to a multiple of the engine's mesh size, so
    a mesh-sharded engine's catch-up chunks divide evenly across shards
    and the sharded wire-RLC tier engages with zero pad waste (odd
    chunks still verify correctly — the engine pads to the mesh — but a
    divisible chunk is all live lanes). A cheap attribute peek
    (crypto/batch.engine_mesh_size), loop-safe by construction."""
    mesh = batch.engine_mesh_size()
    return -(-SYNC_CHUNK // mesh) * mesh


async def _chunks(stream: AsyncIterator[Beacon], size: int):
    """Re-chunk an async stream into lists of up to `size`, flushing early
    when the producer stalls (so live streams stay per-item latency).
    On a stream error the partial buffer is flushed before the error
    propagates (received beacons are not re-fetched from the next peer),
    and a pending read is cancelled if the consumer exits early."""
    buf: list[Beacon] = []
    it = stream.__aiter__()
    task: asyncio.Future | None = None
    try:
        while True:
            task = asyncio.ensure_future(it.__anext__())
            # a replaying server yields back-to-back without real awaiting;
            # give the task a few microtask rounds before declaring a stall
            for _ in range(4):
                if task.done():
                    break
                await asyncio.sleep(0)
            if not task.done() and buf:
                yield buf
                buf = []
            try:
                b = await task
            except StopAsyncIteration:
                task = None
                break
            except Exception:
                task = None
                if buf:
                    yield buf
                raise
            task = None
            buf.append(b)
            if len(buf) >= size:
                yield buf
                buf = []
        if buf:
            yield buf
    finally:
        if task is not None and not task.done():
            task.cancel()


class Syncer:
    """Client side: Follow shuffles peers and streams beacons from last+1,
    verifying each link, with multi-upstream failover: a pass over the
    peer list that fails backs off (shared retry policy, injectable
    clock) and re-runs, and every re-attempt resumes from the stored
    checkpoint — ``_try_node`` streams from ``store.last() + 1``, so a
    span verified+stored before a mid-chunk upstream death is NEVER
    re-fetched or re-verified. Server side: SyncChain replays the
    cursor then streams live beacons via a store callback."""

    def __init__(self, logger: KVLogger, store: CallbackStore, info: Info,
                 client: ProtocolClient, clock: Clock | None = None):
        self._l = logger
        self._store = store
        self._info = info
        self._client = client
        self._clock = clock
        self._policy = RetryPolicy(attempts=max(1, SYNC_PASSES),
                                   base_s=0.2, cap_s=5.0)
        self._following = False
        self._lock = asyncio.Lock()

    def syncing(self) -> bool:
        return self._following

    async def follow(self, up_to: int, peers: list) -> bool:
        """Blocking: fetch/verify/store beacons until up_to (0 = forever).
        Returns True if the target round was reached."""
        async with self._lock:
            if self._following:
                self._l.debug("syncer", "already_following")
                return False
            self._following = True
        # catch-up progress surface (obs/health): rounds/sec + ETA per
        # verified chunk, so a node syncing a year-old chain is
        # observable instead of silent; zeroed when the follow ends
        from ...obs.health import HEALTH

        self._progress_t0 = _time.perf_counter()
        self._progress_done = 0

        async def _one_pass() -> bool:
            order = list(peers)
            random.shuffle(order)
            for peer in order:
                if await self._try_node(up_to, peer):
                    return True
            self._l.debug("syncer", "tried_all_nodes")
            raise TransportError("sync: tried all upstreams")

        try:
            return await retry(_one_pass, op="sync", policy=self._policy,
                               clock=self._clock,
                               retry_on=(TransportError,))
        except TransportError:
            return False
        finally:
            self._following = False
            HEALTH.note_sync_progress(self._progress_done, 0.0, 0, up_to,
                                      active=False)

    def _note_progress(self, up_to: int, current_round: int,
                       newly_stored: int) -> None:
        from ...obs.health import HEALTH

        self._progress_done += newly_stored
        HEALTH.note_sync_progress(
            self._progress_done,
            _time.perf_counter() - self._progress_t0, current_round,
            up_to)

    async def _try_node(self, up_to: int, peer) -> bool:
        try:
            last = self._store.last()
        except StoreError:
            return False
        try:
            stream = self._client.sync_chain(peer, SyncRequest(from_round=last.round + 1))
            async for chunk in _chunks(stream, _verify_chunk_size()):
                # batched dual verification: V1 chain link and — hardening
                # over the reference, which skips this (sync.go:105) — the V2
                # signature when present, so a malicious sync peer cannot
                # poison the unchained signature (the timelock key).
                # retain=False: catch-up streams thousands of historical
                # rounds — they must feed the histograms without evicting
                # live round timelines from the bounded ring
                # executor hand-off: a big span's multi-pairing work is
                # seconds of CPU (or a blocking device dispatch) — run it
                # on a worker thread so /healthz, gossip and DKG traffic
                # keep being served mid-catch-up. to_thread copies the
                # contextvars context, so the trace spans and
                # engine_op_seconds samples land exactly as before.
                with TRACER.activate(round_no=chunk[-1].round,
                                     chain=self._info.genesis_seed,
                                     retain=False), \
                        TRACER.span("sync_verify", chunk=len(chunk),
                                    peer=_addr(peer)):
                    oks = await asyncio.to_thread(
                        batch.verify_beacons, self._info.public_key, chunk)
                stored = 0
                for b, ok in zip(chunk, oks):
                    if not ok:
                        self._l.warn("syncer", "invalid_beacon", peer=_addr(peer),
                                     round=b.round)
                        self._note_progress(up_to, last.round, stored)
                        return False
                    try:
                        self._store.put(b)
                    except StoreError as e:
                        self._l.debug("syncer", "store_failed", err=str(e))
                        self._note_progress(up_to, last.round, stored)
                        return False
                    last = b
                    stored += 1
                    if up_to and last.round >= up_to:
                        self._l.debug("syncer", "finished", round=up_to)
                        self._note_progress(up_to, last.round, stored)
                        return True
                self._note_progress(up_to, last.round, stored)
        except TransportError as e:
            self._l.debug("syncer", "unable_to_sync", peer=_addr(peer), err=str(e))
            return False
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — a crypto-engine failure
            # (device mode re-raises) must not kill the follow task
            self._l.error("syncer", "sync_failed", peer=_addr(peer),
                          err=repr(e))
            return False
        return False

    async def sync_chain(self, from_addr: str, req: SyncRequest) -> AsyncIterator[Beacon]:
        """Server side: replay from the cursor, then live-stream."""
        try:
            last = self._store.last()
        except StoreError:
            return
        if last.round < req.from_round:
            raise TransportError(
                f"no beacon stored above requested round {last.round} < {req.from_round}"
            )
        queue: asyncio.Queue[Beacon] = asyncio.Queue(maxsize=256)
        cb_id = f"sync-{from_addr}-{id(queue)}"

        def _on_beacon(b: Beacon) -> None:
            try:
                queue.put_nowait(b)
            except asyncio.QueueFull:
                pass  # slow consumer: it will re-sync

        self._store.add_callback(cb_id, _on_beacon)
        try:
            sent = 0
            for b in self._store.cursor_from(req.from_round):
                yield b
                sent = b.round
            while True:
                b = await queue.get()
                if b.round > sent:
                    yield b
                    sent = b.round
        finally:
            self._store.remove_callback(cb_id)


def _addr(peer) -> str:
    return peer.address() if hasattr(peer, "address") else str(peer)
