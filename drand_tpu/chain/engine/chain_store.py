"""The aggregation hot loop: consume verified partials, Lagrange-recover the
full signature (dual V1+V2), verify, append, fan out.

Reference: chain/beacon/chain.go (chainStore :22, runAggregator :91,
tryAppend :192, RunSync :222). The recover/verify calls route through the
batched engine when one is configured (the TPU path), else the host tbls.
"""

from __future__ import annotations

import asyncio
import time as _time
from dataclasses import dataclass

from ...crypto import batch
from ...net.packets import PartialBeaconPacket
from ...net.transport import ProtocolClient
from ...obs.flight import FLIGHT
from ...obs.trace import TRACER
from ...utils.aio import spawn
from ...utils.logging import KVLogger
from .. import beacon as chain_beacon
from .. import time_math
from ..beacon import Beacon
from ..store import AppendStore, CallbackStore, DiscrepancyStore, Store, StoreError
from .cache import PartialCache
from .crypto import CryptoStore
from .sync import Syncer
from .ticker import Ticker

# partials accepted up to this many rounds past the last stored beacon
# (chain/beacon/chain.go:87 partialCacheStoreLimit)
PARTIAL_CACHE_STORE_LIMIT = 3


@dataclass
class _PartialInfo:
    addr: str
    p: PartialBeaconPacket


class ChainStore(CallbackStore):
    """CallbackStore + aggregator task + syncer (chainStore analogue)."""

    def __init__(self, logger: KVLogger, conf, client: ProtocolClient,
                 crypto: CryptoStore, store: Store, ticker: Ticker):
        base = DiscrepancyStore(AppendStore(store), conf.group, conf.clock,
                                health=getattr(conf, "health", None),
                                incidents=getattr(conf, "incidents", None))
        super().__init__(base)
        self._l = logger
        self._conf = conf
        # per-node recorder override (BeaconConfig.flight) — the process
        # singleton unless an in-process harness injected one per node
        self._flight = (conf.flight if getattr(conf, "flight", None)
                        is not None else FLIGHT)
        self._client = client
        self._crypto = crypto
        self._ticker = ticker
        self.sync = Syncer(logger.named("sync"), self, crypto.chain_info,
                           client, clock=conf.clock)
        # single merged event queue: ("stored", Beacon) | ("partial", _PartialInfo)
        # — one consumer, no multi-queue cancellation races
        self._events: asyncio.Queue[tuple[str, object]] = asyncio.Queue(maxsize=512)
        # notifies the Handler when a beacon was aggregated without sync
        self.catchup_beacons: asyncio.Queue[Beacon] = asyncio.Queue(maxsize=1)
        # the collector's per-round partial set. An attribute (not an
        # aggregator-loop local) so quorum repair can read it: the
        # handler SERVES these to pulling peers and reads its own gap
        # before pulling. Loop-thread-only access by construction —
        # the aggregator mutates it from _process_event and the
        # handler's service surface runs on the same loop.
        self.cache = PartialCache()
        # latest recovered checkpoint (client/checkpoint.py Checkpoint):
        # loop-thread-only writes from the aggregator, read by the
        # handler's service surface on the same loop
        self.latest_checkpoint = None
        self._agg_task: asyncio.Task | None = None
        self.add_callback("chainstore", self._on_stored)

    def start(self) -> None:
        self._agg_task = asyncio.ensure_future(self._run_aggregator())

    def stop(self) -> None:
        if self._agg_task is not None:
            self._agg_task.cancel()

    def _on_stored(self, b: Beacon) -> None:
        try:
            self._events.put_nowait(("stored", b))
        except asyncio.QueueFull:
            pass

    def new_valid_partial(self, addr: str, p: PartialBeaconPacket) -> None:
        try:
            self._events.put_nowait(("partial", _PartialInfo(addr, p)))
        except asyncio.QueueFull:
            self._l.warn("aggregator", "partial_queue_full", dropping=p.round)

    def partial_indices(self, round_no: int,
                        previous_sig: bytes) -> set[int]:
        """Share indices of the valid partials collected for one round
        (empty when nothing was collected) — the quorum-repair gap
        check. Valid-only by construction: everything in the cache
        passed ingress verification, so a repair trigger can never be
        driven by UNVERIFIED-index events."""
        rc = self.cache.get_round_cache(round_no, previous_sig)
        return set(rc.sigs) if rc is not None else set()

    def partials_for(self, round_no: int, previous_sig: bytes,
                     exclude: set[int]) -> list[PartialBeaconPacket]:
        """The collected partial packets for one round, minus
        ``exclude`` — what a repair PULL serves. Bounded by the group
        size (the cache holds at most one partial per index)."""
        rc = self.cache.get_round_cache(round_no, previous_sig)
        if rc is None:
            return []
        return [PartialBeaconPacket(
                    round=rc.round, previous_sig=rc.prev, partial_sig=sig,
                    partial_sig_v2=rc.sigs_v2.get(idx, b""),
                    partial_ckpt=rc.sigs_ckpt.get(idx, b""))
                for idx, sig in rc.sigs.items() if idx not in exclude]

    async def _run_aggregator(self) -> None:
        last = self.last()
        cache = self.cache
        while True:
            kind, payload = await self._events.get()
            try:
                last = await self._process_event(kind, payload, cache, last)
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — the aggregator task
                # must survive any crypto-engine failure (device mode
                # re-raises instead of falling back): losing this task
                # silently halts the node
                self._l.error("aggregator", "event_failed", err=repr(e))

    async def _process_event(self, kind: str, payload, cache: PartialCache,
                             last: Beacon) -> Beacon:
        if kind == "stored":
            last = payload
            cache.flush_rounds(last.round)
            return last
        partial = payload
        p_round = partial.p.round
        if not (last.round < p_round <= last.round + PARTIAL_CACHE_STORE_LIMIT + 1):
            self._l.debug("aggregator", "ignoring_partial", round=p_round,
                          last=last.round)
            return last
        with TRACER.activate(round_no=p_round,
                             chain=self._crypto.chain_info.genesis_seed):
            return await self._process_partial(partial, cache, last)

    async def _process_partial(self, partial: _PartialInfo, cache: PartialCache,
                               last: Beacon) -> Beacon:
        p_round = partial.p.round
        group = self._crypto.get_group()
        thr, n = group.threshold, len(group)
        with TRACER.span("collect", sender=partial.addr) as sp:
            cache.append(partial.p)
            rc = cache.get_round_cache(p_round, partial.p.previous_sig)
            if rc is not None:
                sp.attrs.update(have=len(rc), threshold=thr)
        if rc is None:
            self._l.error("aggregator", "no_round_cache", round=p_round)
            return last
        self._l.debug("aggregator", "store_partial", addr=partial.addr,
                      round=rc.round, have=f"{len(rc)}/{thr}")
        if len(rc) < thr:
            return last
        # the t-th valid partial is in: quorum time + margin SLI. The
        # recorder dedups (first quorum wins), and the recover-dispatch
        # milestone rides the same gate — straggler partials past the
        # threshold re-enter here while the first aggregation is still
        # on its worker thread and must not append duplicate milestones
        if self._flight.note_quorum(
                rc.round, have=len(rc), threshold=thr,
                now=self._conf.clock.now(),
                period=self._conf.group.period,
                genesis=self._conf.group.genesis_time, n=n):
            self._flight.note_milestone(
                rc.round, "recover", now=self._conf.clock.now(),
                period=self._conf.group.period,
                genesis=self._conf.group.genesis_time)
        new_beacon = await self._aggregate(rc, thr, n)
        if new_beacon is None:
            return last
        cache.flush_rounds(rc.round)
        self._l.info("aggregator", "aggregated_beacon", round=new_beacon.round,
                     v2=new_beacon.is_v2())
        if self._try_append(last, new_beacon):
            return new_beacon
        if new_beacon.round > last.round + 1:
            # aggregated a beacon ahead of our chain: catch up
            peers = [nd.identity for nd in group.nodes]
            spawn(self.sync.follow(new_beacon.round, peers))
        return last

    async def _aggregate(self, rc, thr: int, n: int) -> Beacon | None:
        """Recover + verify V1 and (when possible) V2 — the crypto hot path
        (chain/beacon/chain.go:136-166). Each chain's whole round work
        (partial re-verify + Lagrange recovery + recovered-signature
        check) is ONE fused device dispatch when the engine is active
        (batch.aggregate_round); recovery failure AND a recovered
        signature failing its pairing check both surface as ValueError.
        Partials were already signature-checked on ingress (handler.py),
        so the in-graph re-verify costs no extra dispatches.

        Runs on a worker thread (``asyncio.to_thread``): Lagrange
        recovery + the recovered-signature pairing are tens of
        milliseconds of host CPU (or a blocking device dispatch), and
        the aggregator task shares the event loop with /healthz, gossip
        and the DKG surfaces. to_thread copies contextvars, so the
        recover/verify trace spans still land in the round timeline."""
        from ...crypto.tbls import RecoveredSignatureInvalid

        pub = self._crypto.get_pub()
        msg = rc.msg()
        try:
            _, final_sig = await asyncio.to_thread(
                batch.aggregate_round,
                pub, msg, rc.partials(), thr, n, prevalidated=True)
        except RecoveredSignatureInvalid as e:
            # security-significant: individually-valid partials produced
            # an invalid group signature (byzantine member / corruption)
            self._l.error("aggregator", "invalid_sig", err=str(e), round=rc.round)
            return None
        except ValueError as e:
            self._l.debug("aggregator", "invalid_recovery", err=str(e), round=rc.round)
            return None
        b = Beacon(round=rc.round, previous_sig=rc.prev, signature=final_sig)
        if rc.len_ckpt() >= thr:
            # checkpoint piggyback: recover the group attestation of the
            # head this round chains from. Strictly best-effort — a
            # failed checkpoint recovery never blocks the beacon
            await self._recover_checkpoint(rc, thr, n)
        if rc.len_v2() >= thr:
            msg_v2 = chain_beacon.message_v2(rc.round)
            try:
                _, sig_v2 = await asyncio.to_thread(
                    batch.aggregate_round,
                    pub, msg_v2, rc.partials_v2(), thr, n,
                    prevalidated=True)
            except RecoveredSignatureInvalid as e:
                self._l.error("aggregator", "invalid_sig_v2", err=str(e),
                              round=rc.round)
                return None
            except ValueError as e:
                self._l.debug("aggregator", "invalid_recovery_v2", err=str(e))
                return None  # never accept a beacon whose V2 fails to recover
            b.signature_v2 = sig_v2
        return b

    async def _recover_checkpoint(self, rc, thr: int, n: int) -> None:
        """Recover the checkpoint signature for round rc.round-1 from the
        piggybacked partials (client/checkpoint.py): one Lagrange
        recovery + product check on a worker thread. Any failure is
        logged and dropped — checkpoints are an accelerator for client
        bootstrap, never load-bearing for the chain itself."""
        from ... import metrics
        from ...client.checkpoint import Checkpoint, checkpoint_message

        ckpt_round = rc.round - 1
        chain_hash = self._crypto.chain_info.hash()
        cmsg = checkpoint_message(chain_hash, ckpt_round, rc.prev)
        pub = self._crypto.get_pub()
        try:
            _, ckpt_sig = await asyncio.to_thread(
                batch.aggregate_round,
                pub, cmsg, rc.partials_ckpt(), thr, n, prevalidated=True)
        except ValueError as e:
            # covers RecoveredSignatureInvalid too
            self._l.warn("aggregator", "checkpoint_recovery_failed",
                         err=str(e), round=ckpt_round)
            return
        self.latest_checkpoint = Checkpoint(
            round=ckpt_round, signature=rc.prev, chain_hash=chain_hash,
            ckpt_sig=ckpt_sig)
        metrics.CKPT_ISSUED.inc()
        metrics.CKPT_ROUND.set(ckpt_round)
        self._l.info("aggregator", "checkpoint_recovered", round=ckpt_round)

    def _try_append(self, last: Beacon, new_beacon: Beacon) -> bool:
        if last.round + 1 != new_beacon.round:
            return False
        try:
            # store span covers the append AND the callback fan-out
            # (DiscrepancyStore gauges, sync streams, transitions)
            with TRACER.span("store", v2=new_beacon.is_v2()):
                self.put(new_beacon)
        except StoreError as e:
            self._l.error("aggregator", "error_storing", err=str(e))
            return False
        self._flight.note_milestone(
            new_beacon.round, "store", now=self._conf.clock.now(),
            period=self._conf.group.period,
            genesis=self._conf.group.genesis_time)
        try:
            self.catchup_beacons.put_nowait(new_beacon)
        except asyncio.QueueFull:
            pass
        return True

    async def run_sync(self, up_to: int, peers: list | None) -> None:
        if peers is None:
            peers = [nd.identity for nd in self._crypto.get_group().nodes]
        await self.sync.follow(up_to, peers)
