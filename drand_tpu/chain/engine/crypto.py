"""Thread-safe holder of the node's share/group — swapped atomically at
reshare transitions (reference: chain/beacon/crypto.go).
"""

from __future__ import annotations

import threading

from ...crypto import tbls
from ...crypto.poly import PubPoly
from ...key.group import Group
from ...key.keys import Share
from ..info import Info


class CryptoStore:
    def __init__(self, group: Group, share: Share):
        self._lock = threading.Lock()
        self._group = group
        self._share = share
        self._pub_poly = share.pub_poly()  # one instance: eval cache persists
        self.chain_info = Info.from_group(group)

    def get_group(self) -> Group:
        with self._lock:
            return self._group

    def get_pub(self) -> PubPoly:
        with self._lock:
            return self._pub_poly

    def index(self) -> int:
        with self._lock:
            return self._share.pri_share.index

    def sign_partial(self, msg: bytes) -> bytes:
        """Partial tbls signature with this node's share
        (chain/beacon/crypto.go:55). Host-CPU signing keeps the secret share
        off the accelerator (SURVEY.md §7 side-channel posture)."""
        with self._lock:
            share = self._share.pri_share
        return tbls.sign_partial(share, msg)

    def set_info(self, group: Group, share: Share) -> None:
        """Atomic swap at reshare transition (crypto.go:66)."""
        with self._lock:
            self._group = group
            self._share = share
            self._pub_poly = share.pub_poly()
