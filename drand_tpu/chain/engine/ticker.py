"""Genesis-aligned period ticker (reference: chain/beacon/ticker.go).

Fans out (round, time) to subscriber queues each period; subscribers with a
future start time don't receive ticks until it passes. Mock-clock friendly.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass

from .. import time_math
from ...utils.clock import Clock


@dataclass(frozen=True)
class RoundInfo:
    round: int
    time: int


class Ticker:
    def __init__(self, clock: Clock, period: int, genesis: int):
        self._clock = clock
        self._period = period
        self._genesis = genesis
        self._channels: list[tuple[asyncio.Queue, int]] = []
        self._task: asyncio.Task | None = None
        self._stopped = False

    def start(self) -> None:
        self._task = asyncio.ensure_future(self._run())

    def channel_at(self, start_at: int) -> asyncio.Queue:
        q: asyncio.Queue = asyncio.Queue(maxsize=1)
        self._channels.append((q, start_at))
        return q

    def channel(self) -> asyncio.Queue:
        return self.channel_at(int(self._clock.now()))

    def current_round(self) -> int:
        return time_math.current_round(int(self._clock.now()), self._period, self._genesis)

    def stop(self) -> None:
        self._stopped = True
        if self._task is not None:
            self._task.cancel()

    async def _run(self) -> None:
        try:
            # sleep until the next round boundary, then tick every period.
            # If we start exactly on a boundary (e.g. woken late at genesis),
            # emit that round's tick immediately instead of skipping it.
            now = int(self._clock.now())
            on_boundary = (
                now >= self._genesis and (now - self._genesis) % self._period == 0
            )
            if not on_boundary:
                _, ttime = time_math.next_round(now, self._period, self._genesis)
                if ttime > now:
                    await self._clock.sleep(ttime - now)
            while not self._stopped:
                now = int(self._clock.now())
                info = RoundInfo(
                    round=time_math.current_round(now, self._period, self._genesis),
                    time=now,
                )
                for q, start_at in self._channels:
                    if start_at > info.time:
                        continue
                    try:
                        q.put_nowait(info)
                    except asyncio.QueueFull:
                        pass  # slow consumer: drop, like the reference
                # sleep to the next boundary (not a fixed period: stay aligned)
                _, ttime = time_math.next_round(int(self._clock.now()), self._period, self._genesis)
                delta = ttime - self._clock.now()
                await self._clock.sleep(max(delta, 0.001))
        except asyncio.CancelledError:
            pass
