"""Partial-signature cache with DoS bounds.

Reference: chain/beacon/cache.go — rounds keyed by (round, previousSig);
at most MAX_PARTIALS_PER_NODE cache entries per node index, evicting the
oldest when exceeded (chain/beacon/constants.go:14).
"""

from __future__ import annotations

from ...crypto import tbls
from ...net.packets import PartialBeaconPacket
from .. import beacon as chain_beacon

MAX_PARTIALS_PER_NODE = 100


def round_id(round_no: int, previous_sig: bytes) -> bytes:
    return round_no.to_bytes(8, "big") + previous_sig


class RoundCache:
    def __init__(self, rid: bytes, p: PartialBeaconPacket):
        self.round = p.round
        self.prev = p.previous_sig
        self.id = rid
        self.sigs: dict[int, bytes] = {}
        self.sigs_v2: dict[int, bytes] = {}
        # checkpoint piggyback partials (net/packets.py partial_ckpt):
        # collected alongside the beacon partials, recovered by the
        # aggregator when the round is a checkpoint boundary
        self.sigs_ckpt: dict[int, bytes] = {}

    def append(self, p: PartialBeaconPacket) -> bool:
        idx = tbls.index_of(p.partial_sig)
        if idx in self.sigs:
            return False
        self.sigs[idx] = p.partial_sig
        if p.partial_sig_v2:
            self.sigs_v2[idx] = p.partial_sig_v2
        if p.partial_ckpt:
            self.sigs_ckpt[idx] = p.partial_ckpt
        return True

    def __len__(self) -> int:
        return len(self.sigs)

    def len_v2(self) -> int:
        return len(self.sigs_v2)

    def len_ckpt(self) -> int:
        return len(self.sigs_ckpt)

    def msg(self) -> bytes:
        return chain_beacon.message(self.round, self.prev)

    def partials(self) -> list[bytes]:
        return list(self.sigs.values())

    def partials_v2(self) -> list[bytes]:
        return list(self.sigs_v2.values())

    def partials_ckpt(self) -> list[bytes]:
        return list(self.sigs_ckpt.values())

    def flush_index(self, idx: int) -> None:
        self.sigs.pop(idx, None)
        self.sigs_v2.pop(idx, None)
        self.sigs_ckpt.pop(idx, None)


class PartialCache:
    def __init__(self):
        self.rounds: dict[bytes, RoundCache] = {}
        self.rcvd: dict[int, list[bytes]] = {}

    def append(self, p: PartialBeaconPacket) -> None:
        rid = round_id(p.round, p.previous_sig)
        idx = tbls.index_of(p.partial_sig)
        rc = self._get_cache(rid, p, idx)
        if rc is None:
            return
        if rc.append(p):
            self.rcvd.setdefault(idx, []).append(rid)

    def _get_cache(self, rid: bytes, p: PartialBeaconPacket, idx: int) -> RoundCache | None:
        if rid in self.rounds:
            return self.rounds[rid]
        if len(self.rcvd.get(idx, [])) >= MAX_PARTIALS_PER_NODE:
            # evict this node's oldest entry (the caller's append() records
            # the new id, keeping the per-node bound exact)
            to_evict = self.rcvd[idx][0]
            old = self.rounds.get(to_evict)
            if old is None:
                return None
            old.flush_index(idx)
            self.rcvd[idx] = self.rcvd[idx][1:]
            if len(old) == 0:
                del self.rounds[to_evict]
        rc = RoundCache(rid, p)
        self.rounds[rid] = rc
        return rc

    def get_round_cache(self, round_no: int, previous_sig: bytes) -> RoundCache | None:
        return self.rounds.get(round_id(round_no, previous_sig))

    def flush_rounds(self, round_no: int) -> None:
        """Delete every cached round <= round_no and its rcvd counters."""
        for rid in [r for r, c in self.rounds.items() if c.round <= round_no]:
            cache = self.rounds.pop(rid)
            for idx in cache.sigs:
                remaining = [i for i in self.rcvd.get(idx, []) if i != rid]
                if remaining:
                    self.rcvd[idx] = remaining
                else:
                    self.rcvd.pop(idx, None)
