"""Per-node beacon protocol driver: the round loop.

Reference: chain/beacon/node.go (Handler :36). Each period tick: sign the
next round's V1+V2 messages with the node's share, feed the local aggregator
and broadcast to all peers; fast-path catchup when the chain lags.
"""

from __future__ import annotations

import asyncio
import os
from dataclasses import dataclass
from typing import AsyncIterator

from ...client import checkpoint as ckpt_mod
from ...crypto import tbls
from ...key.group import Group
from ...key.keys import Node, Share
from ...net.packets import PartialBeaconPacket, PartialRequest, SyncRequest
from ...net.transport import (BREAKER_OPEN, BreakerOpenError, PeerBreaker,
                              PeerRejectedError, ProtocolClient,
                              ProtocolService, TransportError)
from ...obs.flight import FLIGHT, FlightRecorder
from ...obs.trace import TRACER
from ...utils.aio import spawn
from ...utils.clock import Clock
from ...utils.logging import KVLogger
from ...utils.retry import RetryPolicy, retry
from .. import beacon as chain_beacon
from .. import time_math
from ..beacon import Beacon
from ..store import Store, genesis_beacon
from .chain_store import ChainStore
from .crypto import CryptoStore
from .ticker import Ticker

# partial-send retry budget (total tries per peer per round; the
# breaker gates every attempt, so a partitioned peer never sees a storm)
SEND_RETRY_ATTEMPTS = int(os.environ.get("DRAND_TPU_SEND_RETRIES", "3"))
# quorum repair (ISSUE 12) fires when the live round's quorum margin
# has shrunk below this fraction of the period (i.e. at
# (1 - fraction) * period past the boundary) while valid partials < t;
# 0 disables repair entirely
REPAIR_MARGIN_FRACTION = float(
    os.environ.get("DRAND_TPU_REPAIR_FRACTION", "0.25"))
# repair pulls SERVED per sender per round before refusing at the door
REPAIR_SERVE_CAP = int(os.environ.get("DRAND_TPU_REPAIR_SERVE_CAP", "4"))


def _breaker_gauge(index: int, state: int) -> None:
    """beacon_peer_breaker_state{index} export (index cardinality
    bounded by the group size, like beacon_peer_reachable)."""
    from ... import metrics

    metrics.PEER_BREAKER_STATE.labels(index=str(index)).set(state)


@dataclass
class BeaconConfig:
    """chain/beacon/node.go:23 Config analogue."""

    public: Node
    share: Share
    group: Group
    clock: Clock
    # flight recorder override. Production keeps the per-process FLIGHT
    # singleton (None); in-process multi-node harnesses (chaos simulator,
    # e2e suites) inject one recorder PER NODE so a byzantine or crashed
    # node's own notes cannot pollute an honest node's telemetry — each
    # recorder then models exactly what that node's process would see.
    flight: "FlightRecorder | None" = None
    # same rule for the chain-health state (obs/health.HealthState):
    # None = the per-process HEALTH singleton; per-node instances keep a
    # minority-partition node's lag/missed view honest (the singleton's
    # head is a monotonic max across every in-process node)
    health: object | None = None
    # incident-manager override (obs/incident.IncidentManager), same
    # per-node rule: None = the per-process INCIDENTS singleton — the
    # chaos harness injects one per probe node so a minority-partition
    # node's detections read ITS OWN samples
    incidents: object | None = None
    # quorum repair (ISSUE 12): active pull of missing partials when
    # the live round is still below threshold past the margin trigger.
    # Off switches the whole monitor (chaos A/B runs, bench baselines).
    repair: bool = True
    # checkpoint issuance interval in rounds (client/checkpoint.py):
    # None = the DRAND_TPU_CKPT_INTERVAL env default; 0 disables —
    # every interval round the partial broadcast piggybacks a partial
    # over the checkpoint message for the round it chains from
    checkpoint_interval: int | None = None


def _verify_partial_packet(pub, p: PartialBeaconPacket,
                           ckpt_msg: bytes | None = None) -> str | None:
    """The pairing-heavy half of partial ingress, shaped for
    ``asyncio.to_thread`` (node.go:96-130). Returns the rejection
    reason, or None when the packet is fully valid. ``ckpt_msg`` is the
    checkpoint message the caller expects a piggybacked checkpoint
    partial to sign (None when p.round-1 is not a checkpoint boundary —
    an unexpected checkpoint partial is then rejected outright)."""
    msg = chain_beacon.message(p.round, p.previous_sig)
    if not tbls.verify_partial(pub, msg, p.partial_sig):
        return "invalid partial signature"
    if p.partial_sig_v2:
        # both partials must come from the same share index: otherwise a
        # malicious member can pair its own V1 partial with a replayed
        # honest V2 partial, inflating the V2 count with duplicate
        # embedded indices and vetoing rounds (reference node.go:121-130
        # lacks this check — fixed here).
        if tbls.index_of(p.partial_sig_v2) != tbls.index_of(p.partial_sig):
            return "partial signature index mismatch"
        msg_v2 = chain_beacon.message_v2(p.round)
        if not tbls.verify_partial(pub, msg_v2, p.partial_sig_v2):
            return "invalid partial signature v2"
    if p.partial_ckpt:
        if ckpt_msg is None:
            return "unexpected checkpoint partial"
        # same-index rule as V2: a checkpoint partial must come from the
        # share that signed the beacon partial it rides with
        if tbls.index_of(p.partial_ckpt) != tbls.index_of(p.partial_sig):
            return "checkpoint partial index mismatch"
        if not tbls.verify_partial(pub, ckpt_msg, p.partial_ckpt):
            return "invalid checkpoint partial"
    return None


class Handler(ProtocolService):
    def __init__(self, client: ProtocolClient, store: Store, conf: BeaconConfig,
                 logger: KVLogger):
        if conf.group.find(conf.public.identity) is None:
            raise ValueError("beacon: keypair not included in the given group")
        self.conf = conf
        self.addr = conf.public.address()
        self._l = logger
        self.flight = conf.flight if conf.flight is not None else FLIGHT
        self.crypto = CryptoStore(conf.group, conf.share)
        store.put(genesis_beacon(self.crypto.chain_info))
        self.ticker = Ticker(conf.clock, conf.group.period, conf.group.genesis_time)
        self.chain = ChainStore(logger.named("chain"), conf, client, self.crypto,
                                store, self.ticker)
        self._client = client
        self._run_task: asyncio.Task | None = None
        self._stopped = False
        self._current_round = 0
        # self-healing state (ISSUE 12): per-peer circuit breakers keyed
        # by share index, the retry policy for outbound partial sends
        # (deadline = half the period — a partial that cannot land by
        # then is better replaced by the repair pull), rounds with a
        # live repair monitor, and the served-pull rate-cap tracker
        period = conf.group.period
        self._breakers: dict[int, PeerBreaker] = {}
        self._send_policy = RetryPolicy(
            attempts=SEND_RETRY_ATTEMPTS, base_s=max(0.05, period / 50),
            cap_s=max(0.25, period / 8), deadline_s=period / 2)
        self._repairing: set[int] = set()
        self._repair_served: dict[str, tuple[int, int]] = {}
        # remediation playbooks (ISSUE 16): short, deadline-free retry
        # budget on the injectable clock — a playbook action is already
        # cooldown-paced by the engine, so two tries is the whole budget
        self._remediate_policy = RetryPolicy(
            attempts=2, base_s=max(0.05, period / 8),
            cap_s=max(0.1, period / 4))
        # checkpoint issuance cadence (client/checkpoint.py): every
        # interval round the partial broadcast attests the head it
        # chains from; the aggregator recovers the group signature
        self._ckpt_interval = (conf.checkpoint_interval
                               if conf.checkpoint_interval is not None
                               else ckpt_mod.CKPT_INTERVAL)

    def _ckpt_msg_for(self, round_no: int, previous_sig: bytes
                      ) -> bytes | None:
        """The checkpoint message a round's partial broadcast piggybacks
        (None when round_no-1 is not a checkpoint boundary). The
        attested round is round_no-1 — ``previous_sig`` IS its recovered
        chain signature."""
        ckpt_round = round_no - 1
        if (self._ckpt_interval <= 0 or ckpt_round < 1
                or ckpt_round % self._ckpt_interval != 0):
            return None
        return ckpt_mod.checkpoint_message(
            self.crypto.chain_info.hash(), ckpt_round, previous_sig)

    def checkpoint(self):
        """Latest recovered checkpoint (client/checkpoint.py Checkpoint)
        or None — what GET /checkpoints/latest serves."""
        return self.chain.latest_checkpoint

    # ------------------------------------------------------------------ API
    async def start(self) -> None:
        """Fresh network: genesis must be in the future (node.go:164)."""
        if self.conf.clock.now() > self.conf.group.genesis_time:
            raise RuntimeError("beacon: genesis time already passed. Call catchup()")
        _, ttime = time_math.next_round(
            int(self.conf.clock.now()), self.conf.group.period,
            self.conf.group.genesis_time)
        self._l.info("beacon", "start")
        self._launch(ttime)

    async def catchup(self) -> None:
        """Rejoin a running network: sync then participate (node.go:180)."""
        n_round, ttime = time_math.next_round(
            int(self.conf.clock.now()), self.conf.group.period,
            self.conf.group.genesis_time)
        self._launch(ttime)
        spawn(self.chain.run_sync(n_round, None))

    async def transition(self, prev_group: Group) -> None:
        """New node joining at a reshare: sync the old chain up to the
        transition round, start at transition time (node.go:190)."""
        target_time = self.conf.group.transition_time
        t_round = time_math.current_round(target_time, self.conf.group.period,
                                          self.conf.group.genesis_time)
        t_time = time_math.time_of_round(self.conf.group.period,
                                         self.conf.group.genesis_time, t_round)
        if t_time != target_time:
            raise ValueError(f"transition time {target_time} not a round boundary")
        self._launch(target_time)
        peers = [nd.identity for nd in prev_group.nodes]
        spawn(self.chain.run_sync(t_round - 1, peers))

    def transition_new_group(self, new_share: Share, new_group: Group) -> None:
        """Existing member: swap share exactly after round T-1 is stored
        (node.go:206)."""
        target_time = new_group.transition_time
        t_round = time_math.current_round(target_time, self.conf.group.period,
                                          self.conf.group.genesis_time)
        target_round = t_round - 1
        self._l.debug("transition", "new_group", at_round=t_round)

        def _cb(b: Beacon) -> None:
            if b.round < target_round:
                return
            self.crypto.set_info(new_group, new_share)
            self.chain.remove_callback("transition")

        self.chain.add_callback("transition", _cb)

    def stop(self) -> None:
        if self._stopped:
            return
        self._stopped = True
        if self._run_task is not None:
            self._run_task.cancel()
        self.chain.stop()
        self.ticker.stop()
        self._l.info("beacon", "stop")

    async def stop_at(self, stop_time: int) -> None:
        now = self.conf.clock.now()
        if stop_time <= now:
            raise ValueError("can't stop in the past or present")
        await self.conf.clock.sleep(stop_time - now)
        self.stop()

    def _note_flight(self, p: PartialBeaconPacket, verdict: str,
                     source: str = "grpc", sender: str | None = None) -> None:
        """Record one partial-ingress event in the flight recorder — a
        ring append under one lock, no crypto, stays on the loop. The
        index prefix is untrusted bytes: a malformed prefix records as
        an unattributed event rather than raising."""
        try:
            idx = tbls.index_of(p.partial_sig)
        except ValueError:
            idx = None
        g = self.conf.group
        self.flight.note_partial(p.round, index=idx, source=source,
                                 verdict=verdict, now=self.conf.clock.now(),
                                 period=g.period, genesis=g.genesis_time,
                                 n=len(g), threshold=g.threshold,
                                 sender=sender)

    # ------------------------------------------------------- service surface
    async def process_partial_beacon(self, from_addr: str,
                                     p: PartialBeaconPacket) -> None:
        """Partial ingress: clock-window check, verify both partial sigs,
        hand to the aggregator (node.go:92-160)."""
        next_round, _ = time_math.next_round(
            int(self.conf.clock.now()), self.conf.group.period,
            self.conf.group.genesis_time)
        current_round = next_round - 1
        # allow one round in the future for clock drift
        if p.round > next_round:
            self._l.error("process_partial", from_addr, invalid_future_round=p.round,
                          current_round=current_round)
            self._note_flight(p, "future", sender=from_addr)
            raise TransportError(
                f"invalid round: {p.round} instead of {current_round}")
        # stale partials are rejected BEFORE paying for pairings: anything
        # outside the aggregator's window (chain_store.py) would be dropped
        # there anyway, after full verification. The reference verifies
        # first (node.go:96-130) — a free DoS amplification this avoids.
        last_round = self.chain.last().round
        from .chain_store import PARTIAL_CACHE_STORE_LIMIT

        if not (last_round < p.round <= last_round + PARTIAL_CACHE_STORE_LIMIT + 1):
            self._l.debug("process_partial", from_addr, stale_round=p.round,
                          last=last_round)
            self._note_flight(p, "stale", sender=from_addr)
            raise TransportError(
                f"stale round: {p.round} (chain at {last_round})")
        with TRACER.activate(round_no=p.round,
                             chain=self.crypto.chain_info.genesis_seed), \
                TRACER.span("partial_verify", node=self.addr,
                            sender=from_addr):
            # executor hand-off: up to four pairings per packet — run
            # them on a worker thread so concurrent partial ingress,
            # /healthz and gossip stay serviced (the gRPC gateway calls
            # this once per peer per round, right at the boundary burst)
            err = await asyncio.to_thread(
                _verify_partial_packet, self.crypto.get_pub(), p,
                self._ckpt_msg_for(p.round, p.previous_sig))
            if err is not None:
                self._l.error("process_partial", from_addr, err=err,
                              round=p.round)
                self._note_flight(p, "invalid", sender=from_addr)
                raise TransportError(err)
            if tbls.index_of(p.partial_sig) == self.crypto.index():
                # a reflected copy of our own partial: ignore
                return
            self._note_flight(p, "valid", sender=from_addr)
            self.chain.new_valid_partial(from_addr, p)

    async def request_partials(self, from_addr: str, req: PartialRequest
                               ) -> list[PartialBeaconPacket]:
        """Serve a quorum-repair PULL from the collector's per-round
        set (ISSUE 12). DoS posture: only the aggregator's live window
        is servable, responses carry only ingress-VERIFIED partials
        (bounded by the group size), and each sender gets at most
        REPAIR_SERVE_CAP pulls per round before being refused at the
        door — a refusal is an ANSWER (PeerRejectedError on the wire),
        so it never reads as unreachability."""
        last_round = self.chain.last().round
        from .chain_store import PARTIAL_CACHE_STORE_LIMIT

        # distinguishable reject reasons: the pulling side treats ONLY
        # "already stored" as the round-exists-elsewhere signal (its
        # sync leg); a server that is merely lagging must not trigger it
        if req.round <= last_round:
            raise TransportError(
                f"round {req.round} already stored (chain at "
                f"{last_round})")
        if req.round > last_round + PARTIAL_CACHE_STORE_LIMIT + 1:
            raise TransportError(
                f"round {req.round} beyond the collector window "
                f"(chain at {last_round})")
        rd, count = self._repair_served.get(from_addr, (0, 0))
        if rd != req.round:
            rd, count = req.round, 0
        if count >= REPAIR_SERVE_CAP:
            raise TransportError("repair pull rate-capped")
        if from_addr not in self._repair_served \
                and len(self._repair_served) >= 4 * len(self.conf.group):
            # address-flood bound: evict only STALE-round entries; if
            # the flood is all live-round spoofed addresses, refuse the
            # newcomer — never wipe live counts (a capped sender could
            # otherwise reset its own budget by spraying addresses)
            self._repair_served = {
                a: rc for a, rc in self._repair_served.items()
                if rc[0] == req.round}
            if len(self._repair_served) >= 4 * len(self.conf.group):
                raise TransportError("repair pull rate-capped")
        self._repair_served[from_addr] = (rd, count + 1)
        exclude = {i for i in req.have if isinstance(i, int)}
        return self.chain.partials_for(req.round, req.previous_sig,
                                       exclude)

    def sync_chain(self, from_addr: str, req: SyncRequest) -> AsyncIterator[Beacon]:
        return self.chain.sync.sync_chain(from_addr, req)

    async def chain_info(self, from_addr: str):
        return self.crypto.chain_info

    # ------------------------------------------------------------ round loop
    def _launch(self, start_time: int) -> None:
        self.ticker.start()
        self.chain.start()
        self._run_task = asyncio.ensure_future(self._run(start_time))

    async def _run(self, start_time: int) -> None:
        chan = self.ticker.channel_at(start_time)
        self._l.debug("run_round", wait_until=start_time)
        # merge ticker + catchup notifications into one event queue
        events: asyncio.Queue[tuple[str, object]] = asyncio.Queue()

        async def _pump(src: asyncio.Queue, tag: str) -> None:
            while True:
                item = await src.get()
                await events.put((tag, item))

        pumps = [
            asyncio.ensure_future(_pump(chan, "tick")),
            asyncio.ensure_future(_pump(self.chain.catchup_beacons, "catchup")),
        ]
        try:
            while True:
                kind, payload = await events.get()
                if kind == "tick":
                    current = payload
                    self._current_round = current.round
                    last = self.chain.last()
                    self._l.debug("beacon_loop", new_round=current.round,
                                  last_beacon=last.round)
                    await self._broadcast_next_partial(current.round, last)
                    if last.round + 1 < current.round:
                        # chain halted for a gap: sync with the group
                        self._l.debug("beacon_loop", run_sync_catchup=current.round)
                        spawn(self.chain.run_sync(current.round, None))
                else:
                    b = payload
                    if b.round < self._current_round:
                        # network recovering: hurry the next beacon after a
                        # catchup-period breather (node.go:256-271)
                        spawn(self._delayed_broadcast(b))
        except asyncio.CancelledError:
            self._l.debug("beacon_loop", "finished")
        finally:
            for p in pumps:
                p.cancel()

    async def _delayed_broadcast(self, upon: Beacon) -> None:
        # network recovering: the catchup-period breather before hurrying
        # the next partial (node.go:256-271)
        with TRACER.activate(round_no=upon.round + 1,
                             chain=self.crypto.chain_info.genesis_seed), \
                TRACER.span("breather", node=self.addr,
                            catchup_period=self.conf.group.catchup_period):
            await self.conf.clock.sleep(self.conf.group.catchup_period)
        if not self._stopped:
            await self._broadcast_next_partial(self._current_round, upon)

    async def _broadcast_next_partial(self, current_round: int, upon: Beacon) -> None:
        previous_sig = upon.signature
        round_no = upon.round + 1
        if current_round == upon.round:
            # we already have this round's beacon: re-broadcast it per spec
            previous_sig = upon.previous_sig
            round_no = current_round
        with TRACER.activate(round_no=round_no,
                             chain=self.crypto.chain_info.genesis_seed):
            with TRACER.span("partial", node=self.addr):
                msg = chain_beacon.message(round_no, previous_sig)
                curr_sig = self.crypto.sign_partial(msg)
                sig_v2 = self.crypto.sign_partial(
                    chain_beacon.message_v2(round_no))
                # checkpoint piggyback: at interval boundaries also
                # attest the head this round chains from
                ckpt_msg = self._ckpt_msg_for(round_no, previous_sig)
                sig_ckpt = (self.crypto.sign_partial(ckpt_msg)
                            if ckpt_msg is not None else b"")
                packet = PartialBeaconPacket(
                    round=round_no,
                    previous_sig=previous_sig,
                    partial_sig=curr_sig,
                    partial_sig_v2=sig_v2,
                    partial_ckpt=sig_ckpt,
                )
            self._l.debug("broadcast_partial", round=round_no)
            self._note_flight(packet, "valid", source="self")
            self.chain.new_valid_partial(self.addr, packet)
            # tasks created inside the activate block copy the trace
            # context, so the outbound calls carry the traceparent
            for node in self.crypto.get_group().nodes:
                if node.address() == self.addr:
                    continue
                spawn(self._send_partial(node, packet))
            # quorum repair (ISSUE 12): watch the LIVE round only —
            # catch-up/hurry rounds already ride the breather+sync
            # machinery, and one monitor per round is the requester-side
            # rate cap
            if (self.conf.repair and REPAIR_MARGIN_FRACTION > 0
                    and round_no == current_round
                    and round_no not in self._repairing):
                self._repairing.add(round_no)
                spawn(self._quorum_repair(round_no, packet))

    def _breaker(self, index: int) -> PeerBreaker:
        br = self._breakers.get(index)
        if br is None:
            # half-open probe cap: at most one probe per round period
            br = self._breakers[index] = PeerBreaker(
                index, cooldown_s=max(1.0, self.conf.group.period),
                on_state=_breaker_gauge)
        return br

    async def _send_partial(self, node, packet: PartialBeaconPacket) -> None:
        """One peer's share of the round broadcast: retried under the
        send policy, with EVERY attempt gated by and fed into the
        peer's circuit breaker — the breaker sees the same outcome
        classification as ``beacon_peer_reachable`` (note_send), so a
        partitioned peer trips it within one round's retry budget and
        subsequent rounds cost one capped probe instead of a storm.
        note_send counts per ATTEMPT (the metric's documented unit)."""
        g = self.conf.group
        br = self._breaker(node.index)

        async def _attempt() -> None:
            now = self.conf.clock.now()
            if not br.allow(now):
                raise BreakerOpenError(node.address())
            try:
                await self._client.partial_beacon(node.identity, packet)
            except PeerRejectedError:
                # the peer ANSWERED and rejected (stale window while it
                # catches up, failed verification, ...): reachable — a
                # lagging-but-alive peer must not read as a partition,
                # and must never trip the breaker
                br.record(True, self.conf.clock.now())
                self.flight.note_send(node.index, True, n=len(g),
                                      threshold=g.threshold)
                raise
            except asyncio.CancelledError:
                raise
            except TransportError:
                # transport failure = the peer is unreachable from
                # here: feeds the reachability gauge, the
                # partition-suspect count AND the breaker
                br.record(False, self.conf.clock.now())
                self.flight.note_send(node.index, False, n=len(g),
                                      threshold=g.threshold)
                raise
            except Exception:  # peer-side errors on loopback transports
                br.record(True, self.conf.clock.now())
                self.flight.note_send(node.index, True, n=len(g),
                                      threshold=g.threshold)
                raise
            br.record(True, self.conf.clock.now())
            self.flight.note_send(node.index, True, n=len(g),
                                  threshold=g.threshold)

        try:
            await retry(_attempt, op="partial", policy=self._send_policy,
                        clock=self.conf.clock,
                        retry_on=(TransportError,),
                        no_retry=(PeerRejectedError,))
        except BreakerOpenError:
            # skipped: no send happened, nothing to classify (the trip
            # itself already flipped reachability + the breaker gauge)
            return
        except PeerRejectedError as e:
            self._l.debug("beacon_round", packet.round, err=str(e),
                          to=node.address())
        except TransportError as e:
            self._l.debug("beacon_round", packet.round, err_request=str(e),
                          to=node.address())
        except asyncio.CancelledError:
            raise
        except Exception as e:
            self._l.debug("beacon_round", packet.round, err=str(e),
                          to=node.address())

    async def _quorum_repair(self, round_no: int,
                             packet: PartialBeaconPacket) -> None:
        """Quorum repair (ISSUE 12): once the live round's margin has
        shrunk below ``REPAIR_MARGIN_FRACTION`` of the period with
        valid partials still below threshold, actively close the gap —
        re-push our own partial to unreached peers and PULL missing
        partials from peers that hold them. The trigger reads the
        collector's VERIFIED set only (never flight events, whose
        rejected entries carry unverified index claims); pulls are
        single-shot per peer per round (the multi-peer sweep is the
        retry), and every pulled packet re-enters through the normal
        ingress verification."""
        g = self.conf.group
        try:
            await self.conf.clock.sleep(
                g.period * (1.0 - REPAIR_MARGIN_FRACTION))
            if self._stopped or self.chain.last().round >= round_no:
                return
            thr = g.threshold
            have = self.chain.partial_indices(round_no,
                                              packet.previous_sig)
            if len(have) >= thr:
                return
            self._l.debug("quorum_repair", round_no, have=len(have),
                          threshold=thr)
            # push side: our own partial again, to peers whose last
            # send failed (breaker-gated inside _send_partial)
            reach = self.flight.reachability()
            for node in g.nodes:
                if node.address() != self.addr \
                        and reach.get(str(node.index)) is False:
                    spawn(self._send_partial(node, packet))
            # pull side: peers whose own partial we are missing first —
            # they hold at least their own contribution
            pulled = 0
            peer_past_round = False
            order = sorted(
                (nd for nd in g.nodes if nd.address() != self.addr),
                key=lambda nd: (nd.index in have, nd.index))
            for node in order:
                if len(have) >= thr:
                    break
                if not self._breaker(node.index).allow(
                        self.conf.clock.now()):
                    continue
                req = PartialRequest(round=round_no,
                                     previous_sig=packet.previous_sig,
                                     have=tuple(sorted(have)))
                try:
                    served = await self._client.request_partials(
                        node.identity, req)
                except asyncio.CancelledError:
                    raise
                except PeerRejectedError as e:
                    # an ANSWERED refusal: the peer is reachable. Only
                    # the "already stored" refusal means the round
                    # exists elsewhere (it aggregated + flushed its
                    # collector — e.g. only OUR inbound is cut) and the
                    # sync leg below can recover it; a lagging peer's
                    # window refusal or a rate-cap must not fake that
                    self._breaker(node.index).record(
                        True, self.conf.clock.now())
                    if "already stored" in str(e):
                        peer_past_round = True
                    continue
                except TransportError:
                    self._breaker(node.index).record(
                        False, self.conf.clock.now())
                    continue
                except Exception:  # peers without the RPC, local errors
                    # something answered (or failed locally) — record
                    # the granted slot as answered so a half-open probe
                    # consumed by this pull can never wedge the breaker
                    self._breaker(node.index).record(
                        True, self.conf.clock.now())
                    continue
                self._breaker(node.index).record(
                    True, self.conf.clock.now())
                for p in served[: len(g)]:
                    try:
                        idx = tbls.index_of(p.partial_sig)
                    except ValueError:
                        continue
                    if idx in have:
                        continue
                    try:
                        await self.process_partial_beacon(
                            node.address(), p)
                    except TransportError:
                        continue  # dupes/garbage: counted by ingress
                    have.add(idx)
                    pulled += 1
            if len(have) >= thr:
                outcome = "recovered"
            elif peer_past_round:
                # the round cannot be re-collected here but it EXISTS
                # on a reachable peer: fetch the stored beacon now
                # instead of waiting for the next tick's gap detection
                # (a whole period later)
                outcome = "synced"
                peers = [nd.identity for nd in g.nodes
                         if nd.address() != self.addr]
                spawn(self.chain.run_sync(round_no, peers))
            else:
                outcome = "failed"
            self.flight.note_repair(
                round_no, outcome=outcome, pulled=pulled,
                now=self.conf.clock.now(), period=g.period,
                genesis=g.genesis_time)
            if outcome != "failed":
                self._l.info("quorum_repair", outcome, round=round_no,
                             pulled=pulled)
        except asyncio.CancelledError:
            raise
        finally:
            self._repairing.discard(round_no)

    # -------------------------------------------- remediation (ISSUE 16)
    async def remediate_sync(self) -> str:
        """The ``sync_resume`` playbook action: kick a catch-up follow
        to the wall-clock round NOW. ``Syncer.follow`` itself is the
        recovery primitive — it shuffles upstreams, fails over to the
        next on error, and every attempt resumes from the stored
        checkpoint (``store.last() + 1``), so this action never
        re-fetches verified spans. Returns the ledger detail; raises
        when the chain is still behind afterwards (the engine records
        ``outcome=failed``)."""
        g = self.conf.group
        target = time_math.current_round(self.conf.clock.now(), g.period,
                                         g.genesis_time)
        start = self.chain.last().round
        if start >= target:
            return f"no lag: head already at round {start}"
        peers = [nd.identity for nd in g.nodes
                 if nd.address() != self.addr]

        async def _attempt() -> None:
            if self.chain.sync.syncing():
                # a follow is already running and rotates upstreams on
                # its own — don't stack a second one on the same store
                return
            if not await self.chain.sync.follow(target, peers) \
                    and self.chain.last().round < target:
                raise TransportError("sync resume: no upstream served "
                                     "the missing span")

        await retry(_attempt, op="sync", policy=self._remediate_policy,
                    clock=self.conf.clock, retry_on=(TransportError,))
        head = self.chain.last().round
        if head < target:
            raise TransportError(
                f"sync resume stalled at round {head}/{target}")
        return (f"resumed from checkpoint {start}: synced "
                f"{head - start} round(s) to head {head}")

    async def remediate_breakers(self) -> str:
        """The ``quorum_pull`` playbook action for a persistent
        breaker_open incident: for each OPEN peer breaker, spend one
        half-open probe slot on a targeted quorum-repair
        ``PartialRequest`` pull — the probe doubles as recovery (a
        served pull both closes the breaker and back-fills the round).
        Pulled packets re-enter through normal ingress verification.
        Raises when every probed peer stayed silent (the fault holds —
        the engine ledgers ``failed`` and the cooldown paces the next
        probe)."""
        g = self.conf.group

        async def _pass() -> tuple[int, int, int]:
            probed = answered = pulled = 0
            last = self.chain.last()
            round_no = last.round + 1
            have = self.chain.partial_indices(round_no, last.signature)
            for node in g.nodes:
                if node.address() == self.addr:
                    continue
                br = self._breaker(node.index)
                if br.state != BREAKER_OPEN:
                    continue
                if not br.allow(self.conf.clock.now()):
                    continue  # probe slot already spent this cooldown
                probed += 1
                req = PartialRequest(round=round_no,
                                     previous_sig=last.signature,
                                     have=tuple(sorted(have)))
                try:
                    served = await self._client.request_partials(
                        node.identity, req)
                except asyncio.CancelledError:
                    raise
                except PeerRejectedError:
                    # an answered refusal closes the breaker: the peer
                    # is back even if it won't serve this round
                    br.record(True, self.conf.clock.now())
                    answered += 1
                    continue
                except TransportError:
                    br.record(False, self.conf.clock.now())
                    continue
                except Exception:  # transports without the RPC
                    br.record(True, self.conf.clock.now())
                    answered += 1
                    continue
                br.record(True, self.conf.clock.now())
                answered += 1
                for p in served[: len(g)]:
                    try:
                        idx = tbls.index_of(p.partial_sig)
                    except ValueError:
                        continue
                    if idx in have:
                        continue
                    try:
                        await self.process_partial_beacon(
                            node.address(), p)
                    except TransportError:
                        continue  # dupes/garbage: counted by ingress
                    have.add(idx)
                    pulled += 1
            if probed > 0 and answered == 0:
                raise TransportError(
                    f"all {probed} open-breaker probe(s) unanswered")
            return probed, answered, pulled

        probed, answered, pulled = await retry(
            _pass, op="repair", policy=self._remediate_policy,
            clock=self.conf.clock, retry_on=(TransportError,))
        if probed == 0:
            return "no open breakers with a free probe slot"
        return (f"probed {probed} open breaker(s): {answered} answered, "
                f"{pulled} partial(s) pulled")
