"""Per-node beacon protocol driver: the round loop.

Reference: chain/beacon/node.go (Handler :36). Each period tick: sign the
next round's V1+V2 messages with the node's share, feed the local aggregator
and broadcast to all peers; fast-path catchup when the chain lags.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass
from typing import AsyncIterator

from ...crypto import tbls
from ...key.group import Group
from ...key.keys import Node, Share
from ...net.packets import PartialBeaconPacket, SyncRequest
from ...net.transport import ProtocolClient, ProtocolService, TransportError
from ...obs.flight import FLIGHT, FlightRecorder
from ...obs.trace import TRACER
from ...utils.aio import spawn
from ...utils.clock import Clock
from ...utils.logging import KVLogger
from .. import beacon as chain_beacon
from .. import time_math
from ..beacon import Beacon
from ..store import Store, genesis_beacon
from .chain_store import ChainStore
from .crypto import CryptoStore
from .ticker import Ticker


@dataclass
class BeaconConfig:
    """chain/beacon/node.go:23 Config analogue."""

    public: Node
    share: Share
    group: Group
    clock: Clock
    # flight recorder override. Production keeps the per-process FLIGHT
    # singleton (None); in-process multi-node harnesses (chaos simulator,
    # e2e suites) inject one recorder PER NODE so a byzantine or crashed
    # node's own notes cannot pollute an honest node's telemetry — each
    # recorder then models exactly what that node's process would see.
    flight: "FlightRecorder | None" = None
    # same rule for the chain-health state (obs/health.HealthState):
    # None = the per-process HEALTH singleton; per-node instances keep a
    # minority-partition node's lag/missed view honest (the singleton's
    # head is a monotonic max across every in-process node)
    health: object | None = None


def _verify_partial_packet(pub, p: PartialBeaconPacket) -> str | None:
    """The pairing-heavy half of partial ingress, shaped for
    ``asyncio.to_thread`` (node.go:96-130). Returns the rejection
    reason, or None when the packet is fully valid."""
    msg = chain_beacon.message(p.round, p.previous_sig)
    if not tbls.verify_partial(pub, msg, p.partial_sig):
        return "invalid partial signature"
    if p.partial_sig_v2:
        # both partials must come from the same share index: otherwise a
        # malicious member can pair its own V1 partial with a replayed
        # honest V2 partial, inflating the V2 count with duplicate
        # embedded indices and vetoing rounds (reference node.go:121-130
        # lacks this check — fixed here).
        if tbls.index_of(p.partial_sig_v2) != tbls.index_of(p.partial_sig):
            return "partial signature index mismatch"
        msg_v2 = chain_beacon.message_v2(p.round)
        if not tbls.verify_partial(pub, msg_v2, p.partial_sig_v2):
            return "invalid partial signature v2"
    return None


class Handler(ProtocolService):
    def __init__(self, client: ProtocolClient, store: Store, conf: BeaconConfig,
                 logger: KVLogger):
        if conf.group.find(conf.public.identity) is None:
            raise ValueError("beacon: keypair not included in the given group")
        self.conf = conf
        self.addr = conf.public.address()
        self._l = logger
        self.flight = conf.flight if conf.flight is not None else FLIGHT
        self.crypto = CryptoStore(conf.group, conf.share)
        store.put(genesis_beacon(self.crypto.chain_info))
        self.ticker = Ticker(conf.clock, conf.group.period, conf.group.genesis_time)
        self.chain = ChainStore(logger.named("chain"), conf, client, self.crypto,
                                store, self.ticker)
        self._client = client
        self._run_task: asyncio.Task | None = None
        self._stopped = False
        self._current_round = 0

    # ------------------------------------------------------------------ API
    async def start(self) -> None:
        """Fresh network: genesis must be in the future (node.go:164)."""
        if self.conf.clock.now() > self.conf.group.genesis_time:
            raise RuntimeError("beacon: genesis time already passed. Call catchup()")
        _, ttime = time_math.next_round(
            int(self.conf.clock.now()), self.conf.group.period,
            self.conf.group.genesis_time)
        self._l.info("beacon", "start")
        self._launch(ttime)

    async def catchup(self) -> None:
        """Rejoin a running network: sync then participate (node.go:180)."""
        n_round, ttime = time_math.next_round(
            int(self.conf.clock.now()), self.conf.group.period,
            self.conf.group.genesis_time)
        self._launch(ttime)
        spawn(self.chain.run_sync(n_round, None))

    async def transition(self, prev_group: Group) -> None:
        """New node joining at a reshare: sync the old chain up to the
        transition round, start at transition time (node.go:190)."""
        target_time = self.conf.group.transition_time
        t_round = time_math.current_round(target_time, self.conf.group.period,
                                          self.conf.group.genesis_time)
        t_time = time_math.time_of_round(self.conf.group.period,
                                         self.conf.group.genesis_time, t_round)
        if t_time != target_time:
            raise ValueError(f"transition time {target_time} not a round boundary")
        self._launch(target_time)
        peers = [nd.identity for nd in prev_group.nodes]
        spawn(self.chain.run_sync(t_round - 1, peers))

    def transition_new_group(self, new_share: Share, new_group: Group) -> None:
        """Existing member: swap share exactly after round T-1 is stored
        (node.go:206)."""
        target_time = new_group.transition_time
        t_round = time_math.current_round(target_time, self.conf.group.period,
                                          self.conf.group.genesis_time)
        target_round = t_round - 1
        self._l.debug("transition", "new_group", at_round=t_round)

        def _cb(b: Beacon) -> None:
            if b.round < target_round:
                return
            self.crypto.set_info(new_group, new_share)
            self.chain.remove_callback("transition")

        self.chain.add_callback("transition", _cb)

    def stop(self) -> None:
        if self._stopped:
            return
        self._stopped = True
        if self._run_task is not None:
            self._run_task.cancel()
        self.chain.stop()
        self.ticker.stop()
        self._l.info("beacon", "stop")

    async def stop_at(self, stop_time: int) -> None:
        now = self.conf.clock.now()
        if stop_time <= now:
            raise ValueError("can't stop in the past or present")
        await self.conf.clock.sleep(stop_time - now)
        self.stop()

    def _note_flight(self, p: PartialBeaconPacket, verdict: str,
                     source: str = "grpc", sender: str | None = None) -> None:
        """Record one partial-ingress event in the flight recorder — a
        ring append under one lock, no crypto, stays on the loop. The
        index prefix is untrusted bytes: a malformed prefix records as
        an unattributed event rather than raising."""
        try:
            idx = tbls.index_of(p.partial_sig)
        except ValueError:
            idx = None
        g = self.conf.group
        self.flight.note_partial(p.round, index=idx, source=source,
                                 verdict=verdict, now=self.conf.clock.now(),
                                 period=g.period, genesis=g.genesis_time,
                                 n=len(g), threshold=g.threshold,
                                 sender=sender)

    # ------------------------------------------------------- service surface
    async def process_partial_beacon(self, from_addr: str,
                                     p: PartialBeaconPacket) -> None:
        """Partial ingress: clock-window check, verify both partial sigs,
        hand to the aggregator (node.go:92-160)."""
        next_round, _ = time_math.next_round(
            int(self.conf.clock.now()), self.conf.group.period,
            self.conf.group.genesis_time)
        current_round = next_round - 1
        # allow one round in the future for clock drift
        if p.round > next_round:
            self._l.error("process_partial", from_addr, invalid_future_round=p.round,
                          current_round=current_round)
            self._note_flight(p, "future", sender=from_addr)
            raise TransportError(
                f"invalid round: {p.round} instead of {current_round}")
        # stale partials are rejected BEFORE paying for pairings: anything
        # outside the aggregator's window (chain_store.py) would be dropped
        # there anyway, after full verification. The reference verifies
        # first (node.go:96-130) — a free DoS amplification this avoids.
        last_round = self.chain.last().round
        from .chain_store import PARTIAL_CACHE_STORE_LIMIT

        if not (last_round < p.round <= last_round + PARTIAL_CACHE_STORE_LIMIT + 1):
            self._l.debug("process_partial", from_addr, stale_round=p.round,
                          last=last_round)
            self._note_flight(p, "stale", sender=from_addr)
            raise TransportError(
                f"stale round: {p.round} (chain at {last_round})")
        with TRACER.activate(round_no=p.round,
                             chain=self.crypto.chain_info.genesis_seed), \
                TRACER.span("partial_verify", node=self.addr,
                            sender=from_addr):
            # executor hand-off: up to four pairings per packet — run
            # them on a worker thread so concurrent partial ingress,
            # /healthz and gossip stay serviced (the gRPC gateway calls
            # this once per peer per round, right at the boundary burst)
            err = await asyncio.to_thread(
                _verify_partial_packet, self.crypto.get_pub(), p)
            if err is not None:
                self._l.error("process_partial", from_addr, err=err,
                              round=p.round)
                self._note_flight(p, "invalid", sender=from_addr)
                raise TransportError(err)
            if tbls.index_of(p.partial_sig) == self.crypto.index():
                # a reflected copy of our own partial: ignore
                return
            self._note_flight(p, "valid", sender=from_addr)
            self.chain.new_valid_partial(from_addr, p)

    def sync_chain(self, from_addr: str, req: SyncRequest) -> AsyncIterator[Beacon]:
        return self.chain.sync.sync_chain(from_addr, req)

    async def chain_info(self, from_addr: str):
        return self.crypto.chain_info

    # ------------------------------------------------------------ round loop
    def _launch(self, start_time: int) -> None:
        self.ticker.start()
        self.chain.start()
        self._run_task = asyncio.ensure_future(self._run(start_time))

    async def _run(self, start_time: int) -> None:
        chan = self.ticker.channel_at(start_time)
        self._l.debug("run_round", wait_until=start_time)
        # merge ticker + catchup notifications into one event queue
        events: asyncio.Queue[tuple[str, object]] = asyncio.Queue()

        async def _pump(src: asyncio.Queue, tag: str) -> None:
            while True:
                item = await src.get()
                await events.put((tag, item))

        pumps = [
            asyncio.ensure_future(_pump(chan, "tick")),
            asyncio.ensure_future(_pump(self.chain.catchup_beacons, "catchup")),
        ]
        try:
            while True:
                kind, payload = await events.get()
                if kind == "tick":
                    current = payload
                    self._current_round = current.round
                    last = self.chain.last()
                    self._l.debug("beacon_loop", new_round=current.round,
                                  last_beacon=last.round)
                    await self._broadcast_next_partial(current.round, last)
                    if last.round + 1 < current.round:
                        # chain halted for a gap: sync with the group
                        self._l.debug("beacon_loop", run_sync_catchup=current.round)
                        spawn(self.chain.run_sync(current.round, None))
                else:
                    b = payload
                    if b.round < self._current_round:
                        # network recovering: hurry the next beacon after a
                        # catchup-period breather (node.go:256-271)
                        spawn(self._delayed_broadcast(b))
        except asyncio.CancelledError:
            self._l.debug("beacon_loop", "finished")
        finally:
            for p in pumps:
                p.cancel()

    async def _delayed_broadcast(self, upon: Beacon) -> None:
        # network recovering: the catchup-period breather before hurrying
        # the next partial (node.go:256-271)
        with TRACER.activate(round_no=upon.round + 1,
                             chain=self.crypto.chain_info.genesis_seed), \
                TRACER.span("breather", node=self.addr,
                            catchup_period=self.conf.group.catchup_period):
            await self.conf.clock.sleep(self.conf.group.catchup_period)
        if not self._stopped:
            await self._broadcast_next_partial(self._current_round, upon)

    async def _broadcast_next_partial(self, current_round: int, upon: Beacon) -> None:
        previous_sig = upon.signature
        round_no = upon.round + 1
        if current_round == upon.round:
            # we already have this round's beacon: re-broadcast it per spec
            previous_sig = upon.previous_sig
            round_no = current_round
        with TRACER.activate(round_no=round_no,
                             chain=self.crypto.chain_info.genesis_seed):
            with TRACER.span("partial", node=self.addr):
                msg = chain_beacon.message(round_no, previous_sig)
                curr_sig = self.crypto.sign_partial(msg)
                sig_v2 = self.crypto.sign_partial(
                    chain_beacon.message_v2(round_no))
                packet = PartialBeaconPacket(
                    round=round_no,
                    previous_sig=previous_sig,
                    partial_sig=curr_sig,
                    partial_sig_v2=sig_v2,
                )
            self._l.debug("broadcast_partial", round=round_no)
            self._note_flight(packet, "valid", source="self")
            self.chain.new_valid_partial(self.addr, packet)
            # tasks created inside the activate block copy the trace
            # context, so the outbound calls carry the traceparent
            for node in self.crypto.get_group().nodes:
                if node.address() == self.addr:
                    continue
                spawn(self._send_partial(node, packet))

    async def _send_partial(self, node, packet: PartialBeaconPacket) -> None:
        from ...net.transport import PeerRejectedError

        g = self.conf.group
        try:
            await self._client.partial_beacon(node.identity, packet)
        except PeerRejectedError as e:
            # the peer ANSWERED and rejected (stale window while it
            # catches up, failed verification, ...): reachable — a
            # lagging-but-alive peer must not read as a partition
            self._l.debug("beacon_round", packet.round, err=str(e),
                          to=node.address())
            self.flight.note_send(node.index, True, n=len(g),
                                  threshold=g.threshold)
            return
        except TransportError as e:
            self._l.debug("beacon_round", packet.round, err_request=str(e),
                          to=node.address())
            # transport failure = the peer is unreachable from here:
            # feeds the reachability gauge + partition-suspect count
            self.flight.note_send(node.index, False, n=len(g),
                                  threshold=g.threshold)
            return
        except asyncio.CancelledError:
            raise
        except Exception as e:  # peer-side errors on loopback transports
            self._l.debug("beacon_round", packet.round, err=str(e), to=node.address())
            self.flight.note_send(node.index, True, n=len(g),
                                  threshold=g.threshold)
            return
        self.flight.note_send(node.index, True, n=len(g),
                              threshold=g.threshold)
