"""Deterministic round <-> time mapping (reference: chain/time.go:18-65).

Round 0 is the fixed genesis beacon; round 1 happens at genesis time;
round k at genesis + (k-1)*period. Overflow-guarded like the reference.
"""

from __future__ import annotations

import math

# reference chain/time.go:8-14: stay below int64 max with headroom
_TIME_BUFFER_BITS = 36
_MAX_TIME_BUFFER = 1 << _TIME_BUFFER_BITS
_MAX_INT64 = (1 << 63) - 1
_MAX_UINT64 = (1 << 64) - 1

TIME_OF_ROUND_ERROR_VALUE = _MAX_INT64 - _MAX_TIME_BUFFER


def time_of_round(period: int, genesis: int, round_no: int) -> int:
    """Unix time at which `round_no` should be produced."""
    if round_no == 0:
        return genesis
    if period < 0:
        return TIME_OF_ROUND_ERROR_VALUE
    period_bits = math.log2(period + 1)
    if round_no >= (_MAX_UINT64 >> (int(period_bits) + 2)):
        return TIME_OF_ROUND_ERROR_VALUE
    delta = (round_no - 1) * period
    val = genesis + delta
    if val > _MAX_INT64 - _MAX_TIME_BUFFER:
        return TIME_OF_ROUND_ERROR_VALUE
    return val


def next_round(now: int, period: int, genesis: int) -> tuple[int, int]:
    """(next upcoming round, its unix time)."""
    if now < genesis:
        return 1, genesis
    from_genesis = now - genesis
    next_r = int(from_genesis // period) + 1
    next_t = genesis + next_r * period
    return next_r + 1, next_t


def current_round(now: int, period: int, genesis: int) -> int:
    """The round active at `now` (round whose scheduled time has passed)."""
    next_r, _ = next_round(now, period, genesis)
    if next_r <= 1:
        return next_r
    return next_r - 1
