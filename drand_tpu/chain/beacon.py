"""The chain data model: Beacon, message derivation, verification.

Reference: chain/beacon.go. A beacon's randomness is SHA-256 of its
signature; V1 signatures chain over the previous signature, the fork's V2
signatures cover only the round number (enabling timelock encryption).
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field

from ..crypto.curves import PointG1
from ..crypto import tbls


def round_to_bytes(round_no: int) -> bytes:
    """8-byte big-endian round encoding (chain/store.go:40)."""
    return round_no.to_bytes(8, "big")


def message(curr_round: int, prev_sig: bytes) -> bytes:
    """V1 signing message: H(prevSig || round) (chain/beacon.go:103)."""
    h = hashlib.sha256()
    h.update(prev_sig)
    h.update(round_to_bytes(curr_round))
    return h.digest()


def message_v2(curr_round: int) -> bytes:
    """V2 signing message: H(round) only — unchained (chain/beacon.go:110)."""
    return hashlib.sha256(round_to_bytes(curr_round)).digest()


def randomness_from_signature(sig: bytes) -> bytes:
    return hashlib.sha256(sig).digest()


@dataclass(slots=True)
class Beacon:
    """One round of the chain (chain/beacon.go:16). Slotted: catch-up
    walks materialize and field-scan millions of these."""

    round: int = 0
    previous_sig: bytes = b""
    signature: bytes = b""
    signature_v2: bytes = b""

    def is_v2(self) -> bool:
        return len(self.signature_v2) > 0

    def randomness(self) -> bytes:
        return randomness_from_signature(self.signature)

    def randomness_v2(self) -> bytes:
        return randomness_from_signature(self.signature_v2)

    def equal(self, other: "Beacon") -> bool:
        return (
            self.round == other.round
            and self.previous_sig == other.previous_sig
            and self.signature == other.signature
            and self.signature_v2 == other.signature_v2
        )

    # hex-JSON codec (reference uses nikkolasg/hexjson for storage)
    def marshal(self) -> bytes:
        d = {
            "round": self.round,
            "previous_sig": self.previous_sig.hex(),
            "signature": self.signature.hex(),
        }
        if self.signature_v2:
            d["signature_v2"] = self.signature_v2.hex()
        return json.dumps(d, sort_keys=True).encode()

    @staticmethod
    def unmarshal(data: bytes) -> "Beacon":
        d = json.loads(data)
        return Beacon(
            round=d["round"],
            previous_sig=bytes.fromhex(d["previous_sig"]),
            signature=bytes.fromhex(d["signature"]),
            signature_v2=bytes.fromhex(d.get("signature_v2", "")),
        )

    def __str__(self) -> str:
        return (
            f"{{round: {self.round}, sig: {self.signature[:3].hex()}, "
            f"sig2: {self.signature_v2[:3].hex()}, prev: {self.previous_sig[:3].hex()}}}"
        )


def verify_beacon(pubkey: PointG1, b: Beacon) -> bool:
    """V1 chained verification against the distributed public key
    (chain/beacon.go:87). Returns False rather than raising: beacons arrive
    from untrusted peers."""
    return tbls.verify_recovered(pubkey, message(b.round, b.previous_sig), b.signature)


def verify_beacon_v2(pubkey: PointG1, b: Beacon) -> bool:
    """V2 unchained verification (chain/beacon.go:94)."""
    return tbls.verify_recovered(pubkey, message_v2(b.round), b.signature_v2)
