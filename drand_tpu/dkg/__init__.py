"""Pedersen distributed key generation and resharing.

TPU-native replacement for the reference's kyber `dkg` package as driven by
core/drand_control.go:123 (runDKG) and :196 (runResharing): deal/response/
justification phases, QUAL selection, fast-sync, nonce binding, and the
resharing variant (OldNodes/PublicCoeffs/OldThreshold).
"""

from .packets import (  # noqa: F401
    Deal,
    DealBundle,
    Justification,
    JustificationBundle,
    Response,
    ResponseBundle,
    STATUS_APPROVAL,
    STATUS_COMPLAINT,
)
from .protocol import DKGConfig, DKGError, DistKeyShare, DKGProtocol  # noqa: F401
from .board import Board, BroadcastBoard, LocalBoard  # noqa: F401
from .phaser import Phase, TimePhaser  # noqa: F401
