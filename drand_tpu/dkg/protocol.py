"""The Pedersen DKG / resharing state machine.

Replaces kyber's `dkg.Protocol` as the reference drives it
(core/drand_control.go:123 runDKG, :196 runResharing; config fields
Suite/Longterm/NewNodes/OldNodes/PublicCoeffs/Threshold/OldThreshold/
FastSync/Nonce/Auth — :126-141, :205-246):

Phases (phaser-bounded, fast-sync short-circuits when all expected bundles
arrived):
  DEAL          every dealer commits to a secret polynomial and sends each
                receiver an ECIES-encrypted share evaluation.
  RESPONSE      every receiver verifies its deals and broadcasts a verdict
                per dealer (approval / complaint).
  JUSTIFICATION complained-against dealers reveal the disputed share in
                plaintext; everyone re-verifies against the commitments.
  FINISH        QUAL = dealers with a valid deal and no unresolved
                complaint. Fresh DKG: share_j = Σ_{i∈QUAL} f_i(j), commits
                summed pointwise. Resharing: dealers share their OLD share
                (f_i(0) = s_i, bound by PublicCoeffs), and the new share is
                the Lagrange combination Σ λ_i f_i(j) over an old-threshold
                QUAL subset — the group key is preserved.

Fresh DKG: dealers == receivers == new_nodes. Resharing: dealers are the
old group, receivers the new group; a node can be either or both. Nodes
leaving the group deal but receive no share (pri_share=None).
"""

from __future__ import annotations

import asyncio
import secrets
from dataclasses import dataclass, field

from ..crypto import batch, ecies, schnorr
from ..crypto.curves import PointG1
from ..crypto.fields import R
from ..crypto.poly import PriPoly, PriShare, PubPoly, lagrange_coefficients
from ..key.keys import Node, Pair
from ..obs.flight import FLIGHT
from ..utils.clock import Clock, SystemClock
from ..utils.logging import KVLogger, default_logger
from .board import Board
from .packets import (
    Deal,
    DealBundle,
    Justification,
    JustificationBundle,
    Response,
    ResponseBundle,
    STATUS_APPROVAL,
    STATUS_COMPLAINT,
)
from .phaser import Phase, TimePhaser


class DKGError(Exception):
    pass


# dealing: receivers per asyncio.to_thread ECIES hand-off (bounded chunks
# keep cancellation responsive and never park a whole n=1024 encrypt run
# on one executor slot)
_DEAL_ENC_CHUNK = 32
# admission: dealers per on-loop work slice between cooperative yields
_ADMIT_CHUNK = 32


@dataclass
class DKGConfig:
    longterm: Pair
    nonce: bytes
    new_nodes: list[Node]
    threshold: int
    # resharing inputs (all-or-nothing):
    old_nodes: list[Node] | None = None
    public_coeffs: list[PointG1] | None = None
    old_threshold: int = 0
    share: PriShare | None = None  # our old share (dealers in a reshare)
    # protocol knobs
    fast_sync: bool = True
    phase_timeout: float = 10.0
    clock: Clock = field(default_factory=SystemClock)
    logger: KVLogger | None = None
    seed: bytes | None = None  # deterministic dealer polynomial (tests only)

    @property
    def resharing(self) -> bool:
        return self.old_nodes is not None

    def dealers(self) -> list[Node]:
        return self.old_nodes if self.resharing else self.new_nodes


@dataclass
class DistKeyShare:
    """kyber dkg.DistKeyShare analogue (core/drand.go:166 WaitDKG output)."""

    commits: list[PointG1]
    pri_share: PriShare | None  # None for a dealer leaving the group
    qual: list[int]             # dealer indices in QUAL

    def public_key(self) -> PointG1:
        return self.commits[0]


class DKGProtocol:
    def __init__(self, conf: DKGConfig, board: Board):
        self.c = conf
        self.board = board
        self._l = (conf.logger or default_logger("dkg")).named("proto")
        dealers = conf.dealers()
        self._dealer_index = _index_of(dealers, conf.longterm)
        self._share_index = _index_of(conf.new_nodes, conf.longterm)
        if self._dealer_index is None and self._share_index is None:
            raise DKGError("longterm key neither deals nor receives")
        if conf.resharing:
            if not conf.public_coeffs or not conf.old_threshold:
                raise DKGError("resharing requires public_coeffs and old_threshold")
            if self._dealer_index is not None and conf.share is None:
                raise DKGError("resharing dealer needs its old share")
            self._old_pub = PubPoly(list(conf.public_coeffs))
        else:
            self._old_pub = None
        self._phaser = TimePhaser(conf.clock, conf.phase_timeout)
        self._sid: str | None = None  # flight-recorder session (run())
        # receiver state
        self._valid_shares: dict[int, int] = {}      # dealer_index -> f_i(me)
        self._valid_commits: dict[int, PubPoly] = {}  # dealer_index -> G_i
        self._complaints_open: dict[int, set[int]] = {}  # dealer -> share idxs
        self._approvals: dict[int, set[int]] = {}    # dealer -> approving idxs

    # ------------------------------------------------------------------ run
    async def run(self) -> DistKeyShare:
        """Execute all phases; returns the distributed key share.

        Every phase transition, bundle arrival (by issuer index) and
        the QUAL outcome land in the flight recorder's DKG timeline
        (``/debug/flight/dkg``) — indices and clock offsets only, never
        shares or key material — so a wedged DKG names the phase and
        the silent dealers instead of demanding log archaeology."""
        dealers = self.c.dealers()
        n_recv = len(self.c.new_nodes)
        sid = FLIGHT.dkg.begin(
            self.c.nonce, mode="reshare" if self.c.resharing else "dkg",
            n_dealers=len(dealers), n_receivers=n_recv,
            threshold=self.c.threshold, now=self.c.clock.now(),
            # role-qualified tag: in a reshare an old-only dealer and a
            # new receiver can share the same numeric index — their
            # in-process timelines must not collide
            tag=(f"s{self._share_index}" if self._share_index is not None
                 else f"d{self._dealer_index}"))
        self._sid = sid
        try:
            FLIGHT.dkg.note_phase(sid, "deal", now=self.c.clock.now())
            my_poly = None
            if self._dealer_index is not None:
                my_poly = self._make_poly()
                await self.board.push_deals(
                    await self._make_deal_bundle(my_poly))

            deals = await self._collect(
                self.board.deals, expect=len(dealers),
                issuer=lambda b: b.dealer_index,
                note=lambda b: FLIGHT.dkg.note_bundle(
                    sid, "deal", b.dealer_index, now=self.c.clock.now()))
            # deliberately ON-LOOP (loopblock baseline entry): deal
            # admission is batched host/device crypto, but the DKG runs
            # in a dedicated phase-clock-driven setup window — an
            # executor hand-off here suspends the node between a phase
            # deadline and its response push, and a concurrently
            # advancing clock (FakeClock tests; aggressive operator
            # timeouts) can close the response window while the thread
            # runs. Bounded: a few batched dispatches per DKG, not per
            # round — and sliced into _ADMIT_CHUNK-dealer chunks with
            # cooperative yields so a n=1024 admission cannot starve
            # the phase clock either.
            await self._process_deals(deals)

            FLIGHT.dkg.note_phase(sid, "response", now=self.c.clock.now())
            if self._share_index is not None:
                await self.board.push_responses(
                    self._make_response_bundle(dealers))
            responses = await self._collect(
                self.board.responses, expect=n_recv,
                issuer=lambda b: b.share_index,
                note=lambda b: FLIGHT.dkg.note_bundle(
                    sid, "response", b.share_index, now=self.c.clock.now()))
            for b in responses:
                self._process_response(b, dealers)

            any_complaints = any(self._complaints_open.values())
            if any_complaints:
                FLIGHT.dkg.note_phase(sid, "justification",
                                      now=self.c.clock.now())
                if self._dealer_index is not None and \
                        self._complaints_open.get(self._dealer_index):
                    await self.board.push_justifications(
                        self._make_justification_bundle(my_poly))
                complained = [d for d, s in self._complaints_open.items()
                              if s]
                justs = await self._collect(
                    self.board.justifications, expect=len(complained),
                    issuer=lambda b: b.dealer_index,
                    note=lambda b: FLIGHT.dkg.note_bundle(
                        sid, "justification", b.dealer_index,
                        now=self.c.clock.now()))
                self._process_justifications(justs)

            FLIGHT.dkg.note_phase(sid, "finish", now=self.c.clock.now())
            result = self._finish(dealers)
        except BaseException as e:
            FLIGHT.dkg.finish(sid, now=self.c.clock.now(),
                              complaints=self._complaints_open,
                              error=repr(e))
            raise
        FLIGHT.dkg.finish(sid, now=self.c.clock.now(), qual=result.qual,
                          complaints=self._complaints_open)
        return result

    # ------------------------------------------------------------- dealing
    def _make_poly(self) -> PriPoly:
        if self.c.resharing:
            # constant term MUST be our old share (bound by public_coeffs)
            coeffs = [self.c.share.value]
            for k in range(1, self.c.threshold):
                coeffs.append(_rand_scalar(self.c.seed, self._dealer_index, k))
            return PriPoly(coeffs)
        coeffs = [_rand_scalar(self.c.seed, self._dealer_index, k)
                  for k in range(self.c.threshold)]
        return PriPoly(coeffs)

    async def _make_deal_bundle(self, poly: PriPoly) -> DealBundle:
        """Dealing made O(n)-cheap for large groups: all receiver
        evaluations in ONE scalar Horner sweep (PriPoly.eval_many), the
        commitment via the fixed-base comb (PriPoly.commit), and the n
        ECIES encrypts — two 255-bit point muls each, the dominant
        dealing cost at n=1024 — handed to ``asyncio.to_thread`` in
        bounded chunks so the dealer never parks the event loop behind
        ~30 s of sequential encryption. Dealing runs BEFORE the dealer
        enters its deal-phase collect, so the thread hand-off here has
        no phase-deadline interplay (unlike admission, which stays
        on-loop — see _process_deals)."""
        nodes = self.c.new_nodes
        commit_pts, shares = await self._offload(
            lambda: (poly.commit().commits,
                     poly.eval_many([n.index for n in nodes])))
        commits = tuple(c.to_bytes() for c in commit_pts)
        deals: list[Deal] = []
        for s0 in range(0, len(nodes), _DEAL_ENC_CHUNK):
            deals.extend(await self._offload(
                self._encrypt_deals, nodes[s0:s0 + _DEAL_ENC_CHUNK],
                shares[s0:s0 + _DEAL_ENC_CHUNK]))
        bundle = DealBundle(
            dealer_index=self._dealer_index, commits=commits,
            deals=tuple(deals), session_id=self.c.nonce)
        return _signed(bundle, self.c.longterm)

    async def _offload(self, fn, *args):
        """Dealing work goes to a worker thread ONLY on the wall clock.
        A FakeClock test driver advances time whenever the loop is idle
        — a dealer parked in ``asyncio.to_thread`` registers no clock
        waiter, so the driver would burn every phase window in real
        milliseconds while the thread still deals (the crashed-dealer
        FakeClock test deadlocks exactly so). Deterministic clocks keep
        dealing inline, with a cooperative yield per chunk instead."""
        if isinstance(self.c.clock, SystemClock):
            return await asyncio.to_thread(fn, *args)
        res = fn(*args)
        await asyncio.sleep(0)
        return res

    @staticmethod
    def _encrypt_deals(nodes: list[Node], shares) -> list[Deal]:
        return [Deal(share_index=n.index,
                     encrypted_share=ecies.encrypt(
                         n.identity.key, s.value.to_bytes(32, "big")))
                for n, s in zip(nodes, shares)]

    async def _process_deals(self, bundles) -> None:
        """Admit a phase's deal bundles and check our own shares, every
        per-dealer check batched into ONE dispatch per kind per phase:

        - parse: ``batch.parse_commits`` — decompression plus one
          lockstep G1 membership chain over every pending commit point;
        - reshare binding: ``batch.reshare_bindings`` — all dealers'
          ``old_pub.eval(dealer_index)`` as one multi-point evaluation
          (device) or one RLC 2-MSM verdict (host), not n Horner walks;
        - own share: ``batch.eval_commits`` (every admitted polynomial
          at our index, one dispatch) + ``batch.share_checks`` (every
          g·s through one fixed-base-comb pass).

        The work stays ON the event loop (the loopblock baseline entry
        documents why an executor hand-off is worse here) but is sliced
        into _ADMIT_CHUNK-dealer chunks with a cooperative yield
        between slices, so a n=1024 admission cannot starve the phase
        clock (tests/test_zz_dkg_scale.py proves the response window
        still closes under FakeClock). Rejections are attributable:
        each mints dkg_bundle_rejects_total{phase,verdict} and a
        flight-recorder note instead of a silent drop."""
        from .. import metrics

        pending = []
        for b in bundles:
            if b.dealer_index in self._valid_commits:
                continue  # first bundle per dealer wins (_collect dedups)
            if len(b.commits) != self.c.threshold:
                metrics.DKG_BUNDLE_REJECTS.labels(
                    phase="deal", verdict="wrong_threshold").inc()
                self._note_reject("deal", "wrong_threshold",
                                  b.dealer_index)
                continue
            pending.append(b)

        admitted: list[tuple[DealBundle, PubPoly]] = []
        for s0 in range(0, len(pending), _ADMIT_CHUNK):
            chunk = pending[s0:s0 + _ADMIT_CHUNK]
            for b, pts in zip(chunk,
                              batch.parse_commits(
                                  [b.commits for b in chunk])):
                if pts is None:
                    metrics.DKG_BUNDLE_REJECTS.labels(
                        phase="deal", verdict="bad_point").inc()
                    self._note_reject("deal", "bad_point", b.dealer_index)
                    continue
                admitted.append((b, PubPoly(pts)))
            await asyncio.sleep(0)

        if self._old_pub is not None and admitted:
            # dealer constant terms must be their OLD public shares —
            # the key-preservation binding of a reshare, decided for
            # the whole phase in one batched dispatch
            verdicts = batch.reshare_bindings(
                self._old_pub,
                [(b.dealer_index, pub.commit()) for b, pub in admitted])
            kept = []
            for (b, pub), ok in zip(admitted, verdicts):
                if not ok:
                    metrics.DKG_BUNDLE_REJECTS.labels(
                        phase="deal", verdict="binding_mismatch").inc()
                    self._note_reject("deal", "binding_mismatch",
                                      b.dealer_index)
                    continue
                kept.append((b, pub))
            admitted = kept

        for b, pub in admitted:
            self._valid_commits[b.dealer_index] = pub
        if self._share_index is None or not admitted:
            return

        evals = batch.eval_commits([pub for _, pub in admitted],
                                   self._share_index)
        checks: list[tuple[int, int, PointG1]] = []
        for s0 in range(0, len(admitted), _ADMIT_CHUNK):
            for (b, _), ev in zip(admitted[s0:s0 + _ADMIT_CHUNK],
                                  evals[s0:s0 + _ADMIT_CHUNK]):
                val = self._decrypt_own_deal(b)
                if val is not None:
                    checks.append((b.dealer_index, val, ev))
            await asyncio.sleep(0)
        oks = batch.share_checks([(val, ev) for _, val, ev in checks])
        for (dealer, val, _), ok in zip(checks, oks):
            if ok:
                self._valid_shares[dealer] = val
            else:
                metrics.DKG_BUNDLE_REJECTS.labels(
                    phase="deal", verdict="bad_share").inc()
                self._note_reject("deal", "bad_share", dealer)

    def _decrypt_own_deal(self, b: DealBundle) -> int | None:
        """Our share value from this bundle's deal for our index, or
        None (no deal for us / malformed ciphertext — the latter leads
        to a complaint exactly as a bad share does)."""
        for d in b.deals:
            if d.share_index != self._share_index:
                continue
            try:
                plain = ecies.decrypt(self.c.longterm.key,
                                      d.encrypted_share)
                return int.from_bytes(plain, "big") % R
            except Exception:  # noqa: BLE001 — malformed ciphertext
                return None
        return None

    def _note_reject(self, phase: str, verdict: str, issuer: int) -> None:
        """Log + flight-note one rejected bundle/item. The
        dkg_bundle_rejects_total counter is minted branch-literally at
        each call site (tools/check_metrics.py KNOWN_LABEL_VALUES lints
        literal label kwargs only)."""
        self._l.warn("dkg", "bundle_reject", phase=phase, verdict=verdict,
                     issuer=issuer)
        if self._sid is not None:
            FLIGHT.dkg.note_reject(self._sid, phase, issuer, verdict,
                                   now=self.c.clock.now())

    # ----------------------------------------------------------- responses
    def _make_response_bundle(self, dealers: list[Node]) -> ResponseBundle:
        responses = []
        for node in dealers:
            ok = node.index in self._valid_shares
            responses.append(Response(
                dealer_index=node.index,
                status=STATUS_APPROVAL if ok else STATUS_COMPLAINT))
        bundle = ResponseBundle(
            share_index=self._share_index, responses=tuple(responses),
            session_id=self.c.nonce)
        return _signed(bundle, self.c.longterm)

    def _process_response(self, b: ResponseBundle, dealers: list[Node]) -> None:
        from .. import metrics

        dealer_idxs = {n.index for n in dealers}
        for r in b.responses:
            if r.dealer_index not in dealer_idxs:
                metrics.DKG_BUNDLE_REJECTS.labels(
                    phase="response", verdict="unknown_dealer").inc()
                self._note_reject("response", "unknown_dealer",
                                  b.share_index)
                continue
            if r.status == STATUS_COMPLAINT:
                self._complaints_open.setdefault(r.dealer_index, set()).add(
                    b.share_index)
            else:
                self._approvals.setdefault(r.dealer_index, set()).add(
                    b.share_index)

    # ------------------------------------------------------ justifications
    def _make_justification_bundle(self, poly: PriPoly) -> JustificationBundle:
        justs = []
        for idx in sorted(self._complaints_open.get(self._dealer_index, ())):
            justs.append(Justification(share_index=idx,
                                       share=poly.eval(idx).value))
        bundle = JustificationBundle(
            dealer_index=self._dealer_index, justifications=tuple(justs),
            session_id=self.c.nonce)
        return _signed(bundle, self.c.longterm)

    def _process_justifications(self, bundles) -> None:
        """A phase's justification bundles verified in batch: each
        complained dealer's admitted commitment polynomial evaluated at
        ALL its disputed share indices in one dispatch
        (crypto.batch.eval_poly_indices — the many-indices dual of
        eval_commits), then every revealed-share g·s check through one
        fixed-base-comb pass (crypto.batch.share_checks) — replacing
        the per-bundle 255-bit generator ladders of the old
        _process_justification. State transitions are identical to the
        sequential loop: a passing justification closes the complaint
        (and, for our own index, adopts the now-public share); a
        failing one leaves it open and mints an attributable reject."""
        from .. import metrics

        work: list[tuple[int, int, int, PointG1]] = []
        for b in bundles:
            pub = self._valid_commits.get(b.dealer_index)
            opened = self._complaints_open.get(b.dealer_index, set())
            if pub is None or not opened:
                continue
            wanted = [j for j in b.justifications
                      if j.share_index in opened]
            if not wanted:
                continue
            evs = batch.eval_poly_indices(
                pub, [j.share_index for j in wanted])
            for j, ev in zip(wanted, evs):
                work.append((b.dealer_index, j.share_index,
                             j.share % R, ev))
        if not work:
            return
        oks = batch.share_checks([(val, ev) for _, _, val, ev in work])
        for (dealer, idx, val, _), ok in zip(work, oks):
            if ok:
                self._complaints_open[dealer].discard(idx)
                if idx == self._share_index:
                    # the revealed (now public) share is still OUR share
                    self._valid_shares[dealer] = val
            else:
                metrics.DKG_BUNDLE_REJECTS.labels(
                    phase="justification", verdict="bad_share").inc()
                self._note_reject("justification", "bad_share", dealer)

    # --------------------------------------------------------------- finish
    def _finish(self, dealers: list[Node]) -> DistKeyShare:
        qual = [n.index for n in dealers
                if n.index in self._valid_commits
                and not self._complaints_open.get(n.index)]
        need = self.c.old_threshold if self.c.resharing else self.c.threshold
        if len(qual) < need:
            raise DKGError(f"QUAL too small: {len(qual)} < {need} "
                           f"(qual={qual})")
        self._l.info("dkg", "qual", members=qual)

        if not self.c.resharing:
            commits = None
            for i in qual:
                pub = self._valid_commits[i]
                commits = pub if commits is None else commits.add(pub)
            pri = None
            if self._share_index is not None:
                missing = [i for i in qual if i not in self._valid_shares]
                if missing:
                    raise DKGError(f"missing shares from QUAL dealers {missing}")
                val = sum(self._valid_shares[i] for i in qual) % R
                pri = PriShare(self._share_index, val)
            return DistKeyShare(commits=list(commits.commits), pri_share=pri,
                                qual=qual)

        # resharing: Lagrange-combine an old-threshold subset of QUAL.
        # The subset MUST be canonical across nodes (first old_threshold of
        # QUAL, which every node derives from the broadcast responses) —
        # a locally-chosen subset would yield divergent group commitments.
        subset = qual[: self.c.old_threshold]
        if self._share_index is not None:
            missing = [i for i in subset if i not in self._valid_shares]
            if missing:
                raise DKGError(
                    f"reshare: missing shares from canonical QUAL subset "
                    f"{missing}")
        lambdas = lagrange_coefficients(subset)
        # generic over the commitment point type (the structural
        # large-group harness substitutes a stand-in group)
        cls = type(self._valid_commits[subset[0]].commits[0])
        commits = []
        for k in range(self.c.threshold):
            acc = cls.infinity()
            for i in subset:
                acc = acc + self._valid_commits[i].commits[k].mul(lambdas[i])
            commits.append(acc)
        pri = None
        if self._share_index is not None:
            val = sum(self._valid_shares[i] * lambdas[i] for i in subset) % R
            pri = PriShare(self._share_index, val)
        return DistKeyShare(commits=commits, pri_share=pri, qual=qual)

    # ------------------------------------------------------------- plumbing
    async def _collect(self, queue: asyncio.Queue, expect: int, issuer,
                       note=None):
        """Drain a board queue until the phase times out — or, under
        fast-sync, as soon as `expect` distinct issuers have arrived.
        ``note`` is called with each newly-accepted bundle AS IT
        ARRIVES (the flight recorder's per-issuer arrival offsets)."""
        items: list = []
        seen: set[int] = set()
        deadline = asyncio.ensure_future(self._phaser.next_phase())
        try:
            while True:
                if self.c.fast_sync and len(seen) >= expect:
                    return items
                get = asyncio.ensure_future(queue.get())
                done, _ = await asyncio.wait(
                    {get, deadline}, return_when=asyncio.FIRST_COMPLETED)
                if get in done:
                    b = get.result()
                    if issuer(b) not in seen:
                        seen.add(issuer(b))
                        items.append(b)
                        if note is not None:
                            note(b)
                else:
                    get.cancel()
                if deadline in done:
                    return items
        finally:
            if not deadline.done():
                deadline.cancel()


def _index_of(nodes: list[Node], pair: Pair) -> int | None:
    for n in nodes:
        if n.identity.key == pair.public.key:
            return n.index
    return None


def _signed(bundle, pair: Pair):
    sig = schnorr.sign(pair.key, bundle.hash())
    return type(bundle)(**{**bundle.__dict__, "signature": sig})


def _rand_scalar(seed: bytes | None, dealer: int, k: int) -> int:
    if seed is None:
        return secrets.randbelow(R - 1) + 1
    from ..crypto.fields import fr_from_seed

    return fr_from_seed(b"dkg-coeff",
                        seed + bytes([dealer & 0xFF, k & 0xFF]))
