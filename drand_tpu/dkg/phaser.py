"""Clock-driven phase transitions for the DKG.

kyber's TimePhaser analogue as configured by the reference
(core/drand_control.go:656-665): each phase lasts `phase_timeout`; the
protocol may move earlier under fast-sync when all expected bundles have
arrived (the phaser just bounds the wait).
"""

from __future__ import annotations

import enum

from ..utils.clock import Clock


class Phase(enum.Enum):
    INIT = 0
    DEAL = 1
    RESPONSE = 2
    JUSTIFICATION = 3
    FINISH = 4


class TimePhaser:
    """Sleeps `timeout` per phase on the injectable clock."""

    def __init__(self, clock: Clock, timeout: float):
        self._clock = clock
        self.timeout = timeout

    async def next_phase(self) -> None:
        await self._clock.sleep(self.timeout)
