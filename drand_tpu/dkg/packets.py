"""DKG wire bundles: deals, responses, justifications.

Mirrors the reference's protobuf DKG packet shapes
(protobuf/crypto/dkg/dkg.proto:14-93, converted at core/convert.go:24) and
kyber's bundle semantics: every bundle carries the issuer's index, a session
nonce, and a signature over the bundle's canonical hash (verified on ingress
— core/broadcast.go:53 `dkg.VerifyPacketSignature` analogue).

Canonical encoding: length-prefixed concatenation; hashes are blake2b-256.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

from ..crypto.curves import PointG1

STATUS_COMPLAINT = 0
STATUS_APPROVAL = 1


def _u16(v: int) -> bytes:
    return v.to_bytes(2, "big")


def _u32(v: int) -> bytes:
    return v.to_bytes(4, "big")


def _blob(b: bytes) -> bytes:
    return _u32(len(b)) + b


@dataclass(frozen=True)
class Deal:
    """Encrypted share evaluation for one receiver (dkg.proto Deal)."""

    share_index: int     # receiver's index in the NEW group
    encrypted_share: bytes  # ECIES under the receiver's longterm key

    def encode(self) -> bytes:
        return _u16(self.share_index) + _blob(self.encrypted_share)


@dataclass(frozen=True)
class DealBundle:
    """All of one dealer's deals plus its polynomial commitments."""

    dealer_index: int           # index in the DEALER set (old group if reshare)
    commits: tuple[bytes, ...]  # compressed G1 commitments, degree t-1
    deals: tuple[Deal, ...]
    session_id: bytes           # the DKG nonce
    signature: bytes = b""      # schnorr by the dealer's longterm key

    def hash(self) -> bytes:
        h = hashlib.blake2b(digest_size=32)
        h.update(b"dkg-deal")
        h.update(_u16(self.dealer_index))
        for c in self.commits:
            h.update(c)
        for d in self.deals:
            h.update(d.encode())
        h.update(_blob(self.session_id))
        return h.digest()

    def commit_points(self) -> list[PointG1]:
        return [PointG1.from_bytes(c) for c in self.commits]


@dataclass(frozen=True)
class Response:
    """Per-dealer verdict from one share receiver."""

    dealer_index: int
    status: int  # STATUS_APPROVAL / STATUS_COMPLAINT

    def encode(self) -> bytes:
        return _u16(self.dealer_index) + bytes([self.status])


@dataclass(frozen=True)
class ResponseBundle:
    share_index: int  # responder's index in the NEW group
    responses: tuple[Response, ...]
    session_id: bytes
    signature: bytes = b""

    def hash(self) -> bytes:
        h = hashlib.blake2b(digest_size=32)
        h.update(b"dkg-response")
        h.update(_u16(self.share_index))
        for r in self.responses:
            h.update(r.encode())
        h.update(_blob(self.session_id))
        return h.digest()


@dataclass(frozen=True)
class Justification:
    """Plaintext share revealed in answer to a complaint."""

    share_index: int
    share: int  # Fr scalar, public once revealed

    def encode(self) -> bytes:
        return _u16(self.share_index) + self.share.to_bytes(32, "big")


@dataclass(frozen=True)
class JustificationBundle:
    dealer_index: int
    justifications: tuple[Justification, ...]
    session_id: bytes
    signature: bytes = b""

    def hash(self) -> bytes:
        h = hashlib.blake2b(digest_size=32)
        h.update(b"dkg-justification")
        h.update(_u16(self.dealer_index))
        for j in self.justifications:
            h.update(j.encode())
        h.update(_blob(self.session_id))
        return h.digest()


DKGPacket = DealBundle | ResponseBundle | JustificationBundle
