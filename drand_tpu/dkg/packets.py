"""DKG wire bundles: deals, responses, justifications.

Mirrors the reference's protobuf DKG packet shapes
(protobuf/crypto/dkg/dkg.proto:14-93, converted at core/convert.go:24) and
kyber's bundle semantics: every bundle carries the issuer's index, a session
nonce, and a signature over the bundle's canonical hash (verified on ingress
— core/broadcast.go:98 `BroadcastDKG` -> core/drand_control.go:139
`dkg.VerifyPacketSignature` analogue).

Canonical hashes follow KYBER'S layout (drand/kyber share/dkg/structs.go
``DealBundle.Hash``/``ResponseBundle.Hash``/``JustificationBundle.Hash``)
so a drand-tpu node's DKG signatures verify under a reference node's
`VerifyPacketSignature` and vice versa: SHA-256; uint32 big-endian
indices; entries sorted by their index; deal ciphertexts / compressed
commitment points / 32-byte big-endian scalars written raw (no length
prefixes, no domain tags); session id written last. The kyber sources
are not present in this image, so the layout is reproduced from the
documented structs.go implementation and pinned by golden vectors in
tests/test_dkg_packets.py — any byte-order fix needed against a live
kyber peer is localized to the three hash() methods below.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

from ..crypto.curves import PointG1

STATUS_COMPLAINT = 0
STATUS_APPROVAL = 1


def _u16(v: int) -> bytes:
    return v.to_bytes(2, "big")


def _u32(v: int) -> bytes:
    return v.to_bytes(4, "big")


def _blob(b: bytes) -> bytes:
    return _u32(len(b)) + b


@dataclass(frozen=True)
class Deal:
    """Encrypted share evaluation for one receiver (dkg.proto Deal)."""

    share_index: int     # receiver's index in the NEW group
    encrypted_share: bytes  # ECIES under the receiver's longterm key

    def encode(self) -> bytes:
        return _u16(self.share_index) + _blob(self.encrypted_share)


@dataclass(frozen=True)
class DealBundle:
    """All of one dealer's deals plus its polynomial commitments."""

    dealer_index: int           # index in the DEALER set (old group if reshare)
    commits: tuple[bytes, ...]  # compressed G1 commitments, degree t-1
    deals: tuple[Deal, ...]
    session_id: bytes           # the DKG nonce
    signature: bytes = b""      # schnorr by the dealer's longterm key

    def hash(self) -> bytes:
        # kyber structs.go DealBundle.Hash: sha256(dealer_index_u32be ||
        # (share_index_u32be || ciphertext)* sorted by share index ||
        # compressed commit points || session_id)
        h = hashlib.sha256()
        h.update(_u32(self.dealer_index))
        for d in sorted(self.deals, key=lambda d: d.share_index):
            h.update(_u32(d.share_index))
            h.update(d.encrypted_share)
        for c in self.commits:
            h.update(c)
        h.update(self.session_id)
        return h.digest()

    def commit_points(self) -> list[PointG1]:
        return [PointG1.from_bytes(c) for c in self.commits]


@dataclass(frozen=True)
class Response:
    """Per-dealer verdict from one share receiver."""

    dealer_index: int
    status: int  # STATUS_APPROVAL / STATUS_COMPLAINT

    def encode(self) -> bytes:
        return _u16(self.dealer_index) + bytes([self.status])


@dataclass(frozen=True)
class ResponseBundle:
    share_index: int  # responder's index in the NEW group
    responses: tuple[Response, ...]
    session_id: bytes
    signature: bytes = b""

    def hash(self) -> bytes:
        # kyber structs.go ResponseBundle.Hash: sha256(share_index_u32be
        # || (dealer_index_u32be || status_byte)* sorted by dealer index
        # || session_id)
        h = hashlib.sha256()
        h.update(_u32(self.share_index))
        for r in sorted(self.responses, key=lambda r: r.dealer_index):
            h.update(_u32(r.dealer_index))
            h.update(bytes([1 if r.status == STATUS_APPROVAL else 0]))
        h.update(self.session_id)
        return h.digest()


@dataclass(frozen=True)
class Justification:
    """Plaintext share revealed in answer to a complaint."""

    share_index: int
    share: int  # Fr scalar, public once revealed

    def encode(self) -> bytes:
        return _u16(self.share_index) + self.share.to_bytes(32, "big")


@dataclass(frozen=True)
class JustificationBundle:
    dealer_index: int
    justifications: tuple[Justification, ...]
    session_id: bytes
    signature: bytes = b""

    def hash(self) -> bytes:
        # kyber structs.go JustificationBundle.Hash: sha256(
        # dealer_index_u32be || (share_index_u32be || scalar_32be)*
        # sorted by share index || session_id)
        h = hashlib.sha256()
        h.update(_u32(self.dealer_index))
        for j in sorted(self.justifications, key=lambda j: j.share_index):
            h.update(_u32(j.share_index))
            h.update(j.share.to_bytes(32, "big"))
        h.update(self.session_id)
        return h.digest()


DKGPacket = DealBundle | ResponseBundle | JustificationBundle
