"""DKG boards: where bundles are pushed and received.

``Board`` is the kyber ``dkg.Board`` analogue; ``BroadcastBoard`` is the
reference's best-effort rebroadcast gossip (core/broadcast.go:38): every
accepted bundle is verified (issuer signature + session nonce), deduped by
hash, delivered locally, and re-sent to every peer — so a bundle reaches
everyone even if its origin can only reach a subset of the group (the
reason the reference gossips DKG packets at all).
"""

from __future__ import annotations

import asyncio

from ..crypto import schnorr
from ..key.keys import Node
from ..utils.aio import spawn
from ..utils.logging import KVLogger
from .packets import DealBundle, JustificationBundle, ResponseBundle


class Board:
    """Local queues + an outbound hook. Protocol consumes the queues."""

    def __init__(self):
        self.deals: asyncio.Queue[DealBundle] = asyncio.Queue()
        self.responses: asyncio.Queue[ResponseBundle] = asyncio.Queue()
        self.justifications: asyncio.Queue[JustificationBundle] = asyncio.Queue()

    async def push_deals(self, bundle: DealBundle) -> None:
        raise NotImplementedError

    async def push_responses(self, bundle: ResponseBundle) -> None:
        raise NotImplementedError

    async def push_justifications(self, bundle: JustificationBundle) -> None:
        raise NotImplementedError


class LocalBoard(Board):
    """Single-process fan-out for tests: a shared registry of boards."""

    def __init__(self, registry: list["LocalBoard"] | None = None):
        super().__init__()
        self._registry = registry if registry is not None else [self]

    @staticmethod
    def make_group(n: int) -> list["LocalBoard"]:
        registry: list[LocalBoard] = []
        for _ in range(n):
            registry.append(LocalBoard(registry))
        return registry

    async def _fan(self, kind: str, bundle) -> None:
        for b in self._registry:
            getattr(b, kind).put_nowait(bundle)

    async def push_deals(self, bundle: DealBundle) -> None:
        await self._fan("deals", bundle)

    async def push_responses(self, bundle: ResponseBundle) -> None:
        await self._fan("responses", bundle)

    async def push_justifications(self, bundle: JustificationBundle) -> None:
        await self._fan("justifications", bundle)


class BroadcastBoard(Board):
    """Gossip board over the node->node transport (core/broadcast.go).

    Outbound: sign is the caller's job (the protocol signs bundles); push
    delivers locally then sends to every peer in parallel.
    Inbound (`receive` — wired to the transport's broadcast_dkg service):
    verify signature against the issuer's longterm key, drop duplicates and
    wrong-session bundles, deliver locally, rebroadcast to all peers.
    """

    def __init__(self, client, own_addr: str, dealers: list[Node],
                 receivers: list[Node], nonce: bytes, logger: KVLogger):
        super().__init__()
        self._client = client
        self._addr = own_addr
        self._dealers = dealers
        self._receivers = receivers
        self._nonce = nonce
        self._l = logger
        self._seen: set[bytes] = set()
        self._peers = {n.address(): n for n in dealers + receivers
                       if n.address() != own_addr}

    # ---------------------------------------------------------------- out
    async def push_deals(self, bundle: DealBundle) -> None:
        await self._accept(bundle, rebroadcast=True)

    async def push_responses(self, bundle: ResponseBundle) -> None:
        await self._accept(bundle, rebroadcast=True)

    async def push_justifications(self, bundle: JustificationBundle) -> None:
        await self._accept(bundle, rebroadcast=True)

    # ----------------------------------------------------------------- in
    async def receive(self, from_addr: str, bundle) -> None:
        """Transport ingress (ProtocolService.broadcast_dkg)."""
        await self._accept(bundle, rebroadcast=True)

    def _issuer(self, bundle) -> Node | None:
        if isinstance(bundle, (DealBundle, JustificationBundle)):
            nodes, idx = self._dealers, bundle.dealer_index
        else:
            nodes, idx = self._receivers, bundle.share_index
        for n in nodes:
            if n.index == idx:
                return n
        return None

    def _verify(self, bundle) -> bool:
        if bundle.session_id != self._nonce:
            return False
        issuer = self._issuer(bundle)
        if issuer is None:
            return False
        return schnorr.verify(issuer.identity.key, bundle.hash(),
                              bundle.signature)

    async def _accept(self, bundle, rebroadcast: bool) -> None:
        key = bundle.hash() + bundle.signature[:16]
        if key in self._seen:
            return
        if not self._verify(bundle):
            self._l.debug("dkg_board", "invalid_bundle",
                          kind=type(bundle).__name__)
            from .. import metrics

            # phase is branch-literal per bundle type (the
            # KNOWN_LABEL_VALUES lint checks literal label kwargs)
            if isinstance(bundle, DealBundle):
                metrics.DKG_BUNDLE_REJECTS.labels(
                    phase="deal", verdict="bad_signature").inc()
            elif isinstance(bundle, ResponseBundle):
                metrics.DKG_BUNDLE_REJECTS.labels(
                    phase="response", verdict="bad_signature").inc()
            else:
                metrics.DKG_BUNDLE_REJECTS.labels(
                    phase="justification", verdict="bad_signature").inc()
            return
        self._seen.add(key)
        from .. import metrics

        metrics.DKG_BUNDLES.labels(kind=type(bundle).__name__).inc()
        if isinstance(bundle, DealBundle):
            self.deals.put_nowait(bundle)
        elif isinstance(bundle, ResponseBundle):
            self.responses.put_nowait(bundle)
        else:
            self.justifications.put_nowait(bundle)
        if rebroadcast:
            for peer in self._peers.values():
                spawn(self._send(peer, bundle))

    async def _send(self, peer: Node, bundle) -> None:
        try:
            await self._client.broadcast_dkg(peer.identity, bundle)
        except asyncio.CancelledError:
            raise
        except Exception as e:  # best-effort gossip (broadcast.go:143)
            self._l.debug("dkg_board", "send_failed", to=peer.address(),
                          err=str(e))
