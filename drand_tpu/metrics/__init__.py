"""Prometheus metrics.

Reference: metrics/metrics.go:17-146 — beacon discrepancy latency, last
round gauges, dial failures, HTTP counters — and the store decorator that
feeds them (chain/beacon/store.go:57 discrepancyStore). Exposed on the
public REST server's /metrics route (the reference serves a dedicated
metrics port; one port fewer here, same scrape surface).
"""

from __future__ import annotations

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
)

REGISTRY = CollectorRegistry()

# chain/beacon metrics (metrics.go:41-50)
BEACON_DISCREPANCY_LATENCY = Gauge(
    "beacon_discrepancy_latency_ms",
    "Milliseconds between the expected round time and the beacon being stored",
    registry=REGISTRY)
LAST_BEACON_ROUND = Gauge(
    "last_beacon_round", "Last aggregated and stored beacon round",
    registry=REGISTRY)

# network health (metrics.go:60-75)
DIAL_FAILURES = Counter(
    "outgoing_connection_failures",
    "Failed outbound node-to-node calls", ["peer"], registry=REGISTRY)
DKG_BUNDLES = Counter(
    "dkg_bundles_received", "DKG bundles accepted by the broadcast board",
    ["kind"], registry=REGISTRY)

# public API (metrics.go:90-120)
HTTP_REQUESTS = Counter(
    "http_api_requests", "Public REST API calls", ["path", "code"],
    registry=REGISTRY)
HTTP_LATENCY = Histogram(
    "http_api_latency_seconds", "Public REST API latency", ["path"],
    registry=REGISTRY)

# crypto engine
ENGINE_BATCHES = Counter(
    "engine_device_batches", "Batched device crypto calls", ["op"],
    registry=REGISTRY)
ENGINE_FALLBACKS = Counter(
    "engine_device_fallbacks", "Device-engine failures that fell back to host",
    registry=REGISTRY)


def render() -> bytes:
    """The /metrics payload."""
    return generate_latest(REGISTRY)
