"""Prometheus metrics — the reference catalogue, four registries.

Reference: metrics/metrics.go:17-146 defines four registries
(PrivateMetrics :17, HTTPMetrics :20, GroupMetrics :22, ClientMetrics
:24) and the catalogue below; client/http/metric.go:14 adds the client
heartbeat set. The store decorator feeding the beacon gauges is
chain/beacon/store.go:57 (discrepancyStore → our DiscrepancyStore).

Catalogue parity (reference name → here):
  api_call_counter               → api_call_counter           [private]
  dial_failures                  → outgoing_connection_failures [group]
  group_connections              → group_connections          [group]
  beacon_discrepancy_latency     → beacon_discrepancy_latency_ms [group]
  last_beacon_round              → last_beacon_round          [group]
  http_call_counter              → http_api_requests          [http]
  http_response_duration         → http_api_latency_seconds   [http]
  http_in_flight                 → http_in_flight             [http]
  client_watch_latency           → client_watch_latency       [client]
  client_http_heartbeat_success  → client_http_heartbeat_success [client]
  client_http_heartbeat_failure  → client_http_heartbeat_failure [client]
  client_http_heartbeat_latency  → client_http_heartbeat_latency [client]
  client_in_flight               → client_in_flight           [client]
  client_api_requests_total      → client_api_requests_total  [client]
  client_request_duration_seconds→ client_request_duration_seconds [client]
  (client_dns/tls_duration_seconds are Go httptrace hooks with no
   asyncio equivalent — intentionally absent)
Additions beyond the reference (the TPU engine + round tracing):
  engine_device_batches, engine_device_fallbacks, dkg_bundles_received
  beacon_stage_seconds{stage}          [group]   per-stage round latency,
      fed by the obs tracing spans (obs/trace.py) — partial, collect,
      recover, verify, store, sync_verify, gossip_validate, breather
  engine_op_seconds{op,path,batch}     [private] per-op device-vs-host
      latency, batch-size-bucketed (crypto/batch.py dispatch wrappers);
      path="host_rlc" marks the randomized-linear-combination batch
      verifier (crypto/batch_verify.py — one 2-pairing product check
      for a whole span instead of one per item); path="wire_rlc" the
      device wire-pipeline RLC tier (ops/engine.py verify_wire_rlc —
      device hash-to-curve + in-graph lane-MSM, 2 Miller pairs per
      catch-up span with no host hashing)
  hash_to_g2_cache_requests{result}    [private] hash-to-G2 memo
      hit/miss counters (crypto/hash_to_curve.py per-round keyed LRU)
Timelock serving tier (drand_tpu/timelock, ISSUE 9):
  timelock_gt_cache_requests{result}   [private] encrypt-side per-round
      e(pub, H2(round)) base memo hit/miss (crypto/timelock.py)
  timelock_pending_ciphertexts         [private] vault backlog waiting
      for a future round's V2 signature
  timelock_ciphertexts_total{result}   [private] vault lifecycle counter
      (submitted | opened | rejected); round-open latency rides
      engine_op_seconds{op="timelock", path=device|host_shared}
  timelock_open_dispatches_total       [private] chunked boundary-open
      dispatches — ceil(K/DRAND_TPU_TIMELOCK_OPEN_CHUNK) per round of K
      pending ciphertexts (ISSUE 20 bounded opens)
  timelock_sweep_shards                [private] token-range shards the
      boundary sweep partitions over (1 = sole sweeper, K = one of a
      relay --workers K group each opening a disjoint shard)
  vault_reads_total{backend}           [private] vault record reads by
      backend (sqlite | segment) — segment-vault migration
      observability (ISSUE 20)
Chain-health / SLO set (obs/health.py, ISSUE 6 — fed by the
DiscrepancyStore on every stored beacon and re-evaluated by /healthz):
  beacon_round_lateness_seconds        [group]   actual emit time vs the
      scheduled round boundary, per stored round
  chain_head_round                     [group]   last stored round
  chain_head_lag_rounds                [group]   expected round - head
  beacon_rounds_missed_total           [group]   rounds whose whole
      period passed with no stored beacon (counted once per round)
  beacon_slo_late_fraction             [group]   sliding-window fraction
      of rounds late by more than period/2
  chain_sync_rounds_per_second         [group]   follow_chain catch-up
      throughput (0 when no follow is running)
  chain_sync_eta_seconds               [group]   follow_chain ETA to the
      target round (-1 = unbounded follow, 0 = idle/done)
Threshold flight recorder (obs/flight.py, ISSUE 10 — fed by partial
ingress, the aggregator, gossip validation and the DKG protocol):
  beacon_quorum_margin_seconds         [group]   period minus the
      arrival offset of the t-th valid partial — the distance-to-
      missed-round early-warning SLI (negative = quorum after the
      round's whole period had already passed)
  beacon_partial_arrival_seconds{source} [group] valid partial/beacon
      arrival offset from the round boundary by ingress source
      (grpc | gossip | self)
  beacon_partial_events_total{index,event} [group] per-share-index
      contribution/lateness/invalid counters (event: contributed |
      late | invalid; late = arrived more than period/2 after the
      boundary; index cardinality is bounded by the group size)
  beacon_contribution_gap              [group]   group size minus the
      distinct valid contributors of the last stored round
  dkg_phase_seconds{phase}             [group]   DKG/reshare phase
      durations (deal | response | justification | finish)
Fault-detection set (obs/flight.py reachability + obs/health.py stall
detection, ISSUE 11 — the chaos simulator's oracle for faults the
ISSUE-6/10 SLIs could not see; fed by the handler's outbound partial
sends and the /healthz pull path):
  beacon_peer_reachable{index}         [group]   1 while the last
      outbound send to that group member succeeded, 0 after a failure
      (index cardinality bounded by the group size, like
      beacon_partial_events_total)
  beacon_partition_suspects            [group]   count of group peers
      currently unreachable from this node — when it reaches
      n - threshold the node itself can no longer see a quorum
  beacon_peer_sends_total{index,outcome} [group] outbound
      partial-broadcast attempts per peer by outcome (ok | failed)
  beacon_ingress_rejects_total{source,verdict} [group] partial/beacon
      ingress rejections by ingress source and verdict (invalid |
      stale | future | duplicate) — the flood/abuse signal the
      per-peer counters cannot carry (window rejects and garbage
      prefixes are deliberately never attributed to a peer)
  chain_sync_stalled                   [group]   1 while the chain lags
      beyond the readiness bound with no catch-up making progress
      (pull-model: re-evaluated by /healthz probes and scrapes)
Self-healing set (utils/retry.py policy, net/transport.py breakers,
handler quorum repair, http_server stale serving — ISSUE 12: the
active-recovery tier the ISSUE-11 fault oracle proved was missing):
  net_retry_attempts_total{op,outcome} [group]   every retry-policy
      attempt by call-site op (partial | sync | repair | control |
      gossip | timelock | watch) and outcome (ok | retry | exhausted |
      rejected — rejected = classified non-retryable, e.g. the peer
      answered with a reject)
  beacon_peer_breaker_state{index}     [group]   per-peer circuit
      breaker state (0 = closed, 1 = half-open, 2 = open); fed by the
      same outbound-send outcomes as beacon_peer_reachable, index
      cardinality bounded by the group size
  beacon_partial_repairs_total{outcome} [group]  quorum-repair
      operations by outcome (recovered = the pull reached threshold
      inside the round's period; synced = peers already stored the
      round, fetched via sync instead; failed = still below threshold)
  relay_stale_served_total             [http]    /public/latest
      responses served from the last-known beacon with the
      X-Drand-Stale header because the upstream was unreachable
Incident engine (obs/incident.py + obs/timeseries.py, ISSUE 15 — the
anomaly rules evaluated on every SLI time-series sample, minting
incidents with frozen forensic bundles):
  incidents_total{rule,severity}       [group]   incidents minted by
      the detector, by rule (missed_round | readiness_flip |
      breaker_open | reachability_drop | sync_stall |
      margin_degraded | ingress_flood | shed_surge | custom) and
      severity (critical | major | warning) — one per SUSTAINED fault
      (re-fires extend the open incident, cooldown suppresses flaps)
  incident_active                      [group]   currently open
      incidents (their rules still firing or not yet cleared)
Auto-remediation (obs/remediate.py, ISSUE 16 — the closed loop:
incidents drive guardrailed playbooks, every attempt audited):
  remediation_actions_total{playbook,outcome} [group]  remediation-
      ledger entries by playbook (sync_resume | quorum_pull |
      partition_posture | respawn_worker | reshare_recommend) and
      outcome (ok | failed | dry_run | budget_exhausted | reverted) —
      dry_run is the default posture until DRAND_TPU_REMEDIATE=live
  remediation_active{playbook}         [group]   playbooks holding an
      action in flight or a sticky posture (partition_posture stays 1
      until its incident closes and the revert runs)
  remediation_mttr_seconds             [group]   open-to-close of
      incidents the engine acted on — MTTR as a measured SLI
Edge fan-out set (http_server/fanout.py hub + chain/segments.py,
ISSUE 14 — the push tier on /public/latest and the packed segment
chain store behind it):
  relay_watchers                       [http]    currently connected
      /public/latest stream watchers (SSE + NDJSON) on this worker
  relay_wakeups_total{proto}           [http]    hub publishes that woke
      at least one watcher of that protocol (sse | ndjson) — ≤1 per
      round per protocol per worker, NOT O(watchers); the push-tier
      cost model in one counter
  relay_shed_total{reason}             [http]    watcher connections
      refused or dropped by the load shedder (watcher_cap = 429 at the
      connection cap with Retry-After on the next round boundary;
      slow_consumer = bounded send queue overflowed, the stream was
      disconnected rather than buffered unboundedly; timelock_slow =
      the same queue overflow on the /timelock open-notify leg)
  timelock_watchers                    [http]    currently connected
      /timelock open-notify stream watchers (SSE + NDJSON) on this
      worker (ISSUE 20)
  timelock_notify_total{event}         [http]    decided-ciphertext
      events pushed on the /timelock leg (opened | rejected), once per
      ciphertext regardless of watcher count
  relay_boundary_delivery_seconds      [http]    scheduled round
      boundary to hub publish on this worker — the server half of
      boundary-to-delivery latency (the bench measures the client half)
  chain_store_reads_total{backend}     [group]   beacon reads served by
      the chain store by backend (sqlite | segment) — the migration
      observability for the packed segment format
Million-client catch-up (client/verify.py + client/checkpoint.py,
ISSUE 17 — RLC span verification, pipelined fetch/verify and signed
checkpoint trust):
  client_catchup_rounds_total          [client]  rounds verified by the
      VerifyingClient catch-up walk (every beacon that passed an RLC
      span check or per-item fallback)
  client_catchup_chunk_rounds          [client]  current adaptive
      catch-up chunk size — grows geometrically toward
      DRAND_TPU_CATCHUP_CHUNK_MAX while spans verify clean, halves on
      a corrupt span
  checkpoint_bootstraps_total{result}  [client]  checkpoint trust
      bootstraps by result (ok = verified + spot-checked, trust jumped
      to the checkpoint round; rejected = the signed checkpoint failed
      verification and the client fell back to the full walk)
  checkpoint_issued_total              [group]   checkpoints recovered
      by the aggregator from piggybacked threshold partials
  checkpoint_round                     [group]   round of the latest
      recovered checkpoint served at /checkpoints/latest
Engine introspection (ISSUE 6):
  engine_compile_seconds{op}           [private] FIRST dispatch of each
      (op, path, batch-bucket) device shape — the jit compile +
      first-run cost, split out so steady-state engine_op_seconds
      percentiles stay clean (crypto/batch.py _timed)
  otlp_export_rounds_total{sink}       [private] round traces exported
      by the OTLP exporter, by sink (http|spool|dropped)

Everything is exposed on /metrics (render() gathers all four registries
— the reference's handler chains its gatherers the same way,
metrics.go:229) and relayed per peer via /peer/{addr}/metrics.
"""

from __future__ import annotations

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
)

# Four registries (metrics.go:17-24). REGISTRY keeps its old name as the
# private/default one for back-compat with existing callers.
REGISTRY = CollectorRegistry()          # PrivateMetrics
HTTP_REGISTRY = CollectorRegistry()     # HTTPMetrics
GROUP_REGISTRY = CollectorRegistry()    # GroupMetrics
CLIENT_REGISTRY = CollectorRegistry()   # ClientMetrics

# ---- private (node-to-node API) -------------------------------------------
API_CALLS = Counter(
    "api_call_counter", "Private gRPC API calls", ["method"],
    registry=REGISTRY)

# ---- group (chain + mesh health) ------------------------------------------
BEACON_DISCREPANCY_LATENCY = Gauge(
    "beacon_discrepancy_latency_ms",
    "Milliseconds between the expected round time and the beacon being stored",
    registry=GROUP_REGISTRY)
LAST_BEACON_ROUND = Gauge(
    "last_beacon_round", "Last aggregated and stored beacon round",
    registry=GROUP_REGISTRY)
DIAL_FAILURES = Counter(
    "outgoing_connection_failures",
    "Failed outbound node-to-node calls", ["peer"],
    registry=GROUP_REGISTRY)
GROUP_CONNECTIONS = Gauge(
    "group_connections", "Open outbound connections to group members",
    registry=GROUP_REGISTRY)
DKG_BUNDLES = Counter(
    "dkg_bundles_received", "DKG bundles accepted by the broadcast board",
    ["kind"], registry=GROUP_REGISTRY)

DKG_BUNDLE_REJECTS = Counter(
    "dkg_bundle_rejects_total",
    "DKG bundles/items rejected during verification, by phase and "
    "verdict (bad_signature|wrong_threshold|bad_point|binding_mismatch|"
    "bad_share|unknown_dealer) — a misbehaving dealer in a large-group "
    "ceremony is attributable, not silently dropped",
    ["phase", "verdict"], registry=GROUP_REGISTRY)

# ---- http (public REST server) --------------------------------------------
HTTP_REQUESTS = Counter(
    "http_api_requests", "Public REST API calls", ["path", "code"],
    registry=HTTP_REGISTRY)
HTTP_LATENCY = Histogram(
    "http_api_latency_seconds", "Public REST API latency", ["path"],
    registry=HTTP_REGISTRY)
HTTP_IN_FLIGHT = Gauge(
    "http_in_flight", "In-flight public REST requests",
    registry=HTTP_REGISTRY)

# ---- client (the consuming side: watches, heartbeats) ---------------------
CLIENT_WATCH_LATENCY = Gauge(
    "client_watch_latency",
    "Duration between time round received and time round expected (ms)",
    registry=CLIENT_REGISTRY)
CLIENT_HEARTBEAT_SUCCESS = Counter(
    "client_http_heartbeat_success", "Successful client heartbeats",
    ["url"], registry=CLIENT_REGISTRY)
CLIENT_HEARTBEAT_FAILURE = Counter(
    "client_http_heartbeat_failure", "Failed client heartbeats",
    ["url"], registry=CLIENT_REGISTRY)
CLIENT_HEARTBEAT_LATENCY = Gauge(
    "client_http_heartbeat_latency", "Last client heartbeat latency (s)",
    ["url"], registry=CLIENT_REGISTRY)
CLIENT_IN_FLIGHT = Gauge(
    "client_in_flight", "In-flight client requests per url",
    ["url"], registry=CLIENT_REGISTRY)
CLIENT_REQUESTS = Counter(
    "client_api_requests_total", "Client requests by url and outcome",
    ["url", "code"], registry=CLIENT_REGISTRY)
CLIENT_REQUEST_DURATION = Histogram(
    "client_request_duration_seconds", "Client request latency",
    ["url"], registry=CLIENT_REGISTRY)

# ---- engine (no reference counterpart: the TPU compute path) --------------
ENGINE_BATCHES = Counter(
    "engine_device_batches", "Batched device crypto calls", ["op"],
    registry=REGISTRY)
ENGINE_FALLBACKS = Counter(
    "engine_device_fallbacks", "Device-engine failures that fell back to host",
    registry=REGISTRY)
H2C_CACHE_REQUESTS = Counter(
    "hash_to_g2_cache_requests",
    "hash_to_g2 memo lookups by result (hit|miss) — the per-round "
    "hash-to-curve LRU in crypto/hash_to_curve.py",
    ["result"], registry=REGISTRY)

# ---- timelock serving tier (drand_tpu/timelock, ISSUE 9) ------------------
TIMELOCK_GT_CACHE_REQUESTS = Counter(
    "timelock_gt_cache_requests",
    "timelock encrypt GT-base memo lookups by result (hit|miss) — the "
    "per-round e(pub, H2(round)) LRU in crypto/timelock.py",
    ["result"], registry=REGISTRY)
TIMELOCK_PENDING = Gauge(
    "timelock_pending_ciphertexts",
    "Ciphertexts in the timelock vault still waiting for their round's "
    "V2 signature", registry=REGISTRY)
TIMELOCK_CIPHERTEXTS = Counter(
    "timelock_ciphertexts_total",
    "Timelock vault ciphertext lifecycle events by result (submitted = "
    "accepted into the vault; opened = decrypted at the round boundary; "
    "rejected = failed the Fujisaki-Okamoto check or could never open)",
    ["result"], registry=REGISTRY)
TIMELOCK_OPEN_DISPATCHES = Counter(
    "timelock_open_dispatches_total",
    "Chunked round-boundary open dispatches — one shared-signature "
    "batched decrypt per chunk of at most DRAND_TPU_TIMELOCK_OPEN_CHUNK "
    "pending ciphertexts, so a round of K opens in ceil(K/chunk) "
    "dispatches with a vault commit and a cooperative yield after each",
    registry=REGISTRY)
TIMELOCK_SWEEP_SHARDS = Gauge(
    "timelock_sweep_shards",
    "Token-range shard count this worker's boundary sweep partitions "
    "over (1 = sole sweeper; K = one of a relay --workers K group, "
    "each opening a disjoint token shard of every round)",
    registry=REGISTRY)
VAULT_READS = Counter(
    "vault_reads_total",
    "Timelock vault record reads (status lookups and submit "
    "idempotency probes) by backend (sqlite|segment) — the migration "
    "observability for the segment vault format",
    ["backend"], registry=REGISTRY)

# ---- round tracing (obs/trace.py) -----------------------------------------
# Stage/op work spans sub-millisecond (host crypto on small groups) to
# tens of seconds (cold-compile device dispatches) — the default
# prometheus buckets start too coarse at the low end.
_LATENCY_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)
BEACON_STAGE_SECONDS = Histogram(
    "beacon_stage_seconds",
    "Per-stage beacon round-lifecycle latency (obs tracing spans)",
    ["stage"], registry=GROUP_REGISTRY, buckets=_LATENCY_BUCKETS)
ENGINE_OP_SECONDS = Histogram(
    "engine_op_seconds",
    "Batched crypto op latency by path (device|host; failed dispatches "
    "land under <path>_error) and batch bucket",
    ["op", "path", "batch"], registry=REGISTRY, buckets=_LATENCY_BUCKETS)
ENGINE_COMPILE_SECONDS = Histogram(
    "engine_compile_seconds",
    "First dispatch of each (op, batch-bucket) device shape — jit "
    "compile + first run, split from steady-state engine_op_seconds",
    ["op"], registry=REGISTRY,
    buckets=(0.1, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0))

# ---- chain health / SLOs (obs/health.py) ----------------------------------
# Lateness spans "on time" (ms after the boundary) to "a whole period
# late"; the SLO threshold is period/2, so the buckets must resolve
# fractions of typical periods (3-30 s).
_LATENESS_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 15.0,
                     30.0, 60.0, 120.0)
BEACON_LATENESS = Histogram(
    "beacon_round_lateness_seconds",
    "Actual beacon emit time minus the scheduled round boundary",
    registry=GROUP_REGISTRY, buckets=_LATENESS_BUCKETS)
CHAIN_HEAD_ROUND = Gauge(
    "chain_head_round", "Last beacon round stored on this node's chain",
    registry=GROUP_REGISTRY)
CHAIN_HEAD_LAG = Gauge(
    "chain_head_lag_rounds",
    "Rounds between the wall-clock expected round and the stored head",
    registry=GROUP_REGISTRY)
MISSED_ROUNDS = Counter(
    "beacon_rounds_missed_total",
    "Rounds whose whole period elapsed with no beacon stored "
    "(counted once per skipped round; a later catch-up does not uncount)",
    registry=GROUP_REGISTRY)
SLO_LATE_FRACTION = Gauge(
    "beacon_slo_late_fraction",
    "Fraction of the sliding round window emitted later than period/2 "
    "after their boundary (the chain-health SLO)",
    registry=GROUP_REGISTRY)
SYNC_ROUNDS_PER_SEC = Gauge(
    "chain_sync_rounds_per_second",
    "follow_chain catch-up throughput over the current follow "
    "(0 when idle)", registry=GROUP_REGISTRY)
SYNC_ETA_SECONDS = Gauge(
    "chain_sync_eta_seconds",
    "Estimated seconds until follow_chain reaches its target round "
    "(-1 for an unbounded follow, 0 when idle/done)",
    registry=GROUP_REGISTRY)

# ---- threshold flight recorder (obs/flight.py) ----------------------------
# Margin spans "quorum landed instantly" (≈ period) down through "barely
# made it" (≈ 0) to "quorum after the period elapsed" (negative) — the
# negative buckets keep a dying group's rounds distinguishable from
# healthy instant-quorum ones.
_MARGIN_BUCKETS = (-60.0, -10.0, -1.0, 0.0, 0.25, 0.5, 1.0, 2.5, 5.0,
                   10.0, 15.0, 30.0, 60.0)
QUORUM_MARGIN = Histogram(
    "beacon_quorum_margin_seconds",
    "Round period minus the time-to-t-th-valid-partial — how far the "
    "round stayed from missing quorum (the early-warning SLI)",
    registry=GROUP_REGISTRY, buckets=_MARGIN_BUCKETS)
PARTIAL_ARRIVAL = Histogram(
    "beacon_partial_arrival_seconds",
    "Valid partial/beacon arrival offset from the scheduled round "
    "boundary, by ingress source (grpc|gossip|self)",
    ["source"], registry=GROUP_REGISTRY, buckets=_LATENESS_BUCKETS)
PARTIAL_EVENTS = Counter(
    "beacon_partial_events_total",
    "Per-share-index partial-signature events (contributed = valid "
    "partial accepted; late = valid but more than period/2 after the "
    "boundary; invalid = failed verification/window checks)",
    ["index", "event"], registry=GROUP_REGISTRY)
CONTRIBUTION_GAP = Gauge(
    "beacon_contribution_gap",
    "Group size minus the distinct valid contributors of the last "
    "stored round (0 = full participation)",
    registry=GROUP_REGISTRY)
DKG_PHASE_SECONDS = Histogram(
    "dkg_phase_seconds",
    "DKG/reshare phase durations by phase "
    "(deal|response|justification|finish)",
    ["phase"], registry=GROUP_REGISTRY,
    buckets=(0.1, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0))

# ---- fault detection (obs/flight.py reachability, obs/health.py) ----------
PEER_REACHABLE = Gauge(
    "beacon_peer_reachable",
    "1 while the last outbound partial send to this group member "
    "succeeded, 0 after a send failure (per share index)",
    ["index"], registry=GROUP_REGISTRY)
PARTITION_SUSPECTS = Gauge(
    "beacon_partition_suspects",
    "Group peers currently unreachable from this node by outbound send "
    "result — at n minus threshold the node cannot see a quorum "
    "(the partition-suspect early warning)",
    registry=GROUP_REGISTRY)
PEER_SENDS = Counter(
    "beacon_peer_sends_total",
    "Outbound partial-broadcast attempts per group member by outcome "
    "(ok = delivered; failed = transport error / unreachable)",
    ["index", "outcome"], registry=GROUP_REGISTRY)
INGRESS_REJECTS = Counter(
    "beacon_ingress_rejects_total",
    "Partial/beacon ingress rejections by source (grpc|gossip|self) "
    "and verdict (invalid|stale|future|duplicate) — the flood/abuse "
    "visibility the peer-attributed counters deliberately do not carry",
    ["source", "verdict"], registry=GROUP_REGISTRY)
SYNC_STALLED = Gauge(
    "chain_sync_stalled",
    "1 while the chain head lags beyond the readiness bound and no "
    "catch-up is making progress (re-evaluated by /healthz and scrapes)",
    registry=GROUP_REGISTRY)

# ---- self-healing (utils/retry.py, net/transport.py, handler repair) ------
NET_RETRY_ATTEMPTS = Counter(
    "net_retry_attempts_total",
    "Retry-policy attempts by call-site op (partial|sync|repair|"
    "control|gossip|timelock|watch) and outcome (ok = attempt "
    "succeeded; "
    "retry = failed with a backoff sleep following; exhausted = failed "
    "with no budget left; rejected = classified non-retryable)",
    ["op", "outcome"], registry=GROUP_REGISTRY)
PEER_BREAKER_STATE = Gauge(
    "beacon_peer_breaker_state",
    "Per-peer circuit breaker state by share index "
    "(0 = closed, 1 = half-open, 2 = open) — open means outbound "
    "sends to that member are skipped until the next capped probe",
    ["index"], registry=GROUP_REGISTRY)
PARTIAL_REPAIRS = Counter(
    "beacon_partial_repairs_total",
    "Quorum-repair operations by outcome (recovered = the pull "
    "reached the threshold inside the round's period; synced = peers "
    "had already stored the round, the beacon is fetched via sync "
    "instead; failed = the round stayed below threshold)",
    ["outcome"], registry=GROUP_REGISTRY)
# ---- incident engine (obs/incident.py, ISSUE 15) --------------------------
INCIDENTS_TOTAL = Counter(
    "incidents_total",
    "Incidents minted by the anomaly detector over the SLI time-series "
    "ring, by rule and severity — one per sustained fault (re-fires "
    "extend the open incident; the per-rule cooldown suppresses flaps)",
    ["rule", "severity"], registry=GROUP_REGISTRY)
INCIDENT_ACTIVE = Gauge(
    "incident_active",
    "Currently open incidents: their rules are still firing or have "
    "not yet stayed quiet for the clear window",
    registry=GROUP_REGISTRY)
# ---- auto-remediation (obs/remediate.py, ISSUE 16) ------------------------
REMEDIATION_ACTIONS = Counter(
    "remediation_actions_total",
    "Remediation-ledger entries by playbook and outcome (ok = action "
    "ran / recommendation written; failed = action raised; dry_run = "
    "engine not armed, annotated what it WOULD do; budget_exhausted = "
    "the global actions-per-window budget refused it; reverted = a "
    "sticky playbook's revert ran when its incident closed)",
    ["playbook", "outcome"], registry=GROUP_REGISTRY)
REMEDIATION_ACTIVE = Gauge(
    "remediation_active",
    "Playbooks currently holding an action in flight or a sticky "
    "posture (1 while held; partition_posture stays 1 until the "
    "reachability incident closes and the revert restores the caps)",
    ["playbook"], registry=GROUP_REGISTRY)
_MTTR_BUCKETS = (1.0, 5.0, 15.0, 30.0, 60.0, 120.0, 300.0, 600.0,
                 1800.0)
REMEDIATION_MTTR = Histogram(
    "remediation_mttr_seconds",
    "Open-to-close duration of incidents the remediation engine acted "
    "on — mean time to recovery as a first-class SLI",
    registry=GROUP_REGISTRY, buckets=_MTTR_BUCKETS)

RELAY_STALE_SERVED = Counter(
    "relay_stale_served_total",
    "/public/latest responses served from the last-known beacon with "
    "the X-Drand-Stale header because the upstream was unreachable",
    registry=HTTP_REGISTRY)

# ---- edge fan-out push tier (http_server/fanout.py, ISSUE 14) -------------
RELAY_WATCHERS = Gauge(
    "relay_watchers",
    "Currently connected /public/latest stream watchers (SSE + NDJSON) "
    "on this relay worker process",
    registry=HTTP_REGISTRY)
RELAY_WAKEUPS = Counter(
    "relay_wakeups_total",
    "Fan-out hub publishes that woke at least one watcher, by stream "
    "protocol (sse|ndjson) — at most one per round per protocol per "
    "worker regardless of watcher count",
    ["proto"], registry=HTTP_REGISTRY)
RELAY_SHED = Counter(
    "relay_shed_total",
    "Stream watchers refused or dropped by the load shedder "
    "(watcher_cap = 429 at the connection cap, Retry-After on the next "
    "round boundary; slow_consumer = bounded send queue overflowed and "
    "the stream was disconnected; timelock_slow = same overflow on the "
    "/timelock open-notify leg)",
    ["reason"], registry=HTTP_REGISTRY)
TIMELOCK_WATCHERS = Gauge(
    "timelock_watchers",
    "Currently connected /timelock open-notify stream watchers "
    "(SSE + NDJSON) on this worker",
    registry=HTTP_REGISTRY)
TIMELOCK_NOTIFY = Counter(
    "timelock_notify_total",
    "Open-notify events published on the /timelock stream leg by "
    "terminal status (opened|rejected) — counted once per decided "
    "ciphertext, not per watcher",
    ["event"], registry=HTTP_REGISTRY)
RELAY_BOUNDARY_DELIVERY = Histogram(
    "relay_boundary_delivery_seconds",
    "Scheduled round boundary to fan-out hub publish on this worker "
    "(the server half of boundary-to-delivery latency)",
    registry=HTTP_REGISTRY,
    buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
             0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0))
CHAIN_STORE_READS = Counter(
    "chain_store_reads_total",
    "Beacon reads served by the chain store, by backend "
    "(sqlite|segment) — get() and cursor batches both count per beacon",
    ["backend"], registry=GROUP_REGISTRY)

# ---- million-client catch-up (client/verify.py, ISSUE 17) -----------------
CLIENT_CATCHUP_ROUNDS = Counter(
    "client_catchup_rounds_total",
    "Rounds verified by the VerifyingClient catch-up walk (RLC span "
    "checks plus per-item fallbacks both count per beacon)",
    registry=CLIENT_REGISTRY)
CLIENT_CATCHUP_CHUNK = Gauge(
    "client_catchup_chunk_rounds",
    "Current adaptive catch-up chunk size — grows geometrically while "
    "spans verify clean, halves when a span contains a corrupt beacon",
    registry=CLIENT_REGISTRY)
CKPT_BOOTSTRAPS = Counter(
    "checkpoint_bootstraps_total",
    "Checkpoint trust bootstraps by result (ok = the signed checkpoint "
    "verified and the spot-check sample passed, head trust jumped in "
    "O(1); rejected = verification failed, fell back to the full walk)",
    ["result"], registry=CLIENT_REGISTRY)
CKPT_ISSUED = Counter(
    "checkpoint_issued_total",
    "Checkpoints recovered by the aggregator from piggybacked "
    "threshold partials at checkpoint-interval rounds",
    registry=GROUP_REGISTRY)
CKPT_ROUND = Gauge(
    "checkpoint_round",
    "Round of the latest recovered checkpoint served at "
    "/checkpoints/latest (0 until the first recovery)",
    registry=GROUP_REGISTRY)

# ---- OTLP export (obs/export.py) ------------------------------------------
OTLP_EXPORT_ROUNDS = Counter(
    "otlp_export_rounds_total",
    "Round traces handed to the OTLP exporter, by sink "
    "(http = POSTed to the collector, spool = appended to the on-disk "
    "NDJSON ring, dropped = both sinks failed)",
    ["sink"], registry=REGISTRY)


def batch_bucket(n: int) -> str:
    """Coarse batch-size bucket label — bounded cardinality for
    engine_op_seconds (matches the engine's compile-bucket scale)."""
    for b in (1, 8, 32, 128, 512):
        if n <= b:
            return str(b)
    return "512+"


def render() -> bytes:
    """The /metrics payload: all four registries gathered (the
    reference's chained-gatherer handler, metrics.go:229-266)."""
    return b"".join(generate_latest(r) for r in
                    (REGISTRY, GROUP_REGISTRY, HTTP_REGISTRY,
                     CLIENT_REGISTRY))
