"""Prometheus metrics — the reference catalogue, four registries.

Reference: metrics/metrics.go:17-146 defines four registries
(PrivateMetrics :17, HTTPMetrics :20, GroupMetrics :22, ClientMetrics
:24) and the catalogue below; client/http/metric.go:14 adds the client
heartbeat set. The store decorator feeding the beacon gauges is
chain/beacon/store.go:57 (discrepancyStore → our DiscrepancyStore).

Catalogue parity (reference name → here):
  api_call_counter               → api_call_counter           [private]
  dial_failures                  → outgoing_connection_failures [group]
  group_connections              → group_connections          [group]
  beacon_discrepancy_latency     → beacon_discrepancy_latency_ms [group]
  last_beacon_round              → last_beacon_round          [group]
  http_call_counter              → http_api_requests          [http]
  http_response_duration         → http_api_latency_seconds   [http]
  http_in_flight                 → http_in_flight             [http]
  client_watch_latency           → client_watch_latency       [client]
  client_http_heartbeat_success  → client_http_heartbeat_success [client]
  client_http_heartbeat_failure  → client_http_heartbeat_failure [client]
  client_http_heartbeat_latency  → client_http_heartbeat_latency [client]
  client_in_flight               → client_in_flight           [client]
  client_api_requests_total      → client_api_requests_total  [client]
  client_request_duration_seconds→ client_request_duration_seconds [client]
  (client_dns/tls_duration_seconds are Go httptrace hooks with no
   asyncio equivalent — intentionally absent)
Additions beyond the reference (the TPU engine):
  engine_device_batches, engine_device_fallbacks, dkg_bundles_received

Everything is exposed on /metrics (render() gathers all four registries
— the reference's handler chains its gatherers the same way,
metrics.go:229) and relayed per peer via /peer/{addr}/metrics.
"""

from __future__ import annotations

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
)

# Four registries (metrics.go:17-24). REGISTRY keeps its old name as the
# private/default one for back-compat with existing callers.
REGISTRY = CollectorRegistry()          # PrivateMetrics
HTTP_REGISTRY = CollectorRegistry()     # HTTPMetrics
GROUP_REGISTRY = CollectorRegistry()    # GroupMetrics
CLIENT_REGISTRY = CollectorRegistry()   # ClientMetrics

# ---- private (node-to-node API) -------------------------------------------
API_CALLS = Counter(
    "api_call_counter", "Private gRPC API calls", ["method"],
    registry=REGISTRY)

# ---- group (chain + mesh health) ------------------------------------------
BEACON_DISCREPANCY_LATENCY = Gauge(
    "beacon_discrepancy_latency_ms",
    "Milliseconds between the expected round time and the beacon being stored",
    registry=GROUP_REGISTRY)
LAST_BEACON_ROUND = Gauge(
    "last_beacon_round", "Last aggregated and stored beacon round",
    registry=GROUP_REGISTRY)
DIAL_FAILURES = Counter(
    "outgoing_connection_failures",
    "Failed outbound node-to-node calls", ["peer"],
    registry=GROUP_REGISTRY)
GROUP_CONNECTIONS = Gauge(
    "group_connections", "Open outbound connections to group members",
    registry=GROUP_REGISTRY)
DKG_BUNDLES = Counter(
    "dkg_bundles_received", "DKG bundles accepted by the broadcast board",
    ["kind"], registry=GROUP_REGISTRY)

# ---- http (public REST server) --------------------------------------------
HTTP_REQUESTS = Counter(
    "http_api_requests", "Public REST API calls", ["path", "code"],
    registry=HTTP_REGISTRY)
HTTP_LATENCY = Histogram(
    "http_api_latency_seconds", "Public REST API latency", ["path"],
    registry=HTTP_REGISTRY)
HTTP_IN_FLIGHT = Gauge(
    "http_in_flight", "In-flight public REST requests",
    registry=HTTP_REGISTRY)

# ---- client (the consuming side: watches, heartbeats) ---------------------
CLIENT_WATCH_LATENCY = Gauge(
    "client_watch_latency",
    "Duration between time round received and time round expected (ms)",
    registry=CLIENT_REGISTRY)
CLIENT_HEARTBEAT_SUCCESS = Counter(
    "client_http_heartbeat_success", "Successful client heartbeats",
    ["url"], registry=CLIENT_REGISTRY)
CLIENT_HEARTBEAT_FAILURE = Counter(
    "client_http_heartbeat_failure", "Failed client heartbeats",
    ["url"], registry=CLIENT_REGISTRY)
CLIENT_HEARTBEAT_LATENCY = Gauge(
    "client_http_heartbeat_latency", "Last client heartbeat latency (s)",
    ["url"], registry=CLIENT_REGISTRY)
CLIENT_IN_FLIGHT = Gauge(
    "client_in_flight", "In-flight client requests per url",
    ["url"], registry=CLIENT_REGISTRY)
CLIENT_REQUESTS = Counter(
    "client_api_requests_total", "Client requests by url and outcome",
    ["url", "code"], registry=CLIENT_REGISTRY)
CLIENT_REQUEST_DURATION = Histogram(
    "client_request_duration_seconds", "Client request latency",
    ["url"], registry=CLIENT_REGISTRY)

# ---- engine (no reference counterpart: the TPU compute path) --------------
ENGINE_BATCHES = Counter(
    "engine_device_batches", "Batched device crypto calls", ["op"],
    registry=REGISTRY)
ENGINE_FALLBACKS = Counter(
    "engine_device_fallbacks", "Device-engine failures that fell back to host",
    registry=REGISTRY)


def render() -> bytes:
    """The /metrics payload: all four registries gathered (the
    reference's chained-gatherer handler, metrics.go:229-266)."""
    return b"".join(generate_latest(r) for r in
                    (REGISTRY, GROUP_REGISTRY, HTTP_REGISTRY,
                     CLIENT_REGISTRY))
