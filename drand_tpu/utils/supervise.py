"""Bounded-respawn worker supervision (ISSUE 16).

Generalizes the relay parent's sweeper-respawn loop (cli/__main__.py
``_relay_parent``, ISSUE 14): a registered worker that dies gets
respawned at most ``respawn_budget`` times, with exponential backoff
between attempts so a crash-looping worker cannot fork-bomb the box.
Two consumers share this one policy:

- the SO_REUSEPORT relay parent supervises its designated timelock
  sweeper worker (a subprocess), and
- the auto-remediation ``respawn_worker`` playbook
  (obs/remediate.py) supervises in-process beacon workers through the
  same budget/backoff, so a respawn decided by an incident rides the
  identical guardrails an operator-run parent applies.

The supervisor itself never blocks: backoff is expressed as a
*not-before* time on the injectable clock (FakeClock in chaos tests —
fully deterministic), and ``maybe_respawn`` returns an outcome string
instead of sleeping. ``respawn`` callables are synchronous; an async
restart is wrapped by the caller (``aio.spawn(net.restart(i))``) so
subprocess parents — which have no event loop at all — and playbook
actions use the same interface.

Thread-safe: decisions are made under ``_lock`` (the repo's named-lock
convention); the registered callables run OUTSIDE it — a subprocess
spawn takes milliseconds and must not stall a concurrent status read.
"""

from __future__ import annotations

import threading
from typing import Callable

from .clock import Clock, SystemClock

# outcomes of one maybe_respawn decision
ALIVE = "alive"
RESPAWNED = "respawned"
RESPAWN_FAILED = "respawn_failed"
BUDGET_EXHAUSTED = "budget_exhausted"
BACKOFF = "backoff"
UNKNOWN = "unknown"


class Supervisor:
    """Registered workers + a bounded, backoff-paced respawn policy."""

    def __init__(self, *, clock: Clock | None = None,
                 respawn_budget: int = 5,
                 backoff_base_s: float = 0.5,
                 backoff_cap_s: float = 30.0):
        self._clock = clock or SystemClock()
        self.respawn_budget = respawn_budget
        self.backoff_base_s = backoff_base_s
        self.backoff_cap_s = backoff_cap_s
        self._lock = threading.Lock()
        # name -> {"is_alive": fn, "respawn": fn, "respawns": int,
        #          "not_before": float}
        self._workers: dict[str, dict] = {}

    # ---------------------------------------------------------- registry
    def register(self, name: str, *, is_alive: Callable[[], bool],
                 respawn: Callable[[], object]) -> None:
        """Register (or replace) one supervised worker. ``is_alive``
        must be cheap and non-blocking (a ``Popen.poll()``, a set
        lookup); ``respawn`` starts a replacement synchronously."""
        with self._lock:
            self._workers[name] = {"is_alive": is_alive,
                                   "respawn": respawn,
                                   "respawns": 0,
                                   "not_before": float("-inf")}

    def unregister(self, name: str) -> None:
        with self._lock:
            self._workers.pop(name, None)

    def workers(self) -> list[str]:
        with self._lock:
            return sorted(self._workers)

    def respawns(self, name: str) -> int:
        with self._lock:
            w = self._workers.get(name)
            return w["respawns"] if w else 0

    # ------------------------------------------------------------- state
    def dead(self) -> list[str]:
        """Registered workers whose ``is_alive`` currently reads False.
        Probes run outside the lock (they are caller code)."""
        with self._lock:
            probes = [(n, w["is_alive"]) for n, w in self._workers.items()]
        out = []
        for name, probe in probes:
            try:
                alive = bool(probe())
            except Exception:  # noqa: BLE001 — a broken probe reads dead
                alive = False
            if not alive:
                out.append(name)
        return sorted(out)

    def status(self) -> dict:
        """Per-worker supervision state for the debug surfaces."""
        dead = set(self.dead())
        with self._lock:
            return {name: {"alive": name not in dead,
                           "respawns": w["respawns"],
                           "budget": self.respawn_budget,
                           "not_before": (None
                                          if w["not_before"] == float("-inf")
                                          else round(w["not_before"], 6))}
                    for name, w in self._workers.items()}

    # ----------------------------------------------------------- respawn
    def maybe_respawn(self, name: str, now: float | None = None) -> str:
        """One supervision decision for ``name``: respawn it if it is
        dead, the budget is not exhausted, and the backoff window has
        passed. Never blocks — returns the outcome."""
        if now is None:
            now = self._clock.now()
        with self._lock:
            w = self._workers.get(name)
            if w is None:
                return UNKNOWN
            probe, respawn = w["is_alive"], w["respawn"]
        try:
            alive = bool(probe())
        except Exception:  # noqa: BLE001
            alive = False
        if alive:
            return ALIVE
        with self._lock:
            w = self._workers.get(name)
            if w is None:
                return UNKNOWN
            if w["respawns"] >= self.respawn_budget:
                return BUDGET_EXHAUSTED
            if now < w["not_before"]:
                return BACKOFF
            # reserve the slot under the lock: a concurrent caller must
            # not double-spawn the same worker
            w["respawns"] += 1
            backoff = min(self.backoff_cap_s,
                          self.backoff_base_s * (2 ** (w["respawns"] - 1)))
            w["not_before"] = now + backoff
        try:
            respawn()
        except Exception:  # noqa: BLE001 — the slot stays spent
            return RESPAWN_FAILED
        return RESPAWNED

    def check(self, now: float | None = None) -> dict[str, str]:
        """Sweep every registered worker once; outcomes by name
        (workers that are alive are included as ``alive``)."""
        return {name: self.maybe_respawn(name, now=now)
                for name in self.workers()}
