"""Injectable clocks — the testing backbone.

Mirrors the reference's pervasive use of jonboulle/clockwork (SURVEY.md §4):
every time-dependent component takes a Clock so multi-node tests advance
rounds deterministically with zero wall-clock waiting
(reference: core/util_test.go:235-257 MoveTime/MoveToTime).
"""

from __future__ import annotations

import asyncio
import heapq
import itertools
import time as _time


class Clock:
    """Abstract clock: wall time + async sleeping."""

    def now(self) -> float:
        raise NotImplementedError

    async def sleep(self, seconds: float) -> None:
        raise NotImplementedError

    async def sleep_until(self, t: float) -> None:
        delta = t - self.now()
        if delta > 0:
            await self.sleep(delta)


class SystemClock(Clock):
    def now(self) -> float:
        return _time.time()

    async def sleep(self, seconds: float) -> None:
        await asyncio.sleep(max(0.0, seconds))


class FakeClock(Clock):
    """Deterministic clock. Tasks calling ``sleep`` block until ``advance``
    moves time past their wake target. ``advance`` steps through intermediate
    wake targets in order and yields control so woken tasks can run (and
    possibly sleep again within the same window) — matching clockwork's
    semantics that drand's tests rely on."""

    def __init__(self, start: float = 1_700_000_000.0):
        self._now = float(start)
        self._waiters: list[tuple[float, int, asyncio.Future]] = []
        self._counter = itertools.count()

    def now(self) -> float:
        return self._now

    async def sleep(self, seconds: float) -> None:
        if seconds <= 0:
            await asyncio.sleep(0)
            return
        fut = asyncio.get_running_loop().create_future()
        heapq.heappush(self._waiters, (self._now + seconds, next(self._counter), fut))
        await fut

    def next_wake(self) -> float | None:
        """Earliest pending wake target, or None when nothing sleeps.
        Lets harnesses (testing/chaos.py) step time deterministically
        from wake target to wake target instead of jumping a whole
        window — the clock then PARKS between targets, so everything a
        delivery triggers is timestamped at the delivery time."""
        return self._waiters[0][0] if self._waiters else None

    def _wake_due(self) -> bool:
        woke = False
        while self._waiters and self._waiters[0][0] <= self._now:
            _, _, fut = heapq.heappop(self._waiters)
            if not fut.done():
                fut.set_result(None)
                woke = True
        return woke

    async def settle(self, rounds: int = 25) -> None:
        """Let scheduled tasks run until quiescent."""
        for _ in range(rounds):
            await asyncio.sleep(0)

    async def advance(self, seconds: float) -> None:
        """Move time forward, waking sleepers in order of their targets."""
        # let freshly-created tasks run up to their first sleep, so they
        # register waiters BEFORE time moves (otherwise they miss the window)
        await self.settle()
        target = self._now + seconds
        while True:
            next_wake = self._waiters[0][0] if self._waiters else None
            if next_wake is not None and next_wake <= target:
                self._now = max(self._now, next_wake)
                self._wake_due()
                await self.settle()
            else:
                break
        self._now = target
        self._wake_due()
        await self.settle()

    async def advance_to(self, t: float) -> None:
        if t > self._now:
            await self.advance(t - self._now)
