"""Secure filesystem helpers.

Reference: fs/fs.go — key material lives in 0700 directories and 0600
files (CreateSecureFolder :26, CreateSecureFile :62); anything looser is
rejected at load time.
"""

from __future__ import annotations

import os
import stat


def create_secure_folder(path: str) -> str:
    """mkdir -p with 0700; raises if it exists with looser permissions."""
    if os.path.isdir(path):
        mode = stat.S_IMODE(os.stat(path).st_mode)
        if mode & 0o077:
            raise PermissionError(
                f"{path} has permissions {oct(mode)}; expected 0700")
        return path
    os.makedirs(path, mode=0o700, exist_ok=True)
    os.chmod(path, 0o700)
    return path


def create_secure_file(path: str) -> str:
    """Create (or truncate) a 0600 file."""
    fd = os.open(path, os.O_CREAT | os.O_WRONLY | os.O_TRUNC, 0o600)
    os.close(fd)
    os.chmod(path, 0o600)
    return path


def write_secure_file(path: str, data: bytes) -> None:
    create_secure_file(path)
    with open(path, "wb") as f:
        f.write(data)


def home_folder() -> str:
    return os.path.expanduser("~")


def file_exists(path: str) -> bool:
    return os.path.isfile(path)
