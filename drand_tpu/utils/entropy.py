"""Entropy sourcing for key generation and DKG secrets.

Reference: entropy/entropy.go — OS randomness by default, with an optional
user-supplied executable whose stdout is mixed in (never trusted alone:
user entropy is XORed with crypto/rand so a bad script cannot weaken the
result below the OS baseline; GetRandom :15, ScriptReader :39).
"""

from __future__ import annotations

import os
import subprocess


def get_random(n: int, script: str | None = None) -> bytes:
    """n random bytes; with `script`, its output is XOR-mixed in."""
    base = os.urandom(n)
    if not script:
        return base
    try:
        out = subprocess.run(
            [script], capture_output=True, timeout=10, check=True
        ).stdout
    except (OSError, subprocess.SubprocessError):
        return base
    if len(out) < n:
        return base
    return bytes(a ^ b for a, b in zip(base, out[:n]))
