"""Persistent XLA compilation cache.

The batched pairing graphs are large (the Miller loop + final-exp scan
bodies); first compilation costs minutes of XLA time. Enabling JAX's
persistent compilation cache makes that a once-per-machine cost instead of
once-per-process — essential for the test suite, bench.py, and the daemon's
startup latency. Mirrors the role of Go's on-disk build cache for the
reference (which pays its compile cost at `go build`, not at runtime).
"""

from __future__ import annotations

import os


def enable_persistent_cache(path: str | None = None) -> None:
    """Idempotently point JAX's compilation cache at a writable directory."""
    import jax

    if path is None:
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        path = os.path.join(root, ".jax_cache")
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
