"""One resolved TOML parser for every caller: stdlib ``tomllib`` on
Python 3.11+, the API-identical ``tomli`` below that (this image ships
Python 3.10). Import the module object::

    from ..utils.toml_compat import tomllib
"""

try:
    import tomllib
except ModuleNotFoundError:  # Python < 3.11
    import tomli as tomllib  # noqa: F401
