"""ONE deadline-aware retry policy for the beacon plane (ISSUE 12).

Every network edge that retries in this codebase goes through this
module: partial-beacon sends (chain/engine/handler.py), sync chunk
fetches (chain/engine/sync.py follow passes), control and gossip dials
(net/control.py, relay/gossip.py), and the timelock sweep's upstream
round fetch (timelock/service.py). One policy object means one backoff
shape, one metric, and one determinism rule instead of five hand-rolled
loops that each invent their own.

Backoff is **decorrelated jitter** (the AWS architecture-blog variant):
each sleep is drawn uniformly from ``[base, 3 * previous_sleep]`` and
capped, which decorrelates retry storms across peers better than
plain exponential-with-jitter while keeping the first retry fast.

Determinism: sleeps go through an **injectable Clock**
(:mod:`drand_tpu.utils.clock`), so a FakeClock chaos run steps retry
wake-ups exactly like every other timer — wall-clock never leaks into
a scheduled fault's margin math. The jitter source is injectable too
(``rng=random.Random(seed)``) for exact-value tests. The analyzer
enforces the other half of this contract: a raw ``asyncio.sleep``
inside a retry loop in net/, chain/ or timelock/ is a medium
``loopblock:retry-sleep`` finding.

Observability: every attempt lands on
``net_retry_attempts_total{op,outcome}``:

- ``ok``        — the attempt succeeded
- ``retry``     — the attempt failed and a backoff sleep follows
- ``exhausted`` — the attempt failed with no retries left (attempt
  budget spent, or the next sleep would cross the deadline)
- ``rejected``  — the error is classified non-retryable (``no_retry``
  class or the ``giveup`` predicate) — e.g. a peer that ANSWERED with
  a rejection must not be hammered

``op`` is the call-site tag (partial | sync | repair | control |
gossip | timelock | watch) — bounded by the code paths that mint it,
like the ingress-reject verdict label.
"""

from __future__ import annotations

import asyncio
import random as _random
from dataclasses import dataclass
from typing import Awaitable, Callable

from .clock import Clock, SystemClock


def _attempt_counter(op: str, outcome: str):
    """Branch-literal outcome labels (the check_metrics
    KNOWN_LABEL_VALUES enum rule); ``op`` is dynamic-but-bounded by the
    call sites, like the ingress-reject verdict."""
    from .. import metrics

    if outcome == "ok":
        return metrics.NET_RETRY_ATTEMPTS.labels(op=op, outcome="ok")
    if outcome == "retry":
        return metrics.NET_RETRY_ATTEMPTS.labels(op=op, outcome="retry")
    if outcome == "rejected":
        return metrics.NET_RETRY_ATTEMPTS.labels(op=op,
                                                 outcome="rejected")
    return metrics.NET_RETRY_ATTEMPTS.labels(op=op, outcome="exhausted")


@dataclass(frozen=True)
class RetryPolicy:
    """How often and how hard to retry. ``attempts`` is the TOTAL try
    budget (1 = no retries); ``deadline_s`` bounds the whole operation
    including backoff sleeps — a sleep that would cross it is never
    started (deadline-aware, not deadline-oblivious)."""

    attempts: int = 3
    base_s: float = 0.1
    cap_s: float = 2.0
    deadline_s: float | None = None


DEFAULT_POLICY = RetryPolicy()


async def retry(fn: Callable[[], Awaitable], *, op: str,
                policy: RetryPolicy = DEFAULT_POLICY,
                clock: Clock | None = None,
                rng: _random.Random | None = None,
                retry_on: tuple[type[BaseException], ...] = (Exception,),
                no_retry: tuple[type[BaseException], ...] = (),
                giveup: Callable[[BaseException], bool] | None = None):
    """Run ``await fn()`` under ``policy``.

    - exceptions in ``no_retry`` (checked FIRST — subclasses of a
      ``retry_on`` class stay non-retryable) or matching ``giveup(e)``
      re-raise immediately (outcome ``rejected``);
    - exceptions in ``retry_on`` back off and retry until the attempt
      budget or deadline runs out (final failure re-raises, outcome
      ``exhausted``);
    - anything else — including ``CancelledError`` — propagates
      untouched and uncounted (it is not a network outcome).
    """
    clock = clock if clock is not None else SystemClock()
    uniform = rng.uniform if rng is not None else _random.uniform
    start = clock.now()
    sleep_s = policy.base_s
    attempt = 0
    while True:
        attempt += 1
        try:
            result = await fn()
        except asyncio.CancelledError:
            raise
        except no_retry as e:
            _attempt_counter(op, "rejected").inc()
            raise
        except retry_on as e:
            if giveup is not None and giveup(e):
                _attempt_counter(op, "rejected").inc()
                raise
            # decorrelated jitter: next sleep in [base, 3*prev], capped
            sleep_s = min(policy.cap_s, uniform(policy.base_s,
                                                sleep_s * 3))
            past_deadline = (
                policy.deadline_s is not None
                and clock.now() - start + sleep_s > policy.deadline_s)
            if attempt >= policy.attempts or past_deadline:
                _attempt_counter(op, "exhausted").inc()
                raise
            _attempt_counter(op, "retry").inc()
            await clock.sleep(sleep_s)
        else:
            _attempt_counter(op, "ok").inc()
            return result
