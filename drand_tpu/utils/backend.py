"""Backend initialization with a hard watchdog.

Every entry point that touches the device (bench.py, the driver entry,
``drand start``, the demo) goes through the tunneled axon TPU backend,
and the tunnel can be down in two distinct ways:

- **fail fast**: ``jax.devices()`` raises ``RuntimeError: Unable to
  initialize backend 'axon': UNAVAILABLE`` — retryable, the tunnel may
  come back within a minute.
- **hang**: the PJRT client blocks forever inside a C call. Python-level
  signal handlers never run while the main thread is stuck in C, so the
  only reliable escape is a watchdog *thread* that force-exits the
  process (``os._exit`` works from any thread regardless of what the
  main thread is doing).

``init_backend`` wraps both: it retries fast failures until ``deadline``
and arms a watchdog thread against hangs. On persistent failure it
either raises :class:`BackendUnavailable` (fast-fail path) or runs the
caller's ``on_fail`` callback and force-exits (hang path) — it never
blocks past the deadline. This is the repo-wide fix for the round-3
outage that turned the driver's official record red (BENCH_r03 rc=1,
MULTICHIP_r03 rc=124).

The reference has no analogue — a Go binary linking kilic/bls12-381 has
no remote device to lose (drand/core/drand.go boots purely on-host).
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Callable, Optional


class BackendUnavailable(RuntimeError):
    """The jax backend could not be initialized within the deadline."""


def backend_already_up() -> bool:
    """True iff this process has already initialized a jax backend (in
    which case touching jax cannot hang — init happens once)."""
    try:
        from jax._src import xla_bridge

        return bool(xla_bridge._backends)
    except Exception:  # noqa: BLE001 — private API; treat drift as "no"
        return False


_PROBE_RESULT: Optional[bool] = None
_PROBE_TIME: float = 0.0
_PROBE_THREAD: Optional[threading.Thread] = None
# _PROBE_LOCK serializes the probe itself (held for up to timeout+init
# — NEVER grab it from the event loop); _VERDICT_LOCK guards the three
# shared fields above and is only ever held for the assignment, so the
# loop-side writers (probe_backend_bg, the fast paths) stay non-blocking
# (tools/analyze threadshare: thread-shared mutable state names its lock)
_PROBE_LOCK = threading.Lock()
_VERDICT_LOCK = threading.Lock()

# A negative verdict expires: a daemon outliving a tunnel outage must
# regain the device path without a restart (ADVICE r4). Positive verdicts
# are permanent — once a backend initialized in-process it stays up.
NEG_PROBE_TTL = float(os.environ.get("DRAND_TPU_PROBE_TTL", "300"))


def _probe_expired() -> bool:
    return (_PROBE_RESULT is False
            and NEG_PROBE_TTL > 0
            and time.monotonic() - _PROBE_TIME > NEG_PROBE_TTL)


def probe_backend(timeout: float = 90.0, *, cache: bool = True) -> bool:
    """Check in a THROWAWAY SUBPROCESS whether this environment's default
    jax backend can initialize, then (on success) initialize it in-process
    too, so later callers find it warm. Never hangs the caller
    indefinitely: the child is killed at ``timeout``.

    This is the hang-safe guard for long-lived processes (the daemon)
    where ``init_backend``'s force-exit watchdog would be worse than the
    outage: a daemon must degrade to the host crypto path, not die.
    Inherits the environment verbatim, so the verdict matches what an
    in-process init would do (CPU-pinned test runs probe the CPU backend
    and return instantly). The result is cached per process.

    BLOCKS for up to ``timeout`` + one real backend init — synchronous
    contexts (bench, CLI one-shots, tests) call this directly; event-loop
    code must use :func:`probe_backend_bg` + :func:`probe_state` instead
    (crypto/batch.engine does).

    ``DRAND_TPU_PROBE_TIMEOUT`` overrides ``timeout``; ``0`` skips the
    probe entirely (always "up" — for environments known to be local).
    """
    global _PROBE_RESULT, _PROBE_TIME
    if cache and _PROBE_RESULT is not None and not _probe_expired():
        return _PROBE_RESULT
    if backend_already_up():
        with _VERDICT_LOCK:
            _PROBE_RESULT = True
        return True
    # a background probe may already be in flight (daemon startup):
    # join it instead of launching a duplicate subprocess
    th = _PROBE_THREAD
    if (th is not None and th.is_alive()
            and th is not threading.current_thread()):
        th.join(timeout + 60)
        if _PROBE_RESULT is not None:
            return _PROBE_RESULT
    with _PROBE_LOCK:
        if cache and _PROBE_RESULT is not None and not _probe_expired():
            return _PROBE_RESULT
        env_t = os.environ.get("DRAND_TPU_PROBE_TIMEOUT")
        if env_t is not None:
            timeout = float(env_t)
        if timeout <= 0:
            with _VERDICT_LOCK:
                _PROBE_RESULT = True
            return True
        import subprocess

        try:
            proc = subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                timeout=timeout, capture_output=True)
            ok = proc.returncode == 0
        except subprocess.TimeoutExpired:
            ok = False
        if ok:
            # proven not to hang moments ago: warm the in-process backend
            # so the engine's first real dispatch doesn't pay init on the
            # hot path. (A tunnel dying in this window can still hang —
            # but then every path through jax is lost anyway; the probe's
            # job was to keep the common outage case non-blocking.)
            try:
                import jax

                jax.devices()
            except Exception:  # noqa: BLE001 — flapping tunnel
                ok = False
        with _VERDICT_LOCK:
            _PROBE_RESULT = ok
            _PROBE_TIME = time.monotonic()
        return ok


def probe_state() -> Optional[bool]:
    """Cached probe verdict: True/False, or None when no probe has
    completed yet. A negative verdict older than ``NEG_PROBE_TTL``
    triggers a background re-probe (and keeps answering False until it
    completes) — long-lived daemons regain the device path when the
    tunnel recovers."""
    if backend_already_up():
        return True
    if _probe_expired():
        probe_backend_bg()
    return _PROBE_RESULT


def probe_backend_bg(timeout: float = 90.0) -> None:
    """Kick off :func:`probe_backend` on a daemon thread (idempotent) —
    the event-loop-safe way to warm the backend: callers poll
    :func:`probe_state` and use the host path until it flips to True.
    The daemon calls this at startup; crypto/batch.engine calls it on
    first use from loop context."""
    global _PROBE_THREAD
    # check-and-spawn under the (short) verdict lock: a loop caller and
    # a worker racing here must not launch two probe subprocesses (the
    # second would also clobber the first's _PROBE_THREAD handle, so
    # probe_backend's join-an-in-flight-probe path could join the
    # wrong thread)
    with _VERDICT_LOCK:
        if (_PROBE_RESULT is not None and not _probe_expired()) or (
                _PROBE_THREAD is not None and _PROBE_THREAD.is_alive()):
            return
        _PROBE_THREAD = threading.Thread(
            target=probe_backend, args=(timeout,), daemon=True,
            name="backend-probe")
        _PROBE_THREAD.start()


def init_backend(
    deadline: float = 180.0,
    *,
    retry_interval: float = 15.0,
    on_fail: Optional[Callable[[str], None]] = None,
    exit_code: int = 0,
    log: Callable[[str], None] = lambda m: print(m, file=sys.stderr,
                                                 flush=True),
):
    """Initialize the default jax backend, bounded by ``deadline`` seconds.

    Returns ``(platform, devices)`` on success.

    On a *fast* persistent failure (init keeps raising until the deadline)
    raises :class:`BackendUnavailable`. On a *hang* (init neither returns
    nor raises), the watchdog thread calls ``on_fail(reason)`` if given
    and then ``os._exit(exit_code)`` — the process cannot outlive
    ``deadline`` by more than a few seconds either way.

    ``DRAND_TPU_BACKEND_DEADLINE`` overrides ``deadline`` (seconds;
    ``0`` disables the watchdog entirely — for tests that fake time).
    """
    env = os.environ.get("DRAND_TPU_BACKEND_DEADLINE")
    if env is not None:
        deadline = float(env)
    if deadline <= 0:
        import jax

        return jax.default_backend(), jax.devices()

    done = threading.Event()
    # Margin so a fast-fail loop that is *about* to give up cleanly isn't
    # pre-empted by the hang watchdog.
    hang_deadline = deadline + 2 * retry_interval

    def _watchdog():
        if done.wait(hang_deadline):
            return
        reason = (f"backend init hung for {hang_deadline:.0f}s "
                  f"(tunnel down?); force-exiting")
        try:
            log(f"WATCHDOG: {reason}")
            if on_fail is not None:
                on_fail(reason)
        finally:
            os._exit(exit_code)

    threading.Thread(target=_watchdog, daemon=True,
                     name="backend-watchdog").start()

    t_end = time.monotonic() + deadline
    attempt = 0
    last_err: Optional[BaseException] = None
    while True:
        attempt += 1
        try:
            import jax

            devs = jax.devices()  # triggers backend init
            platform = jax.default_backend()
            done.set()
            if attempt > 1:
                log(f"backend up after {attempt} attempts: {platform}")
            return platform, devs
        except Exception as e:  # noqa: BLE001 — init raises RuntimeError
            last_err = e
            remaining = t_end - time.monotonic()
            if remaining <= 0:
                break
            log(f"backend init attempt {attempt} failed "
                f"({type(e).__name__}: {e}); retrying for {remaining:.0f}s")
            time.sleep(min(retry_interval, max(0.5, remaining)))
    done.set()
    msg = (f"backend unavailable after {attempt} attempts over "
           f"{deadline:.0f}s: {last_err}")
    if on_fail is not None:
        try:
            on_fail(msg)
        except Exception:  # noqa: BLE001 — never mask the real error
            pass
    raise BackendUnavailable(msg)
