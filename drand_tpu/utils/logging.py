"""Leveled key-value logger (reference: log/log.go — go-kit style kv pairs).

Lines emitted inside an active round-trace context (obs/trace.py) carry
``trace=<id> round=<r>`` automatically, so logs, metrics and the
/debug/trace timeline all join on the same correlation key.
"""

from __future__ import annotations

import logging
import sys

_FORMAT = "%(asctime)s %(levelname).1s %(name)s %(message)s"

# Accepts the standard aliases; anything unknown falls back to info —
# a bad config value must not crash daemon startup.
_LEVELS = {
    "none": logging.CRITICAL,
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warn": logging.WARNING,
    "warning": logging.WARNING,
    "error": logging.ERROR,
}


def _fmt_kv(args: tuple, kwargs: dict) -> str:
    parts = [str(a) for a in args]
    parts += [f"{k}={v}" for k, v in kwargs.items()]
    try:
        from ..obs import trace as _trace

        tid = _trace.current_trace_id()
        if tid is not None and "trace" not in kwargs:
            parts.append(f"trace={tid}")
            rnd = _trace.current_round()
            if rnd is not None and "round" not in kwargs:
                parts.append(f"round={rnd}")
    except Exception:  # noqa: BLE001 — logging must never raise
        pass
    return " ".join(parts)


class KVLogger:
    """logger.info("beacon_loop", round=12, last=11) style."""

    def __init__(self, name: str, level: int = logging.INFO):
        self._log = logging.getLogger(name)
        self._log.setLevel(level)

    def named(self, suffix: str) -> "KVLogger":
        return KVLogger(f"{self._log.name}.{suffix}", self._log.level)

    def debug(self, *args, **kwargs):
        self._log.debug(_fmt_kv(args, kwargs))

    def info(self, *args, **kwargs):
        self._log.info(_fmt_kv(args, kwargs))

    def warn(self, *args, **kwargs):
        self._log.warning(_fmt_kv(args, kwargs))

    def error(self, *args, **kwargs):
        self._log.error(_fmt_kv(args, kwargs))


def default_logger(name: str = "drand", level: str = "info") -> KVLogger:
    lvl = _LEVELS.get(str(level).lower(), logging.INFO)
    root = logging.getLogger()
    if not root.handlers:
        h = logging.StreamHandler(sys.stderr)
        h.setFormatter(logging.Formatter(_FORMAT))
        root.addHandler(h)
    return KVLogger(name, lvl)
