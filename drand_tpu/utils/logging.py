"""Leveled key-value logger (reference: log/log.go — go-kit style kv pairs)."""

from __future__ import annotations

import logging
import sys

_FORMAT = "%(asctime)s %(levelname).1s %(name)s %(message)s"


def _fmt_kv(args: tuple, kwargs: dict) -> str:
    parts = [str(a) for a in args]
    parts += [f"{k}={v}" for k, v in kwargs.items()]
    return " ".join(parts)


class KVLogger:
    """logger.info("beacon_loop", round=12, last=11) style."""

    def __init__(self, name: str, level: int = logging.INFO):
        self._log = logging.getLogger(name)
        self._log.setLevel(level)

    def named(self, suffix: str) -> "KVLogger":
        return KVLogger(f"{self._log.name}.{suffix}", self._log.level)

    def debug(self, *args, **kwargs):
        self._log.debug(_fmt_kv(args, kwargs))

    def info(self, *args, **kwargs):
        self._log.info(_fmt_kv(args, kwargs))

    def warn(self, *args, **kwargs):
        self._log.warning(_fmt_kv(args, kwargs))

    def error(self, *args, **kwargs):
        self._log.error(_fmt_kv(args, kwargs))


def default_logger(name: str = "drand", level: str = "info") -> KVLogger:
    lvl = {"none": logging.CRITICAL, "info": logging.INFO, "debug": logging.DEBUG}[level]
    root = logging.getLogger()
    if not root.handlers:
        h = logging.StreamHandler(sys.stderr)
        h.setFormatter(logging.Formatter(_FORMAT))
        root.addHandler(h)
    return KVLogger(name, lvl)
