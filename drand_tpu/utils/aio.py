"""Event-loop task discipline: the one sanctioned fire-and-forget entry.

``asyncio`` keeps only a WEAK reference to scheduled tasks — a bare
``asyncio.ensure_future(coro())`` whose return value is dropped can be
garbage-collected mid-flight, silently cancelling the work (the exact
bug PR 6 fixed by hand in the OTLP exporter). :func:`spawn` parks every
task in a module-level registry until it completes, so a background
task lives exactly as long as its coroutine, and logs any exception
that would otherwise vanish with the task object.

``tools/analyze``'s asyncsanity pass enforces this mechanically: a
discarded ``create_task``/``ensure_future`` result anywhere under
``drand_tpu/`` is a finding; routing the call through ``spawn`` is the
fix.
"""

from __future__ import annotations

import asyncio
from typing import Awaitable

_TASKS: set[asyncio.Future] = set()


def _on_done(task: asyncio.Future) -> None:
    _TASKS.discard(task)
    if task.cancelled():
        return
    exc = task.exception()
    if exc is None:
        return
    # a fire-and-forget task's exception has no awaiter to surface it;
    # without this hook it only appears at GC time (or never)
    from .logging import default_logger

    name = task.get_name() if hasattr(task, "get_name") else "task"
    default_logger("aio").error("spawn", "task_failed", task=name,
                                err=repr(exc))


def spawn(coro: Awaitable, *, name: str | None = None) -> asyncio.Future:
    """Schedule ``coro`` as a background task with a STRONG reference
    held until completion. Returns the task (callers may still await or
    cancel it; most drop it, which is the point)."""
    task = asyncio.ensure_future(coro)
    if name is not None and hasattr(task, "set_name"):
        task.set_name(name)
    # a task whose loop closed before it finished never runs _on_done;
    # keeping it here would pin its coroutine frame for the process
    # lifetime AND mute the destroyed-pending-task GC warning
    for t in [t for t in _TASKS if t.get_loop().is_closed()]:
        _TASKS.discard(t)
    _TASKS.add(task)
    task.add_done_callback(_on_done)
    return task


def pending_tasks() -> int:
    """How many spawned tasks are still in flight (introspection/tests)."""
    return len(_TASKS)
