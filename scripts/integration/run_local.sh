#!/usr/bin/env bash
# Local multi-process integration run — the analogue of the reference's
# test/test-integration/run_local.sh (docker-compose cluster there; plain
# processes here, same checks: DKG, beacon production, per-node agreement,
# client verification). TLS variant: run_local.sh --tls.
#
# Usage: scripts/integration/run_local.sh [--tls] [--nodes N] [--rounds R]
set -euo pipefail

NODES=3
ROUNDS=3
TLS=""
PERIOD=3
while [[ $# -gt 0 ]]; do
    case "$1" in
        --tls) TLS="--tls"; shift ;;
        --nodes) NODES="$2"; shift 2 ;;
        --rounds) ROUNDS="$2"; shift 2 ;;
        *) echo "unknown arg $1" >&2; exit 2 ;;
    esac
done

REPO="$(cd "$(dirname "$0")/../.." && pwd)"
export PYTHONPATH="$REPO"
WORK="$(mktemp -d /tmp/drand-tpu-integ.XXXXXX)"
echo "workdir: $WORK (nodes=$NODES rounds=$ROUNDS tls=${TLS:-no})"
cd "$WORK"

PIDS=()
cleanup() {
    for p in "${PIDS[@]:-}"; do kill "$p" 2>/dev/null || true; done
    wait 2>/dev/null || true
}
trap cleanup EXIT

BASE_NODE=26000
BASE_CTL=26100
BASE_HTTP=26200

for i in $(seq 0 $((NODES - 1))); do
    python -m drand_tpu.cli generate-keypair $TLS --folder "n$i" \
        "127.0.0.1:$((BASE_NODE + i))" > /dev/null
done

if [[ -n "$TLS" ]]; then
    # pre-generate each node's self-signed cert (the daemon would create
    # it on first --tls start) and distribute into every trusted pool
    for i in $(seq 0 $((NODES - 1))); do
        python - "$i" "$((BASE_NODE + i))" <<'EOF'
import sys
from drand_tpu.net import tls
i, port = sys.argv[1], sys.argv[2]
tls.generate_self_signed(f"127.0.0.1:{port}", f"n{i}/tls")
EOF
    done
    for i in $(seq 0 $((NODES - 1))); do
        mkdir -p "n$i/tls/trusted"
        for j in $(seq 0 $((NODES - 1))); do
            [[ "$i" == "$j" ]] && continue
            cp "n$j/tls/cert.pem" "n$i/tls/trusted/n$j.pem"
        done
    done
fi

for i in $(seq 0 $((NODES - 1))); do
    args=(start --folder "n$i" --control $((BASE_CTL + i)) --dkg-timeout 5)
    [[ -n "$TLS" ]] && args+=(--tls)
    [[ "$i" == 0 ]] && args+=(--public-listen "127.0.0.1:$BASE_HTTP")
    python -m drand_tpu.cli "${args[@]}" > "d$i.log" 2>&1 &
    PIDS+=($!)
done
sleep 3

echo "secret-0123456789abcdef0" > secret
python -m drand_tpu.cli share --control "$BASE_CTL" --leader \
    --nodes "$NODES" --threshold $(((NODES / 2) + 1)) --period "$PERIOD" \
    --secret-file secret --timeout 30 > leader.json &
SHARE_PIDS=($!)
for i in $(seq 1 $((NODES - 1))); do
    python -m drand_tpu.cli share --control $((BASE_CTL + i)) \
        --connect "127.0.0.1:$BASE_NODE" --secret-file secret \
        --timeout 30 > "f$i.json" &
    SHARE_PIDS+=($!)
done
for p in "${SHARE_PIDS[@]}"; do wait "$p"; done
echo "DKG complete"

# genesis = now + alignment; wait for ROUNDS beacons, then fetch each
# through the verifying client stack (verification happens client-side)
sleep $((35 + PERIOD * ROUNDS))

for i in $(seq 1 "$ROUNDS"); do
    out=$(python -m drand_tpu.cli get public \
        --url "http://127.0.0.1:$BASE_HTTP" --round "$i")
    echo "round $i verified: $(echo "$out" | python -c \
        'import json,sys; print(json.load(sys.stdin)["randomness"][:16])')"
done

# per-node agreement on the last round via each control port
python -m drand_tpu.cli util check "127.0.0.1:$BASE_NODE" > /dev/null \
    2>&1 || true
code=$(curl -s -o /dev/null -w "%{http_code}" \
    "http://127.0.0.1:$BASE_HTTP/health")
[[ "$code" == "200" ]] || { echo "health check failed: $code"; exit 1; }

echo "INTEGRATION OK (nodes=$NODES rounds=$ROUNDS tls=${TLS:-no})"
