#!/bin/bash
# A/B the round-5 perf features on the real chip (VERDICT r4 item 1).
#
# Runs the headline config under each knob combination, interleaved so
# the tunnel's minute-scale load variance hits all variants alike, and
# prints one JSON line per run (knobs are embedded in each record).
# Usage:  scripts/ab_bench.sh [trials_per_variant]
set -u
cd "$(dirname "$0")/.."
REPS=${1:-2}
export BENCH_CONFIGS=headline BENCH_BATCH=${BENCH_BATCH:-128} BENCH_TRIALS=${BENCH_TRIALS:-2}
VARIANTS=(
  "DRAND_TPU_LAZY=1 DRAND_TPU_PAIRFOLD=1 DRAND_TPU_CONV=tree"   # full r5
  "DRAND_TPU_LAZY=0 DRAND_TPU_PAIRFOLD=1 DRAND_TPU_CONV=tree"   # -lazy
  "DRAND_TPU_LAZY=1 DRAND_TPU_PAIRFOLD=0 DRAND_TPU_CONV=tree"   # -pairfold
  "DRAND_TPU_LAZY=0 DRAND_TPU_PAIRFOLD=0 DRAND_TPU_CONV=tree"   # r4 tree
  "DRAND_TPU_LAZY=0 DRAND_TPU_PAIRFOLD=0 DRAND_TPU_CONV=unroll" # r3 base
)
for rep in $(seq 1 "$REPS"); do
  for v in "${VARIANTS[@]}"; do
    pkill -f "python bench.py" 2>/dev/null; sleep 1
    echo "### rep $rep: $v" >&2
    env $v python bench.py 2>>/tmp/ab_bench.err | tail -1
  done
done
