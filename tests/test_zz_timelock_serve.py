"""Timelock serving tier (ISSUE 9): crypto, vault, service, HTTP, engine.

Late-alphabet name per the tier-1 chunking convention (ROADMAP): the one
device test compiles the shared-signature GT graph, which dominates its
chunk — run via tools/tier1_chunks.sh.

Covers the adversarial matrix (wrong-round signature, truncated V,
flipped W byte, pre-V2 beacon, cross-chain ciphertext, empty/large
plaintext, malformed/swapped U), the accept/reject bit-identity between
the batched tiers and the per-item host oracle, the ONE-dispatch meter
proof, the KAT-failure fallback ledger, and vault persistence across a
simulated daemon restart.
"""

from __future__ import annotations

import asyncio
import base64

import pytest

from drand_tpu.chain.beacon import Beacon, message, message_v2
from drand_tpu.chain.info import Info
from drand_tpu.client import timelock as client_timelock
from drand_tpu.client.interface import Client, ClientError, Result
from drand_tpu.crypto import batch, bls
from drand_tpu.crypto import pairing as host_pairing
from drand_tpu.crypto import timelock as tl
from drand_tpu.crypto.curves import PointG1
from drand_tpu.crypto.fields import R
from drand_tpu.crypto.hash_to_curve import hash_to_g2

SK, PUB = bls.keygen(seed=b"zz-timelock-tests")
INFO = Info(public_key=PUB, period=3, genesis_time=1_700_000_000,
            genesis_seed=b"\x07" * 32)
ROUND = 42
IDENT = message_v2(ROUND)
SIG_BYTES = bls.sign(SK, IDENT)


def _result(rd: int, v2: bool = True) -> Result:
    return Result(round=rd, signature=bls.sign(SK, message(rd, b"prev")),
                  signature_v2=bls.sign(SK, message_v2(rd)) if v2 else b"")


def _oracle_outcomes(sig_bytes: bytes, cts) -> list[tuple[bool, bytes]]:
    """The per-item host oracle's verdicts, as (ok, plaintext)."""
    out = []
    for ct in cts:
        try:
            out.append((True, tl.decrypt(sig_bytes, ct)))
        except ValueError:
            out.append((False, b""))
    return out


def _adversarial_matrix():
    """(label, Ciphertext) rows: the ISSUE 9 matrix, built against the
    round's real key material."""
    ok_ct = tl.encrypt(PUB, IDENT, b"sealed bid: 417")
    empty = tl.encrypt(PUB, IDENT, b"")
    large = tl.encrypt(PUB, IDENT, b"\xa5" * 65536)
    flipped_w = tl.Ciphertext(ok_ct.u, ok_ct.v,
                              bytes([ok_ct.w[0] ^ 1]) + ok_ct.w[1:])
    trunc_v = tl.Ciphertext(ok_ct.u, ok_ct.v[:-1], ok_ct.w)
    bad_u = tl.Ciphertext(b"\xff" * 48, ok_ct.v, ok_ct.w)
    swapped_u = tl.Ciphertext(PointG1.generator().mul(12345).to_bytes(),
                              ok_ct.v, ok_ct.w)
    return [("ok", ok_ct), ("empty", empty), ("large", large),
            ("flipped_w", flipped_w), ("trunc_v", trunc_v),
            ("bad_u", bad_u), ("swapped_u", swapped_u)]


# ---------------------------------------------------------------- crypto

def test_envelope_carries_version_and_future_versions_fail_closed():
    env = client_timelock.encrypt_to_round(INFO, ROUND, b"x")
    assert env["v"] == client_timelock.SCHEME_VERSION
    r = _result(ROUND)
    assert client_timelock.decrypt_with_beacon(env, r, info=INFO) == b"x"
    env2 = dict(env)
    env2["v"] = 2
    with pytest.raises(ClientError, match="scheme version"):
        client_timelock.decrypt_with_beacon(env2, r)


def test_cross_chain_ciphertext_rejected():
    env = client_timelock.encrypt_to_round(INFO, ROUND, b"x")
    other = Info(public_key=PUB, period=7, genesis_time=1_600_000_000,
                 genesis_seed=b"\x08" * 32)
    with pytest.raises(ClientError, match="cross-chain"):
        client_timelock.decrypt_with_beacon(env, _result(ROUND),
                                            info=other)
    # without info the check cannot run (legacy callers) — still decrypts
    assert client_timelock.decrypt_with_beacon(env, _result(ROUND)) == b"x"


def test_wrong_round_and_pre_v2_beacon_rejected():
    env = client_timelock.encrypt_to_round(INFO, ROUND, b"x")
    with pytest.raises(ClientError, match="need round"):
        client_timelock.decrypt_with_beacon(env, _result(ROUND - 1))
    with pytest.raises(ClientError, match="no V2 signature"):
        client_timelock.decrypt_with_beacon(env, _result(ROUND, v2=False))


def test_gen_mul_comb_matches_generic_mul():
    g = PointG1.generator()
    for k in (0, 1, 2, 255, 256, (1 << 128) - 1, R - 1, R, R + 5):
        assert tl._gen_mul(k) == g.mul(k % R), k


def test_gt_base_cache_counts_hits_and_misses():
    tl.gt_cache_clear()
    before = tl.gt_cache_info()
    tl.encrypt(PUB, b"gt-cache-probe", b"a")
    tl.encrypt(PUB, b"gt-cache-probe", b"b")
    tl.encrypt(PUB, b"gt-cache-probe-2", b"c")
    info = tl.gt_cache_info()
    assert info["misses"] - before["misses"] == 2
    assert info["hits"] - before["hits"] == 1
    from drand_tpu import metrics

    text = metrics.render().decode()
    assert 'timelock_gt_cache_requests_total{result="hit"}' in text
    assert 'timelock_gt_cache_requests_total{result="miss"}' in text


def test_round_decryptor_gt_equals_canonical_pairing():
    rd = tl.RoundDecryptor(SIG_BYTES)
    ct = tl.encrypt(PUB, IDENT, b"gt-equality")
    u = PointG1.from_bytes(ct.u)
    sig_pt = rd.sig
    assert rd.gt(u) == host_pairing.pairing(u, sig_pt)
    assert rd.decrypt(ct) == b"gt-equality"


def test_host_batch_bit_identical_to_oracle_across_matrix():
    labels, cts = zip(*_adversarial_matrix())
    oracle = _oracle_outcomes(SIG_BYTES, cts)
    c0, p0 = host_pairing.N_PRODUCT_CHECKS, host_pairing.N_MILLER_PAIRS
    got = tl.decrypt_batch(SIG_BYTES, cts)
    # one shared-line pass for the whole round at the host meter
    assert host_pairing.N_PRODUCT_CHECKS - c0 == 1
    assert [(ok, pt) for ok, pt, _ in got] == oracle, labels
    expected = dict(zip(labels, (ok for ok, _ in oracle)))
    assert expected == {"ok": True, "empty": True, "large": True,
                        "flipped_w": False, "trunc_v": False,
                        "bad_u": False, "swapped_u": False}
    # wrong-round signature: everything rejects, identically
    wrong = bls.sign(SK, message_v2(ROUND + 1))
    oracle_w = _oracle_outcomes(wrong, cts)
    got_w = tl.decrypt_batch(wrong, cts)
    assert [(ok, pt) for ok, pt, _ in got_w] == oracle_w
    assert not any(ok for ok, _ in oracle_w)


# ----------------------------------------------------------------- vault

def test_vault_roundtrip_and_opened_rows_are_immutable(tmp_path):
    from drand_tpu.timelock import TimelockVault, VaultError

    v = TimelockVault(str(tmp_path / "tl.db"))
    env = client_timelock.encrypt_to_round(INFO, 9, b"x")
    assert v.submit("tok-1", 9, env) is True
    assert v.submit("tok-1", 9, env) is False  # idempotent
    assert v.pending_rounds() == [9]
    assert v.pending_rounds(up_to=8) == []
    assert v.pending_for_round(9)[0][0] == "tok-1"
    v.set_opened("tok-1", b"plain")
    rec = v.get("tok-1")
    assert rec["status"] == "opened" and rec["plaintext"] == b"plain"
    with pytest.raises(VaultError):
        v.set_opened("tok-1", b"other")
    with pytest.raises(VaultError):
        v.set_rejected("tok-1", "nope")
    v.close()


# --------------------------------------------------------------- service

class FakeChain(Client):
    """Hand-advanced chain for service tests."""

    def __init__(self, head: int = 1, v2: bool = True):
        self.head = head
        self.v2 = v2

    async def get(self, round_no: int = 0) -> Result:
        rd = self.head if round_no == 0 else round_no
        if rd > self.head:
            raise ClientError(f"round {rd} not yet produced")
        return _result(rd, v2=self.v2)

    async def info(self) -> Info:
        return INFO


@pytest.fixture()
def host_mode():
    """Pin the dispatcher to host crypto (a service test must not probe
    or compile a device engine)."""
    old = (batch._MODE, batch._MIN_BATCH, batch._ENGINE)
    batch.configure("host")
    yield
    batch._MODE, batch._MIN_BATCH, batch._ENGINE = old


@pytest.mark.asyncio
async def test_service_open_at_boundary_and_restart_persistence(
        tmp_path, host_mode):
    from drand_tpu.timelock import TimelockService, TimelockVault

    db = str(tmp_path / "tl.db")
    chain = FakeChain(head=1)
    svc = TimelockService(TimelockVault(db), chain)
    await svc.start()
    env = client_timelock.encrypt_to_round(INFO, 3, b"till round 3")
    rec = await svc.submit(env)
    assert rec["status"] == "pending"
    token = rec["id"]

    # simulated daemon restart mid-wait: state comes back from sqlite
    await svc.close()
    svc = TimelockService(TimelockVault(db), chain)
    await svc.start()
    assert (await svc.status(token))["status"] == "pending"

    # the chain reaches round 3: boundary hook opens it
    chain.head = 3
    svc.on_result(await chain.get(3))
    for _ in range(200):
        await asyncio.sleep(0.02)
        rec = await svc.status(token)
        if rec["status"] != "pending":
            break
    assert rec["status"] == "opened"
    assert base64.b64decode(rec["plaintext"]) == b"till round 3"
    await svc.close()


@pytest.mark.asyncio
async def test_service_validation_and_pre_v2_stays_pending(
        tmp_path, host_mode):
    from drand_tpu.timelock import (TimelockError, TimelockService,
                                    TimelockVault)

    chain = FakeChain(head=1, v2=False)
    svc = TimelockService(TimelockVault(str(tmp_path / "tl.db")), chain)
    await svc.start()
    env = client_timelock.encrypt_to_round(INFO, 2, b"x")
    # cross-chain: bound to another chain hash
    bad = dict(env)
    bad["chain_hash"] = "ab" * 32
    with pytest.raises(TimelockError, match="cross-chain"):
        await svc.submit(bad)
    # a non-string chain_hash is a validation error, not a 500
    bad_t = dict(env)
    bad_t["chain_hash"] = 123
    with pytest.raises(TimelockError, match="hex string"):
        await svc.submit(bad_t)
    # future scheme version fails closed
    bad_v = dict(env)
    bad_v["v"] = 9
    with pytest.raises(TimelockError, match="scheme version"):
        await svc.submit(bad_v)
    # oversize payload
    big = client_timelock.encrypt_to_round(
        INFO, 2, b"\x00" * (tl.SIGMA_LEN + 70000))
    import drand_tpu.timelock.service as svc_mod

    assert svc_mod.MAX_PLAINTEXT == 65536
    with pytest.raises(TimelockError, match="too large"):
        await svc.submit(big)
    # a beacon without a V2 signature (pre-V2 era, or a source that
    # omitted the field) must NEVER decide the ciphertext: opened and
    # rejected rows are immutable, so it stays pending for a source
    # that can serve the signature
    rec = await svc.submit(env)
    chain.head = 2
    svc.on_result(await chain.get(2))
    await asyncio.sleep(0.3)
    got = await svc.status(rec["id"])
    assert got["status"] == "pending"
    # the same round from a V2-serving source then opens it
    chain.v2 = True
    svc.on_result(await chain.get(2))
    for _ in range(200):
        await asyncio.sleep(0.02)
        got = await svc.status(rec["id"])
        if got["status"] != "pending":
            break
    assert got["status"] == "opened"
    await svc.close()


@pytest.mark.asyncio
async def test_store_hook_note_round_complete(tmp_path, host_mode):
    """The DiscrepancyStore path: storing a beacon fires the registered
    service's boundary sweep (daemon deployments need no watch loop)."""
    from drand_tpu.chain.store import DiscrepancyStore, MemStore
    from drand_tpu.timelock import TimelockService, TimelockVault

    class _Group:
        period = INFO.period
        genesis_time = INFO.genesis_time

        @staticmethod
        def get_genesis_seed():
            return INFO.genesis_seed

    class _Clock:
        @staticmethod
        def now():
            return INFO.genesis_time + 2 * INFO.period

    chain = FakeChain(head=2)
    svc = TimelockService(TimelockVault(str(tmp_path / "tl.db")), chain)
    await svc.start()
    rec = await svc.submit(client_timelock.encrypt_to_round(INFO, 2, b"s"))
    store = DiscrepancyStore(MemStore(), _Group, _Clock)
    r2 = _result(2)
    store.put(Beacon(round=2, previous_sig=b"prev",
                     signature=r2.signature, signature_v2=r2.signature_v2))
    for _ in range(200):
        await asyncio.sleep(0.02)
        got = await svc.status(rec["id"])
        if got["status"] != "pending":
            break
    assert got["status"] == "opened"
    assert base64.b64decode(got["plaintext"]) == b"s"
    await svc.close()


def test_envelope_token_collapses_malleable_encodings():
    """One ciphertext must map to ONE vault row: hex case, junk keys,
    omitted-vs-explicit version and bool-typed round are all the same
    submission (otherwise a client floods the backlog cap from a single
    ciphertext by varying the encoding per POST)."""
    from drand_tpu.timelock.service import envelope_token

    env = client_timelock.encrypt_to_round(INFO, ROUND, b"one ct")
    tok = envelope_token(env)
    upper = dict(env)
    upper["U"] = env["U"].upper()
    junk = dict(env)
    junk["junk_key"] = "x" * 100
    no_v = {k: v for k, v in env.items() if k != "v"}
    bool_round = dict(env)
    bool_round["round"] = True
    assert envelope_token(upper) == tok
    assert envelope_token(junk) == tok
    # round collapses to its int value; the rest of the envelope pins it
    assert envelope_token(no_v) == tok
    env_r1 = dict(env)
    env_r1["round"] = 1
    assert envelope_token(bool_round) == envelope_token(env_r1) != tok
    # a genuinely different ciphertext gets a different token
    assert envelope_token(
        client_timelock.encrypt_to_round(INFO, ROUND, b"other")) != tok


# ------------------------------------------------------------------ http

@pytest.mark.asyncio
async def test_http_routes_submit_status_etag(tmp_path, host_mode):
    from aiohttp.test_utils import TestClient, TestServer

    from drand_tpu.http_server.server import PublicServer
    from drand_tpu.timelock import TimelockService, TimelockVault

    chain = FakeChain(head=1)
    svc = TimelockService(TimelockVault(str(tmp_path / "tl.db")), chain)
    server = PublicServer(chain, timelock_service=svc)
    tc = TestClient(TestServer(server.app))
    await tc.start_server()
    await svc.start()
    try:
        env = client_timelock.encrypt_to_round(INFO, 3, b"webhook")
        r = await tc.post("/timelock", json=env)
        assert r.status == 202
        token = (await r.json())["id"]
        # resubmission is idempotent (content-derived id)
        assert (await (await tc.post("/timelock", json=env)).json())[
            "id"] == token
        # malformed / cross-chain / unknown-id error paths
        assert (await tc.post("/timelock", data=b"not json")).status == 400
        bad = dict(env)
        bad["chain_hash"] = "cd" * 32
        assert (await tc.post("/timelock", json=bad)).status == 400
        assert (await tc.get("/timelock/deadbeef")).status == 404
        st = await tc.get(f"/timelock/{token}")
        assert (await st.json())["status"] == "pending"
        assert st.headers["Cache-Control"] == "no-store"
        # the boundary: opened results are immutable + ETag'd
        chain.head = 3
        svc.on_result(await chain.get(3))
        for _ in range(200):
            await asyncio.sleep(0.02)
            body = await (await tc.get(f"/timelock/{token}")).json()
            if body["status"] != "pending":
                break
        assert body["status"] == "opened"
        assert base64.b64decode(body["plaintext"]) == b"webhook"
        resp = await tc.get(f"/timelock/{token}")
        assert "immutable" in resp.headers["Cache-Control"]
        etag = resp.headers["ETag"]
        cached = await tc.get(f"/timelock/{token}",
                              headers={"If-None-Match": etag})
        assert cached.status == 304
    finally:
        await svc.close()
        await tc.close()


# ---------------------------------------------------------------- engine

def test_kat_failure_falls_back_to_host_with_ledger_entry(monkeypatch):
    """A device engine whose timelock KAT fails must never decide the
    round: the dispatcher falls back to the host shared-signature tier
    and records it in the fallback ledger."""
    from drand_tpu.ops.engine import BatchedEngine

    eng = BatchedEngine(buckets=(4,))
    monkeypatch.setattr(eng, "_check_tl_bucket", lambda b: False)
    old = (batch._MODE, batch._MIN_BATCH, batch._ENGINE)
    batch.configure("device", min_batch=1, engine=eng)
    batch.reset_fallback_ledger()
    try:
        cts = [tl.encrypt(PUB, IDENT, b"kat-fb-%d" % i) for i in range(3)]
        out = batch.decrypt_round_batch(SIG_BYTES, cts)
        assert [(ok, pt) for ok, pt, _ in out] == \
            _oracle_outcomes(SIG_BYTES, cts)
        led = batch.fallback_ledger()
        assert led and led[-1]["op"] == "timelock"
        assert "known-answer" in led[-1]["reason"]
    finally:
        batch._MODE, batch._MIN_BATCH, batch._ENGINE = old
        batch.reset_fallback_ledger()


def test_device_round_open_one_dispatch_meter_and_oracle_identical():
    """The acceptance proof: K pending ciphertexts (including the
    adversarial rows) open via ONE batched engine dispatch — 1 product
    check, one Miller pair per live lane at the device meter — with
    accept/reject bools bit-identical to the per-item host oracle, under
    engine_op_seconds{op="timelock", path="device"}. Compile-heavy (the
    shared-signature GT graph)."""
    from conftest import sample_count

    from drand_tpu import metrics
    from drand_tpu.ops import engine as eng_mod
    from drand_tpu.ops.engine import BatchedEngine

    labels, cts = zip(*_adversarial_matrix())
    oracle = _oracle_outcomes(SIG_BYTES, cts)
    eng = BatchedEngine(buckets=(8,))
    old = (batch._MODE, batch._MIN_BATCH, batch._ENGINE)
    batch.configure("device", min_batch=1, engine=eng)
    try:
        # first dispatch pays compile + KAT and lands in
        # engine_compile_seconds (the ISSUE-6 split); re-dispatch for
        # the metered steady-state window
        out = batch.decrypt_round_batch(SIG_BYTES, cts)
        assert [(ok, pt) for ok, pt, _ in out] == oracle, labels
        c0, p0 = eng_mod.N_PRODUCT_CHECKS, eng_mod.N_MILLER_PAIRS
        bucket = metrics.batch_bucket(len(cts))
        h0 = sample_count(metrics.REGISTRY, "engine_op_seconds",
                          op="timelock", path="device", batch=bucket)
        out2 = batch.decrypt_round_batch(SIG_BYTES, cts)
        assert [(ok, pt) for ok, pt, _ in out2] == oracle
        # bad_u never decodes, so 6 of the 7 rows ride the batch; ONE
        # dispatch total
        assert eng_mod.N_PRODUCT_CHECKS - c0 == 1
        assert eng_mod.N_MILLER_PAIRS - p0 == 6
        assert eng.introspect()["kat"]["timelock"] == {"8": True}
        assert sample_count(metrics.REGISTRY, "engine_op_seconds",
                            op="timelock", path="device",
                            batch=bucket) == h0 + 1
    finally:
        batch._MODE, batch._MIN_BATCH, batch._ENGINE = old


# ------------------------------------------------------------- gRPC mirror

@pytest.mark.asyncio
async def test_grpc_timelock_submit_status_mirror(tmp_path, host_mode):
    """The drand.Public TimelockSubmit/TimelockStatus methods mirror
    POST /timelock + GET /timelock/{id} for non-HTTP clients (ISSUE 11
    satellite, PR-9 carry-over): same envelope JSON in, same status
    record out, the SAME TimelockService.submit canonicalization path
    (idempotent token across encodings), and the HTTP error taxonomy
    mapped onto grpc codes. A node without a vault answers
    UNIMPLEMENTED."""
    import grpc

    from drand_tpu.net.grpc_transport import GrpcClient, GrpcGateway
    from drand_tpu.net.transport import ProtocolService, TransportError
    from drand_tpu.timelock import TimelockService, TimelockVault

    chain = FakeChain(head=1)
    svc = TimelockService(TimelockVault(str(tmp_path / "tl.db")), chain)
    gw = GrpcGateway(ProtocolService(), "127.0.0.1:0",
                     timelock_service=svc)
    await gw.start()
    await svc.start()
    cli = GrpcClient(own_addr="tester:0")
    target = f"127.0.0.1:{gw.port}"
    try:
        env = client_timelock.encrypt_to_round(INFO, 3, b"grpc sealed")
        rec = await cli.timelock_submit(target, env)
        assert rec["status"] == "pending" and rec["round"] == 3
        token = rec["id"]
        # idempotent resubmission — the HTTP tier's content-derived
        # token, because it IS the HTTP tier's submit path
        assert (await cli.timelock_submit(target, env))["id"] == token
        # status roundtrip + unknown id -> None (NOT_FOUND)
        st = await cli.timelock_status(target, token)
        assert st["status"] == "pending" and st["id"] == token
        assert await cli.timelock_status(target, "deadbeef") is None
        # validation errors map to INVALID_ARGUMENT
        bad = dict(env)
        bad["chain_hash"] = "cd" * 32
        with pytest.raises(TransportError, match="INVALID_ARGUMENT"):
            await cli.timelock_submit(target, bad)
        with pytest.raises(TransportError, match="INVALID_ARGUMENT"):
            raw = cli._channel(target)[0].unary_unary(
                "/drand.Public/TimelockSubmit")
            try:
                await raw(b"not json", timeout=5.0)
            except grpc.aio.AioRpcError as e:
                raise TransportError(
                    f"TimelockSubmit: {e.code().name}") from e
        # the boundary opens it; the gRPC status serves the plaintext
        chain.head = 3
        svc.on_result(await chain.get(3))
        for _ in range(200):
            await asyncio.sleep(0.02)
            st = await cli.timelock_status(target, token)
            if st["status"] != "pending":
                break
        assert st["status"] == "opened"
        assert base64.b64decode(st["plaintext"]) == b"grpc sealed"
    finally:
        await cli.close()
        await svc.close()
        await gw.stop()

    # a gateway with no vault attached answers UNIMPLEMENTED
    gw2 = GrpcGateway(ProtocolService(), "127.0.0.1:0")
    await gw2.start()
    cli2 = GrpcClient(own_addr="tester:0")
    try:
        with pytest.raises(TransportError, match="UNIMPLEMENTED"):
            await cli2.timelock_submit(f"127.0.0.1:{gw2.port}",
                                       {"round": 3})
    finally:
        await cli2.close()
        await gw2.stop()
