"""Metrics: discrepancy store decorator, /metrics scrape surface, the
round-tracing stage/op histograms, and the static catalogue lint."""

import pathlib
import sys

import aiohttp
import pytest
from conftest import sample_count as _sample_count

from drand_tpu import metrics
from drand_tpu.client.direct import DirectClient
from drand_tpu.crypto import batch
from drand_tpu.http_server.server import PublicServer
from drand_tpu.testing.harness import BeaconTestNetwork

N, T, PERIOD = 3, 2, 5

STAGES = ("partial", "collect", "recover", "verify", "store")


@pytest.mark.asyncio
async def test_discrepancy_and_scrape():
    net = BeaconTestNetwork(n=N, t=T, period=PERIOD)
    await net.start_all()
    await net.advance_to_genesis()
    for _ in range(2):
        await net.clock.advance(PERIOD)
    for i in range(N):
        await net.wait_round(i, 2)
    try:
        # the discrepancy store fed the gauges while rounds were produced
        assert metrics.LAST_BEACON_ROUND._value.get() >= 2
        # fake clock: beacons land "instantly" at the round boundary
        assert abs(metrics.BEACON_DISCREPANCY_LATENCY._value.get()) < 10_000

        server = PublicServer(DirectClient(net.nodes[0].handler),
                              clock=net.clock)
        site = await server.start("127.0.0.1", 0)
        port = site._server.sockets[0].getsockname()[1]
        async with aiohttp.ClientSession() as sess:
            async with sess.get(f"http://127.0.0.1:{port}/public/1") as r:
                assert r.status == 200
            async with sess.get(f"http://127.0.0.1:{port}/metrics") as r:
                assert r.status == 200
                body = await r.text()
        assert "last_beacon_round" in body
        assert "beacon_discrepancy_latency_ms" in body
        assert "http_api_requests" in body
        # the tracing histograms ride the same scrape surface
        assert "beacon_stage_seconds" in body
        await server.stop()
    finally:
        net.stop_all()


@pytest.mark.asyncio
async def test_stage_histograms_emitted_by_harness_round():
    """Every named pipeline stage lands beacon_stage_seconds samples
    while a round is produced (the tentpole's continuous perf surface)."""
    before = {s: _sample_count(metrics.GROUP_REGISTRY,
                               "beacon_stage_seconds", stage=s)
              for s in STAGES}
    net = BeaconTestNetwork(n=N, t=T, period=PERIOD)
    await net.start_all()
    await net.advance_to_genesis()
    await net.clock.advance(PERIOD)
    for i in range(N):
        await net.wait_round(i, 1)
    try:
        for s in STAGES:
            after = _sample_count(metrics.GROUP_REGISTRY,
                                  "beacon_stage_seconds", stage=s)
            assert after > before[s], f"no {s!r} stage samples"
    finally:
        net.stop_all()


class _FakeEngine:
    """Minimal device engine: enough surface for the dispatch wrappers."""

    def verify_partials(self, pub_poly, msg, partials, dst=None):
        return [True] * len(partials)


def test_engine_dispatch_metrics():
    """engine_device_batches (the ISSUE 1 dead-metric fix) and
    engine_op_seconds{op,path,batch} move at the dispatch sites; the
    FIRST dispatch of a cold device shape lands in
    engine_compile_seconds{op} instead (ISSUE 6 compile split), so the
    steady-state series only moves from the second call on."""
    old = (batch._MODE, batch._MIN_BATCH, batch._ENGINE)
    batch.configure("device", min_batch=1, engine=_FakeEngine())
    try:
        b0 = _sample_count(metrics.REGISTRY, "engine_device_batches",
                           op="verify_partials")
        assert batch.verify_partials(None, b"m", [b"p1", b"p2"]) == [True, True]
        # shape (verify_partials, device, "8") is warm now — whether this
        # call or an earlier test paid the compile sample
        d1 = _sample_count(metrics.REGISTRY, "engine_op_seconds",
                           op="verify_partials", path="device", batch="8")
        assert batch.verify_partials(None, b"m", [b"p1", b"p2"]) == [True, True]
        assert _sample_count(metrics.REGISTRY, "engine_device_batches",
                             op="verify_partials") == b0 + 2
        assert _sample_count(metrics.REGISTRY, "engine_op_seconds",
                             op="verify_partials", path="device",
                             batch="8") == d1 + 1
        assert _sample_count(metrics.REGISTRY, "engine_compile_seconds",
                             op="verify_partials") >= 1
    finally:
        batch._MODE, batch._MIN_BATCH, batch._ENGINE = old


def test_batch_bucket_bounds():
    assert metrics.batch_bucket(1) == "1"
    assert metrics.batch_bucket(2) == "8"
    assert metrics.batch_bucket(8) == "8"
    assert metrics.batch_bucket(129) == "512"
    assert metrics.batch_bucket(4096) == "512+"


def test_metrics_lint():
    """tools/check_metrics.py from tier-1: every declared metric is
    referenced outside its declaration; names unique across registries."""
    tools = pathlib.Path(__file__).resolve().parent.parent / "tools"
    sys.path.insert(0, str(tools))
    try:
        import check_metrics

        assert check_metrics.run_lint() == []
    finally:
        sys.path.remove(str(tools))
