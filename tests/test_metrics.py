"""Metrics: discrepancy store decorator + /metrics scrape surface."""

import aiohttp
import pytest

from drand_tpu import metrics
from drand_tpu.client.direct import DirectClient
from drand_tpu.http_server.server import PublicServer
from drand_tpu.testing.harness import BeaconTestNetwork

N, T, PERIOD = 3, 2, 5


@pytest.mark.asyncio
async def test_discrepancy_and_scrape():
    net = BeaconTestNetwork(n=N, t=T, period=PERIOD)
    await net.start_all()
    await net.advance_to_genesis()
    for _ in range(2):
        await net.clock.advance(PERIOD)
    for i in range(N):
        await net.wait_round(i, 2)
    try:
        # the discrepancy store fed the gauges while rounds were produced
        assert metrics.LAST_BEACON_ROUND._value.get() >= 2
        # fake clock: beacons land "instantly" at the round boundary
        assert abs(metrics.BEACON_DISCREPANCY_LATENCY._value.get()) < 10_000

        server = PublicServer(DirectClient(net.nodes[0].handler),
                              clock=net.clock)
        site = await server.start("127.0.0.1", 0)
        port = site._server.sockets[0].getsockname()[1]
        async with aiohttp.ClientSession() as sess:
            async with sess.get(f"http://127.0.0.1:{port}/public/1") as r:
                assert r.status == 200
            async with sess.get(f"http://127.0.0.1:{port}/metrics") as r:
                assert r.status == 200
                body = await r.text()
        assert "last_beacon_round" in body
        assert "beacon_discrepancy_latency_ms" in body
        assert "http_api_requests" in body
        await server.stop()
    finally:
        net.stop_all()
