"""Protobuf wire interop: byte-layout goldens and a live round-trip over
the drand.Public service + protobuf SyncChain.

Reference layouts: protobuf/drand/api.proto:36-55 (PublicRandResponse),
protocol.proto:84-92 (SyncRequest/BeaconPacket), common.proto:44-60
(ChainInfoPacket). The golden byte strings below are hand-derived from
the proto3 wire spec (tag = field<<3|type, varint, length-delimited) —
they pin OUR encoder to the ecosystem layout without generated code.
"""

import asyncio

import pytest

from drand_tpu.net import protowire as pw


# ---------------------------------------------------------------------------
# golden bytes
# ---------------------------------------------------------------------------

def test_public_rand_request_bytes():
    # round = 7 -> field 1 varint: tag 0x08, value 0x07
    assert pw.encode(pw.PUBLIC_RAND_REQUEST, {"round": 7}) == b"\x08\x07"
    # round = 0 is the proto3 default: empty message
    assert pw.encode(pw.PUBLIC_RAND_REQUEST, {"round": 0}) == b""
    assert pw.decode(pw.PUBLIC_RAND_REQUEST, b"\x08\x07") == {"round": 7}
    assert pw.decode(pw.PUBLIC_RAND_REQUEST, b"") == {"round": 0}


def test_public_rand_response_bytes():
    vals = {"round": 300, "signature": b"\xaa\xbb",
            "previous_signature": b"\xcc",
            "randomness": b"\x01\x02", "signature_v2": b"\xdd"}
    # field 1 varint 300 = 0xAC 0x02; field 2 len: 0x12 0x02 aa bb;
    # field 3: 0x1a 0x01 cc; field 4: 0x22 0x02 01 02; field 5: 0x2a 0x01 dd
    expect = (b"\x08\xac\x02" b"\x12\x02\xaa\xbb" b"\x1a\x01\xcc"
              b"\x22\x02\x01\x02" b"\x2a\x01\xdd")
    assert pw.encode(pw.PUBLIC_RAND_RESPONSE, vals) == expect
    assert pw.decode(pw.PUBLIC_RAND_RESPONSE, expect) == vals


def test_sync_request_and_beacon_packet_bytes():
    assert pw.encode(pw.SYNC_REQUEST, {"from_round": 1}) == b"\x08\x01"
    b = pw.encode(pw.BEACON_PACKET,
                  {"previous_sig": b"\x11", "round": 2,
                   "signature": b"\x22\x33"})
    assert b == b"\x0a\x01\x11" b"\x10\x02" b"\x1a\x02\x22\x33"
    back = pw.decode(pw.BEACON_PACKET, b)
    assert back == {"previous_sig": b"\x11", "round": 2,
                    "signature": b"\x22\x33"}


def test_chain_info_packet_negative_genesis():
    # proto3 int64: negative values are 10-byte varints
    vals = {"public_key": b"\x01", "period": 30, "genesis_time": -1,
            "hash": b"", "group_hash": b""}
    enc = pw.encode(pw.CHAIN_INFO_PACKET, vals)
    assert pw.decode(pw.CHAIN_INFO_PACKET, enc)["genesis_time"] == -1


def test_unknown_fields_skipped():
    # field 15 (unknown to PUBLIC_RAND_REQUEST), then round=3
    data = b"\x7a\x02\xff\xff" + b"\x08\x03"
    assert pw.decode(pw.PUBLIC_RAND_REQUEST, data)["round"] == 3


def test_truncated_raises():
    with pytest.raises(pw.WireError):
        pw.decode(pw.PUBLIC_RAND_RESPONSE, b"\x12\x05\xaa")


def test_invalid_utf8_str_raises():
    # a str field with invalid UTF-8 is a wire error (INVALID_ARGUMENT at
    # the gateway), not a stray UnicodeDecodeError
    with pytest.raises(pw.WireError, match="invalid UTF-8"):
        pw.decode(pw.IDENTITY, b"\x0a\x01\xff")


def test_wire_type_mismatch_raises():
    # ADVICE r3: a bytes field arriving as fixed64 (wt=1) / fixed32 (wt=5)
    # must be rejected, not have the raw 8/4 bytes become its value
    with pytest.raises(pw.WireError, match="wrong wire type"):
        pw.decode(pw.PUBLIC_RAND_RESPONSE,
                  b"\x11" + b"\x00" * 8)  # field 2 (signature), wt=1
    with pytest.raises(pw.WireError, match="wrong wire type"):
        pw.decode(pw.PUBLIC_RAND_RESPONSE,
                  b"\x15" + b"\x00" * 4)  # field 2 (signature), wt=5
    # int field arriving length-delimited is likewise rejected
    with pytest.raises(pw.WireError, match="wrong wire type"):
        pw.decode(pw.PUBLIC_RAND_REQUEST, b"\x0a\x01\x03")
    # unknown fields with fixed wire types are still skipped
    assert pw.decode(pw.PUBLIC_RAND_REQUEST,
                     b"\x79" + b"\x00" * 8 + b"\x08\x03")["round"] == 3


# ---------------------------------------------------------------------------
# protocol plane (protocol.proto:16-92, dkg.proto:14-93): byte goldens
# ---------------------------------------------------------------------------

def test_partial_beacon_packet_bytes():
    vals = {"round": 5, "previous_sig": b"\xaa\xbb",
            "partial_sig": b"\x01\x02", "partial_sig_v2": b"\x03"}
    enc = pw.encode(pw.PARTIAL_BEACON_PACKET, vals)
    assert enc == (b"\x08\x05" b"\x12\x02\xaa\xbb"
                   b"\x1a\x02\x01\x02" b"\x22\x01\x03")
    assert pw.decode(pw.PARTIAL_BEACON_PACKET, enc) == vals


def test_identity_and_signal_packet_bytes():
    ident = {"address": "a:1", "key": b"\x09", "tls": True,
             "signature": b"\x07"}
    ident_b = pw.encode(pw.IDENTITY, ident)
    assert ident_b == b"\x0a\x03a:1" b"\x12\x01\x09" b"\x18\x01" b"\x22\x01\x07"
    assert pw.decode(pw.IDENTITY, ident_b) == ident

    sig_pkt = {"node": ident, "secret_proof": b"\x55",
               "previous_group_hash": b"\x66"}
    enc = pw.encode(pw.SIGNAL_DKG_PACKET, sig_pkt)
    assert enc == (b"\x0a" + bytes([len(ident_b)]) + ident_b
                   + b"\x12\x01\x55" + b"\x1a\x01\x66")
    assert pw.decode(pw.SIGNAL_DKG_PACKET, enc) == sig_pkt


def test_group_packet_roundtrip():
    g = {"nodes": [
            {"public": {"address": "n0:1", "key": b"\x01", "tls": False,
                        "signature": b""}, "index": 0},
            {"public": {"address": "n1:2", "key": b"\x02", "tls": True,
                        "signature": b"\x03"}, "index": 1}],
         "threshold": 2, "period": 30, "genesis_time": 1700000000,
         "transition_time": 0, "genesis_seed": b"\x44" * 4,
         "dist_key": [b"\x0c\x01", b"\x0c\x02"], "catchup_period": 15}
    enc = pw.encode(pw.GROUP_PACKET, g)
    assert pw.decode(pw.GROUP_PACKET, enc) == g
    info = {"new_group": g, "secret_proof": b"\x5e", "dkg_timeout": 10,
            "signature": b"\x51"}
    assert pw.decode(pw.DKG_INFO_PACKET,
                     pw.encode(pw.DKG_INFO_PACKET, info)) == info


def test_dkg_packet_oneof_bytes():
    deal = {"share_index": 1, "encrypted_share": b"\xee"}
    deal_b = pw.encode(pw.DEAL, deal)
    assert deal_b == b"\x08\x01\x12\x01\xee"
    bundle = {"dealer_index": 2, "commits": [b"\x0c\x01", b"\x0c\x02"],
              "deals": [deal], "session_id": b"\x5e", "signature": b"\x51"}
    bundle_b = pw.encode(pw.DEAL_BUNDLE, bundle)
    assert bundle_b == (b"\x08\x02"
                        b"\x12\x02\x0c\x01" b"\x12\x02\x0c\x02"
                        b"\x1a\x05" + deal_b
                        + b"\x22\x01\x5e" + b"\x2a\x01\x51")
    pkt = {"dkg": {"deal": bundle, "response": None, "justification": None}}
    enc = pw.encode(pw.DKG_PACKET, pkt)
    inner = pw.encode(pw.DKG_BUNDLE, pkt["dkg"])
    assert enc == b"\x0a" + bytes([len(inner)]) + inner
    assert inner == b"\x0a" + bytes([len(bundle_b)]) + bundle_b
    dec = pw.decode(pw.DKG_PACKET, enc)
    arm, val = pw.oneof_of(dec["dkg"], pw.DKG_BUNDLE_ARMS)
    assert arm == "deal" and val == bundle


def test_dkg_response_and_justification_roundtrip():
    rb = {"share_index": 3,
          "responses": [{"dealer_index": 0, "status": True},
                        {"dealer_index": 1, "status": False}],
          "session_id": b"\x5e", "signature": b"\x52"}
    assert pw.decode(pw.RESPONSE_BUNDLE,
                     pw.encode(pw.RESPONSE_BUNDLE, rb)) == rb
    jb = {"dealer_index": 1,
          "justifications": [{"share_index": 2, "share": b"\x99"}],
          "session_id": b"\x5e", "signature": b"\x53"}
    assert pw.decode(pw.JUSTIFICATION_BUNDLE,
                     pw.encode(pw.JUSTIFICATION_BUNDLE, jb)) == jb
    # bool false is omitted on the wire (proto3 default)
    assert pw.encode(pw.RESPONSE, {"dealer_index": 0, "status": False}) == b""


def test_repeated_keeps_default_elements_and_packed_varints():
    # a default-valued element inside a repeated field must be emitted —
    # dropping it would shift every later element's position
    g = {"nodes": [], "threshold": 0, "period": 0, "genesis_time": 0,
         "transition_time": 0, "genesis_seed": b"",
         "dist_key": [b"\x01", b"", b"\x02"], "catchup_period": 0}
    enc = pw.encode(pw.GROUP_PACKET, g)
    assert pw.decode(pw.GROUP_PACKET, enc)["dist_key"] == [b"\x01", b"",
                                                           b"\x02"]
    # packed repeated varints (proto3's default for repeated scalars)
    spec = {1: ("xs", ("rep", "u32"))}
    assert pw.decode(spec, b"\x0a\x04\x05\x00\x96\x01")["xs"] == [5, 0, 150]
    # unpacked occurrences still accumulate
    assert pw.decode(spec, b"\x08\x05\x08\x07")["xs"] == [5, 7]


def test_oneof_multiple_arms_last_wins():
    # proto3 oneof semantics: the last-populated arm wins (ADVICE r4)
    two = {"deal": {"dealer_index": 1, "commits": [], "deals": [],
                    "session_id": b"", "signature": b""},
           "response": {"share_index": 1, "responses": [],
                        "session_id": b"", "signature": b""},
           "justification": None}
    arm, val = pw.oneof_of(two, pw.DKG_BUNDLE_ARMS)
    assert arm == "response" and val["share_index"] == 1


# ---------------------------------------------------------------------------
# live round-trip: ecosystem-style client against our gateway
# ---------------------------------------------------------------------------

class _Svc:
    """Minimal Public + sync service over a fixed small chain."""

    def __init__(self, beacons, info):
        self._b = {b.round: b for b in beacons}
        self._last = max(self._b)
        self._info = info

    async def public_rand(self, from_addr, round_no):
        from drand_tpu.net.transport import TransportError

        b = self._b.get(round_no or self._last)
        if b is None:
            raise TransportError(f"no round {round_no}")
        return b

    async def public_rand_stream(self, from_addr):
        for r in sorted(self._b):
            yield self._b[r]

    async def chain_info(self, from_addr):
        return self._info

    async def sync_chain(self, from_addr, req):
        for r in sorted(self._b):
            if r >= req.from_round:
                yield self._b[r]


@pytest.mark.asyncio
async def test_interop_public_service_roundtrip():
    from drand_tpu.chain.beacon import Beacon
    from drand_tpu.chain.info import Info
    from drand_tpu.client.grpc_interop import GrpcInteropSource
    from drand_tpu.crypto.curves import PointG1
    from drand_tpu.net.grpc_transport import GrpcGateway

    pub = PointG1.generator().mul(0x1234)
    info = Info(public_key=pub, period=30, genesis_time=1700000000,
                genesis_seed=b"\x07" * 32, group_hash=b"\x09" * 32)
    beacons = [Beacon(round=r, previous_sig=b"p%d" % r,
                      signature=b"s%d" % r, signature_v2=b"v%d" % r)
               for r in (1, 2, 3)]
    gw = GrpcGateway(_Svc(beacons, info), "127.0.0.1:0")
    await gw.start()
    try:
        src = GrpcInteropSource(f"127.0.0.1:{gw.port}")
        got_info = await src.info()
        assert got_info.public_key == pub
        assert got_info.period == 30
        assert got_info.genesis_time == 1700000000
        assert got_info.group_hash == b"\x09" * 32
        r2 = await src.get(2)
        assert r2.round == 2 and r2.signature == b"s2"
        rows = []
        async for r in src.watch():
            rows.append(r.round)
        assert rows == [1, 2, 3]
        await src.close()
    finally:
        await gw.stop()


@pytest.mark.asyncio
async def test_interop_protobuf_sync_chain():
    """A protobuf SyncRequest on the standard method streams protobuf
    BeaconPackets (codec sniffing on the shared handler)."""
    import grpc.aio

    from drand_tpu.chain.beacon import Beacon
    from drand_tpu.chain.info import Info
    from drand_tpu.crypto.curves import PointG1
    from drand_tpu.net.grpc_transport import GrpcGateway

    info = Info(public_key=PointG1.generator(), period=30,
                genesis_time=1, genesis_seed=b"", group_hash=b"")
    beacons = [Beacon(round=r, previous_sig=b"p", signature=b"s%d" % r)
               for r in (1, 2, 3)]
    gw = GrpcGateway(_Svc(beacons, info), "127.0.0.1:0")
    await gw.start()
    try:
        ch = grpc.aio.insecure_channel(f"127.0.0.1:{gw.port}")
        stream = ch.unary_stream("/drand.Protocol/SyncChain")(
            pw.encode(pw.SYNC_REQUEST, {"from_round": 2}))
        rounds = []
        async for raw in stream:
            msg = pw.decode(pw.BEACON_PACKET, raw)
            rounds.append(msg["round"])
            assert msg["signature"] == b"s%d" % msg["round"]
        assert rounds == [2, 3]

        # ADVICE r4: from_round=0 — which proto3 encodes as the EMPTY
        # message — is the reference's full-chain sync request
        # (chain/beacon/sync.go:134-150); both forms stream from round 1
        for full in (b"", pw.encode(pw.SYNC_REQUEST, {"from_round": 0})):
            stream = ch.unary_stream("/drand.Protocol/SyncChain")(full)
            rounds = [pw.decode(pw.BEACON_PACKET, raw)["round"]
                      async for raw in stream]
            assert rounds == [1, 2, 3]
        await ch.close()
    finally:
        await gw.stop()


@pytest.mark.asyncio
async def test_interop_protobuf_partial_beacon_aggregated():
    """A protobuf PartialBeaconPacket on /drand.Protocol/PartialBeacon —
    exactly what a reference peer sends (protocol.proto:30,63-75) — is
    accepted by a REAL beacon handler and aggregated into the chain.
    Node 1 never runs; its partial reaches node 0 ONLY over the protobuf
    wire, so round 1 existing in node 0's store proves the path."""
    import asyncio

    import grpc.aio

    from drand_tpu.chain import beacon as chain_beacon
    from drand_tpu.crypto import tbls
    from drand_tpu.net.grpc_transport import GrpcGateway
    from drand_tpu.testing.harness import BeaconTestNetwork

    net = BeaconTestNetwork(n=2, t=2, period=2)
    gw = GrpcGateway(net.nodes[0].handler, "127.0.0.1:0")
    await gw.start()
    try:
        await net.start_all(indices=[0])
        await net.advance_to_genesis()
        await asyncio.sleep(0.1)  # let node 0 sign its own round-1 partial
        assert net.nodes[0].store.last().round == 0  # 1-of-2: stuck

        prev = net.group.get_genesis_seed()
        sk1 = net.shares[1].pri_share
        packet = pw.encode(pw.PARTIAL_BEACON_PACKET, {
            "round": 1, "previous_sig": prev,
            "partial_sig": tbls.sign_partial(
                sk1, chain_beacon.message(1, prev)),
            "partial_sig_v2": tbls.sign_partial(
                sk1, chain_beacon.message_v2(1))})
        ch = grpc.aio.insecure_channel(f"127.0.0.1:{gw.port}")
        resp = await ch.unary_unary("/drand.Protocol/PartialBeacon")(packet)
        assert resp == b""  # drand.Empty

        for _ in range(100):
            if net.nodes[0].store.last().round >= 1:
                break
            await asyncio.sleep(0.05)
        last = net.nodes[0].store.last()
        assert last.round == 1, "protobuf partial was not aggregated"
        pub = net.group.public_key.key()
        assert chain_beacon.verify_beacon(pub, last)
        assert last.is_v2(), "v2 partial did not aggregate"

        # garbage that parses as an all-defaults packet must be rejected
        with pytest.raises(grpc.aio.AioRpcError) as ei:
            await ch.unary_unary("/drand.Protocol/PartialBeacon")(b"")
        assert ei.value.code() == grpc.StatusCode.INVALID_ARGUMENT
        await ch.close()
    finally:
        await gw.stop()
        net.stop_all()
