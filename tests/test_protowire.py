"""Protobuf wire interop: byte-layout goldens and a live round-trip over
the drand.Public service + protobuf SyncChain.

Reference layouts: protobuf/drand/api.proto:36-55 (PublicRandResponse),
protocol.proto:84-92 (SyncRequest/BeaconPacket), common.proto:44-60
(ChainInfoPacket). The golden byte strings below are hand-derived from
the proto3 wire spec (tag = field<<3|type, varint, length-delimited) —
they pin OUR encoder to the ecosystem layout without generated code.
"""

import asyncio

import pytest

from drand_tpu.net import protowire as pw


# ---------------------------------------------------------------------------
# golden bytes
# ---------------------------------------------------------------------------

def test_public_rand_request_bytes():
    # round = 7 -> field 1 varint: tag 0x08, value 0x07
    assert pw.encode(pw.PUBLIC_RAND_REQUEST, {"round": 7}) == b"\x08\x07"
    # round = 0 is the proto3 default: empty message
    assert pw.encode(pw.PUBLIC_RAND_REQUEST, {"round": 0}) == b""
    assert pw.decode(pw.PUBLIC_RAND_REQUEST, b"\x08\x07") == {"round": 7}
    assert pw.decode(pw.PUBLIC_RAND_REQUEST, b"") == {"round": 0}


def test_public_rand_response_bytes():
    vals = {"round": 300, "signature": b"\xaa\xbb",
            "previous_signature": b"\xcc",
            "randomness": b"\x01\x02", "signature_v2": b"\xdd"}
    # field 1 varint 300 = 0xAC 0x02; field 2 len: 0x12 0x02 aa bb;
    # field 3: 0x1a 0x01 cc; field 4: 0x22 0x02 01 02; field 5: 0x2a 0x01 dd
    expect = (b"\x08\xac\x02" b"\x12\x02\xaa\xbb" b"\x1a\x01\xcc"
              b"\x22\x02\x01\x02" b"\x2a\x01\xdd")
    assert pw.encode(pw.PUBLIC_RAND_RESPONSE, vals) == expect
    assert pw.decode(pw.PUBLIC_RAND_RESPONSE, expect) == vals


def test_sync_request_and_beacon_packet_bytes():
    assert pw.encode(pw.SYNC_REQUEST, {"from_round": 1}) == b"\x08\x01"
    b = pw.encode(pw.BEACON_PACKET,
                  {"previous_sig": b"\x11", "round": 2,
                   "signature": b"\x22\x33"})
    assert b == b"\x0a\x01\x11" b"\x10\x02" b"\x1a\x02\x22\x33"
    back = pw.decode(pw.BEACON_PACKET, b)
    assert back == {"previous_sig": b"\x11", "round": 2,
                    "signature": b"\x22\x33"}


def test_chain_info_packet_negative_genesis():
    # proto3 int64: negative values are 10-byte varints
    vals = {"public_key": b"\x01", "period": 30, "genesis_time": -1,
            "hash": b"", "group_hash": b""}
    enc = pw.encode(pw.CHAIN_INFO_PACKET, vals)
    assert pw.decode(pw.CHAIN_INFO_PACKET, enc)["genesis_time"] == -1


def test_unknown_fields_skipped():
    # field 15 (unknown to PUBLIC_RAND_REQUEST), then round=3
    data = b"\x7a\x02\xff\xff" + b"\x08\x03"
    assert pw.decode(pw.PUBLIC_RAND_REQUEST, data)["round"] == 3


def test_truncated_raises():
    with pytest.raises(pw.WireError):
        pw.decode(pw.PUBLIC_RAND_RESPONSE, b"\x12\x05\xaa")


# ---------------------------------------------------------------------------
# live round-trip: ecosystem-style client against our gateway
# ---------------------------------------------------------------------------

class _Svc:
    """Minimal Public + sync service over a fixed small chain."""

    def __init__(self, beacons, info):
        self._b = {b.round: b for b in beacons}
        self._last = max(self._b)
        self._info = info

    async def public_rand(self, from_addr, round_no):
        from drand_tpu.net.transport import TransportError

        b = self._b.get(round_no or self._last)
        if b is None:
            raise TransportError(f"no round {round_no}")
        return b

    async def public_rand_stream(self, from_addr):
        for r in sorted(self._b):
            yield self._b[r]

    async def chain_info(self, from_addr):
        return self._info

    async def sync_chain(self, from_addr, req):
        for r in sorted(self._b):
            if r >= req.from_round:
                yield self._b[r]


@pytest.mark.asyncio
async def test_interop_public_service_roundtrip():
    from drand_tpu.chain.beacon import Beacon
    from drand_tpu.chain.info import Info
    from drand_tpu.client.grpc_interop import GrpcInteropSource
    from drand_tpu.crypto.curves import PointG1
    from drand_tpu.net.grpc_transport import GrpcGateway

    pub = PointG1.generator().mul(0x1234)
    info = Info(public_key=pub, period=30, genesis_time=1700000000,
                genesis_seed=b"\x07" * 32, group_hash=b"\x09" * 32)
    beacons = [Beacon(round=r, previous_sig=b"p%d" % r,
                      signature=b"s%d" % r, signature_v2=b"v%d" % r)
               for r in (1, 2, 3)]
    gw = GrpcGateway(_Svc(beacons, info), "127.0.0.1:0")
    await gw.start()
    try:
        src = GrpcInteropSource(f"127.0.0.1:{gw.port}")
        got_info = await src.info()
        assert got_info.public_key == pub
        assert got_info.period == 30
        assert got_info.genesis_time == 1700000000
        assert got_info.group_hash == b"\x09" * 32
        r2 = await src.get(2)
        assert r2.round == 2 and r2.signature == b"s2"
        rows = []
        async for r in src.watch():
            rows.append(r.round)
        assert rows == [1, 2, 3]
        await src.close()
    finally:
        await gw.stop()


@pytest.mark.asyncio
async def test_interop_protobuf_sync_chain():
    """A protobuf SyncRequest on the standard method streams protobuf
    BeaconPackets (codec sniffing on the shared handler)."""
    import grpc.aio

    from drand_tpu.chain.beacon import Beacon
    from drand_tpu.chain.info import Info
    from drand_tpu.crypto.curves import PointG1
    from drand_tpu.net.grpc_transport import GrpcGateway

    info = Info(public_key=PointG1.generator(), period=30,
                genesis_time=1, genesis_seed=b"", group_hash=b"")
    beacons = [Beacon(round=r, previous_sig=b"p", signature=b"s%d" % r)
               for r in (1, 2, 3)]
    gw = GrpcGateway(_Svc(beacons, info), "127.0.0.1:0")
    await gw.start()
    try:
        ch = grpc.aio.insecure_channel(f"127.0.0.1:{gw.port}")
        stream = ch.unary_stream("/drand.Protocol/SyncChain")(
            pw.encode(pw.SYNC_REQUEST, {"from_round": 2}))
        rounds = []
        async for raw in stream:
            msg = pw.decode(pw.BEACON_PACKET, raw)
            rounds.append(msg["round"])
            assert msg["signature"] == b"s%d" % msg["round"]
        assert rounds == [2, 3]
        await ch.close()
    finally:
        await gw.stop()
