"""Golden tests: batch-last G2 point arithmetic + ψ fast paths
(ops/bl_curve.py) vs the host curve and endo oracles."""

import pytest

pytestmark = pytest.mark.device

import random

import numpy as np
import jax.numpy as jnp

from drand_tpu.crypto import endo
from drand_tpu.crypto import hash_to_curve as h2c
from drand_tpu.crypto.curves import PointG2
from drand_tpu.crypto.fields import R
from drand_tpu.ops import bl_curve as blc
from drand_tpu.ops import curve as xc
from drand_tpu.ops.pallas_pairing import value_bit_getter

rng = random.Random(0xB1C2)
B = 4


def rand_points(n=B, subgroup=True):
    if subgroup:
        return [PointG2.generator().mul(rng.randrange(1, R))
                for _ in range(n)]
    out = []
    for i in range(n):
        u0, u1 = h2c.hash_to_field_fp2(b"blc-%d-%d" % (i, rng.randrange(99)),
                                       h2c.DEFAULT_DST_G2, 2)
        out.append(h2c.map_to_curve_g2(u0) + h2c.map_to_curve_g2(u1))
    return out


def x_getter():
    return value_bit_getter(jnp.asarray(blc.X_BITS))


def test_pt_add_dbl_golden():
    ps = rand_points()
    qs = rand_points()
    dp = blc.pack_g2_points(ps)
    dq = blc.pack_g2_points(qs)
    got_add = blc.unpack_g2_points(xc.pt_add(blc.F2, dp, dq))
    assert got_add == [p + q for p, q in zip(ps, qs)]
    got_dbl = blc.unpack_g2_points(xc.pt_dbl(blc.F2, dp))
    assert got_dbl == [p.double() for p in ps]
    # exceptional cases: P + P, P + (-P), P + inf
    dnegp = blc.pack_g2_points([-p for p in ps])
    assert blc.unpack_g2_points(xc.pt_add(blc.F2, dp, dp)) == \
        [p.double() for p in ps]
    assert all(r.is_infinity()
               for r in blc.unpack_g2_points(xc.pt_add(blc.F2, dp, dnegp)))
    dinf = blc.pack_g2_points([PointG2.infinity()] * B)
    assert blc.unpack_g2_points(xc.pt_add(blc.F2, dp, dinf)) == ps


def test_psi_golden():
    ps = rand_points(subgroup=False)
    dp = blc.pack_g2_points(ps)
    assert blc.unpack_g2_points(blc.psi(dp)) == [endo.psi(p) for p in ps]
    assert blc.unpack_g2_points(blc.psi2(dp)) == [endo.psi2(p) for p in ps]


def test_mul_x_and_subgroup_check():
    from drand_tpu.crypto.fields import X_BLS

    ps = rand_points()
    dp = blc.pack_g2_points(ps)
    got = blc.unpack_g2_points(blc.mul_x(blc.F2, dp, x_getter()))
    assert got == [endo._mul_int(p, X_BLS) for p in ps]
    ok = np.asarray(blc.subgroup_check(blc.F2, dp, x_getter()))
    assert ok.all()
    bad = rand_points(subgroup=False)
    dbad = blc.pack_g2_points(bad)
    ok_bad = np.asarray(blc.subgroup_check(blc.F2, dbad, x_getter()))
    assert not ok_bad.any()


def test_clear_cofactor_golden():
    ps = rand_points(subgroup=False)
    dp = blc.pack_g2_points(ps)
    got = blc.unpack_g2_points(
        blc.clear_cofactor(blc.F2, dp, x_getter()))
    want = [endo.clear_cofactor_fast(p) for p in ps]
    assert got == want
    assert all(g.in_subgroup() for g in got)
