"""Private randomness (ECIES) and timelock-to-round client surfaces.

Reference: core/drand_public.go:126-160 (PrivateRand) and
core/timelock_test.go:17-72 (timelock encryption over V2 signatures).
"""

import pytest

from drand_tpu.client import ClientError
from drand_tpu.client.direct import DirectClient
from drand_tpu.client.private import private_rand
from drand_tpu.client.timelock import (
    decrypt_with_beacon,
    dumps,
    encrypt_to_round,
    loads,
)
from drand_tpu.testing.harness import BeaconTestNetwork

N, T, PERIOD = 3, 2, 5


@pytest.mark.asyncio
async def test_private_rand_roundtrip():
    net = BeaconTestNetwork(n=N, t=T, period=PERIOD)
    # private rand needs only identities + transport, not a running chain
    client = net.network.client_for("consumer:1")

    class _Consumer:
        async def private_rand(self, f, r):  # pragma: no cover
            raise NotImplementedError

    net.network.register("consumer:1", _Consumer())

    # wire the daemon-side handler onto node 0's service: the harness
    # registers the beacon Handler, which lacks private_rand — attach the
    # daemon implementation shape directly
    from drand_tpu.crypto import ecies
    from drand_tpu.crypto.curves import PointG1
    from drand_tpu.utils import entropy

    node_pair = net.pairs[0]

    async def _private_rand(from_addr, request):
        client_key = PointG1.from_bytes(
            ecies.decrypt(node_pair.key, bytes(request)))
        return ecies.encrypt(client_key, entropy.get_random(32))

    net.nodes[0].handler.private_rand = _private_rand

    out1 = await private_rand(client, net.pairs[0].public)
    out2 = await private_rand(client, net.pairs[0].public)
    assert len(out1) == 32 and out1 != out2


@pytest.mark.asyncio
async def test_timelock_round_trip_and_wrong_round():
    net = BeaconTestNetwork(n=N, t=T, period=PERIOD)
    await net.start_all()
    await net.advance_to_genesis()
    for _ in range(3):
        await net.clock.advance(PERIOD)
    for i in range(N):
        await net.wait_round(i, 3)
    try:
        src = DirectClient(net.nodes[0].handler)
        info = await src.info()
        secret = b"the launch code is 0000"
        ct = loads(dumps(encrypt_to_round(info, 3, secret)))
        r3 = await src.get(3)
        assert decrypt_with_beacon(ct, r3) == secret
        # the wrong round's signature must not decrypt
        r2 = await src.get(2)
        with pytest.raises(ClientError):
            decrypt_with_beacon(ct, r2)
        # tampering is rejected by the FO check
        ct_bad = dict(ct)
        ct_bad["W"] = ct["W"][:-4] + ("AAA=" if not ct["W"].endswith("AAA=")
                                      else "BBB=")
        with pytest.raises(Exception):
            decrypt_with_beacon(ct_bad, r3)
    finally:
        net.stop_all()
