"""Crypto-core tests: field towers, curve groups, pairing, hash-to-curve.

Mirrors the reference's per-package unit-test level (SURVEY.md §4): plain
deterministic unit tests, goldens between fast and slow paths.
"""

import hashlib

import pytest

from drand_tpu.crypto.fields import (
    P,
    R,
    Fp,
    Fp2,
    Fp6,
    Fp12,
    fp_inv,
    fp_sqrt,
    fr_inv,
    fr_mul,
)
from drand_tpu.crypto.curves import PointG1, PointG2, H1, H2
from drand_tpu.crypto.pairing import (
    final_exponentiation,
    final_exponentiation_slow,
    miller_loop,
    multi_pairing,
    pairing,
    pairing_check,
)
from drand_tpu.crypto.hash_to_curve import (
    DEFAULT_DST_G2,
    expand_message_xmd,
    hash_to_field_fp2,
    hash_to_g2,
    map_to_curve_g2,
)


# ---------------------------------------------------------------------------
# fields
# ---------------------------------------------------------------------------

def _rand_fp2(seed: int) -> Fp2:
    h = hashlib.sha256(b"fp2%d" % seed).digest() + hashlib.sha256(b"fp2b%d" % seed).digest()
    return Fp2(int.from_bytes(h[:32], "big"), int.from_bytes(h[32:], "big"))


def _rand_fp12(seed: int) -> Fp12:
    return Fp12(
        Fp6(_rand_fp2(seed), _rand_fp2(seed + 100), _rand_fp2(seed + 200)),
        Fp6(_rand_fp2(seed + 300), _rand_fp2(seed + 400), _rand_fp2(seed + 500)),
    )


class TestFields:
    def test_fp_inverse(self):
        for a in (1, 2, 12345, P - 1):
            assert a * fp_inv(a) % P == 1

    def test_fp_sqrt_roundtrip(self):
        for a in (4, 9, 1234567):
            r = fp_sqrt(a * a % P)
            assert r is not None and r * r % P == a * a % P

    def test_fp2_field_axioms(self):
        a, b, c = _rand_fp2(1), _rand_fp2(2), _rand_fp2(3)
        assert a * (b + c) == a * b + a * c
        assert (a * b) * c == a * (b * c)
        assert a * a.inverse() == Fp2.one()
        assert a.square() == a * a

    def test_fp2_sqrt(self):
        for i in range(5):
            a = _rand_fp2(i)
            sq = a.square()
            r = sq.sqrt()
            assert r is not None and r.square() == sq

    def test_fp2_frobenius_is_pth_power(self):
        a = _rand_fp2(7)
        assert a.frobenius() == a.pow(P)

    def test_fp6_axioms(self):
        a = Fp6(_rand_fp2(1), _rand_fp2(2), _rand_fp2(3))
        b = Fp6(_rand_fp2(4), _rand_fp2(5), _rand_fp2(6))
        assert a * a.inverse() == Fp6.one()
        assert a * b == b * a
        assert a.mul_by_v() == a * Fp6(Fp2.zero(), Fp2.one(), Fp2.zero())

    def test_fp12_axioms(self):
        a, b = _rand_fp12(1), _rand_fp12(2)
        assert a * a.inverse() == Fp12.one()
        assert a * b == b * a
        assert a.square() == a * a

    def test_fp12_frobenius(self):
        a = _rand_fp12(3)
        assert a.frobenius(1) == a.pow(P)
        assert a.frobenius(2) == a.pow(P).pow(P)

    def test_cyclotomic_square_matches_square(self):
        # put an element into the cyclotomic subgroup first
        f = _rand_fp12(4)
        f1 = f.conjugate() * f.inverse()
        m = f1.frobenius(2) * f1
        assert m.cyclotomic_square() == m.square()
        assert m.cyclotomic_pow(987654321) == m.pow(987654321)

    def test_fr(self):
        assert fr_mul(3, fr_inv(3)) == 1
        assert fr_mul(R - 1, R - 1) == 1  # (-1)^2


# ---------------------------------------------------------------------------
# curves
# ---------------------------------------------------------------------------

class TestCurves:
    def test_generators_valid(self):
        for cls in (PointG1, PointG2):
            g = cls.generator()
            assert g.is_on_curve()
            assert g.mul(R).is_infinity()

    def test_group_law(self):
        for cls in (PointG1, PointG2):
            g = cls.generator()
            assert g.mul(5) + g.mul(7) == g.mul(12)
            assert g.mul(5) - g.mul(5) == cls.infinity()
            assert g.double() == g + g
            assert (g + g.mul(3)).mul(2) == g.mul(8)

    def test_infinity_arithmetic(self):
        g = PointG1.generator()
        inf = PointG1.infinity()
        assert g + inf == g
        assert inf + g == g
        assert inf.double() == inf
        assert g.mul(0) == inf

    def test_serialization_roundtrip(self):
        for cls in (PointG1, PointG2):
            g = cls.generator()
            for k in (1, 2, 777, R - 1):
                p = g.mul(k)
                b = p.to_bytes()
                assert len(b) == cls.COMPRESSED_SIZE
                assert cls.from_bytes(b) == p
            # infinity
            assert cls.from_bytes(cls.infinity().to_bytes()).is_infinity()

    def test_serialization_both_signs(self):
        g = PointG2.generator()
        p = g.mul(42)
        assert PointG2.from_bytes((-p).to_bytes()) == -p

    def test_deserialize_rejects_garbage(self):
        with pytest.raises(ValueError):
            PointG1.from_bytes(b"\x00" * 48)  # no compression flag
        with pytest.raises(ValueError):
            PointG1.from_bytes(b"\x80" + b"\xff" * 47)  # x >= p

    def test_known_generator_bytes(self):
        # zcash-format generator encodings (well-known constants)
        g1b = PointG1.generator().to_bytes()
        assert g1b.hex().startswith("97f1d3a73197d7942695638c4fa9ac0f")
        g2b = PointG2.generator().to_bytes()
        assert len(g2b) == 96 and g2b[0] & 0x80

    def test_cofactors(self):
        # cofactor-cleared random curve points are in the r-subgroup
        assert H1 * R != 0 and H2 * R != 0
        g2 = PointG2.generator()
        assert g2.clear_cofactor() == g2.mul(H2 % R) or g2.clear_cofactor().in_subgroup()


# ---------------------------------------------------------------------------
# pairing
# ---------------------------------------------------------------------------

class TestPairing:
    def test_non_degenerate(self):
        e = pairing(PointG1.generator(), PointG2.generator())
        assert not e.is_one()
        assert e.pow(R) == Fp12.one()  # lands in the order-r subgroup

    def test_bilinearity(self):
        g1, g2 = PointG1.generator(), PointG2.generator()
        e = pairing(g1, g2)
        assert pairing(g1.mul(6), g2.mul(35)) == e.pow(210)
        assert pairing(g1.mul(6), g2.mul(35)) == pairing(g1.mul(35), g2.mul(6))
        assert pairing(g1.mul(2), g2) == e.square()

    def test_final_exp_fast_matches_slow(self):
        g1, g2 = PointG1.generator(), PointG2.generator()
        f = miller_loop([(g1.mul(3), g2.mul(5))])
        assert final_exponentiation(f) == final_exponentiation_slow(f)

    def test_multi_pairing_is_product(self):
        g1, g2 = PointG1.generator(), PointG2.generator()
        lhs = multi_pairing([(g1.mul(3), g2.mul(4)), (g1.mul(5), g2.mul(6))])
        rhs = pairing(g1, g2).pow(3 * 4 + 5 * 6)
        assert lhs == rhs

    def test_pairing_check(self):
        g1, g2 = PointG1.generator(), PointG2.generator()
        assert pairing_check([(g1.mul(11), g2), (-g1, g2.mul(11))])
        assert not pairing_check([(g1.mul(11), g2), (-g1, g2.mul(12))])

    def test_infinity_pairs_skipped(self):
        g1, g2 = PointG1.generator(), PointG2.generator()
        assert multi_pairing([(PointG1.infinity(), g2)]).is_one()
        assert multi_pairing([(g1, PointG2.infinity())]).is_one()


# ---------------------------------------------------------------------------
# hash-to-curve
# ---------------------------------------------------------------------------

class TestHashToCurve:
    def test_expand_message_xmd_shape(self):
        out = expand_message_xmd(b"msg", b"DST", 128)
        assert len(out) == 128
        # deterministic + length-dependent (len_in_bytes feeds b_0)
        assert out[:32] != expand_message_xmd(b"msg", b"DST", 32)
        assert out == expand_message_xmd(b"msg", b"DST", 128)
        assert out != expand_message_xmd(b"msg2", b"DST", 128)
        assert out != expand_message_xmd(b"msg", b"DST2", 128)

    def test_hash_to_field(self):
        els = hash_to_field_fp2(b"abc", DEFAULT_DST_G2, 2)
        assert len(els) == 2 and els[0] != els[1]

    def test_map_to_curve_on_curve(self):
        for i in range(4):
            u = _rand_fp2(i + 50)
            p = map_to_curve_g2(u)
            assert p.is_on_curve()

    def test_hash_to_g2_valid_and_deterministic(self):
        q = hash_to_g2(b"round 1 message")
        assert q.is_on_curve() and q.in_subgroup() and not q.is_infinity()
        assert q == hash_to_g2(b"round 1 message")
        assert q != hash_to_g2(b"round 2 message")

    def test_dst_separation(self):
        assert hash_to_g2(b"m", b"DST-A") != hash_to_g2(b"m", b"DST-B")

    def test_rfc9380_conformance(self):
        """The selected isogeny must reproduce the RFC 9380 J.10.1 vector —
        guaranteeing interop with blst/kyber/real drand chains."""
        from drand_tpu.crypto import hash_to_curve as h

        assert h.RFC_CONFORMANT
        p = hash_to_g2(b"", h._RFC_J10_1_DST)
        px, py = p.to_affine()
        assert px == h._RFC_J10_1_PX and py == h._RFC_J10_1_PY


class TestPairingCanonical:
    def test_canonical_vs_cubed(self):
        g1, g2 = PointG1.generator(), PointG2.generator()
        f = miller_loop([(g1, g2)])
        canon = final_exponentiation(f, canonical=True)
        cubed = final_exponentiation(f, canonical=False)
        assert canon.pow(3) == cubed
        assert canon == final_exponentiation_slow(f, canonical=True)
