"""Lazy-reduction domain (ops/bl.py LAZY path) — value-level goldens.

The lazy path accumulates unreduced product convolutions and REDCs once
per output coefficient (f2_mul 3->2, f6_mul 18->6, f12_mul 54->12
REDCs). Its soundness rests on static per-site bounds (limb < 2^31
everywhere, redc input < 2^30 limbs / bl.REDC_VALUE_CEILING ~2^778.59
value with wrap_passes=6 — statically re-verified at import by
bl._redc_wrap_converges)
— the probes here are the ones the round-3 reduce_light bug taught us:
content-varied batches, CHAINED non-canonical values, and max-limb
adversarial inputs, all against the host tower (crypto/fields).

Reference parity: kyber-bls12381's backend performs the same
BLST-style lazy Fp2 accumulation in assembly (/root/reference/go.mod:9).
"""

import random

import numpy as np
import jax.numpy as jnp
import pytest

from drand_tpu.crypto import fields as hf
from drand_tpu.ops import bl, limb as _x

P = hf.P
RINV = pow(_x.R_MONT, -1, P)
rng = random.Random(0xA55)

pytestmark = pytest.mark.skipif(not bl.LAZY, reason="lazy path disabled")


def pack2(vals):
    return np.stack([bl.pack_fp([v[0] for v in vals]),
                     bl.pack_fp([v[1] for v in vals])], axis=0)


def rand2():
    return hf.Fp2(rng.randrange(P), rng.randrange(P))


def rand6():
    return hf.Fp6(rand2(), rand2(), rand2())


def rand12():
    return hf.Fp12(rand6(), rand6())


def pack6(vals):
    return np.stack([pack2([(v.c0.c0, v.c0.c1) for v in vals]),
                     pack2([(v.c1.c0, v.c1.c1) for v in vals]),
                     pack2([(v.c2.c0, v.c2.c1) for v in vals])], axis=0)


def pack12(vals):
    return np.stack([pack6([v.c0 for v in vals]),
                     pack6([v.c1 for v in vals])], axis=0)


def unpack12(r, i):
    out = []
    for h in range(2):
        for ci in range(3):
            out.append((bl.unpack_fp(np.asarray(r[h, ci, 0]))[i],
                        bl.unpack_fp(np.asarray(r[h, ci, 1]))[i]))
    return out


def flat12(e):
    return [(c.c0, c.c1) for half in (e.c0, e.c1)
            for c in (half.c0, half.c1, half.c2)]


B = 4


def test_f2_mul_lazy_matches_host():
    a = [rand2() for _ in range(B)]
    b = [rand2() for _ in range(B)]
    r = bl.f2_mul(jnp.asarray(pack2([(x.c0, x.c1) for x in a])),
                  jnp.asarray(pack2([(x.c0, x.c1) for x in b])))
    for i in range(B):
        e = a[i] * b[i]
        assert bl.unpack_fp(np.asarray(r[0]))[i] == e.c0
        assert bl.unpack_fp(np.asarray(r[1]))[i] == e.c1


def test_f6_f12_mul_lazy_match_host():
    a6, b6 = [rand6() for _ in range(B)], [rand6() for _ in range(B)]
    r = bl.f6_mul(jnp.asarray(pack6(a6)), jnp.asarray(pack6(b6)))
    for i in range(B):
        e = a6[i] * b6[i]
        for ci, ec in enumerate([e.c0, e.c1, e.c2]):
            assert bl.unpack_fp(np.asarray(r[ci, 0]))[i] == ec.c0
            assert bl.unpack_fp(np.asarray(r[ci, 1]))[i] == ec.c1
    a12, b12 = [rand12() for _ in range(B)], [rand12() for _ in range(B)]
    r = bl.f12_mul(jnp.asarray(pack12(a12)), jnp.asarray(pack12(b12)))
    for i in range(B):
        assert unpack12(r, i) == flat12(a12[i] * b12[i])
    r = bl.f12_sqr(jnp.asarray(pack12(a12)))
    for i in range(B):
        assert unpack12(r, i) == flat12(a12[i] * a12[i])


def test_lazy_chained_non_canonical():
    """Repeated lazy muls feed the engine's lazy-carry outputs back in —
    the probe class that caught the round-3 reduce_light truncation."""
    a12 = [rand12() for _ in range(B)]
    x_d = jnp.asarray(pack12(a12))
    x_h = list(a12)
    for step in range(8):
        x_d = bl.f12_mul(x_d, x_d) if step % 2 == 0 else bl.f12_sqr(x_d)
        x_h = [v * v for v in x_h]
        for i in range(B):
            assert unpack12(x_d, i) == flat12(x_h[i]), (step, i)


def test_lazy_max_limb_adversarial():
    """All limbs at the 4100 engine-invariant ceiling — the worst case
    for every conv coefficient and complement bound simultaneously."""
    mx12 = np.full((2, 3, 2, 32, B), 4100, np.int32)
    vmax = _x.limbs_to_int(np.full(32, 4100)) % P
    c = vmax * RINV % P
    cf2 = hf.Fp2(c, c)
    cf6 = hf.Fp6(cf2, cf2, cf2)
    e = hf.Fp12(cf6, cf6) * hf.Fp12(cf6, cf6)
    r = bl.f12_mul(jnp.asarray(mx12), jnp.asarray(mx12))
    for i in range(B):
        assert unpack12(r, i) == flat12(e)


def test_redc_magnitude_ceiling():
    """redc stays exact through the authoritative REDC_VALUE_CEILING
    (~2^778.59 — the Z-site worst case the profiles are built for),
    probing random values at and just under the full ceiling width."""
    assert bl.REDC_VALUE_CEILING > 1 << 778  # the old figures undershot
    assert bl._redc_wrap_converges(bl.REDC_VALUE_CEILING, wrap_passes=6)
    for vbits in (769, 774, 778):
        for _ in range(10):
            lim = np.asarray(
                [rng.randrange(min(1 << 24, (1 << max(0, vbits - 12 * k))))
                 if 12 * k <= vbits else 0 for k in range(66)], np.int32)
            t = jnp.asarray(np.stack([lim, lim], axis=-1))
            val = _x.limbs_to_int(lim)
            got = bl.unpack_fp(np.asarray(bl.redc(t)))[0]
            assert got == val * RINV % P * RINV % P, vbits
    # the exact ceiling value itself (greedy top-down limb decomposition)
    rem = bl.REDC_VALUE_CEILING
    lims = [0] * 66
    for k in range(65, -1, -1):
        lims[k] = min((1 << 24) - 1, rem >> (12 * k))
        rem -= lims[k] << (12 * k)
    lim = np.asarray(lims, np.int32)
    val = _x.limbs_to_int(lim)
    assert val == bl.REDC_VALUE_CEILING
    t = jnp.asarray(np.stack([lim, lim], axis=-1))
    got = bl.unpack_fp(np.asarray(bl.redc(t)))[0]
    assert got == val * RINV % P * RINV % P


def test_cyclotomic_sqr_lazy_matches_host():
    """Lazy Granger-Scott square on real cyclotomic-subgroup elements
    (pairing outputs), chained to exercise non-canonical feedback."""
    from drand_tpu.crypto.pairing import pairing as host_pairing
    from drand_tpu.crypto.curves import PointG1, PointG2

    elems = [host_pairing(PointG1.generator().mul(rng.randrange(1, 1 << 40)),
                          PointG2.generator().mul(rng.randrange(1, 1 << 40)))
             for _ in range(B)]
    x_d = jnp.asarray(pack12(elems))
    x_h = list(elems)
    for step in range(4):
        x_d = bl.f12_cyclotomic_sqr(x_d)
        x_h = [v * v for v in x_h]
        for i in range(B):
            assert unpack12(x_d, i) == flat12(x_h[i]), (step, i)
