"""Gossip relay/mesh: flood propagation, validation, dedup.

Reference: lp2p/relaynode.go (relay), lp2p/client (validating subscriber):
an invalid beacon injected into the mesh must not propagate; valid beacons
reach every mesh member through any path.
"""

import asyncio

import pytest

from drand_tpu.chain.beacon import Beacon, message, message_v2
from drand_tpu.client.direct import DirectClient
from drand_tpu.relay.gossip import GossipNode, GossipRelay
from drand_tpu.testing.harness import BeaconTestNetwork
from drand_tpu.testing.mock_server import MockBeaconServer
from drand_tpu.utils.clock import FakeClock


@pytest.mark.asyncio
async def test_mesh_propagation_and_validation():
    mock = MockBeaconServer(nrounds=5)
    clock = FakeClock(start=mock.chain_info.genesis_time + 1000)
    # 3-node line topology: A -> B -> C (and reverse links)
    nodes = [GossipNode(mock.chain_info, clock=clock) for _ in range(3)]
    for n in nodes:
        await n.serve("127.0.0.1:0")
    addrs = [f"127.0.0.1:{n.port}" for n in nodes]
    nodes[0].add_peer(addrs[1])
    nodes[1].add_peer(addrs[0])
    nodes[1].add_peer(addrs[2])
    nodes[2].add_peer(addrs[1])
    try:
        # a valid beacon published at A floods to C through B
        await nodes[0].publish(mock.beacons[1])
        for _ in range(50):
            if nodes[2]._tip >= 1:
                break
            await asyncio.sleep(0.05)
        assert nodes[2]._tip == 1
        assert (await nodes[2].get(1)).round == 1

        # an invalid beacon is dropped at the entry node and never floods
        bad = Beacon(round=2, previous_sig=mock.beacons[1].signature,
                     signature=b"\x99" * 96)
        await nodes[0].publish(bad)
        await asyncio.sleep(0.2)
        assert nodes[1]._tip == 1 and nodes[2]._tip == 1

        # dedup: republishing the same beacon is a no-op (no infinite loops
        # in the cyclic topology by construction of _seen)
        await nodes[0].publish(mock.beacons[1])
        await asyncio.sleep(0.1)
        assert nodes[2]._tip == 1
    finally:
        for n in nodes:
            await n.stop()


@pytest.mark.asyncio
async def test_relay_feeds_mesh_from_live_network():
    net = BeaconTestNetwork(n=3, t=2, period=5)
    await net.start_all()
    await net.advance_to_genesis()
    await net.clock.advance(5)
    await net.wait_round(0, 1)
    src = DirectClient(net.nodes[0].handler)
    info = await src.info()
    relay_node = GossipNode(info, clock=net.clock)
    sub_node = GossipNode(info, clock=net.clock)
    await relay_node.serve("127.0.0.1:0")
    await sub_node.serve("127.0.0.1:0")
    relay_node.add_peer(f"127.0.0.1:{sub_node.port}")
    relay = GossipRelay(src, relay_node)
    relay.start()
    try:
        watcher = sub_node.watch()
        take = asyncio.ensure_future(watcher.__anext__())
        await asyncio.sleep(0.1)
        await net.clock.advance(5)
        for i in range(3):
            await net.wait_round(i, 2)
        r = await asyncio.wait_for(take, timeout=10)
        assert r.round >= 2
        assert len(r.randomness) == 32
    finally:
        relay.stop()
        await relay_node.stop()
        await sub_node.stop()
        net.stop_all()


@pytest.mark.asyncio
async def test_source_ip_scoring_eviction_and_recovery():
    """Gossipsub-v1.1-analogue pruning: a source IP delivering
    SCORE_INVALID_LIMIT invalid beacons is banned for EVICT_COOLOFF
    (its deliveries refused, forwards to co-located peers skipped),
    then traffic resumes after the cooloff."""
    from drand_tpu.relay import gossip as g

    mock = MockBeaconServer(nrounds=5)
    clock = FakeClock(start=mock.chain_info.genesis_time + 1000)
    a = GossipNode(mock.chain_info, clock=clock)
    b = GossipNode(mock.chain_info, clock=clock)
    await a.serve("127.0.0.1:0")
    await b.serve("127.0.0.1:0")
    addr_a, addr_b = (f"127.0.0.1:{a.port}", f"127.0.0.1:{b.port}")
    a.add_peer(addr_b)
    b.add_peer(addr_a)
    try:
        # a flood of invalid beacons from one source IP bans the IP at B
        for i in range(g.SCORE_INVALID_LIMIT):
            bad = Beacon(round=1, previous_sig=b"\x01" * 96 + bytes([i]),
                         signature=b"\x99" * 96)
            await b._accept(
                __import__("drand_tpu.net.wire", fromlist=["wire"]).encode(
                    bad), validate=True, sender="127.0.0.1")
        sc = b._ip_scores["127.0.0.1"]
        assert sc.banned_until > clock.now(), "source ip not banned"

        # while banned, B refuses deliveries from that source
        await a.publish(mock.beacons[1])
        await asyncio.sleep(0.3)
        assert b._tip == 0

        # after the cooloff the flow resumes
        await clock.advance(g.EVICT_COOLOFF + 1)
        await a.publish(mock.beacons[2])
        for _ in range(50):
            if b._tip >= 2:
                break
            await asyncio.sleep(0.05)
        assert b._tip == 2
    finally:
        await a.stop()
        await b.stop()


@pytest.mark.asyncio
async def test_dead_peer_evicted_after_forward_failures():
    """A consistently unreachable peer is pruned after SCORE_FAIL_LIMIT
    consecutive forward failures instead of being retried forever."""
    from drand_tpu.relay import gossip as g

    mock = MockBeaconServer(nrounds=g.SCORE_FAIL_LIMIT + 2)
    clock = FakeClock(start=mock.chain_info.genesis_time + 1000)
    a = GossipNode(mock.chain_info, clock=clock)
    await a.serve("127.0.0.1:0")
    a.add_peer("127.0.0.1:1")  # nothing listens there
    try:
        # each DISTINCT beacon triggers one forward attempt (dedup blocks
        # repeats), so SCORE_FAIL_LIMIT publishes accumulate the failures
        for r in range(1, g.SCORE_FAIL_LIMIT + 1):
            await a.publish(mock.beacons[r])
            st = a._peers["127.0.0.1:1"]
            for _ in range(100):
                if st.fails >= r or st.banned_until:
                    break
                await asyncio.sleep(0.02)
        st = a._peers["127.0.0.1:1"]
        for _ in range(100):
            if st.banned_until:
                break
            await asyncio.sleep(0.05)
        assert st.banned_until > clock.now()
        assert st.channel is None
    finally:
        await a.stop()


@pytest.mark.asyncio
async def test_ip_ban_cross_check_ipv6_and_hostname(monkeypatch):
    """ADVICE r5: the egress skip of ingress-banned sources must key
    _ip_scores the way _peer_ip writes it — IPv6 peers configured as
    '[::1]:port' and hostname-configured peers both have to match their
    bare-IP ban entries (the raw rsplit host never did)."""
    import drand_tpu.relay.gossip as gmod

    mock = MockBeaconServer(nrounds=2)
    clock = FakeClock(start=mock.chain_info.genesis_time + 1000)
    node = GossipNode(mock.chain_info, clock=clock)

    def ban(ip):
        sc = gmod._IpScore()
        sc.banned_until = clock.now() + 100
        node._ip_scores[ip] = sc

    gmod._resolve_host.cache_clear()
    try:
        # IPv6: configured with brackets, ingress table keyed bare
        node.add_peer("[::1]:9999")
        ban("::1")
        st = node._peers["[::1]:9999"]
        assert node._live_channel("[::1]:9999", st) is None

        # hostname peer resolving to a banned A record (stubbed DNS)
        monkeypatch.setattr(
            gmod.socket, "getaddrinfo",
            lambda host, port, *a, **k: [(2, 1, 6, "", ("192.0.2.7", 0))])
        node.add_peer("flooder.example:9000")
        ban("192.0.2.7")
        st2 = node._peers["flooder.example:9000"]
        assert node._live_channel("flooder.example:9000", st2) is None

        # an unbanned literal-IP peer still yields a channel
        node.add_peer("10.0.0.5:9000")
        st3 = node._peers["10.0.0.5:9000"]
        assert node._live_channel("10.0.0.5:9000", st3) is not None
    finally:
        gmod._resolve_host.cache_clear()
        await node.stop()


@pytest.mark.asyncio
async def test_concurrent_duplicate_publish_validates_once():
    """Validation runs on a worker thread (asyncio.to_thread), which
    opens a suspension point between the _seen dedup check and the
    _seen insert. The in-flight guard must collapse N concurrent
    deliveries of the same flooded beacon to ONE validation and ONE
    subscriber wakeup — without it every duplicate re-validates and
    re-floods (per-message amplification at every round boundary)."""
    import time as _time

    from drand_tpu.net import wire

    mock = MockBeaconServer(nrounds=3)
    clock = FakeClock(start=mock.chain_info.genesis_time + 1000)
    node = GossipNode(mock.chain_info, clock=clock)
    calls = 0
    real_validate = node._validate

    def counting_validate(b, max_live=None):
        nonlocal calls
        calls += 1
        _time.sleep(0.15)  # hold the worker thread so duplicates overlap
        return real_validate(b, max_live)

    node._validate = counting_validate
    q: asyncio.Queue = asyncio.Queue()
    node._subs.append(q)
    raw = wire.encode(mock.beacons[1])
    await asyncio.gather(*(node._accept(raw, validate=True)
                           for _ in range(5)))
    assert calls == 1
    assert q.qsize() == 1
    assert node._tip == 1

    # post-validation re-delivery is the ordinary _seen no-op
    await node._accept(raw, validate=True)
    assert calls == 1
    assert q.qsize() == 1


@pytest.mark.asyncio
async def test_boundary_crossing_duplicate_forces_revalidation():
    """The liveness half of a validation verdict is a clock snapshot,
    not a property of the bytes: when the first flooded copy of round N
    arrives a moment before N's boundary, its validation rejects
    (far-future) — and every concurrent duplicate arrives AFTER the
    boundary, when the round is live. Peers mark the message seen and
    never re-send, so silently dropping those duplicates loses the
    round until catch-up. The in-flight guard must instead note the
    fresher clock and revalidate once with the new bound."""
    import threading

    from drand_tpu.net import wire

    mock = MockBeaconServer(nrounds=3)
    period = mock.chain_info.period
    # mid round 1: max_live = 2, so round 3 is one boundary in the future
    clock = FakeClock(start=mock.chain_info.genesis_time + period // 2)
    node = GossipNode(mock.chain_info, clock=clock)
    started = threading.Event()
    release = threading.Event()
    bounds = []
    real_validate = node._validate

    def gated_validate(b, max_live=None):
        bounds.append(max_live)
        started.set()
        release.wait(5)  # hold the worker thread across the boundary
        return real_validate(b, max_live)

    node._validate = gated_validate
    q: asyncio.Queue = asyncio.Queue()
    node._subs.append(q)
    raw = wire.encode(mock.beacons[3])
    first = asyncio.create_task(node._accept(raw, validate=True))
    await asyncio.to_thread(started.wait, 5)
    # the boundary crosses while validation is in flight; the flooded
    # duplicate sees a clock that admits round 3
    await clock.advance(period)
    await node._accept(raw, validate=True)  # in-flight duplicate
    release.set()
    await first
    # stale bound rejected, the duplicate's clock forced ONE retry with
    # the fresh bound, and the beacon landed
    assert bounds == [2, 3]
    assert node._tip == 3
    assert q.qsize() == 1
    # and the retry did not leak the in-flight entry
    assert node._inflight == {}
