"""Byte-golden vectors for the kyber-layout DKG bundle hashes and the
kyber-layout schnorr challenge.

The layouts mirror drand/kyber share/dkg/structs.go (bundle hashes:
sha256, u32be indices, index-sorted entries, raw concatenation, session
id last) and sign/schnorr (challenge = sha512(R || pub || msg) reduced
big-endian mod r) — /root/reference/core/broadcast.go:98 and
core/drand_control.go:139 are the ingress points whose verification a
drand-tpu node must satisfy. These vectors pin the byte layout so an
accidental reordering is caught; they are self-generated (kyber is not
available in this image to cross-sign).
"""

import hashlib

from drand_tpu.dkg import packets as pk
from drand_tpu.crypto import schnorr
from drand_tpu.crypto.curves import PointG1


def test_deal_bundle_hash_layout():
    b = pk.DealBundle(
        dealer_index=3,
        commits=(b"\x01" * 48, b"\x02" * 48),
        deals=(pk.Deal(2, b"ct-two"), pk.Deal(0, b"ct-zero")),
        session_id=b"sess")
    # layout recomputed by hand: index u32be, deals SORTED by share
    # index (0 before 2), raw ciphertexts, commits, session id
    h = hashlib.sha256()
    h.update((3).to_bytes(4, "big"))
    h.update((0).to_bytes(4, "big") + b"ct-zero")
    h.update((2).to_bytes(4, "big") + b"ct-two")
    h.update(b"\x01" * 48 + b"\x02" * 48)
    h.update(b"sess")
    assert b.hash() == h.digest()
    # sorting is canonical: the declaration order must not matter
    b2 = pk.DealBundle(dealer_index=3, commits=b.commits,
                       deals=(b.deals[1], b.deals[0]), session_id=b"sess")
    assert b2.hash() == b.hash()


def test_response_bundle_hash_layout():
    b = pk.ResponseBundle(
        share_index=1,
        responses=(pk.Response(5, pk.STATUS_COMPLAINT),
                   pk.Response(2, pk.STATUS_APPROVAL)),
        session_id=b"nonce")
    h = hashlib.sha256()
    h.update((1).to_bytes(4, "big"))
    h.update((2).to_bytes(4, "big") + b"\x01")   # approval = 1
    h.update((5).to_bytes(4, "big") + b"\x00")   # complaint = 0
    h.update(b"nonce")
    assert b.hash() == h.digest()


def test_justification_bundle_hash_layout():
    b = pk.JustificationBundle(
        dealer_index=7,
        justifications=(pk.Justification(4, 0xDEADBEEF),
                        pk.Justification(1, 3)),
        session_id=b"sid")
    h = hashlib.sha256()
    h.update((7).to_bytes(4, "big"))
    h.update((1).to_bytes(4, "big") + (3).to_bytes(32, "big"))
    h.update((4).to_bytes(4, "big") + (0xDEADBEEF).to_bytes(32, "big"))
    h.update(b"sid")
    assert b.hash() == h.digest()


def test_schnorr_challenge_is_kyber_layout():
    sk = 0x51E77
    msg = b"dkg packet bytes"
    sig = schnorr.sign(sk, msg)
    pub = PointG1.generator().mul(sk)
    assert schnorr.verify(pub, msg, sig)
    # re-derive the challenge exactly as kyber's schnorr.go hash() and
    # re-check the verification equation s*G == R + c*pub by hand
    big_r = PointG1.from_bytes(sig[:48])
    s = int.from_bytes(sig[48:], "big")
    c = int.from_bytes(
        hashlib.sha512(sig[:48] + pub.to_bytes() + msg).digest(),
        "big") % schnorr.R
    assert PointG1.generator().mul(s) == big_r + pub.mul(c)


def test_bundle_hash_pinned_vectors():
    """Frozen digests: any layout change must be a conscious decision."""
    d = pk.DealBundle(1, (b"\x0a" * 48,), (pk.Deal(0, b"x"),), b"s").hash()
    r = pk.ResponseBundle(0, (pk.Response(1, 1),), b"s").hash()
    j = pk.JustificationBundle(2, (pk.Justification(0, 9),), b"s").hash()
    assert d.hex() == hashlib.sha256(
        (1).to_bytes(4, "big") + (0).to_bytes(4, "big") + b"x"
        + b"\x0a" * 48 + b"s").hexdigest()
    assert r.hex() == hashlib.sha256(
        (0).to_bytes(4, "big") + (1).to_bytes(4, "big") + b"\x01"
        + b"s").hexdigest()
    assert j.hex() == hashlib.sha256(
        (2).to_bytes(4, "big") + (0).to_bytes(4, "big")
        + (9).to_bytes(32, "big") + b"s").hexdigest()
