"""Self-healing beacon plane (ISSUE 12): the shared retry policy,
per-peer circuit breakers, quorum repair, and degraded-mode serving —
each proven through the observability surfaces the chaos oracle
already trusts (margins, bitmaps, the missed counter, the new
self-healing metric set).

Late-alphabet filename per the tier-1 chunking convention (ROADMAP
operational constraint). Host-only: structural crypto where a network
runs, no device graphs, no fresh XLA compiles.
"""

import asyncio
import random

import aiohttp
import pytest
from conftest import sample_count as _sample_count

from drand_tpu import metrics
from drand_tpu.chain.beacon import Beacon
from drand_tpu.chain.info import Info
from drand_tpu.client.interface import Client, ClientError, Result
from drand_tpu.crypto.curves import PointG1
from drand_tpu.http_server.server import PublicServer
from drand_tpu.net.packets import PartialRequest
from drand_tpu.net.transport import (BREAKER_CLOSED, BREAKER_HALF_OPEN,
                                     BREAKER_OPEN, PeerBreaker,
                                     PeerRejectedError, TransportError)
from drand_tpu.obs.state import isolated_observability
from drand_tpu.testing import chaos as chaos_mod
from drand_tpu.testing.chaos import (ChaosBeaconNetwork, FaultEvent,
                                     LinkPolicy, structural_crypto)
from drand_tpu.utils.clock import FakeClock
from drand_tpu.utils.retry import RetryPolicy, retry

PERIOD = 4


def _retries(op, outcome):
    return _sample_count(metrics.GROUP_REGISTRY, "net_retry_attempts",
                         op=op, outcome=outcome)


def _repairs(outcome):
    return _sample_count(metrics.GROUP_REGISTRY, "beacon_partial_repairs",
                         outcome=outcome)


async def _drive(clock: FakeClock, task: asyncio.Future) -> None:
    """Step a FakeClock through every wake target until the task ends."""
    while not task.done():
        await asyncio.sleep(0)
        nw = clock.next_wake()
        if nw is not None:
            await clock.advance(nw - clock.now())


# ---------------------------------------------------------------------------
# 1. the retry policy: backoff window, deadline awareness, outcome metrics
# ---------------------------------------------------------------------------

@pytest.mark.asyncio
async def test_retry_policy_backoff_deadline_and_outcomes():
    with isolated_observability():
        clock = FakeClock()
        calls = {"n": 0}

        async def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise TransportError("transient")
            return "done"

        ok0, rt0 = _retries("partial", "ok"), _retries("partial", "retry")
        t0 = clock.now()
        task = asyncio.ensure_future(retry(
            flaky, op="partial",
            policy=RetryPolicy(attempts=5, base_s=0.1, cap_s=1.0),
            clock=clock, rng=random.Random(7),
            retry_on=(TransportError,)))
        await _drive(clock, task)
        assert task.result() == "done" and calls["n"] == 3
        assert _retries("partial", "ok") == ok0 + 1
        assert _retries("partial", "retry") == rt0 + 2
        # two decorrelated-jitter sleeps, each within [base, cap]
        elapsed = clock.now() - t0
        assert 0.2 <= elapsed <= 2.0

        # deadline-aware: the next sleep would cross the budget, so the
        # failure surfaces as exhausted WITHOUT sleeping past it
        async def always_down():
            raise TransportError("down")

        ex0 = _retries("sync", "exhausted")
        t0 = clock.now()
        task = asyncio.ensure_future(retry(
            always_down, op="sync",
            policy=RetryPolicy(attempts=10, base_s=0.5, cap_s=0.5,
                               deadline_s=1.2),
            clock=clock, rng=random.Random(7),
            retry_on=(TransportError,)))
        await _drive(clock, task)
        with pytest.raises(TransportError):
            task.result()
        assert _retries("sync", "exhausted") == ex0 + 1
        assert clock.now() - t0 <= 1.2 + 1e-9

        # non-retryable classification: one attempt, outcome rejected
        async def answered_no():
            calls["n"] += 1
            raise PeerRejectedError("stale round")

        calls["n"] = 0
        rj0 = _retries("partial", "rejected")
        with pytest.raises(PeerRejectedError):
            await retry(answered_no, op="partial", clock=clock,
                        retry_on=(TransportError,),
                        no_retry=(PeerRejectedError,))
        assert calls["n"] == 1
        assert _retries("partial", "rejected") == rj0 + 1


# ---------------------------------------------------------------------------
# 2. breaker unit matrix: trip, immunity, half-open probe cap
# ---------------------------------------------------------------------------

def test_breaker_trip_threshold_and_reject_immunity():
    states = []
    br = PeerBreaker(3, threshold=3, cooldown_s=10.0,
                     on_state=lambda i, s: states.append((i, s)))
    assert states == [(3, BREAKER_CLOSED)]
    # answered-with-reject resets the consecutive-failure count: a
    # lagging-but-alive peer can NEVER trip the breaker
    for _ in range(10):
        br.record(False, 0.0)
        br.record(False, 0.0)
        br.record(True, 0.0)  # PeerRejectedError classifies as ok
    assert br.state == BREAKER_CLOSED
    # three consecutive transport failures trip it
    for _ in range(2):
        br.record(False, 0.0)
    assert br.state == BREAKER_CLOSED
    br.record(False, 0.0)
    assert br.state == BREAKER_OPEN
    assert states[-1] == (3, BREAKER_OPEN)


def test_breaker_half_open_probe_cap_and_reclose():
    br = PeerBreaker(0, threshold=2, cooldown_s=10.0)
    br.record(False, 0.0)
    br.record(False, 0.0)
    assert br.state == BREAKER_OPEN
    assert not br.allow(9.9)
    # one probe per cooldown window, concurrent callers denied
    assert br.allow(10.0) and br.state == BREAKER_HALF_OPEN
    assert not br.allow(10.0)
    assert not br.allow(19.9)
    # a probe failing LATE (slow link) must not push the reserved slot
    br.record(False, 15.0)
    assert br.state == BREAKER_OPEN
    assert br.allow(20.0), "next probe slot was reserved at grant time"
    # failures from sends that passed allow() before the trip never
    # move the slot either
    br.record(False, 21.0)
    assert not br.allow(25.0)
    assert br.allow(30.0)
    br.record(True, 30.0)
    assert br.state == BREAKER_CLOSED
    # wedge regression: a granted probe whose outcome NEVER lands
    # (caller died between allow and record) must not blacklist the
    # peer forever — the reserved slot expires after a full cooldown
    br.record(False, 40.0)
    br.record(False, 40.0)
    assert br.allow(50.0) and br.state == BREAKER_HALF_OPEN
    # outcome never recorded; within the reserved window: denied
    assert not br.allow(55.0)
    # past it: grantable again
    assert br.allow(60.0)


# ---------------------------------------------------------------------------
# 3. quorum repair: the drop-the-push round recovers inside its period
# ---------------------------------------------------------------------------

# n=5, t=4 drop matrix: nodes 3 and 4 push to nobody, and node 0's
# pushes to 3 and 4 are lost too — every node's received set stays
# below t (0,1,2 hold {0,1,2}; 3 holds {1,2,3}; 4 holds {1,2,4}), so
# the round misses on the passive plane; the union covers all 5
# indices, so pulls recover it. Drops are receiver-side (in flight):
# every sender saw a successful send — retries and breakers stay out
# of the picture, this is PURELY the pull's win.
def _drop_the_push(at_round: int) -> list[FaultEvent]:
    evs = []
    for src in (3, 4):
        for dst in range(5):
            if dst != src:
                evs.append(FaultEvent(at_round, "link",
                                      {"src": src, "dst": dst,
                                       "policy": LinkPolicy(drop=1.0)}))
    for dst in (3, 4):
        evs.append(FaultEvent(at_round, "link",
                              {"src": 0, "dst": dst,
                               "policy": LinkPolicy(drop=1.0)}))
    return evs


@pytest.mark.asyncio
async def test_quorum_repair_recovers_dropped_push_with_margin():
    with structural_crypto(), isolated_observability():
        net = ChaosBeaconNetwork(n=5, t=4, period=PERIOD)
        await net.start_all()
        await net.advance_to_genesis()
        rec0 = _repairs("recovered")
        obs = await net.run_schedule(_drop_the_push(4), rounds=6)
        net.stop_all()

        faulted = [ob for ob in obs if ob.round >= 4]
        assert faulted
        for ob in faulted:
            # the round that would have missed recovers INSIDE its own
            # period: stored, margin still positive, missed never moves
            assert ob.stored, f"round {ob.round} missed despite repair"
            assert ob.missed_total == 0
            assert ob.margin_s is not None and ob.margin_s > 0
            # the bitmap shows a full quorum of contributors, at least
            # one of them a dark pusher (3 or 4) whose partial ONLY a
            # pull could have delivered; the pull stops at threshold,
            # so one column may legitimately stay dark
            marks = sum(ob.bitmap.count(c) for c in "#~")
            assert marks >= 4, ob.bitmap
            assert ob.bitmap[3] in "#~" or ob.bitmap[4] in "#~", ob.bitmap
        assert _repairs("recovered") > rec0
        # the repair milestone landed on the probe's flight record
        rec = next(r for r in net.flight(0).rounds(16)
                   if r["round"] == faulted[0].round)
        names = [m["name"] for m in rec["milestones"]]
        assert "repair" in names


@pytest.mark.asyncio
async def test_same_drop_schedule_misses_without_repair():
    """The acceptance control: the identical schedule on the passive
    (pre-ISSUE-12) plane misses rounds — asserted through the same
    missed counter + bitmap surfaces."""
    with structural_crypto(), isolated_observability():
        net = ChaosBeaconNetwork(n=5, t=4, period=PERIOD, repair=False)
        await net.start_all()
        await net.advance_to_genesis()
        obs = await net.run_schedule(_drop_the_push(4), rounds=6)
        net.stop_all()

        assert max(ob.missed_total for ob in obs) >= 1
        missed = [ob for ob in obs if not ob.stored]
        assert missed, "drop-the-push stored everything without repair?"
        # the probe's bitmap fingers the dark pushers
        withmap = [ob for ob in obs if ob.round >= 4 and ob.bitmap]
        for ob in withmap:
            assert ob.bitmap[3] == "." and ob.bitmap[4] == ".", ob.bitmap


# ---------------------------------------------------------------------------
# 4. breaker keeps send growth bounded through a no-heal partition
# ---------------------------------------------------------------------------

@pytest.mark.asyncio
async def test_breaker_bounds_sends_during_no_heal_partition():
    with structural_crypto(), isolated_observability():
        net = ChaosBeaconNetwork(n=4, t=3, period=PERIOD)
        await net.start_all()
        await net.advance_to_genesis()
        await net.run_schedule([], rounds=2)
        f0 = _sample_count(metrics.GROUP_REGISTRY, "beacon_peer_sends",
                           index="3", outcome="failed")
        sched = [FaultEvent(4, "partition",
                            {"groups": [[0, 1, 2], [3]]})]
        obs = await net.run_schedule(sched, rounds=6)
        net.stop_all()

        # majority keeps quorum the whole way
        for ob in obs:
            assert ob.stored and ob.missed_total == 0
        # every surviving node's breaker for peer 3 is OPEN
        for h in net.handlers[:3]:
            assert h._breakers[3].state == BREAKER_OPEN
        # bounded growth: without the breaker each of the 3 senders
        # would burn its full retry budget every round (3 senders x 6
        # rounds x 3 attempts = 54 failed sends); with it, each sender
        # pays the one trip burst plus at most one capped probe per
        # round
        failed = _sample_count(metrics.GROUP_REGISTRY,
                               "beacon_peer_sends",
                               index="3", outcome="failed") - f0
        assert failed > 0
        assert failed <= 3 * (3 + 6), failed
        assert metrics.PEER_BREAKER_STATE.labels(
            index="3")._value.get() == BREAKER_OPEN


# ---------------------------------------------------------------------------
# 5. the repair-serving surface: window + per-sender rate cap
# ---------------------------------------------------------------------------

@pytest.mark.asyncio
async def test_request_partials_window_and_rate_cap():
    from drand_tpu.chain.engine import handler as handler_mod

    with structural_crypto(), isolated_observability():
        net = ChaosBeaconNetwork(n=3, t=2, period=PERIOD)
        await net.start_all()
        await net.advance_to_genesis()
        await net.run_schedule([], rounds=2)
        h = net.handlers[0]
        last = net.stores[0].last()

        # stored rounds are not repairable (the sync path's job)
        with pytest.raises(TransportError):
            await h.request_partials(
                "attacker:1", PartialRequest(round=last.round,
                                             previous_sig=last.signature))
        # the live window serves the collector's verified set, minus
        # what the requester already holds
        live = PartialRequest(round=last.round + 1,
                              previous_sig=last.signature)
        served = await h.request_partials("peer:1", live)
        assert all(p.round == last.round + 1 for p in served)
        have_all = PartialRequest(round=last.round + 1,
                                  previous_sig=last.signature,
                                  have=(0, 1, 2))
        assert await h.request_partials("peer:1", have_all) == []
        # per-sender per-round rate cap refuses at the door
        for _ in range(handler_mod.REPAIR_SERVE_CAP - 2):
            await h.request_partials("peer:1", live)
        with pytest.raises(TransportError, match="rate-capped"):
            await h.request_partials("peer:1", live)
        # a different sender still gets served
        assert await h.request_partials("peer:2", live) is not None
        # an address flood cannot reset a capped sender's budget: after
        # spraying live-round requests from many spoofed addresses,
        # the original sender is STILL refused
        for i in range(4 * 3 + 2):
            try:
                await h.request_partials(f"spoof:{i}", live)
            except TransportError:
                pass
        with pytest.raises(TransportError, match="rate-capped"):
            await h.request_partials("peer:1", live)
        net.stop_all()


# ---------------------------------------------------------------------------
# 6. syncer failover: resumable checkpoint, no re-verify after a death
# ---------------------------------------------------------------------------

@pytest.mark.asyncio
async def test_syncer_resumes_without_reverifying_after_upstream_death():
    from drand_tpu.chain import beacon as chain_beacon
    from drand_tpu.chain.store import AppendStore, CallbackStore, MemStore
    from drand_tpu.chain.engine.sync import Syncer
    from drand_tpu.crypto import batch
    from drand_tpu.utils.logging import default_logger

    with structural_crypto(), isolated_observability():
        # a 12-round structural chain
        chain = []
        prev = b"\x00" * 96
        for r in range(1, 13):
            sig = chaos_mod.group_sig(chain_beacon.message(r, prev))
            chain.append(Beacon(round=r, previous_sig=prev, signature=sig))
            prev = sig
        info = Info(public_key=PointG1.generator(), period=PERIOD,
                    genesis_time=100, genesis_seed=b"seed",
                    group_hash=b"gh")
        store = CallbackStore(AppendStore(MemStore()))
        store.put(Beacon(round=0, previous_sig=b"", signature=b"\x00" * 96))

        state = {"dead_once": False}

        class StubClient:
            async def sync_chain(self, peer, req):
                for b in chain:
                    if b.round < req.from_round:
                        continue
                    if not state["dead_once"] and b.round > 5:
                        # mid-chunk upstream death on the first pass
                        state["dead_once"] = True
                        raise TransportError("upstream died")
                    yield b

        verified = []
        real = batch.verify_beacons

        def counting(pub, beacons, *a, **kw):
            verified.extend(b.round for b in beacons)
            return real(pub, beacons, *a, **kw)

        batch.verify_beacons = counting
        try:
            rt0 = _retries("sync", "retry")
            sy = Syncer(default_logger("t", level="none"), store, info,
                        StubClient(), clock=FakeClock())
            task = asyncio.ensure_future(sy.follow(12, ["peer"]))
            await _drive(sy._clock, task)
            assert task.result() is True
        finally:
            batch.verify_beacons = real

        assert store.last().round == 12
        # the second pass resumed from the checkpoint: every round
        # verified EXACTLY once, the stored span never re-fetched
        assert sorted(verified) == list(range(1, 13))
        assert _retries("sync", "retry") >= rt0 + 1


# ---------------------------------------------------------------------------
# 7. degraded-mode serving: stale /public/latest with the explicit header
# ---------------------------------------------------------------------------

class _FlakyUpstream(Client):
    """Serves one beacon, then the upstream 'dies' on demand."""

    def __init__(self, info: Info, result: Result):
        self._info = info
        self._result = result
        self.dead = False

    async def get(self, round_no: int = 0) -> Result:
        if self.dead:
            raise ClientError("upstream unreachable")
        return self._result

    async def info(self) -> Info:
        if self.dead:
            raise ClientError("upstream unreachable")
        return self._info

    async def watch(self):
        if self.dead:
            raise ClientError("upstream unreachable")
        yield self._result
        await asyncio.Event().wait()


async def _get(port, path):
    async with aiohttp.ClientSession() as s:
        async with s.get(f"http://127.0.0.1:{port}{path}") as r:
            return r.status, dict(r.headers), await r.json()


@pytest.mark.asyncio
async def test_relay_serves_stale_with_header_when_upstream_lost():
    with isolated_observability():
        info = Info(public_key=PointG1.generator(), period=1,
                    genesis_time=1, genesis_seed=b"s", group_hash=b"g")
        res = Result(round=7, signature=b"\x07" * 96,
                     previous_signature=b"\x06" * 96)
        upstream = _FlakyUpstream(info, res)
        server = PublicServer(upstream)
        site = await server.start("127.0.0.1", 0)
        port = site._server.sockets[0].getsockname()[1]
        try:
            await asyncio.sleep(0.05)  # let the watch loop prime _latest
            s0 = _sample_count(metrics.HTTP_REGISTRY, "relay_stale_served")
            status, headers, body = await _get(port, "/public/latest")
            assert status == 200 and body["round"] == 7
            assert "X-Drand-Stale" not in headers

            upstream.dead = True
            status, headers, body = await _get(port, "/public/latest")
            # degraded mode: last-known beacon, explicit staleness, 200
            assert status == 200 and body["round"] == 7
            assert int(headers["X-Drand-Stale"]) > 0
            assert headers["Cache-Control"] == "no-store"
            assert _sample_count(metrics.HTTP_REGISTRY,
                                 "relay_stale_served") == s0 + 1
        finally:
            await server.stop()

        # a relay that never saw a beacon still 404s — stale serving
        # needs something to be stale
        dead = _FlakyUpstream(info, res)
        dead.dead = True
        server = PublicServer(dead)
        site = await server.start("127.0.0.1", 0)
        port = site._server.sockets[0].getsockname()[1]
        try:
            status, headers, _ = await _get(port, "/public/latest")
            assert status == 404
            assert "X-Drand-Stale" not in headers
        finally:
            await server.stop()
