"""Round-lifecycle tracing (obs/trace.py): span nesting, ring bounds,
cross-node correlation-id propagation, and log/metric correlation."""

import asyncio
import logging

import pytest
from conftest import sample_count

from drand_tpu import metrics
from drand_tpu.net.transport import LocalNetwork, ProtocolService
from drand_tpu.obs import trace
from drand_tpu.utils.logging import KVLogger, default_logger


@pytest.fixture(autouse=True)
def _fresh_obs():
    # the scoped helper (obs/state.py): every singleton reset on entry
    # AND exit, so no scenario inherits or bequeaths recorder state
    from drand_tpu.obs.state import isolated_observability

    with isolated_observability():
        yield


def _stage_count(stage: str) -> float:
    return sample_count(metrics.GROUP_REGISTRY, "beacon_stage_seconds",
                        stage=stage)


# ---------------------------------------------------------------- ids

def test_round_trace_id_deterministic_across_nodes():
    # every group member derives the same id for the same (chain, round)
    a = trace.round_trace_id(7, b"seed")
    b = trace.round_trace_id(7, b"seed")
    assert a == b and len(a) == 32 and int(a, 16) >= 0
    assert trace.round_trace_id(8, b"seed") != a
    assert trace.round_trace_id(7, b"other-chain") != a


def test_traceparent_roundtrip_and_malformed():
    tid = trace.round_trace_id(3, b"c")
    hdr = trace.make_traceparent(tid, "ab" * 8)
    assert trace.parse_traceparent(hdr) == (tid, "ab" * 8)
    for bad in (None, "", "00-zz-ff-01", "xx", "00-" + "0" * 32 + "-01",
                "00-" + "g" * 32 + "-" + "0" * 16 + "-01",
                # int(x, 16) laxness must not leak through: 0x / sign /
                # underscore / uppercase forms are malformed per W3C
                "00-0x" + "0" * 28 + "aa-" + "0" * 16 + "-01",
                "00-+" + "0" * 31 + "-" + "0" * 16 + "-01",
                "00-" + "0" * 30 + "_1-" + "0" * 16 + "-01",
                "00-" + "A" * 32 + "-" + "0" * 16 + "-01"):
        assert trace.parse_traceparent(bad) is None


# -------------------------------------------------------------- spans

def test_span_nesting_and_ring_record():
    with trace.TRACER.activate(round_no=5, chain=b"seed") as tid:
        assert trace.current_trace_id() == tid
        assert trace.current_round() == 5
        with trace.TRACER.span("outer") as outer:
            with trace.TRACER.span("inner", detail=1) as inner:
                assert inner.parent_id == outer.span_id
    assert trace.current_trace_id() is None
    rounds = trace.TRACER.rounds(4)
    assert len(rounds) == 1
    rec = rounds[0]
    assert rec["round"] == 5 and rec["trace_id"] == tid
    by_name = {s["name"]: s for s in rec["spans"]}
    assert by_name["inner"]["parent_id"] == by_name["outer"]["span_id"]
    assert by_name["inner"]["attrs"] == {"detail": 1}
    assert all(s["duration_ms"] >= 0.0 for s in rec["spans"])


def test_span_without_context_hits_histogram_not_ring():
    before = _stage_count("orphan_stage")
    with trace.TRACER.span("orphan_stage"):
        pass
    assert _stage_count("orphan_stage") == before + 1
    assert trace.TRACER.rounds(8) == []


def test_ring_bounds_rounds_and_spans():
    t = trace.Tracer(max_rounds=3, max_spans=2)
    for r in range(1, 6):
        with t.activate(round_no=r, chain=b"x"):
            for _ in range(4):  # 2 over the per-round span cap
                with t.span("s"):
                    pass
    recs = t.rounds(10)
    assert [rec["round"] for rec in recs] == [5, 4, 3]  # oldest evicted
    for rec in recs:
        assert len(rec["spans"]) == 2 and rec["dropped"] == 2


def test_retain_false_feeds_histograms_not_new_ring_entries():
    t = trace.Tracer(max_rounds=4)
    # a live round timeline exists...
    with t.activate(round_no=1, chain=b"x"):
        with t.span("store"):
            pass
    # ...then a historical catch-up sweep (retain=False) flies past:
    # histograms move, the live entry survives, no new entries appear
    before = _stage_count("sync_verify")
    for r in range(100, 120):
        with t.activate(round_no=r, chain=b"x", retain=False):
            with t.span("sync_verify"):
                pass
    assert _stage_count("sync_verify") == before + 20
    assert [rec["round"] for rec in t.rounds(10)] == [1]
    # retain=False still APPENDS to an existing live entry
    with t.activate(round_no=1, chain=b"x", retain=False):
        with t.span("gossip_validate"):
            pass
    assert len(t.rounds(1)[0]["spans"]) == 2


def test_span_marks_error_on_exception():
    ok_before = _stage_count("recover")
    err_before = _stage_count("recover_error")
    with trace.TRACER.activate(round_no=6, chain=b"seed"):
        with pytest.raises(RuntimeError):
            with trace.TRACER.span("recover"):
                raise RuntimeError("wedged dispatch")
    (sp,) = trace.TRACER.rounds(1)[0]["spans"]
    assert sp["attrs"]["error"] is True
    assert sp["duration_ms"] is not None
    # a wedged dispatch's duration must not masquerade as real recover
    # latency: failed stages land under stage="recover_error"
    assert _stage_count("recover") == ok_before
    assert _stage_count("recover_error") == err_before + 1
    # ...but a semantic rejection (ValueError: below-threshold round)
    # is an instant raise, not a wedged stage — it lands under
    # "recover_invalid" so *_error alerts don't page on degraded rounds
    inv_before = _stage_count("recover_invalid")
    with trace.TRACER.activate(round_no=7, chain=b"seed"):
        with pytest.raises(ValueError):
            with trace.TRACER.span("recover"):
                raise ValueError("not enough valid partials: 1 < 2")
    assert _stage_count("recover_invalid") == inv_before + 1
    assert _stage_count("recover_error") == err_before + 1
    # task cancellation (daemon stop mid-stage) is routine, not failure
    can_before = _stage_count("breather_cancelled")
    with trace.TRACER.activate(round_no=8, chain=b"seed"):
        with pytest.raises(asyncio.CancelledError):
            with trace.TRACER.span("breather"):
                raise asyncio.CancelledError()
    assert _stage_count("breather_cancelled") == can_before + 1
    assert _stage_count("breather_error") == 0.0


def test_adopt_traceparent_stitches_remote_spans():
    tid = trace.round_trace_id(9, b"seed")
    hdr = trace.make_traceparent(tid, "11" * 8)
    with trace.TRACER.activate_traceparent(hdr):
        with trace.TRACER.span("remote_leg") as sp:
            assert sp.trace_id == tid
            assert sp.parent_id == "11" * 8
    # malformed ingress is a no-op passthrough
    with trace.TRACER.activate_traceparent("not-a-traceparent"):
        assert trace.current_trace_id() is None


# ------------------------------------------- cross-node propagation

class _RecordingService(ProtocolService):
    def __init__(self):
        self.seen: list[str | None] = []

    async def process_partial_beacon(self, from_addr, packet):
        self.seen.append(trace.current_trace_id())


@pytest.mark.asyncio
async def test_trace_context_propagates_over_local_network():
    net = LocalNetwork()
    svc = _RecordingService()
    net.register("b.test:1", svc)
    client = net.client_for("a.test:1")

    class _Peer:
        def address(self):
            return "b.test:1"

    with trace.TRACER.activate(round_no=4, chain=b"seed") as tid:
        await client.partial_beacon(_Peer(), None)
        # tasks spawned inside the context copy it (the broadcast path)
        task = asyncio.ensure_future(client.partial_beacon(_Peer(), None))
    await task
    assert svc.seen == [tid, tid]


def test_grpc_metadata_helpers_roundtrip():
    assert trace.outbound_metadata() is None  # no active context
    with trace.TRACER.activate(round_no=2, chain=b"seed") as tid:
        md = trace.outbound_metadata()
    assert md is not None

    class _Ctx:
        def invocation_metadata(self):
            return md

    class _Raising:
        def invocation_metadata(self):
            raise RuntimeError("broken call context")

    parsed = trace.parse_traceparent(trace.traceparent_from_context(_Ctx()))
    assert parsed is not None and parsed[0] == tid
    # untrusted ingress must never raise out of the helper
    assert trace.traceparent_from_context(_Raising()) is None
    assert trace.traceparent_from(object()) is None


# ------------------------------------------------- log correlation

def test_kv_log_lines_carry_round_correlation(caplog):
    logger = KVLogger("trace-corr-test")
    with caplog.at_level(logging.INFO, logger="trace-corr-test"):
        with trace.TRACER.activate(round_no=11, chain=b"seed") as tid:
            logger.info("aggregator", "stored")
        logger.info("aggregator", "outside")
    inside, outside = caplog.messages
    assert f"trace={tid}" in inside and "round=11" in inside
    assert "trace=" not in outside


def test_default_logger_accepts_aliases_and_bad_levels():
    # "warn"/"warning"/"error" are valid; junk falls back to info
    # instead of raising KeyError at daemon startup
    for lvl, expect in (("warn", logging.WARNING),
                       ("Warning", logging.WARNING),
                       ("ERROR", logging.ERROR),
                       ("debug", logging.DEBUG),
                       ("bogus", logging.INFO)):
        lg = default_logger("lvl-test", level=lvl)
        assert lg._log.level == expect
