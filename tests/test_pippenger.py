"""Pippenger MSM golden tests vs the host reference.

Small scalar widths AND a small window (c=2) keep suite compile time
bounded while exercising every structural element (windowing, bucket
select, tree reduction with infinity padding, suffix-sum combine, window
doubling chain) — the scan body's size scales with 2^c point-ops and at
the engine's default c=4 each XLA:CPU compile runs many minutes; the
full 255-bit c=4 G2 shape is exercised by the engine recovery path and
bench on the TPU.
"""

import random

import numpy as np
import pytest

pytestmark = pytest.mark.device

import jax
import jax.numpy as jnp

from drand_tpu.crypto.curves import PointG1, PointG2
from drand_tpu.crypto.fields import R
from drand_tpu.ops import curve

NBITS = 40


def _bits(k: int) -> np.ndarray:
    return curve.scalar_to_bits(k, NBITS)


@pytest.mark.parametrize("n,cls", [(6, PointG1), (5, PointG2)])
def test_pippenger_matches_host(n, cls):
    rng = random.Random(1000 + n)
    F = curve.F1 if cls is PointG1 else curve.F2
    conv = curve.g1_to_device if cls is PointG1 else curve.g2_to_device
    back = curve.g1_from_device if cls is PointG1 else curve.g2_from_device
    pts = [cls.generator().mul(rng.randrange(1, R)) for _ in range(n)]
    ks = [rng.randrange(0, 1 << NBITS) for _ in range(n)]
    ptd = curve.stack_points([conv(p) for p in pts])
    bits = jnp.asarray(np.stack([_bits(k) for k in ks]))
    got = jax.jit(lambda p, b: curve.msm_pippenger(F, p, b, c=2))(ptd, bits)
    host = cls.msm(ks, pts)
    assert back(tuple(np.asarray(x) for x in got)) == host


def test_pippenger_zero_scalars_and_infinity_points():
    rng = random.Random(7)
    pts = [PointG1.generator().mul(rng.randrange(1, R)) for _ in range(3)]
    pts.append(PointG1.infinity())
    ks = [0, rng.randrange(1, 1 << NBITS), 0, rng.randrange(1, 1 << NBITS)]
    ptd = curve.stack_points([curve.g1_to_device(p) for p in pts])
    bits = jnp.asarray(np.stack([_bits(k) for k in ks]))
    got = jax.jit(lambda p, b: curve.msm_pippenger(curve.F1, p, b,
                                                   c=2))(ptd, bits)
    host = PointG1.msm(ks, pts)
    assert curve.g1_from_device(tuple(np.asarray(x) for x in got)) == host
