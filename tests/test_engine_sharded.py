"""Mesh-sharded engine verification — the batch axis distributed over a
device mesh must give identical results to the single-device engine.

Runs on the conftest-provisioned 8-device virtual CPU mesh (the driver's
dryrun_multichip validates the same pattern; real multi-chip TPU uses
the shard_map Pallas variant). SURVEY §5: catchup verification sharded
across chips with pjit.
"""

import numpy as np
import pytest

pytestmark = pytest.mark.device
import jax


@pytest.fixture
def mesh():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")
    from jax.sharding import Mesh

    return Mesh(np.array(devs[:8]), ("data",))


def _triples(n, sk=0x515):
    from drand_tpu.crypto import bls
    from drand_tpu.crypto.curves import PointG1, PointG2
    from drand_tpu.crypto.hash_to_curve import hash_to_g2

    pub = PointG1.generator().mul(sk)
    out, want = [], []
    for i in range(n):
        m = b"shard-%d" % i
        sig = PointG2.from_bytes(bls.sign(sk, m), subgroup_check=False)
        bad = i % 5 == 2
        out.append((pub, sig, hash_to_g2(b"other" if bad else m)))
        want.append(not bad)
    return out, want


def test_sharded_verify_matches_single_device(mesh):
    from drand_tpu.ops.engine import BatchedEngine

    triples, want = _triples(13)
    single = BatchedEngine(buckets=(16,))
    sharded = BatchedEngine(buckets=(16,), mesh=mesh)
    out_s = single.verify_bls(triples)
    out_m = sharded.verify_bls(triples)
    assert list(out_s) == want
    assert list(out_m) == want


def test_sharded_bucket_kat_gates(mesh):
    """The sharded path goes through the same known-answer validation."""
    from drand_tpu.ops.engine import BatchedEngine

    eng = BatchedEngine(buckets=(16,), mesh=mesh)
    assert eng._check_bucket(16) is True
    assert eng._bucket_ok == {16: True}
