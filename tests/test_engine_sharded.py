"""Mesh-sharded engine verification — the batch axis distributed over a
device mesh must give identical results to the single-device engine.

Runs on the conftest-provisioned 8-device virtual CPU mesh (the driver's
dryrun_multichip validates the same pattern; real multi-chip TPU uses
the shard_map Pallas variant). SURVEY §5: catchup verification sharded
across chips with pjit.

ISSUE 8 additions: the SHARDED wire-RLC catch-up tier (per-shard device
h2c + decompression + lane-MSM, one cross-shard reduction, one pairing
row per span — meter-proven 2 Miller pairs), the pad-to-mesh fix for
buckets that don't divide the mesh, and the dispatcher's
``wire_rlc_sharded`` path label.
"""

import types as _types

import numpy as np
import pytest

pytestmark = pytest.mark.device
import jax

from conftest import sample_count as _sample_count


@pytest.fixture
def mesh():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")
    from jax.sharding import Mesh

    return Mesh(np.array(devs[:8]), ("data",))


@pytest.fixture(scope="module")
def mesh4():
    devs = jax.devices()
    if len(devs) < 4:
        pytest.skip("needs >= 4 virtual CPU devices")
    from jax.sharding import Mesh

    return Mesh(np.array(devs[:4]), ("data",))


def _triples(n, sk=0x515):
    from drand_tpu.crypto import bls
    from drand_tpu.crypto.curves import PointG1, PointG2
    from drand_tpu.crypto.hash_to_curve import hash_to_g2

    pub = PointG1.generator().mul(sk)
    out, want = [], []
    for i in range(n):
        m = b"shard-%d" % i
        sig = PointG2.from_bytes(bls.sign(sk, m), subgroup_check=False)
        bad = i % 5 == 2
        out.append((pub, sig, hash_to_g2(b"other" if bad else m)))
        want.append(not bad)
    return out, want


def test_sharded_verify_matches_single_device(mesh):
    from drand_tpu.ops.engine import BatchedEngine

    triples, want = _triples(13)
    single = BatchedEngine(buckets=(16,))
    sharded = BatchedEngine(buckets=(16,), mesh=mesh)
    out_s = single.verify_bls(triples)
    out_m = sharded.verify_bls(triples)
    assert list(out_s) == want
    assert list(out_m) == want


def test_sharded_bucket_kat_gates(mesh):
    """The sharded path goes through the same known-answer validation."""
    from drand_tpu.ops.engine import BatchedEngine

    eng = BatchedEngine(buckets=(16,), mesh=mesh)
    assert eng._check_bucket(16) is True
    assert eng._bucket_ok == {16: True}


def test_prime_bucket_pads_to_mesh(mesh):
    """Regression (ISSUE 8 satellite): a bucket that does not divide the
    mesh used to drop silently to a single device — it must now pad up
    to the next mesh multiple (generator rows masked out via ``valid``)
    and still produce exact verdicts on a prime-sized span."""
    from drand_tpu.ops.engine import BatchedEngine

    triples, want = _triples(13, sk=0x9B1)
    eng = BatchedEngine(buckets=(13,), mesh=mesh)
    out = eng.verify_bls(triples)
    assert list(out) == want
    # the dispatched executable really carries the padded, mesh-divisible
    # batch: 13 rows round up to 16 over the 8-way mesh
    dev, valid, n = eng._launch_bucket(triples, 13)
    assert np.asarray(dev).shape[0] == 16
    assert valid.shape == (16,) and not valid[13:].any()
    assert n == 13


# ---------------------------------------------------------------------------
# Sharded wire-RLC catch-up tier (ISSUE 8 tentpole)
# ---------------------------------------------------------------------------

def _chain(sk, nrounds):
    from drand_tpu.chain.beacon import Beacon, message
    from drand_tpu.crypto import bls

    prev, out = b"\x42" * 32, []
    for rnd in range(1, nrounds + 1):
        sig = bls.sign(sk, message(rnd, prev))
        out.append(Beacon(round=rnd, previous_sig=prev, signature=sig))
        prev = sig
    return out


class TestShardedWireRLC:
    @pytest.fixture(scope="class")
    def keys(self):
        from drand_tpu.crypto import bls

        return bls.keygen(seed=b"sharded-wire-rlc")

    @pytest.fixture(scope="class")
    def engine(self, mesh4):
        from drand_tpu.ops.engine import BatchedEngine

        eng = BatchedEngine(buckets=(8,), wire_prep=True, mesh=mesh4)
        eng.rlc_min = 2
        return eng

    def test_sharded_span_two_miller_pairs(self, engine, keys):
        """THE acceptance shape: an all-valid span through the SHARDED
        wire-RLC tier — per-shard h2c + decompression + lane-MSM, one
        cross-shard reduction — still dispatches exactly one pairing
        row = 2 Miller pairs for the whole span."""
        from drand_tpu.ops import engine as eng_mod

        sk, pub = keys
        beacons = _chain(sk, 8)
        got = engine.verify_beacons_wire_rlc(pub, beacons)
        assert got is not None and got.all() and len(got) == 8
        # the shard-shape KAT vouched for the sharded executable
        assert engine._wire_rlc_sharded_ok.get(8) is True
        assert engine._wire_rlc_ok == {}  # unsharded combine never built
        c0, p0 = eng_mod.N_PRODUCT_CHECKS, eng_mod.N_MILLER_PAIRS
        got = engine.verify_beacons_wire_rlc(pub, beacons)
        assert got is not None and got.all()
        assert eng_mod.N_PRODUCT_CHECKS - c0 == 1
        assert eng_mod.N_MILLER_PAIRS - p0 == 2

    def test_cross_shard_reduction_matches_host(self, engine, keys):
        """The gathered per-shard partial sums fold to exactly the host
        MSM of the same points and scalars — the single cross-shard
        reduction loses nothing."""
        from drand_tpu.chain.beacon import message
        from drand_tpu.crypto import batch_verify
        from drand_tpu.crypto.curves import PointG2
        from drand_tpu.crypto.hash_to_curve import (DEFAULT_DST_G2,
                                                    hash_to_g2)

        sk, pub = keys
        beacons = _chain(sk, 8)
        checks = [(message(b.round, b.previous_sig), b.signature)
                  for b in beacons]
        cs = [3 + 2 * i for i in range(8)]
        got = engine._combine_wire_chunk(checks, cs, 8, DEFAULT_DST_G2,
                                         sharded=True)
        assert got is not None
        mask, s_comb, m_comb = got
        assert list(mask) == [True] * 8
        sig_pts = [PointG2.from_bytes(s, subgroup_check=False)
                   for _, s in checks]
        msg_pts = [hash_to_g2(m) for m, _ in checks]
        assert s_comb == batch_verify.msm_window(sig_pts, cs, nbits=8)
        assert m_comb == batch_verify.msm_window(msg_pts, cs, nbits=8)

    def test_one_bad_lane_bisection_bit_identical(self, engine, keys):
        """A decodable-but-wrong signature fails the sharded combined
        check: the tier returns None (false-reject-only) and the
        per-item cascade produces verdicts bit-identical to the host
        oracle, flagging exactly the bad lane."""
        from drand_tpu.chain import beacon as chain_beacon

        sk, pub = keys
        beacons = _chain(sk, 8)
        beacons[3].signature = beacons[2].signature
        assert engine.verify_beacons_wire_rlc(pub, beacons) is None
        got = engine.verify_beacons(pub, beacons)
        oracle = [chain_beacon.verify_beacon(pub, b) for b in beacons]
        assert list(got) == oracle
        assert oracle == [True, True, True, False, True, True, True, True]

    def test_dispatch_label_wire_rlc_sharded(self, engine, keys,
                                             monkeypatch):
        """crypto/batch.py labels mesh-sharded spans under their own
        engine_op_seconds{path="wire_rlc_sharded"} (check_metrics lints
        the label into the documented set)."""
        from drand_tpu import metrics
        from drand_tpu.crypto import batch

        sk, pub = keys
        beacons = _chain(sk, 8)
        monkeypatch.delenv("DRAND_TPU_BATCH_VERIFY", raising=False)
        assert engine.wire_rlc_sharded_active(8) is True
        old = (batch._MODE, batch._MIN_BATCH, batch._ENGINE)
        batch.configure("device", engine=engine)
        try:
            out = batch.verify_beacons(pub, beacons)
            assert out.all() and len(out) == 8
            # first dispatch of the cold shape lands in
            # engine_compile_seconds (the ISSUE 6 split); the next one
            # samples the path label
            h1 = _sample_count(metrics.REGISTRY, "engine_op_seconds",
                               op="verify_beacons",
                               path="wire_rlc_sharded")
            out = batch.verify_beacons(pub, beacons)
            assert out.all()
            assert _sample_count(metrics.REGISTRY, "engine_op_seconds",
                                 op="verify_beacons",
                                 path="wire_rlc_sharded") == h1 + 1
        finally:
            batch._MODE, batch._MIN_BATCH, batch._ENGINE = old

    def test_introspect_reports_shard_family(self, engine):
        import json

        data = engine.introspect()
        json.dumps(data)
        assert data["mesh"] == {"axes": ["data"], "size": 4}
        assert data["wire_rlc_sharded_buckets"] == [8]
        assert data["kat"]["wire_rlc_sharded"] == {"b8/m4": True}

    def test_follow_chain_drives_sharded_path(self, engine, keys):
        """Integration (ISSUE 8 acceptance): a Syncer.follow catch-up
        over a stubbed peer stream verifies its span through the
        mesh-sharded wire-RLC tier — the dispatch lands under
        engine_op_seconds{path="wire_rlc_sharded"} and the whole chain
        stores."""
        import asyncio

        from drand_tpu import metrics
        from drand_tpu.chain.beacon import Beacon
        from drand_tpu.chain.engine import sync as sync_mod
        from drand_tpu.chain.store import CallbackStore, MemStore
        from drand_tpu.crypto import batch
        from drand_tpu.utils.logging import default_logger

        sk, pub = keys
        beacons = _chain(sk, 8)
        store = CallbackStore(MemStore())
        store.put(Beacon(round=0, previous_sig=b"",
                         signature=beacons[0].previous_sig))
        info = _types.SimpleNamespace(public_key=pub, genesis_seed=b"t")

        class _StubTransport:
            def sync_chain(self, peer, req):
                async def gen():
                    for b in beacons[req.from_round - 1:]:
                        yield b
                return gen()

        old = (batch._MODE, batch._MIN_BATCH, batch._ENGINE)
        batch.configure("device", min_batch=1, engine=engine)
        try:
            # configure() cleared the compile-split warm set: burn the
            # first (compile-labelled) dispatch so the follow below
            # samples the steady-state path label
            assert batch.verify_beacons(pub, beacons).all()
            n0 = _sample_count(metrics.REGISTRY, "engine_op_seconds",
                               op="verify_beacons",
                               path="wire_rlc_sharded")
            syncer = sync_mod.Syncer(default_logger("test.sync"), store,
                                     info, _StubTransport())
            assert asyncio.run(syncer.follow(8, ["peer"])) is True
            assert store.last().round == 8
            assert _sample_count(metrics.REGISTRY, "engine_op_seconds",
                                 op="verify_beacons",
                                 path="wire_rlc_sharded") == n0 + 1
        finally:
            batch._MODE, batch._MIN_BATCH, batch._ENGINE = old

    def test_sync_chunks_size_mesh_divisibly(self, engine, monkeypatch):
        """Syncer.follow's verify chunks round up to a mesh multiple so
        the sharded tier engages with all-live lanes."""
        from drand_tpu.chain.engine import sync as sync_mod
        from drand_tpu.crypto import batch

        old = (batch._MODE, batch._MIN_BATCH, batch._ENGINE)
        batch.configure("device", engine=engine)
        try:
            assert batch.engine_mesh_size() == 4
            monkeypatch.setattr(sync_mod, "SYNC_CHUNK", 13)
            assert sync_mod._verify_chunk_size() == 16
            monkeypatch.setattr(sync_mod, "SYNC_CHUNK", 64)
            assert sync_mod._verify_chunk_size() == 64
        finally:
            batch._MODE, batch._MIN_BATCH, batch._ENGINE = old
        assert batch.engine_mesh_size() in (1, 4)  # restored engine peek
