"""gRPC Public service (PublicRand / stream) and the TLS transport.

Reference: protobuf/drand/api.proto:15-31 (Public service),
client/grpc/client.go (gRPC source), net/listener.go:108 + net/certs.go
(TLS with a manually-trusted cert pool).
"""

import asyncio
import importlib.util

import pytest

from drand_tpu.chain.beacon import verify_beacon
from drand_tpu.client import new_client
from drand_tpu.client.grpc_source import GrpcSource
from drand_tpu.net import tls
from drand_tpu.net.grpc_transport import GrpcClient, GrpcGateway
from drand_tpu.net.transport import TransportError
from drand_tpu.testing.harness import BeaconTestNetwork
from drand_tpu.testing.mock_server import MockBeaconServer


class _PublicOnlyService:
    """Adapter: serve a BeaconTestNetwork node's chain over the Public
    surface (what the daemon does in production)."""

    def __init__(self, handler):
        self._h = handler

    async def public_rand(self, from_addr, round_no):
        store = self._h.chain
        b = store.last() if round_no == 0 else store.get(round_no)
        if b is None or b.round == 0:
            raise TransportError(f"no round {round_no}")
        return b

    async def public_rand_stream(self, from_addr):
        q = asyncio.Queue(maxsize=32)
        cb = f"t-{id(q)}"
        self._h.chain.add_callback(cb, q.put_nowait)
        try:
            while True:
                yield await q.get()
        finally:
            self._h.chain.remove_callback(cb)

    async def chain_info(self, from_addr):
        return self._h.crypto.chain_info


async def _make_live_gateway(tls_pair=None):
    net = BeaconTestNetwork(n=3, t=2, period=5)
    await net.start_all()
    await net.advance_to_genesis()
    for _ in range(3):
        await net.clock.advance(5)
    for i in range(3):
        await net.wait_round(i, 3)
    svc = _PublicOnlyService(net.nodes[0].handler)
    gw = GrpcGateway(svc, "127.0.0.1:0", tls=tls_pair)
    await gw.start()
    return net, gw, f"127.0.0.1:{gw.port}"


@pytest.mark.asyncio
async def test_grpc_public_rand_and_verified_stack():
    net, gw, addr = await _make_live_gateway()
    try:
        src = GrpcSource(addr)
        info = await src.info()
        r = await src.get(2)
        assert r.round == 2
        # full verified stack over gRPC
        client = new_client([src], chain_info=info)
        r3 = await client.get(3)
        assert r3.round == 3 and len(r3.randomness) == 32
        # missing round errors as ClientError
        from drand_tpu.client import ClientError

        with pytest.raises(ClientError):
            await src.get(99999)
        await src.close()
    finally:
        await gw.stop()
        net.stop_all()


@pytest.mark.asyncio
async def test_grpc_public_stream():
    net, gw, addr = await _make_live_gateway()
    try:
        src = GrpcSource(addr)

        async def take_one():
            async for r in src.watch():
                return r

        task = asyncio.ensure_future(take_one())
        await asyncio.sleep(0.2)  # let the stream register
        last = net.nodes[0].handler.chain.last().round
        await net.clock.advance(5)
        for i in range(3):
            await net.wait_round(i, last + 1)
        r = await asyncio.wait_for(task, timeout=10)
        assert r.round >= last + 1
        await src.close()
    finally:
        await gw.stop()
        net.stop_all()


_needs_cryptography = pytest.mark.skipif(
    importlib.util.find_spec("cryptography") is None,
    reason="self-signed cert generation needs the 'cryptography' package")


@_needs_cryptography
@pytest.mark.asyncio
async def test_tls_transport_roundtrip(tmp_path):
    """Server under TLS; client trusts it only via the CertManager pool —
    an empty pool (plaintext dial) must fail, the pooled cert succeeds."""
    cert, key = tls.generate_self_signed("127.0.0.1:0", str(tmp_path))
    net, gw, addr = await _make_live_gateway(tls_pair=(cert, key))
    try:
        pool = tls.CertManager()
        pool.add(cert)
        secure = GrpcClient(own_addr="tls-client", certs=pool)
        b = await secure.public_rand(addr, 1)
        assert b.round == 1
        info = await secure.chain_info(addr)
        assert verify_beacon(info.public_key, b)
        await secure.close()

        plain = GrpcClient(own_addr="plain-client")
        with pytest.raises(TransportError):
            await plain.public_rand(addr, 1)
        await plain.close()
    finally:
        await gw.stop()
        net.stop_all()


@_needs_cryptography
@pytest.mark.asyncio
async def test_tls_multi_cert_pool_same_host(tmp_path):
    """Root pools holding SEVERAL self-signed node certs for the same
    host must validate against any of them — regression for the subject
    collision that broke 3+-node TLS meshes (BoringSSL looks roots up by
    subject; certs now carry the full address as CN so subjects are
    unique per node)."""
    certs = [tls.generate_self_signed(f"127.0.0.1:{30000 + i}",
                                      str(tmp_path / f"n{i}"))
             for i in range(3)]
    net, gw, addr = await _make_live_gateway(tls_pair=certs[0])
    try:
        pool = tls.CertManager()
        # server's cert LAST: the order that failed with colliding CNs
        pool.add(certs[1][0])
        pool.add(certs[2][0])
        pool.add(certs[0][0])
        client = GrpcClient(own_addr="pool-client", certs=pool)
        b = await client.public_rand(addr, 1)
        assert b.round == 1
        await client.close()
    finally:
        await gw.stop()
        net.stop_all()
