"""RLC batch verification: the host path vs the per-item oracle, the
dispatch knob, the pairing-count acceptance, and the device combine
graphs.

Host crypto is the semantics oracle: every bool array out of
crypto/batch_verify.py must be bit-identical to the per-item loop on
every fixture, including the adversarial ones (one bad item among N,
duplicate share indices, point-at-infinity signatures, a V2-only
corruption in a dual-sig span). The all-valid fast path must cost
exactly ONE 2-pairing product check, counted via the
crypto/pairing.N_PRODUCT_CHECKS counter.
"""

import numpy as np
import pytest
from conftest import sample_count as _sample_count

from drand_tpu import metrics
from drand_tpu.chain import beacon as chain_beacon
from drand_tpu.chain.beacon import Beacon, message, message_v2
from drand_tpu.crypto import batch, batch_verify, bls, tbls
from drand_tpu.crypto import pairing as hpairing
from drand_tpu.crypto.curves import PointG1
from drand_tpu.crypto.poly import PriPoly


@pytest.fixture(scope="module")
def keys():
    sk, pub = bls.keygen(seed=b"rlc-verify-test")
    return sk, pub


@pytest.fixture(scope="module")
def threshold_setup():
    poly = PriPoly.random(3, seed=b"rlc-verify-poly")
    return poly, poly.commit()


def _make_chain(sk: int, nrounds: int, v2: bool = False) -> list[Beacon]:
    prev, out = b"\x42" * 32, []
    for rnd in range(1, nrounds + 1):
        sig = bls.sign(sk, message(rnd, prev))
        sig2 = bls.sign(sk, message_v2(rnd)) if v2 else b""
        out.append(Beacon(round=rnd, previous_sig=prev, signature=sig,
                          signature_v2=sig2))
        prev = sig
    return out


def _oracle_beacons(pub, beacons):
    out = []
    for b in beacons:
        ok = chain_beacon.verify_beacon(pub, b)
        if ok and b.is_v2():
            ok = chain_beacon.verify_beacon_v2(pub, b)
        out.append(ok)
    return out


@pytest.fixture()
def host_mode():
    old = (batch._MODE, batch._MIN_BATCH, batch._ENGINE)
    batch.configure("host")
    yield
    batch._MODE, batch._MIN_BATCH, batch._ENGINE = old


class TestHostRLC:
    def test_all_valid_64_span_one_product_check(self, keys, host_mode,
                                                 monkeypatch):
        """The acceptance criterion: a 64-beacon all-valid span through
        the host dispatch performs exactly one 2-pairing product check
        and lands a host_rlc histogram sample."""
        sk, pub = keys
        beacons = _make_chain(sk, 64)
        monkeypatch.delenv("DRAND_TPU_BATCH_VERIFY", raising=False)
        h0 = _sample_count(metrics.REGISTRY, "engine_op_seconds",
                           op="verify_beacons", path="host_rlc")
        c0, p0 = hpairing.N_PRODUCT_CHECKS, hpairing.N_MILLER_PAIRS
        oks = batch.verify_beacons(pub, beacons)
        assert oks.all() and len(oks) == 64
        assert hpairing.N_PRODUCT_CHECKS - c0 == 1
        assert hpairing.N_MILLER_PAIRS - p0 == 2
        assert _sample_count(metrics.REGISTRY, "engine_op_seconds",
                             op="verify_beacons",
                             path="host_rlc") == h0 + 1

    def test_escape_hatch_restores_per_item(self, keys, host_mode,
                                            monkeypatch):
        """DRAND_TPU_BATCH_VERIFY=0: the exact per-item behavior — one
        product check per beacon check, samples under path="host"."""
        sk, pub = keys
        beacons = _make_chain(sk, 6)
        monkeypatch.setenv("DRAND_TPU_BATCH_VERIFY", "0")
        h0 = _sample_count(metrics.REGISTRY, "engine_op_seconds",
                           op="verify_beacons", path="host")
        r0 = _sample_count(metrics.REGISTRY, "engine_op_seconds",
                           op="verify_beacons", path="host_rlc")
        c0 = hpairing.N_PRODUCT_CHECKS
        oks = batch.verify_beacons(pub, beacons)
        assert oks.all()
        assert hpairing.N_PRODUCT_CHECKS - c0 == 6  # one per V1 check
        assert _sample_count(metrics.REGISTRY, "engine_op_seconds",
                             op="verify_beacons", path="host") == h0 + 1
        assert _sample_count(metrics.REGISTRY, "engine_op_seconds",
                             op="verify_beacons", path="host_rlc") == r0

    def test_one_bad_beacon_bisection_matches_oracle(self, keys):
        sk, pub = keys
        beacons = _make_chain(sk, 9)
        beacons[4].signature = beacons[3].signature
        got = batch_verify.verify_beacons_rlc(pub, beacons)
        assert list(got) == _oracle_beacons(pub, beacons)
        assert list(got) == [True] * 4 + [False] + [True] * 4

    def test_v2_only_corruption_in_dual_span(self, keys):
        """A dual-sig span where only the V2 signature of one beacon is
        corrupt — the combined check must attribute the failure to that
        beacon alone, exactly like the per-item dual loop."""
        sk, pub = keys
        beacons = _make_chain(sk, 6, v2=True)
        beacons[2].signature_v2 = beacons[1].signature_v2
        c0 = hpairing.N_PRODUCT_CHECKS
        got = batch_verify.verify_beacons_rlc(pub, beacons)
        oracle = _oracle_beacons(pub, beacons)
        assert list(got) == oracle == [True, True, False, True, True, True]
        assert hpairing.N_PRODUCT_CHECKS - c0 > 1  # bisection ran

    def test_one_bad_partial_among_n(self, threshold_setup):
        poly, pub = threshold_setup
        msg = b"rlc-round-1"
        parts = [tbls.sign_partial(s, msg) for s in poly.shares(8)]
        bad = parts[5][:5] + bytes([parts[5][5] ^ 1]) + parts[5][6:]
        parts[5] = bad
        got = batch_verify.verify_partials_rlc(pub, msg, parts)
        oracle = [tbls.verify_partial(pub, msg, p) for p in parts]
        assert got == oracle
        assert got == [True] * 5 + [False] + [True] * 2

    def test_duplicate_share_indices(self, threshold_setup):
        poly, pub = threshold_setup
        msg = b"rlc-round-2"
        parts = [tbls.sign_partial(s, msg) for s in poly.shares(4)]
        mixed = [parts[0], parts[0], parts[1], parts[1], parts[2]]
        got = batch_verify.verify_partials_rlc(pub, msg, mixed)
        oracle = [tbls.verify_partial(pub, msg, p) for p in mixed]
        assert got == oracle == [True] * 5
        # duplicate of a CORRUPT partial: both copies flagged
        bad = parts[3][:5] + bytes([parts[3][5] ^ 1]) + parts[3][6:]
        mixed = [parts[0], bad, bad, parts[1]]
        got = batch_verify.verify_partials_rlc(pub, msg, mixed)
        assert got == [tbls.verify_partial(pub, msg, p) for p in mixed]
        assert got == [True, False, False, True]

    def test_infinity_and_malformed_prefiltered(self, threshold_setup):
        """Point-at-infinity and malformed items are rejected per-item
        BEFORE the combination — the rest of the span still verifies in
        one product check (no bisection triggered)."""
        poly, pub = threshold_setup
        msg = b"rlc-round-3"
        parts = [tbls.sign_partial(s, msg) for s in poly.shares(3)]
        inf_sig = (5).to_bytes(2, "big") + b"\xc0" + b"\x00" * 95
        mixed = parts + [inf_sig, b"", parts[0][:50]]
        c0 = hpairing.N_PRODUCT_CHECKS
        got = batch_verify.verify_partials_rlc(pub, msg, mixed)
        rlc_checks = hpairing.N_PRODUCT_CHECKS - c0
        oracle = [tbls.verify_partial(pub, msg, p) for p in mixed]
        assert got == oracle == [True] * 3 + [False] * 3
        assert rlc_checks == 1

    def test_aggregate_round_host_api_unchanged(self, threshold_setup,
                                                host_mode, monkeypatch):
        """Host aggregate_round keeps its API and, with the RLC path on,
        an all-valid round costs 2 product checks total (combined
        partials + recovered signature) instead of t-proportional."""
        poly, pub = threshold_setup
        msg = b"rlc-agg-round"
        parts = [tbls.sign_partial(s, msg) for s in poly.shares(6)]
        monkeypatch.delenv("DRAND_TPU_BATCH_VERIFY", raising=False)
        c0 = hpairing.N_PRODUCT_CHECKS
        oks, sig = batch.aggregate_round(pub, msg, parts, 3, 6)
        assert oks == [True] * 6
        assert sig == tbls.recover(pub, msg, parts, 3, 6)
        assert hpairing.N_PRODUCT_CHECKS - c0 == 2

    def test_scalars_nonzero_and_nonconstant(self):
        a = batch_verify.rlc_scalars(64)
        b = batch_verify.rlc_scalars(64)
        assert all(0 < c < (1 << batch_verify.RLC_SCALAR_BITS) for c in a + b)
        assert a != b                 # fresh randomness across calls
        assert len(set(a)) > 1        # not a constant vector within a call

    def test_host_rlc_partials_metric_sample(self, threshold_setup,
                                             host_mode, monkeypatch):
        poly, pub = threshold_setup
        msg = b"rlc-metrics-partials"
        parts = [tbls.sign_partial(s, msg) for s in poly.shares(4)]
        monkeypatch.delenv("DRAND_TPU_BATCH_VERIFY", raising=False)
        h0 = _sample_count(metrics.REGISTRY, "engine_op_seconds",
                           op="verify_partials", path="host_rlc")
        assert batch.verify_partials(pub, msg, parts) == [True] * 4
        assert _sample_count(metrics.REGISTRY, "engine_op_seconds",
                             op="verify_partials",
                             path="host_rlc") == h0 + 1


def test_fallback_warning_rearms_after_device_success():
    """crypto/batch: the warn-once device-fallback flag resets when a
    later device dispatch succeeds, so a backend that recovers and then
    breaks again warns again."""
    old = batch._FALLBACK_LOGGED
    try:
        batch._FALLBACK_LOGGED = False
        batch._note_fallback("verify_beacons", RuntimeError("boom"))
        assert batch._FALLBACK_LOGGED is True
        batch._note_device_ok()
        assert batch._FALLBACK_LOGGED is False
        batch._note_fallback("verify_beacons", RuntimeError("boom again"))
        assert batch._FALLBACK_LOGGED is True
    finally:
        batch._FALLBACK_LOGGED = old


def test_h2c_memo_counters():
    """The hash_to_g2 keyed LRU exports hit/miss counters."""
    from drand_tpu.crypto import hash_to_curve as h2c

    msg = b"rlc-h2c-memo-probe"
    m0 = _sample_count(metrics.REGISTRY, "hash_to_g2_cache_requests",
                       result="miss")
    h0 = _sample_count(metrics.REGISTRY, "hash_to_g2_cache_requests",
                       result="hit")
    info0 = h2c.h2c_cache_info()
    first = h2c.hash_to_g2(msg)
    assert _sample_count(metrics.REGISTRY, "hash_to_g2_cache_requests",
                         result="miss") == m0 + 1
    again = h2c.hash_to_g2(msg)
    assert again == first
    assert _sample_count(metrics.REGISTRY, "hash_to_g2_cache_requests",
                         result="hit") == h0 + 1
    info1 = h2c.h2c_cache_info()
    assert info1["misses"] == info0["misses"] + 1
    assert info1["hits"] == info0["hits"] + 1
    assert info1["maxsize"] >= info1["size"]


# ---------------------------------------------------------------------------
# Device combine graphs (CPU backend in the suite; compile-heavy)
# ---------------------------------------------------------------------------


@pytest.mark.device
class TestDeviceRLC:
    @pytest.fixture(scope="class")
    def engine(self):
        from drand_tpu.ops.engine import BatchedEngine

        eng = BatchedEngine(buckets=(2,))
        eng.rlc_min = 2
        eng.rlc_lane_buckets = (4,)
        return eng

    def test_verify_beacons_rlc_and_fallback(self, engine, keys):
        sk, pub = keys
        beacons = _make_chain(sk, 4)
        assert engine.verify_beacons(pub, beacons).all()
        # the combine KATs ran and the shapes are trusted
        assert engine._rlc_ok.get(("g2g2", 4)) is True
        # a corrupted beacon fails the combined check and falls back to
        # the per-item graphs with exact verdicts
        beacons[2].signature = beacons[1].signature
        got = engine.verify_beacons(pub, beacons)
        assert list(got) == [True, True, False, True]

    def test_verify_partials_and_agg_rlc(self, engine, threshold_setup):
        poly, pub = threshold_setup
        msg = b"rlc-device-round"
        parts = [tbls.sign_partial(s, msg) for s in poly.shares(4)]
        assert engine.verify_partials(pub, msg, parts) == [True] * 4
        assert engine._rlc_ok.get(("g1g2", 4)) is True
        oks, sig = engine.aggregate_round(pub, msg, parts, 3, 4)
        assert oks == [True] * 4
        assert sig == tbls.recover(pub, msg, parts, 3, 4)
        # corrupt one partial: exact per-item verdicts via the fallback
        bad = parts[0][:5] + bytes([parts[0][5] ^ 1]) + parts[0][6:]
        oks, sig = engine.aggregate_round(pub, msg,
                                          [bad] + parts[1:], 3, 4)
        assert oks == [False, True, True, True]
        assert sig == tbls.recover(pub, msg, parts[1:], 3, 4)

    def test_escape_hatch_skips_device_rlc(self, engine, keys,
                                           monkeypatch):
        sk, pub = keys
        monkeypatch.setenv("DRAND_TPU_BATCH_VERIFY", "0")
        assert engine._rlc_wanted(64) is False
        monkeypatch.delenv("DRAND_TPU_BATCH_VERIFY", raising=False)
        assert engine._rlc_wanted(64) is True
        assert engine._rlc_wanted(engine.rlc_min - 1) is False
