"""Backend-init watchdog (utils/backend.py).

Round-3 regression class: the axon tunnel going down turned the driver's
official record red (BENCH_r03 rc=1 unparseable traceback, MULTICHIP_r03
rc=124 infinite hang). Every entry point now goes through
``init_backend``, which must (a) succeed when a backend is available,
(b) raise ``BackendUnavailable`` within the deadline on fast repeated
failures, and (c) force-exit with the caller's exit code + diagnostic
when the init call hangs in C (only a watchdog thread can escape that).

The reference has no analogue (a Go binary has no remote device to
lose); this is axon-environment hardening.
"""

import asyncio
import subprocess
import sys
import threading
import time
import types

import pytest

from drand_tpu.utils import backend as B

REPO = __file__.rsplit("/tests/", 1)[0]


def test_init_backend_success():
    platform, devs = B.init_backend(deadline=120)
    assert devs, "no devices from a live backend"
    assert platform in ("cpu", "tpu", "axon")


def test_fast_failure_raises_within_deadline(monkeypatch):
    calls = []

    fake = types.ModuleType("jax")

    def _devices():
        calls.append(time.monotonic())
        raise RuntimeError("Unable to initialize backend 'axon': UNAVAILABLE")

    fake.devices = _devices
    fake.default_backend = lambda: "axon"
    monkeypatch.setitem(sys.modules, "jax", fake)
    monkeypatch.delenv("DRAND_TPU_BACKEND_DEADLINE", raising=False)

    failures = []
    t0 = time.monotonic()
    with pytest.raises(B.BackendUnavailable, match="UNAVAILABLE"):
        B.init_backend(deadline=2.0, retry_interval=0.3,
                       on_fail=failures.append)
    dt = time.monotonic() - t0
    assert len(calls) >= 3, "did not retry fast failures"
    assert dt < 10, f"gave up too slowly ({dt:.1f}s for a 2s deadline)"
    assert failures and "unavailable" in failures[0]


def test_hang_force_exits_with_diagnostic():
    """A hanging backend init must not outlive the watchdog: the process
    exits with the requested code after running on_fail (bench.py uses
    this to emit its structured final JSON line)."""
    script = f"""
import sys, time, types
fake = types.ModuleType("jax")
fake.devices = lambda: time.sleep(3600)   # hang "in init"
fake.default_backend = lambda: "axon"
sys.modules["jax"] = fake
sys.path.insert(0, {REPO!r})
from drand_tpu.utils.backend import init_backend
init_backend(deadline=1.0, retry_interval=0.5,
             on_fail=lambda r: print("FINAL-LINE " + r, flush=True),
             exit_code=7)
print("UNREACHABLE", flush=True)
"""
    env = {"PATH": "/usr/bin:/bin", "PYTHONPATH": REPO}
    t0 = time.monotonic()
    proc = subprocess.run([sys.executable, "-c", script], env=env,
                          capture_output=True, text=True, timeout=60)
    dt = time.monotonic() - t0
    assert proc.returncode == 7, (proc.returncode, proc.stderr)
    assert "FINAL-LINE" in proc.stdout
    assert "UNREACHABLE" not in proc.stdout
    assert dt < 30, f"watchdog too slow: {dt:.1f}s"


@pytest.mark.asyncio
async def test_engine_probe_nonblocking_from_to_thread(monkeypatch):
    """crypto/batch.engine() must treat asyncio.to_thread workers like
    event-loop callers: with no probe verdict yet it kicks the
    background probe and raises BackendUnavailable (host fallback)
    instead of joining the synchronous ~90 s probe — the daemon's
    aggregator/sync/catch-up workers serve round-deadline work."""
    from drand_tpu.crypto import batch

    monkeypatch.setattr(batch, "_MODE", "auto")
    monkeypatch.setattr(batch, "_ENGINE", None)
    monkeypatch.setattr(B, "backend_already_up", lambda: False)
    monkeypatch.setattr(B, "probe_state", lambda: None)
    kicked = []
    monkeypatch.setattr(B, "probe_backend_bg",
                        lambda *a, **k: kicked.append(1))

    def must_not_block(*a, **k):
        raise AssertionError("synchronous probe joined from a "
                             "to_thread worker")

    monkeypatch.setattr(B, "probe_backend", must_not_block)
    with pytest.raises(B.BackendUnavailable):
        await asyncio.to_thread(batch.engine)
    assert kicked


def test_engine_singleton_construction_is_locked(monkeypatch):
    """Two worker threads racing the lazy _ENGINE init must construct
    exactly one engine (duplicate BatchedEngine = duplicate jit setup
    and a discarded KAT-verdict cache)."""
    from drand_tpu.crypto import batch
    from drand_tpu.ops import engine as ops_engine

    monkeypatch.setattr(batch, "_MODE", "auto")
    monkeypatch.setattr(batch, "_ENGINE", None)
    monkeypatch.setattr(B, "probe_state", lambda: True)

    built = []

    class FakeEngine:
        def __init__(self):
            built.append(self)
            time.sleep(0.1)  # widen the race window

    monkeypatch.setattr(ops_engine, "BatchedEngine", FakeEngine)
    results = []
    threads = [threading.Thread(target=lambda: results.append(
        batch.engine())) for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(built) == 1
    assert results[0] is results[1] is built[0]
