"""Backend-init watchdog (utils/backend.py).

Round-3 regression class: the axon tunnel going down turned the driver's
official record red (BENCH_r03 rc=1 unparseable traceback, MULTICHIP_r03
rc=124 infinite hang). Every entry point now goes through
``init_backend``, which must (a) succeed when a backend is available,
(b) raise ``BackendUnavailable`` within the deadline on fast repeated
failures, and (c) force-exit with the caller's exit code + diagnostic
when the init call hangs in C (only a watchdog thread can escape that).

The reference has no analogue (a Go binary has no remote device to
lose); this is axon-environment hardening.
"""

import subprocess
import sys
import time
import types

import pytest

from drand_tpu.utils import backend as B

REPO = __file__.rsplit("/tests/", 1)[0]


def test_init_backend_success():
    platform, devs = B.init_backend(deadline=120)
    assert devs, "no devices from a live backend"
    assert platform in ("cpu", "tpu", "axon")


def test_fast_failure_raises_within_deadline(monkeypatch):
    calls = []

    fake = types.ModuleType("jax")

    def _devices():
        calls.append(time.monotonic())
        raise RuntimeError("Unable to initialize backend 'axon': UNAVAILABLE")

    fake.devices = _devices
    fake.default_backend = lambda: "axon"
    monkeypatch.setitem(sys.modules, "jax", fake)
    monkeypatch.delenv("DRAND_TPU_BACKEND_DEADLINE", raising=False)

    failures = []
    t0 = time.monotonic()
    with pytest.raises(B.BackendUnavailable, match="UNAVAILABLE"):
        B.init_backend(deadline=2.0, retry_interval=0.3,
                       on_fail=failures.append)
    dt = time.monotonic() - t0
    assert len(calls) >= 3, "did not retry fast failures"
    assert dt < 10, f"gave up too slowly ({dt:.1f}s for a 2s deadline)"
    assert failures and "unavailable" in failures[0]


def test_hang_force_exits_with_diagnostic():
    """A hanging backend init must not outlive the watchdog: the process
    exits with the requested code after running on_fail (bench.py uses
    this to emit its structured final JSON line)."""
    script = f"""
import sys, time, types
fake = types.ModuleType("jax")
fake.devices = lambda: time.sleep(3600)   # hang "in init"
fake.default_backend = lambda: "axon"
sys.modules["jax"] = fake
sys.path.insert(0, {REPO!r})
from drand_tpu.utils.backend import init_backend
init_backend(deadline=1.0, retry_interval=0.5,
             on_fail=lambda r: print("FINAL-LINE " + r, flush=True),
             exit_code=7)
print("UNREACHABLE", flush=True)
"""
    env = {"PATH": "/usr/bin:/bin", "PYTHONPATH": REPO}
    t0 = time.monotonic()
    proc = subprocess.run([sys.executable, "-c", script], env=env,
                          capture_output=True, text=True, timeout=60)
    dt = time.monotonic() - t0
    assert proc.returncode == 7, (proc.returncode, proc.stderr)
    assert "FINAL-LINE" in proc.stdout
    assert "UNREACHABLE" not in proc.stdout
    assert dt < 30, f"watchdog too slow: {dt:.1f}s"
