"""Golden tests: batch-last Pallas pairing path (ops/pallas_pairing.py)
vs the proven XLA device pairing (ops/pairing.py) and the host truth.

The pure-jnp math functions are validated here on CPU (they are the same
code the Pallas kernels trace); the Mosaic-compiled kernels themselves
are known-answer-validated on the TPU by the engine before use.
"""

import random

import numpy as np
import jax.numpy as jnp
import pytest

pytestmark = pytest.mark.device

from drand_tpu.crypto import pairing as hp
from drand_tpu.crypto.curves import PointG1, PointG2
from drand_tpu.ops import bl, limb, pairing as xp_pair, tower
from drand_tpu.ops import pallas_pairing as pp

B = 2
rng = random.Random(0x9A1A)


def rand_pairs(n=B):
    """n verification-shaped inputs: pairs ((-g1, sig), (pub, msg))."""
    out = []
    for _ in range(n):
        sk = rng.randrange(1, 1 << 64)
        pub = PointG1.generator().mul(sk)
        msg = PointG2.generator().mul(rng.randrange(1, 1 << 64))
        sig = msg.mul(sk)
        out.append((pub, sig, msg))
    return out


def pack_batch_leading(triples):
    pubs = np.stack([np.asarray(xp_pair.g1_affine_to_device(p))
                     for p, _, _ in triples])
    sigs = np.stack([np.asarray(xp_pair.g2_affine_to_device(s))
                     for _, s, _ in triples])
    msgs = np.stack([np.asarray(xp_pair.g2_affine_to_device(m))
                     for _, _, m in triples])
    return pubs, sigs, msgs


def fp12_list_from_bl(f):
    """(2, 3, 2, 32, B) -> list of host Fp12 (via the limb-last codec)."""
    g = np.moveaxis(np.asarray(f), -1, 0)  # (B, 2, 3, 2, 32)
    return [tower.fp12_from_device(g[i]) for i in range(g.shape[0])]


def test_miller_loop_matches_xla_device_path():
    triples = rand_pairs()
    pubs, sigs, msgs = pack_batch_leading(triples)
    # XLA (batch-leading) reference
    neg_g1 = np.broadcast_to(pp._neg_g1_np(), pubs.shape)
    xp_coords = jnp.stack([jnp.asarray(neg_g1[:, 0]),
                           jnp.asarray(pubs[:, 0])], axis=-2)
    yp_coords = jnp.stack([jnp.asarray(neg_g1[:, 1]),
                           jnp.asarray(pubs[:, 1])], axis=-2)
    q = jnp.stack([jnp.asarray(sigs), jnp.asarray(msgs)], axis=-4)
    f_ref = xp_pair.miller_loop((xp_coords, yp_coords), q)
    ref = [tower.fp12_from_device(np.asarray(f_ref)[i]) for i in range(B)]
    # batch-last
    xpl, ypl, ql = pp.pack_verify_inputs(pubs, sigs, msgs)
    f_bl = pp.miller_loop_bl(
        xpl, ypl, ql, pp.value_bit_getter(jnp.asarray(pp.MILLER_FLAGS)))
    got = fp12_list_from_bl(f_bl)
    assert got == ref


def test_final_exp_and_verify_match_host():
    triples = rand_pairs()
    pubs, sigs, msgs = pack_batch_leading(triples)
    xpl, ypl, ql = pp.pack_verify_inputs(pubs, sigs, msgs)
    f = pp.miller_loop_bl(
        xpl, ypl, ql, pp.value_bit_getter(jnp.asarray(pp.MILLER_FLAGS)))
    out = pp.final_exp_bl(f)
    got = fp12_list_from_bl(out)
    # valid signatures: the (cubed) pairing product is exactly one
    for g in got:
        assert g == g.one(), "valid verification must hit the identity"
    # full entry point, pure-jnp path
    ok = pp.verify_prepared_pl(pubs, sigs, msgs, use_pallas=False)
    assert np.asarray(ok).tolist() == [True] * B


def test_verify_rejects_wrong_signature():
    triples = rand_pairs()
    pubs, sigs, msgs = pack_batch_leading(triples)
    # corrupt row 1: swap in an unrelated signature
    bad = PointG2.generator().mul(0xDEAD)
    sigs[1] = np.asarray(xp_pair.g2_affine_to_device(bad))
    ok = pp.verify_prepared_pl(pubs, sigs, msgs, use_pallas=False)
    assert np.asarray(ok).tolist() == [True, False]


def test_final_exp_nontrivial_matches_host_codec():
    """Final exp of a NON-verifying product must equal the host's (cubed,
    non-canonical) final exponentiation — full GT value, not just ==1.
    Runs at the suite-wide batch B so the miller/final-exp graphs
    compiled by the earlier tests are REUSED (a B=1 shape here used to
    recompile the whole chain — half the suite's wall time)."""
    triples = rand_pairs()  # batch B, same compiled shapes as above
    pubs, sigs, msgs = pack_batch_leading(triples)
    # mismatched message in row 0: a nontrivial GT element there
    p1, s1, _ = triples[0]
    other = PointG2.generator().mul(0xBEEF)
    msgs[0] = np.asarray(xp_pair.g2_affine_to_device(other))
    host = hp.multi_pairing(
        [(-PointG1.generator(), s1), (p1, other)], canonical=False)
    xpl, ypl, ql = pp.pack_verify_inputs(pubs, sigs, msgs)
    f = pp.miller_loop_bl(
        xpl, ypl, ql, pp.value_bit_getter(jnp.asarray(pp.MILLER_FLAGS)))
    got = fp12_list_from_bl(pp.final_exp_bl(f))[0]
    assert got == host
