"""Control-plane protobuf interop: reference operator tooling semantics.

Drives a REAL daemon pair through the control port using control.proto
framing only (no JSON): PingPong, InitDKG (leader side), Share,
PublicKey, GroupFile, ChainInfo and Shutdown — the packet shapes of
protobuf/drand/control.proto:14-37, which is what `drand share/stop/
show` send (net/control.go ControlClient). The follower runs the same
DKG through the daemon API directly; the leader's group response coming
back as a GroupPacket proves the codec end to end.
"""

import asyncio

import grpc
import grpc.aio
import pytest

from drand_tpu.core.config import Config
from drand_tpu.core.daemon import Drand
from drand_tpu.key.group import Group
from drand_tpu.key.store import FileStore
from drand_tpu.net import protowire as pw
from drand_tpu.net.control import ControlServer
from drand_tpu.net.transport import LocalNetwork
from drand_tpu.utils.clock import FakeClock

SECRET = b"setup-secret-0123456789abcdef"


def make_daemon(i, net, clock, tmp_path):
    addr = f"d{i}.test:71{i:02d}"
    ks = FileStore(str(tmp_path / f"node{i}"))
    conf = Config(clock=clock, dkg_timeout=10)
    d = Drand.fresh(ks, conf, net.client_for(addr), addr)
    net.register(addr, d)
    return addr, d


@pytest.mark.asyncio
async def test_control_protobuf_full_cycle(tmp_path):
    clock = FakeClock(1_700_000_000.0)
    net = LocalNetwork()
    addr0, d0 = make_daemon(0, net, clock, tmp_path)
    addr1, d1 = make_daemon(1, net, clock, tmp_path)

    ctl = ControlServer(d0, 0)
    await ctl.start()
    ch = grpc.aio.insecure_channel(f"127.0.0.1:{ctl.port}")

    async def call(method, spec, payload, resp_spec, timeout=60.0):
        fn = ch.unary_unary(f"/drand.Control/{method}")
        raw = await fn(pw.encode(spec, payload), timeout=timeout)
        return pw.decode(resp_spec, raw)

    try:
        # PingPong over the empty protobuf message
        assert await call("PingPong", pw.EMPTY, {}, pw.EMPTY) == {}

        # InitDKG via protobuf on the leader; follower joins natively
        # (leader first: the follower's signal needs the setup manager)
        leader = asyncio.ensure_future(call(
            "InitDKG", pw.INIT_DKG_PACKET, {
                "info": {"leader": True, "nodes": 2, "threshold": 2,
                         "timeout": 20, "secret": SECRET},
                "beacon_period": 5,
            }, pw.GROUP_PACKET, timeout=120.0))
        await asyncio.sleep(0.2)
        await d1.init_dkg_follower(addr0, SECRET, timeout=20)
        gp = await leader
        group = Group.from_proto_dict(gp)
        assert group.threshold == 2 and len(group.nodes) == 2
        assert group.period == 5
        assert group.hash() == d0.group.hash()
        assert gp["dist_key"], "distributed key missing from GroupPacket"

        # Share: index + 32-byte big-endian scalar (ShareResponse:2,3)
        sh = await call("Share", pw.SHARE_REQUEST, {}, pw.SHARE_RESPONSE)
        assert sh["index"] == d0.share.pri_share.index
        assert len(sh["share"]) == 32
        assert int.from_bytes(sh["share"], "big") > 0

        # PublicKey: compressed G1 key (PublicKeyResponse:2)
        pk = await call("PublicKey", pw.PUBLIC_KEY_REQUEST, {},
                        pw.PUBLIC_KEY_RESPONSE)
        assert pk["pub_key"] == d0.priv.public.key.to_bytes()

        # GroupFile round-trips the same group
        gf = await call("GroupFile", pw.GROUP_REQUEST, {}, pw.GROUP_PACKET)
        assert Group.from_proto_dict(gf).hash() == d0.group.hash()

        # ChainInfo carries the group public key
        ci = await call("ChainInfo", pw.CHAIN_INFO_REQUEST, {},
                        pw.CHAIN_INFO_PACKET)
        assert ci["public_key"] == d0.group.public_key.key().to_bytes()
        assert ci["period"] == 5

        # Shutdown via protobuf framing stops the daemon
        await call("Shutdown", pw.SHUTDOWN_REQUEST, {},
                   pw.SHUTDOWN_RESPONSE)
        assert d0.beacon is None or d0._stopped  # daemon stopped
    finally:
        await ch.close()
        await ctl.stop()
        d1.stop()


@pytest.mark.asyncio
async def test_control_json_still_native(tmp_path):
    """The JSON codec keeps working on the shared method names."""
    import json

    clock = FakeClock(1_700_000_000.0)
    net = LocalNetwork()
    _, d0 = make_daemon(0, net, clock, tmp_path)
    ctl = ControlServer(d0, 0)
    await ctl.start()
    ch = grpc.aio.insecure_channel(f"127.0.0.1:{ctl.port}")
    try:
        fn = ch.unary_unary("/drand.Control/PublicKey")
        raw = await fn(json.dumps({}).encode(), timeout=10.0)
        out = json.loads(raw)
        assert out["public_key"] == d0.priv.public.key.to_bytes().hex()
    finally:
        await ch.close()
        await ctl.stop()


@pytest.mark.asyncio
async def test_follow_rejects_mismatched_info_hash(tmp_path, monkeypatch):
    """ADVICE r5 high: a follow must validate the fetched chain info
    against the operator-supplied info_hash (core/drand_control.go:822-
    829) — a lying peer serving different chain info must abort instead
    of getting its self-supplied key pinned. Covers the daemon core (the
    native JSON path calls straight through) and the protobuf streaming
    endpoint."""
    from drand_tpu.chain.info import Info
    from drand_tpu.core.daemon import DrandError
    from drand_tpu.crypto.curves import PointG1

    clock = FakeClock(1_700_000_000.0)
    net = LocalNetwork()
    _, d0 = make_daemon(0, net, clock, tmp_path)
    lying_info = Info(public_key=PointG1.generator().mul(7), period=30,
                      genesis_time=1_700_000_000, genesis_seed=b"s" * 32,
                      group_hash=b"g" * 32)

    async def fake_chain_info(peer):
        return lying_info

    monkeypatch.setattr(d0.client, "chain_info", fake_chain_info)

    with pytest.raises(DrandError, match="hash mismatch"):
        await d0.follow_chain(["evil.test:7000"], info_hash=b"\x00" * 32)

    # the matching hash pins the chain and proceeds into the syncer
    class FakeSyncer:
        def __init__(self, *a, **k):
            pass

        async def follow(self, up_to, peers):
            return True

    import drand_tpu.chain.engine.sync as sync_mod

    monkeypatch.setattr(sync_mod, "Syncer", FakeSyncer)
    assert await d0.follow_chain(["peer.test:7000"],
                                 info_hash=lying_info.hash())
    # and with no hash supplied the legacy unpinned behavior remains
    assert await d0.follow_chain(["peer.test:7000"])

    # protobuf codec: StartFollowChain aborts FAILED_PRECONDITION
    ctl = ControlServer(d0, 0)
    await ctl.start()
    ch = grpc.aio.insecure_channel(f"127.0.0.1:{ctl.port}")
    try:
        fn = ch.unary_stream("/drand.Control/StartFollowChain")
        call = fn(pw.encode(pw.START_FOLLOW_REQUEST, {
            "info_hash": (b"\x11" * 32).hex(),
            "nodes": ["evil.test:7000"], "up_to": 0}))
        with pytest.raises(grpc.aio.AioRpcError) as ei:
            async for _ in call:
                pass
        assert ei.value.code() == grpc.StatusCode.FAILED_PRECONDITION
        assert "hash mismatch" in (ei.value.details() or "")
    finally:
        await ch.close()
        await ctl.stop()
