"""Standalone client CLI + archive relay (reference cmd/client, cmd/relay-s3).

Runs the `client` and `relay-archive` subcommands as real subprocesses
against a live in-process REST server, with the chain hash pinned so the
full verified stack is exercised end to end.
"""

import asyncio
import json
import os
import subprocess
import sys

import pytest

from drand_tpu.chain.info import Info
from drand_tpu.client.direct import DirectClient
from drand_tpu.http_server.server import PublicServer
from drand_tpu.testing.harness import BeaconTestNetwork

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
N, T, PERIOD, ROUNDS = 3, 2, 5, 3


def cli_env():
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    env.pop("JAX_PLATFORMS", None)
    return env


async def run_cli(args, timeout=240):
    proc = await asyncio.create_subprocess_exec(
        sys.executable, "-m", "drand_tpu.cli", *args,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        env=cli_env(), cwd=REPO)
    out, err = await asyncio.wait_for(proc.communicate(), timeout)
    return proc.returncode, out.decode(), err.decode()


async def start_stack():
    net = BeaconTestNetwork(n=N, t=T, period=PERIOD)
    await net.start_all()
    await net.advance_to_genesis()
    for _ in range(ROUNDS):
        await net.clock.advance(PERIOD)
    for i in range(N):
        await net.wait_round(i, ROUNDS)
    server = PublicServer(DirectClient(net.nodes[0].handler), clock=net.clock)
    site = await server.start("127.0.0.1", 0)
    port = site._server.sockets[0].getsockname()[1]
    chain_hash = Info.from_group(net.group).hash().hex()
    return net, server, f"http://127.0.0.1:{port}", chain_hash


@pytest.mark.asyncio
async def test_client_cli_verified_get():
    net, server, url, chain_hash = await start_stack()
    try:
        rc, out, err = await run_cli(
            ["client", "--url", url, "--chain-hash", chain_hash,
             "--round", "2"])
        assert rc == 0, err
        got = json.loads(out)
        assert got["round"] == 2
        want = net.nodes[0].handler.chain.get(2)
        assert bytes.fromhex(got["signature"]) == want.signature
    finally:
        await server.stop()
        net.stop_all()


@pytest.mark.asyncio
async def test_relay_archive_backfill(tmp_path):
    net, server, url, chain_hash = await start_stack()
    try:
        rc, out, err = await run_cli(
            ["relay-archive", "--url", url, "--chain-hash", chain_hash,
             "--out", str(tmp_path), "--once"])
        assert rc == 0, err
        info = json.loads((tmp_path / "info").read_text())
        assert info["hash"] == chain_hash
        for rd in range(1, ROUNDS + 1):
            b = json.loads((tmp_path / "public" / str(rd)).read_text())
            assert b["round"] == rd
            assert bytes.fromhex(b["signature"]) == \
                net.nodes[0].handler.chain.get(rd).signature
    finally:
        await server.stop()
        net.stop_all()
