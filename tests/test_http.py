"""HTTP surface: REST server + HTTP client against a live network.

Reference coverage model: http/server_test.go + client/http tests — real
TCP on localhost, JSON wire format parity, /health semantics, and the full
verified client stack over HTTP.
"""

import asyncio
import json

import aiohttp
import pytest

from drand_tpu.client import new_client
from drand_tpu.client.direct import DirectClient
from drand_tpu.client.http import HTTPClient
from drand_tpu.http_server.server import PublicServer
from drand_tpu.testing.harness import BeaconTestNetwork

N, T, PERIOD = 3, 2, 5


async def start_stack(rounds=3):
    net = BeaconTestNetwork(n=N, t=T, period=PERIOD)
    await net.start_all()
    await net.advance_to_genesis()
    for _ in range(rounds):
        await net.clock.advance(PERIOD)
    for i in range(N):
        await net.wait_round(i, rounds)
    server = PublicServer(DirectClient(net.nodes[0].handler), clock=net.clock)
    site = await server.start("127.0.0.1", 0)
    port = site._server.sockets[0].getsockname()[1]
    return net, server, f"http://127.0.0.1:{port}"


@pytest.mark.asyncio
async def test_rest_endpoints_and_json_format():
    net, server, url = await start_stack()
    try:
        async with aiohttp.ClientSession() as sess:
            async with sess.get(url + "/info") as resp:
                assert resp.status == 200
                info = await resp.json()
                for k in ("public_key", "period", "genesis_time",
                          "group_hash", "hash"):
                    assert k in info, k
                assert info["period"] == PERIOD
            async with sess.get(url + "/public/2") as resp:
                assert resp.status == 200
                b = await resp.json()
                assert b["round"] == 2
                assert len(bytes.fromhex(b["signature"])) == 96
                assert len(bytes.fromhex(b["randomness"])) == 32
                assert "signature_v2" in b
            async with sess.get(url + "/public/latest") as resp:
                assert (await resp.json())["round"] >= 3
            async with sess.get(url + "/public/999999") as resp:
                assert resp.status == 404
            async with sess.get(url + "/health") as resp:
                assert resp.status == 200
                h = await resp.json()
                assert h["current"] >= 3 and h["expected"] >= h["current"] - 1
    finally:
        await server.stop()
        net.stop_all()


@pytest.mark.asyncio
async def test_verified_client_over_http():
    net, server, url = await start_stack()
    try:
        src = HTTPClient(url, clock=net.clock)
        info = await src.info()
        # chain hash computed from served fields matches the node's own
        node_info = net.nodes[0].handler.crypto.chain_info
        assert info.hash() == node_info.hash()
        client = new_client([src], chain_hash=node_info.hash())
        r = await client.get(2)
        assert r.round == 2 and len(r.randomness) == 32
        await client.close()
    finally:
        await server.stop()
        net.stop_all()


@pytest.mark.asyncio
async def test_health_degrades_when_chain_stalls():
    net, server, url = await start_stack()
    try:
        # stop all nodes, then advance the clock several periods: expected
        # round grows, current stays — health must go 500
        net.stop_all()
        await net.clock.advance(PERIOD * 5)
        async with aiohttp.ClientSession() as sess:
            async with sess.get(url + "/health") as resp:
                assert resp.status == 500
                h = await resp.json()
                assert h["expected"] > h["current"] + 1
    finally:
        await server.stop()
